"""Cross-round perf gate: diff the newest BENCH_r{N}.json against the
previous round's and fail on regressions beyond a fence.

Reference analog: the reference runs ``release/microbenchmark`` nightly
and tracks deltas externally; here the fence is in-repo so a perf
regression (like round 3's actor-call/put drop) cannot land silently.

Usage:
    python ci/perf_gate.py                 # compare newest vs previous
    python ci/perf_gate.py NEW.json OLD.json
    PERF_GATE_FENCE=0.10 python ci/perf_gate.py

Exit 0: no metric regressed more than the fence (default 10%).
Exit 1: regression(s) found — printed with both values.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

# metric name -> candidate paths into the bench JSON (all
# higher-is-better). Two layouts exist: the full-run doc (core metrics
# under detail.core) and a BENCH_MODE=core-only doc (under detail).
METRICS = {
    "train_tokens_per_sec_per_chip": [("value",)],
    "train_mfu": [("detail", "mfu")],
    "train_large_tokens_per_sec": [("detail", "train_large", "value")],
    "train_longctx_tokens_per_sec": [("detail", "train_longctx", "value")],
    "serve_tokens_per_sec": [("detail", "serve", "value")],
    "core_tasks_per_sec": [("detail", "core", "tasks_per_sec"),
                           ("detail", "tasks_per_sec")],
    "core_actor_calls_per_sec": [("detail", "core", "actor_calls_per_sec"),
                                 ("detail", "actor_calls_per_sec")],
    "core_puts_1kb_per_sec": [("detail", "core", "puts_1kb_per_sec"),
                              ("detail", "puts_1kb_per_sec")],
    "core_gets_1kb_per_sec": [("detail", "core", "gets_1kb_per_sec"),
                              ("detail", "gets_1kb_per_sec")],
    # envelope probe (fork-server worker pool axes); key names are
    # envelope-unique so a mode-only doc can't collide with core paths
    "envelope_tasks_per_sec": [
        ("detail", "envelope", "envelope_tasks_per_sec"),
        ("detail", "envelope_tasks_per_sec")],
    "envelope_actors_created_per_sec": [
        ("detail", "envelope", "actors_created_per_sec"),
        ("detail", "actors_created_per_sec")],
    "envelope_actor_calls_per_sec": [
        ("detail", "envelope", "steady_actor_calls_per_sec"),
        ("detail", "steady_actor_calls_per_sec")],
    # batched actor control plane (round 6): warm location-resolve rate
    # off the pushed CH_ACTOR table (absent in pre-round-6 baselines:
    # the gate skips keys either side lacks)
    "envelope_actor_resolves_per_sec": [
        ("detail", "envelope", "actor_resolves_per_sec"),
        ("detail", "actor_resolves_per_sec")],
    # serve scale-out plane (round 8): 2-replica cluster tokens/s over
    # the single-replica leg on repeat-prefix traffic — the prefix-
    # affinity routing win (absent in pre-round-8 baselines: skipped)
    "serve_scaleout_efficiency_2x": [
        ("detail", "serve_scaleout", "efficiency_2x"),
        ("detail", "efficiency_2x")],
    "serve_scaleout_2rep_tokens_per_sec": [
        ("detail", "serve_scaleout", "legs", "2",
         "cluster_tokens_per_sec"),
        ("detail", "legs", "2", "cluster_tokens_per_sec")],
    # training telemetry plane (round 9): share of run wall clock
    # attributed to productive steps by the goodput accounting (absent
    # in pre-round-9 baselines: skipped)
    "train_goodput_fraction": [
        ("detail", "train_telemetry", "goodput_fraction"),
        ("detail", "goodput_fraction")],
    # data plane leg (round 11): map_batches scan throughput and
    # push-based shuffle row rate on a two-node cluster, with
    # per-stage bytes/s coming from the memory plane's object
    # accounting (absent in pre-round-11 baselines: skipped)
    "data_map_batches_gib_per_sec": [
        ("detail", "data", "map_batches_gib_per_sec"),
        ("detail", "map_batches_gib_per_sec")],
    "data_push_shuffle_rows_per_sec": [
        ("detail", "data", "push_shuffle_rows_per_sec"),
        ("detail", "push_shuffle_rows_per_sec")],
}

# LOWER-is-better latency keys (round 7: measured serve TTFT
# decomposition from the metrics plane) — a regression is an INCREASE
# past the fence. Absent in pre-round-7 baselines: skipped until both
# sides carry them.
#
# Round 8 dropped the per-stage prefill_s / pipeline_stall_s fences:
# continuous admission legitimately MOVES device-stream residence
# between those stages (a prefill admitted mid-chunk books the stream
# queue it sits behind as prefill time, where the blocking admission
# path booked it as queue wait). The composite p50 TTFT fence plus the
# queue_wait fences below still catch any real end-to-end regression.
METRICS_LOWER = {
    "serve_sustained_p50_ttft_s": [
        ("detail", "serve", "sustained", "p50_ttft_s"),
        ("detail", "sustained", "p50_ttft_s")],
    "serve_ttft_queue_wait_s": [
        ("detail", "serve", "sustained", "ttft_breakdown", "queue_wait_s"),
        ("detail", "sustained", "ttft_breakdown", "queue_wait_s")],
    # queue wait as a SHARE of TTFT (round 8: the continuous-admission
    # acceptance number — was ~68% of sustained p50 before admission
    # between decode chunks; absent in older baselines: skipped)
    "serve_ttft_queue_wait_share": [
        ("detail", "serve", "sustained", "ttft_breakdown",
         "queue_wait_share"),
        ("detail", "sustained", "ttft_breakdown", "queue_wait_share")],
    "serve_ttft_ship_s": [
        ("detail", "serve", "sustained", "ttft_breakdown", "ship_s"),
        ("detail", "sustained", "ttft_breakdown", "ship_s")],
}

# ABSOLUTE ceilings checked on the NEW doc alone (no baseline diff):
# ratios that must sit near zero regardless of history, where a
# relative fence would let the value creep up 10% per round forever.
# Round 9: tracing-enabled hot-path overhead — the per-call tracing
# probe delta amortized over the measured per-op cost (the round-4
# probe-gate methodology; bench_core produces it, and
# tests/test_tracing_plane.py gates the same ratio in-test) must stay
# under 3%. Key absent (pre-round-9 doc): skipped.
METRICS_CEILING = {
    "tracing_hot_path_overhead_ratio": (
        [("detail", "core", "tracing_overhead", "ratio"),
         ("detail", "tracing_overhead", "ratio")],
        0.03),
    # training telemetry stamping cost amortized over the steady-state
    # per-step wall (min-of-k probe delta, same methodology) must stay
    # under 1% — the ISSUE-13 acceptance fence
    "train_telemetry_overhead_ratio": (
        [("detail", "train_telemetry", "telemetry_overhead", "ratio"),
         ("detail", "telemetry_overhead", "ratio")],
        0.01),
    # log-plane capture cost: per-LINE emit delta (stamped tee write
    # minus a plain write) amortized over the per-op cost must stay
    # under 3% — the ISSUE-14 acceptance fence (ship/store/echo run
    # off-process; the emit is the whole hot-path tax)
    "log_capture_overhead_ratio": (
        [("detail", "core", "log_overhead", "ratio"),
         ("detail", "log_overhead", "ratio")],
        0.03),
    # crash chaos soak (round 10): conservation is absolute — a single
    # lost or wedged call is a failure regardless of history (ceiling
    # 0 means any violation trips the gate), and the per-class MTTR
    # means fence recovery latency. Keys absent (doc from another
    # bench mode): skipped.
    "chaos_soak_invariant_violations": (
        [("detail", "chaos_soak", "chaos_soak_invariant_violations"),
         ("detail", "chaos_soak_invariant_violations")],
        0.0),
    "chaos_mttr_replica_mean_s": (
        [("detail", "chaos_soak", "chaos_mttr_replica_mean_s"),
         ("detail", "chaos_mttr_replica_mean_s")],
        5.0),
    "chaos_mttr_raylet_mean_s": (
        [("detail", "chaos_soak", "chaos_mttr_raylet_mean_s"),
         ("detail", "chaos_mttr_raylet_mean_s")],
        10.0),
    # health-probe tax on a serving replica (probe rate x min ping RTT,
    # a deliberate over-estimate) must stay under 1% — proactive
    # failover may not cost serving throughput (ISSUE-16 guard vs the
    # round-8 serve plane)
    "serve_probe_overhead_ratio": (
        [("detail", "chaos_soak", "probe_overhead", "ratio"),
         ("detail", "probe_overhead", "ratio")],
        0.01),
    # memory plane (round 11): owner-side accounting tax on a put —
    # callsite capture + owned-table store probe delta (min-of-k)
    # amortized over the measured per-put cost must stay under 3%
    # (ISSUE-17 acceptance fence; same probe methodology as tracing
    # and log above)
    "memory_accounting_overhead_ratio": (
        [("detail", "core", "memory_accounting_overhead", "ratio"),
         ("detail", "memory_accounting_overhead", "ratio")],
        0.03),
}

# train metric paths only exist in full-run docs; the train bench value
# doubles as core_tasks in core-only docs — guard that collision
_TRAIN_ONLY = {"train_tokens_per_sec_per_chip"}


def _dig_one(doc: dict, path: tuple):
    cur = doc
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return None
        cur = cur[key]
    return cur if isinstance(cur, (int, float)) else None


def _dig(doc: dict, name: str):
    if name in _TRAIN_ONLY and doc.get("metric") != \
            "llama_train_tokens_per_sec_per_chip":
        return None
    for path in METRICS.get(name) or METRICS_LOWER[name]:
        v = _dig_one(doc, path)
        if v is not None:
            return v
    return None


def _load(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    # driver-recorded rounds wrap the bench line under "parsed" (null
    # when the driver could not parse the bench tail — fall back to the
    # wrapper so the gate skips its metrics instead of crashing)
    return doc.get("parsed") or doc


def main(argv: list[str]) -> int:
    fence = float(os.environ.get("PERF_GATE_FENCE", "0.10"))
    if len(argv) >= 3:
        new_path, old_path = argv[1], argv[2]
    else:
        rounds = sorted(
            glob.glob("BENCH_r*.json"),
            key=lambda p: int(re.search(r"r(\d+)", p).group(1)))
        if len(rounds) < 2:
            print("perf gate: fewer than two BENCH_r*.json rounds; skip")
            return 0
        old_path, new_path = rounds[-2], rounds[-1]
    new, old = _load(new_path), _load(old_path)
    print(f"perf gate: {new_path} vs {old_path} (fence {fence:.0%})")
    failures = []
    for name in METRICS:
        a, b = _dig(new, name), _dig(old, name)
        if a is None or b is None or b <= 0:
            continue
        delta = a / b - 1.0
        flag = "REGRESSION" if delta < -fence else "ok"
        print(f"  {name:34s} {b:>12.1f} -> {a:>12.1f}  "
              f"{delta:+7.1%}  {flag}")
        if delta < -fence:
            failures.append((name, b, a, delta))
    for name in METRICS_LOWER:
        a, b = _dig(new, name), _dig(old, name)
        if a is None or b is None or b <= 0:
            continue
        delta = a / b - 1.0
        flag = "REGRESSION" if delta > fence else "ok"
        print(f"  {name:34s} {b:>12.4f} -> {a:>12.4f}  "
              f"{delta:+7.1%}  {flag} (lower=better)")
        if delta > fence:
            failures.append((name, b, a, delta))
    for name, (paths, ceiling) in METRICS_CEILING.items():
        a = None
        for path in paths:
            a = _dig_one(new, path)
            if a is not None:
                break
        if a is None:
            continue
        flag = "REGRESSION" if a > ceiling else "ok"
        print(f"  {name:34s} {'(ceiling)':>12s} -> {a:>12.5f}  "
              f"< {ceiling:.2f}  {flag}")
        if a > ceiling:
            failures.append((name, ceiling, a, a - ceiling))
    if failures:
        print(f"perf gate: {len(failures)} metric(s) regressed past "
              f"the {fence:.0%} fence")
        return 1
    print("perf gate: pass")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
