#!/usr/bin/env bash
# CI pipeline for ray_tpu (reference analog: the reference's ci/ +
# .buildkite pipelines — lint, C++ build + sanitizer suites, Python
# tests, multi-chip dryrun). Run locally with `bash ci/run_ci.sh`;
# .github/workflows/ci.yml invokes the same stages.
set -euo pipefail
cd "$(dirname "$0")/.."

stage() { echo; echo "=== CI stage: $1 ==="; }

# --nightly: ONLY the scaled scalability-envelope tier (minutes; the
# reference runs its envelope nightly on real clusters —
# release/benchmarks/README.md)
if [ "${1:-}" = "--nightly" ]; then
  stage "nightly scalability envelope (2k actors / 1M tasks / 5k args / 4 nodes)"
  python -m pytest tests/test_envelope_nightly.py -m nightly -q -s
  stage "nightly fork-server envelope (10k actors via preforked zygotes)"
  JAX_PLATFORMS=cpu python -m pytest tests/test_fork_envelope_nightly.py \
    -m nightly -q -s
  stage "nightly actor control plane (40k actors through the batched plane)"
  JAX_PLATFORMS=cpu python -m pytest tests/test_actor_plane_nightly.py \
    -m nightly -q -s
  stage "nightly serve soak (paged engine page/refcount flatness)"
  python -m pytest tests/test_serve_soak_nightly.py -m nightly -q -s
  stage "nightly serve autoscaling swing (square wave, pushed metrics)"
  JAX_PLATFORMS=cpu python -m pytest tests/test_serve_autoscale_nightly.py \
    -m nightly -q -s
  stage "nightly RL plane (pixel-obs throughput + learning)"
  # conftest forces the 8-device virtual CPU platform the mesh
  # learners need
  python -m pytest tests/test_rllib_extras.py -m nightly -q -s
  stage "nightly chaos matrix (raylet<->raylet + owner<->worker partitions)"
  # the full partition matrix holds each cut across >= 2 heartbeat
  # timeouts; the fast default tier runs only the driver<->GCS smoke
  JAX_PLATFORMS=cpu python -m pytest tests/test_chaos_partitions.py \
    -m nightly -q -s
  stage "nightly crash chaos soak (3 seeds x 300s, worker/replica/raylet/GCS + partitions)"
  # seeded process-kill + partition schedule over a mixed workload
  # (tasks, actors, serve) with conservation invariants: every
  # submitted call resolves or raises typed, nothing wedges, planes
  # stay intact. The gate fences violations==0, the per-class MTTR
  # means, and the <1% health-probe overhead guard (ISSUE-16).
  JAX_PLATFORMS=cpu CHAOS_SOAK_SEEDS=0,1,2 CHAOS_SOAK_DURATION=300 \
    CHAOS_SOAK_OUT=/tmp/chaos_nightly.json \
    BENCH_MODE=chaos_soak python bench.py > /tmp/bench_chaos_ci.json
  python ci/perf_gate.py /tmp/bench_chaos_ci.json \
    "$(ls BENCH_r*.json 2>/dev/null | sort -V | tail -1 || echo /tmp/bench_chaos_ci.json)"
  stage "nightly log plane (rotation holds disk bounded under worker churn at scale)"
  # a flood of printing workers must keep the node's log dir under the
  # rotation budget (max_bytes * (rotate_count+1) per proc) while every
  # line still reaches the store — proves capture rotation + monitor
  # cleanup hold disk bounded for the envelope tiers above
  JAX_PLATFORMS=cpu python -m pytest tests/test_log_plane_nightly.py \
    -m nightly -q -s
  stage "nightly memory leak soak (50k ref churn across 2 raylets, planted leak)"
  # churns >= 50k owned refs through put/submit/release cycles on a
  # two-external-raylet cluster: the leak detector must flag ZERO
  # false positives on the churn (refs die promptly), then flag
  # exactly the one deliberately-held ref with its creation call site
  JAX_PLATFORMS=cpu python -m pytest tests/test_memory_leak_nightly.py \
    -m nightly -q -s
  stage "nightly train telemetry leg (step decomposition + goodput + overhead fence)"
  # telemetry-ON train leg: asserts decomposition sums to step wall and
  # stamping overhead < 1% of steady step wall; the gate re-checks the
  # ceiling against the emitted doc
  JAX_PLATFORMS=cpu BENCH_MODE=train_telemetry python bench.py \
    > /tmp/bench_train_telemetry_ci.json
  python ci/perf_gate.py /tmp/bench_train_telemetry_ci.json \
    "$(ls BENCH_r*.json 2>/dev/null | sort -V | tail -1 || echo /tmp/bench_train_telemetry_ci.json)"
  echo "nightly tiers: green"
  exit 0
fi

stage "lint (syntax + bytecode compile of every source)"
python -m compileall -q ray_tpu tests bench.py __graft_entry__.py

stage "native build (shm store, collectives, scheduler, capi, crc)"
make -C src -j"$(nproc)" all

if [ "${SKIP_SANITIZERS:-0}" != "1" ]; then
  stage "native sanitizer suites (ASan + TSan on the shm store)"
  make -C src sanitizers
fi

stage "python unit + integration tests"
python -m pytest tests/ -x -q

stage "multi-chip dryrun (virtual 8-device mesh: fsdp_tp/sp/ep/pp/hybrid)"
# SKIP_1B here: the flagship leg has its own gated stage below (the
# driver's dryrun runs it INLINE via dryrun_multichip's default)
SKIP_1B=1 JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

if [ "${SKIP_1B:-0}" != "1" ]; then
  stage "flagship-size dryrun (1.0B params, fsdp over 8 virtual devices; minutes)"
  python -c "import __graft_entry__ as g; g.dryrun_multichip_1b(8)"
fi

if [ "${SKIP_PERF_GATE:-0}" != "1" ]; then
  stage "perf gate (current tree's core bench vs last round, ±10% fence)"
  LAST_BENCH=$(ls BENCH_r*.json 2>/dev/null | sort -V | tail -1 || true)
  if [ -n "$LAST_BENCH" ]; then
    BENCH_MODE=core BENCH_CORE_OPS=2000 python bench.py > /tmp/bench_core_ci.json
    python ci/perf_gate.py /tmp/bench_core_ci.json "$LAST_BENCH"
  else
    echo "no recorded BENCH_r*.json; skipping gate"
  fi
fi

stage "single-chip compile check of the flagship entry"
JAX_PLATFORMS=cpu python - <<'EOF'
import jax
import __graft_entry__ as g
fn, args = g.entry()
jax.jit(fn).lower(*args).compile()
print("entry() compiles")
EOF

echo
echo "CI: all stages green"
