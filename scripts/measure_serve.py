"""Measure the serve engine's latency anatomy on the real chip:
per-dispatch overhead vs chunk size, decode step time, and prefill
time — the numbers that decide the TTFT/throughput tradeoff (tunnel
RTT ~100ms is the TTFT floor; chunk time is the queue-wait).

Usage:
  PYTHONPATH=/root/repo:/root/.axon_site python scripts/measure_serve.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import llama
from ray_tpu.serve.paged_llm import PagedLLMEngine


def main():
    cfg = llama.LlamaConfig(
        vocab_size=32768, d_model=1536, n_layers=12, n_heads=12,
        n_kv_heads=4, head_dim=128, d_ff=6144, remat="none",
    )
    params = llama.init_params(cfg, jax.random.key(0))

    # --- sync RTT ---
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((4,))
    np.asarray(f(x))
    t = time.perf_counter()
    for _ in range(5):
        np.asarray(f(x))
    rtt = (time.perf_counter() - t) / 5
    print(f"sync RTT: {rtt*1e3:.1f} ms", flush=True)

    eng = PagedLLMEngine(params=params, cfg=cfg, max_batch=20,
                         max_len=2048, decode_chunk=32)
    dev = {
        "lens": jnp.asarray(np.full(20, 128, np.int32)),
        "active": jnp.asarray(np.ones(20, bool)),
        "temps": jnp.asarray(np.zeros(20, np.float32)),
    }
    last = jnp.asarray(np.ones(20, np.int32))

    for chunk in (2, 4, 8, 16, 32):
        t0 = time.perf_counter()
        toks, lens, _ = eng._decode_call(chunk, last, dev)
        np.asarray(toks)
        compile_s = time.perf_counter() - t0
        dev["lens"] = jnp.asarray(np.full(20, 128, np.int32))
        reps = max(2, 96 // chunk)
        t0 = time.perf_counter()
        cur = last
        for _ in range(reps):
            toks, lens, _ = eng._decode_call(chunk, cur, dev)
            dev["lens"] = lens
            cur = toks[-1]          # data dependency: relay can't memoize
        np.asarray(toks)
        el = time.perf_counter() - t0
        per_chunk = el / reps
        print(f"chunk {chunk:2d}: {per_chunk*1e3:7.1f} ms/chunk  "
              f"{per_chunk/chunk*1e3:6.2f} ms/step  "
              f"{20*chunk/per_chunk:6.0f} tok/s@b20  "
              f"(compile {compile_s:.1f}s)", flush=True)

    # --- prefill dispatch+sync time at a couple of batch sizes ---
    rng = np.random.default_rng(0)
    for nb in (1, 4):
        # reserve slots 0..nb-1 manually via the engine internals
        class R:
            temperature = 0.0
            max_new_tokens = 4
        items = []
        for s in range(nb):
            r = R()
            r.prompt = rng.integers(1, 32000, 128).astype(np.int32)
            ok = eng._reserve_slot_resources(r, s)
            assert ok
            items.append(eng._pack_admit(r, s, 128))
        t0 = time.perf_counter()
        firsts = eng._dispatch_prefill(items, len(items[0][3]))
        np.asarray(firsts)
        el = time.perf_counter() - t0
        # free the pages again
        for s in range(nb):
            eng._on_slot_retired(s)
        eng._age_deferred_frees(drain_all=True)
        print(f"prefill b{nb} (dispatch+sync, first incl compile): "
              f"{el*1e3:.1f} ms", flush=True)
        t0 = time.perf_counter()
        for s in range(nb):
            r = R()
            r.prompt = rng.integers(1, 32000, 128).astype(np.int32)
            eng._reserve_slot_resources(r, s)
        items = [eng._pack_admit(r, s, 128) for s in range(nb)]
        firsts = eng._dispatch_prefill(items, len(items[0][3]))
        np.asarray(firsts)
        el = time.perf_counter() - t0
        for s in range(nb):
            eng._on_slot_retired(s)
        eng._age_deferred_frees(drain_all=True)
        print(f"prefill b{nb} warm: {el*1e3:.1f} ms", flush=True)


if __name__ == "__main__":
    main()
