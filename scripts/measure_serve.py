"""Measure the serve engine's latency anatomy on the real chip:
per-dispatch overhead vs chunk size, decode step time vs batch, and
prefill time — the numbers that decide the TTFT/throughput tradeoff
(tunnel RTT ~100ms is the TTFT floor; chunk time is the queue-wait).

Usage: cd /root/repo && python scripts/measure_serve.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import llama
from ray_tpu.serve.paged_llm import PagedLLMEngine


def main():
    cfg = llama.LlamaConfig(
        vocab_size=32768, d_model=1536, n_layers=12, n_heads=12,
        n_kv_heads=4, head_dim=128, d_ff=6144, remat="none",
    )
    params = llama.init_params(cfg, jax.random.key(0))

    # --- sync RTT ---
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((4,))
    np.asarray(f(x))
    t = time.perf_counter()
    for _ in range(5):
        np.asarray(f(x))
    rtt = (time.perf_counter() - t) / 5
    print(f"sync RTT: {rtt*1e3:.1f} ms")

    for chunk in (1, 2, 4, 8, 16, 32):
        eng = PagedLLMEngine(params=params, cfg=cfg, max_batch=20,
                             max_len=2048, decode_chunk=chunk)
        eng.warmup(128)
        # simulate the decode loop: N chained chunk dispatches with one
        # final sync — measures per-chunk cost incl. dispatch overhead.
        # MUST chain through a data dependency (relay memoizes identical
        # dispatches).
        dev = {
            "lens": jnp.asarray(np.full(20, 128, np.int32)),
            "active": jnp.asarray(np.ones(20, bool)),
            "temps": jnp.asarray(np.zeros(20, np.float32)),
        }
        last = jnp.asarray(np.ones(20, np.int32))
        # warm the decode program
        toks, lens = eng._decode_call(chunk, last, dev)
        np.asarray(toks)
        reps = max(1, 64 // chunk)
        dev["lens"] = jnp.asarray(np.full(20, 128, np.int32))
        t0 = time.perf_counter()
        cur = last
        for _ in range(reps):
            toks, lens = eng._decode_call(chunk, cur, dev)
            dev["lens"] = lens
            cur = toks[-1]
        np.asarray(toks)
        el = time.perf_counter() - t0
        per_chunk = el / reps
        per_step = per_chunk / chunk
        print(f"chunk {chunk:2d}: {per_chunk*1e3:7.1f} ms/chunk  "
              f"{per_step*1e3:6.2f} ms/step  "
              f"({20*chunk/per_chunk:.0f} tok/s at batch 20)")
        eng.stop()

    # --- prefill time (batch 1 and 4, 128 tokens) ---
    eng = PagedLLMEngine(params=params, cfg=cfg, max_batch=20,
                         max_len=2048, decode_chunk=8)
    eng.warmup(128)
    rng = np.random.default_rng(0)
    for nb in (1, 2, 4):
        # time via engine submit of nb requests at once, measuring the
        # admit dispatch+sync inside; approximate with direct call:
        t0 = time.perf_counter()
        reqs = [eng.submit(rng.integers(1, 32000, 128), max_new_tokens=1)
                for _ in range(nb)]
        for r in reqs:
            list(r.tokens())
        el = time.perf_counter() - t0
        print(f"prefill batch {nb}: {el*1e3:.1f} ms end-to-end "
              f"(incl ~1 RTT + loop latency)")
    eng.stop()


if __name__ == "__main__":
    main()
