"""RL plane throughput bench: vectorized rollouts + LearnerGroup
env-steps/s on a pixel-shaped (84x84) observation env.

Reference analog: the rllib suites in ``release/release_tests.yaml``
(Atari/MuJoCo-class throughput runs) — this gives the RL plane a
recorded perf number like train/serve/core have.

Usage (the mesh learner mode wants >1 device — use the virtual CPU
mesh):

    cd /root/repo && JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/run_rl_bench.py [round]

Writes RLBENCH_r{N}.json at the repo root.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

def main():
    rnd = sys.argv[1] if len(sys.argv) > 1 else "05"
    # the axon sitecustomize forces its platform regardless of
    # JAX_PLATFORMS: re-init as an 8-device virtual CPU platform (same
    # mechanism as __graft_entry__.dryrun_multichip)
    import __graft_entry__ as graft

    graft._force_cpu_platform(8)
    import ray_tpu
    from ray_tpu.rllib import IMPALAConfig

    ray_tpu.init(num_cpus=8, num_tpus=0)
    try:
        algo = (IMPALAConfig()
                .environment("PixelCartPole-v0")
                .rollouts(num_rollout_workers=2, num_envs_per_worker=8)
                .training(unroll_length=32, num_learners=2,
                          learner_mode="mesh", hidden=128, seed=0)
                .build())
        # warm one iteration (spawns workers, compiles the learner)
        t0 = time.monotonic()
        algo.train()
        warm_s = time.monotonic() - t0
        # run until BOTH floors are met: a minimum wall-clock (default
        # 30s — a 2s single-shot measurement is one scheduler draw, not
        # a throughput number) and a minimum iteration count (variance
        # needs samples). Per-iteration rates are recorded so the
        # artifact itself shows spread, not just the mean.
        min_elapsed = float(os.environ.get("RL_BENCH_MIN_ELAPSED_S", "30"))
        min_iters = int(os.environ.get("RL_BENCH_MIN_ITERS", "8"))
        steps_per_iter = 2 * 8 * 32     # workers * envs * unroll
        iter_rates = []
        t0 = time.monotonic()
        steps = 0
        while len(iter_rates) < min_iters or \
                time.monotonic() - t0 < min_elapsed:
            it0 = time.monotonic()
            algo.train()
            iter_rates.append(
                round(steps_per_iter / (time.monotonic() - it0), 1))
            steps += steps_per_iter
        el = time.monotonic() - t0
        algo.stop()
        mean = sum(iter_rates) / len(iter_rates)
        std = (sum((r - mean) ** 2 for r in iter_rates)
               / len(iter_rates)) ** 0.5
        out = {
            "metric": "rl_env_steps_per_sec",
            "value": round(steps / el, 1),
            "unit": "env-steps/s",
            "detail": {
                "env": "PixelCartPole-v0 (84x84 pixel obs)",
                "obs_dim": 84 * 84,
                "rollout_workers": 2,
                "envs_per_worker": 8,
                "unroll_length": 32,
                "learners": 2,
                "learner_mode": "mesh",
                "iters": len(iter_rates),
                "elapsed_s": round(el, 1),
                "first_iter_s": round(warm_s, 1),
                "iter_rates": iter_rates,
                "iter_rate_mean": round(mean, 1),
                "iter_rate_std": round(std, 1),
                "iter_rate_min": min(iter_rates),
                "iter_rate_max": max(iter_rates),
            },
        }
    finally:
        ray_tpu.shutdown()
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), f"RLBENCH_r{rnd}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
