#!/usr/bin/env python
"""Chaos soak CLI: seeded crash/partition schedule over a mixed
workload, conservation invariants checked at the end.

    python scripts/run_chaos_soak.py --duration 300 --seeds 0,1,2 \
        --out CHAOS_r10.json

Exit code 0 iff zero invariant violations across all seeds. See
docs/crash_chaos.md for the crash-point catalog and the per-class MTTR
definitions this reports.
"""

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--duration", type=float, default=300.0,
                    help="soak length per seed, seconds")
    ap.add_argument("--seeds", default="0",
                    help="comma-separated seeds (one soak per seed)")
    ap.add_argument("--classes", default="worker,replica,raylet,gcs",
                    help="fault classes to inject")
    ap.add_argument("--no-partitions", action="store_true",
                    help="skip metrics-plane partition faults")
    ap.add_argument("--inject-period", type=float, default=8.0,
                    help="mean seconds between injections")
    ap.add_argument("--out", default="CHAOS_r10.json",
                    help="report path ('' to skip writing)")
    args = ap.parse_args(argv)

    from ray_tpu.chaos_soak import run_soak_matrix

    seeds = [int(s) for s in args.seeds.split(",") if s.strip()]
    classes = tuple(c.strip() for c in args.classes.split(",")
                    if c.strip())
    report = run_soak_matrix(
        args.duration, seeds, classes,
        out_path=args.out or None,
        partitions=not args.no_partitions,
        inject_period_s=args.inject_period)
    bad = report["chaos_soak_invariant_violations"]
    for sd, run in report["runs"].items():
        w = run["workloads"]
        print(f"seed {sd}: "
              + ", ".join(f"{n}={s['ok']}/{s['submitted']} ok"
                          f" (+{s['typed_errors']} typed)"
                          for n, s in w.items())
              + f", violations={run['chaos_soak_invariant_violations']}")
        for cls, entry in run["per_class"].items():
            keys = [k for k in entry if k.endswith("_mean_s")]
            stats = ", ".join(f"{k}={entry[k]:.2f}" for k in keys)
            print(f"  {cls}: {entry['injections']} injections"
                  + (f", {stats}" if stats else ""))
    if bad:
        print(f"CHAOS SOAK FAILED: {bad} invariant violations")
        for sd, run in report["runs"].items():
            for v in run["violations"]:
                print(f"  seed {sd}: {json.dumps(v, default=str)}")
        return 1
    print("chaos soak: conservation held (0 violations)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
