"""Sweep flash-attention block configs at the longctx bench shape.

Measures achieved TFLOP/s of a full gradient (fwd + dq + dkv kernels)
through ``ray_tpu.ops.flash_attention`` at the bench "longctx" shape
(b=1, s=16384, 12 q heads / 4 kv heads, d=128) and the headline shape
(b=8, s=2048).  Run on the real chip:  python scripts/sweep_flash.py
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from ray_tpu.ops.flash_attention import flash_attention


def attn_flops(b, s, h, d, causal=True):
    # fwd: 2 matmuls (QK^T, PV): 2 * 2*b*h*s*s*d ; causal halves it
    f = 4 * b * h * s * s * d
    if causal:
        f //= 2
    # bwd: dq pass (2 matmuls: dOV^T, dS K) + recomputed S (1) = 3
    # dkv pass (dV, dK, recomputed S, dOV^T) = 4  -> 7 matmul-equivalents
    bwd = 7 * 2 * b * h * s * s * d // (2 if causal else 1)
    return f + bwd


def bench_cfg(b, s, hq, hkv, d, bq, bk, iters=20):
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (b, s, hq, d), jnp.bfloat16)
    k = jax.random.normal(key, (b, s, hkv, d), jnp.bfloat16)
    v = jax.random.normal(key, (b, s, hkv, d), jnp.bfloat16)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    # chain iterations through a data dependency: identical repeated
    # dispatches can be memoized by the device transport, so every
    # iteration must consume the previous one's output
    def step(qq, _):
        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(qq, k, v)
        return qq + 1e-6 * dq.astype(qq.dtype), None

    runner = jax.jit(lambda qq: jax.lax.scan(step, qq, None, length=iters)[0])
    try:
        r = runner(q)
        jax.block_until_ready(r)
    except Exception as e:  # noqa: BLE001
        first_line = (str(e).splitlines() or [""])[0]
        print(f"  bq={bq} bk={bk}: FAIL {type(e).__name__}: "
              f"{first_line[:120]}")
        return None
    t0 = time.perf_counter()
    r = runner(q)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / iters
    fl = attn_flops(b, s, hq, d)
    print(f"  bq={bq:5d} bk={bk:5d}: {dt*1e3:8.2f} ms  "
          f"{fl/dt/1e12:6.2f} TF/s")
    return dt


def main():
    print(f"devices: {jax.devices()}")
    for (b, s, hq, hkv, d, tag) in [
        (1, 16384, 12, 4, 128, "longctx"),
        (8, 2048, 12, 4, 128, "headline"),
    ]:
        print(f"== {tag}: b={b} s={s} hq={hq} hkv={hkv} d={d}")
        for bq, bk in [(256, 1024), (512, 512), (512, 1024), (1024, 512),
                       (1024, 1024), (512, 2048)]:
            if bq > s or bk > s:
                continue
            bench_cfg(b, s, hq, hkv, d, bq, bk)


if __name__ == "__main__":
    main()
