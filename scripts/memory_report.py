#!/usr/bin/env python
"""Cluster memory report: ownership-attributed object accounting in a
`ray memory`-style table (who owns what, pinned vs spilled vs
in-process, creation call sites, make-room pressure attribution).

    python scripts/memory_report.py --address 127.0.0.1:6379
    python scripts/memory_report.py --address ... --leaks
    python scripts/memory_report.py --address ... --watch 5

Omitting --address starts a local runtime and reports this process
only. See docs/memory_plane.md.
"""

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--address", default=None,
                    help="GCS host:port (omit for a local runtime)")
    ap.add_argument("--top", type=int, default=20,
                    help="rows per table section")
    ap.add_argument("--json", action="store_true",
                    help="raw summary JSON instead of tables")
    ap.add_argument("--leaks", action="store_true",
                    help="suspected leaked refs only")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SEC",
                    help="re-render every SEC seconds until ^C")
    args = ap.parse_args(argv)

    import ray_tpu
    from ray_tpu.scripts.cli import _fmt_bytes, _print_table, \
        render_memory_summary
    from ray_tpu.util import state as _state

    if args.address:
        ray_tpu.init(address=args.address)
    else:
        ray_tpu.init()
    try:
        while True:
            if args.leaks:
                leaks = _state.memory_leaks()
                if args.json:
                    print(json.dumps(leaks, indent=2, default=str))
                elif not leaks:
                    print("no suspected leaks")
                else:
                    _print_table(
                        ["OBJECT ID", "SIZE", "OWNER", "AGE", "IDLE",
                         "CALLSITE"],
                        [[lk["object_id"][:16],
                          _fmt_bytes(lk["size_bytes"]),
                          lk["owner"][:12], f"{lk['age_s']:.0f}s",
                          f"{lk['owner_idle_s']:.0f}s",
                          lk.get("callsite") or "-"]
                         for lk in leaks])
            else:
                summary = _state.memory_summary(top_n=args.top)
                if args.json:
                    print(json.dumps(summary, indent=2, default=str))
                else:
                    print(render_memory_summary(summary, top=args.top))
            if not args.watch:
                return 0
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    sys.exit(main())
