"""Run the scalability-envelope axes and write ENVELOPE_r{N}.json.

Reference analog: ``release/benchmarks/README.md:9-31`` — the reference
proves its envelope nightly (40k actors / 1M queued tasks / 10k args).
This runs the same axes on one host over a real multi-raylet cluster
(external OS processes) and records timings in a driver/judge-visible
artifact.

Usage: cd /root/repo && python scripts/run_envelope.py [round_number]
Sizes come from the envelope_nightly_* flags
(RAY_TPU_ENVELOPE_NIGHTLY_* env overrides).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the 2k-actor fork storm can starve the driver's heartbeat thread for
# minutes on a small host — a reaped LIVE driver loses its actors mid-
# flood (same reason the node heartbeat_timeout is 90s below)
os.environ.setdefault("RAY_TPU_CLIENT_TIMEOUT_S", "600")
# tail actors of a 500-wide creation wave can take minutes to come
# ALIVE on a saturated host — the default 60s resolve deadline is sized
# for interactive use, not envelope floods
os.environ.setdefault("RAY_TPU_ACTOR_RESOLVE_TIMEOUT_S", "1800")

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.utils.config import get_config


def main():
    rnd = sys.argv[1] if len(sys.argv) > 1 else "06"
    cfg = get_config()
    n_actors = cfg.envelope_nightly_actors
    n_queued = cfg.envelope_nightly_queued_tasks
    n_args = cfg.envelope_nightly_task_args
    n_plane = cfg.envelope_nightly_plane_actors
    plane_window = cfg.envelope_plane_window
    # ENVELOPE_AXES=queued_tasks,actors reruns a subset, merging into an
    # existing artifact (axes are independent; a 25-minute all-axes run
    # must not be repeated to redo one)
    axes = set((os.environ.get("ENVELOPE_AXES")
                or "queued_tasks,task_args,actors,plane").split(","))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), f"ENVELOPE_r{rnd}.json")
    out: dict = {"axes": {}, "nodes": 4,
                 "reference_scale": {"actors": 40_000,
                                     "queued_tasks": 1_000_000,
                                     "task_args": 10_000}}
    if os.path.exists(path):
        with open(path) as f:
            prev = json.load(f)
        out["axes"].update(prev.get("axes", {}))

    def save():
        # written after EVERY axis: a late failure must not discard a
        # 20-minute drain measurement
        with open(path, "w") as f:
            json.dump(out, f, indent=1)

    c = Cluster(external_gcs=True, heartbeat_timeout_s=90.0)
    c.add_node(num_cpus=4)
    for _ in range(3):
        c.add_node(num_cpus=4, external=True)
    c.wait_for_nodes(4)
    ray_tpu.init(address=c.gcs_address)
    try:
        # --- queued-task drain (reference scale: 1M) ---
        @ray_tpu.remote
        def nop(i):
            return i

        if "queued_tasks" not in axes:
            n_queued = 0
        window = 250_000
        t0 = time.monotonic()
        done = 0
        while done < n_queued:
            take = min(window, n_queued - done)
            refs = [nop.remote(done + i) for i in range(take)]
            vals = ray_tpu.get(refs, timeout=1800)
            assert vals[0] == done and vals[-1] == done + take - 1
            done += take
            print(f"  drained {done}/{n_queued}", flush=True)
        el = time.monotonic() - t0
        if n_queued:
            out["axes"]["queued_tasks"] = {
                "n": n_queued, "window": window,
                "drain_s": round(el, 1),
                "tasks_per_sec": round(n_queued / el, 1)}
            print(f"queued_tasks: {n_queued} in {el:.1f}s "
                  f"({n_queued/el:.0f}/s)", flush=True)
            save()

        # --- many-args ---
        if "task_args" in axes:
            refs = [ray_tpu.put(i) for i in range(n_args)]

            @ray_tpu.remote
            def consume(*xs):
                return sum(xs)

            t0 = time.monotonic()
            total = ray_tpu.get(consume.remote(*refs), timeout=600)
            assert total == sum(range(n_args))
            out["axes"]["task_args"] = {
                "n": n_args,
                "roundtrip_s": round(time.monotonic() - t0, 2)}
            print(f"task_args: {n_args} ok", flush=True)
            save()

        # --- actor flood ---
        if "actors" not in axes:
            n_actors = 0

        @ray_tpu.remote(num_cpus=0)
        class A:
            def __init__(self, i):
                self.i = i

            def who(self):
                return self.i

        from ray_tpu.runtime import core as _core
        from ray_tpu.runtime.rpc import RpcClient

        rt = _core.get_runtime()
        gcs_probe = RpcClient(tuple(c.gcs_address), label="driver")
        gcs_probe.call("actor_plane_stats", reset=True)
        polls0 = getattr(rt, "_actor_get_polls", 0)
        t0 = time.monotonic()
        actors = [A.remote(i) for i in range(n_actors)]
        submit_s = time.monotonic() - t0
        # drain the registration coalescer so register_s isolates the
        # batched GCS ingest from placement + worker spawn
        if actors and hasattr(rt, "_reg_drain"):
            for a in actors:
                rt._reg_drain(a._actor_id.hex())
        register_s = time.monotonic() - t0
        try:
            got = ray_tpu.get([a.who.remote() for a in actors],
                              timeout=3600) if actors else []
            create_s = time.monotonic() - t0
            assert got == list(range(n_actors))
            if actors:
                t1 = time.monotonic()
                got2 = ray_tpu.get([a.who.remote() for a in actors],
                                   timeout=600)
                steady_s = time.monotonic() - t1
                assert got2 == got
                plane = gcs_probe.call("actor_plane_stats")
                out["axes"]["actors"] = {
                    "n": n_actors,
                    "create_and_first_call_s": round(create_s, 1),
                    "steady_round_trip_s": round(steady_s, 1),
                    "steady_calls_per_sec": round(n_actors / steady_s,
                                                  1),
                    "phases": {
                        "submit_s": round(submit_s, 2),
                        "register_s": round(register_s, 2),
                        "register_batches": plane["register_batches"],
                        "register_batch_max":
                            plane["register_batch_max"],
                        "host_batches": plane["host_batches"],
                        "host_batch_max": plane["host_batch_max"],
                        "ready_batches": plane["ready_batches"],
                        "place_mean_ms": round(
                            1e3 * plane["place_s"]
                            / max(1, plane["placed"]), 2),
                        "ready_mean_ms": round(
                            1e3 * plane["ready_s"]
                            / max(1, plane["ready"]), 2),
                    },
                    "resolve_fallback_polls":
                        getattr(rt, "_actor_get_polls", 0) - polls0}
                print(f"actors: {n_actors} created+called in "
                      f"{create_s:.1f}s; steady round {steady_s:.1f}s",
                      flush=True)
                save()
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:  # noqa: BLE001
                    pass

        # --- batched control plane at reference scale (40k actors) ---
        # windowed ramp (same shape as the fork-envelope nightly): each
        # window of actors is created, called once, and killed before
        # the next, so 40k actors flow through the registration /
        # placement / ready plane while at most `plane_window` are live
        if "plane" in axes and n_plane:
            gcs_probe.call("actor_plane_stats", reset=True)
            polls0 = getattr(rt, "_actor_get_polls", 0)
            t0 = time.monotonic()
            done = 0
            steady_s = 0.0
            while done < n_plane:
                take = min(plane_window, n_plane - done)
                wave = [A.remote(done + i) for i in range(take)]
                got = ray_tpu.get([a.who.remote() for a in wave],
                                  timeout=1800)
                assert got == list(range(done, done + take))
                # warm second round: every actor answers again off the
                # pushed location table — summed across all waves this
                # is the 40k steady-state calls/s
                t1 = time.monotonic()
                got2 = ray_tpu.get([a.who.remote() for a in wave],
                                   timeout=600)
                steady_s += time.monotonic() - t1
                assert got2 == got
                for a in wave:
                    try:
                        ray_tpu.kill(a)
                    except Exception:  # noqa: BLE001
                        pass
                done += take
                if done % 5000 == 0 or done == n_plane:
                    el = time.monotonic() - t0
                    print(f"  plane {done}/{n_plane} "
                          f"({done/el:.0f} actors/s)", flush=True)
            el = time.monotonic() - t0
            plane = gcs_probe.call("actor_plane_stats")
            out["axes"]["plane"] = {
                "n": n_plane, "window": plane_window,
                "elapsed_s": round(el, 1),
                "actors_per_sec": round(n_plane / el, 1),
                "create_and_first_call_s": round(el - steady_s, 1),
                "created_per_sec": round(n_plane / (el - steady_s), 1),
                "steady_round_trip_s": round(steady_s, 1),
                "steady_calls_per_sec": round(n_plane / steady_s, 1),
                "register_batches": plane["register_batches"],
                "register_batch_max": plane["register_batch_max"],
                "host_batches": plane["host_batches"],
                "host_batch_max": plane["host_batch_max"],
                "place_mean_ms": round(
                    1e3 * plane["place_s"] / max(1, plane["placed"]),
                    2),
                "ready_mean_ms": round(
                    1e3 * plane["ready_s"] / max(1, plane["ready"]), 2),
                "resolve_fallback_polls":
                    getattr(rt, "_actor_get_polls", 0) - polls0}
            print(f"plane: {n_plane} actors through the batched plane "
                  f"in {el:.1f}s ({n_plane/el:.0f}/s)", flush=True)
            save()
    finally:
        ray_tpu.shutdown()
        c.shutdown()

    save()
    print(f"wrote {path}")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
