"""Run the scalability-envelope axes and write ENVELOPE_r{N}.json.

Reference analog: ``release/benchmarks/README.md:9-31`` — the reference
proves its envelope nightly (40k actors / 1M queued tasks / 10k args).
This runs the same axes on one host over a real multi-raylet cluster
(external OS processes) and records timings in a driver/judge-visible
artifact.

Usage: cd /root/repo && python scripts/run_envelope.py [round_number]
Sizes come from the envelope_nightly_* flags
(RAY_TPU_ENVELOPE_NIGHTLY_* env overrides).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the 2k-actor fork storm can starve the driver's heartbeat thread for
# minutes on a small host — a reaped LIVE driver loses its actors mid-
# flood (same reason the node heartbeat_timeout is 90s below)
os.environ.setdefault("RAY_TPU_CLIENT_TIMEOUT_S", "600")

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.utils.config import get_config


def main():
    rnd = sys.argv[1] if len(sys.argv) > 1 else "05"
    cfg = get_config()
    n_actors = cfg.envelope_nightly_actors
    n_queued = cfg.envelope_nightly_queued_tasks
    n_args = cfg.envelope_nightly_task_args
    # ENVELOPE_AXES=queued_tasks,actors reruns a subset, merging into an
    # existing artifact (axes are independent; a 25-minute all-axes run
    # must not be repeated to redo one)
    axes = set((os.environ.get("ENVELOPE_AXES")
                or "queued_tasks,task_args,actors").split(","))
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), f"ENVELOPE_r{rnd}.json")
    out: dict = {"axes": {}, "nodes": 4,
                 "reference_scale": {"actors": 40_000,
                                     "queued_tasks": 1_000_000,
                                     "task_args": 10_000}}
    if os.path.exists(path):
        with open(path) as f:
            prev = json.load(f)
        out["axes"].update(prev.get("axes", {}))

    def save():
        # written after EVERY axis: a late failure must not discard a
        # 20-minute drain measurement
        with open(path, "w") as f:
            json.dump(out, f, indent=1)

    c = Cluster(external_gcs=True, heartbeat_timeout_s=90.0)
    c.add_node(num_cpus=4)
    for _ in range(3):
        c.add_node(num_cpus=4, external=True)
    c.wait_for_nodes(4)
    ray_tpu.init(address=c.gcs_address)
    try:
        # --- queued-task drain (reference scale: 1M) ---
        @ray_tpu.remote
        def nop(i):
            return i

        if "queued_tasks" not in axes:
            n_queued = 0
        window = 250_000
        t0 = time.monotonic()
        done = 0
        while done < n_queued:
            take = min(window, n_queued - done)
            refs = [nop.remote(done + i) for i in range(take)]
            vals = ray_tpu.get(refs, timeout=1800)
            assert vals[0] == done and vals[-1] == done + take - 1
            done += take
            print(f"  drained {done}/{n_queued}", flush=True)
        el = time.monotonic() - t0
        if n_queued:
            out["axes"]["queued_tasks"] = {
                "n": n_queued, "window": window,
                "drain_s": round(el, 1),
                "tasks_per_sec": round(n_queued / el, 1)}
            print(f"queued_tasks: {n_queued} in {el:.1f}s "
                  f"({n_queued/el:.0f}/s)", flush=True)
            save()

        # --- many-args ---
        if "task_args" in axes:
            refs = [ray_tpu.put(i) for i in range(n_args)]

            @ray_tpu.remote
            def consume(*xs):
                return sum(xs)

            t0 = time.monotonic()
            total = ray_tpu.get(consume.remote(*refs), timeout=600)
            assert total == sum(range(n_args))
            out["axes"]["task_args"] = {
                "n": n_args,
                "roundtrip_s": round(time.monotonic() - t0, 2)}
            print(f"task_args: {n_args} ok", flush=True)
            save()

        # --- actor flood ---
        if "actors" not in axes:
            n_actors = 0

        @ray_tpu.remote(num_cpus=0)
        class A:
            def __init__(self, i):
                self.i = i

            def who(self):
                return self.i

        t0 = time.monotonic()
        actors = [A.remote(i) for i in range(n_actors)]
        try:
            got = ray_tpu.get([a.who.remote() for a in actors],
                              timeout=3600) if actors else []
            create_s = time.monotonic() - t0
            assert got == list(range(n_actors))
            if actors:
                t1 = time.monotonic()
                got2 = ray_tpu.get([a.who.remote() for a in actors],
                                   timeout=600)
                steady_s = time.monotonic() - t1
                assert got2 == got
                out["axes"]["actors"] = {
                    "n": n_actors,
                    "create_and_first_call_s": round(create_s, 1),
                    "steady_round_trip_s": round(steady_s, 1),
                    "steady_calls_per_sec": round(n_actors / steady_s,
                                                  1)}
                print(f"actors: {n_actors} created+called in "
                      f"{create_s:.1f}s; steady round {steady_s:.1f}s",
                      flush=True)
                save()
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:  # noqa: BLE001
                    pass
    finally:
        ray_tpu.shutdown()
        c.shutdown()

    save()
    print(f"wrote {path}")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
