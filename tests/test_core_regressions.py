"""Regression tests for scheduling/ownership edge cases found in review."""

import time

import pytest

import ray_tpu
from ray_tpu.utils.exceptions import ActorDiedError, TaskError


def test_non_iterable_with_num_returns_raises_not_hangs(ray_tpu_start):
    @ray_tpu.remote(num_returns=2)
    def bad():
        return 5  # not iterable

    a, b = bad.remote()
    with pytest.raises(TaskError):
        ray_tpu.get(a, timeout=5)


def test_wrong_return_count_raises(ray_tpu_start):
    @ray_tpu.remote(num_returns=3)
    def two():
        return 1, 2

    refs = two.remote()
    with pytest.raises(TaskError, match="num_returns=3"):
        ray_tpu.get(refs[0], timeout=5)


def test_infeasible_task_fails_fast(ray_tpu_start):
    @ray_tpu.remote(num_cpus=999)
    def f():
        return 1

    with pytest.raises(ValueError, match="exceeds cluster capacity"):
        f.remote()


def test_big_task_does_not_starve_small(ray_tpu_start):
    # A queued 8-CPU task must not block a 1-CPU task behind it while the
    # 8 CPUs are partly held (no head-of-line blocking).
    @ray_tpu.remote(num_cpus=4)
    def hold():
        time.sleep(1.0)
        return "held"

    @ray_tpu.remote(num_cpus=8)
    def big():
        return "big"

    @ray_tpu.remote(num_cpus=1)
    def small():
        return "small"

    h = hold.remote()
    b = big.remote()  # cannot run until hold finishes
    s = small.remote()  # fits right now; must not wait behind big
    assert ray_tpu.get(s, timeout=0.5) == "small"
    assert ray_tpu.get([h, b], timeout=10) == ["held", "big"]


def test_actor_ordering_with_late_dependency(ray_tpu_start):
    # An earlier actor call blocked on a slow dependency must still execute
    # before a later dependency-free call on the same actor.
    @ray_tpu.remote
    def slow_value():
        time.sleep(0.3)
        return 10

    @ray_tpu.remote
    class Box:
        def __init__(self):
            self.v = 0

        def set(self, v):
            self.v = v
            return self.v

        def read(self):
            return self.v

    box = Box.remote()
    set_ref = box.set.remote(slow_value.remote())
    read_ref = box.read.remote()  # submitted later; must see v=10
    assert ray_tpu.get(read_ref, timeout=5) == 10
    assert ray_tpu.get(set_ref) == 10


def test_kill_fails_inflight_calls_not_hang(ray_tpu_start):
    @ray_tpu.remote
    class Slow:
        def work(self, t):
            time.sleep(t)
            return t

    s = Slow.remote()
    r1 = s.work.remote(0.5)
    r2 = s.work.remote(0.5)  # queued behind r1
    time.sleep(0.1)
    ray_tpu.kill(s)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(r2, timeout=5)


def test_actor_resources_held_for_lifetime(ray_tpu_start):
    @ray_tpu.remote(num_cpus=3)
    class Holder:
        def ping(self):
            return "pong"

    h1 = Holder.remote()
    h2 = Holder.remote()
    assert ray_tpu.get(h1.ping.remote()) == "pong"
    assert ray_tpu.get(h2.ping.remote()) == "pong"
    avail = ray_tpu.available_resources()
    assert avail["CPU"] == pytest.approx(2.0)  # 8 - 2*3
    ray_tpu.kill(h1)
    time.sleep(0.1)
    assert ray_tpu.available_resources()["CPU"] == pytest.approx(5.0)


def test_options_typo_rejected_everywhere(ray_tpu_start):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(ValueError, match="Invalid task options"):
        f.options(num_gpus=1)

    @ray_tpu.remote
    class A:
        def m(self):
            return 1

    with pytest.raises(ValueError, match="Invalid actor options"):
        A.options(max_retrys=3)


def test_task_bookkeeping_cleanup(ray_tpu_start):
    @ray_tpu.remote
    def f(i):
        return i

    rt = ray_tpu_start
    refs = [f.remote(i) for i in range(50)]
    ray_tpu.get(refs)
    time.sleep(0.1)
    assert len(rt._return_owner) == 0


def test_as_future_threadless(ray_tpu_start):
    import threading

    @ray_tpu.remote
    def f():
        time.sleep(0.2)
        return 42

    refs = [f.remote() for _ in range(20)]
    time.sleep(0.3)  # let dispatch/overflow threads settle
    before = threading.active_count()
    futs = [r.future() for r in refs]
    assert threading.active_count() - before < 5  # no thread-per-future
    assert [x.result(timeout=5) for x in futs] == [42] * 20


def test_pool_saturation_actor_creation_no_deadlock(ray_tpu_start):
    """Tasks that fill every worker thread and then block on a named actor
    they create on-demand must not deadlock: actor creation runs on a
    dedicated thread and blocked workers grow the pool (reference analog:
    blocked ray.get releases the worker lease so new workers spawn)."""

    class Rendezvous:
        def __init__(self, n):
            self.n = n
            self.seen = set()

        def join(self, r):
            self.seen.add(r)
            return len(self.seen)

        def full(self):
            return len(self.seen) == self.n

    world = ray_tpu_start._pool._max_workers  # saturate exactly

    @ray_tpu.remote
    def rank_fn(rank, world):
        cls = ray_tpu.remote(Rendezvous)
        try:
            coord = cls.options(name="rdv", max_concurrency=4).remote(world)
        except ValueError:
            coord = ray_tpu.get_actor("rdv")
        ray_tpu.get(coord.join.remote(rank))
        deadline = time.monotonic() + 30
        while not ray_tpu.get(coord.full.remote()):
            if time.monotonic() > deadline:
                raise TimeoutError("rendezvous never completed")
            time.sleep(0.005)
        return rank

    outs = ray_tpu.get([rank_fn.remote(r, world) for r in range(world)],
                       timeout=60)
    assert sorted(outs) == list(range(world))


def test_nested_task_chain_no_pool_deadlock(ray_tpu_start):
    """Every worker blocks on a child task; pool growth must let the
    children run."""

    @ray_tpu.remote
    def child(x):
        return x + 1

    @ray_tpu.remote
    def parent(x):
        return ray_tpu.get(child.remote(x))

    n = ray_tpu_start._pool._max_workers
    assert ray_tpu.get([parent.remote(i) for i in range(n)],
                       timeout=60) == [i + 1 for i in range(n)]


def test_blocked_parent_releases_cpu_for_child():
    """Parent tasks holding every CPU block on children that also need
    CPUs: the blocked-worker protocol must release the parents' resources
    so the children can be admitted (reference: blocked ray.get releases
    the worker lease)."""
    import ray_tpu as rt_mod

    rt_mod.shutdown()
    rt_mod.init(num_cpus=2, num_tpus=0)
    try:
        @ray_tpu.remote(num_cpus=1)
        def child(x):
            return x * 2

        @ray_tpu.remote(num_cpus=1)
        def parent(x):
            return ray_tpu.get(child.remote(x))

        assert ray_tpu.get([parent.remote(i) for i in range(2)],
                           timeout=30) == [0, 2]
    finally:
        rt_mod.shutdown()
