"""Ulysses sequence-parallel attention + paged KV attention tests.
(both net-new vs the reference — SURVEY §2c SP rows; vLLM-style paging)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import reference_attention
from ray_tpu.parallel.mesh import create_mesh
from ray_tpu.parallel.ulysses import ulysses_attention_sharded


@pytest.fixture(scope="module")
def sp_mesh():
    devices = jax.devices()
    assert len(devices) >= 4
    return create_mesh({"sp": 4}, devices=devices[:4])


def test_ulysses_matches_dense(sp_mesh):
    b, s, h, d = 2, 32, 8, 16
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h, d), jnp.float32)

    expect = reference_attention(q, k, v, causal=True, scale=d ** -0.5)
    got = ulysses_attention_sharded(sp_mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_non_causal(sp_mesh):
    b, s, h, d = 1, 16, 4, 8
    key = jax.random.key(1)
    q = jax.random.normal(key, (b, s, h, d), jnp.float32)
    got = ulysses_attention_sharded(sp_mesh, q, q, q, causal=False)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, q) * (d ** -0.5)
    probs = jax.nn.softmax(logits, axis=-1)
    expect = jnp.einsum("bhqk,bkhd->bqhd", probs, q)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_head_divisibility_error(sp_mesh):
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.ulysses import ulysses_attention

    q = jnp.zeros((1, 8, 6, 4))  # 6 heads not divisible by sp=4
    spec = P(None, "sp", None, None)
    fn = jax.shard_map(ulysses_attention, mesh=sp_mesh,
                   in_specs=(spec, spec, spec), out_specs=spec)
    with pytest.raises(ValueError, match="divisible"):
        fn(q, q, q)


# ---------------------------------------------------------------------------
# paged attention
# ---------------------------------------------------------------------------

class _Cfg:
    n_layers = 2
    n_heads = 4
    n_kv_heads = 2
    head_dim = 8


def test_paged_matches_dense_decode():
    from ray_tpu.ops.paged_attention import (PageAllocator, assign_pages,
                                             init_paged_cache,
                                             paged_attention, paged_write)

    cfg = _Cfg()
    page = 4
    cache = init_paged_cache(cfg, num_pages=16, page_size=page,
                             max_batch=2, max_pages_per_seq=4,
                             dtype=jnp.float32)
    alloc = PageAllocator(16)

    rng = np.random.default_rng(0)
    lens = [7, 10]
    kv = {}
    for slot, n in enumerate(lens):
        cache = assign_pages(cache, alloc, slot, n)
        k_new = rng.normal(size=(n, cfg.n_kv_heads, cfg.head_dim)) \
            .astype(np.float32)
        v_new = rng.normal(size=(n, cfg.n_kv_heads, cfg.head_dim)) \
            .astype(np.float32)
        kv[slot] = (k_new, v_new)
        for layer in range(cfg.n_layers):
            cache = paged_write(cache, layer, slot, jnp.asarray(k_new),
                                jnp.asarray(v_new), 0)
        cache.lengths[slot] = n

    q = rng.normal(size=(2, cfg.n_heads, cfg.head_dim)).astype(np.float32)
    out = paged_attention(jnp.asarray(q), cache, layer=1)

    # dense reference per sequence (GQA: repeat kv heads)
    scale = cfg.head_dim ** -0.5
    for slot, n in enumerate(lens):
        k_new, v_new = kv[slot]
        n_rep = cfg.n_heads // cfg.n_kv_heads
        k_r = np.repeat(k_new, n_rep, axis=1)   # [n, nh, hd]
        v_r = np.repeat(v_new, n_rep, axis=1)
        logits = np.einsum("hd,khd->hk", q[slot], k_r) * scale
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        expect = np.einsum("hk,khd->hd", probs, v_r)
        np.testing.assert_allclose(np.asarray(out[slot]), expect,
                                   rtol=2e-4, atol=2e-4)


def test_page_allocator_reuse_and_exhaustion():
    from ray_tpu.ops.paged_attention import PageAllocator

    alloc = PageAllocator(4)
    a = alloc.alloc(0, 3)
    assert len(set(a)) == 3
    with pytest.raises(MemoryError):
        alloc.alloc(1, 2)
    alloc.free_slot(0)
    b = alloc.alloc(1, 4)
    assert len(set(b)) == 4
    assert alloc.pages_needed(7, 1, 4) == 0   # 7+1 = 8 fits in 2 pages
    assert alloc.pages_needed(8, 1, 4) == 1


def test_release_slot_frees_pages():
    from ray_tpu.ops.paged_attention import (PageAllocator, assign_pages,
                                             init_paged_cache,
                                             release_slot)

    cfg = _Cfg()
    cache = init_paged_cache(cfg, num_pages=8, page_size=4, max_batch=2,
                             max_pages_per_seq=4, dtype=jnp.float32)
    alloc = PageAllocator(8)
    cache = assign_pages(cache, alloc, 0, 16)  # 4 pages
    assert len(alloc.free) == 4
    # overflow raises the allocator's documented exhaustion error
    cache.lengths[0] = 16
    with pytest.raises(MemoryError):
        assign_pages(cache, alloc, 0, 1)
    cache = release_slot(cache, alloc, 0)
    assert len(alloc.free) == 8
    assert int(cache.lengths[0]) == 0
    assert np.all(np.asarray(cache.page_table)[0] == -1)


def test_paged_write_all_matches_per_layer():
    from ray_tpu.ops.paged_attention import (PageAllocator, assign_pages,
                                             init_paged_cache, paged_write,
                                             paged_write_all)

    cfg = _Cfg()
    rng = np.random.default_rng(3)
    kv = rng.normal(size=(cfg.n_layers, 6, cfg.n_kv_heads,
                          cfg.head_dim)).astype(np.float32)

    def fresh():
        c = init_paged_cache(cfg, num_pages=8, page_size=4, max_batch=1,
                             max_pages_per_seq=4, dtype=jnp.float32)
        a = PageAllocator(8)
        return assign_pages(c, a, 0, 6)

    c1 = fresh()
    for layer in range(cfg.n_layers):
        c1 = paged_write(c1, layer, 0, jnp.asarray(kv[layer]),
                         jnp.asarray(kv[layer]), 0)
    c2 = fresh()
    c2 = paged_write_all(c2, 0, jnp.asarray(kv), jnp.asarray(kv), 0)
    np.testing.assert_allclose(np.asarray(c1.k_pages),
                               np.asarray(c2.k_pages))
    np.testing.assert_allclose(np.asarray(c1.v_pages),
                               np.asarray(c2.v_pages))
