"""Runtime-env tests (reference analog: python/ray/tests/test_runtime_env*
— P4: env_vars / working_dir / py_modules, env-keyed worker caching)."""

import os
import sys
import textwrap

import pytest

import ray_tpu
from ray_tpu.runtime_env import RuntimeEnv, env_key, snapshot_dir


def test_runtime_env_validation(tmp_path):
    assert RuntimeEnv(pip=["requests"]) == {"pip": ["requests"]}
    with pytest.raises(ValueError):
        RuntimeEnv(bogus_field=1)
    with pytest.raises(ValueError):
        RuntimeEnv(working_dir=str(tmp_path / "missing"))
    with pytest.raises(TypeError):
        RuntimeEnv(env_vars={"A": 1})
    e = RuntimeEnv(env_vars={"A": "1"}, config={"x": 2})
    assert e.to_dict()["env_vars"] == {"A": "1"}


def test_env_key_stability():
    a = env_key({"env_vars": {"A": "1", "B": "2"}})
    b = env_key({"env_vars": {"B": "2", "A": "1"}})
    assert a == b
    assert env_key(None) == "" == env_key({})
    assert a != env_key({"env_vars": {"A": "x"}})


def test_snapshot_dir_content_addressed(tmp_path):
    d = tmp_path / "wd"
    d.mkdir()
    (d / "f.txt").write_text("hello")
    s1 = snapshot_dir(str(d))
    s2 = snapshot_dir(str(d))
    assert s1 == s2
    assert open(os.path.join(s1, "f.txt")).read() == "hello"
    (d / "f.txt").write_text("changed")
    s3 = snapshot_dir(str(d))
    assert s3 != s1


def test_env_vars_local_mode(ray_tpu_start):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_TEST_VAR": "abc"}})
    def f():
        return os.environ.get("MY_TEST_VAR")

    @ray_tpu.remote
    def g():
        return os.environ.get("MY_TEST_VAR")

    assert ray_tpu.get(f.remote()) == "abc"
    assert ray_tpu.get(g.remote()) is None  # restored after f


def test_env_vars_actor_local_mode(ray_tpu_start):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_VAR": "zzz"}})
    class A:
        def peek(self):
            return os.environ.get("ACTOR_VAR")

    a = A.remote()
    assert ray_tpu.get(a.peek.remote()) == "zzz"


def test_py_modules_local_mode(ray_tpu_start, tmp_path):
    mod = tmp_path / "my_test_module_rtenv"
    mod.mkdir()
    (mod / "__init__.py").write_text("VALUE = 41\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod)]})
    def f():
        import my_test_module_rtenv

        return my_test_module_rtenv.VALUE + 1

    try:
        assert ray_tpu.get(f.remote()) == 42
    finally:
        sys.modules.pop("my_test_module_rtenv", None)


def test_unsupported_field_fails_at_submit(ray_tpu_start):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(ValueError):
        f.options(runtime_env={"conda": {"deps": []}}).remote()


def test_cluster_worker_env_isolation(tmp_path):
    """Cluster mode: workers are cached per env key; env_vars land in the
    worker PROCESS env and different envs get different workers."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=4)
    try:
        ray_tpu.shutdown()
        rt = ray_tpu.init(address=cluster.gcs_address)

        @ray_tpu.remote(runtime_env={"env_vars": {"WORKER_FLAVOR": "a"}})
        def fa():
            return os.environ.get("WORKER_FLAVOR"), os.getpid()

        @ray_tpu.remote(runtime_env={"env_vars": {"WORKER_FLAVOR": "b"}})
        def fb():
            return os.environ.get("WORKER_FLAVOR"), os.getpid()

        (va, pa), (vb, pb) = ray_tpu.get([fa.remote(), fb.remote()])
        assert va == "a" and vb == "b"
        assert pa != pb  # different env -> different worker process
        # same env reuses the cached worker
        va2, pa2 = ray_tpu.get(fa.remote())
        assert va2 == "a" and pa2 == pa
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_cluster_working_dir(tmp_path):
    from ray_tpu.cluster_utils import Cluster

    wd = tmp_path / "wd"
    wd.mkdir()
    (wd / "data.txt").write_text("payload")
    (wd / "helper_mod_rtenv.py").write_text(
        textwrap.dedent("""
        def read():
            return open("data.txt").read()
        """))

    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)

        @ray_tpu.remote(runtime_env={"working_dir": str(wd)})
        def f():
            import helper_mod_rtenv

            return helper_mod_rtenv.read()

        assert ray_tpu.get(f.remote()) == "payload"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_nested_env_var_tasks_no_deadlock(ray_tpu_start):
    """A task with env_vars that blocks on a child with env_vars must not
    deadlock: the env session suspends while blocked in get()."""

    @ray_tpu.remote(runtime_env={"env_vars": {"CHILD_V": "c"}})
    def child():
        return os.environ.get("CHILD_V")

    @ray_tpu.remote(runtime_env={"env_vars": {"PARENT_V": "p"}})
    def parent():
        inner = ray_tpu.get(child.remote())
        # parent's overlay must be restored after the blocked get
        return inner, os.environ.get("PARENT_V")

    assert ray_tpu.get(parent.remote(), timeout=30) == ("c", "p")


def test_cluster_env_eviction_at_worker_cap():
    """A node at its worker cap with only mismatched-env idle workers
    must evict one to run a task with a new env, not starve forever."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=1)  # cap = 1 worker
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)

        @ray_tpu.remote
        def plain():
            return "plain"

        @ray_tpu.remote(runtime_env={"env_vars": {"EV": "1"}})
        def with_env():
            return os.environ.get("EV")

        assert ray_tpu.get(plain.remote(), timeout=30) == "plain"
        # pool is now one idle worker with env_key="" — must be evicted
        assert ray_tpu.get(with_env.remote(), timeout=30) == "1"
        # and back again the other way
        assert ray_tpu.get(plain.remote(), timeout=30) == "plain"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_eviction_does_not_drain_warm_pool():
    """One new-env task at the cap must evict at most one warm worker,
    not one per dispatch retry while the replacement boots."""
    import time as _time

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)

        import tempfile as _tf

        barrier_dir = _tf.mkdtemp()

        @ray_tpu.remote
        def plain(i, bdir):
            # filesystem barrier: both tasks must be in flight at once so
            # the raylet provably spawns TWO workers (under load, quick
            # sequential tasks can share one)
            open(os.path.join(bdir, f"in{i}"), "w").close()
            deadline = _time.monotonic() + 20
            while len(os.listdir(bdir)) < 2:
                if _time.monotonic() > deadline:
                    raise TimeoutError("barrier")
                _time.sleep(0.01)
            return os.getpid()

        # warm two default-env workers (cap is reached)
        pids = set(ray_tpu.get(
            [plain.remote(i, barrier_dir) for i in range(2)], timeout=30))
        assert len(pids) == 2

        @ray_tpu.remote(runtime_env={"env_vars": {"EVICT_T": "1"}})
        def with_env():
            return os.environ.get("EVICT_T")

        assert ray_tpu.get(with_env.remote(), timeout=30) == "1"
        _time.sleep(0.5)  # let any (wrong) cascade evictions play out
        raylet = next(iter(cluster.nodes.values())).raylet
        alive_default = [
            w for w in raylet._workers.values()
            if w.state in ("idle", "busy") and w.env_key == ""
        ]
        # exactly one default worker was evicted; the other survived
        assert len(alive_default) == 1, [
            (w.state, w.env_key) for w in raylet._workers.values()]
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_cluster_tracing_spans(tmp_path):
    """Cluster mode: run spans must appear even though workers were
    spawned by the raylet (trace dir rides the wire context)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util import tracing

    trace_dir = str(tmp_path / "tr")
    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)
        tracing.enable_tracing(trace_dir)

        @ray_tpu.remote
        def traced():
            return 7

        with tracing.span("cluster-root"):
            assert ray_tpu.get(traced.remote(), timeout=30) == 7

        spans = tracing.read_spans(trace_dir)
        assert any(s["name"].startswith("run:") for s in spans), spans
        root = next(s for s in spans if s["name"] == "cluster-root")
        run = next(s for s in spans if s["name"].startswith("run:"))
        assert run["trace_id"] == root["trace_id"]
    finally:
        tracing.disable_tracing()
        ray_tpu.shutdown()
        cluster.shutdown()


@pytest.fixture
def local_package(tmp_path):
    """A tiny hand-assembled WHEEL — installs offline with no build
    backend (this image ships no setuptools, and build isolation would
    try to download one)."""
    import base64
    import hashlib
    import zipfile

    whl = tmp_path / "tinylib-0.0.1-py3-none-any.whl"
    files = {
        "tinylib/__init__.py": b"MAGIC = 'tiny-42'\n",
        "tinylib-0.0.1.dist-info/METADATA":
            b"Metadata-Version: 2.1\nName: tinylib\nVersion: 0.0.1\n",
        "tinylib-0.0.1.dist-info/WHEEL":
            b"Wheel-Version: 1.0\nGenerator: test\nRoot-Is-Purelib: true"
            b"\nTag: py3-none-any\n",
    }
    record_rows = []
    for name, data in files.items():
        digest = base64.urlsafe_b64encode(
            hashlib.sha256(data).digest()).rstrip(b"=").decode()
        record_rows.append(f"{name},sha256={digest},{len(data)}")
    record_rows.append("tinylib-0.0.1.dist-info/RECORD,,")
    with zipfile.ZipFile(whl, "w") as z:
        for name, data in files.items():
            z.writestr(name, data)
        z.writestr("tinylib-0.0.1.dist-info/RECORD",
                   "\n".join(record_rows) + "\n")
    return str(whl)


def test_pip_runtime_env_installs_into_venv(local_package, tmp_path,
                                            monkeypatch):
    """The pip plugin builds a cached venv and tasks in that env import
    the package (reference: _private/runtime_env/pip.py)."""
    monkeypatch.setenv("RAY_TPU_RUNTIME_ENV_CACHE", str(tmp_path / "cache"))
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=1)
    ray_tpu.init(address=c.gcs_address)
    try:
        @ray_tpu.remote(runtime_env={"pip": [local_package]})
        def probe():
            import tinylib
            return tinylib.MAGIC

        assert ray_tpu.get(probe.remote(), timeout=300) == "tiny-42"

        # plain-env tasks must NOT see the package
        @ray_tpu.remote
        def plain():
            try:
                import tinylib  # noqa: F401
                return "leaked"
            except ImportError:
                return "isolated"

        assert ray_tpu.get(plain.remote(), timeout=60) == "isolated"
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_pip_env_cached_across_calls(local_package, tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_RUNTIME_ENV_CACHE", str(tmp_path / "cache"))
    import time

    from ray_tpu.runtime_env import ensure_pip_env

    t0 = time.monotonic()
    site1 = ensure_pip_env([local_package])
    first = time.monotonic() - t0
    t0 = time.monotonic()
    site2 = ensure_pip_env([local_package])
    second = time.monotonic() - t0
    assert site1 == site2
    assert second < first / 5, (first, second)


def test_pip_env_validation():
    from ray_tpu.runtime_env import RuntimeEnv

    assert RuntimeEnv(pip=["numpy"]) == {"pip": ["numpy"]}
    assert RuntimeEnv(pip={"packages": ["x"]}) == {"pip": ["x"]}
    with pytest.raises(TypeError):
        RuntimeEnv(pip=[1, 2])
    with pytest.raises(ValueError, match="conda"):
        RuntimeEnv(conda={"dependencies": []})


def test_bad_pip_env_fails_fast(tmp_path, monkeypatch):
    """A failing install surfaces as RuntimeEnvSetupError instead of an
    infinite worker spawn/install/crash loop."""
    monkeypatch.setenv("RAY_TPU_RUNTIME_ENV_CACHE", str(tmp_path / "cache"))
    import time

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.utils.exceptions import RayTpuError

    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=1)
    ray_tpu.init(address=c.gcs_address)
    try:
        @ray_tpu.remote(runtime_env={
            "pip": ["/definitely/not/a/package/path"]})
        def broken():
            return 1

        start = time.monotonic()
        with pytest.raises(RayTpuError, match="runtime env setup failed"):
            ray_tpu.get(broken.remote(), timeout=120)
        assert time.monotonic() - start < 90
    finally:
        ray_tpu.shutdown()
        c.shutdown()


# ---------------------------------------------------------------------------
# round-3: conda + container plugins
# ---------------------------------------------------------------------------

def test_runtime_env_accepts_conda_and_container():
    from ray_tpu.runtime_env import RuntimeEnv

    e = RuntimeEnv(conda="base",
                   container={"image": "python:3.12",
                              "run_options": ["--gpus=all"]})
    assert e["conda"] == "base"
    assert e["container"]["image"] == "python:3.12"
    e2 = RuntimeEnv(conda={"dependencies": ["numpy=1.26"]})
    assert e2["conda"]["dependencies"] == ["numpy=1.26"]
    import pytest

    with pytest.raises(ValueError):
        RuntimeEnv(conda={"name": "x"})          # no dependencies
    with pytest.raises(TypeError):
        RuntimeEnv(container={"run_options": []})  # no image


def test_container_command_construction():
    from ray_tpu.runtime_env import container_command

    cmd = container_command(
        {"image": "my/img:1", "run_options": ["--memory=4g"]},
        ["python", "-m", "ray_tpu.runtime.worker_main"],
        {"RAY_TPU_RAYLET_HOST": "127.0.0.1", "RAY_TPU_RAYLET_PORT": "5",
         "HOME": "/root", "JAX_PLATFORMS": "cpu"},
        runtime="docker", mounts=["/data"])
    assert cmd[0] == "docker" and cmd[1] == "run"
    assert "--network=host" in cmd and "--ipc=host" in cmd
    assert "-e=RAY_TPU_RAYLET_HOST=127.0.0.1" in cmd
    assert "-e=JAX_PLATFORMS=cpu" in cmd
    assert not any(c.startswith("-e=HOME") for c in cmd)  # filtered
    assert "-v=/data:/data" in cmd
    assert "--memory=4g" in cmd
    # image comes after options, worker argv last
    assert cmd.index("my/img:1") > cmd.index("--memory=4g")
    assert cmd[-3:] == ["python", "-m", "ray_tpu.runtime.worker_main"]


def test_conda_create_commands_and_missing_binary(monkeypatch):
    from ray_tpu import runtime_env as re_mod

    cmds = re_mod.conda_create_commands(
        {"dependencies": ["numpy", "pandas=2.2", {"pip": ["x"]}]},
        "/cache/conda/abc", "/opt/conda/bin/conda")
    assert cmds == [
        ["/opt/conda/bin/conda", "create", "--yes", "--quiet",
         "--prefix", "/cache/conda/abc", "numpy", "pandas=2.2"],
        # environment.yml pip subsection installs INSIDE the env
        ["/opt/conda/bin/conda", "run", "--prefix", "/cache/conda/abc",
         "python", "-m", "pip", "install", "--no-input", "x"],
    ]
    import pytest as _pt

    with _pt.raises(ValueError, match="unsupported conda dependency"):
        re_mod.conda_create_commands(
            {"dependencies": [["not-a-dep"]]}, "/d", "/c")
    monkeypatch.delenv("CONDA_EXE", raising=False)
    monkeypatch.setattr(re_mod.shutil, "which", lambda *_: None)
    import pytest

    with pytest.raises(RuntimeError, match="no conda"):
        re_mod.ensure_conda_env({"dependencies": ["numpy"]})


def test_conda_spec_env_with_stub_runner(monkeypatch, tmp_path):
    """Full ensure_conda_env flow with a stubbed conda binary + runner
    (the create is simulated by materializing the site-packages)."""
    import os

    from ray_tpu import runtime_env as re_mod

    monkeypatch.setenv("RAY_TPU_RUNTIME_ENV_CACHE", str(tmp_path))
    fake_conda = tmp_path / "bin" / "conda"
    fake_conda.parent.mkdir(parents=True)
    fake_conda.write_text("#!/bin/sh\n")
    monkeypatch.setenv("CONDA_EXE", str(fake_conda))
    calls = []

    def runner(cmd):
        calls.append(cmd)
        prefix = cmd[cmd.index("--prefix") + 1]
        os.makedirs(os.path.join(prefix, "lib", "python3.12",
                                 "site-packages"))

    site = re_mod.ensure_conda_env({"dependencies": ["numpy"]},
                                   runner=runner)
    assert site.endswith("site-packages")
    assert len(calls) == 1
    # second call hits the ready-marker cache: no new create
    site2 = re_mod.ensure_conda_env({"dependencies": ["numpy"]},
                                    runner=runner)
    assert site2 == site and len(calls) == 1


def test_container_env_fails_fast_without_runtime(monkeypatch):
    """No docker/podman: tasks with a container env get
    RuntimeEnvSetupError quickly, not a spawn loop."""
    import shutil as _sh

    import pytest

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.utils.exceptions import RayTpuError

    monkeypatch.setattr(_sh, "which", lambda name: None)
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=1)
    ray_tpu.init(address=c.gcs_address)
    try:
        @ray_tpu.remote(runtime_env={"container": {"image": "img:1"}})
        def f():
            return 1

        with pytest.raises((RayTpuError, Exception)) as ei:
            ray_tpu.get(f.remote(), timeout=60)
        assert "container" in str(ei.value) or "docker" in str(ei.value)
    finally:
        ray_tpu.shutdown()
        c.shutdown()
