"""Pipeline parallelism: numerics vs the plain scan forward, and training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama
from ray_tpu.parallel.mesh import create_mesh
from ray_tpu.parallel.pipeline import (
    llama_forward_pipelined,
    pipeline_apply,
    split_stages,
)


def test_split_stages():
    import jax.numpy as jnp

    p = {"w": jnp.arange(8.0).reshape(8, 1)}
    s = split_stages(p, 4)
    assert s["w"].shape == (4, 2, 1)
    with pytest.raises(ValueError, match="divisible"):
        split_stages(p, 3)


def test_pipeline_apply_identity_chain():
    # stage_fn multiplies by per-stage constant; with 4 stages the pipeline
    # must compose all stages in order for every microbatch.
    mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
    stage_params = {"c": jnp.array([[2.0], [3.0], [5.0], [7.0]])}  # [S, 1]
    x = jnp.ones((8, 2, 4))  # [M=8, mb=2, d=4]

    def stage_fn(sp, xm):
        return xm * sp["c"][0]

    out = pipeline_apply(stage_fn, stage_params, x, mesh=mesh, axis="pp")
    np.testing.assert_allclose(np.asarray(out), 2.0 * 3.0 * 5.0 * 7.0)


def test_pipeline_needs_enough_microbatches():
    mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
    stage_params = {"c": jnp.ones((4, 1))}
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(lambda sp, x: x, stage_params, jnp.ones((2, 1, 4)),
                       mesh=mesh)


def test_llama_pipelined_matches_plain():
    mesh = create_mesh({"pp": 2}, devices=jax.devices()[:2])
    cfg = llama.llama_tiny()  # 2 layers -> 1 per stage
    params = llama.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)

    want = np.asarray(llama.forward(cfg, params, tokens,
                                    attn_impl="reference"))
    got = np.asarray(
        jax.jit(
            lambda p, t: llama_forward_pipelined(
                cfg, p, t, mesh=mesh, n_microbatches=4
            )
        )(params, tokens)
    )
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)
    corr = np.corrcoef(got.ravel(), want.ravel())[0, 1]
    assert corr > 0.999, corr


def test_pipeline_value_and_grad_matches_autodiff():
    """1F1B grads == plain autodiff grads on a toy stage chain + head."""
    from ray_tpu.parallel.pipeline import pipeline_value_and_grad

    S, M, mb, s, d = 4, 8, 2, 3, 8
    mesh = create_mesh({"pp": S, "fsdp": 2}, devices=jax.devices()[:8])
    key = jax.random.key(0)
    stage_params = {"w": jax.random.normal(key, (S, 1, d, d)) * 0.3}
    head_params = {"h": jax.random.normal(jax.random.key(1), (d, 16)) * 0.3}
    x = jax.random.normal(jax.random.key(2), (M, mb, s, d))
    tgt = jax.random.randint(jax.random.key(3), (M, mb, s), 0, 16)
    msk = jnp.ones((M, mb, s), jnp.float32)

    def stage_fn(sp, xm):
        return jnp.tanh(xm @ sp["w"][0])

    def head_fn(hp, y, t, m):
        logits = (y @ hp["h"]).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        tl = jnp.take_along_axis(logits, t[..., None], -1).squeeze(-1)
        return jnp.sum((lse - tl) * m), jnp.sum(m)

    (loss_sum, w_sum), (d_sp, d_head, d_x) = jax.jit(
        lambda sp, hp, x: pipeline_value_and_grad(
            stage_fn, head_fn, sp, hp, x, tgt, msk, mesh=mesh, axis="pp")
    )(stage_params, head_params, x)

    def ref_loss(sp, hp, x):
        y = x
        for i in range(S):
            y = stage_fn({"w": sp["w"][i]}, y)
        l, w = head_fn(hp, y, tgt, msk)
        return l

    ref, (g_sp, g_hp, g_x) = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(
        jax.device_get(stage_params), jax.device_get(head_params),
        jax.device_get(x))
    ref = np.float64(jax.device_get(ref))
    np.testing.assert_allclose(float(loss_sum), ref, rtol=1e-5)
    assert float(w_sum) == M * mb * s
    np.testing.assert_allclose(np.asarray(d_sp["w"]), np.asarray(g_sp["w"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(d_head["h"]), np.asarray(g_hp["h"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(d_x), np.asarray(g_x),
                               rtol=1e-4, atol=1e-5)


def test_pp_fsdp_trainer_matches_fsdp():
    """JaxTrainer(strategy='pp_fsdp') step == fsdp step: same loss, same
    grad norm, same updated params (VERDICT r1 item 2's done-criterion)."""
    from ray_tpu.train.trainer import JaxTrainer, TrainConfig

    cfg = llama.llama_tiny(vocab_size=64)
    batch = jax.random.randint(jax.random.key(1), (8, 17), 0, 64,
                               dtype=jnp.int32)

    pp_mesh = create_mesh({"pp": 2, "dp": 2, "fsdp": 2},
                          devices=jax.devices()[:8])
    tr_pp = JaxTrainer(
        cfg, TrainConfig(strategy="pp_fsdp", warmup_steps=1, total_steps=10,
                         n_microbatches=4),
        mesh=pp_mesh)
    assert tr_pp.pp_axis == "pp"
    st_pp = tr_pp.init_state(jax.random.key(0))
    st_pp, m_pp = tr_pp.train_step(st_pp, batch)

    fsdp_mesh = create_mesh({"dp": 2, "fsdp": 2}, devices=jax.devices()[:4])
    tr_ref = JaxTrainer(
        cfg, TrainConfig(strategy="fsdp", warmup_steps=1, total_steps=10),
        mesh=fsdp_mesh)
    st_ref = tr_ref.init_state(jax.random.key(0))
    st_ref, m_ref = tr_ref.train_step(st_ref, batch)

    np.testing.assert_allclose(float(m_pp["loss"]), float(m_ref["loss"]),
                               rtol=1e-4)
    np.testing.assert_allclose(float(m_pp["grad_norm"]),
                               float(m_ref["grad_norm"]), rtol=1e-3)
    got = jax.device_get(st_pp.params)
    want = jax.device_get(st_ref.params)
    for path, a in jax.tree_util.tree_flatten_with_path(got)[0]:
        b_leaf = want
        for p in path:
            b_leaf = b_leaf[p.key]
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_leaf),
                                   rtol=2e-3, atol=2e-5, err_msg=str(path))


def test_llama_pipelined_trains():
    import optax

    mesh = create_mesh({"pp": 2}, devices=jax.devices()[:2])
    cfg = llama.llama_tiny(vocab_size=64)
    params = llama.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 17), 0, 64)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]

    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits = llama_forward_pipelined(cfg, p, inputs, mesh=mesh,
                                             n_microbatches=2)
            return llama.cross_entropy_loss(logits, targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses
