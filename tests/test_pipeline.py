"""Pipeline parallelism: numerics vs the plain scan forward, and training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama
from ray_tpu.parallel.mesh import create_mesh
from ray_tpu.parallel.pipeline import (
    llama_forward_pipelined,
    pipeline_apply,
    split_stages,
)


def test_split_stages():
    import jax.numpy as jnp

    p = {"w": jnp.arange(8.0).reshape(8, 1)}
    s = split_stages(p, 4)
    assert s["w"].shape == (4, 2, 1)
    with pytest.raises(ValueError, match="divisible"):
        split_stages(p, 3)


def test_pipeline_apply_identity_chain():
    # stage_fn multiplies by per-stage constant; with 4 stages the pipeline
    # must compose all stages in order for every microbatch.
    mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
    stage_params = {"c": jnp.array([[2.0], [3.0], [5.0], [7.0]])}  # [S, 1]
    x = jnp.ones((8, 2, 4))  # [M=8, mb=2, d=4]

    def stage_fn(sp, xm):
        return xm * sp["c"][0]

    out = pipeline_apply(stage_fn, stage_params, x, mesh=mesh, axis="pp")
    np.testing.assert_allclose(np.asarray(out), 2.0 * 3.0 * 5.0 * 7.0)


def test_pipeline_needs_enough_microbatches():
    mesh = create_mesh({"pp": 4}, devices=jax.devices()[:4])
    stage_params = {"c": jnp.ones((4, 1))}
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(lambda sp, x: x, stage_params, jnp.ones((2, 1, 4)),
                       mesh=mesh)


def test_llama_pipelined_matches_plain():
    mesh = create_mesh({"pp": 2}, devices=jax.devices()[:2])
    cfg = llama.llama_tiny()  # 2 layers -> 1 per stage
    params = llama.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)

    want = np.asarray(llama.forward(cfg, params, tokens,
                                    attn_impl="reference"))
    got = np.asarray(
        jax.jit(
            lambda p, t: llama_forward_pipelined(
                cfg, p, t, mesh=mesh, n_microbatches=4
            )
        )(params, tokens)
    )
    np.testing.assert_allclose(got, want, rtol=5e-2, atol=5e-2)
    corr = np.corrcoef(got.ravel(), want.ravel())[0, 1]
    assert corr > 0.999, corr


def test_llama_pipelined_trains():
    import optax

    mesh = create_mesh({"pp": 2}, devices=jax.devices()[:2])
    cfg = llama.llama_tiny(vocab_size=64)
    params = llama.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 17), 0, 64)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]

    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits = llama_forward_pipelined(cfg, p, inputs, mesh=mesh,
                                             n_microbatches=2)
            return llama.cross_entropy_loss(logits, targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses
