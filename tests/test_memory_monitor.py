"""Memory monitor: kill workers under host memory pressure, surface
OutOfMemoryError.

Reference analog: ``python/ray/tests/test_memory_pressure.py`` —
``MemoryMonitor`` (common/memory_monitor.h:52) + retriable-FIFO worker
killing policy (raylet/worker_killing_policy_retriable_fifo.cc).
"""

import os
import time

import pytest

pytestmark = pytest.mark.skipif(
    not os.path.exists("/proc/meminfo"),
    reason="host memory sampling reads /proc/meminfo (Linux only)")

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.utils.config import reset_config


@pytest.fixture
def pressure_cluster(monkeypatch):
    """Cluster whose raylet believes the host is ALWAYS above the memory
    threshold (0.01 used fraction triggers on any real host)."""
    monkeypatch.setenv("RAY_TPU_MEMORY_USAGE_THRESHOLD", "0.01")
    monkeypatch.setenv("RAY_TPU_MEMORY_MONITOR_REFRESH_MS", "100")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    monkeypatch.setenv("RAY_TPU_MEMORY_USAGE_THRESHOLD", "0")
    reset_config()


def test_oom_kill_surfaces_out_of_memory_error(pressure_cluster):
    @ray_tpu.remote(max_retries=0)
    def hog():
        time.sleep(30)   # stays busy until the monitor kills it
        return "survived"

    ref = hog.remote()
    with pytest.raises(ray_tpu.exceptions.OutOfMemoryError):
        ray_tpu.get(ref, timeout=30)


def test_monitor_disabled_leaves_workers_alone(monkeypatch):
    monkeypatch.setenv("RAY_TPU_MEMORY_USAGE_THRESHOLD", "0")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    try:
        @ray_tpu.remote
        def work():
            time.sleep(0.5)
            return 7

        assert ray_tpu.get(work.remote(), timeout=30) == 7
    finally:
        ray_tpu.shutdown()
        c.shutdown()
