"""MoE router/dispatch numerics and the Mixtral model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import mixtral
from ray_tpu.ops.moe import moe_ffn, router_topk


def test_router_dispatch_shapes_and_capacity():
    t, e, c, k = 16, 4, 4, 2
    logits = jax.random.normal(jax.random.key(0), (t, e))
    dispatch, combine, aux = router_topk(logits, top_k=k, capacity=c)
    assert dispatch.shape == (t, e, c)
    d = np.asarray(dispatch)
    # each (expert, slot) holds at most one token
    assert d.sum(axis=0).max() <= 1.0 + 1e-6
    # each token dispatched at most k times
    assert d.sum(axis=(1, 2)).max() <= k + 1e-6
    # combine weights per token sum to <= 1 (== 1 when nothing dropped)
    cw = np.asarray(combine).sum(axis=(1, 2))
    assert (cw <= 1.0 + 1e-5).all()
    assert float(aux) > 0


def test_router_respects_capacity_drop():
    # all tokens want expert 0; with capacity 2 only 2 survive per slot
    t, e = 8, 4
    logits = jnp.full((t, e), -10.0).at[:, 0].set(10.0)
    dispatch, combine, _ = router_topk(logits, top_k=1, capacity=2)
    d = np.asarray(dispatch)
    assert d[:, 0].sum() == 2.0  # only 2 tokens made it into expert 0
    assert d[:, 1:].sum() >= 0  # others may go nowhere in top-1


def test_moe_ffn_runs_and_differentiable():
    t, d, f, e = 32, 16, 32, 4
    key = jax.random.key(0)
    ks = jax.random.split(key, 5)
    x = jax.random.normal(ks[0], (t, d))
    router = jax.random.normal(ks[1], (d, e)) * 0.1
    wi_g = jax.random.normal(ks[2], (e, d, f)) * 0.1
    wi_u = jax.random.normal(ks[3], (e, d, f)) * 0.1
    wo = jax.random.normal(ks[4], (e, f, d)) * 0.1

    out, aux = moe_ffn(x, router, wi_g, wi_u, wo, top_k=2,
                       capacity_factor=2.0)
    assert out.shape == (t, d)
    assert np.isfinite(np.asarray(out)).all()

    g = jax.grad(
        lambda *ps: jnp.sum(moe_ffn(x, *ps, top_k=2, capacity_factor=2.0)[0] ** 2)
    )(router, wi_g, wi_u, wo)
    assert np.isfinite(np.asarray(g)).all()


def test_moe_generous_capacity_preserves_all_tokens():
    # with capacity >= t*k/e guaranteed roomy, no token drops: combine sums=1
    t, e = 16, 4
    logits = jax.random.normal(jax.random.key(0), (t, e))
    dispatch, combine, _ = router_topk(logits, top_k=2, capacity=t)
    np.testing.assert_allclose(np.asarray(combine).sum(axis=(1, 2)),
                               np.ones(t), rtol=1e-5)


def test_mixtral_forward_and_train():
    import optax

    cfg = mixtral.mixtral_tiny(vocab_size=64)
    params = mixtral.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 17), 0, 64)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]

    logits, aux = mixtral.forward(cfg, params, inputs, return_aux_loss=True)
    assert logits.shape == (4, 16, 64)
    assert np.isfinite(np.asarray(logits)).all()
    assert float(aux) > 0

    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: mixtral.loss_fn(cfg, p, inputs, targets)
        )(params)
        updates, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.95, losses


def test_mixtral_expert_parallel_sharding(cpu_mesh_devices):
    from ray_tpu.parallel.mesh import create_mesh
    from ray_tpu.parallel.sharding import PRESETS, shard_tree

    # fp32: in bf16, near-tie router decisions flip under sharded matmul
    # reduction order and reroute a fraction of tokens (expected behavior,
    # but it breaks exact parity checks).
    import dataclasses

    cfg = dataclasses.replace(mixtral.mixtral_tiny(), dtype="float32")
    params = mixtral.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)
    want = np.asarray(mixtral.forward(cfg, params, tokens))

    mesh = create_mesh({"ep": 4, "tp": 2})
    rules = PRESETS["moe"].with_overrides(batch=None)
    axes = mixtral.param_logical_axes(cfg)
    sharded = shard_tree(params, axes, mesh, rules)
    from jax.sharding import PartitionSpec as P

    assert sharded["blocks"]["wi_gate"].sharding.spec == P(None, "ep", None, "tp")

    got = np.asarray(
        jax.jit(lambda p, t: mixtral.forward(cfg, p, t))(sharded, tokens)
    )
    np.testing.assert_allclose(got, want, rtol=0.1, atol=0.1)
    corr = np.corrcoef(got.ravel(), want.ravel())[0, 1]
    assert corr > 0.999, corr
