"""Scalability envelope — the NIGHTLY tier (one order above CI smoke).

Reference analog: ``release/benchmarks/README.md:9-31`` — the reference
proves its envelope on real clusters nightly (40k actors, 1M queued
tasks, 10k args). This tier runs the same axes at 10x the CI smoke
sizes (2,000 actors, 200k queued tasks, 5,000 args) on a multi-raylet
cluster of external OS processes. Minutes, not seconds — selected only
by ``ci/run_ci.sh --nightly`` (``pytest -m nightly``).
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.utils.config import get_config

# slow as well: an explicit `-m 'not slow'` on the command line REPLACES
# the addopts default (`-m 'not nightly'`), and a minutes-long envelope
# tier must never ride into a bounded default/tier-1 run that way
pytestmark = [pytest.mark.nightly, pytest.mark.slow]

# tier sizes are flags (RAY_TPU_ENVELOPE_NIGHTLY_* env overrides):
# defaults 2,000 actors / 1,000,000 queued / 5,000 args
_N_ACTORS = get_config().envelope_nightly_actors
_N_QUEUED = get_config().envelope_nightly_queued_tasks
_N_ARGS = get_config().envelope_nightly_task_args


@pytest.fixture(scope="module")
def big_cluster():
    ray_tpu.shutdown()
    # 90s node-death timeout (reference: ~30s health-check window on
    # dedicated multi-core hosts): this tier runs 2k worker processes on
    # whatever host CI gives it — a raylet PROCESS starved of cpu for
    # tens of seconds must not get its node declared dead and its
    # objects tombstoned (liveness beats also ride a dedicated GCS
    # connection so they never queue behind flood control traffic)
    c = Cluster(external_gcs=True, heartbeat_timeout_s=90.0)
    # 3 external raylets + the head: every data/control plane hop is a
    # real OS-process boundary
    c.add_node(num_cpus=4)
    for _ in range(3):
        c.add_node(num_cpus=4, external=True)
    c.wait_for_nodes(4)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_2000_actors_alive(big_cluster):
    """2,000 concurrent trivial actors across 4 nodes (reference axis:
    40k cluster-wide on 64 hosts ~= 600/host; this is 500/host)."""
    @ray_tpu.remote(num_cpus=0)
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    n = _N_ACTORS
    t0 = time.monotonic()
    actors = [A.remote(i) for i in range(n)]
    try:
        # generous: spawning 2k interpreter processes is fork-bound —
        # on a starved CI host the ramp alone can take >10 minutes
        got = ray_tpu.get([a.who.remote() for a in actors], timeout=1800)
        create_s = time.monotonic() - t0
        assert got == list(range(n))
        # second round-trip on live actors (steady-state health)
        got2 = ray_tpu.get([a.who.remote() for a in actors], timeout=600)
        assert got2 == got
        print(f"\n{n} actors created+called in {create_s:.1f}s")
    finally:
        # ALWAYS reap: 2k leaked actor workers would starve the
        # module's remaining tests of the whole host
        for a in actors:
            ray_tpu.kill(a)


def test_1m_queued_tasks_drain(big_cluster):
    """1,000,000 no-op tasks queued at once all complete — REFERENCE
    SCALE for this axis (release/benchmarks/README.md:30: 1M on one
    m4.16xlarge). Submitted in windows so the host never holds 1M
    in-flight refs' results unconsumed."""
    @ray_tpu.remote
    def nop(i):
        return i

    n = _N_QUEUED
    window = 250_000
    t0 = time.monotonic()
    done = 0
    first_window_submit_s = None
    while done < n:
        take = min(window, n - done)
        refs = [nop.remote(done + i) for i in range(take)]
        if first_window_submit_s is None:
            first_window_submit_s = time.monotonic() - t0
        out = ray_tpu.get(refs, timeout=1800)
        assert len(out) == take and out[0] == done \
            and out[-1] == done + take - 1
        done += take
    total_s = time.monotonic() - t0
    print(f"\n{n} tasks: first-window submit {first_window_submit_s:.1f}s, "
          f"drain {total_s:.1f}s ({n / total_s:.0f} tasks/s)")


def test_5000_object_args_to_one_task(big_cluster):
    """One task consuming 5,000 ObjectRef args (reference axis: 10k)."""
    n = _N_ARGS
    refs = [ray_tpu.put(i) for i in range(n)]

    @ray_tpu.remote
    def consume(*xs):
        return sum(xs)

    assert ray_tpu.get(consume.remote(*refs),
                       timeout=600) == sum(range(n))


def test_flagship_1b_dryrun_in_subprocess():
    """The 1.0B-param fsdp-8 sharding dryrun (own subprocess: it
    re-initializes the jax platform)."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip_1b(8)"],
        capture_output=True, text=True, timeout=1200,
        cwd=str(__import__('pathlib').Path(__file__).resolve().parents[1]))
    assert out.returncode == 0, out.stderr[-2000:]
    assert "dryrun 1b ok" in out.stdout


def test_cross_node_task_spray(big_cluster):
    """Tasks land on every node (placement actually spreads under
    load); 4,000 tasks report their NODE id — a single node passing
    this is impossible, unlike a pid count (one 4-cpu node spawns 4+
    workers on its own)."""
    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    nodes = set(ray_tpu.get([where.remote() for _ in range(4000)],
                            timeout=600))
    # queue-depth spillback must spread the flood across every raylet
    assert len(nodes) == 4, f"flood stayed on {len(nodes)} node(s)"


def test_trace_context_survives_steady_actor_phase(big_cluster):
    """Round-9 tracing leg: the steady actor phase runs with tracing
    ENABLED and (a) a traced slice of the steady calls lands in the GCS
    TraceStore as ONE trace whose worker-side ``run:`` spans prove the
    context crossed real process boundaries at this scale, (b) the warm
    actor-location resolve rate — the ``envelope_actor_resolves_per_sec``
    axis ``ci/perf_gate.py`` fences — stays within 30% of the
    tracing-off rate measured seconds earlier in the same session. The
    bound is deliberately generous (nightly hosts are noisy); the tight
    <3% hot-path fence lives in tests/test_tracing_plane.py.
    """
    from ray_tpu import api
    from ray_tpu.util import state as state_api
    from ray_tpu.util import tracing

    @ray_tpu.remote(num_cpus=0)
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    n = _N_ACTORS
    actors = [A.remote(i) for i in range(n)]
    rt = api._runtime()
    try:
        assert ray_tpu.get([a.who.remote() for a in actors],
                           timeout=1800) == list(range(n))

        # baseline: warm location-resolve rate with tracing OFF
        t0 = time.monotonic()
        for a in actors:
            rt._actor_location(a._actor_id.hex())
        rate_off = n / max(time.monotonic() - t0, 1e-9)

        tracing.enable_tracing()
        try:
            # full steady round with tracing enabled; a bounded slice
            # rides inside ONE root span — the GCS store caps spans per
            # trace, and 2k submit+run pairs in a single trace would
            # blow past the cap while proving nothing more than 100 do.
            # The workers were spawned BEFORE enable_tracing(), so the
            # only way their spans exist at all is the wire context
            # carrying the switch across the RPC (execution_span
            # adoption) — exactly the survival this leg asserts.
            traced_slice = actors[:100]
            with tracing.span("nightly-steady") as root:
                ray_tpu.get([a.who.remote() for a in traced_slice],
                            timeout=600)
            ray_tpu.get([a.who.remote() for a in actors[100:]],
                        timeout=600)
            tid = root.trace_id

            # resolve rate again, tracing enabled
            t0 = time.monotonic()
            for a in actors:
                rt._actor_location(a._actor_id.hex())
            rate_on = n / max(time.monotonic() - t0, 1e-9)

            # context survived: worker-side run: spans for the traced
            # slice reached the GCS store under the SAME trace id
            trace = None
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                rt._metrics_pusher.flush_now()
                trace = state_api.get_trace(tid)
                if trace and any(s["name"].startswith("run:")
                                 for s in trace["spans"]):
                    break
                time.sleep(0.5)
            assert trace is not None, "trace never reached the GCS store"
            names = {s["name"] for s in trace["spans"]}
            assert any(nm.startswith("run:") for nm in names), names
            assert len({s["pid"] for s in trace["spans"]}) >= 2

            print(f"\nresolves/s: off={rate_off:.0f} on={rate_on:.0f} "
                  f"({rate_on / rate_off:.2f}x)")
            assert rate_on >= 0.7 * rate_off, (
                f"tracing regressed warm actor resolves: "
                f"{rate_on:.0f}/s vs {rate_off:.0f}/s tracing-off")
        finally:
            tracing.disable_tracing()
    finally:
        for a in actors:
            ray_tpu.kill(a)
