"""Tests for the widened data layer: groupby/aggregates, zip, column ops,
parquet IO, push-based shuffle, preprocessors.
(reference analogs: python/ray/data/tests/test_all_to_all.py,
test_parquet.py, preprocessors/)"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.data.aggregate import Count, Max, Mean, Min, Std, Sum
from ray_tpu.data.context import DataContext
from ray_tpu.data import preprocessors as pp


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def _table(n=20):
    return rd.from_numpy({
        "k": np.arange(n) % 3,
        "x": np.arange(n, dtype=np.float64),
    })


def test_groupby_aggregates(rt):
    rows = _table(9).groupby("k").sum("x").take_all()
    # k=0: 0+3+6=9, k=1: 1+4+7=12, k=2: 2+5+8=15
    got = {int(r["k"]): r["sum(x)"] for r in rows}
    assert got == {0: 9.0, 1: 12.0, 2: 15.0}

    rows = _table(9).groupby("k").count().take_all()
    assert all(r["count"] == 3 for r in rows)

    rows = _table(9).groupby("k").mean("x").take_all()
    assert {int(r["k"]): r["mean(x)"] for r in rows} == {
        0: 3.0, 1: 4.0, 2: 5.0}


def test_groupby_multi_agg_and_std(rt):
    out = _table(10).groupby("k").aggregate(Min("x"), Max("x"),
                                            Std("x", ddof=0)).take_all()
    r0 = next(r for r in out if int(r["k"]) == 0)
    vals = np.array([0.0, 3.0, 6.0, 9.0])
    assert r0["min(x)"] == 0.0 and r0["max(x)"] == 9.0
    assert abs(r0["std(x)"] - vals.std()) < 1e-9


def test_global_aggregates(rt):
    ds = _table(10)
    assert ds.sum("x") == 45.0
    assert ds.min("x") == 0.0
    assert ds.max("x") == 9.0
    assert ds.mean("x") == 4.5
    assert abs(ds.std("x") - np.arange(10, dtype=float).std(ddof=1)) < 1e-9
    out = ds.aggregate(Count(), Sum("x"))
    assert out["count"] == 10 and out["sum(x)"] == 45.0


def test_map_groups(rt):
    out = _table(9).groupby("k").map_groups(
        lambda g: {"k": g["k"][:1], "total": np.array([g["x"].sum()])}
    ).take_all()
    assert {int(r["k"]): float(r["total"]) for r in out} == {
        0: 9.0, 1: 12.0, 2: 15.0}


def test_zip_and_column_ops(rt):
    a = rd.from_numpy({"x": np.arange(6)})
    b = rd.from_numpy({"y": np.arange(6) * 10})
    z = a.zip(b)
    rows = z.take_all()
    assert all(r["y"] == 10 * r["x"] for r in rows)

    ds = rd.from_numpy({"x": np.arange(4, dtype=np.float64)})
    ds2 = ds.add_column("sq", lambda b: b["x"] ** 2)
    assert [r["sq"] for r in ds2.take_all()] == [0.0, 1.0, 4.0, 9.0]
    assert set(ds2.select_columns(["sq"]).schema()) == {"sq"}
    assert set(ds2.drop_columns(["sq"]).schema()) == {"x"}
    assert set(ds2.rename_columns({"sq": "square"}).schema()) == {
        "x", "square"}


def test_unique_schema_split(rt):
    ds = _table(12)
    assert ds.unique("k") == [0, 1, 2]
    sch = ds.schema()
    assert sch["x"] == np.float64
    parts = ds.split(3)
    assert sum(p.count() for p in parts) == 12


def test_parquet_roundtrip(rt, tmp_path):
    ds = _table(16)
    out = str(tmp_path / "pq")
    ds.write_parquet(out)
    back = rd.read_parquet(out)
    assert back.count() == 16
    assert back.sum("x") == ds.sum("x")
    # column projection
    only_k = rd.read_parquet(out, columns=["k"])
    assert set(only_k.schema()) == {"k"}


def test_csv_json_write(rt, tmp_path):
    ds = _table(6)
    ds.write_csv(str(tmp_path / "csv"))
    ds.write_json(str(tmp_path / "json"))
    back_csv = rd.read_csv(
        [str(p) for p in sorted((tmp_path / "csv").glob("*.csv"))])
    assert back_csv.count() == 6
    back_json = rd.read_json(
        [str(p) for p in sorted((tmp_path / "json").glob("*.json"))])
    assert back_json.count() == 6
    assert sum(float(r["x"]) for r in back_json.take_all()) == 15.0


def test_push_based_shuffle(rt):
    ctx = DataContext.get_current()
    ctx.use_push_based_shuffle = True
    try:
        ds = rd.range(100).random_shuffle(seed=7)
        vals = sorted(int(r["id"]) for r in ds.take_all())
        assert vals == list(range(100))
        # actually permuted (probability of identity is ~0)
        first = [int(r["id"]) for r in
                 rd.range(100).random_shuffle(seed=7).take(10)]
        assert first != list(range(10))
    finally:
        ctx.use_push_based_shuffle = False


def test_preprocessor_standard_scaler(rt):
    ds = rd.from_numpy({"a": np.array([1.0, 2.0, 3.0, 4.0]),
                        "b": np.array([10.0, 10.0, 10.0, 10.0])})
    sc = pp.StandardScaler(["a", "b"]).fit(ds)
    out = sc.transform(ds).take_all()
    a = np.array([r["a"] for r in out])
    assert abs(a.mean()) < 1e-9 and abs(a.std() - 1.0) < 1e-9
    assert all(r["b"] == 0.0 for r in out)  # zero-variance column


def test_preprocessor_minmax_label_onehot(rt):
    ds = rd.from_items([
        {"x": 0.0, "cat": "a"}, {"x": 5.0, "cat": "b"},
        {"x": 10.0, "cat": "a"},
    ])
    mm = pp.MinMaxScaler(["x"]).fit(ds)
    xs = [r["x"] for r in mm.transform(ds).take_all()]
    assert xs == [0.0, 0.5, 1.0]

    le = pp.LabelEncoder("cat").fit(ds)
    cats = [int(r["cat"]) for r in le.transform(ds).take_all()]
    assert cats == [0, 1, 0]

    oh = pp.OneHotEncoder(["cat"]).fit(ds)
    row = oh.transform(ds).take_all()[1]
    assert row["cat_a"] == 0 and row["cat_b"] == 1


def test_preprocessor_concat_chain_batchmapper(rt):
    ds = rd.from_numpy({"f1": np.arange(4, dtype=np.float64),
                        "f2": np.arange(4, dtype=np.float64) * 2})
    chain = pp.Chain(
        pp.StandardScaler(["f1"]),
        pp.BatchMapper(lambda b: {**b, "f2": b["f2"] + 1}),
        pp.Concatenator(["f1", "f2"], "features"),
    ).fit(ds)
    out = chain.transform(ds).take_all()
    assert out[0]["features"].shape == (2,)
    # serving-time single batch path
    batch = chain.transform_batch(
        {"f1": np.array([0.0, 3.0]), "f2": np.array([1.0, 1.0])})
    assert batch["features"].shape == (2, 2)


def test_unfit_preprocessor_raises(rt):
    with pytest.raises(RuntimeError):
        pp.StandardScaler(["x"]).transform(rd.range(3))


def test_random_sample_not_positionally_biased(rt):
    ds = rd.from_numpy({"x": np.arange(80)}, num_blocks=8)
    kept = [int(r["x"]) for r in ds.random_sample(0.5, seed=1).take_all()]
    # with per-block identical masks, kept positions mod 10 would form a
    # fixed subset; distinct streams make that astronomically unlikely
    mods = {k % 10 for k in kept}
    assert len(mods) > 5
    # reproducible
    kept2 = [int(r["x"]) for r in ds.random_sample(0.5, seed=1).take_all()]
    assert kept == kept2


def test_split_exact_count_with_few_rows(rt):
    parts = rd.from_numpy({"x": np.arange(2)}).split(4)
    assert len(parts) == 4
    assert sum(p.count() for p in parts) == 2


def test_push_shuffle_reproducible(rt):
    ctx = DataContext.get_current()
    ctx.use_push_based_shuffle = True
    try:
        a = [int(r["id"]) for r in
             rd.range(60).random_shuffle(seed=5).take_all()]
        b = [int(r["id"]) for r in
             rd.range(60).random_shuffle(seed=5).take_all()]
        assert a == b
        assert sorted(a) == list(range(60))
    finally:
        ctx.use_push_based_shuffle = False


def test_zip_no_silent_overwrite(rt):
    a = rd.from_numpy({"k": np.arange(3), "k_1": np.arange(3) * 2})
    b = rd.from_numpy({"k": np.arange(3) * 5})
    cols = set(a.zip(b).schema())
    assert cols == {"k", "k_1", "k_2"}


def test_iter_torch_batches():
    import numpy as np
    import torch

    import ray_tpu.data as rdata

    ds = rdata.range(100, num_blocks=4).map_batches(
        lambda b: {"x": np.asarray(b["id"], np.float32) * 2})
    batches = list(ds.iterator().iter_torch_batches(batch_size=32))
    assert all(isinstance(b["x"], torch.Tensor) for b in batches)
    total = torch.cat([b["x"] for b in batches])
    assert total.shape == (100,)
    assert float(total.sum()) == float(2 * sum(range(100)))


def test_from_pandas_and_to_rows():
    import pandas as pd

    import ray_tpu.data as rdata

    df = pd.DataFrame({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    ds = rdata.from_pandas(df, num_blocks=2)
    rows = ds.take_all()
    assert [r["a"] for r in rows] == [1, 2, 3]
    assert [r["b"] for r in rows] == ["x", "y", "z"]


def test_read_text_and_binary(tmp_path):
    import ray_tpu.data as rdata

    p1 = tmp_path / "a.txt"
    p1.write_text("hello\nworld\n\nlast\n")
    ds = rdata.read_text(str(p1))
    assert [r["text"] for r in ds.take_all()] == ["hello", "world", "last"]

    p2 = tmp_path / "blob.bin"
    p2.write_bytes(b"\x00\x01\x02")
    ds2 = rdata.read_binary_files(str(p2), include_paths=True)
    row = ds2.take_all()[0]
    assert row["bytes"] == b"\x00\x01\x02" and row["path"].endswith("blob.bin")


def test_to_pandas_roundtrip():
    import pandas as pd

    import ray_tpu.data as rdata

    df = pd.DataFrame({"a": [1, 2, 3], "b": [4.0, 5.0, 6.0]})
    out = rdata.from_pandas(df).to_pandas()
    pd.testing.assert_frame_equal(
        out.sort_values("a").reset_index(drop=True), df)


def test_to_pandas_multidim_column():
    import numpy as np

    import ray_tpu.data as rdata

    ds = rdata.from_numpy({"emb": np.arange(8.0).reshape(4, 2)})
    df = ds.to_pandas()
    assert len(df) == 4
    assert list(df["emb"].iloc[0]) == [0.0, 1.0]


def test_arrow_interop_roundtrip(ray_tpu_start):
    """from_arrow -> transforms -> to_arrow (reference: Arrow-native
    blocks + from_arrow/to_arrow surface)."""
    import pyarrow as pa

    from ray_tpu import data as rdata

    table = pa.table({"x": list(range(10)), "y": [f"r{i}" for i in range(10)]})
    ds = rdata.from_arrow(table)
    out = ds.map_batches(lambda b: {"x2": b["x"] * 2}).to_arrow()
    assert isinstance(out, pa.Table)
    assert sorted(out.column("x2").to_pylist()) == [2 * i for i in range(10)]


def test_read_parquet_file_uri(tmp_path, ray_tpu_start):
    """pyarrow.fs URI paths resolve (file:// here; s3://, gs:// share the
    same code path with credentials)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu import data as rdata

    p = tmp_path / "t.parquet"
    pq.write_table(pa.table({"a": [1, 2, 3]}), p)
    rows = rdata.read_parquet(f"file://{p}").take_all()
    assert sorted(r["a"] for r in rows) == [1, 2, 3]


def test_read_csv_and_text_file_uri(tmp_path, ray_tpu_start):
    from ray_tpu import data as rdata

    csv = tmp_path / "t.csv"
    csv.write_text("a,b\n1,x\n2,y\n")
    rows = rdata.read_csv(f"file://{csv}").take_all()
    assert len(rows) == 2 and rows[0]["b"] in ("x", "y")

    txt = tmp_path / "t.txt"
    txt.write_text("hello\nworld\n")
    rows = rdata.read_text(f"file://{txt}").take_all()
    assert sorted(r["text"] for r in rows) == ["hello", "world"]


def test_actor_pool_autoscales(ray_tpu_start):
    """Actor-pool map scales up under queued work and back down when the
    input drains (reference: ActorPoolMapOperator autoscaling)."""
    import time

    from ray_tpu import data as rdata
    from ray_tpu.data.execution import MapOperator

    captured = []
    orig_init = MapOperator.__init__

    def spy_init(self, *a, **k):
        orig_init(self, *a, **k)
        captured.append(self)

    MapOperator.__init__ = spy_init
    try:
        def slowish(batch):
            time.sleep(0.05)
            return batch

        rows = (rdata.range(400)
                .map_batches(lambda: slowish, compute="actors",
                             actor_pool_size=1, max_actor_pool_size=4)
                .take_all())
        assert len(rows) == 400
        op = next(o for o in captured if o.name == "MapBatches")
        assert op.metrics.get("actors_started", 0) >= 2, op.metrics
        assert len(op._pool) == 0, "idle actors not retired after drain"
    finally:
        MapOperator.__init__ = orig_init


# ---------------------------------------------------------------------------
# round-3 additions: DatasetStats + TFRecord + WebDataset
# ---------------------------------------------------------------------------

def test_dataset_stats(ray_tpu_start):
    import ray_tpu.data as rdata

    ds = rdata.range(32, num_blocks=4).map_batches(
        lambda b: {"id": b["id"] * 2})
    ds.take_all()
    out = ds.stats()
    assert "Operator 0 Input" in out
    assert "rows" in out and "task wall" in out
    assert out["MapBatches"]["tasks"] == 4
    assert out["MapBatches"]["rows_out"] == 32
    # an unexecuted dataset executes once to produce stats
    fresh = rdata.range(4, num_blocks=2).map_batches(lambda b: b)
    assert fresh.stats()["MapBatches"]["tasks"] == 2


def test_tfrecord_roundtrip(ray_tpu_start, tmp_path):
    import ray_tpu.data as rdata

    rows = [
        {"label": 3, "name": "cat", "scores": [0.5, 1.5]},
        {"label": 7, "name": "dog", "scores": [2.0]},
        {"label": 1, "name": b"raw-bytes", "scores": [0.0, -1.0, 4.0]},
    ]
    path = str(tmp_path / "data.tfrecord")
    rdata.write_tfrecords_file(rows, path)
    back = rdata.read_tfrecords(path).take_all()
    assert len(back) == 3
    assert back[0]["label"] == 3
    assert back[0]["name"] == b"cat"         # bytes feature (TF semantics)
    assert back[0]["scores"] == [0.5, 1.5]
    assert back[1]["scores"] == 2.0          # single element unwraps
    assert back[2]["name"] == b"raw-bytes"


def test_tfrecord_crc_detects_corruption(tmp_path):
    from ray_tpu.data import tfrecord as tfr

    framed = bytearray(tfr.frame_record(tfr.build_example({"x": 1})))
    framed[14] ^= 0xFF    # flip a payload byte
    import pytest

    with pytest.raises(ValueError, match="CRC"):
        list(tfr.iter_records(bytes(framed)))


def test_webdataset_reader(ray_tpu_start, tmp_path):
    import io
    import tarfile

    import ray_tpu.data as rdata

    tar_path = tmp_path / "shard-000.tar"
    with tarfile.open(tar_path, "w") as tar:
        for key, cls, txt in (("s1", 0, "hello"), ("s2", 4, "world")):
            for ext, payload in (("cls", str(cls).encode()),
                                 ("txt", txt.encode()),
                                 ("bin", b"\x00\x01")):
                data = io.BytesIO(payload)
                info = tarfile.TarInfo(f"{key}.{ext}")
                info.size = len(payload)
                tar.addfile(info, data)
    rows = rdata.read_webdataset(str(tar_path)).take_all()
    assert len(rows) == 2
    by_key = {r["__key__"]: r for r in rows}
    assert by_key["s1"]["cls"] == 0 and by_key["s1"]["txt"] == "hello"
    assert by_key["s2"]["cls"] == 4 and by_key["s2"]["bin"] == b"\x00\x01"
