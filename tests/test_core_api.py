"""Core task/actor/object API tests.

Modeled on the reference's ``python/ray/tests/test_basic.py`` /
``test_actor.py`` coverage: submission, chaining, multiple returns, errors,
retries, wait semantics, actor ordering, named actors, kill.
"""

import time

import pytest

import ray_tpu
from ray_tpu.utils.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    TaskError,
)


def test_put_get(ray_tpu_start):
    ref = ray_tpu.put({"a": 1})
    assert ray_tpu.get(ref) == {"a": 1}


def test_put_objectref_rejected(ray_tpu_start):
    ref = ray_tpu.put(1)
    with pytest.raises(TypeError):
        ray_tpu.put(ref)


def test_simple_task(ray_tpu_start):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2)) == 3


def test_task_with_kwargs(ray_tpu_start):
    @ray_tpu.remote
    def f(a, b=10):
        return a * b

    assert ray_tpu.get(f.remote(2, b=3)) == 6
    assert ray_tpu.get(f.remote(2)) == 20


def test_task_chaining_ref_args(ray_tpu_start):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    r1 = double.remote(1)
    r2 = double.remote(r1)
    r3 = double.remote(r2)
    assert ray_tpu.get(r3) == 8


def test_many_parallel_tasks(ray_tpu_start):
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(200)]
    assert ray_tpu.get(refs) == [i * i for i in range(200)]


def test_num_returns(ray_tpu_start):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_task_error_propagates(ray_tpu_start):
    @ray_tpu.remote
    def boom():
        raise ValueError("kapow")

    with pytest.raises(TaskError, match="kapow"):
        ray_tpu.get(boom.remote())


def test_error_propagates_through_chain(ray_tpu_start):
    @ray_tpu.remote
    def boom():
        raise ValueError("root cause")

    @ray_tpu.remote
    def consume(x):
        return x

    with pytest.raises(TaskError, match="root cause"):
        ray_tpu.get(consume.remote(boom.remote()))


def test_retry_exceptions(ray_tpu_start):
    counter = {"n": 0}

    @ray_tpu.remote(max_retries=3, retry_exceptions=True)
    def flaky():
        counter["n"] += 1
        if counter["n"] < 3:
            raise RuntimeError("transient")
        return "ok"

    assert ray_tpu.get(flaky.remote()) == "ok"
    assert counter["n"] == 3


def test_get_timeout(ray_tpu_start):
    @ray_tpu.remote
    def slow():
        time.sleep(5)

    with pytest.raises(GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.1)


def test_wait(ray_tpu_start):
    @ray_tpu.remote
    def sleepy(t):
        time.sleep(t)
        return t

    fast = sleepy.remote(0.01)
    slow = sleepy.remote(2.0)
    ready, not_ready = ray_tpu.wait([fast, slow], num_returns=1, timeout=1.0)
    assert ready == [fast]
    assert not_ready == [slow]


def test_wait_all(ray_tpu_start):
    @ray_tpu.remote
    def f(i):
        return i

    refs = [f.remote(i) for i in range(10)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=10, timeout=10.0)
    assert len(ready) == 10 and not not_ready


def test_options_override(ray_tpu_start):
    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get(f.options(num_cpus=2).remote()) == 1


def test_direct_call_rejected(ray_tpu_start):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(TypeError):
        f()


# --- actors ---


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def incr(self, by=1):
        self.n += by
        return self.n

    def value(self):
        return self.n

    def fail(self):
        raise RuntimeError("actor method failed")


def test_actor_basic(ray_tpu_start):
    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote()) == 1
    assert ray_tpu.get(c.incr.remote(5)) == 6
    assert ray_tpu.get(c.value.remote()) == 6


def test_actor_init_args(ray_tpu_start):
    c = Counter.remote(100)
    assert ray_tpu.get(c.value.remote()) == 100


def test_actor_ordering(ray_tpu_start):
    c = Counter.remote()
    refs = [c.incr.remote() for _ in range(100)]
    # Ordered execution: the i-th call must observe i prior increments.
    assert ray_tpu.get(refs) == list(range(1, 101))


def test_actor_method_error(ray_tpu_start):
    c = Counter.remote()
    with pytest.raises(TaskError, match="actor method failed"):
        ray_tpu.get(c.fail.remote())
    # actor still alive afterwards
    assert ray_tpu.get(c.incr.remote()) == 1


def test_actor_ref_args(ray_tpu_start):
    @ray_tpu.remote
    def produce():
        return 7

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(produce.remote())) == 7


def test_named_actor(ray_tpu_start):
    Counter.options(name="global_counter").remote(42)
    h = ray_tpu.get_actor("global_counter")
    assert ray_tpu.get(h.value.remote()) == 42


def test_named_actor_duplicate(ray_tpu_start):
    Counter.options(name="dup").remote()
    with pytest.raises(ValueError):
        Counter.options(name="dup").remote()


def test_kill_actor(ray_tpu_start):
    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote()) == 1
    ray_tpu.kill(c)
    with pytest.raises(ActorDiedError):
        ray_tpu.get(c.incr.remote())


def test_actor_handle_passing(ray_tpu_start):
    c = Counter.remote()

    @ray_tpu.remote
    def bump(handle):
        return ray_tpu.get(handle.incr.remote())

    assert ray_tpu.get(bump.remote(c)) == 1


def test_actor_init_failure(ray_tpu_start):
    @ray_tpu.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("bad init")

        def ping(self):
            return "pong"

    b = Broken.remote()
    with pytest.raises(ActorDiedError):
        ray_tpu.get(b.ping.remote())


def test_max_concurrency(ray_tpu_start):
    @ray_tpu.remote(max_concurrency=4)
    class Parallel:
        def block(self, t):
            time.sleep(t)
            return t

    p = Parallel.remote()
    start = time.monotonic()
    refs = [p.block.remote(0.2) for _ in range(4)]
    ray_tpu.get(refs)
    elapsed = time.monotonic() - start
    assert elapsed < 0.6, f"expected concurrent execution, took {elapsed:.2f}s"


def test_cluster_resources(ray_tpu_start):
    res = ray_tpu.cluster_resources()
    assert res["CPU"] == 8.0
