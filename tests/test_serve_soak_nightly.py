"""Nightly serve-engine soak: sustained load must hold KV pages and
prefix-cache state flat — no page leak, no refcount drift, no deferred
frees stranded (the failure mode VERDICT r3 flagged for long-running
workloads generally: resources that only ever grow).

Run via ``ci/run_ci.sh --nightly`` (``pytest -m nightly``); the CI
default tier skips it (minutes of decode on CPU).
"""

import threading

import numpy as np
import pytest

import jax

from ray_tpu.models import llama
from ray_tpu.serve.paged_llm import PagedLLMEngine

# slow as well: an explicit `-m 'not slow'` on the command line REPLACES
# the addopts default (`-m 'not nightly'`) — keep the soak out of
# bounded default/tier-1 runs either way
pytestmark = [pytest.mark.nightly, pytest.mark.slow]


def _soak(eng, vocab, *, rounds, concurrency, rng, shared_prefix=None):
    done = []
    lock = threading.Lock()
    remaining = [rounds - concurrency]

    def consume(req):
        toks = list(req.tokens())
        with lock:
            done.append(len(toks))
            go = remaining[0] > 0
            if go:
                remaining[0] -= 1
        if go:
            threading.Thread(target=consume, args=(_submit(),),
                             daemon=True).start()

    def _submit():
        # numpy Generators are not thread-safe: take every draw under
        # the shared lock (consume threads chain submissions concurrently)
        with lock:
            tail = rng.integers(1, vocab, int(rng.integers(8, 48)))
            new_tokens = int(rng.integers(4, 24))
        prompt = (np.concatenate([shared_prefix, tail])
                  if shared_prefix is not None else tail)
        return eng.submit(prompt, max_new_tokens=new_tokens)

    for _ in range(concurrency):
        threading.Thread(target=consume, args=(_submit(),),
                         daemon=True).start()
    import time
    deadline = time.monotonic() + 300
    while True:
        with lock:
            if len(done) >= rounds:
                return done
        assert time.monotonic() < deadline, \
            f"soak stalled: {len(done)}/{rounds} done"
        assert eng.error is None, eng.error
        time.sleep(0.05)


def test_serve_soak_pages_flat():
    """Hundreds of randomized requests (varying prompt + output lengths,
    a shared prefix mixed in): at idle, every non-cached page is back in
    the free list, refcounts are zero, and deferred frees drained."""
    cfg = llama.llama_tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=4,
                         max_len=256, page_size=32, num_pages=24,
                         decode_chunk=8)
    eng.start()
    rng = np.random.default_rng(0)
    shared = rng.integers(1, cfg.vocab_size, 64)   # 2 cacheable pages

    done = _soak(eng, cfg.vocab_size, rounds=120, concurrency=4, rng=rng,
                 shared_prefix=shared)
    assert len(done) == 120

    # drain: give the engine loop a few idle passes to age deferred frees
    import time
    for _ in range(100):
        st = eng.stats()
        idle = st["prefix_cache"]["cached_idle_pages"]
        free = st["kv_pages_free"]
        if free + idle == eng.num_pages:
            break
        time.sleep(0.05)
    st = eng.stats()
    eng.stop()
    idle = st["prefix_cache"]["cached_idle_pages"]
    # EVERY page is either free or cached-idle — nothing leaked, nothing
    # still "owned" by a retired slot, no refcount held by a dead request
    assert st["kv_pages_free"] + idle == eng.num_pages, st
    assert not eng._alloc.owned, eng._alloc.owned
    assert not eng._prefix._refs, eng._prefix._refs
    assert not eng._deferred_free
    assert eng.total_finished == 120


def test_serve_soak_int8_pages_flat():
    """Same invariant under the int8 KV layout."""
    cfg = llama.llama_tiny()
    params = llama.init_params(cfg, jax.random.key(1))
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                         max_len=128, page_size=32, num_pages=12,
                         decode_chunk=8, kv_dtype="int8")
    eng.start()
    rng = np.random.default_rng(2)
    done = _soak(eng, cfg.vocab_size, rounds=40, concurrency=2, rng=rng)
    assert len(done) == 40
    import time
    for _ in range(100):
        st = eng.stats()
        if (st["kv_pages_free"]
                + st["prefix_cache"]["cached_idle_pages"]) == eng.num_pages:
            break
        time.sleep(0.05)
    st = eng.stats()
    eng.stop()
    assert (st["kv_pages_free"]
            + st["prefix_cache"]["cached_idle_pages"]) == eng.num_pages, st
    assert not eng._alloc.owned
