"""DreamerV3 (rllib/dreamer.py): RSSM world model + imagination
actor-critic.

Reference analog: rllib/algorithms/dreamerv3 (SURVEY.md P18 names
DreamerV3 among the reference's algorithm families). Tests run the tiny
config on CartPole over the real task/actor runtime.
"""

import math

import numpy as np
import pytest

from ray_tpu.rllib import DreamerV3Config


@pytest.fixture(scope="module")
def algo():
    cfg = (DreamerV3Config()
           .environment("CartPole-v1")
           .rollouts(num_rollout_workers=1, rollout_fragment_length=96)
           .training(seq_len=12, batch_size=4, horizon=6,
                     embed=16, h_dim=32, n_cats=4, n_classes=4,
                     hidden=32, learning_starts=96,
                     num_updates_per_iter=2, seed=0))
    a = cfg.build()
    yield a
    a.stop()


def test_dreamer_trains_and_losses_finite(algo):
    results = [algo.train() for _ in range(3)]
    last = results[-1]
    assert last["training_iteration"] == 3
    assert last["buffer_size"] >= 96 * 3
    # learning kicked in by iteration >= 2 and every loss is finite
    for key in ("wm_loss", "recon_loss", "reward_loss", "cont_loss",
                "kl_loss", "actor_loss", "critic_loss",
                "policy_entropy"):
        assert key in last, f"missing {key}"
        assert math.isfinite(last[key]), (key, last[key])
    # categorical entropy of a 2-action policy is bounded by ln 2
    assert 0.0 <= last["policy_entropy"] <= math.log(2) + 1e-3


def test_dreamer_world_model_improves(algo):
    """Repeated updates on a FIXED batch must reduce reconstruction
    loss (the world model actually fits; a fresh-data comparison would
    be noisy as exploration shifts the distribution)."""
    import jax

    rng = np.random.default_rng(42)
    batch = algo.buffer.sample(4, algo.config.seq_len, rng)
    state = (algo.params, algo.target_critic, algo.opt_wm,
             algo.opt_actor, algo.opt_critic, algo.ret_scale)
    key = jax.random.key(7)
    losses = []
    for _ in range(6):
        key, sub = jax.random.split(key)
        *state, metrics = algo._update(*state, batch, sub)
        losses.append(float(metrics["recon_loss"]))
    assert losses[-1] < losses[0], losses
    assert all(math.isfinite(v) for v in losses)


def test_dreamer_compute_single_action(algo):
    from ray_tpu.rllib.env import make_env

    env = make_env("CartPole-v1", seed=3)
    obs = env.reset()
    state = None
    for _ in range(10):
        a, state = algo.compute_single_action(obs, state)
        assert a in (0, 1)
        obs, _, done, _ = env.step(a)
        if done:
            obs = env.reset()
            state = None


def test_dreamer_rejects_continuous_env():
    with pytest.raises(ValueError, match="discrete"):
        DreamerV3Config().environment("Pendulum-v1").build()


def test_cartpole_truncation_distinguished():
    """Time-limit episode ends are truncations (cont should stay 1);
    pole-fall ends are terminations."""
    from ray_tpu.rllib.env import CartPole

    env = CartPole(seed=0)
    env.reset()
    env.max_steps = 3
    done = False
    while not done:
        _, _, done, _ = env.step(0)
    # 3 steps from a near-zero init cannot tip the pole: the episode
    # deterministically ended by the cap, which MUST read as truncation
    assert env.steps == 3
    assert env.truncated is True
    env.reset()
    assert env.truncated is False


def test_sequence_replay_marks_writer_joints():
    from ray_tpu.rllib.dreamer import SequenceReplay

    buf = SequenceReplay(256, obs_dim=2)

    def frag(n, first0):
        return {"obs": np.zeros((n, 2), np.float32),
                "actions": np.zeros((n,), np.int32),
                "rewards": np.zeros((n,), np.float32),
                "is_first": np.r_[float(first0), np.zeros(n - 1)],
                "cont": np.ones((n,), np.float32)}

    buf.add_batch(frag(8, 1.0), writer=0)   # worker A episode start
    buf.add_batch(frag(8, 0.0), writer=1)   # worker B mid-episode frag
    buf.add_batch(frag(8, 0.0), writer=0)   # back to A: joint again
    # joints at positions 8 and 16 forced to sequence starts
    assert buf.is_first[8] == 1.0
    assert buf.is_first[16] == 1.0
    # same-worker continuation is NOT severed
    buf.add_batch(frag(8, 0.0), writer=0)
    assert buf.is_first[24] == 0.0
    # freshest step is sampleable (off-by-one guard)
    rng = np.random.default_rng(0)
    starts = [rng.integers(0, buf.size - 4 + 1) for _ in range(50)]
    assert max(starts) == buf.size - 4


def test_reward_head_learns_action_dependent_rewards(algo):
    """The arrival-aligned layout makes action-dependent rewards
    learnable: rewards[t] is caused by actions[t], which feat_t's GRU
    encodes. Synthetic batches where reward == action must drive the
    reward loss well below the action-marginal floor (~0.25 MSE)."""
    import jax

    rng = np.random.default_rng(0)
    c = algo.config

    def batch():
        acts = rng.integers(0, 2, (4, c.seq_len)).astype(np.int32)
        obs = rng.standard_normal((4, c.seq_len,
                                   algo.obs_dim)).astype(np.float32)
        first = np.zeros((4, c.seq_len), np.float32)
        first[:, 0] = 1.0
        acts[:, 0] = 0
        rew = acts.astype(np.float32)       # reward == arriving action
        rew[:, 0] = 0.0
        return {"obs": obs, "actions": acts, "rewards": rew,
                "is_first": first, "cont": np.ones((4, c.seq_len),
                                                   np.float32)}

    state = (algo.params, algo.target_critic, algo.opt_wm,
             algo.opt_actor, algo.opt_critic, algo.ret_scale)
    key = jax.random.key(11)
    loss = None
    for _ in range(60):
        key, sub = jax.random.split(key)
        *state, metrics = algo._update(*state, batch(), sub)
        loss = float(metrics["reward_loss"])
    # symlog(1)=0.693: the marginal-mean predictor floors at ~0.12 in
    # symlog MSE; conditioning on the action must beat it decisively
    assert loss < 0.06, loss


def test_sequence_replay_samples_across_ring_wrap():
    """Full-ring sampling draws windows across the capacity-1 -> 0
    boundary (they are temporally contiguous; the write head marks
    is_first where continuity actually breaks) — advisor finding:
    excluding them permanently under-sampled steps after index 0."""
    import numpy as np

    from ray_tpu.rllib.dreamer import SequenceReplay

    rep = SequenceReplay(capacity=32, obs_dim=2)
    for i in range(40):   # wraps: pos ends at 8, ring full
        rep.add_batch({
            "obs": np.full((1, 2), i, np.float32),
            "actions": np.zeros((1,), np.int32),
            "rewards": np.zeros((1,), np.float32),
            "is_first": np.zeros((1,), np.float32),
            "cont": np.ones((1,), np.float32),
        })
    assert rep.size == rep.capacity
    rng = np.random.default_rng(0)
    wrapped = 0
    for _ in range(200):
        batch = rep.sample(4, seq_len=8, rng=rng)
        # a window wraps iff its obs sequence is non-monotonic
        firsts = batch["obs"][:, :, 0]
        wrapped += int((np.diff(firsts, axis=1) < 0).any())
    assert wrapped > 0, "no sampled window ever crossed the ring wrap"
