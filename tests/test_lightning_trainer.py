"""LightningTrainer tests (reference analog:
train/lightning/lightning_trainer.py:241 — module protocol driven by the
loop adapter; the real pl.Trainer path activates when lightning is
installed)."""

import numpy as np
import pytest

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.lightning import LightningTrainer


def _make_module_init():
    """Closure (workers can't import test modules by reference)."""

    def module_init(config):
        import torch
        from torch import nn

        class LinearModule(nn.Module):
            """LightningModule-protocol module: training_step +
            configure_optimizers + train_dataloader (+ validation)."""

            def __init__(self):
                super().__init__()
                torch.manual_seed(0)
                self.net = nn.Linear(4, 1)
                self.w_true = torch.tensor(
                    [[1.0], [-2.0], [3.0], [0.5]])

            def _batches(self, seed, n):
                g = np.random.default_rng(seed)
                for _ in range(n):
                    x = torch.tensor(
                        g.normal(size=(32, 4)).astype(np.float32))
                    yield x, x @ self.w_true

            def train_dataloader(self):
                return self._batches(0, config["steps"])

            def val_dataloader(self):
                return self._batches(1, 4)

            def training_step(self, batch, batch_idx):
                x, y = batch
                return ((self.net(x) - y) ** 2).mean()

            def validation_step(self, batch, batch_idx):
                x, y = batch
                return {"val_loss": ((self.net(x) - y) ** 2).mean()}

            def configure_optimizers(self):
                return torch.optim.SGD(self.net.parameters(), lr=0.1)

        return LinearModule()

    return module_init


def test_lightning_trainer_fits(ray_tpu_start, tmp_path):
    trainer = LightningTrainer(
        _make_module_init(),
        trainer_kwargs={"max_epochs": 3},
        train_loop_config={"steps": 20},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["train_loss"] < 0.5
    assert result.metrics["val_loss"] < 0.5
    assert result.metrics["epoch"] == 2


def test_lightning_checkpoint_bridge(ray_tpu_start, tmp_path):
    trainer = LightningTrainer(
        _make_module_init(),
        trainer_kwargs={"max_epochs": 1},
        train_loop_config={"steps": 5},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.checkpoint_dir is not None
    import os

    import torch

    ckpt = torch.load(os.path.join(result.checkpoint_dir, "checkpoint.pt"),
                      weights_only=True)
    assert "state_dict" in ckpt and ckpt["epoch"] == 0


def test_lightning_rejects_non_protocol_module(ray_tpu_start, tmp_path):
    trainer = LightningTrainer(
        lambda cfg: object(),
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is not None
    assert "protocol" in str(result.error)
