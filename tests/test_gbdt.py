"""Distributed GBDT trainers (reference: train/xgboost + train/lightgbm
over gbdt_trainer.py; here a native histogram implementation whose
distributed mode sums worker histograms — tests check learning quality,
exact 1-vs-N-worker determinism, and the Trainer API contract)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (LightGBMTrainer, Result, RunConfig,
                           ScalingConfig, XGBoostTrainer)
from ray_tpu.train.gbdt import GBTModel


@pytest.fixture(scope="module", autouse=True)
def _rt():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def _regression_data(n=2000, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (2.0 * X[:, 0] - 1.5 * X[:, 1] * (X[:, 2] > 0)
         + 0.5 * np.sin(3 * X[:, 3]) + rng.normal(scale=0.1, size=n))
    return X, y


def _as_dict(X, y):
    d = {f"f{i}": X[:, i] for i in range(X.shape[1])}
    d["label"] = y
    return d


def test_xgboost_regression_learns(tmp_path):
    X, y = _regression_data()
    trainer = XGBoostTrainer(
        params={"objective": "reg:squarederror", "eta": 0.3,
                "max_depth": 5},
        label_column="label",
        datasets={"train": _as_dict(X[:1500], y[:1500]),
                  "valid": _as_dict(X[1500:], y[1500:])},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
        num_boost_round=20)
    result = trainer.fit()
    assert isinstance(result, Result)
    hist = result.metrics_history
    # boosting reduces train loss monotonically-ish and generalizes
    assert hist[-1]["train-rmse"] < hist[0]["train-rmse"] * 0.5
    assert result.metrics["valid-rmse"] < np.std(y) * 0.6
    assert result.metrics["num_trees"] == 20


def test_xgboost_binary_classification(tmp_path):
    rng = np.random.default_rng(1)
    X = rng.normal(size=(2000, 6))
    y = ((X[:, 0] + X[:, 1] * X[:, 2]) > 0).astype(np.float64)
    trainer = XGBoostTrainer(
        params={"objective": "binary:logistic", "eta": 0.3,
                "max_depth": 4},
        label_column="label",
        datasets={"train": _as_dict(X, y)},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
        num_boost_round=15)
    result = trainer.fit()
    assert result.metrics["train-error"] < 0.1
    assert result.metrics["train-logloss"] < 0.3


def test_worker_count_invariance(tmp_path):
    """The distributed histogram sum is exact (float64): 1-worker and
    3-worker training produce identical models — the determinism check
    the wrapped-library reference can't make."""
    X, y = _regression_data(n=1200, f=5, seed=2)
    preds = []
    for n_workers in (1, 3):
        trainer = XGBoostTrainer(
            params={"objective": "reg:squarederror", "max_depth": 4},
            label_column="label",
            datasets={"train": _as_dict(X, y)},
            scaling_config=ScalingConfig(num_workers=n_workers),
            run_config=RunConfig(storage_path=str(tmp_path)),
            num_boost_round=8)
        result = trainer.fit()
        model = GBTModel.load(f"{result.checkpoint_dir}/model.pkl")
        preds.append(model.predict(X[:200]))
    np.testing.assert_allclose(preds[0], preds[1], rtol=1e-10)


def test_lightgbm_leafwise_learns(tmp_path):
    X, y = _regression_data(n=1500, f=6, seed=3)
    trainer = LightGBMTrainer(
        params={"objective": "reg:squarederror", "learning_rate": 0.2,
                "num_leaves": 15},
        label_column="label",
        datasets={"train": _as_dict(X, y)},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
        num_boost_round=15)
    result = trainer.fit()
    hist = result.metrics_history
    assert hist[-1]["train-rmse"] < hist[0]["train-rmse"] * 0.5
    # leaf-wise growth respects the leaf budget
    model = GBTModel.load(f"{result.checkpoint_dir}/model.pkl")
    for tree in model.trees:
        assert (tree.feature < 0).sum() <= 15 + 14  # leaves + internals


def test_model_checkpoint_roundtrip(tmp_path):
    X, y = _regression_data(n=600, f=4, seed=4)
    trainer = XGBoostTrainer(
        params={"max_depth": 3},
        label_column="label",
        datasets={"train": _as_dict(X, y)},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path)),
        num_boost_round=5)
    result = trainer.fit()
    model = GBTModel.load(f"{result.checkpoint_dir}/model.pkl")
    p1 = model.predict(X[:50])
    # saved model survives a save/load cycle byte-identically
    model.save(str(tmp_path / "again.pkl"))
    p2 = GBTModel.load(str(tmp_path / "again.pkl")).predict(X[:50])
    np.testing.assert_array_equal(p1, p2)
    # raw-feature prediction tracks the training targets
    assert np.corrcoef(model.predict(X), y)[0, 1] > 0.8


def test_trains_from_ray_tpu_dataset(tmp_path):
    """datasets= accepts ray_tpu.data Datasets (the reference's primary
    ingestion path)."""
    from ray_tpu import data as rd

    X, y = _regression_data(n=800, f=4, seed=5)
    items = [{"f0": float(X[i, 0]), "f1": float(X[i, 1]),
              "f2": float(X[i, 2]), "f3": float(X[i, 3]),
              "label": float(y[i])} for i in range(len(y))]
    ds = rd.from_items(items)
    trainer = XGBoostTrainer(
        params={"max_depth": 4},
        label_column="label",
        datasets={"train": ds},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
        num_boost_round=8)
    result = trainer.fit()
    assert result.metrics["train-rmse"] < np.std(y)
