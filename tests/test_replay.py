"""Prioritized replay + n-step folding tests (reference analog:
``rllib/utils/replay_buffers`` unit tests)."""

import numpy as np
import pytest

from ray_tpu.rllib.replay import (
    PrioritizedReplayBuffer,
    SumTree,
    nstep_batch,
)


def test_sumtree_total_and_search():
    t = SumTree(6)
    t.set(np.arange(6), np.array([1.0, 0.0, 2.0, 3.0, 0.0, 4.0]))
    assert t.total == pytest.approx(10.0)
    # prefix masses land in the leaf owning that probability span:
    # spans: [0,1) -> 0, [1,3) -> 2, [3,6) -> 3, [6,10) -> 5
    got = t.prefix_search(np.array([0.5, 1.5, 2.9, 3.0, 5.9, 9.9]))
    np.testing.assert_array_equal(got, [0, 2, 2, 3, 3, 5])


def test_sumtree_update_repairs_path():
    t = SumTree(4)
    t.set(np.arange(4), np.ones(4))
    t.set(np.array([2]), np.array([5.0]))
    assert t.total == pytest.approx(8.0)
    assert t.prefix_search(np.array([7.9]))[0] == 3


def _batch(n, obs_dim=3, rng=None):
    rng = rng or np.random.default_rng(0)
    return {
        "obs": rng.normal(size=(n, obs_dim)).astype(np.float32),
        "next_obs": rng.normal(size=(n, obs_dim)).astype(np.float32),
        "actions": rng.integers(0, 2, size=n).astype(np.int32),
        "rewards": rng.normal(size=n).astype(np.float32),
        "dones": np.zeros(n, np.float32),
        "discounts": np.full(n, 0.99, np.float32),
    }


def test_prioritized_sampling_prefers_high_priority():
    buf = PrioritizedReplayBuffer(64, 3, alpha=1.0)
    buf.add_batch(_batch(64))
    # crank one transition's priority way up
    buf.update_priorities(np.array([7]), np.array([100.0]))
    rng = np.random.default_rng(1)
    counts = np.zeros(64)
    for _ in range(50):
        mb = buf.sample(16, rng)
        for i in mb["idx"]:
            counts[i] += 1
    assert counts[7] == counts.max()
    assert counts[7] > 25  # far above the uniform expectation (~12.5)


def test_is_weights_counteract_priority():
    buf = PrioritizedReplayBuffer(32, 3, alpha=1.0)
    buf.add_batch(_batch(32))
    buf.update_priorities(np.array([3]), np.array([50.0]))
    mb = buf.sample(32, np.random.default_rng(0), beta=1.0)
    w = mb["weights"]
    hot = mb["idx"] == 3
    assert hot.any()
    # the over-sampled transition gets the SMALLEST weight
    assert w[hot].max() < w[~hot].min()
    assert w.max() == pytest.approx(1.0)


def test_ring_wraparound_keeps_priorities_consistent():
    buf = PrioritizedReplayBuffer(16, 3)
    for _ in range(5):
        buf.add_batch(_batch(7))
    assert buf.size == 16
    mb = buf.sample(8, np.random.default_rng(0))
    assert (mb["idx"] < 16).all()


def test_nstep_folding_values():
    gamma = 0.5
    batch = {
        "obs": np.arange(5, dtype=np.float32)[:, None],
        "next_obs": (np.arange(5, dtype=np.float32) + 1)[:, None],
        "actions": np.zeros(5, np.int32),
        "rewards": np.array([1.0, 1.0, 1.0, 1.0, 1.0], np.float32),
        "dones": np.array([0, 0, 1, 0, 0], np.float32),
    }
    out = nstep_batch(batch, 3, gamma)
    # t=0: r0 + g r1 + g^2 r2, horizon ends at the t=2 terminal
    assert out["rewards"][0] == pytest.approx(1 + 0.5 + 0.25)
    assert out["dones"][0] == 1.0 and out["discounts"][0] == 0.0
    # t=1: two steps to the terminal
    assert out["rewards"][1] == pytest.approx(1 + 0.5)
    # t=3: full 2-step horizon clipped at the fragment end, no terminal
    assert out["rewards"][3] == pytest.approx(1 + 0.5)
    assert out["dones"][3] == 0.0
    assert out["discounts"][3] == pytest.approx(gamma ** 2)
    assert out["next_obs"][3, 0] == 5.0
    # t=4: nothing to look ahead at
    assert out["rewards"][4] == pytest.approx(1.0)
    assert out["discounts"][4] == pytest.approx(gamma)


def test_nstep_one_adds_discounts_only():
    batch = _batch(4)
    del batch["discounts"]
    batch["dones"][2] = 1.0
    out = nstep_batch(batch, 1, 0.9)
    np.testing.assert_allclose(out["rewards"], batch["rewards"])
    assert out["discounts"][2] == 0.0
    assert out["discounts"][0] == pytest.approx(0.9)


def test_dqn_prioritized_nstep_learns_bandit(ray_tpu_start):
    from ray_tpu.rllib import DQNConfig

    algo = (DQNConfig()
            .environment("Bandit-v0")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
            .training(lr=5e-3, learning_starts=128, n_step=2,
                      prioritized_replay=True, epsilon_decay_iters=10)
            .build())
    try:
        last = 0.0
        for _ in range(30):
            last = algo.train()["episode_return_mean"]
            if last >= 0.9:
                break
        assert last >= 0.9
    finally:
        algo.stop()


def test_dueling_dqn_learns_bandit(ray_tpu_start):
    from ray_tpu.rllib import DQNConfig

    algo = (DQNConfig().environment("Bandit-v0")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
            .training(lr=5e-3, learning_starts=128, dueling=True,
                      epsilon_decay_iters=10)
            .build())
    try:
        last = 0.0
        for _ in range(30):
            last = algo.train()["episode_return_mean"]
            if last >= 0.9:
                break
        assert last >= 0.9
    finally:
        algo.stop()


def test_c51_learns_bandit(ray_tpu_start):
    from ray_tpu.rllib import DQNConfig

    algo = (DQNConfig().environment("Bandit-v0")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
            .training(lr=5e-3, learning_starts=128, num_atoms=21,
                      v_min=-1.0, v_max=2.0, epsilon_decay_iters=10)
            .build())
    try:
        last = 0.0
        for _ in range(30):
            last = algo.train()["episode_return_mean"]
            if last >= 0.9:
                break
        assert last >= 0.9
    finally:
        algo.stop()


def test_c51_projection_point_mass():
    """With discounts=0 (terminal), the projected target must be a point
    mass at the clipped reward; cross entropy then trains the online
    dist toward it."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rllib.dqn import _c51_update, dist_forward, init_qnet
    import optax

    n_actions, atoms = 2, 11
    params = init_qnet(jax.random.key(0), 3, n_actions, 32, atoms)
    target = jax.tree.map(lambda x: x, params)
    tx = optax.adam(5e-3)
    opt = tx.init(params)
    batch = {
        "obs": jnp.ones((16, 3), jnp.float32),
        "next_obs": jnp.ones((16, 3), jnp.float32),
        "actions": jnp.zeros((16,), jnp.int32),
        "rewards": jnp.full((16,), 0.5, jnp.float32),
        "dones": jnp.ones((16,), jnp.float32),
        "discounts": jnp.zeros((16,), jnp.float32),
        "weights": jnp.ones((16,), jnp.float32),
    }
    step = jax.jit(lambda p, o: _c51_update(
        p, o, target, batch, tx=tx, double_q=True, n_actions=n_actions,
        num_atoms=atoms, v_min=-1.0, v_max=1.0))
    for _ in range(300):
        params, opt, loss, _ = step(params, opt)
    dist = dist_forward(params, batch["obs"][:1], n_actions, atoms)
    ev = float((dist[0, 0] * jnp.linspace(-1, 1, atoms)).sum())
    # expected value of the learned distribution -> the 0.5 reward
    assert abs(ev - 0.5) < 0.1, ev


def test_dueling_plus_c51_rejected():
    from ray_tpu.rllib import DQNConfig

    with pytest.raises(ValueError, match="dueling"):
        DQNConfig().training(dueling=True, num_atoms=51).build()


def test_c51_degenerate_support_rejected():
    from ray_tpu.rllib import DQNConfig

    with pytest.raises(ValueError, match="v_max > v_min"):
        DQNConfig().training(num_atoms=21, v_min=1.0, v_max=1.0).build()
