"""Multi-agent RLlib: env API, policy mapping, multi-policy replay,
and MA-PPO learning (reference: rllib/env/multi_agent_env.py:30,
rllib/policy/policy_map.py:20)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.multi_agent import (
    AGENT_DONE_ALL,
    CoopMatchEnv,
    MultiAgentCartPole,
    MultiAgentPPOConfig,
    MultiAgentReplay,
    PolicyMap,
    _MultiAgentRolloutWorker,
)


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def test_multi_agent_env_api():
    env = MultiAgentCartPole(num_agents=3, seed=0)
    obs = env.reset()
    assert set(obs) == set(env.agent_ids)
    obs2, rews, dones, infos = env.step({a: 0 for a in env.agent_ids})
    assert set(rews) == set(env.agent_ids)
    assert AGENT_DONE_ALL in dones
    # run to completion: __all__ flips once every pole fell
    for _ in range(600):
        if dones[AGENT_DONE_ALL]:
            break
        obs2, rews, dones, infos = env.step({a: 0 for a in obs2})
    assert dones[AGENT_DONE_ALL]


def test_policy_map_lru_spill(tmp_path):
    pm = PolicyMap(capacity=2, spill_dir=str(tmp_path))
    pm["p0"] = {"w": np.zeros(3)}
    pm["p1"] = {"w": np.ones(3)}
    pm["p2"] = {"w": np.full(3, 2.0)}     # evicts p0 to disk
    assert len(pm) == 3
    assert set(pm.keys()) == {"p0", "p1", "p2"}
    # spilled policy loads back transparently (and may displace another)
    np.testing.assert_array_equal(pm["p0"]["w"], np.zeros(3))
    np.testing.assert_array_equal(pm["p2"]["w"], np.full(3, 2.0))


def test_multi_policy_replay_keyed_by_policy():
    rep = MultiAgentReplay(capacity_per_policy=64, seed=0)
    rep.add("p0", {"obs": np.zeros((10, 2)), "r": np.zeros(10)})
    rep.add("p1", {"obs": np.ones((5, 2)), "r": np.ones(5)})
    assert rep.size("p0") == 10 and rep.size("p1") == 5
    b0 = rep.sample("p0", 8)
    b1 = rep.sample("p1", 8)
    assert float(b0["obs"].sum()) == 0.0
    assert float(b1["obs"].sum()) == 16.0     # all ones
    # ring wrap: adding past capacity keeps size at capacity
    rep.add("p0", {"obs": np.zeros((100, 2)), "r": np.zeros(100)})
    assert rep.size("p0") == 64


def test_policy_mapping_routes_per_agent_obs():
    """Each agent's observations must land in ITS policy's batch —
    agents get distinguishable obs via distinct seeds/contexts."""

    class TaggedEnv(CoopMatchEnv):
        # a0 sees +10 offset obs, a1 sees -10: routing errors are
        # visible in the batch contents
        def reset(self):
            obs = super().reset()
            return {"a0": obs["a0"] + 10.0, "a1": obs["a1"] - 10.0}

    import cloudpickle

    mapping = cloudpickle.dumps(lambda aid: f"pol_{aid}")
    w = _MultiAgentRolloutWorker(TaggedEnv, mapping, seed=0)
    policies = {
        "pol_a0": _init_np(0), "pol_a1": _init_np(1),
    }
    out = w.sample(policies, num_steps=32, gamma=0.99, lam=0.95)
    batches = out["batches"]
    assert set(batches) == {"pol_a0", "pol_a1"}
    assert (batches["pol_a0"]["obs"] > 5).all()
    assert (batches["pol_a1"]["obs"] < -5).all()


def _init_np(seed):
    import jax

    from ray_tpu.rllib.ppo import init_module

    params = init_module(jax.random.key(seed), 2, 2, 16)
    import numpy as _np

    return jax.tree.map(_np.asarray, params)


def _run_until(algo, target, iters):
    best = -np.inf
    for _ in range(iters):
        result = algo.train()
        best = max(best, result["episode_return_mean"])
        if best >= target:
            break
    return best


def test_ma_ppo_learns_shared_policy(rt):
    algo = (MultiAgentPPOConfig()
            .environment("CoopMatch-v0")
            .multi_agent(policies=["shared"],
                         policy_mapping_fn=lambda aid: "shared")
            .rollouts(num_rollout_workers=1, rollout_fragment_length=256)
            .training(lr=3e-3, minibatch_size=256, hidden=32, seed=0)
            .build())
    try:
        # random play matches with prob 0.25 -> return 0.25; solved = 1.0
        best = _run_until(algo, 0.9, 30)
        assert best >= 0.9, f"shared MA-PPO failed to learn: {best}"
    finally:
        algo.stop()


def test_ma_ppo_learns_independent_policies(rt):
    algo = (MultiAgentPPOConfig()
            .environment("CoopMatch-v0")
            .multi_agent(policies=["p_a0", "p_a1"],
                         policy_mapping_fn=lambda aid: f"p_{aid}")
            .rollouts(num_rollout_workers=1, rollout_fragment_length=256)
            .training(lr=3e-3, minibatch_size=256, hidden=32, seed=1)
            .build())
    try:
        best = _run_until(algo, 0.9, 40)
        assert best >= 0.9, f"independent MA-PPO failed to learn: {best}"
        result = algo.train()
        assert result["policy_ids"] == ["p_a0", "p_a1"]
    finally:
        algo.stop()


def test_ma_ppo_bad_mapping_rejected(rt):
    with pytest.raises(ValueError, match="not in"):
        (MultiAgentPPOConfig()
         .environment("CoopMatch-v0")
         .multi_agent(policies=["only"],
                      policy_mapping_fn=lambda aid: aid)
         .build())
