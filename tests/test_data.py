"""ray_tpu.data tests (reference analog: python/ray/data/tests/)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def test_range_count(rt):
    assert rdata.range(100).count() == 100


def test_map_batches(rt):
    ds = rdata.range(100, num_blocks=4).map_batches(
        lambda b: {"id": b["id"] * 2})
    out = np.sort(np.concatenate([b["id"] for b in ds.iter_batches()]))
    assert np.array_equal(out, np.arange(100) * 2)


def test_map_and_filter_rows(rt):
    ds = (rdata.from_items(list(range(20)))
          .map(lambda x: x + 1)
          .filter(lambda x: x % 2 == 0))
    assert sorted(ds.take_all()) == list(range(2, 21, 2))


def test_flat_map(rt):
    ds = rdata.from_items([1, 2, 3], num_blocks=1).flat_map(
        lambda x: [x, x * 10])
    assert sorted(ds.take_all()) == [1, 2, 3, 10, 20, 30]


def test_limit(rt):
    assert rdata.range(1000).limit(17).count() == 17


def test_repartition(rt):
    ds = rdata.range(100, num_blocks=10).repartition(3)
    bundles = list(ds.iter_bundles())
    assert len(bundles) == 3
    assert sum(b.num_rows for b in bundles) == 100


def test_random_shuffle_preserves_rows(rt):
    ds = rdata.range(50).random_shuffle(seed=7)
    ids = sorted(int(x["id"]) for x in ds.take_all())
    assert ids == list(range(50))


def test_sort(rt):
    items = [{"k": v} for v in [5, 3, 9, 1, 7]]
    ds = rdata.from_items(items, num_blocks=2).sort("k")
    assert [r["k"] for r in ds.take_all()] == [1, 3, 5, 7, 9]


def test_union(rt):
    a = rdata.from_items([1, 2], num_blocks=1)
    b = rdata.from_items([3, 4], num_blocks=1)
    assert sorted(a.union(b).take_all()) == [1, 2, 3, 4]


def test_streaming_actually_streams(rt):
    """Downstream results must arrive before upstream fully finishes."""
    import time

    seen_at = []

    # more blocks than worker threads so completion comes in waves
    ds = rdata.range(80, num_blocks=32).map_batches(
        lambda b: (time.sleep(0.05), b)[1])
    for _ in ds.iter_batches():
        seen_at.append(time.monotonic())
    # if it buffered everything, gaps collapse to ~0 at the end; streaming
    # spreads arrivals over the whole run
    assert seen_at[-1] - seen_at[0] > 0.02


def test_actor_pool_compute(rt):
    class Stateful:
        def __init__(self):
            self.calls = 0

        def __call__(self, batch):
            self.calls += 1
            return {"id": batch["id"] + 1000}

    ds = rdata.range(40, num_blocks=4).map_batches(
        Stateful, compute="actors", actor_pool_size=2)
    out = sorted(int(i) for b in ds.iter_batches() for i in b["id"])
    assert out == [i + 1000 for i in range(40)]


def test_iter_batches_rebatch(rt):
    it = rdata.range(100, num_blocks=7).iterator()
    batches = list(it.iter_batches(batch_size=32))
    sizes = [len(b["id"]) for b in batches]
    assert sizes[:3] == [32, 32, 32]
    assert sum(sizes) == 100


def test_iter_jax_batches(rt):
    import jax.numpy as jnp

    it = rdata.range(64, num_blocks=4).iterator()
    batches = list(it.iter_jax_batches(batch_size=16,
                                       dtypes={"id": np.int32}))
    assert len(batches) == 4
    assert batches[0]["id"].dtype == jnp.int32
    total = sum(int(b["id"].sum()) for b in batches)
    assert total == sum(range(64))


def test_streaming_split(rt):
    splits = rdata.range(80, num_blocks=8).streaming_split(2)
    rows0 = [int(r["id"]) for r in splits[0].iter_rows()]
    rows1 = [int(r["id"]) for r in splits[1].iter_rows()]
    assert sorted(rows0 + rows1) == list(range(80))
    assert rows0 and rows1


def test_read_json_csv(rt, tmp_path):
    jp = tmp_path / "d.jsonl"
    jp.write_text('{"a": 1}\n{"a": 2}\n')
    assert sorted(r["a"] for r in rdata.read_json(str(jp)).take_all()) == [1, 2]
    cp = tmp_path / "d.csv"
    cp.write_text("x,y\n1,2\n3,4\n")
    rows = rdata.read_csv(str(cp)).take_all()
    assert sorted(r["x"] for r in rows) == ["1", "3"]


def test_materialize_and_stats(rt):
    ds = rdata.range(30, num_blocks=3).map_batches(
        lambda b: {"id": b["id"]})
    mat = ds.materialize()
    assert mat.count() == 30
    st = ds.stats()
    assert st["MapBatches"]["tasks"] == 3
