"""Nightly log-plane tier: rotation holds disk bounded under sustained
printing at scale (ci/run_ci.sh --nightly).

The fast tier (test_log_plane.py) proves one LogCapture rotates; this
tier proves the END-TO-END budget — many workers each flooding multiple
megabytes through tiny rotation bounds inherited from the environment —
keeps the node's whole log dir under
``procs * max_bytes * (rotate_count + 1)`` while lines keep reaching
the GCS store throughout."""

import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import state

pytestmark = pytest.mark.nightly

# tight bounds so the flood forces MANY rotations per worker
MAX_BYTES = 64 << 10
ROTATE_COUNT = 2
WORKERS = 4
ROUNDS = 6
LINES_PER_ROUND = 4000       # ~0.5 MB/round/worker >> 3 * 64 KiB budget


def test_rotation_holds_disk_bounded_under_flood(monkeypatch):
    from ray_tpu.utils.config import reset_config

    monkeypatch.setenv("RAY_TPU_LOG_MAX_BYTES", str(MAX_BYTES))
    monkeypatch.setenv("RAY_TPU_LOG_ROTATE_COUNT", str(ROTATE_COUNT))
    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.25")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    node = c.add_node(num_cpus=WORKERS)
    try:
        ray_tpu.init(address=c.gcs_address, log_to_driver=False)

        @ray_tpu.remote
        def flood(worker, round_no):
            pad = "z" * 96
            for i in range(LINES_PER_ROUND):
                print(f"flood w{worker} r{round_no} {i:06d} {pad}")
            return LINES_PER_ROUND

        log_dir = node.raylet.log_dir
        total_lines = 0
        for round_no in range(ROUNDS):
            got = ray_tpu.get(
                [flood.remote(w, round_no) for w in range(WORKERS)],
                timeout=300)
            total_lines += sum(got)
            # the budget holds MID-FLOOD, not just at the end: every
            # .log generation stays under max_bytes (+1 line of slack),
            # and per-proc generation count never exceeds the cap
            by_stem: dict = {}
            for name in os.listdir(log_dir):
                if ".log" not in name:
                    continue
                stem = name.split(".log")[0]
                by_stem.setdefault(stem, []).append(name)
                path = os.path.join(log_dir, name)
                try:
                    size = os.path.getsize(path)
                except OSError:
                    continue        # rotated away mid-listdir
                assert size <= MAX_BYTES + 4096, \
                    f"round {round_no}: {name} grew to {size} bytes " \
                    f"(cap {MAX_BYTES})"
            for stem, names in by_stem.items():
                assert len(names) <= ROTATE_COUNT + 1, \
                    f"round {round_no}: {stem} kept {sorted(names)}"

        assert total_lines == WORKERS * ROUNDS * LINES_PER_ROUND
        # the plane stayed live through every rotation: the store kept
        # ingesting (most lines are legitimately LOST to rotation —
        # that's the bound working — but the stream never went dark)
        deadline = time.monotonic() + 30
        listing = {}
        while time.monotonic() < deadline:
            listing = state.list_logs()
            if listing.get("ingested", 0) > WORKERS * ROUNDS:
                break
            time.sleep(0.5)
        assert listing.get("ingested", 0) > WORKERS * ROUNDS, listing
        worker_procs = [p for p in listing["procs"]
                        if p.startswith("worker-")]
        assert worker_procs, listing
    finally:
        ray_tpu.shutdown()
        c.shutdown()
        reset_config()
