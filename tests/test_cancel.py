"""Cluster-mode task cancellation (reference: python/ray/tests/
test_cancel.py — ray.cancel dequeues queued tasks, interrupts running
ones, no-ops on finished tasks)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def one_cpu_cluster():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=1)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_cancel_queued_task(one_cpu_cluster):
    @ray_tpu.remote
    def busy():
        time.sleep(5)
        return "done"

    @ray_tpu.remote
    def quick():
        return "ran"

    blocker = busy.remote()          # occupies the only CPU
    time.sleep(0.5)
    victim = quick.remote()          # stays queued behind it
    ray_tpu.cancel(victim)
    with pytest.raises(ray_tpu.exceptions.TaskCancelledError):
        ray_tpu.get(victim, timeout=20)
    ray_tpu.cancel(blocker, force=True)


def test_cancel_running_task(one_cpu_cluster):
    @ray_tpu.remote(max_retries=0)
    def sleeper():
        time.sleep(30)
        return "done"

    ref = sleeper.remote()
    time.sleep(1.5)                  # let it start running
    t0 = time.monotonic()
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.exceptions.TaskCancelledError):
        ray_tpu.get(ref, timeout=20)
    assert time.monotonic() - t0 < 15   # did not wait out the sleep


def test_cancel_finished_task_is_noop(one_cpu_cluster):
    @ray_tpu.remote
    def val():
        return 7

    ref = val.remote()
    assert ray_tpu.get(ref) == 7
    ray_tpu.cancel(ref)
    assert ray_tpu.get(ref) == 7     # still readable


def test_cancel_force_kills_worker(one_cpu_cluster):
    @ray_tpu.remote(max_retries=0)
    def hang():
        while True:
            time.sleep(1)

    ref = hang.remote()
    time.sleep(1.5)
    ray_tpu.cancel(ref, force=True)
    with pytest.raises(ray_tpu.exceptions.TaskCancelledError):
        ray_tpu.get(ref, timeout=20)
