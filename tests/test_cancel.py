"""Cluster-mode task cancellation (reference: python/ray/tests/
test_cancel.py — ray.cancel dequeues queued tasks, interrupts running
ones, no-ops on finished tasks)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def one_cpu_cluster():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=1)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_cancel_queued_task(one_cpu_cluster):
    @ray_tpu.remote
    def busy():
        time.sleep(5)
        return "done"

    @ray_tpu.remote
    def quick():
        return "ran"

    blocker = busy.remote()          # occupies the only CPU
    time.sleep(0.5)
    victim = quick.remote()          # stays queued behind it
    ray_tpu.cancel(victim)
    with pytest.raises(ray_tpu.exceptions.TaskCancelledError):
        ray_tpu.get(victim, timeout=20)
    ray_tpu.cancel(blocker, force=True)


def test_cancel_running_task(one_cpu_cluster):
    @ray_tpu.remote(max_retries=0)
    def sleeper():
        time.sleep(30)
        return "done"

    ref = sleeper.remote()
    time.sleep(1.5)                  # let it start running
    t0 = time.monotonic()
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.exceptions.TaskCancelledError):
        ray_tpu.get(ref, timeout=20)
    assert time.monotonic() - t0 < 15   # did not wait out the sleep


def test_cancel_finished_task_is_noop(one_cpu_cluster):
    @ray_tpu.remote
    def val():
        return 7

    ref = val.remote()
    assert ray_tpu.get(ref) == 7
    ray_tpu.cancel(ref)
    assert ray_tpu.get(ref) == 7     # still readable


def test_cancel_force_kills_worker(one_cpu_cluster):
    @ray_tpu.remote(max_retries=0)
    def hang():
        while True:
            time.sleep(1)

    ref = hang.remote()
    time.sleep(1.5)
    ray_tpu.cancel(ref, force=True)
    with pytest.raises(ray_tpu.exceptions.TaskCancelledError):
        ray_tpu.get(ref, timeout=20)


def test_free_releases_and_forgets(one_cpu_cluster):
    """experimental.free drops all copies AND lineage: memory reclaimed,
    subsequent get raises instead of reconstructing."""
    import numpy as np

    from ray_tpu.experimental import free

    @ray_tpu.remote(max_retries=2)
    def make():
        return np.ones(1 << 20, dtype=np.float64)  # 8 MiB

    ref = make.remote()
    val = ray_tpu.get(ref)
    first = float(val[0])
    del val          # release the zero-copy view: a held read ref blocks
    assert first == 1.0  # the free's delete (best-effort semantics)
    free(ref)
    with pytest.raises((ray_tpu.exceptions.ObjectLostError,
                        ray_tpu.exceptions.GetTimeoutError)):
        ray_tpu.get(ref, timeout=8)


def test_free_local_mode():
    import numpy as np

    from ray_tpu.experimental import free

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    try:
        ref = ray_tpu.put(np.arange(10))
        assert ray_tpu.get(ref) is not None
        free(ref)
        with pytest.raises(ray_tpu.exceptions.GetTimeoutError):
            ray_tpu.get(ref, timeout=2)
    finally:
        ray_tpu.shutdown()
