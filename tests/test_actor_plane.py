"""Batched actor control plane (round 6): registration coalescing,
bounded placement fan-out, pushed location resolution.

Reference analog: the reference's GCS-based actor management
(``gcs_actor_manager.cc`` + ``gcs_actor_scheduler.cc``) batches WAL
writes and drives placement from a bounded executor rather than a
thread per actor, and owners learn actor locations from the actor
channel pubsub, not by polling ``GetActorInfo``. These tests pin the
same properties at CI scale; the 40k axis lives in
``test_actor_plane_nightly.py``.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.runtime import core as _core
from ray_tpu.utils.config import get_config


@pytest.fixture
def cluster():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=4)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


@ray_tpu.remote(num_cpus=0)
class Echo:
    def __init__(self, i):
        self.i = i

    def who(self):
        return self.i


def _flood(n):
    actors = [Echo.remote(i) for i in range(n)]
    got = ray_tpu.get([a.who.remote() for a in actors], timeout=300)
    assert got == list(range(n))
    return actors


def _kill_all(actors):
    for a in actors:
        ray_tpu.kill(a)


def test_flood_batches_registration_and_bounds_placement(cluster):
    """One creation burst exercises both plane legs: it reaches the GCS
    as register_actors batches (fewer lock/WAL cycles than actors, not
    N singleton calls), and placement fan-out runs on the small shared
    executor as host_actors batches — the thread-per-actor
    _schedule_actor model is gone (acceptance criterion)."""
    gcs = cluster.gcs
    gcs.rpc_actor_plane_stats(None, None, reset=True)
    pool = get_config().gcs_placement_pool_size
    n = 100
    actors = _flood(n)
    try:
        plane = gcs.rpc_actor_plane_stats(None, None)
        assert plane["register_actors"] == n
        assert plane["register_batch_max"] > 1, \
            "creation burst never coalesced into a batch"
        assert plane["register_batches"] < n, \
            (f"{plane['register_batches']} batches for {n} actors — "
             "the coalescer degenerated to one frame per actor")
        # placement: bounded executor, batched host_actors frames
        assert 0 < len(gcs._place_threads) <= pool
        live = [t for t in threading.enumerate()
                if t.name.startswith("gcs-place-")]
        assert len(live) <= pool, \
            f"{len(live)} placement threads for a {pool}-thread pool"
        assert plane["host_actors"] >= n
        assert plane["host_batch_max"] > 1
    finally:
        _kill_all(actors)


def test_steady_state_resolution_is_zero_poll(cluster):
    """After warm-up, repeated calls to every actor resolve locations
    from the pushed CH_ACTOR table: the get_actor fallback poll counter
    must stay flat across the steady rounds."""
    rt = _core.get_runtime()
    assert rt._actor_pubsub, "driver should subscribe to CH_ACTOR"
    n = 32
    actors = _flood(n)
    try:
        polls0 = rt._actor_get_polls
        for _ in range(3):
            got = ray_tpu.get([a.who.remote() for a in actors],
                              timeout=120)
            assert got == list(range(n))
        assert rt._actor_get_polls == polls0, \
            (f"steady-state calls fell back to polling "
             f"({rt._actor_get_polls - polls0} get_actor polls)")
    finally:
        _kill_all(actors)


def test_pushed_table_sees_actor_death(cluster):
    """The pushed table is a liveness view, not just a create-time
    cache: a kill propagates over CH_ACTOR and the driver's table entry
    flips to DEAD without any polling."""
    rt = _core.get_runtime()
    (a,) = _flood(1)
    aid = a._actor_id.hex()
    assert rt._actor_table[aid]["state"] == "ALIVE"
    ray_tpu.kill(a)
    deadline = 10.0
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline:
        if rt._actor_table.get(aid, {}).get("state") == "DEAD":
            break
        time.sleep(0.02)
    assert rt._actor_table[aid]["state"] == "DEAD"
    assert aid not in rt._actor_locations


def test_subscribe_is_deduped_per_conn_channel(cluster):
    """Regression (round-6 satellite): a client re-sending subscribe on
    an already-subscribed channel must not be fanned out to twice —
    every CH_ACTOR event would arrive duplicated."""
    gcs = cluster.gcs

    class _Conn:
        def fileno(self):
            return -1

        def sendall(self, data):   # swallow the subscribe ack frame
            pass

    conn, lock = _Conn(), threading.Lock()
    gcs.rpc_subscribe(conn, lock, channels=["actor"])
    gcs.rpc_subscribe(conn, lock, channels=["actor", "error"])
    for ch in ("actor", "error"):
        with gcs._lock:
            entries = [c for c, _ in gcs._subs[ch] if c is conn]
        assert len(entries) == 1, \
            f"conn subscribed {len(entries)}x to channel {ch!r}"
    # drop the fake conn so the pub flusher never tries to send to it
    with gcs._lock:
        for ch in ("actor", "error"):
            gcs._subs[ch] = [(c, s) for c, s in gcs._subs[ch]
                             if c is not conn]


def test_get_actor_reply_has_no_creation_spec(cluster):
    """Regression (round-6 satellite): actor metadata replies carry
    routing state only — the pickled creation spec (closure bytes) must
    never ride rpc_get_actor / rpc_list_actors, where every location
    fallback would re-ship it."""
    (a,) = _flood(1)
    gcs = cluster.gcs
    try:
        info = gcs.rpc_get_actor(None, None,
                                 actor_id=a._actor_id.hex())
        assert info is not None
        assert "creation_spec" not in info
        assert info["state"] == "ALIVE"
        for row in gcs.rpc_list_actors(None, None):
            assert "creation_spec" not in row
    finally:
        _kill_all([a])


def test_500_actor_smoke(cluster):
    """Tier-1 bounded smoke of the nightly 40k probe: 500 actors
    through the batched plane on one node, every one answering, plane
    counters consistent."""
    gcs = cluster.gcs
    gcs.rpc_actor_plane_stats(None, None, reset=True)
    n = get_config().envelope_plane_window
    actors = _flood(n)
    try:
        plane = gcs.rpc_actor_plane_stats(None, None)
        assert plane["register_actors"] == n
        assert plane["ready_actors"] == n
        assert plane["in_flight"] == 0
    finally:
        _kill_all(actors)
