"""Namespaces for named actors.

Reference analog: ``python/ray/tests/test_namespace.py`` —
``init(namespace=...)`` scopes named actors per logical job
(``worker.py:1157,1258``; ``get_actor(name, namespace)`` ``:2784``).
"""

import pytest

import ray_tpu


@pytest.fixture
def cluster():
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


@ray_tpu.remote
class Holder:
    def __init__(self, tag="?"):
        self.tag = tag

    def get_tag(self):
        return self.tag


def test_two_drivers_do_not_collide(cluster):
    """Two 'jobs' (drivers) create same-named actors without collision
    and each resolves its own (VERDICT done-criterion)."""
    # driver A ("detached": it must outlive driver A's shutdown to prove
    # driver B resolves its own namespace — non-detached actors die with
    # their owner now, matching the reference)
    ray_tpu.init(address=cluster.gcs_address, namespace="job-a")
    a = Holder.options(name="shared-name",
                       lifetime="detached").remote("from-a")
    assert ray_tpu.get(a.get_tag.remote()) == "from-a"
    id_a = a.actor_id.hex()
    ray_tpu.shutdown()
    # driver B: same actor name, different job — NO collision
    ray_tpu.init(address=cluster.gcs_address, namespace="job-b")
    b = Holder.options(name="shared-name").remote("from-b")
    assert ray_tpu.get(b.get_tag.remote()) == "from-b"
    assert b.actor_id.hex() != id_a
    # each namespace resolves its own instance
    assert ray_tpu.get(
        ray_tpu.get_actor("shared-name").get_tag.remote()) == "from-b"
    assert ray_tpu.get(
        ray_tpu.get_actor("shared-name",
                          namespace="job-a").get_tag.remote()) == "from-a"
    # same name in the SAME namespace still collides
    with pytest.raises(Exception):
        Holder.options(name="shared-name").remote("again")


def test_init_namespace_and_get_actor(cluster):
    ray_tpu.init(address=cluster.gcs_address, namespace="ns1")
    h = Holder.options(name="scoped").remote("v1")
    assert ray_tpu.get(h.get_tag.remote()) == "v1"
    again = ray_tpu.get_actor("scoped")
    assert ray_tpu.get(again.get_tag.remote()) == "v1"
    # unknown in another namespace
    with pytest.raises(ValueError):
        ray_tpu.get_actor("scoped", namespace="elsewhere")


def test_tasks_inherit_job_namespace(cluster):
    """A task of job X resolves job X's named actors (ambient
    namespace propagation to workers)."""
    ray_tpu.init(address=cluster.gcs_address, namespace="propagate-ns")
    h = Holder.options(name="findme").remote("hello")
    ray_tpu.get(h.get_tag.remote())

    @ray_tpu.remote
    def lookup():
        actor = ray_tpu.get_actor("findme")
        return ray_tpu.get(actor.get_tag.remote())

    assert ray_tpu.get(lookup.remote(), timeout=30) == "hello"


def test_explicit_namespace_option(cluster):
    ray_tpu.init(address=cluster.gcs_address, namespace="mine")
    Holder.options(name="x", namespace="other").remote("in-other")
    with pytest.raises(ValueError):
        ray_tpu.get_actor("x")   # not in "mine"
    got = ray_tpu.get_actor("x", namespace="other")
    assert ray_tpu.get(got.get_tag.remote()) == "in-other"


def test_inprocess_namespaces(ray_tpu_start):
    h = Holder.options(name="n1").remote("local")
    assert ray_tpu.get(ray_tpu.get_actor("n1").get_tag.remote()) == "local"
    with pytest.raises(ValueError):
        ray_tpu.get_actor("n1", namespace="not-here")
