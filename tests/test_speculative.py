"""Speculative decoding tests.

The load-bearing property: for ANY draft, greedy speculative output is
bit-exact to the target's own greedy decode — drafts affect speed only.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama
from ray_tpu.models.decoding import SamplingParams, generate
from ray_tpu.models.speculative import speculative_generate


@pytest.fixture(scope="module")
def models():
    cfg_t = llama.llama_tiny(vocab_size=128)
    cfg_d = llama.LlamaConfig(
        vocab_size=128, d_model=64, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=128, head_dim=32, remat="none")
    params_t = llama.init_params(cfg_t, jax.random.key(0))
    params_d = llama.init_params(cfg_d, jax.random.key(1))
    return cfg_t, params_t, cfg_d, params_d


def _prompts():
    return jnp.array([[5, 9, 17, 33, 2, 0, 0, 0],
                      [7, 7, 7, 7, 7, 7, 7, 7]], dtype=jnp.int32)


def test_exact_vs_target_greedy_independent_draft(models):
    """An unrelated random draft must not change the output."""
    cfg_t, params_t, cfg_d, params_d = models
    want = generate(cfg_t, params_t, _prompts(),
                    sampling=SamplingParams(temperature=0.0,
                                            max_new_tokens=24))
    got = speculative_generate(cfg_t, params_t, cfg_d, params_d,
                               _prompts(), k_spec=4, max_new_tokens=24)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_perfect_draft_accepts_everything(models):
    """draft == target -> every proposal accepted: rounds collapse to
    ~max_new/(k+1) and output stays exact."""
    cfg_t, params_t, _, _ = models
    want = generate(cfg_t, params_t, _prompts(),
                    sampling=SamplingParams(temperature=0.0,
                                            max_new_tokens=24))
    got, stats = speculative_generate(
        cfg_t, params_t, cfg_t, params_t, _prompts(),
        k_spec=4, max_new_tokens=24, return_stats=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    rounds = int(stats["rounds"])
    # 24 tokens at up to 5/round -> 5 rounds; allow one slack round
    assert rounds <= 6, rounds
    assert int(stats["accepted"].sum()) >= 2 * rounds


def test_various_k(models):
    cfg_t, params_t, cfg_d, params_d = models
    want = generate(cfg_t, params_t, _prompts(),
                    sampling=SamplingParams(temperature=0.0,
                                            max_new_tokens=17))
    for k in (1, 2, 7):
        got = speculative_generate(cfg_t, params_t, cfg_d, params_d,
                                   _prompts(), k_spec=k,
                                   max_new_tokens=17)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want),
                                      err_msg=f"k={k}")


def test_eos_stops_and_pads(models):
    """Whatever token the target emits 3rd becomes EOS; output must pad
    after it exactly like the plain decoder."""
    cfg_t, params_t, cfg_d, params_d = models
    plain = generate(cfg_t, params_t, _prompts(),
                     sampling=SamplingParams(temperature=0.0,
                                             max_new_tokens=20))
    eos = int(np.asarray(plain)[0, 3])
    want = generate(cfg_t, params_t, _prompts(),
                    sampling=SamplingParams(temperature=0.0,
                                            max_new_tokens=20),
                    eos_id=eos)
    got = speculative_generate(cfg_t, params_t, cfg_d, params_d,
                               _prompts(), k_spec=4, max_new_tokens=20,
                               eos_id=eos)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_jit_wrapper_compiles_once(models):
    from ray_tpu.models.speculative import speculative_generate_jit

    cfg_t, params_t, cfg_d, params_d = models
    out1 = speculative_generate_jit(cfg_t, params_t, cfg_d, params_d,
                                    _prompts(), k_spec=2,
                                    max_new_tokens=8)
    out2 = speculative_generate_jit(cfg_t, params_t, cfg_d, params_d,
                                    _prompts() + 1, k_spec=2,
                                    max_new_tokens=8)
    assert out1.shape == out2.shape == (2, 8)
