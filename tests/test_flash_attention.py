"""Flash attention kernel numerics (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import reference_attention
from ray_tpu.ops.flash_attention import flash_attention


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("gqa", [1, 4])
def test_flash_matches_reference(causal, gqa):
    b, s, h, d = 2, 256, 4, 64
    q = jax.random.normal(jax.random.key(0), (b, s, h, d), dtype=jnp.float32)
    k = jax.random.normal(jax.random.key(1), (b, s, h // gqa, d), dtype=jnp.float32)
    v = jax.random.normal(jax.random.key(2), (b, s, h // gqa, d), dtype=jnp.float32)
    got = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_uneven_blocks():
    b, s, h, d = 1, 384, 2, 64  # 384 = 3 * 128: q/kv block walk is uneven
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))
    got = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                          interpret=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_bf16():
    b, s, h, d = 1, 256, 2, 64
    q = jax.random.normal(jax.random.key(0), (b, s, h, d)).astype(jnp.bfloat16)
    k = jax.random.normal(jax.random.key(1), (b, s, h, d)).astype(jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (b, s, h, d)).astype(jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                          interpret=True)
    want = reference_attention(q, k, v, causal=True)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, dtype=np.float32), np.asarray(want, dtype=np.float32),
        rtol=3e-2, atol=3e-2,
    )


def test_flash_grad_matches_reference():
    b, s, h, d = 1, 256, 2, 64
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                            interpret=True) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_flash_rejects_bad_seq():
    q = jnp.zeros((1, 200, 2, 64))   # 200 is not a multiple of 128
    with pytest.raises(ValueError, match="multiple of 128"):
        flash_attention(q, q, q, block_q=128, block_k=128, interpret=True)


def test_flash_block_autofit():
    """Requested blocks that don't divide seq shrink to a fitting
    128-multiple instead of erroring (640 = 5 x 128)."""
    b, s, h, d = 1, 640, 2, 64
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))
    got = flash_attention(q, k, v, causal=True, block_q=512, block_k=1024,
                          interpret=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_grad_gqa_matches_reference():
    """Backward with GQA: dk/dv are group-summed across the q-heads that
    share each kv head."""
    b, s, h, d = 1, 256, 4, 64
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h // 2, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h // 2, d))
    w = jax.random.normal(jax.random.key(3), (b, s, h, d))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, causal=True, block_q=128, block_k=128,
            interpret=True) * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) * w)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-2, atol=2e-2)
