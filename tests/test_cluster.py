"""Distributed (multi-process) cluster tests.

Reference analog: ``python/ray/tests/`` distributed suites on the
``ray_start_cluster`` fixture (conftest.py:491) — tasks/actors across
real worker processes, cross-node objects, node failure.
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=4)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_remote_task_roundtrip(cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(2, 3)) == 5


def test_task_in_separate_process(cluster):
    import os

    @ray_tpu.remote
    def worker_pid():
        return os.getpid()

    pid = ray_tpu.get(worker_pid.remote())
    assert pid != os.getpid()


def test_object_ref_args(cluster):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    r1 = double.remote(10)
    r2 = double.remote(r1)
    assert ray_tpu.get(r2) == 40


def test_put_get_numpy_zero_copy(cluster):
    import numpy as np

    arr = np.arange(100_000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    assert np.array_equal(out, arr)


def test_task_error_propagates(cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("kaboom")

    from ray_tpu.utils.exceptions import TaskError

    with pytest.raises(TaskError, match="kaboom"):
        ray_tpu.get(boom.remote())


def test_parallel_tasks(cluster):
    @ray_tpu.remote
    def slow(i):
        time.sleep(0.2)
        return i

    start = time.monotonic()
    out = ray_tpu.get([slow.remote(i) for i in range(4)])
    elapsed = time.monotonic() - start
    assert sorted(out) == [0, 1, 2, 3]
    assert elapsed < 1.5, f"tasks did not run in parallel: {elapsed:.2f}s"


def test_actor_lifecycle(cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, k=1):
            self.n += k
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    refs = [c.incr.remote() for _ in range(5)]
    assert ray_tpu.get(refs) == [11, 12, 13, 14, 15]  # submission order
    assert ray_tpu.get(c.value.remote()) == 15


def test_named_actor(cluster):
    @ray_tpu.remote
    class Registry:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v
            return True

        def get(self, k):
            return self.d.get(k)

    r = Registry.options(name="registry").remote()
    assert ray_tpu.get(r.set.remote("a", 1))
    r2 = ray_tpu.get_actor("registry")
    assert ray_tpu.get(r2.get.remote("a")) == 1


def test_actor_death_error(cluster):
    @ray_tpu.remote
    class Dyer:
        def ping(self):
            return "pong"

    d = Dyer.remote()
    assert ray_tpu.get(d.ping.remote()) == "pong"
    ray_tpu.kill(d)
    from ray_tpu.utils.exceptions import ActorError, TaskError

    with pytest.raises((ActorError, TaskError)):
        ray_tpu.get(d.ping.remote(), timeout=15)


def test_wait(cluster):
    @ray_tpu.remote
    def fast():
        return 1

    @ray_tpu.remote
    def slow():
        time.sleep(2)
        return 2

    f, s = fast.remote(), slow.remote()
    ready, not_ready = ray_tpu.wait([f, s], num_returns=1, timeout=1.5)
    assert f in ready
    assert s in not_ready


class TestMultiNode:
    @pytest.fixture
    def two_node_cluster(self):
        ray_tpu.shutdown()
        c = Cluster()
        c.add_node(num_cpus=2, resources={"head_res": 1})
        c.add_node(num_cpus=2, resources={"other_res": 1})
        c.wait_for_nodes(2)
        ray_tpu.init(address=c.gcs_address)
        yield c
        ray_tpu.shutdown()
        c.shutdown()

    def test_cluster_resources(self, two_node_cluster):
        total = ray_tpu.cluster_resources()
        assert total["CPU"] == 4.0

    def test_cross_node_scheduling(self, two_node_cluster):
        @ray_tpu.remote(resources={"other_res": 1})
        def on_other():
            import os
            return os.environ["RAY_TPU_NODE_ID"]

        @ray_tpu.remote(resources={"head_res": 1})
        def on_head():
            import os
            return os.environ["RAY_TPU_NODE_ID"]

        n1 = ray_tpu.get(on_other.remote())
        n2 = ray_tpu.get(on_head.remote())
        assert n1 != n2

    def test_cross_node_object_transfer(self, two_node_cluster):
        import numpy as np

        @ray_tpu.remote(resources={"other_res": 1})
        def produce():
            return np.ones(50_000, dtype=np.float32)

        @ray_tpu.remote(resources={"head_res": 1})
        def consume(arr):
            return float(arr.sum())

        assert ray_tpu.get(consume.remote(produce.remote())) == 50_000.0

    def test_infeasible_task_errors(self, two_node_cluster):
        @ray_tpu.remote(num_cpus=64)
        def huge():
            return 1

        from ray_tpu.utils.exceptions import RayTpuError

        with pytest.raises((RayTpuError, ValueError)):
            ray_tpu.get(huge.remote(), timeout=15)


class TestFaultTolerance:
    @pytest.fixture
    def ft_cluster(self):
        ray_tpu.shutdown()
        c = Cluster(heartbeat_timeout_s=1.5)
        c.add_node(num_cpus=2)
        ray_tpu.init(address=c.gcs_address)
        yield c
        ray_tpu.shutdown()
        c.shutdown()

    def test_actor_restart_on_worker_kill(self, ft_cluster):
        @ray_tpu.remote(max_restarts=1)
        class Phoenix:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

            def die(self):
                import os
                os._exit(1)

        p = Phoenix.remote()
        assert ray_tpu.get(p.incr.remote()) == 1
        p.die.remote()
        # restarted actor loses state but serves again
        deadline = time.monotonic() + 20
        value = None
        while time.monotonic() < deadline:
            try:
                value = ray_tpu.get(p.incr.remote(), timeout=10)
                break
            except Exception:
                time.sleep(0.2)
        assert value == 1

    def test_node_death_detected(self, ft_cluster):
        extra = ft_cluster.add_node(num_cpus=1, resources={"extra": 1},
                                    external=True)
        ft_cluster.wait_for_nodes(2)
        ft_cluster.remove_node(extra)  # SIGKILL
        from ray_tpu.runtime.rpc import RpcClient

        client = RpcClient(ft_cluster.gcs_address)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            nodes = client.call("get_nodes", alive_only=True)
            if len(nodes) == 1:
                break
            time.sleep(0.2)
        client.close()
        assert len(nodes) == 1


def test_placement_group_basic(cluster):
    from ray_tpu.runtime.rpc import RpcClient

    client = RpcClient(cluster.gcs_address)
    r = client.call("create_placement_group", pg_id="pg1",
                    bundles=[{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert r["ok"]
    info = client.call("get_placement_group", pg_id="pg1")
    assert info["state"] == "CREATED"
    assert len(info["bundle_nodes"]) == 2
    client.call("remove_placement_group", pg_id="pg1")
    client.close()


def test_memstore_put_refs_resolve_everywhere(cluster):
    """Small puts live in the owner's memory store until their ref is
    serialized: top-level args, refs NESTED in containers, and refs
    returned through tasks must all resolve on workers (promotion
    hooks) and back on the driver (memstore read)."""
    a = ray_tpu.put(20)
    b = ray_tpu.put(22)

    @ray_tpu.remote
    def add_nested(pair):
        x, y = pair
        return ray_tpu.get(x) + ray_tpu.get(y)

    assert ray_tpu.get(add_nested.remote((a, b))) == 42

    @ray_tpu.remote
    def passthrough(rs):
        return rs   # refs round-trip through the worker un-resolved
        # (top-level ref args resolve to values; nested ones don't)

    back = ray_tpu.get(passthrough.remote([a]))
    assert ray_tpu.get(back[0]) == 20
