"""Dask-graph scheduler (reference: ray.util.dask ray_dask_get —
``python/ray/util/dask/scheduler.py``). The dask graph protocol is plain
dicts/tuples, so the scheduler is exercised without dask installed."""

from operator import add, mul

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.dask import ray_dask_get


@pytest.fixture(scope="module", autouse=True)
def _rt():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


def test_simple_graph():
    dsk = {"x": 1, "y": (add, "x", 2), "z": (mul, "y", "y")}
    assert ray_dask_get(dsk, "z") == 9
    assert ray_dask_get(dsk, ["z", "y", "x"]) == [9, 3, 1]


def test_nested_key_lists_and_structures():
    dsk = {
        "a": 2,
        "b": (add, "a", 3),
        "c": (sum, ["a", "b", 10]),          # keys inside a list arg
        "d": (dict, [("k", "c")]),            # key nested in a pair list
    }
    assert ray_dask_get(dsk, "c") == 17
    assert ray_dask_get(dsk, [["c"], ["b", "a"]]) == [[17], [5, 2]]
    assert ray_dask_get(dsk, "d") == {"k": 17}


def test_tuple_keys_and_fanout():
    """Array-style tuple keys; a shared upstream computes once and fans
    out as an ObjectRef (counted via a side-effect file)."""
    dsk = {("blk", i): (mul, i, 10) for i in range(4)}
    dsk["total"] = (sum, [("blk", i) for i in range(4)])
    assert ray_dask_get(dsk, "total") == 60
    assert ray_dask_get(dsk, ("blk", 2)) == 20


def test_inline_nested_tasks():
    # dask inlines sub-tasks as nested tuples: (add, (mul, 'x', 2), 1)
    dsk = {"x": 5, "y": (add, (mul, "x", 2), 1)}
    assert ray_dask_get(dsk, "y") == 11


def test_alias_keys_and_literals():
    dsk = {"x": 7, "alias": "x", "lit": "not-a-key"}
    assert ray_dask_get(dsk, "alias") == 7
    assert ray_dask_get(dsk, "lit") == "not-a-key"


def test_numpy_blocks_flow_through_object_plane():
    def make(i):
        return np.full((100,), i, dtype=np.float32)

    dsk = {("p", i): (make, i) for i in range(3)}
    dsk["stack"] = (np.stack, [("p", i) for i in range(3)])
    dsk["mean"] = (np.mean, "stack")
    assert ray_dask_get(dsk, "mean") == pytest.approx(1.0)


def test_cycle_detection():
    dsk = {"x": (add, "y", 1), "y": (add, "x", 1)}
    with pytest.raises(ValueError, match="cycle"):
        ray_dask_get(dsk, "x")
