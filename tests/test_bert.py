"""BERT encoder family tests: bidirectionality, masking, MLM training,
sharding presets on the virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import bert


def test_encode_shapes():
    cfg = bert.bert_tiny(vocab_size=100)
    params = bert.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 100)
    h = bert.encode(cfg, params, tokens)
    assert h.shape == (2, 16, cfg.d_model)
    logits = bert.mlm_logits(cfg, params, h)
    assert logits.shape == (2, 16, 100)
    assert logits.dtype == jnp.float32


def test_bidirectional_context():
    """Unlike the causal families, changing a LATER token must change
    EARLIER hidden states."""
    cfg = bert.bert_tiny(vocab_size=64)
    params = bert.init_params(cfg, jax.random.key(0))
    t1 = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], dtype=jnp.int32)
    t2 = t1.at[0, -1].set(63)
    h1 = np.asarray(bert.encode(cfg, params, t1), dtype=np.float32)
    h2 = np.asarray(bert.encode(cfg, params, t2), dtype=np.float32)
    assert not np.allclose(h1[0, 0], h2[0, 0], atol=1e-4)


def test_attention_mask_blocks_padding():
    """Real-token hiddens must be invariant to what the pad slots
    contain when attention_mask marks them as padding."""
    cfg = bert.bert_tiny(vocab_size=64)
    params = bert.init_params(cfg, jax.random.key(0))
    mask = jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]], dtype=jnp.int32)
    t1 = jnp.array([[5, 6, 7, 8, 1, 1, 1, 1]], dtype=jnp.int32)
    t2 = jnp.array([[5, 6, 7, 8, 9, 10, 11, 12]], dtype=jnp.int32)
    h1 = np.asarray(bert.encode(cfg, params, t1, attention_mask=mask),
                    dtype=np.float32)
    h2 = np.asarray(bert.encode(cfg, params, t2, attention_mask=mask),
                    dtype=np.float32)
    np.testing.assert_allclose(h1[0, :4], h2[0, :4], atol=2e-2)


def test_mlm_training_reduces_loss():
    import optax

    cfg = bert.bert_tiny(vocab_size=64)
    params = bert.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    targets = jnp.asarray(rng.integers(4, 64, size=(4, 16)),
                          dtype=jnp.int32)
    mask_pos = jnp.asarray(rng.random((4, 16)) < 0.3)
    tokens = jnp.where(mask_pos, 3, targets)  # 3 = [MASK]

    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(
            lambda p: bert.mlm_loss(cfg, p, tokens, targets,
                                    loss_mask=mask_pos))(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    first = None
    for _ in range(12):
        params, opt_state, loss = step(params, opt_state)
        if first is None:
            first = float(loss)
    assert np.isfinite(float(loss))
    assert float(loss) < first - 0.5


def test_bert_sharded_encode():
    """Encode jits under a real fsdp_tp sharding on the 8-device mesh
    using the family's logical axes."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.parallel.mesh import create_mesh
    from ray_tpu.parallel.sharding import (
        PRESETS,
        is_axes_leaf,
        logical_sharding,
    )

    cfg = bert.bert_tiny(vocab_size=128)
    mesh = create_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    rules = PRESETS["fsdp_tp"]
    axes = bert.param_logical_axes(cfg)
    shardings = jax.tree.map(
        lambda ax: (logical_sharding(tuple(ax), mesh, rules) if ax
                    else NamedSharding(mesh, P())),
        axes, is_leaf=is_axes_leaf)
    params = jax.jit(lambda k: bert.init_params(cfg, k),
                     out_shardings=shardings)(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, 16), 0, 128)
    h = jax.jit(lambda p, t: bert.encode(cfg, p, t))(params, tokens)
    assert h.shape == (8, 16, cfg.d_model)
    assert np.isfinite(np.asarray(h, dtype=np.float32)).all()


def test_overlong_sequence_rejected():
    cfg = bert.bert_tiny()
    params = bert.init_params(cfg, jax.random.key(0))
    with pytest.raises(ValueError, match="max_seq_len"):
        bert.encode(cfg, params, jnp.zeros((1, 300), dtype=jnp.int32))


def test_bert_trains_through_jax_trainer():
    """MLM through JaxTrainer's custom loss hook: sharded state init,
    dict batches, loss goes down."""
    from ray_tpu.parallel.mesh import create_mesh
    from ray_tpu.train.trainer import JaxTrainer, TrainConfig

    cfg = bert.bert_tiny(vocab_size=128)

    def mlm(model_cfg, params, batch):
        return bert.mlm_loss(model_cfg, params, batch["tokens"],
                             batch["targets"],
                             loss_mask=batch["loss_mask"])

    mesh = create_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    trainer = JaxTrainer(cfg, TrainConfig(strategy="fsdp_tp",
                                          learning_rate=1e-3,
                                          warmup_steps=2,
                                          total_steps=30),
                         mesh=mesh, loss_fn=mlm)
    state = trainer.init_state(jax.random.key(0))
    rng = np.random.default_rng(0)
    targets = jnp.asarray(rng.integers(4, 128, size=(8, 16)),
                          dtype=jnp.int32)
    mask = jnp.asarray(rng.random((8, 16)) < 0.3)
    batch = {"tokens": jnp.where(mask, 3, targets), "targets": targets,
             "loss_mask": mask}
    losses = []
    for _ in range(8):
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] - 0.3


def test_custom_loss_with_rank1_batch_leaf():
    """The documented loss_fn contract allows [B]-shaped leaves (e.g.
    classification labels) next to [B, S] tokens."""
    from ray_tpu.parallel.mesh import create_mesh
    from ray_tpu.train.trainer import JaxTrainer, TrainConfig

    cfg = bert.bert_tiny(vocab_size=64)

    def cls_loss(model_cfg, params, batch):
        h = bert.encode(model_cfg, params, batch["tokens"])
        # mean-pool -> binary logit from the first hidden unit
        logit = h.mean(axis=1)[:, 0]
        y = batch["labels"].astype(jnp.float32)
        return jnp.mean((logit - y) ** 2)

    mesh = create_mesh({"dp": 4, "tp": 2})
    trainer = JaxTrainer(cfg, TrainConfig(strategy="fsdp_tp",
                                          warmup_steps=2,
                                          total_steps=10),
                         mesh=mesh, loss_fn=cls_loss)
    state = trainer.init_state(jax.random.key(0))
    batch = {"tokens": jnp.ones((8, 12), jnp.int32),
             "labels": jnp.array([0, 1, 0, 1, 1, 0, 1, 0], jnp.int32)}
    state, metrics = trainer.train_step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_bert_without_loss_fn_rejected():
    from ray_tpu.parallel.mesh import create_mesh
    from ray_tpu.train.trainer import JaxTrainer, TrainConfig

    with pytest.raises(ValueError, match="loss_fn"):
        JaxTrainer(bert.bert_tiny(), TrainConfig(strategy="dp"),
                   mesh=create_mesh({"dp": 8}))


def test_custom_loss_batch_scalar_leaf_replicates():
    """0-d/scalar leaves in a custom-loss batch are replicated, not
    batch-sharded."""
    from ray_tpu.parallel.mesh import create_mesh
    from ray_tpu.train.trainer import JaxTrainer, TrainConfig

    cfg = bert.bert_tiny(vocab_size=64)

    def loss(model_cfg, params, batch):
        h = bert.encode(model_cfg, params, batch["tokens"])
        return jnp.mean(h ** 2) * batch["scale"]

    trainer = JaxTrainer(cfg, TrainConfig(strategy="dp", warmup_steps=2),
                         mesh=create_mesh({"dp": 8}), loss_fn=loss)
    state = trainer.init_state(jax.random.key(0))
    batch = {"tokens": jnp.ones((8, 8), jnp.int32),
             "scale": jnp.float32(0.5)}
    _, metrics = trainer.train_step(state, batch)
    assert np.isfinite(float(metrics["loss"]))
