"""TorchTrainer tests (reference analog: train/tests/test_torch_trainer.py
— DDP over gloo with the shared session surface)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train import session
from ray_tpu.train.torch import TorchTrainer


def _make_linear_loop():
    """Returns the loop as a CLOSURE: cluster workers can't import this
    test module, so the fn must cloudpickle by value, not by reference."""

    def _linear_loop(config):
        import torch
        from torch import nn

        from ray_tpu.train import session as sess
        from ray_tpu.train.torch import prepare_model

        torch.manual_seed(0)
        model = prepare_model(nn.Linear(4, 1))
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        rng = np.random.default_rng(sess.get_context().get_world_rank())
        w_true = np.array([1.0, -2.0, 3.0, 0.5], np.float32)
        for step in range(config["steps"]):
            x = torch.tensor(rng.normal(size=(32, 4)).astype(np.float32))
            y = x @ torch.tensor(w_true)[:, None]
            loss = ((model(x) - y) ** 2).mean()
            opt.zero_grad()
            loss.backward()
            opt.step()
            sess.report({"loss": float(loss.detach()),
                         "w0": float(
                             next(model.parameters())[0, 0].detach())})

    return _linear_loop


def test_torch_trainer_single_worker(ray_tpu_start, tmp_path):
    trainer = TorchTrainer(
        _make_linear_loop(), train_loop_config={"steps": 30},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["loss"] < 0.2


def test_torch_trainer_ddp_cluster(tmp_path):
    """Two rank PROCESSES with a real gloo process group: params must
    stay identical across ranks (DDP grad sync)."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=4)
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)
        trainer = TorchTrainer(
            _make_linear_loop(), train_loop_config={"steps": 20},
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.error is None, result.error
        assert result.metrics["loss"] < 0.5
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_fit_surfaces_predeserialization_failure(tmp_path):
    """A rank whose train_fn can't even deserialize never reaches the
    report bus; fit() must surface the error instead of polling forever
    (regression: this hung before the finished-refs check)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import DataParallelTrainer

    class _ExplodesOnLoad:
        def __reduce__(self):
            def boom():
                raise RuntimeError("deserialization-boom")

            return (boom, ())

    def make_fn():
        poison = _ExplodesOnLoad()

        def train_fn(config):
            _ = poison  # forces the poison object into the closure
            return 1

        return train_fn

    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)
        trainer = DataParallelTrainer(
            make_fn(), scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.error is not None
        assert "boom" in str(result.error)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
