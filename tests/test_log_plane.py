"""Cluster log plane (tier 1): capture/rotation, task-attributed
retrieval, driver echo rate limiting, error-group dedup, idempotent
push frames, and the multi-process acceptance path.

Reference analog: ``python/ray/tests/test_output.py`` +
``test_state_api_log.py`` — but against ray_tpu's stamped-capture
design (runtime/log_plane.py): every line carries its task/trace
context in-band, so attribution is exact instead of inferred."""

import os
import sys
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.runtime import log_plane
from ray_tpu.util import state


def _wait(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture
def log_cluster(monkeypatch):
    """One in-process node with fast push intervals (the segment annex
    rides the 2s metrics pusher by default — too slow for a test)."""
    from ray_tpu.utils.config import reset_config

    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.25")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=4)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    reset_config()


# ----------------------------------------------------------------------
# capture + rotation (no cluster: the LogCapture file contract alone)
# ----------------------------------------------------------------------

def test_capture_rotation_under_byte_cap(tmp_path):
    cap = log_plane.LogCapture("rotor", str(tmp_path),
                               max_bytes=2048, rotate_count=2)
    line = "rotation-payload-" + "x" * 80
    for _ in range(200):
        cap.emit("o", line)
    cap.close()
    names = sorted(os.listdir(tmp_path))
    assert "rotor.log" in names and "rotor.log.1" in names \
        and "rotor.log.2" in names, names
    assert "rotor.log.3" not in names, \
        "rotate_count=2 must keep at most 2 old generations"
    # every generation respects the byte cap (+ one line of slack: the
    # rotation check runs after the write that crossed the cap)
    slack = 2048 + len(line) + 64
    for name in names:
        assert os.path.getsize(tmp_path / name) <= slack, name
    # each generation declares its own epoch so monitor offsets and the
    # task segment annex agree about which file an offset belongs to
    epochs = []
    for name in names:
        first = (tmp_path / name).read_bytes().split(b"\n", 1)[0]
        e = log_plane.parse_epoch(first.decode())
        assert e is not None, f"{name} missing #epoch header: {first!r}"
        epochs.append(e)
    assert len(set(epochs)) == len(epochs), f"duplicate epochs: {epochs}"
    assert cap.epoch == max(epochs)


def test_capture_line_stamp_roundtrip(tmp_path):
    cap = log_plane.LogCapture("stampy", str(tmp_path), max_bytes=1 << 20)
    with cap.task_span("t-123", "fn", "jobA", "trace-9"):
        cap.emit("e", "inside the span")
    cap.emit("o", "outside")
    cap.close()
    lines = (tmp_path / "stampy.log").read_text().splitlines()
    parsed = [log_plane.parse_line(ln) for ln in lines]
    parsed = [p for p in parsed if p is not None]   # drop #epoch
    ts, stream, trace, task, name, job, text = parsed[0]
    assert (stream, trace, task, name, job, text) == \
        ("e", "trace-9", "t-123", "fn", "jobA", "inside the span")
    assert parsed[1][3] is None and parsed[1][6] == "outside"
    # the recorded segment covers exactly the spanned line
    seg = cap._segments[-1]
    assert seg["task"] == "t-123" and seg["end"] > seg["start"]


# ----------------------------------------------------------------------
# task -> offset attribution roundtrip (cluster)
# ----------------------------------------------------------------------

def test_get_log_by_task_id_returns_exact_segment(log_cluster):
    @ray_tpu.remote
    def attributed():
        print("attr-line-one-corge")
        print("attr-line-two-corge")
        return ray_tpu.get_runtime_context().get_task_id()

    tid = ray_tpu.get(attributed.remote())
    assert tid, "worker did not bind a task id during execution"

    def fetch():
        out = state.get_log(task_id=tid)
        return out if out.get("lines") else None

    # segment annex rides the 0.25s metrics pusher; lines ride the
    # monitor's push loop — poll until both have landed
    out = _wait(fetch, 20, f"attributed segment for task {tid}")
    texts = [r["line"] for r in out["lines"]]
    # exactly that segment: both lines, nothing else bleeding in
    assert texts == ["attr-line-one-corge", "attr-line-two-corge"], texts
    assert all(r["task"] in (tid, None) for r in out["lines"])


def test_get_log_by_proc_and_list_logs(log_cluster):
    @ray_tpu.remote
    def speak():
        print("proc-tail-sentinel-garply")
        return 1

    assert ray_tpu.get(speak.remote()) == 1

    def worker_proc():
        procs = state.list_logs().get("procs") or {}
        hits = [p for p in procs if p.startswith("worker-")]
        return hits[0] if hits else None

    proc = _wait(worker_proc, 20, "worker logs to reach the store")

    def has_sentinel():
        out = state.get_log(proc=proc, tail=50)
        return out if any("garply" in r["line"]
                          for r in out.get("lines") or []) else None

    out = _wait(has_sentinel, 20, "sentinel line in the stored proc tail")
    rec = next(r for r in out["lines"] if "garply" in r["line"])
    assert rec["stream"] == "o" and rec["task"]
    listing = state.list_logs()
    assert listing["ingested"] > 0
    assert listing["procs"][proc]["lines"] > 0


# ----------------------------------------------------------------------
# driver echo: prefix, rate limit, opt-out
# ----------------------------------------------------------------------

def test_echo_rate_limit_suppresses_floods(monkeypatch, capsys):
    from ray_tpu.utils.config import reset_config

    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.25")
    monkeypatch.setenv("RAY_TPU_LOG_ECHO_RATE_LINES_S", "5")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    try:
        ray_tpu.init(address=c.gcs_address)

        @ray_tpu.remote
        def flood():
            for i in range(80):
                print(f"flood-line-{i:03d}-waldo")
            return 1

        @ray_tpu.remote
        def trickle():
            print("post-flood-trickle-fred")
            return 2

        assert ray_tpu.get(flood.remote()) == 1
        deadline = time.monotonic() + 20
        seen = ""
        while time.monotonic() < deadline:
            cap = capsys.readouterr()
            seen += cap.out + cap.err
            if "flood-line" in seen:
                break
            time.sleep(0.2)
        # a later, slower source line forces the limiter to report what
        # it swallowed (the suppression notice rides the next allowed
        # line from the same proc)
        time.sleep(1.0)
        assert ray_tpu.get(trickle.remote()) == 2
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            cap = capsys.readouterr()
            seen += cap.out + cap.err
            if "suppressed by the echo rate limit" in seen:
                break
            time.sleep(0.2)
        echoed = [ln for ln in seen.splitlines() if "flood-line" in ln]
        assert echoed, "no flood lines reached the driver at all"
        assert len(echoed) < 60, \
            f"rate limit (5/s) let {len(echoed)}/80 burst lines through"
        assert "suppressed by the echo rate limit" in seen, \
            f"limiter never reported its suppressed count; saw:\n{seen[-2000:]}"
    finally:
        ray_tpu.shutdown()
        c.shutdown()
        reset_config()


def test_echo_prefix_and_opt_out(monkeypatch, capsys):
    from ray_tpu.utils.config import reset_config

    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.25")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    try:
        ray_tpu.init(address=c.gcs_address, log_to_driver=False)

        @ray_tpu.remote
        def mute():
            print("opt-out-should-not-echo-thud")
            return 3

        assert ray_tpu.get(mute.remote()) == 3
        time.sleep(1.5)
        cap = capsys.readouterr()
        assert "opt-out-should-not-echo-thud" not in cap.out + cap.err
        # ...but the line still reached the STORE (opt-out silences the
        # echo, not the plane)
        _wait(lambda: any(
            "opt-out-should-not-echo-thud" in r["line"]
            for p in (state.list_logs().get("procs") or {})
            for r in state.get_log(proc=p, tail=200).get("lines") or []),
            20, "opted-out line to still reach the log store")
    finally:
        ray_tpu.shutdown()
        c.shutdown()
        reset_config()


# ----------------------------------------------------------------------
# error aggregation
# ----------------------------------------------------------------------

def test_summarize_errors_dedups_into_one_group(log_cluster):
    from ray_tpu.util import tracing

    tracing.enable_tracing()
    try:
        @ray_tpu.remote
        def boom(i):
            raise ValueError(f"boom-sentinel error #{i}")

        for i in (17, 42):
            with pytest.raises(Exception):
                ray_tpu.get(boom.remote(i))

        def group():
            hits = [g for g in state.summarize_errors()
                    if "boom-sentinel" in g["sample"]]
            return hits if hits and hits[0]["count"] >= 2 else None

        hits = _wait(group, 25, "deduplicated boom-sentinel error group")
        # numbers are folded out of the signature: TWO raises with
        # different payloads -> ONE group, count 2
        assert len(hits) == 1, \
            f"expected one group, got {[g['signature'] for g in hits]}"
        g = hits[0]
        assert g["count"] >= 2
        assert g["first_ts"] <= g["last_ts"]
        assert g["procs"], "group lost its emitting process"
        # tracing was on: the group links back to the task's trace
        assert g["traces"], f"error group carries no trace link: {g}"
    finally:
        tracing.disable_tracing()


def test_error_line_classifier():
    assert log_plane.is_error_line("ValueError: bad thing")
    assert log_plane.is_error_line("2026-01-01 ERROR something failed")
    assert not log_plane.is_error_line('  File "x.py", line 3, in f')
    assert not log_plane.is_error_line("Traceback (most recent call last):")
    assert not log_plane.is_error_line("all good here")
    a = log_plane.error_signature("ValueError: boom #17 at 0xdeadbeef")
    b = log_plane.error_signature("ValueError: boom #42 at 0xfeedface")
    assert a == b


# ----------------------------------------------------------------------
# satellites: flight recorder / stuck-call tails / chrome trace merge
# ----------------------------------------------------------------------

def test_flight_snapshot_and_trace_merge_carry_captured_lines(tmp_path):
    from ray_tpu.util import tracing

    try:
        cap = log_plane.install_capture("flighty", log_dir=str(tmp_path))
        assert cap is not None
        with log_plane.task_context("task-ft", "fn", None, "tr-0042"):
            print("flight-line-one")
            print("flight-line-two")
        # flight recorder payload includes the captured tail
        snap = tracing.flight_snapshot()
        tail = [r["line"] for r in snap.get("log_tail") or []]
        assert "flight-line-one" in tail and "flight-line-two" in tail
        # stuck-call enrichment source: last attributed lines by task
        assert log_plane.recent_lines("task-ft", 5) == \
            ["flight-line-one", "flight-line-two"]
        # chrome merge: attributed lines become instant events on the
        # emitting task's trace lane
        events = log_plane.chrome_instant_events()
        mine = [e for e in events if e["tid"] == "tr-0042"]
        assert len(mine) == 2 and all(e["ph"] == "i" for e in mine)
    finally:
        log_plane.uninstall_capture()


# ----------------------------------------------------------------------
# idempotent ingest (chaos-duplicated push frames)
# ----------------------------------------------------------------------

def _entry(proc="worker-abc", file="worker-abc.log@1", offs=(10, 30, 55)):
    lines = [(off, time.time(), "o", f"line-at-{off}", None, None,
              None, None) for off in offs]
    return {"proc": proc, "pid": 7, "file": file, "lines": lines}


def test_log_store_duplicate_frames_are_idempotent():
    store = log_plane.LogStore()
    first = store.ingest("node-1", [_entry()])
    assert len(first) == 1 and len(first[0]["lines"]) == 3
    # exact replay: nothing accepted, nothing re-stored, dedup counted
    replay = store.ingest("node-1", [_entry()])
    assert replay == [], "duplicate frame must not fan out (double echo)"
    assert store.deduped == 3
    assert len(store.tail("worker-abc")["lines"]) == 3
    # partial overlap: only the genuinely new offsets are accepted
    partial = store.ingest("node-1", [_entry(offs=(30, 55, 80))])
    assert [r[0] for r in partial[0]["lines"]] == [80]
    assert len(store.tail("worker-abc")["lines"]) == 4
    # a NEW epoch resets the watermark (post-rotation offsets restart)
    fresh = store.ingest("node-1", [_entry(file="worker-abc.log@2",
                                           offs=(10,))])
    assert len(fresh[0]["lines"]) == 1


def test_log_store_epoch_ordering_in_tail_cursor():
    store = log_plane.LogStore()
    for epoch in range(9, 12):
        store.ingest("n", [_entry(file=f"worker-abc.log@{epoch}",
                                  offs=(10,))])
    # cursor at epoch 10: lexicographic compare would wrongly exclude
    # epoch 11 ("@11" < "@9") — _pos_key orders epochs numerically
    out = store.tail("worker-abc", after=("worker-abc.log@10", 10))
    assert [r["file"] for r in out["lines"]] == ["worker-abc.log@11"]


# ----------------------------------------------------------------------
# multi-process acceptance: two EXTERNAL raylets, a remote actor's
# print reaches the driver echo AND the task-attributed query
# ----------------------------------------------------------------------

def test_multiprocess_print_reaches_echo_and_get_log(monkeypatch, capsys):
    from ray_tpu.utils.config import reset_config

    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.25")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2, external=True)
    c.add_node(num_cpus=2, external=True)
    c.wait_for_nodes(2, timeout=30)
    try:
        ray_tpu.init(address=c.gcs_address)

        @ray_tpu.remote
        class Chatter:
            def chat(self):
                print("multiproc-actor-says-xyzzy", file=sys.stderr)
                return ray_tpu.get_runtime_context().get_task_id()

        chatter = Chatter.remote()
        tid = ray_tpu.get(chatter.chat.remote(), timeout=60)
        assert tid

        # 1) driver echo with the (fn pid=N, node=M) identity prefix
        deadline = time.monotonic() + 25
        seen = ""
        while time.monotonic() < deadline:
            cap = capsys.readouterr()
            seen += cap.out + cap.err
            if "multiproc-actor-says-xyzzy" in seen:
                break
            time.sleep(0.2)
        line = next((ln for ln in seen.splitlines()
                     if "multiproc-actor-says-xyzzy" in ln), None)
        assert line is not None, \
            f"actor print never echoed; saw:\n{seen[-2000:]}"
        assert " pid=" in line and "node=" in line \
            and line.startswith("("), line

        # 2) the exact attributed segment through get_log(task_id=...)
        def fetch():
            out = state.get_log(task_id=tid)
            return out if out.get("lines") else None

        out = _wait(fetch, 25,
                    "attributed actor-method segment across processes")
        texts = [r["line"] for r in out["lines"]]
        assert "multiproc-actor-says-xyzzy" in texts, texts
    finally:
        ray_tpu.shutdown()
        c.shutdown()
        reset_config()
