"""Scalability-envelope tests at CI scale.

Reference analog: ``release/benchmarks/`` (the scalability envelope —
many actors, deep task queues, many args/returns, large objects,
broadcast) and ``release/benchmarks/README.md``'s single-node
dimensions. Real envelope numbers live in ``bench.py`` / BENCH_r*.json;
these tests pin down the same AXES at sizes that run in seconds, so a
regression that breaks an axis (not just slows it) fails the suite.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.utils.config import get_config

# CI-tier sizes are flags (RAY_TPU_ENVELOPE_* env overrides)
_N_ACTORS = get_config().envelope_actors
_N_QUEUED = get_config().envelope_queued_tasks
_N_ARGS = get_config().envelope_task_args


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def test_many_actors_alive(rt):
    """Hundreds of concurrent trivial actors on one node
    (envelope axis: 40k actors cluster-wide)."""
    @ray_tpu.remote
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    actors = [A.remote(i) for i in range(_N_ACTORS)]
    got = ray_tpu.get([a.who.remote() for a in actors])
    assert got == list(range(_N_ACTORS))
    for a in actors:
        ray_tpu.kill(a)


def test_deep_task_queue_drains(rt):
    """Tens of thousands of no-op tasks queued at once all complete
    (envelope axis: 1M queued on one node)."""
    @ray_tpu.remote
    def nop(i):
        return i

    n = _N_QUEUED
    refs = [nop.remote(i) for i in range(n)]
    out = ray_tpu.get(refs)
    assert out[0] == 0 and out[-1] == n - 1 and len(out) == n


def test_many_object_args_to_one_task(rt):
    """One task taking many ObjectRef args (envelope axis: 10k+;
    flag envelope_task_args)."""
    refs = [ray_tpu.put(i) for i in range(_N_ARGS)]

    @ray_tpu.remote
    def consume(*xs):
        return sum(xs)

    assert ray_tpu.get(consume.remote(*refs)) == sum(range(_N_ARGS))


def test_many_returns_from_one_task(rt):
    """One task returning 500 objects (envelope axis: 3k+)."""
    @ray_tpu.remote(num_returns=500)
    def produce():
        return tuple(range(500))

    refs = produce.remote()
    assert len(refs) == 500
    assert ray_tpu.get(refs[0]) == 0 and ray_tpu.get(refs[-1]) == 499


def test_many_objects_in_one_get(rt):
    """ray_tpu.get over 5,000 store objects (envelope axis: 10k+)."""
    refs = [ray_tpu.put(i) for i in range(5000)]
    assert ray_tpu.get(refs) == list(range(5000))


def test_large_object_integrity(rt):
    """A 256 MiB numpy object round-trips bit-exact through the shm
    store (envelope axis: 100 GiB max get; sized for CI)."""
    rng = np.random.default_rng(0)
    arr = rng.integers(0, 255, size=256 << 20, dtype=np.uint8)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref)
    assert out.nbytes == arr.nbytes
    # spot-check contents without a second full pass
    idx = rng.integers(0, arr.size, size=4096)
    np.testing.assert_array_equal(out[idx], arr[idx])


def test_nested_task_fanout(rt):
    """Tasks launching tasks: a two-level 20x20 fan-out completes
    (envelope axis: 10k simultaneous tasks via nested submission)."""
    @ray_tpu.remote
    def leaf(i, j):
        return i * 100 + j

    @ray_tpu.remote
    def branch(i):
        return sum(ray_tpu.get([leaf.remote(i, j) for j in range(20)]))

    total = sum(ray_tpu.get([branch.remote(i) for i in range(20)]))
    want = sum(i * 100 * 20 + sum(range(20)) for i in range(20))
    assert total == want
