"""Elastic mesh reformation tests (SURVEY §7 hard-parts: mesh rebuild
from checkpoint as a first-class fast operation; net-new vs reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama
from ray_tpu.train.elastic import ElasticTrainer
from ray_tpu.train.trainer import TrainConfig


def _data_iter(batch=8, seq=17, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    while True:
        yield rng.integers(0, vocab, size=(batch, seq)).astype(np.int32)


def _axes_fn(n):
    # prefer dp x fsdp factorizations
    if n % 4 == 0:
        return {"dp": n // 4, "fsdp": 4}
    if n % 2 == 0:
        return {"dp": n // 2, "fsdp": 2}
    return {"dp": n}


@pytest.fixture
def tiny_cfg():
    return llama.LlamaConfig(
        vocab_size=256, d_model=32, n_layers=2, n_heads=4, n_kv_heads=4,
        d_ff=64, head_dim=8, remat="none")


def test_reform_to_fewer_devices(tiny_cfg, tmp_path):
    """Train on 8 devices, checkpoint, lose half the slice, reform on 4,
    resume at the same step with identical params."""
    et = ElasticTrainer(
        tiny_cfg, TrainConfig(total_steps=100, warmup_steps=1),
        checkpoint_dir=str(tmp_path / "ck"), mesh_axes_fn=_axes_fn,
        devices=jax.devices()[:8], checkpoint_every=5)
    data = _data_iter()
    state = et.init_state(jax.random.key(0))
    state = et.fit(state, data, steps=5)  # hits a checkpoint at step 5
    params_before = jax.tree.map(np.asarray, state.params)
    step_before = int(state.step)

    # "failure": half the devices disappear
    state2 = et.reform(devices=jax.devices()[:4])
    assert int(state2.step) == step_before
    assert et.trainer.mesh.devices.size == 4
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6),
        params_before, jax.tree.map(np.asarray, state2.params))

    # training continues on the smaller mesh
    state3 = et.fit(state2, data, steps=2)
    assert int(state3.step) == step_before + 2
    assert len(et.reform_events) == 1
    ev = et.reform_events[0]
    assert ev.old_devices == 8 and ev.new_devices == 4
    et.close()


def test_reform_to_more_devices(tiny_cfg, tmp_path):
    """Scale UP: checkpoint on 2 devices, reform on 8."""
    et = ElasticTrainer(
        tiny_cfg, TrainConfig(total_steps=100, warmup_steps=1),
        checkpoint_dir=str(tmp_path / "ck"), mesh_axes_fn=_axes_fn,
        devices=jax.devices()[:2], checkpoint_every=2)
    data = _data_iter()
    state = et.init_state(jax.random.key(1))
    state = et.fit(state, data, steps=2)
    loss_small = None

    state2 = et.reform(devices=jax.devices()[:8])
    assert et.trainer.mesh.devices.size == 8
    # the step function compiles and runs on the new mesh
    state3, metrics = et.trainer.train_step(state2, next(data))
    loss_small = float(metrics["loss"])
    assert np.isfinite(loss_small)
    et.close()


def test_save_restore_roundtrip_same_mesh(tiny_cfg, tmp_path):
    et = ElasticTrainer(
        tiny_cfg, TrainConfig(total_steps=50, warmup_steps=1),
        checkpoint_dir=str(tmp_path / "ck"), mesh_axes_fn=_axes_fn,
        devices=jax.devices()[:4], checkpoint_every=100)
    data = _data_iter()
    state = et.init_state(jax.random.key(2))
    state, _ = et.trainer.train_step(state, next(data))
    et.save(state, force=True)
    et.ckpt.wait()
    restored = et.restore_latest()
    assert int(restored.step) == int(state.step)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6),
        jax.tree.map(np.asarray, state.params),
        jax.tree.map(np.asarray, restored.params))
    et.close()
