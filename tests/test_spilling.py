"""Object spilling: memory-pressure spill to disk + restore on read.

Reference analog: ``python/ray/tests/test_object_spilling*.py`` —
objects exceeding store capacity spill to external storage
(``_private/external_storage.py`` FileSystemStorage) and transparently
restore on ``ray.get``.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def small_store_cluster():
    ray_tpu.shutdown()
    c = Cluster()
    # 32 MiB store: ten 8 MiB objects cannot coexist in shm
    c.add_node(num_cpus=2, store_capacity=32 << 20)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_put_beyond_capacity_roundtrips(small_store_cluster):
    """Objects totaling 3x store capacity all stay readable."""
    refs = []
    arrays = []
    for i in range(12):
        arr = np.full(2 << 20, i, dtype=np.float32)  # 8 MiB each
        arrays.append(arr)
        refs.append(ray_tpu.put(arr))
    # reading them all back forces restore of spilled entries
    for arr, ref in zip(arrays, refs):
        got = ray_tpu.get(ref)
        np.testing.assert_array_equal(got, arr)


def test_task_outputs_spill_and_restore(small_store_cluster):
    @ray_tpu.remote
    def make(i):
        return np.full(2 << 20, i, dtype=np.float32)  # 8 MiB

    refs = [make.remote(i) for i in range(10)]
    totals = [float(ray_tpu.get(r)[0]) for r in refs]
    assert totals == [float(i) for i in range(10)]


def test_spill_stats_reported(small_store_cluster):
    import time

    refs = [ray_tpu.put(np.full(2 << 20, i, dtype=np.float32))
            for i in range(12)]
    # the spill loop ticks every 200 ms; give it time to act on pressure
    deadline = time.monotonic() + 10
    spilled = 0
    node = next(iter(small_store_cluster.nodes.values()))
    while time.monotonic() < deadline:
        spilled = node.raylet.spill_stats["num_spilled"]
        if spilled > 0:
            break
        time.sleep(0.2)
    assert spilled > 0, "spill loop never spilled under 3x memory pressure"
    del refs
