"""Workflow widening tests: retries, catch_exceptions, run_async,
get_output, events, metadata.
(reference analogs: workflow/tests/ — api.py run/run_async, step options,
http_event_provider)"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import DAGNode


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def _node(fn, *args, **kw):
    return DAGNode(fn, args, kw)


def test_step_retries(rt, tmp_path):
    """A flaky step succeeds within its retry budget; the attempt count
    flows through a file (closures don't round-trip to tasks)."""
    marker = tmp_path / "attempts"

    def flaky():
        n = int(marker.read_text()) if marker.exists() else 0
        marker.write_text(str(n + 1))
        if n < 2:
            raise RuntimeError("flake")
        return "ok"

    node = _node(flaky).options(workflow_max_retries=3)
    out = workflow.run(node, workflow_id="wf_retry",
                       storage=str(tmp_path / "st"))
    assert out == "ok"
    assert int(marker.read_text()) == 3


def test_step_retries_exhausted(rt, tmp_path):
    def always_fails():
        raise RuntimeError("perma")

    node = _node(always_fails).options(workflow_max_retries=1)
    with pytest.raises(Exception, match="perma"):
        workflow.run(node, workflow_id="wf_fail",
                     storage=str(tmp_path / "st"))
    assert workflow.status("wf_fail", storage=str(tmp_path / "st")) == \
        "FAILED"


def test_catch_exceptions_saga(rt, tmp_path):
    def boom():
        raise ValueError("expected")

    def compensate(res):
        value, err = res
        return f"compensated:{type(err).__name__}" if err else value

    failing = _node(boom).options(workflow_catch_exceptions=True)
    saga = _node(compensate, failing)
    out = workflow.run(saga, workflow_id="wf_saga",
                       storage=str(tmp_path / "st"))
    assert out == "compensated:ValueError"


def test_run_async_and_get_output(rt, tmp_path):
    def slow(x):
        time.sleep(0.1)
        return x * 2

    node = _node(slow, 21)
    ref = workflow.run_async(node, workflow_id="wf_async",
                             storage=str(tmp_path / "st"))
    assert ray_tpu.get(ref, timeout=30) == 42
    assert workflow.get_output("wf_async",
                               storage=str(tmp_path / "st")) == 42
    meta = workflow.metadata("wf_async", storage=str(tmp_path / "st"))
    assert meta["status"] == "SUCCESS" and meta["steps_completed"]


def test_event_trigger(rt, tmp_path):
    def after(payload):
        return b"payload:" + payload

    node = _node(after, workflow.event("go", timeout_s=10))

    def fire():
        time.sleep(0.3)
        workflow.signal_event("go", b"fired")

    threading.Thread(target=fire, daemon=True).start()
    out = workflow.run(node, workflow_id="wf_event",
                       storage=str(tmp_path / "st"))
    assert out == b"payload:fired"


def test_event_timeout(rt, tmp_path):
    node = workflow.event("never", timeout_s=0.3)
    with pytest.raises(Exception, match="never fired"):
        workflow.run(node, workflow_id="wf_event_t",
                     storage=str(tmp_path / "st"))


def test_step_ids_are_content_addressed(tmp_path, ray_tpu_start):
    """Inserting an unrelated step must not remap another step's
    checkpoint (VERDICT r1 weak #8: the topo-index scheme silently did)."""
    import ray_tpu.workflow as workflow
    from ray_tpu.dag import DAGNode

    # counting via a FILE: a closed-over counter would itself change the
    # function's content-addressed identity (captured state is hashed)
    marker = str(tmp_path / "expensive_calls")

    def expensive(x, _marker=marker):
        with open(_marker, "a") as f:
            f.write("x")
        return x * 10

    def cheap(x):
        return x + 1

    def combine(a, b=0):
        return a + b

    def n_calls():
        import os
        return os.path.getsize(marker) if os.path.exists(marker) else 0

    store = str(tmp_path)
    dag1 = DAGNode(combine, (DAGNode(expensive, (4,), {}),), {})
    assert workflow.run(dag1, workflow_id="wf_ca", storage=store) == 40
    assert n_calls() == 1

    # edited DAG: a NEW unrelated step joins; `expensive(4)` keeps its
    # identity and its checkpoint is reused, not remapped or re-run
    dag2 = DAGNode(combine,
                   (DAGNode(expensive, (4,), {}),),
                   {"b": DAGNode(cheap, (1,), {})})
    assert workflow.run(dag2, workflow_id="wf_ca", storage=store) == 42
    assert n_calls() == 1, "checkpoint was not reused"

    # changing a step's INPUT changes its id -> it re-runs
    dag3 = DAGNode(combine, (DAGNode(expensive, (5,), {}),), {})
    assert workflow.run(dag3, workflow_id="wf_ca", storage=store) == 50
    assert n_calls() == 2
