"""Versioned resource syncer (runtime/resource_sync.py).

Reference analog: ``src/ray/common/ray_syncer/ray_syncer.h:86`` —
versioned RESOURCE_VIEW sync at RPC latency. Round-3 behavior (whole-
snapshot heartbeats) left the scheduling view up to a heartbeat period
stale; these tests pin the new contract by running raylets with a
pathologically LONG heartbeat so only the event-driven push can explain
a fresh view.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.runtime.rpc import RpcClient


@pytest.fixture
def slow_heartbeat_cluster():
    from ray_tpu.utils.config import reset_config

    ray_tpu.shutdown()
    # external raylets inherit the env: 30s heartbeats mean any view
    # freshness below comes from the versioned syncer, not the beat.
    # reset_config() on BOTH sides: the flag registry caches env reads,
    # and a 30s heartbeat leaking into later tests' in-process raylets
    # breaks their failure-detection timing
    os.environ["RAY_TPU_RAYLET_HEARTBEAT_INTERVAL_S"] = "30"
    reset_config()
    c = Cluster(heartbeat_timeout_s=120.0)
    c.add_node(num_cpus=2)
    c.add_node(num_cpus=1, resources={"widget": 1}, external=True)
    c.wait_for_nodes(2)
    yield c
    os.environ.pop("RAY_TPU_RAYLET_HEARTBEAT_INTERVAL_S", None)
    reset_config()
    ray_tpu.shutdown()
    c.shutdown()


def _widget_available(gcs_address) -> float:
    client = RpcClient(tuple(gcs_address))
    try:
        return client.call("cluster_resources")["available"].get(
            "widget", 0.0)
    finally:
        client.close()


def test_view_tracks_mutations_at_rpc_latency(slow_heartbeat_cluster):
    """Acquire and release of a remote node's resource must appear in
    the GCS view within ~the push debounce, not the heartbeat period."""
    c = slow_heartbeat_cluster
    ray_tpu.init(address=c.gcs_address)

    @ray_tpu.remote(resources={"widget": 1}, num_cpus=0)
    def hold(t):
        time.sleep(t)
        return "done"

    assert _widget_available(c.gcs_address) == 1.0
    ref = hold.remote(1.0)
    # acquisition visible fast
    deadline = time.monotonic() + 2.0
    acquired_at = None
    while time.monotonic() < deadline:
        if _widget_available(c.gcs_address) == 0.0:
            acquired_at = time.monotonic()
            break
        time.sleep(0.02)
    assert acquired_at is not None, \
        "widget acquisition never reached the GCS view"
    # release visible fast after the task ends (well under the 30s beat)
    assert ray_tpu.get([ref], timeout=60)[0] == "done"
    deadline = time.monotonic() + 2.0
    released = False
    while time.monotonic() < deadline:
        if _widget_available(c.gcs_address) == 1.0:
            released = True
            break
        time.sleep(0.02)
    assert released, "widget release never reached the GCS view " \
                     "(event-driven push missing; heartbeat is 30s)"


def test_task_schedules_promptly_after_remote_release(
        slow_heartbeat_cluster):
    """VERDICT done-criterion: a placement decision made right after a
    remote resource frees must succeed promptly — the old snapshot
    heartbeat would leave the view stale for the full period."""
    c = slow_heartbeat_cluster
    ray_tpu.init(address=c.gcs_address)

    @ray_tpu.remote(resources={"widget": 1}, num_cpus=0)
    def use_widget():
        return os.getpid()

    @ray_tpu.remote(resources={"widget": 1}, num_cpus=0)
    def hold(t):
        time.sleep(t)
        return "held"

    ref = hold.remote(0.8)
    time.sleep(0.2)   # the widget is now visibly busy
    assert ray_tpu.get([ref], timeout=60)[0] == "held"
    # submit AFTER release: placement consults the GCS view; with a 30s
    # heartbeat only the syncer can have marked the widget free
    t0 = time.monotonic()
    out = ray_tpu.get([use_widget.remote()], timeout=60)[0]
    elapsed = time.monotonic() - t0
    assert isinstance(out, int)
    assert elapsed < 5.0, f"scheduling stalled {elapsed:.1f}s on a " \
                          f"stale resource view"


def test_heartbeat_payload_is_version_only():
    """The liveness beat must not carry the resource dict (payload
    O(1)); the versioned push channel owns the view."""
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    try:
        node = next(iter(c.nodes.values())).raylet
        assert node.resource_syncer is not None
        v0 = node.resource_syncer.version
        ray_tpu.init(address=c.gcs_address)

        @ray_tpu.remote(num_cpus=1)
        def f():
            return 1

        assert ray_tpu.get([f.remote()], timeout=30)[0] == 1
        # dispatch + completion bumped the version (event stream alive)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if node.resource_syncer.version > v0:
                break
            time.sleep(0.02)
        assert node.resource_syncer.version > v0
    finally:
        ray_tpu.shutdown()
        c.shutdown()
