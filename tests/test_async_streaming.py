"""Async (asyncio) actors + streaming generator tasks.

Reference coverage analog: ``python/ray/tests/test_asyncio.py`` (async
actors: overlapping awaits, max_concurrency bounding) and
``test_streaming_generator.py`` (``num_returns="streaming"`` consumed
ref-by-ref while the task runs).
"""

import asyncio  # noqa: F401 - used inside remote bodies
import time

import pytest

import ray_tpu
from ray_tpu.runtime.streaming import ObjectRefGenerator
from ray_tpu.utils.exceptions import TaskError


@pytest.fixture
def two_cpu_cluster():
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


# ----------------------------------------------------------------------
# async actors
# ----------------------------------------------------------------------

@ray_tpu.remote
class AsyncWorkerA:
    def __init__(self):
        self.peak = 0
        self.live = 0

    async def slow(self, delay):
        import asyncio

        self.live += 1
        self.peak = max(self.peak, self.live)
        await asyncio.sleep(delay)
        self.live -= 1
        return self.peak

    def sync_peak(self):
        return self.peak


def test_async_actor_overlapping_awaits_cluster(two_cpu_cluster):
    a = AsyncWorkerA.remote()
    t0 = time.monotonic()
    refs = [a.slow.remote(0.4) for _ in range(6)]
    peaks = ray_tpu.get(refs)
    elapsed = time.monotonic() - t0
    # six 0.4s awaits overlapping: far below the 2.4s serial floor
    assert elapsed < 1.6, elapsed
    assert max(peaks) >= 4   # awaits genuinely interleaved


def test_async_actor_overlapping_awaits_inprocess(ray_tpu_start):
    a = AsyncWorkerA.remote()
    t0 = time.monotonic()
    peaks = ray_tpu.get([a.slow.remote(0.3) for _ in range(4)])
    assert time.monotonic() - t0 < 1.0
    assert max(peaks) >= 3


def test_async_actor_max_concurrency_bounds(two_cpu_cluster):
    a = AsyncWorkerA.options(max_concurrency=2).remote()
    peaks = ray_tpu.get([a.slow.remote(0.15) for _ in range(6)])
    assert max(peaks) <= 2


def test_async_actor_sync_method_and_errors(two_cpu_cluster):
    @ray_tpu.remote
    class B:
        async def boom(self):
            raise ValueError("async boom")

        def fine(self):
            return "ok"

    b = B.remote()
    assert ray_tpu.get(b.fine.remote()) == "ok"
    with pytest.raises(TaskError):
        ray_tpu.get(b.boom.remote())
    assert ray_tpu.get(b.fine.remote()) == "ok"   # actor survives


def test_async_remote_function(two_cpu_cluster):
    @ray_tpu.remote
    def coro_task(x):
        async def body():
            import asyncio

            await asyncio.sleep(0.01)
            return x * 2

        return body()

    # async def at module pickling level: define via wrapper returning coro
    assert ray_tpu.get(coro_task.remote(21)) == 42


# ----------------------------------------------------------------------
# streaming generators
# ----------------------------------------------------------------------

def test_streaming_generator_cluster(two_cpu_cluster):
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        import time as _t

        for i in range(n):
            _t.sleep(0.15)
            yield i * 10

    t0 = time.monotonic()
    g = gen.remote(5)
    assert isinstance(g, ObjectRefGenerator)
    first_ref = next(g)
    first_at = time.monotonic() - t0
    out = [ray_tpu.get(first_ref)] + [ray_tpu.get(r) for r in g]
    total = time.monotonic() - t0
    assert out == [0, 10, 20, 30, 40]
    # the STREAMING property: the first yield was consumable well
    # before the stream finished. Stated relative to the total (the
    # remaining 4 yields take >= 0.6s) — an absolute bound on first_at
    # entangles worker-spawn latency, which is SECONDS on a loaded
    # 1-cpu box deep into a full-suite run (flaked twice there while
    # passing 5/5 in isolation)
    assert first_at < total - 0.3, (first_at, total)
    assert total >= 0.7   # the stream outlived the first item


def test_streaming_generator_inprocess(ray_tpu_start):
    @ray_tpu.remote(num_returns="streaming")
    def gen():
        yield "a"
        yield "b"

    vals = [ray_tpu.get(r) for r in gen.remote()]
    assert vals == ["a", "b"]


def test_streaming_dynamic_alias(ray_tpu_start):
    @ray_tpu.remote(num_returns="dynamic")
    def gen():
        yield 1

    refs = list(gen.remote())
    assert ray_tpu.get(refs[0]) == 1


def test_streaming_midstream_error(two_cpu_cluster):
    @ray_tpu.remote(num_returns="streaming")
    def bad():
        yield 1
        yield 2
        raise RuntimeError("stream died")

    g = bad.remote()
    it = iter(g)
    assert ray_tpu.get(next(it)) == 1
    assert ray_tpu.get(next(it)) == 2
    with pytest.raises(Exception) as ei:
        ray_tpu.get(next(it))
    assert "stream died" in str(ei.value)
    with pytest.raises(StopIteration):
        next(it)


def test_streaming_generator_as_task_arg(two_cpu_cluster):
    @ray_tpu.remote(num_returns="streaming")
    def produce():
        for i in range(3):
            yield i

    @ray_tpu.remote
    def consume(g):
        return sum(ray_tpu.get(r) for r in g)

    g = produce.remote()
    assert ray_tpu.get(consume.remote(g)) == 3


def test_streaming_actor_method(two_cpu_cluster):
    @ray_tpu.remote
    class Gen:
        def produce(self, n):
            for i in range(n):
                yield i * 3

    g = Gen.remote()
    out = [ray_tpu.get(r) for r in
           g.produce.options(num_returns="streaming").remote(4)]
    assert out == [0, 3, 6, 9]


def test_streaming_invalid_num_returns(ray_tpu_start):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(ValueError):
        f.options(num_returns="bogus").remote()
