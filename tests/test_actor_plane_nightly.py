"""Batched actor control plane — the NIGHTLY 40k-actor axis.

Reference analog: ``release/benchmarks/README.md:9`` — 40k actors is
the reference's published envelope, proven nightly on 64 hosts. On few
hosts the binding constraint is not memory (the fork-server pool covers
that at 10k concurrent, ``test_fork_envelope_nightly.py``) but the
CONTROL PLANE: per-actor registration RPCs, thread-per-actor placement
and location polling collapse long before 40k. This axis drives 40k
actors THROUGH that plane — windowed like the reference's long-running
many-actor release test (create → call → kill per window) so at most
``envelope_plane_window`` are alive at once — and asserts the batched
machinery actually carried them.

Sized by ``RAY_TPU_ENVELOPE_NIGHTLY_PLANE_ACTORS`` (default 40,000) and
``RAY_TPU_ENVELOPE_PLANE_WINDOW`` (default 500). Selected only by
``ci/run_ci.sh --nightly`` (``pytest -m nightly``).
"""

import os
import time

import pytest

# wave-tail actors can take minutes to come ALIVE on a saturated host;
# the interactive-sized resolve deadline would error the whole wave
os.environ.setdefault("RAY_TPU_ACTOR_RESOLVE_TIMEOUT_S", "1800")

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.runtime import core as _core
from ray_tpu.runtime.rpc import RpcClient
from ray_tpu.utils.config import get_config

pytestmark = [pytest.mark.nightly, pytest.mark.slow]

_N_ACTORS = get_config().envelope_nightly_plane_actors
_WINDOW = get_config().envelope_plane_window


@pytest.fixture(scope="module")
def plane_cluster():
    ray_tpu.shutdown()
    # same shape as the fork-envelope nightly: generous heartbeat (a
    # raylet starved of cpu during the ramp must not be declared dead),
    # 3 external raylets + an in-process head
    c = Cluster(external_gcs=True, heartbeat_timeout_s=90.0)
    c.add_node(num_cpus=4)
    for _ in range(3):
        c.add_node(num_cpus=4, external=True)
    c.wait_for_nodes(4)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_40k_actors_through_batched_plane(plane_cluster):
    """40,000 actors flow through registration/placement/ready in
    batches; creation rate and the plane decomposition are the recorded
    envelope numbers (printed with ``-s``)."""
    c = plane_cluster
    rt = _core.get_runtime()

    @ray_tpu.remote(num_cpus=0)
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    probe = RpcClient(tuple(c.gcs_address), label="driver")
    probe.call("actor_plane_stats", reset=True)
    polls0 = rt._actor_get_polls
    n, window = _N_ACTORS, _WINDOW
    done = 0
    t0 = time.monotonic()
    try:
        while done < n:
            take = min(window, n - done)
            wave = [A.remote(done + i) for i in range(take)]
            got = ray_tpu.get([a.who.remote() for a in wave],
                              timeout=1800)
            assert got == list(range(done, done + take))
            for a in wave:
                ray_tpu.kill(a)
            done += take
            if done % 5000 == 0:
                el = time.monotonic() - t0
                print(f"  {done}/{n} ({done / el:.0f} actors/s)",
                      flush=True)
        el = time.monotonic() - t0
        plane = probe.call("actor_plane_stats")
        polls = rt._actor_get_polls - polls0
        print(f"\n{n} actors through the batched plane in {el:.1f}s "
              f"({n / el:.1f} actors/s); register_batches="
              f"{plane['register_batches']} (max "
              f"{plane['register_batch_max']}), host_batches="
              f"{plane['host_batches']} (max {plane['host_batch_max']}),"
              f" place_mean="
              f"{1e3 * plane['place_s'] / max(1, plane['placed']):.1f}ms"
              f", ready_mean="
              f"{1e3 * plane['ready_s'] / max(1, plane['ready']):.1f}ms"
              f", fallback_polls={polls}")
        # the axis is only proven if the BATCHED plane carried it:
        # coalesced registration, batched placement, and (near-)zero
        # fallback polling against the pushed location table
        assert plane["register_actors"] == n
        assert plane["register_batch_max"] > 1
        assert plane["register_batches"] < n
        assert plane["host_batch_max"] > 1
        # resolution rode CH_ACTOR pushes; a handful of quiet-window
        # fallbacks under CPU starvation are tolerated, per-actor
        # polling (>= 1 poll/actor) is the regression this guards
        assert polls < n / 10, \
            f"{polls} fallback polls for {n} actors — pushed table idle"
    finally:
        probe.close()
