"""Per-node observability: worker stack dumps, sampling profiles, host
stats (reference: the dashboard reporter agent + py-spy integration —
``dashboard/modules/reporter/profile_manager.py:11-51``)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import state as state_api


@pytest.fixture
def cluster():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_sampler_unit():
    from ray_tpu.util.profiling import dump_stacks, sample_profile

    stacks = dump_stacks()
    assert any("MainThread" in k for k in stacks)
    assert any("test_sampler_unit" in v for v in stacks.values())

    prof = sample_profile(duration_s=0.3, hz=50)
    assert prof["samples"] > 5
    assert "profiling.py:sample_profile" not in prof["folded"]


def test_host_stats_unit(tmp_path):
    pytest.importorskip("psutil")
    from ray_tpu.util.profiling import host_stats

    stats = host_stats(str(tmp_path))
    assert stats["mem_total"] > 0
    assert "spill_disk_free" in stats


def test_worker_stacks_via_state_api(cluster):
    @ray_tpu.remote
    def busy_beaver():
        time.sleep(8)
        return "done"

    ref = busy_beaver.remote()
    time.sleep(1.0)   # worker is now inside time.sleep
    stacks = state_api.dump_worker_stacks()
    flat = json.dumps(stacks)
    assert "busy_beaver" in flat, f"task frame missing: {flat[:500]}"
    ray_tpu.cancel(ref, force=True)


def test_profile_worker_flamegraph(cluster):
    @ray_tpu.remote
    def spin(seconds):
        t0 = time.monotonic()
        n = 0
        while time.monotonic() - t0 < seconds:
            n += 1
        return n

    ref = spin.remote(6)
    time.sleep(0.8)
    workers = state_api.dump_worker_stacks()
    node_id, per_worker = next(iter(workers.items()))
    victim = next(w for w, s in per_worker.items()
                  if "spin" in json.dumps(s))
    prof = state_api.profile_worker(victim, duration_s=1.0, hz=100)
    assert prof.get("samples", 0) > 10, prof
    assert "spin" in prof["folded"]
    assert ray_tpu.get(ref, timeout=30) > 0


def test_heartbeat_carries_host_stats(cluster):
    pytest.importorskip("psutil")
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        nodes = state_api.list_nodes()
        if nodes and nodes[0].get("host_stats", {}).get("mem_total"):
            return
        time.sleep(0.25)
    raise AssertionError(f"no host stats in node table: {nodes}")


def test_dashboard_stacks_endpoint(cluster):
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray_tpu.remote
    def napper():
        time.sleep(6)

    ref = napper.remote()
    time.sleep(1.0)
    dash = start_dashboard()
    try:
        with urllib.request.urlopen(dash.url + "/api/stacks",
                                    timeout=30) as resp:
            body = json.loads(resp.read())
        assert "napper" in json.dumps(body)
    finally:
        stop_dashboard()
        ray_tpu.cancel(ref, force=True)
