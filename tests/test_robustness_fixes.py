"""Regression tests for the round-5 robustness findings.

Each test pins a specific fixed defect and fails on the pre-fix code:

1. ``Runtime.get()`` slow path: a sustained direct-result stream woke
   the memstore cv every cycle and each wake skipped ``ensure_local``
   entirely — a remote shm object never got its pull issued (starvation).
2. ``Runtime._accept_direct_results``: a result arriving after its last
   local ref died was kept in the memory store forever (the release hook
   had already fired; no death notice would ever come again).
3. ``ProxyManager._spawn_child``: the announce-line read had no real
   timeout (``readline()`` blocks between deadline checks), and the
   spawn ran UNDER the manager lock — one wedged child start blocked
   every other session's hello.
4. ``AccelerateTrainer``: structured YAML configs were mangled by the
   line-splitting fallback even when the ``yaml`` package was available.
"""

import sys
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.runtime.task_spec import SchedulingStrategy


@pytest.fixture
def two_node_cluster():
    ray_tpu.shutdown()
    c = Cluster(heartbeat_timeout_s=1.0)
    c.add_node(num_cpus=2)
    c.add_node(num_cpus=2, resources={"side": 4})
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


# ----------------------------------------------------------------------
# 1. get() slow path vs a direct-result arrival storm
# ----------------------------------------------------------------------

def test_get_survives_direct_arrival_storm(two_node_cluster):
    """A remote (other-node, shm-sized) object must resolve even while
    direct results arrive continuously: the memstore-cv wake may defer
    the ensure_local window only a bounded number of times (~100 ms),
    not indefinitely. Pre-fix, every wake skipped ensure_local and this
    get() starved to GetTimeoutError."""
    from ray_tpu import api

    side = next(h for h in two_node_cluster.nodes.values()
                if h.raylet is not None
                and "side" in h.raylet.total_resources)

    @ray_tpu.remote(scheduling_strategy=SchedulingStrategy(
        kind="NODE_AFFINITY", node_id=side.node_id))
    def big():
        time.sleep(0.5)     # land AFTER the storm is underway
        return np.ones(1 << 18, dtype=np.float64)   # 2 MiB: shm path

    ref = big.remote()
    rt = api._runtime()
    stop = threading.Event()

    def storm():
        # perpetual direct-arrival wakeups: exactly the signal a stream
        # of small task returns produces
        while not stop.is_set():
            with rt._mem_cv:
                rt._mem_arrivals += 1
                rt._mem_cv.notify_all()
            time.sleep(0.001)

    t = threading.Thread(target=storm, daemon=True)
    t.start()
    try:
        got = ray_tpu.get(ref, timeout=20)
        assert float(got[0]) == 1.0
    finally:
        stop.set()
        t.join(timeout=5)


# ----------------------------------------------------------------------
# 2. direct results for already-dead refs must not leak the memstore
# ----------------------------------------------------------------------

def test_direct_result_for_dead_ref_is_evicted(two_node_cluster):
    from ray_tpu import api

    rt = api._runtime()
    if not rt._use_memstore:
        pytest.skip("memory store disabled (ref counting off)")

    live = ray_tpu.put(123)            # holds a local ref
    live_hex = live.id.hex()
    dead_hex = "ab" * 16               # no ref anywhere: died in flight
    assert rt._refs.count(dead_hex) == 0

    rt._accept_direct_results({dead_hex: b"payload-of-a-dead-ref",
                               live_hex: b"payload-of-a-live-ref"})
    assert dead_hex not in rt._memstore, \
        "dead-ref direct result leaked into the memory store"
    # the live oid stays resident (normal direct-return behavior)
    assert live_hex in rt._memstore
    del live


# ----------------------------------------------------------------------
# 3. proxier: announce timeout + spawn outside the manager lock
# ----------------------------------------------------------------------

@pytest.fixture
def proxy_manager():
    from ray_tpu.client.proxier import ProxyManager

    manager = ProxyManager(port=0, child_spawn_timeout_s=1.0)
    yield manager
    manager.stop()


def test_proxier_spawn_timeout_is_real(proxy_manager):
    """A child that starts but never announces must fail the hello at
    the spawn timeout. Pre-fix, readline() blocked forever: the 60 s
    deadline was only checked between lines that never came."""
    proxy_manager._spawn_cmd = [sys.executable, "-c",
                                "import time; time.sleep(60)"]
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="did not announce"):
        proxy_manager.rpc_client_hello(None, None)
    assert time.monotonic() - t0 < 10.0
    # the failed spawn must not poison the token table
    assert proxy_manager._children == {}


def test_proxier_dead_child_reported(proxy_manager):
    proxy_manager._spawn_cmd = [sys.executable, "-c", "raise SystemExit(3)"]
    with pytest.raises(RuntimeError, match="died at startup"):
        proxy_manager.rpc_client_hello(None, None)
    assert proxy_manager._children == {}


def test_proxier_spawns_do_not_serialize_across_tokens(proxy_manager):
    """Two different sessions' hellos must spawn their children
    CONCURRENTLY. Pre-fix, the spawn ran under the manager lock: a slow
    child start serialized every hello behind it."""
    proxy_manager._spawn_timeout = 30.0
    proxy_manager._spawn_cmd = [
        sys.executable, "-c",
        "import time; time.sleep(1.2); "
        "print('client server on 127.0.0.1:1', flush=True); "
        "time.sleep(30)"]
    results = {}

    def hello(token):
        results[token] = proxy_manager.rpc_client_hello(
            None, None, session_token=token)

    t0 = time.monotonic()
    threads = [threading.Thread(target=hello, args=(tok,))
               for tok in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    elapsed = time.monotonic() - t0
    assert set(results) == {"a", "b"}
    # concurrent: ~1.2 s. Serialized (pre-fix): >= 2.4 s.
    assert elapsed < 2.3, f"hellos serialized: {elapsed:.2f}s"


def test_proxier_same_token_waits_for_inflight_spawn(proxy_manager):
    proxy_manager._spawn_timeout = 30.0
    proxy_manager._spawn_cmd = [
        sys.executable, "-c",
        "import time; time.sleep(0.8); "
        "print('client server on 127.0.0.1:2', flush=True); "
        "time.sleep(30)"]
    results = []

    def hello():
        results.append(proxy_manager.rpc_client_hello(
            None, None, session_token="tok"))

    threads = [threading.Thread(target=hello) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    assert len(results) == 3
    addrs = {tuple(r["redirect"]) for r in results}
    assert addrs == {("127.0.0.1", 2)}   # ONE child served all three
    assert len(proxy_manager._children) == 1


# ----------------------------------------------------------------------
# 4. accelerate config parsing
# ----------------------------------------------------------------------

NESTED_YAML = """\
compute_environment: LOCAL_MACHINE
deepspeed_config:
  zero_stage: 3
  offload_optimizer_device: cpu
mixed_precision: bf16
"""


def test_accelerate_nested_yaml_parses_with_yaml_package():
    pytest.importorskip("yaml")
    from ray_tpu.train.accelerate import _parse_accelerate_config

    cfg = _parse_accelerate_config(NESTED_YAML)
    # pre-fix the fallback splitter produced garbage like
    # {"deepspeed_config": "", "zero_stage": "3", ...}
    assert cfg["deepspeed_config"] == {"zero_stage": 3,
                                       "offload_optimizer_device": "cpu"}
    assert cfg["mixed_precision"] == "bf16"
    assert "zero_stage" not in cfg


def test_accelerate_fallback_rejects_nested_yaml(monkeypatch):
    from ray_tpu.train import accelerate

    monkeypatch.setitem(sys.modules, "yaml", None)   # import -> ImportError
    with pytest.raises(ValueError, match="nested"):
        accelerate._parse_accelerate_config(NESTED_YAML)


def test_accelerate_fallback_parses_flat_config(monkeypatch):
    from ray_tpu.train import accelerate

    monkeypatch.setitem(sys.modules, "yaml", None)
    cfg = accelerate._parse_accelerate_config(
        "---\n# a comment\nmixed_precision: bf16\ncpu: true  # inline\n")
    assert cfg == {"mixed_precision": "bf16", "cpu": "true"}


def test_accelerate_json_config_still_works():
    from ray_tpu.train.accelerate import _parse_accelerate_config

    assert _parse_accelerate_config('{"cpu": true}') == {"cpu": True}
