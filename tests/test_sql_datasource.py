"""SQL datasource against sqlite3 (reference:
python/ray/data/datasource/sql_datasource.py — zero new deps)."""

import sqlite3

import pytest

from ray_tpu.data.sql import read_sql, write_sql


@pytest.fixture
def db(tmp_path):
    path = str(tmp_path / "t.db")
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE users (id INTEGER, name TEXT, score REAL)")
    conn.executemany("INSERT INTO users VALUES (?, ?, ?)",
                     [(i, f"user{i}", i * 1.5) for i in range(20)])
    conn.commit()
    conn.close()
    return path


def test_read_sql_rows(db):
    ds = read_sql("SELECT * FROM users ORDER BY id",
                  lambda: sqlite3.connect(db))
    rows = ds.take_all()
    assert len(rows) == 20
    assert rows[0] == {"id": 0, "name": "user0", "score": 0.0}
    assert rows[19]["name"] == "user19"


def test_read_sql_projection_and_filter(db):
    ds = read_sql("SELECT id, score FROM users WHERE id >= 15",
                  lambda: sqlite3.connect(db))
    rows = ds.take_all()
    assert len(rows) == 5
    assert set(rows[0]) == {"id", "score"}


def test_read_sql_composes_with_transforms(db):
    ds = read_sql("SELECT id FROM users", lambda: sqlite3.connect(db))
    doubled = ds.map(lambda r: {"id": r["id"] * 2})
    assert sum(r["id"] for r in doubled.take_all()) == 2 * sum(range(20))


def test_write_sql_roundtrip(db, tmp_path):
    out = str(tmp_path / "out.db")
    conn = sqlite3.connect(out)
    conn.execute("CREATE TABLE scores (id INTEGER, score REAL)")
    conn.commit()
    conn.close()
    ds = read_sql("SELECT id, score FROM users WHERE id < 5",
                  lambda: sqlite3.connect(db))
    write_sql(ds, "INSERT INTO scores VALUES (?, ?)",
              lambda: sqlite3.connect(out))
    conn = sqlite3.connect(out)
    rows = conn.execute("SELECT * FROM scores ORDER BY id").fetchall()
    conn.close()
    assert rows == [(i, i * 1.5) for i in range(5)]


def test_dataset_write_sql_method(db, tmp_path):
    import ray_tpu.data as rd

    out = str(tmp_path / "m.db")
    conn = sqlite3.connect(out)
    conn.execute("CREATE TABLE t (id INTEGER)")
    conn.commit()
    conn.close()
    ds = rd.read_sql("SELECT id FROM users WHERE id < 3",
                     lambda: sqlite3.connect(db))
    ds.write_sql("INSERT INTO t VALUES (?)",
                 lambda: sqlite3.connect(out))
    conn = sqlite3.connect(out)
    assert conn.execute("SELECT COUNT(*) FROM t").fetchone()[0] == 3
    conn.close()


class _FakeCollection:
    """pymongo Collection double (the datasource is duck-typed so the
    real pymongo stays optional)."""

    def __init__(self, store):
        self.store = store

    def find(self, query=None, projection=None):
        rows = [dict(d) for d in self.store]
        if query:
            rows = [r for r in rows
                    if all(r.get(k) == v for k, v in query.items())]
        if projection:
            keep = {k for k, v in projection.items() if v}
            rows = [{k: r[k] for k in r if k in keep or k == "_id"}
                    for r in rows]
        return iter(rows)

    def insert_many(self, docs):
        self.store.extend(dict(d) for d in docs)


def test_read_mongo_rows_and_query():
    from ray_tpu.data.mongo import read_mongo

    store = [{"_id": i, "name": f"u{i}", "score": i * 2} for i in range(8)]
    ds = read_mongo(lambda: _FakeCollection(store))
    rows = ds.take_all()
    assert len(rows) == 8 and rows[0]["_id"] == "0"   # _id stringified
    ds2 = read_mongo(lambda: _FakeCollection(store),
                     query={"name": "u3"})
    assert [r["score"] for r in ds2.take_all()] == [6]


def test_write_mongo_roundtrip():
    import ray_tpu.data as rd
    from ray_tpu.data.mongo import write_mongo

    src = [{"_id": i, "v": i} for i in range(5)]
    sink: list = []
    ds = rd.read_mongo(lambda: _FakeCollection(src))
    write_mongo(ds, lambda: _FakeCollection(sink))
    assert sorted(int(d["v"]) for d in sink) == list(range(5))
