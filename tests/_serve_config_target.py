"""Import target for serve declarative-config tests (the module an
``import_path`` in a config YAML points at)."""

from ray_tpu import serve


@serve.deployment
class Greeter:
    def __init__(self, greeting: str = "hello"):
        self.greeting = greeting

    def __call__(self, name: str) -> str:
        return f"{self.greeting} {name}"


greeter = Greeter  # plain Deployment (unbound)
bound_greeter = Greeter.bind("hi")


from collections import namedtuple

Point = namedtuple("Point", "x y")
