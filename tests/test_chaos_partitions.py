"""Chaos tier: sustained network partitions + duplicate-delivery
idempotency, driven through the deterministic fault-injection plane
(``ray_tpu/runtime/fault_injection.py``).

Reference analog: ``python/ray/tests/chaos`` — but deterministic: every
fault here is a seeded rule switched on and off through the GCS KV key
mid-workload, not a random killer.

Default tier runs the driver↔GCS partition smoke; the raylet↔raylet and
worker↔owner matrices are ``slow`` (ci/run_ci.sh runs them nightly).
"""

import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.runtime import fault_injection as fi
from ray_tpu.runtime.task_spec import SchedulingStrategy

HEARTBEAT_S = 1.0
# hold every partition across >= 2 heartbeat timeouts: liveness machinery
# (GCS health checks, raylet beats) must fire while the wire is down
PARTITION_S = 2.2 * HEARTBEAT_S


@pytest.fixture
def chaos_cluster():
    ray_tpu.shutdown()
    fi.plane.clear()
    c = Cluster(heartbeat_timeout_s=HEARTBEAT_S)
    c.add_node(num_cpus=2)
    c.add_node(num_cpus=2, resources={"side": 4})
    ray_tpu.init(address=c.gcs_address)
    yield c
    fi.plane.clear()     # never leave a partition open across teardown
    ray_tpu.shutdown()
    fi.stop_kv_watcher()
    c.shutdown()
    fi.plane.clear()


def _addr(address) -> str:
    return f"{address[0]}:{address[1]}"


def _open_partition(cluster, *, src, dst_name, dst_addrs, version):
    """Switch a partition ON through the GCS KV key (the runtime path:
    every process applies it from the KV watch; in-process test clusters
    share one plane, applied by the GCS at kv_put time)."""
    fi.put_plan(cluster.gcs_address, {
        "version": version, "seed": 7,
        "endpoints": {dst_name: [_addr(a) for a in dst_addrs]},
        "rules": [{"id": f"cut-{src}-{dst_name}", "fault": "partition",
                   "src": src, "dst": dst_name, "direction": "both"}]})
    assert fi.plane.active


def _heal(cluster, *, version):
    fi.put_plan(cluster.gcs_address, {"version": version, "rules": []})
    assert not fi.plane.active


def _wait(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def _assert_no_leaks(cluster, actor_workers=()):
    """After the workload drains: no worker stuck in 'leased', and the
    GCS ref tables empty once the driver's release flush lands."""
    import gc

    # exception tracebacks (pytest.raises) pin the test frame — and with
    # it every ObjectRef local — in a reference CYCLE that only a full
    # collection breaks; without this the "leak" is the test's own frame
    gc.collect()
    def no_leased():
        for h in cluster.nodes.values():
            if h.raylet is None:
                continue
            for w in h.raylet.workers.workers.values():
                if w.worker_id in actor_workers:
                    continue
                if w.state == "leased":
                    return False
        return True

    _wait(no_leased, 30, "leases to drain")
    _wait(lambda: not cluster.gcs._ref_holders, 30,
          f"object refs to drain (left: "
          f"{list(cluster.gcs._ref_holders)[:5]})")
    _wait(lambda: not cluster.gcs._ref_pin_count, 30,
          "object pins to drain")


@ray_tpu.remote
class Ordered:
    """Records call order — partitions must never reorder or duplicate
    a single caller's actor calls (seq-buffer contract)."""

    def __init__(self):
        self.log = []

    def add(self, i):
        self.log.append(i)
        return i

    def snapshot(self):
        return list(self.log)


@ray_tpu.remote(max_retries=3)
def double(i):
    return i * 2


@ray_tpu.remote(max_retries=3)
def sgd_step(w, x):
    # small dense train step (the chaos workload's "training" leg)
    g = 2.0 * x.T @ (x @ w)
    return w - 0.01 * g


# ----------------------------------------------------------------------
# default-tier smoke: driver <-> GCS control partition mid-workload
# ----------------------------------------------------------------------

def test_driver_gcs_partition_smoke(chaos_cluster):
    c = chaos_cluster

    # -- workload part 1: start everything BEFORE the cut ---------------
    actor = Ordered.remote()
    actor_futs = [actor.add.remote(i) for i in range(10)]
    task_refs = [double.remote(i) for i in range(20)]
    w = np.eye(4)
    x = np.ones((8, 4))
    w_ref = sgd_step.remote(w, x)

    # -- cut the driver's control channels to the GCS -------------------
    _open_partition(c, src="driver", dst_name="gcs",
                    dst_addrs=[c.gcs_address], version=1)
    t_cut = time.monotonic()

    # the data plane (driver->raylet, owner->worker) stays up: keep
    # submitting THROUGH the partition
    actor_futs += [actor.add.remote(i) for i in range(10, 20)]
    task_refs += [double.remote(i) for i in range(20, 40)]
    w_ref = sgd_step.remote(w_ref, x)

    # hold the partition across >= 2 heartbeat timeouts, then heal
    time.sleep(max(0.0, PARTITION_S - (time.monotonic() - t_cut)))
    _heal(c, version=2)

    # -- workload part 2: control plane must be back --------------------
    actor2 = Ordered.remote()          # actor creation needs the GCS
    post_fut = actor2.add.remote(99)
    w_ref = sgd_step.remote(w_ref, x)

    # -- everything completes, in order, with correct values ------------
    assert ray_tpu.get(task_refs, timeout=60) == [i * 2 for i in range(40)]
    assert ray_tpu.get(actor_futs, timeout=60) == list(range(20))
    assert ray_tpu.get(post_fut, timeout=60) == 99
    log = ray_tpu.get(actor.snapshot.remote(), timeout=60)
    assert log == list(range(20)), "actor call order broken by partition"
    final_w = ray_tpu.get(w_ref, timeout=60)
    assert final_w.shape == (4, 4)
    assert np.all(np.isfinite(final_w))

    # the plane actually fired (the partition was real, not a no-op)
    assert any("cut-driver-gcs" in rid for rid in fi.plane.stats), \
        f"partition rule never fired: {fi.plane.stats}"

    # -- zero leaks after heal + drain ----------------------------------
    hosting = {w.worker_id
               for h in c.nodes.values() if h.raylet
               for w in h.raylet.workers.workers.values()
               if getattr(w, "actor_id", None)}
    del task_refs, actor_futs, post_fut, w_ref, final_w, log
    ray_tpu.kill(actor)
    ray_tpu.kill(actor2)
    _assert_no_leaks(c, actor_workers=hosting)


# ----------------------------------------------------------------------
# slow tier: raylet <-> raylet data-plane partition
# ----------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.nightly
def test_raylet_raylet_partition_blocks_then_heals(chaos_cluster):
    c = chaos_cluster
    side = next(h for h in c.nodes.values()
                if h.raylet is not None
                and "side" in h.raylet.total_resources)

    @ray_tpu.remote(max_retries=3, scheduling_strategy=SchedulingStrategy(
        kind="NODE_AFFINITY", node_id=side.node_id))
    def make(i):
        return np.full(1 << 17, i, dtype=np.float64)   # 1 MiB: shm path

    refs = [make.remote(i) for i in range(4)]
    # materialize one to prove the pull path works pre-cut
    assert float(ray_tpu.get(refs[0], timeout=60)[0]) == 0.0
    _wait(lambda: all(  # the rest are sealed remotely before the cut
        side.raylet.store.contains(bytes.fromhex(r.id.hex()))
        for r in refs), 60, "side-node results to seal")

    _open_partition(c, src="raylet", dst_name="side",
                    dst_addrs=[side.raylet.address], version=1)
    # cross-node pull must FAIL while the wire is down (the partition is
    # real): refs[1] lives only on the side node
    with pytest.raises(ray_tpu.exceptions.GetTimeoutError):
        ray_tpu.get(refs[1], timeout=PARTITION_S)
    _heal(c, version=2)

    # ...and succeed after heal, no object lost
    for i, r in enumerate(refs):
        assert float(ray_tpu.get(r, timeout=90)[0]) == float(i)
    del refs, r    # the loop variable is a live ref too
    _assert_no_leaks(c)


# ----------------------------------------------------------------------
# slow tier: owner <-> worker push-plane partition
# ----------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.nightly
def test_worker_owner_partition_tasks_fall_back_and_recover(chaos_cluster):
    c = chaos_cluster
    actor = Ordered.remote()
    pre = [actor.add.remote(i) for i in range(5)]
    assert ray_tpu.get(pre, timeout=60) == list(range(5))

    # sever EVERY owner->worker channel (lease pushes + direct actor
    # submission). Partition = reset + connect-refuse, never a silent
    # black hole: in-flight pushes fail synchronously and the owner
    # falls back to the raylet-mediated path.
    fi.put_plan(c.gcs_address, {
        "version": 2, "seed": 7,
        "rules": [{"id": "cut-owner", "fault": "partition",
                   "src": "owner", "direction": "both"}]})
    mid_tasks = [double.remote(i) for i in range(10)]
    mid_actor = [actor.add.remote(i) for i in range(5, 10)]
    time.sleep(PARTITION_S)
    _heal(c, version=3)

    assert ray_tpu.get(mid_tasks, timeout=90) == [i * 2 for i in range(10)]
    assert ray_tpu.get(mid_actor, timeout=90) == list(range(5, 10))
    post = [actor.add.remote(i) for i in range(10, 15)]
    assert ray_tpu.get(post, timeout=90) == list(range(10, 15))
    # exactly-once, in-order actor delivery across the severed channel
    log = ray_tpu.get(actor.snapshot.remote(), timeout=60)
    assert log == list(range(15))
    del pre, mid_tasks, mid_actor, post, log
    ray_tpu.kill(actor)
    _assert_no_leaks(c)


# ----------------------------------------------------------------------
# idempotency: injected duplicates applied exactly once
# ----------------------------------------------------------------------

def _head_raylet(cluster):
    return cluster.nodes[cluster._head_id].raylet


def test_duplicate_lease_grant_applied_once(chaos_cluster):
    c = chaos_cluster
    ray_tpu.get(double.remote(1), timeout=60)   # warm a worker
    raylet = _head_raylet(c)
    token = "lease-tok-1"
    r1 = raylet.rpc_request_lease(None, None, demand={"CPU": 1},
                                  timeout_s=10, token=token)
    assert r1.get("ok"), r1
    # the retry (same token: the reply was lost, the owner redialled)
    r2 = raylet.rpc_request_lease(None, None, demand={"CPU": 1},
                                  timeout_s=10, token=token)
    assert r2 == r1, "duplicate lease request granted a second worker"
    leased = [w for w in raylet.workers.workers.values()
              if w.state == "leased"]
    assert len(leased) == 1, \
        f"{len(leased)} workers leased for one logical acquisition"
    # replay is NOT blind: once the worker leaves 'leased', the token
    # must re-grant instead of handing out a stale address
    with raylet.workers.lock:
        leased[0].state = "idle"
        leased[0].acquired = None
    raylet.scheduler.release({"CPU": 1})
    r3 = raylet.rpc_request_lease(None, None, demand={"CPU": 1},
                                  timeout_s=10, token=token)
    assert r3.get("ok")
    with raylet.workers.lock:   # hand it back for teardown
        w = raylet.workers.workers.get(r3["worker_id"])
        if w is not None and w.state == "leased":
            w.state = "idle"
            w.acquired = None
    raylet.scheduler.release({"CPU": 1})


def test_duplicate_put_report_applied_once(chaos_cluster):
    raylet = _head_raylet(chaos_cluster)
    applied = []
    orig = raylet.objects.report_object

    def counting(oid, size):
        applied.append(oid)
        return orig(oid, size)

    raylet.objects.report_object = counting
    try:
        entries = [("cd" * 16, 64), ("ef" * 16, 64)]
        r1 = raylet.rpc_report_objects(None, None, entries=entries,
                                       token="put-tok-1")
        # injected duplicate delivery of the SAME batch
        r2 = raylet.rpc_report_objects(None, None, entries=entries,
                                       token="put-tok-1")
        assert r2 == r1
        assert len(applied) == 2, \
            f"duplicate report re-applied pins: {applied}"
        # a different token is a different batch: applies normally
        raylet.rpc_report_objects(None, None, entries=entries,
                                  token="put-tok-2")
        assert len(applied) == 4
    finally:
        raylet.objects.report_object = orig


def test_duplicate_task_push_replays_full_reply(chaos_cluster):
    from ray_tpu.runtime.worker_main import TaskPushServer

    class _StubWorker:
        def __init__(self):
            self._push_conn_lock = threading.Lock()
            self.lease_conns = set()
            self.cancelled_push_ids = set()
            self.push_task_thread = None
            self.current_push_task_id = None
            self.runs = []

        def _execute(self, task):
            self.runs.append(task["task_id"])
            sink = task.get("_direct_sink")
            if sink is not None:
                sink["oid-" + task["task_id"]] = b"direct-result"

    worker = _StubWorker()
    server = TaskPushServer(worker)
    try:
        r1 = server.rpc_push_task(None, None,
                                  task={"task_id": "t1", "name": "t"})
        assert r1["results"] == {"oid-t1": b"direct-result"}
        # duplicate delivery (injected, or owner re-push after a lost
        # reply): must NOT re-execute, must return the SAME results —
        # they ride the reply and exist nowhere else
        r2 = server.rpc_push_task(None, None,
                                  task={"task_id": "t1", "name": "t"})
        assert r2 == r1
        assert worker.runs == ["t1"], f"task re-executed: {worker.runs}"

        b1 = server.rpc_push_tasks(None, None, tasks=[
            {"task_id": "t2"}, {"task_id": "t3"}])
        b2 = server.rpc_push_tasks(None, None, tasks=[
            {"task_id": "t2"}, {"task_id": "t3"}])
        assert b2 == b1
        assert worker.runs == ["t1", "t2", "t3"]
    finally:
        server.stop()


def test_push_reply_cache_is_bounded(chaos_cluster):
    from ray_tpu.runtime.worker_main import TaskPushServer

    class _StubWorker:
        _push_conn_lock = threading.Lock()
        lease_conns = set()
        cancelled_push_ids = set()
        push_task_thread = None
        current_push_task_id = None

        def _execute(self, task):
            sink = task.get("_direct_sink")
            sink["oid-" + task["task_id"]] = b"x" * 1024

    server = TaskPushServer(_StubWorker())
    try:
        for i in range(TaskPushServer.REPLY_CACHE_ENTRIES + 64):
            server.rpc_push_task(None, None, task={"task_id": f"t{i}"})
        assert len(server._push_replies) <= \
            TaskPushServer.REPLY_CACHE_ENTRIES
        assert server._push_reply_bytes <= TaskPushServer.REPLY_CACHE_BYTES
        # evicted oldest, kept newest
        assert server._cached_push_reply("t0") is None
        last = f"t{TaskPushServer.REPLY_CACHE_ENTRIES + 63}"
        assert server._cached_push_reply(last) is not None
    finally:
        server.stop()


def test_duplicate_actor_registration_is_idempotent(chaos_cluster):
    gcs = chaos_cluster.gcs
    # infeasible resources keep the actor PENDING: an empty creation
    # spec would be scheduled, die instantly, and (correctly) free the
    # name — which is not the conflict path under test
    kwargs = dict(actor_id="idem-actor-1", name="idem-name",
                  creation_spec=b"", resources={"__never__": 1},
                  max_restarts=0, namespace="chaos", owner_id=None)
    r1 = gcs.rpc_register_actor(None, None, **kwargs)
    assert r1["ok"]
    # duplicate delivery of the registration: same actor_id acks (it
    # must not reject its OWN name as taken)
    r2 = gcs.rpc_register_actor(None, None, **kwargs)
    assert r2["ok"]
    assert len([a for a in gcs._actors
                if a == "idem-actor-1"]) == 1
    # a DIFFERENT actor wanting the same name still conflicts
    with pytest.raises(ValueError, match="already taken"):
        gcs.rpc_register_actor(None, None, **{
            **kwargs, "actor_id": "idem-actor-2"})


def test_injected_duplicate_lease_rpc_end_to_end(chaos_cluster):
    """Full wire-level check: a duplicate-delivery rule on the raylet's
    request_lease recv path runs the handler twice, and the token keeps
    the second application a replay."""
    from ray_tpu.runtime.rpc import RpcClient

    c = chaos_cluster
    ray_tpu.get(double.remote(1), timeout=60)   # warm a worker
    raylet = _head_raylet(c)
    fi.put_plan(c.gcs_address, {
        "version": 1, "seed": 7,
        "rules": [{"id": "dup-lease", "fault": "duplicate",
                   "src": "raylet", "direction": "recv",
                   "method": "request_lease", "max_hits": 1}]})
    client = RpcClient(raylet.address, label="driver")
    try:
        before = sum(1 for w in raylet.workers.workers.values()
                     if w.state == "leased")
        reply = client.call("request_lease", demand={"CPU": 1},
                            timeout_s=10, token="dup-tok-1", timeout=30)
        assert reply.get("ok"), reply
        assert fi.plane.stats.get("dup-lease") == 1
        after = sum(1 for w in raylet.workers.workers.values()
                    if w.state == "leased")
        assert after - before == 1, \
            "injected duplicate granted a second worker"
    finally:
        client.close()
        _heal(c, version=2)
        with raylet.workers.lock:
            w = raylet.workers.workers.get(reply["worker_id"])
            if w is not None and w.state == "leased":
                w.state = "idle"
                w.acquired = None
        raylet.scheduler.release({"CPU": 1})


def test_duplicate_register_actors_batch_applied_once(chaos_cluster):
    """Round-6 plane: duplicate delivery of a register_actors BATCH
    (the driver's coalescer retries the whole frame after a lost reply)
    acks every entry again without double-creating, and an intra-batch
    name conflict is a per-entry error, not a batch failure."""
    gcs = chaos_cluster.gcs
    batch = [dict(actor_id=f"batch-idem-{i}",
                  name="batch-name" if i == 0 else None,
                  creation_spec=b"", resources={"__never__": 1},
                  max_restarts=0, namespace="chaos", owner_id=None)
             for i in range(4)]
    r1 = gcs.rpc_register_actors(None, None, actors=batch)
    assert all(res["ok"] for res in r1["results"]), r1
    # duplicate delivery of the SAME batch: every entry re-acks
    r2 = gcs.rpc_register_actors(None, None, actors=batch)
    assert all(res["ok"] for res in r2["results"]), r2
    for i in range(4):
        assert len([a for a in gcs._actors
                    if a == f"batch-idem-{i}"]) == 1
    # a DIFFERENT actor claiming a batch-mate's name fails ITS entry
    # only — its batch-mates still register
    r3 = gcs.rpc_register_actors(None, None, actors=[
        {**batch[0], "actor_id": "batch-idem-thief"},
        {**batch[1], "actor_id": "batch-idem-new"}])
    assert not r3["results"][0]["ok"]
    assert "taken" in r3["results"][0]["error"]
    assert r3["results"][1]["ok"]


def test_duplicate_host_actors_batch_is_noop(chaos_cluster):
    """Round-6 plane: the GCS retries a host_actors batch once when the
    shared placement channel dies mid-call — a duplicate for an actor
    already hosted must dedup per entry, never run a second copy."""
    c = chaos_cluster

    @ray_tpu.remote(num_cpus=0)
    class A:
        def who(self):
            return 42

    a = A.remote()
    assert ray_tpu.get(a.who.remote(), timeout=60) == 42
    aid = a._actor_id.hex()
    info = c.gcs._actors[aid]
    raylet = c.nodes[info.node_id].raylet
    try:
        reply = raylet.rpc_host_actors(None, None, actors=[
            {"actor_id": aid, "spec": info.creation_spec,
             "incarnation": info.num_restarts}])
        assert reply["results"][0].get("dedup"), reply
        hosts = [w for w in raylet.workers.workers.values()
                 if w.state == "actor" and w.actor_id == aid]
        assert len(hosts) == 1, \
            f"duplicate host_actors ran {len(hosts)} copies"
        # and the actor still answers (the dup didn't disturb it)
        assert ray_tpu.get(a.who.remote(), timeout=60) == 42
    finally:
        ray_tpu.kill(a)


# ----------------------------------------------------------------------
# round 7: metrics-plane chaos — CH_METRICS faults cost observability
# fidelity only, never task submission / lease grants / serve handling
# ----------------------------------------------------------------------

@pytest.fixture
def metrics_chaos_cluster(monkeypatch):
    import ray_tpu.runtime.metrics_plane as mp
    from ray_tpu.utils.config import reset_config

    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.1")
    reset_config()
    ray_tpu.shutdown()
    fi.plane.clear()
    c = Cluster(heartbeat_timeout_s=HEARTBEAT_S)
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    # Deterministic RPC-path pusher: in an in-process cluster the GCS
    # self-loop (direct ingest, no wire) races the raylet/driver pushers
    # for the process-wide claim. Hand the role to a test-owned pusher
    # so the injected CH_METRICS faults provably cross the RPC boundary.
    mp._claimed = None
    pusher = mp.MetricsPusher(c.gcs_address, src="chaos-test",
                              kind="driver", interval_s=0.1).start()
    assert pusher._thread is not None, "test pusher failed to claim"
    yield c, pusher
    pusher.stop()
    fi.plane.clear()
    ray_tpu.shutdown()
    fi.stop_kv_watcher()
    c.shutdown()
    fi.plane.clear()
    reset_config()


async def _ok_app(scope, receive, send):
    await send({"type": "http.response.start", "status": 200,
                "headers": []})
    await send({"type": "http.response.body", "body": b"ok"})


def test_metrics_frame_chaos_never_blocks_work(metrics_chaos_cluster):
    """Dropped, duplicated, AND delayed push_metrics frames while tasks,
    lease grants, and serve ingress handling run at full speed."""
    from ray_tpu.serve.ingress import _ASGIDriver

    c, pusher = metrics_chaos_cluster
    assert ray_tpu.get(double.remote(1), timeout=60) == 2
    asgi = _ASGIDriver(_ok_app)
    assert asgi.handle({"method": "GET", "path": "/"})["status"] == 200

    fi.put_plan(c.gcs_address, {
        "version": 1, "seed": 7,
        "rules": [
            {"id": "delay-metrics", "fault": "delay", "src": "gcs",
             "direction": "recv", "method": "push_metrics",
             "delay_s": 0.2, "max_hits": 4},
            {"id": "dup-metrics", "fault": "duplicate", "src": "gcs",
             "direction": "recv", "method": "push_metrics",
             "every": 3, "max_hits": 2},
            {"id": "drop-metrics", "fault": "drop", "src": "gcs",
             "direction": "recv", "method": "push_metrics",
             "every": 2, "max_hits": 2},
        ]})

    # keep the workload flowing until every fault class has fired; each
    # leg stays fast THROUGHOUT (instrumentation is registry-local — a
    # faulted push frame can only stall the pusher thread)
    rule_ids = ("delay-metrics", "dup-metrics", "drop-metrics")
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        assert ray_tpu.get([double.remote(i) for i in range(10)],
                           timeout=60) == [i * 2 for i in range(10)]
        t0 = time.monotonic()
        assert asgi.handle({"method": "GET", "path": "/"})["status"] == 200
        # < the 2s metrics RPC timeout: serve handling provably never
        # waited on the faulted metrics wire
        assert time.monotonic() - t0 < 1.0, \
            "serve ingress handling slowed by metrics faults"
        if all(fi.plane.stats.get(r) for r in rule_ids):
            break
        time.sleep(0.1)
    assert all(fi.plane.stats.get(r) for r in rule_ids), \
        f"metrics faults never fired: {fi.plane.stats}"

    # a direct lease grant under the (possibly mid-drop-timeout) plane
    raylet = _head_raylet(c)
    t0 = time.monotonic()
    r = raylet.rpc_request_lease(None, None, demand={"CPU": 1},
                                 timeout_s=5, token="metrics-chaos-lease")
    assert r.get("ok"), r
    assert time.monotonic() - t0 < 2.0, \
        "lease grant slowed by metrics faults"
    with raylet.workers.lock:
        w = raylet.workers.workers.get(r["worker_id"])
        if w is not None and w.state == "leased":
            w.state = "idle"
            w.acquired = None
    raylet.scheduler.release({"CPU": 1})

    _heal(c, version=2)
    # the plane keeps flowing after the chaos (drops cost fidelity only)
    pushed = pusher.pushed
    _wait(lambda: pusher.pushed > pushed, 30,
          "metrics pushes to resume after frame chaos")


def test_metrics_partitioned_gcs_work_unaffected_then_resumes(
        metrics_chaos_cluster):
    """A full partition of the metrics channel to the GCS: submission,
    actor calls, and queries stay up; pushes stall silently and resume
    on heal."""
    from ray_tpu.util import state as state_api

    c, pusher = metrics_chaos_cluster
    assert ray_tpu.get(double.remote(1), timeout=60) == 2
    _wait(lambda: pusher.pushed > 0, 30, "first metrics frames")

    fi.put_plan(c.gcs_address, {
        "version": 1, "seed": 7,
        "endpoints": {"gcs": [_addr(c.gcs_address)]},
        "rules": [{"id": "cut-metrics-gcs", "fault": "partition",
                   "src": "metrics", "dst": "gcs", "direction": "both"}]})
    t_cut = time.monotonic()

    # the whole work surface rides THROUGH the severed metrics channel
    actor = Ordered.remote()
    assert ray_tpu.get([double.remote(i) for i in range(30)],
                       timeout=60) == [i * 2 for i in range(30)]
    assert ray_tpu.get([actor.add.remote(i) for i in range(10)],
                       timeout=60) == list(range(10))
    # ...and the query path (driver-labeled, not partitioned) answers
    assert isinstance(state_api.cluster_metrics().get("names"), dict)

    # the partition is real: the pusher's channel was actually cut
    _wait(lambda: fi.plane.stats.get("cut-metrics-gcs"), 30,
          "metrics partition to fire")
    time.sleep(max(0.0, PARTITION_S - (time.monotonic() - t_cut)))

    pushed_during = pusher.pushed
    _heal(c, version=2)
    # pushes resume (heartbeat handler timers keep generating deltas)
    _wait(lambda: pusher.pushed > pushed_during, 30,
          "metrics pushes to resume after heal")
    ray_tpu.kill(actor)


def test_trace_span_chaos_never_blocks_work(metrics_chaos_cluster):
    """Round 9: dropped, duplicated, AND delayed push_spans frames while
    traced tasks and actor calls run at full speed — trace collection is
    fire-and-forget on the pusher thread, so span-frame faults cost
    trace fidelity only, never submission latency."""
    from ray_tpu.util import tracing

    c, pusher = metrics_chaos_cluster
    tracing.enable_tracing()
    try:
        assert ray_tpu.get(double.remote(1), timeout=60) == 2
        fi.put_plan(c.gcs_address, {
            "version": 1, "seed": 7,
            "rules": [
                {"id": "delay-spans", "fault": "delay", "src": "gcs",
                 "direction": "recv", "method": "push_spans",
                 "delay_s": 0.2, "max_hits": 4},
                {"id": "dup-spans", "fault": "duplicate", "src": "gcs",
                 "direction": "recv", "method": "push_spans",
                 "every": 3, "max_hits": 2},
                {"id": "drop-spans", "fault": "drop", "src": "gcs",
                 "direction": "recv", "method": "push_spans",
                 "every": 2, "max_hits": 2},
            ]})

        rule_ids = ("delay-spans", "dup-spans", "drop-spans")
        actor = Ordered.remote()
        sent = 0
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            # traced workload: every round generates spans for the
            # pusher to ship into the faulted wire
            with tracing.span(f"chaos-round-{sent}"):
                t0 = time.monotonic()
                assert ray_tpu.get([double.remote(i) for i in range(10)],
                                   timeout=60) == [i * 2
                                                   for i in range(10)]
                assert ray_tpu.get(actor.add.remote(sent),
                                   timeout=60) == sent
                # well under the 2s span-push RPC timeout: submission
                # provably never waited on the faulted span wire
                assert time.monotonic() - t0 < 2.0, \
                    "traced submission slowed by span-frame faults"
            sent += 1
            if all(fi.plane.stats.get(r) for r in rule_ids):
                break
            time.sleep(0.1)
        assert all(fi.plane.stats.get(r) for r in rule_ids), \
            f"span faults never fired: {fi.plane.stats}"

        _heal(c, version=2)
        # span pushes keep flowing after the chaos
        shipped = pusher.pushed_spans
        with tracing.span("post-heal"):
            pass
        _wait(lambda: pusher.pushed_spans > shipped, 30,
              "span pushes to resume after frame chaos")
        ray_tpu.kill(actor)
    finally:
        tracing.disable_tracing()


def test_trace_partitioned_gcs_flight_recorder_still_answers(
        metrics_chaos_cluster):
    """A full partition of the metrics/trace channel to the GCS: traced
    work keeps completing, the LOCAL flight recorder still dumps (pure
    process memory — the acceptance 'works while GCS unreachable'), and
    span pushes resume on heal."""
    from ray_tpu.util import state as state_api
    from ray_tpu.util import tracing

    c, pusher = metrics_chaos_cluster
    tracing.enable_tracing()
    try:
        with tracing.span("pre-cut"):
            assert ray_tpu.get(double.remote(1), timeout=60) == 2
        _wait(lambda: pusher.pushed_spans > 0, 30, "first span push")

        fi.put_plan(c.gcs_address, {
            "version": 1, "seed": 7,
            "endpoints": {"gcs": [_addr(c.gcs_address)]},
            "rules": [{"id": "cut-trace-gcs", "fault": "partition",
                       "src": "metrics", "dst": "gcs",
                       "direction": "both"}]})
        t_cut = time.monotonic()

        # traced submission rides THROUGH the severed span channel
        with tracing.span("during-cut") as cut_span:
            assert ray_tpu.get([double.remote(i) for i in range(20)],
                               timeout=60) == [i * 2 for i in range(20)]
        # the flight recorder answers from local memory mid-partition
        out = state_api.flight_record()
        assert any(s["name"] == "during-cut"
                   for s in out["local"]["spans"])
        assert any(s["trace_id"] == cut_span.trace_id
                   for s in out["local"]["spans"])
        # ...and the local stuck-call registry stays queryable too
        assert isinstance(tracing.local_stuck_calls(0.0), list)

        _wait(lambda: fi.plane.stats.get("cut-trace-gcs"), 30,
              "trace partition to fire")
        time.sleep(max(0.0, PARTITION_S - (time.monotonic() - t_cut)))

        shipped = pusher.pushed_spans
        _heal(c, version=2)
        with tracing.span("post-heal"):
            pass
        _wait(lambda: pusher.pushed_spans > shipped, 30,
              "span pushes to resume after heal")
    finally:
        tracing.disable_tracing()


def test_dropped_register_actors_retried_without_orphan(chaos_cluster):
    """Round-6 plane: a register_actors frame dropped on the GCS recv
    path leaves NO partial state (no orphan registration), and the
    caller's retry registers exactly once."""
    from ray_tpu.runtime.rpc import RpcClient

    c = chaos_cluster
    fi.put_plan(c.gcs_address, {
        "version": 1, "seed": 7,
        "rules": [{"id": "drop-reg", "fault": "drop",
                   "src": "gcs", "direction": "recv",
                   "method": "register_actors", "max_hits": 1}]})
    batch = [dict(actor_id="dropped-actor-1", name=None,
                  creation_spec=b"", resources={"__never__": 1},
                  max_restarts=0, namespace="chaos", owner_id=None)]
    client = RpcClient(c.gcs_address, label="driver")
    try:
        with pytest.raises(TimeoutError):
            client.call("register_actors", actors=batch, timeout=2)
    finally:
        client.close()   # pipelined stream is desynced after a timeout
    assert fi.plane.stats.get("drop-reg") == 1
    assert "dropped-actor-1" not in c.gcs._actors, \
        "dropped frame left an orphan registration"
    _heal(c, version=2)
    retry = RpcClient(c.gcs_address, label="driver")
    try:
        reply = retry.call("register_actors", actors=batch, timeout=30)
        assert reply["results"][0]["ok"], reply
    finally:
        retry.close()
    assert len([a for a in c.gcs._actors
                if a == "dropped-actor-1"]) == 1


# ----------------------------------------------------------------------
# round 8: serve autoscaler vs a partitioned metrics plane — the
# metrics-driven policy must degrade to the polled loop (scaling and
# serving continue) and return to pushed metrics on heal
# ----------------------------------------------------------------------

@pytest.fixture
def serve_chaos_cluster(monkeypatch):
    import ray_tpu.runtime.metrics_plane as mp
    from ray_tpu import serve
    from ray_tpu.utils.config import reset_config

    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.1")
    # small aggregation windows: pre-partition gauge data must age out
    # of the autoscaler's query horizon within a couple of seconds
    monkeypatch.setenv("RAY_TPU_METRICS_WINDOW_S", "0.5")
    # replica gauges push from WORKER subprocesses: those processes must
    # watch the KV plan key themselves or the partition never reaches
    # their pusher connections (the in-process plane only covers the
    # driver/GCS/raylet threads)
    monkeypatch.setenv("RAY_TPU_FAULT_INJECTION_ENABLED", "1")
    reset_config()
    ray_tpu.shutdown()
    fi.plane.clear()
    c = Cluster(heartbeat_timeout_s=HEARTBEAT_S)
    c.add_node(num_cpus=4)
    ray_tpu.init(address=c.gcs_address)
    # deterministic RPC-path pusher (see metrics_chaos_cluster): the
    # injected partition must provably cross the RPC boundary
    mp._claimed = None
    pusher = mp.MetricsPusher(c.gcs_address, src="serve-chaos",
                              kind="driver", interval_s=0.1).start()
    assert pusher._thread is not None, "test pusher failed to claim"
    yield c, pusher
    serve.shutdown()
    pusher.stop()
    fi.plane.clear()
    ray_tpu.shutdown()
    fi.stop_kv_watcher()
    c.shutdown()
    fi.plane.clear()
    reset_config()


def test_metrics_partition_degrades_autoscaler_then_heals(
        serve_chaos_cluster):
    """Partition the metrics plane from the GCS mid-load: the
    autoscaler flips from the pushed-metrics policy to the polled
    per-replica loop (scale is held, no request is dropped), and flips
    back once the plane heals and frames flow again."""
    from ray_tpu import serve

    c, pusher = serve_chaos_cluster

    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0, "upscale_delay_s": 0.2,
        "downscale_delay_s": 120.0, "metrics_window_s": 1.5})
    class Slow:
        def __call__(self, delay):
            time.sleep(delay)
            return "ok"

    handle = serve.run(Slow.bind(), name="chaos_auto")

    stop = threading.Event()
    failures: list = []
    rounds = [0]

    def load():
        while not stop.is_set():
            try:
                refs = [handle.remote(0.3) for _ in range(4)]
                for r in refs:
                    ray_tpu.get(r, timeout=30)
            except Exception as e:  # noqa: BLE001 - any drop fails the test
                failures.append(repr(e))
                return
            rounds[0] += 1

    th = threading.Thread(target=load, daemon=True)
    th.start()
    try:
        def dep():
            return serve.status()["deployments"].get("chaos_auto", {})

        _wait(lambda: dep().get("running", 0) >= 2
              and dep().get("autoscale_mode") == "metrics",
              30, "metrics-mode upscale under load")

        fi.put_plan(c.gcs_address, {
            "version": 1, "seed": 7,
            "endpoints": {"gcs": [_addr(c.gcs_address)]},
            "rules": [{"id": "cut-metrics-gcs", "fault": "partition",
                       "src": "metrics", "dst": "gcs",
                       "direction": "both"}]})
        _wait(lambda: fi.plane.stats.get("cut-metrics-gcs"), 30,
              "metrics partition to fire")

        # pushed windows go stale -> the policy degrades to polled;
        # replicas stay up and serving never blocks
        _wait(lambda: dep().get("autoscale_mode") == "polled", 30,
              "autoscaler degradation to polled")
        assert not failures, failures
        assert dep().get("running", 0) >= 2, \
            "polled policy should hold the scale-up under load"
        assert handle.call(0.05) == "ok", \
            "serving must not block during the metrics partition"
        before = rounds[0]
        _wait(lambda: rounds[0] > before, 30,
              "load to keep flowing under the partition")

        pushed_during = pusher.pushed
        _heal(c, version=2)
        _wait(lambda: pusher.pushed > pushed_during, 30,
              "metrics pushes to resume after heal")
        _wait(lambda: dep().get("autoscale_mode") == "metrics", 30,
              "autoscaler back on pushed metrics after heal")
        assert not failures, failures
    finally:
        stop.set()
        th.join(timeout=60)
    assert not failures, failures


def test_train_telemetry_partition_never_blocks_steps(
        metrics_chaos_cluster):
    """Round 9: a metrics<->GCS partition during training costs
    telemetry fidelity only — step stamping stays registry-local and
    fast (frames drop on the pusher thread, steps never wait), the
    train.* series resume on heal, and train_goodput keeps answering
    from the surviving progress annexes."""
    from ray_tpu.train.telemetry import StepTelemetry
    from ray_tpu.util import state as state_api

    c, pusher = metrics_chaos_cluster
    t = StepTelemetry("chaos-train", 0)
    for _ in range(3):
        with t.timeit("compute"):
            pass
        t.on_report({})
    _wait(lambda: pusher.pushed > 0, 30, "first metrics frames")

    fi.put_plan(c.gcs_address, {
        "version": 1, "seed": 7,
        "endpoints": {"gcs": [_addr(c.gcs_address)]},
        "rules": [{"id": "cut-metrics-gcs", "fault": "partition",
                   "src": "metrics", "dst": "gcs", "direction": "both"}]})
    t_cut = time.monotonic()

    # train THROUGH the severed metrics channel: every stamp must stay
    # far under the 2s metrics RPC timeout (telemetry drops, not blocks)
    steps_during = 0
    while time.monotonic() - t_cut < PARTITION_S:
        with t.timeit("compute"):
            pass
        t0 = time.monotonic()
        t.on_report({})
        assert time.monotonic() - t0 < 0.5, \
            "step stamping waited on the partitioned metrics wire"
        steps_during += 1
        time.sleep(0.02)
    assert steps_during > 10
    _wait(lambda: fi.plane.stats.get("cut-metrics-gcs"), 30,
          "metrics partition to fire")

    # goodput still answers mid-partition (driver-local annexes survive)
    g = state_api.train_goodput("chaos-train")
    assert g["buckets"]["productive"] > 0, g

    pushed_during = pusher.pushed
    _heal(c, version=2)
    # series flow again after heal: new steps land fresh observations
    _wait(lambda: pusher.pushed > pushed_during, 30,
          "metrics pushes to resume after heal")
    for _ in range(2):
        with t.timeit("compute"):
            pass
        t.on_report({})
    t.close()

    def step_series_groups():
        q = state_api.cluster_metrics("train.step_s",
                                      tags={"run": "chaos-train"},
                                      group_by=["rank"])
        return q.get("groups") or []

    _wait(lambda: len(step_series_groups()) > 0, 30,
          "train.step_s series to land in the GCS store after heal")
    g = state_api.train_goodput("chaos-train")
    assert g["buckets"]["productive"] > 0
    assert g["goodput_fraction"] is not None


# ----------------------------------------------------------------------
# round 10: log-plane chaos — push_logs frames dropped, duplicated,
# delayed, or fully partitioned cost log fidelity only, never task
# throughput; the driver echo resumes after heal
# ----------------------------------------------------------------------

@ray_tpu.remote(max_retries=3)
def shout(tag):
    print(f"chaos-shout-{tag}")
    return tag


def test_log_push_chaos_never_blocks_tasks(metrics_chaos_cluster, capsys):
    """Dropped, duplicated, AND delayed push_logs frames while printing
    tasks run at full speed. Duplicated frames must neither double-store
    nor double-echo (LogStore (file, offset) watermark)."""
    from ray_tpu.util import state as state_api

    c, _pusher = metrics_chaos_cluster
    assert ray_tpu.get(shout.remote("warmup"), timeout=60) == "warmup"

    fi.put_plan(c.gcs_address, {
        "version": 1, "seed": 7,
        "rules": [
            {"id": "delay-logs", "fault": "delay", "src": "gcs",
             "direction": "recv", "method": "push_logs",
             "delay_s": 0.2, "max_hits": 4},
            {"id": "dup-logs", "fault": "duplicate", "src": "gcs",
             "direction": "recv", "method": "push_logs",
             "every": 2, "max_hits": 4},
            {"id": "drop-logs", "fault": "drop", "src": "gcs",
             "direction": "recv", "method": "push_logs",
             "every": 3, "max_hits": 2},
        ]})

    rule_ids = ("delay-logs", "dup-logs", "drop-logs")
    deadline = time.monotonic() + 90
    batch = 0
    while time.monotonic() < deadline:
        tags = [f"b{batch}-{i}" for i in range(8)]
        t0 = time.monotonic()
        assert ray_tpu.get([shout.remote(t) for t in tags],
                           timeout=60) == tags
        # << the 2s log-push RPC timeout: execution provably never
        # waited on the faulted log wire (capture is local os.write;
        # shipping is the raylet monitor's thread)
        assert time.monotonic() - t0 < 5.0, \
            "printing tasks slowed by log-push faults"
        batch += 1
        if all(fi.plane.stats.get(r) for r in rule_ids):
            break
        time.sleep(0.1)
    assert all(fi.plane.stats.get(r) for r in rule_ids), \
        f"log-push faults never fired: {fi.plane.stats}"

    _heal(c, version=2)
    # the duplicated frames were re-ingested and caught by the offset
    # watermark — so they never re-published, i.e. never double-echoed
    _wait(lambda: (state_api.list_logs().get("deduped") or 0) > 0, 30,
          "the duplicated push_logs frames to hit the dedup watermark")
    # spot-check the echo stream: no sentinel line printed twice
    seen = ""
    t_end = time.monotonic() + 5
    while time.monotonic() < t_end:
        cap = capsys.readouterr()
        seen += cap.out + cap.err
        time.sleep(0.2)
    for ln in set(l for l in seen.splitlines() if "chaos-shout-b" in l):
        assert seen.count(ln) == 1, f"double-echoed line: {ln!r}"


def test_log_partition_tasks_flow_echo_resumes(metrics_chaos_cluster,
                                               capsys):
    """A full partition of the metrics/log channel to the GCS: printing
    tasks keep executing at full speed and log QUERIES keep answering;
    after heal, fresh lines reach the store and the driver echo again."""
    from ray_tpu.util import state as state_api

    c, _pusher = metrics_chaos_cluster
    assert ray_tpu.get(shout.remote("pre-cut"), timeout=60) == "pre-cut"
    _wait(lambda: (state_api.list_logs().get("ingested") or 0) > 0, 30,
          "first log lines to reach the store")

    fi.put_plan(c.gcs_address, {
        "version": 1, "seed": 7,
        "endpoints": {"gcs": [_addr(c.gcs_address)]},
        "rules": [{"id": "cut-logs-gcs", "fault": "partition",
                   "src": "metrics", "dst": "gcs", "direction": "both"}]})
    t_cut = time.monotonic()

    # the whole printing workload rides THROUGH the severed log channel
    while time.monotonic() - t_cut < PARTITION_S:
        tags = [f"cut-{i}" for i in range(6)]
        t0 = time.monotonic()
        assert ray_tpu.get([shout.remote(t) for t in tags],
                           timeout=60) == tags
        assert time.monotonic() - t0 < 5.0, \
            "printing tasks waited on the partitioned log wire"
        time.sleep(0.05)
    # ...and the query path (driver-labeled, not partitioned) answers
    assert isinstance(state_api.list_logs().get("procs"), dict)
    _wait(lambda: fi.plane.stats.get("cut-logs-gcs"), 30,
          "log partition to fire")

    ingested_during = state_api.list_logs().get("ingested") or 0
    _heal(c, version=2)
    capsys.readouterr()     # drop pre-heal echo noise
    assert ray_tpu.get(shout.remote("post-heal-xyzzy"),
                       timeout=60) == "post-heal-xyzzy"
    # shipping resumes: the post-heal line lands in the store...
    _wait(lambda: (state_api.list_logs().get("ingested") or 0)
          > ingested_during, 30, "log ingest to resume after heal")
    # ...and the driver echo stream comes back with it
    deadline = time.monotonic() + 25
    seen = ""
    while time.monotonic() < deadline:
        cap = capsys.readouterr()
        seen += cap.out + cap.err
        if "chaos-shout-post-heal-xyzzy" in seen:
            break
        time.sleep(0.2)
    assert "chaos-shout-post-heal-xyzzy" in seen, \
        f"echo never resumed after heal; saw:\n{seen[-2000:]}"


# ----------------------------------------------------------------------
# round 11: memory-plane chaos — mem/owners + mem/node annex frames
# ride push_metrics; faults on that wire cost accounting freshness
# only, never puts, spills, or the debugging surface's availability
# ----------------------------------------------------------------------

def test_mem_annex_frame_chaos_never_blocks_puts_and_spills(
        metrics_chaos_cluster):
    """Dropped, duplicated, AND delayed annex-carrying metrics frames:
    puts stay fast, a forced make-room spill completes, and after heal
    the ownership annexes are fresh with no dup-frame double count."""
    from ray_tpu.runtime import core as _core
    from ray_tpu.util import state as state_api

    c, _pusher = metrics_chaos_cluster
    driver_id = _core.get_runtime().client_id
    fi.put_plan(c.gcs_address, {
        "version": 1, "seed": 7,
        "rules": [
            {"id": "delay-mem-annex", "fault": "delay", "src": "gcs",
             "direction": "recv", "method": "push_metrics",
             "delay_s": 0.2, "max_hits": 4},
            {"id": "dup-mem-annex", "fault": "duplicate", "src": "gcs",
             "direction": "recv", "method": "push_metrics",
             "every": 3, "max_hits": 2},
            {"id": "drop-mem-annex", "fault": "drop", "src": "gcs",
             "direction": "recv", "method": "push_metrics",
             "every": 2, "max_hits": 2},
        ]})

    refs = []
    rule_ids = ("delay-mem-annex", "dup-mem-annex", "drop-mem-annex")
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        t0 = time.monotonic()
        refs.extend(ray_tpu.put(b"a" * (64 << 10)) for _ in range(4))
        # puts never wait on the faulted metrics wire (accounting is a
        # lock-free in-process store; the annex ships off-thread)
        assert time.monotonic() - t0 < 2.0, \
            "puts slowed by metrics-frame faults"
        if all(fi.plane.stats.get(r) for r in rule_ids):
            break
        time.sleep(0.1)
    assert all(fi.plane.stats.get(r) for r in rule_ids), \
        f"annex frame faults never fired: {fi.plane.stats}"

    # a forced make-room spill completes under the faulted plane (the
    # pressure path never touches the metrics channel)
    raylet = _head_raylet(c)
    t0 = time.monotonic()
    raylet.objects.spill_bytes(64 << 10)
    assert time.monotonic() - t0 < 5.0, \
        "make-room spill waited on the faulted metrics wire"
    assert ray_tpu.get(refs[0], timeout=60) == b"a" * (64 << 10)

    _heal(c, version=2)

    # annexes heal: the summary converges on the LIVE ownership table —
    # duplicated frames cannot double-count (annexes are last-write-
    # wins by key, not accumulated)
    n_refs = len(refs)

    def fresh():
        s = state_api.memory_summary(top_n=5)
        mine = [o for o in s["owners"] if o["owner"] == driver_id]
        return mine[0] if s["mode"] == "cluster" and mine else None

    _wait(lambda: (m := fresh()) is not None and m["owned"] >= n_refs,
          40, "ownership annex to refresh after heal")
    mine = fresh()
    assert mine is not None and mine["owned"] <= n_refs + 8, \
        f"dup annex frames double-counted ownership: {mine['owned']} " \
        f"owned vs {n_refs} live refs"
    del refs


def test_memory_summary_degrades_mid_partition_and_heals(chaos_cluster):
    """A full driver<->GCS partition: memory_summary() answers from the
    local annex registry (marked degraded) in bounded time instead of
    hanging, then heals back to cluster mode."""
    from ray_tpu.runtime import core as _core
    from ray_tpu.util import state as state_api

    c = chaos_cluster
    driver_id = _core.get_runtime().client_id
    refs = [ray_tpu.put(b"d" * (32 << 10)) for _ in range(4)]

    def cluster_mode():
        s = state_api.memory_summary(top_n=5)
        return s if s["mode"] == "cluster" and any(
            o["owner"] == driver_id for o in s["owners"]) else None

    _wait(cluster_mode, 40, "cluster-mode summary before the cut")

    _open_partition(c, src="driver", dst_name="gcs",
                    dst_addrs=[c.gcs_address], version=1)
    t_cut = time.monotonic()
    try:
        t0 = time.monotonic()
        s = state_api.memory_summary(top_n=5)
        wall = time.monotonic() - t0
        # bounded and NEVER an exception: the surface degrades
        assert wall < 20.0, f"degraded answer took {wall:.1f}s"
        assert s["mode"] == "degraded", s["mode"]
        assert s.get("degraded"), "degraded answer must carry the cause"
        # the local answer still knows this process's OWN objects
        mine = [o for o in s["owners"] if o.get("owner") == driver_id]
        assert mine and mine[0]["owned"] >= 4, \
            f"local-process fallback lost owned entries: {s['owners']}"
        time.sleep(max(0.0, PARTITION_S - (time.monotonic() - t_cut)))
        assert fi.plane.stats.get("cut-driver-gcs"), \
            f"partition never fired: {fi.plane.stats}"
    finally:
        _heal(c, version=2)

    # heals: back to the GCS-joined cluster answer
    _wait(cluster_mode, 40, "summary to heal back to cluster mode")
    del refs
