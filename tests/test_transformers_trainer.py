"""TransformersTrainer: HF Trainer on rank workers (reference:
train/huggingface/transformers/transformers_trainer.py)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import ray_tpu
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train import TransformersTrainer


class _TinyDataset(torch.utils.data.Dataset):
    """32 samples of a learnable binary rule."""

    def __init__(self):
        rng = np.random.default_rng(0)
        self.x = rng.normal(size=(32, 8)).astype(np.float32)
        self.y = (self.x[:, 0] > 0).astype(np.int64)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return {"x": self.x[i], "labels": self.y[i]}


class _TinyModel(transformers.PreTrainedModel):
    config_class = transformers.PretrainedConfig

    def __init__(self, config):
        super().__init__(config)
        self.net = torch.nn.Sequential(
            torch.nn.Linear(8, 16), torch.nn.Tanh(), torch.nn.Linear(16, 2))

    def forward(self, x=None, labels=None):
        logits = self.net(x)
        loss = None
        if labels is not None:
            loss = torch.nn.functional.cross_entropy(logits, labels)
        return {"loss": loss, "logits": logits}


def trainer_init(config):
    import tempfile

    model = _TinyModel(transformers.PretrainedConfig())
    args = transformers.TrainingArguments(
        output_dir=tempfile.mkdtemp(prefix="hf_out_"),
        max_steps=8, per_device_train_batch_size=8,
        logging_steps=4, report_to=[], use_cpu=True,
        save_strategy="no", disable_tqdm=True,
    )
    return transformers.Trainer(model=model, args=args,
                                train_dataset=_TinyDataset())


def test_transformers_trainer_single_worker():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    result = TransformersTrainer(
        trainer_init,
        scaling_config=ScalingConfig(num_workers=1),
    ).fit()
    assert result.metrics["global_step"] == 8
    assert np.isfinite(result.metrics["training_loss"])
    ray_tpu.shutdown()


def test_accelerate_trainer_single_worker():
    pytest.importorskip("accelerate")
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train import AccelerateTrainer, session

    def loop(config):
        import torch
        from accelerate import Accelerator

        acc = Accelerator(cpu=True)
        model = torch.nn.Linear(4, 1)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        model, opt = acc.prepare(model, opt)
        x = torch.randn(64, 4)
        y = x.sum(dim=1, keepdim=True)
        for _ in range(20):
            loss = torch.nn.functional.mse_loss(model(x), y)
            acc.backward(loss)
            opt.step()
            opt.zero_grad()
        session.report({"loss": float(loss.detach())})

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    result = AccelerateTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1)).fit()
    assert result.metrics["loss"] < 2.0
    ray_tpu.shutdown()


def test_accelerate_trainer_two_workers_ddp():
    """accelerate must SEE the distribution (env vars) — prepare() DDP-wraps
    and num_processes == world size (regression: unset RANK/WORLD_SIZE made
    every rank train the full data independently)."""
    pytest.importorskip("accelerate")
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import AccelerateTrainer

    def loop(config):
        import torch
        from accelerate import Accelerator

        from ray_tpu.train import session as sess

        acc = Accelerator(cpu=True)
        model = torch.nn.Linear(2, 1)
        model = acc.prepare(model)
        sess.report({
            "num_processes": int(acc.num_processes),
            "ddp_wrapped": int(isinstance(
                model, torch.nn.parallel.DistributedDataParallel)),
        })

    ray_tpu.shutdown()
    cluster = Cluster()
    cluster.add_node(num_cpus=4)
    ray_tpu.init(address=cluster.gcs_address)
    try:
        result = AccelerateTrainer(
            loop, scaling_config=ScalingConfig(num_workers=2)).fit()
        assert result.error is None, result.error
        assert result.metrics["num_processes"] == 2, result.metrics
        assert result.metrics["ddp_wrapped"] == 1, result.metrics
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
