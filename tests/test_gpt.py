"""GPT-2 family tests: numerics, sharded training through JaxTrainer.

Reference analog: the reference exercises GPT-class models through its
Train integrations; here the family is in-framework
(``ray_tpu/models/gpt.py``) and must train under the same sharding
presets as Llama.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt


def test_gpt_forward_shapes_and_dtype():
    cfg = gpt.gpt_tiny(vocab_size=128)
    params = gpt.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 128)
    logits = gpt.forward(cfg, params, tokens)
    assert logits.shape == (2, 16, 128)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_gpt_param_axes_mirror_params():
    cfg = gpt.gpt_tiny()
    params = gpt.init_params(cfg, jax.random.key(0))
    axes = gpt.param_logical_axes(cfg)
    assert jax.tree.structure(
        jax.tree.map(lambda _: 0, params)) == jax.tree.structure(
        jax.tree.map(lambda _: 0, axes,
                     is_leaf=lambda x: isinstance(x, tuple) or x is None))


def test_gpt_causal_masking():
    """Perturbing a future token must not change earlier logits."""
    cfg = gpt.gpt_tiny(vocab_size=64)
    params = gpt.init_params(cfg, jax.random.key(0))
    t1 = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], dtype=jnp.int32)
    t2 = t1.at[0, -1].set(63)
    l1 = np.asarray(gpt.forward(cfg, params, t1))
    l2 = np.asarray(gpt.forward(cfg, params, t2))
    np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=1e-4)
    assert not np.allclose(l1[0, -1], l2[0, -1], atol=1e-4)


def test_gpt_position_embedding_matters():
    cfg = gpt.gpt_tiny(vocab_size=64)
    params = gpt.init_params(cfg, jax.random.key(0))
    tok = jnp.array([[5, 5, 5, 5]], dtype=jnp.int32)
    logits = np.asarray(gpt.forward(cfg, params, tok))
    # identical tokens at different positions -> different logits
    assert not np.allclose(logits[0, 0], logits[0, 1], atol=1e-4)


@pytest.mark.parametrize("strategy", ["fsdp", "fsdp_tp"])
def test_gpt_trains_sharded(strategy):
    from ray_tpu.parallel.mesh import create_mesh
    from ray_tpu.train.trainer import JaxTrainer, TrainConfig

    cfg = gpt.gpt_tiny(vocab_size=128)
    mesh = create_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    trainer = JaxTrainer(cfg, TrainConfig(strategy=strategy,
                                          learning_rate=1e-3,
                                          warmup_steps=2,
                                          total_steps=20),
                         mesh=mesh)
    state = trainer.init_state(jax.random.key(0))
    batch = jax.random.randint(jax.random.key(1), (4, 17), 0, 128)
    losses = []
    for _ in range(8):
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    # memorizing one small batch must drive the loss down
    assert losses[-1] < losses[0] - 0.1


def test_gpt_rejects_llama_only_paths():
    from ray_tpu.parallel.mesh import create_mesh
    from ray_tpu.train.trainer import JaxTrainer, TrainConfig

    cfg = gpt.gpt_tiny()
    mesh = create_mesh({"dp": 8})
    # guard fires at construction, before any sharded state is built
    with pytest.raises(ValueError, match="llama-only"):
        JaxTrainer(cfg, TrainConfig(strategy="dp", fused_loss=True),
                   mesh=mesh)


def test_gpt_rejects_overlong_sequence():
    cfg = gpt.gpt_tiny(vocab_size=64)  # max_seq_len=128
    params = gpt.init_params(cfg, jax.random.key(0))
    tokens = jnp.zeros((1, 200), dtype=jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        gpt.forward(cfg, params, tokens)
