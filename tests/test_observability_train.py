"""Training telemetry plane: step decomposition, MFU/goodput
accounting, straggler detection, and cluster-wide on-demand profiling
(reference: Ray Train's run-state tracking + the dashboard reporter
agent's py-spy profiling, ``dashboard/modules/reporter/``)."""

import json
import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import train as rtrain
from ray_tpu.air.config import FailureConfig, RunConfig, ScalingConfig
from ray_tpu.cluster_utils import Cluster
from ray_tpu.train.telemetry import StepTelemetry
from ray_tpu.util import state as state_api
from ray_tpu.util import tracing


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


@pytest.fixture
def cluster():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


# ---------------------------------------------------------------------
# step decomposition + MFU (unit: no cluster)
# ---------------------------------------------------------------------

def test_step_decomposition_sums_to_wall():
    t = StepTelemetry("unit-decomp", 0)
    t.set_flops_per_step(1e9, peak_flops=1e12)

    with t.timeit("data_wait"):
        time.sleep(0.02)
    s1 = t.on_report({})
    # first-step residual is compile (jit tracing happens in step 1)
    assert s1["step"] == 1
    assert s1["stages"]["data_wait"] >= 0.02
    assert s1["stages"]["compile"] > 0
    assert "compute" not in s1["stages"]
    assert abs(sum(s1["stages"].values()) - s1["wall_s"]) < 1e-9
    assert s1["mfu"] == pytest.approx(1e9 / s1["wall_s"] / 1e12)

    with t.timeit("collective_sync"):
        time.sleep(0.01)
    s2 = t.on_report({})
    # steady-state residual is compute
    assert s2["stages"]["compute"] > 0
    assert "compile" not in s2["stages"]
    assert abs(sum(s2["stages"].values()) - s2["wall_s"]) < 1e-9

    # goodput buckets mirror the stage decomposition
    assert t.goodput["compile"] == pytest.approx(s1["stages"]["compile"])
    assert t.goodput["productive"] == pytest.approx(s2["stages"]["compute"])
    assert t.goodput["stall"] == pytest.approx(
        s1["stages"]["data_wait"] + s2["stages"]["collective_sync"])
    t.close()


# ---------------------------------------------------------------------
# trainer integration: train.* series + goodput through the real fit
# ---------------------------------------------------------------------

def test_fit_emits_train_series_and_goodput(rt, tmp_path):
    def loop(config):
        for i in range(3):
            with rtrain.timeit("data_wait"):
                time.sleep(0.005)
            rtrain.report({"loss": 1.0 / (i + 1)})

    trainer = rtrain.DataParallelTrainer(
        loop,
        train_loop_config={"flops_per_step": 1e9, "peak_flops": 1e12},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path),
                             name="telemetry-fit"))
    result = trainer.fit()
    assert result.error is None

    q = state_api.cluster_metrics("train.step_s",
                                  tags={"run": "telemetry-fit"},
                                  group_by=["rank"])
    ranks = {g["tags"]["rank"] for g in q.get("groups") or []}
    assert ranks == {"0", "1"}, q

    mfu = state_api.cluster_metrics("train.mfu",
                                    tags={"run": "telemetry-fit"},
                                    group_by=["rank"])
    assert mfu.get("groups"), "declared FLOPs must produce train.mfu"

    g = state_api.train_goodput("telemetry-fit")
    assert set(g["ranks"]) >= {"0", "1"}
    assert g["buckets"]["productive"] > 0
    assert g["buckets"]["stall"] > 0          # the data_wait sleeps
    assert g["buckets"]["compile"] > 0        # first-step residual
    assert 0 < g["goodput_fraction"] <= 1


def test_failure_retry_lands_in_restart_bucket(rt, tmp_path):
    """Satellite 3: a mid-run failure + FailureConfig retry books the
    retry gap as restart badput, and productive time resumes counting
    on the new attempt."""
    marker = tmp_path / "failed_once"

    def flaky(config):
        for i in range(3):
            rtrain.report({"i": i})
            if i == 1 and not marker.exists():
                marker.write_text("x")
                raise RuntimeError("transient-failure")

    trainer = rtrain.DataParallelTrainer(
        flaky, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path / "exp"),
                             name="retry-run",
                             failure_config=FailureConfig(max_failures=1)))
    result = trainer.fit()
    assert result.error is None

    g = state_api.train_goodput("retry-run")
    assert g["buckets"]["restart"] > 0, g
    # the second attempt's steps 2..3 are steady-state -> productive
    assert g["buckets"]["productive"] > 0, g
    assert "driver" in g["ranks"]  # restart is driver-recorded


def test_elastic_reform_books_restart_and_resumes(tmp_path):
    """Satellite 3 (elastic flavor): a reform mid-run lands its wall
    clock in the restart bucket and step decomposition keeps summing
    after it."""
    import jax

    from ray_tpu.models import llama
    from ray_tpu.train.elastic import ElasticTrainer
    from ray_tpu.train.trainer import TrainConfig

    cfg = llama.LlamaConfig(
        vocab_size=64, d_model=16, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=32, head_dim=8, remat="none")
    et = ElasticTrainer(
        cfg, TrainConfig(total_steps=50, warmup_steps=1),
        checkpoint_dir=str(tmp_path / "ck"), devices=jax.devices()[:2],
        checkpoint_every=2, run_name="elastic-telemetry")

    import numpy as np
    rng = np.random.default_rng(0)

    def data():
        while True:
            yield rng.integers(0, 64, size=(4, 9)).astype(np.int32)

    it = data()
    state = et.init_state(jax.random.key(0))
    state = et.fit(state, it, steps=2)       # checkpoint at step 2
    assert len(et.telemetry.history) == 2
    prod_before = et.telemetry.goodput["productive"]

    state = et.reform(devices=jax.devices()[:2])
    assert et.telemetry.goodput["restart"] == 0  # restart is run-level
    state = et.fit(state, it, steps=2)
    # productive-step time RESUMED counting after the reform
    assert et.telemetry.goodput["productive"] > prod_before
    for stamp in et.telemetry.history:
        assert abs(sum(stamp["stages"].values()) - stamp["wall_s"]) < 1e-9
    et.close()

    g = state_api.train_goodput("elastic-telemetry")
    assert g["buckets"]["restart"] > 0, g
    assert g["buckets"]["productive"] > 0, g


# ---------------------------------------------------------------------
# stragglers + watchdog
# ---------------------------------------------------------------------

def test_stragglers_and_watchdog_token():
    t0 = StepTelemetry("straggle-run", 0)
    t1 = StepTelemetry("straggle-run", 1)
    for _ in range(3):
        with t0.timeit("compute"):
            pass
        t0.on_report({})
    with t1.timeit("compute"):
        pass
    t1.on_report({})

    # each rank holds an in-flight watchdog token for its NEXT step, so
    # a stuck step surfaces in the stuck-call report
    def train_calls():
        return [c for c in tracing.local_stuck_calls(threshold_s=0.0)
                if c.get("kind") == "train_step"
                and str(c.get("detail", "")).startswith("straggle-run:")]

    calls = train_calls()
    assert len(calls) == 2, calls
    assert any(c["detail"] == "straggle-run:rank1:step2" for c in calls)

    # lagger publishes its final progress, then the front rank moves on
    # (lag_s = front rank's last stamp minus this rank's)
    t1.close()
    time.sleep(0.05)
    t0.close()
    # close retires the tokens (no dangling 'stuck' entries)
    assert not train_calls()

    rep = state_api.train_stragglers("straggle-run", skew_s=0.01)
    assert rep["max_step"] == 3
    lagger = rep["ranks"]["1"]
    assert lagger["behind_steps"] == 2
    assert lagger["straggler"] is True
    assert rep["stragglers"] == ["1"]
    assert rep["ranks"]["0"]["straggler"] is False


# ---------------------------------------------------------------------
# satellite 1: sampler lifecycle
# ---------------------------------------------------------------------

def test_sampler_reentrant_idempotent_joins():
    from ray_tpu.util.profiling import Sampler

    s = Sampler(hz=200)
    s.start()
    s.start()                                 # re-entrant
    time.sleep(0.1)
    s.stop()                                  # inner stop: still running
    assert any(t.name == "ray_tpu-sampler" for t in threading.enumerate())
    res = s.stop()                            # outer stop: joins
    assert res["samples"] > 0
    assert not any(t.name == "ray_tpu-sampler"
                   for t in threading.enumerate())
    again = s.stop()                          # extra stop: no-op
    assert again["samples"] == res["samples"]


def test_sampler_caps_stack_table():
    from ray_tpu.util.profiling import Sampler

    stop_evt = threading.Event()

    def busy():
        while not stop_evt.is_set():
            sum(range(64))

    th = threading.Thread(target=busy, daemon=True)
    th.start()
    try:
        s = Sampler(hz=200, max_stacks=1)
        s.start()
        time.sleep(0.3)
        res = s.stop()
    finally:
        stop_evt.set()
        th.join(timeout=5)
    # >= 2 distinct stacks (this thread + busy) against a 1-entry cap
    assert res["dropped_stacks"] > 0, res
    assert len(res["folded"].splitlines()) == 1


# ---------------------------------------------------------------------
# tentpole acceptance: cluster-wide profiling fan-out
# ---------------------------------------------------------------------

def test_profile_cluster_merges_multiple_processes(cluster):
    @ray_tpu.remote
    def spin(seconds):
        t0 = time.monotonic()
        n = 0
        while time.monotonic() - t0 < seconds:
            n += 1
        return n

    refs = [spin.remote(8) for _ in range(2)]
    time.sleep(0.8)                    # workers are now inside spin()
    prof = state_api.profile_cluster(duration_s=1.0, hz=50)
    assert prof["errors"] == {}, prof["errors"]
    pids = {m["pid"] for m in prof["procs"].values()
            if isinstance(m, dict) and m.get("pid")}
    # >= 3 distinct OS processes in ONE merged window (acceptance):
    # driver/gcs/raylet share the test process; each worker is its own
    assert len(pids) >= 3, prof["procs"]
    assert any(k.startswith("worker:") for k in prof["procs"])
    assert "driver" in prof["procs"] and "gcs" in prof["procs"]
    # merged collapsed stacks carry the per-proc prefix and the hot fn
    assert "spin" in prof["folded"]
    assert any(line.startswith("driver;")
               for line in prof["folded"].splitlines())
    for r in refs:
        ray_tpu.cancel(r, force=True)


def test_dashboard_profile_endpoints(cluster):
    from ray_tpu.dashboard import start_dashboard, stop_dashboard

    @ray_tpu.remote
    def napper():
        time.sleep(8)

    ref = napper.remote()
    time.sleep(0.8)
    dash = start_dashboard()
    try:
        with urllib.request.urlopen(
                dash.url + "/api/profile?duration=0.5&hz=50",
                timeout=60) as resp:
            prof = json.loads(resp.read())
        assert prof["procs"] and prof["folded"]
        # satellite 2: one-shot dump, no sampling window
        with urllib.request.urlopen(
                dash.url + "/api/profile/stacks?proc=driver",
                timeout=30) as resp:
            body = json.loads(resp.read())
        assert "MainThread" in json.dumps(body)
        with urllib.request.urlopen(
                dash.url + "/api/profile/stacks?proc=gcs",
                timeout=30) as resp:
            gcs_body = json.loads(resp.read())
        assert gcs_body, gcs_body
    finally:
        stop_dashboard()
        ray_tpu.cancel(ref, force=True)
