"""Client mode tests: remote driver over RPC (reference analog:
python/ray/util/client tests — P6)."""

import subprocess
import sys
import time

import pytest

import ray_tpu


@pytest.fixture
def client_server():
    """Standalone server process (like `ray start` + client server)."""
    import socket

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    env = dict(__import__("os").environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.client.server", "--port", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    # wait for the listening line
    deadline = time.monotonic() + 60
    line = proc.stdout.readline().decode()
    assert "client server on" in line, line
    assert time.monotonic() < deadline
    ray_tpu.shutdown()
    yield f"client://127.0.0.1:{port}"
    ray_tpu.shutdown()
    proc.terminate()
    proc.wait(timeout=10)


def test_client_tasks_objects(client_server):
    ray_tpu.init(address=client_server)

    @ray_tpu.remote
    def add(a, b):
        return a + b

    ref = ray_tpu.put(10)
    out = ray_tpu.get(add.remote(ref, 32))
    assert out == 42

    refs = [add.remote(i, i) for i in range(5)]
    assert ray_tpu.get(refs) == [0, 2, 4, 6, 8]

    ready, not_ready = ray_tpu.wait(refs, num_returns=5, timeout=10)
    assert len(ready) == 5 and not not_ready


def test_client_task_error_propagates(client_server):
    ray_tpu.init(address=client_server)

    @ray_tpu.remote
    def boom():
        raise ValueError("kapow")

    with pytest.raises(Exception, match="kapow"):
        ray_tpu.get(boom.remote())


def test_client_actors(client_server):
    ray_tpu.init(address=client_server)

    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.v = start

        def inc(self, by=1):
            self.v += by
            return self.v

    c = Counter.options(name="client_counter").remote(100)
    assert ray_tpu.get(c.inc.remote()) == 101
    assert ray_tpu.get(c.inc.remote(9)) == 110

    # named lookup round-trips through the server
    again = ray_tpu.get_actor("client_counter")
    assert ray_tpu.get(again.inc.remote()) == 111

    ray_tpu.kill(c)
    time.sleep(0.2)
    with pytest.raises(Exception):
        ray_tpu.get(c.inc.remote(), timeout=5)


def test_client_cluster_resources(client_server):
    ray_tpu.init(address=client_server)
    total = ray_tpu.cluster_resources()
    assert total.get("CPU", 0) > 0


def test_client_attached_to_cluster(tmp_path):
    """Client -> server -> real multi-process cluster (the proxier
    deployment shape)."""
    import os
    import socket

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    gcs = f"{cluster.gcs_address[0]}:{cluster.gcs_address[1]}"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.client.server",
         "--port", str(port), "--address", gcs],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        line = proc.stdout.readline().decode()
        assert "client server on" in line, line
        ray_tpu.shutdown()
        ray_tpu.init(address=f"client://127.0.0.1:{port}")

        @ray_tpu.remote
        def pid():
            return __import__("os").getpid()

        worker_pid = ray_tpu.get(pid.remote())
        # ran in a cluster worker process, not the client, not the server
        assert worker_pid not in (proc.pid, __import__("os").getpid())
    finally:
        ray_tpu.shutdown()
        proc.terminate()
        proc.wait(timeout=10)
        cluster.shutdown()


def test_client_session_reconnect_resumes(client_server):
    """A dropped connection + redial with the same session token resumes
    the session: server-held refs survive (reference: client reconnect
    grace — the proxier keeps the client's driver alive ~30s)."""
    ray_tpu.init(address=client_server)
    from ray_tpu.runtime import core as _core

    rt = _core.get_runtime()
    ref = ray_tpu.put({"k": 41})
    # sever the transport underneath the reconnecting client
    rt._rpc._client._sock.close()
    # next call redials, re-hellos with the token, and the server-side
    # session (still within grace) serves the same object
    assert ray_tpu.get(ref, timeout=30) == {"k": 41}


def test_client_disconnect_reaps_session_actors(client_server):
    """Explicit disconnect kills the session's non-detached actors;
    detached ones survive (owner-scoped lifetime over client sessions)."""
    ray_tpu.init(address=client_server)

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    scoped = A.options(name="scoped_actor").remote()
    detached = A.options(name="kept_actor", lifetime="detached").remote()
    assert ray_tpu.get(scoped.ping.remote()) == "pong"
    assert ray_tpu.get(detached.ping.remote()) == "pong"
    ray_tpu.shutdown()          # client_disconnect -> immediate reap

    ray_tpu.init(address=client_server)   # fresh session
    a = ray_tpu.get_actor("kept_actor")
    assert ray_tpu.get(a.ping.remote()) == "pong"
    with pytest.raises(ValueError):
        ray_tpu.get_actor("scoped_actor")


def test_client_gc_releases_server_holds(client_server):
    """Dropped client-side ObjectRefs release their server-side session
    holds incrementally (reference: the client ReleaseObject protocol)
    instead of pinning until disconnect."""
    import gc

    ray_tpu.init(address=client_server)
    from ray_tpu.runtime import core as _core

    rt = _core.get_runtime()
    refs = [ray_tpu.put(i) for i in range(10)]
    held0 = rt._rpc.call("client_held_count")["held"]
    assert held0 >= 10
    keep = refs[0]
    del refs
    gc.collect()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        held = rt._rpc.call("client_held_count")["held"]
        if held <= held0 - 9:
            break
        time.sleep(0.2)
    assert held <= held0 - 9, f"holds not released: {held0} -> {held}"
    assert ray_tpu.get(keep) == 0   # the surviving ref still resolves


@pytest.fixture
def client_proxier():
    """Per-job proxier endpoint (reference: proxier.py ProxyManager)."""
    import socket

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()

    env = dict(__import__("os").environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu.client.proxier",
         "--port", str(port), "--child-idle-exit", "5"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    line = proc.stdout.readline().decode()
    assert "client proxier on" in line, line
    ray_tpu.shutdown()
    yield f"client://127.0.0.1:{port}"
    ray_tpu.shutdown()
    proc.terminate()
    proc.wait(timeout=10)


def test_proxier_per_job_process_isolation(client_proxier):
    """Two client jobs get DIFFERENT server processes (reference:
    proxier.py:113 — one SpecificServer per job)."""

    def server_pid():
        from ray_tpu.runtime import core as _core

        rt = _core.get_runtime()
        info = rt._rpc.call("client_hello", session_token=rt._token)

        # sanity: the redirected session actually works end to end
        @ray_tpu.remote
        def add(a, b):
            return a + b

        assert ray_tpu.get(add.remote(20, 22)) == 42
        ray_tpu.shutdown()
        return info["server_pid"]

    ray_tpu.init(address=client_proxier)
    pid_a = server_pid()
    ray_tpu.init(address=client_proxier)   # new token -> new job
    pid_b = server_pid()
    assert pid_a != pid_b, "both jobs landed in one server process"
