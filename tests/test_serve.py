"""Serve tests (reference analog: serve/tests/ incl. the no-cluster unit
layer serve/tests/unit/)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def rt(ray_tpu_start):
    yield ray_tpu_start
    serve.shutdown()


def test_deploy_and_call(rt):
    @serve.deployment
    class Echo:
        def __call__(self, x):
            return {"echo": x}

    handle = serve.run(Echo.bind())
    assert handle.call("hi") == {"echo": "hi"}


def test_constructor_args_and_methods(rt):
    @serve.deployment
    class Adder:
        def __init__(self, base):
            self.base = base

        def __call__(self, x):
            return self.base + x

        def double(self, x):
            return 2 * x

    handle = serve.run(Adder.bind(100))
    assert handle.call(5) == 105
    assert handle.options(method_name="double").call(21) == 42


def test_multiple_replicas_route(rt):
    import os

    @serve.deployment(num_replicas=3)
    class WhoAmI:
        def __init__(self):
            self.id = id(self)

        def __call__(self, _):
            return self.id

    handle = serve.run(WhoAmI.bind())
    # serve.run returns at >=1 replica; wait (via the public status API)
    # for the rest to come up — on a loaded host they start late, and 30
    # fast calls can otherwise land inside one refresh TTL and only ever
    # see the first replica
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        dep = serve.status()["deployments"].get("WhoAmI", {})
        if dep.get("running", 0) >= 2:
            break
        time.sleep(0.1)
    seen = {handle.call(None) for _ in range(30)}
    assert len(seen) >= 2, "p2c routing should hit multiple replicas"


def test_redeploy_updates(rt):
    @serve.deployment
    class V:
        def __call__(self, _):
            return "v1"

    serve.run(V.bind())

    @serve.deployment(name="V")
    class V2:
        def __call__(self, _):
            return "v2"

    handle = serve.run(V2.bind())
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if handle.call(None) == "v2":
            return
        time.sleep(0.1)
    pytest.fail("redeploy did not take effect")


def test_user_config_reconfigure(rt):
    @serve.deployment(user_config={"threshold": 7})
    class Conf:
        def __init__(self):
            self.threshold = 0

        def reconfigure(self, cfg):
            self.threshold = cfg["threshold"]

        def __call__(self, _):
            return self.threshold

    handle = serve.run(Conf.bind())
    assert handle.call(None) == 7


def test_dynamic_batching(rt):
    @serve.deployment(max_concurrent_queries=16)
    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.1)
        def handle(self, items):
            self.batch_sizes.append(len(items))
            return [i * 2 for i in items]

        def __call__(self, x):
            return self.handle(x)

        def sizes(self):
            return self.batch_sizes

    handle = serve.run(Batched.bind())
    refs = [handle.remote(i) for i in range(16)]
    out = sorted(ray_tpu.get(refs))
    assert out == [i * 2 for i in range(16)]
    sizes = handle.options(method_name="sizes").call()
    assert max(sizes) > 1, f"no batching happened: {sizes}"


def test_autoscaling_up(rt):
    @serve.deployment(
        autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                            "target_ongoing_requests": 1.0,
                            "upscale_delay_s": 0.1},
        max_concurrent_queries=4)
    class Slow:
        def __call__(self, _):
            time.sleep(0.4)
            return 1

    handle = serve.run(Slow.bind())
    refs = [handle.remote(None) for _ in range(12)]
    deadline = time.monotonic() + 15
    scaled = False
    controller = ray_tpu.get_actor(serve.api.CONTROLLER_NAME)
    while time.monotonic() < deadline:
        deps = ray_tpu.get(controller.list_deployments.remote())
        if deps["Slow"]["running"] > 1:
            scaled = True
            break
        time.sleep(0.2)
    ray_tpu.get(refs)
    assert scaled, "autoscaler did not add replicas under load"


def test_http_proxy(rt):
    @serve.deployment
    class Api:
        def __call__(self, payload):
            return {"sum": payload["a"] + payload["b"]}

    serve.run(Api.bind())
    server, (host, port) = serve.start_http_proxy()
    try:
        req = urllib.request.Request(
            f"http://{host}:{port}/Api",
            data=json.dumps({"a": 2, "b": 3}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            body = json.loads(resp.read())
        assert body["result"]["sum"] == 5
        health = urllib.request.urlopen(
            f"http://{host}:{port}/healthz", timeout=10)
        assert health.status == 200
    finally:
        server.shutdown()


def test_delete_deployment(rt):
    @serve.deployment
    class Gone:
        def __call__(self, _):
            return 1

    handle = serve.run(Gone.bind())
    assert handle.call(None) == 1
    serve.delete("Gone")
    time.sleep(0.3)
    with pytest.raises(Exception):
        fresh = serve.get_deployment_handle("Gone")
        fresh.call(None)


# ---------------------------------------------------------------------------
# multiplexing / streaming / status (reference: serve/multiplex.py,
# replica handle_request_streaming, serve.status())
# ---------------------------------------------------------------------------

def test_multiplexed_models(rt):
    from ray_tpu import serve

    loads = []

    @serve.deployment(num_replicas=1)
    class Mux:
        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id):
            loads.append(model_id)
            return {"id": model_id, "scale": int(model_id[-1])}

        def __call__(self, payload):
            model = self.get_model()
            return model["scale"] * payload["x"]

    handle = serve.run(Mux.bind(), name="mux")
    h1 = handle.options(multiplexed_model_id="m1")
    h2 = handle.options(multiplexed_model_id="m2")
    assert h1.call({"x": 10}) == 10
    assert h2.call({"x": 10}) == 20
    # cached: calling again must not reload
    assert h1.call({"x": 5}) == 5
    handle._refresh(ttl=0)
    assert set(ray_tpu.get(
        handle._replicas[0].multiplexed_model_ids.remote())) == {"m1", "m2"}

    # LRU eviction at capacity 2: m1 was used most recently, so loading
    # m3 evicts m2 (least recently used)
    h3 = handle.options(multiplexed_model_id="m3")
    assert h3.call({"x": 1}) == 3
    ids = ray_tpu.get(handle._replicas[0].multiplexed_model_ids.remote())
    assert "m2" not in ids and set(ids) == {"m1", "m3"}
    serve.delete("mux")


def test_streaming_response(rt):
    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class Streamer:
        def generate(self, n):
            for i in range(n):
                yield i * i

    handle = serve.run(Streamer.bind(), name="streamer")
    chunks = list(handle.options(method_name="generate").stream(30))
    assert chunks == [i * i for i in range(30)]
    serve.delete("streamer")


def test_streaming_error_propagates(rt):
    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class Bad:
        def generate(self):
            yield 1
            raise ValueError("stream-boom")

    handle = serve.run(Bad.bind(), name="bad_stream")
    gen = handle.options(method_name="generate").stream()
    with pytest.raises(Exception, match="stream-boom"):
        list(gen)
    serve.delete("bad_stream")


def test_serve_status(rt):
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class S:
        def __call__(self, payload):
            return 1

    handle = serve.run(S.bind(), name="stat")
    for _ in range(4):
        handle.call({})
    st = serve.status()
    assert "stat" in st["deployments"]
    assert st["deployments"]["stat"]["total_requests"] >= 4
    serve.delete("stat")


def test_streaming_with_multiplex(rt):
    """Multiplexed model id must reach the streaming request context."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class MuxStream:
        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id):
            return model_id

        def generate(self, n):
            m = self.get_model()
            for i in range(n):
                yield f"{m}-{i}"

    handle = serve.run(MuxStream.bind(), name="muxstream")
    out = list(handle.options(method_name="generate",
                              multiplexed_model_id="mm").stream(2))
    assert out == ["mm-0", "mm-1"]
    serve.delete("muxstream")


def test_streaming_chunks_before_error_delivered(rt):
    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class Partial:
        def generate(self):
            yield "a"
            yield "b"
            raise RuntimeError("later-boom")

    handle = serve.run(Partial.bind(), name="partial")
    got = []
    with pytest.raises(Exception, match="later-boom"):
        for c in handle.options(method_name="generate").stream():
            got.append(c)
    assert got == ["a", "b"]
    serve.delete("partial")


def test_deployment_graph_composition(rt):
    """Deployment-graph composition (reference: serve deployment graphs —
    passing one bound deployment into another's .bind()): serve.run on
    the outer node deploys the whole graph, and the replica receives
    live handles for nested deployments."""
    @serve.deployment
    class Preprocess:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Model:
        def __init__(self, prefix):
            self.prefix = prefix

        def __call__(self, x):
            return f"{self.prefix}:{x}"

    @serve.deployment
    class Pipeline:
        def __init__(self, pre, model):
            self.pre = pre
            self.model = model

        def __call__(self, x):
            y = ray_tpu.get(self.pre.remote(x))
            return ray_tpu.get(self.model.remote(y))

    handle = serve.run(Pipeline.bind(Preprocess.bind(),
                                     Model.bind("out")))
    assert handle.call(21) == "out:42"
    # all three deployments are live and individually addressable
    st = serve.status()
    assert {"Pipeline", "Preprocess", "Model"} <= set(st["deployments"])
    inner = serve.get_deployment_handle("Preprocess")
    assert inner.call(5) == 10


def test_deployment_graph_nested_in_containers(rt):
    """Nested deployments inside lists/dicts of init args resolve too."""
    @serve.deployment
    class Leaf:
        def __init__(self, k):
            self.k = k

        def __call__(self):
            return self.k

    @serve.deployment
    class Fanout:
        def __init__(self, legs):
            self.legs = legs

        def __call__(self):
            return sorted(ray_tpu.get([h.remote() for h in
                                       self.legs.values()]))

    handle = serve.run(Fanout.bind(
        {"a": Leaf.options(name="LeafA").bind(1),
         "b": Leaf.options(name="LeafB").bind(2)}))
    assert handle.call() == [1, 2]


def test_deployment_graph_name_collision_rejected(rt):
    """Two DIFFERENT bind nodes under one name must raise, not silently
    alias to whichever deployed first."""
    @serve.deployment
    class Leaf:
        def __init__(self, k):
            self.k = k

        def __call__(self):
            return self.k

    @serve.deployment
    class Fanout:
        def __init__(self, legs):
            self.legs = legs

        def __call__(self):
            return [ray_tpu.get(h.remote()) for h in self.legs]

    with pytest.raises(ValueError, match="disambiguate"):
        serve.run(Fanout.bind([Leaf.bind(1), Leaf.bind(2)]))
    # identical bind nodes under one name are fine (true sharing)
    shared = Leaf.bind(7)
    handle = serve.run(Fanout.bind([shared, shared]))
    assert handle.call() == [7, 7]


def test_apply_config_dict(rt):
    """Declarative config → live deployments (reference: serve/schema.py
    + REST config)."""
    handles = serve.apply_config({
        "applications": [{
            "name": "app",
            "deployments": [{
                "name": "G2",
                "import_path": "tests._serve_config_target:greeter",
                "init_args": ["yo"],
                "num_replicas": 2,
            }],
        }],
    })
    assert set(handles) == {"G2"}
    assert handles["G2"].call("x") == "yo x"
    meta = serve.status()["deployments"]["G2"]
    assert meta["target"] == 2 or meta.get("num_replicas") == 2, meta


def test_apply_config_file_yaml(rt, tmp_path):
    cfg = tmp_path / "serve.yaml"
    cfg.write_text(
        "deployments:\n"
        "  - import_path: tests._serve_config_target:bound_greeter\n"
        "    user_config: {}\n")
    handles = serve.apply_config_file(str(cfg))
    assert handles["Greeter"].call("there") == "hi there"


def test_apply_config_validation_errors(rt):
    with pytest.raises(ValueError, match="import_path is required"):
        serve.apply_config({"deployments": [{"name": "X"}]})
    with pytest.raises(ValueError, match="unknown field"):
        serve.apply_config({"deployments": [
            {"import_path": "tests._serve_config_target:greeter",
             "replicas": 2}]})
    with pytest.raises(ValueError, match="expected a @serve.deployment"):
        serve.apply_config({"deployments": [
            {"import_path": "tests._serve_config_target:serve"}]})


def test_cli_serve_deploy_and_status(tmp_path, capsys):
    """`ray-tpu serve-deploy config.yaml` end to end (local mode)."""
    from ray_tpu.scripts.cli import main

    cfg = tmp_path / "serve.yaml"
    cfg.write_text(
        "deployments:\n"
        "  - import_path: tests._serve_config_target:greeter\n"
        "    init_args: [hey]\n")
    try:
        main(["serve-deploy", str(cfg), "--num-cpus", "4"])
        out = capsys.readouterr().out
        assert "deployed Greeter" in out
        assert serve.get_deployment_handle("Greeter").call("u") == "hey u"
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


def test_namedtuple_init_args_survive_graph_walk(rt):
    """Plain structured init args (incl. namedtuples) pass through the
    deployment-graph walker untouched."""
    from tests._serve_config_target import Point

    @serve.deployment
    class Holder:
        def __init__(self, p, coords):
            self.p = p
            self.coords = coords

        def __call__(self):
            return (type(self.p).__name__, self.p.x + self.p.y,
                    self.coords)

    handle = serve.run(Holder.bind(Point(1, 2), (3, 4)))
    assert handle.call() == ("Point", 3, (3, 4))


def test_apply_config_top_level_typo_rejected(rt):
    with pytest.raises(ValueError, match="unknown top-level"):
        serve.apply_config({"deploymets": []})
    with pytest.raises(ValueError, match="applications"):
        serve.apply_config({})


def test_apply_config_kwargs_only_keeps_bound_args(rt):
    """init_kwargs in the config must not wipe the import target's
    bound positional args."""
    handles = serve.apply_config({"deployments": [{
        "import_path": "tests._serve_config_target:bound_greeter",
        "init_kwargs": {},
    }]})
    assert handles["Greeter"].call("k") == "hi k"


def test_apply_config_cross_app_name_collision_rejected(rt):
    cfg = {"applications": [
        {"name": "a1", "deployments": [
            {"import_path": "tests._serve_config_target:greeter"}]},
        {"name": "a2", "deployments": [
            {"import_path": "tests._serve_config_target:greeter"}]},
    ]}
    with pytest.raises(ValueError, match="already declared"):
        serve.apply_config(cfg)


def test_apply_config_is_atomic(rt):
    """A bad later entry must leave NOTHING deployed."""
    cfg = {"deployments": [
        {"import_path": "tests._serve_config_target:greeter"},
        {"import_path": "tests._serve_config_target:nope"},
    ]}
    with pytest.raises(ValueError, match="no attribute"):
        serve.apply_config(cfg)
    assert "Greeter" not in serve.status()["deployments"]
