"""State API, metrics, timeline, CLI tests (reference: state/metrics
tests + scripts tests)."""

import json

import pytest

import ray_tpu
from ray_tpu.util import metrics as m
from ray_tpu.util import state


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def test_state_local_mode(rt):
    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.options(name="obs_actor").remote()
    ray_tpu.get(a.ping.remote())
    actors = state.list_actors()
    assert any(x["name"] == "obs_actor" for x in actors)
    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]
    summary = state.cluster_summary()
    assert summary["initialized"] and summary["mode"] == "local"
    assert summary["actors"].get("ALIVE", 0) >= 1


def test_task_timeline(rt, tmp_path):
    @ray_tpu.remote
    def traced(x):
        return x

    ray_tpu.get([traced.remote(i) for i in range(3)])
    trace = ray_tpu.timeline(str(tmp_path / "trace.json"))
    assert len([e for e in trace if e["name"].endswith("traced")]) == 3
    loaded = json.loads((tmp_path / "trace.json").read_text())
    assert loaded and loaded[0]["ph"] == "X"


def test_state_cluster_mode():
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    try:
        ray_tpu.init(address=c.gcs_address)

        @ray_tpu.remote
        class B:
            def ping(self):
                return 1

        b = B.options(name="cl_actor").remote()
        ray_tpu.get(b.ping.remote())
        assert any(x["name"] == "cl_actor" for x in state.list_actors())
        assert state.cluster_summary()["mode"] == "cluster"
        assert state.list_jobs() == [] or isinstance(state.list_jobs(), list)
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_metrics_counter_gauge_histogram():
    c = m.Counter("test_requests_total", "reqs", ("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    g = m.Gauge("test_inflight", "inflight")
    g.set(5)
    h = m.Histogram("test_latency_s", "lat", boundaries=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = m.export_prometheus()
    assert 'test_requests_total{route="/a"} 3.0' in text
    assert "test_inflight 5.0" in text
    assert "test_latency_s_count 3" in text
    assert 'test_latency_s_bucket{le="0.1"} 1' in text


def test_cli_status_and_list(capsys):
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.scripts.cli import main

    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    addr = f"{c.gcs_address[0]}:{c.gcs_address[1]}"
    try:
        main(["status", "--address", addr])
        out = capsys.readouterr().out
        assert "Nodes: 1 alive" in out
        main(["list", "nodes", "--address", addr])
        rows = json.loads(capsys.readouterr().out)
        assert len(rows) == 1
        main(["memory", "--address", addr])
        assert "workers=" in capsys.readouterr().out
    finally:
        c.shutdown()
