"""Dashboard HTTP head, tracing spans, usage stats.
(reference analogs: dashboard/head.py + modules, util/tracing/
tracing_helper.py, _private/usage/usage_lib.py)"""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu._private import usage_stats
from ray_tpu.util import tracing


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read()


def test_dashboard_endpoints(rt):
    from ray_tpu.dashboard import Dashboard

    @ray_tpu.remote
    def f(x):
        return x + 1

    ray_tpu.get([f.remote(i) for i in range(3)])

    dash = Dashboard(port=0).start()
    try:  # noqa: SIM105
        status, body = _get(dash.url + "/api/cluster_status")
        assert status == 200
        summary = json.loads(body)
        assert summary["initialized"] and summary["mode"] == "local"

        for ep in ("nodes", "actors", "tasks", "jobs",
                   "placement_groups", "objects", "timeline"):
            status, body = _get(f"{dash.url}/api/{ep}")
            assert status == 200, ep
            json.loads(body)

        status, body = _get(dash.url + "/api/tasks")
        assert any(t["name"].endswith("f") for t in json.loads(body))

        from ray_tpu.util.metrics import Counter

        c = Counter("dash_test_counter", "test")
        c.inc(3)
        status, body = _get(dash.url + "/metrics")
        assert status == 200
        assert b"dash_test_counter" in body

        status, body = _get(dash.url + "/")
        assert status == 200 and b"ray_tpu dashboard" in body
    finally:
        dash.stop()


def test_dashboard_spa_served(rt):
    """`/` serves the packaged single-page app (reference analog:
    dashboard/client React UI), not just an API listing."""
    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(port=0).start()
    try:
        status, body = _get(dash.url + "/")
        assert status == 200
        for marker in (b"ray_tpu dashboard", b'id="tabs"',
                       b"placement_groups", b"sparkline", b"/api/"):
            assert marker in body, marker
    finally:
        dash.stop()


def test_dashboard_404(rt):
    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(port=0).start()
    try:
        try:
            _get(dash.url + "/api/nope")
            raised = False
        except urllib.error.HTTPError as e:
            raised = e.code == 404
        assert raised
    finally:
        dash.stop()


def test_tracing_spans_parented(rt, tmp_path):
    trace_dir = str(tmp_path / "traces")
    tracing.enable_tracing(trace_dir)
    try:
        @ray_tpu.remote
        def child():
            return 1

        @ray_tpu.remote
        def parent():
            return ray_tpu.get(child.remote())

        with tracing.span("driver-root"):
            assert ray_tpu.get(parent.remote()) == 1

        spans = tracing.read_spans(trace_dir)
        names = {s["name"] for s in spans}
        assert "driver-root" in names
        assert any(n.startswith("submit:") and n.endswith("parent")
                   for n in names)
        assert any(n.startswith("run:") and n.endswith("child")
                   for n in names)
        # all spans share the driver-root trace id
        root = next(s for s in spans if s["name"] == "driver-root")
        run_child = next(s for s in spans
                         if s["name"].startswith("run:")
                         and s["name"].endswith("child"))
        assert run_child["trace_id"] == root["trace_id"]
        # chrome conversion shape
        trace = tracing.to_chrome_trace(spans)
        assert all(e["ph"] == "X" and "ts" in e for e in trace)
    finally:
        tracing.disable_tracing()


def test_tracing_disabled_no_overhead(rt, tmp_path):
    assert not tracing.is_enabled()

    @ray_tpu.remote
    def f():
        return 2

    assert ray_tpu.get(f.remote()) == 2
    assert tracing.read_spans(str(tmp_path)) == []


def test_usage_stats(tmp_path, monkeypatch):
    usage_stats.record_library_usage("train")
    usage_stats.record_extra_usage_tag("tasks_submitted", 5)
    report = usage_stats.usage_report()
    assert "train" in report["libraries"]
    assert report["counters"]["tasks_submitted"] >= 5
    path = usage_stats.write_report(str(tmp_path / "usage.json"))
    assert json.load(open(path))["enabled"]

    monkeypatch.setenv("RAY_TPU_USAGE_STATS_ENABLED", "0")
    before = dict(usage_stats.usage_report()["counters"])
    usage_stats.record_extra_usage_tag("tasks_submitted", 1)
    assert usage_stats.usage_report()["counters"] == before


def test_cluster_timeline_has_events():
    """Cluster mode: workers report task events to the GCS sink, so
    ray_tpu.timeline() is non-empty (it was silently [] before)."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)

        @ray_tpu.remote
        def work(i):
            return i

        ray_tpu.get([work.remote(i) for i in range(10)])
        import time as _time

        deadline = _time.monotonic() + 10
        trace = []
        while _time.monotonic() < deadline:
            trace = ray_tpu.timeline()
            if any("work" in e["name"] for e in trace):
                break
            _time.sleep(0.2)
        assert any("work" in e["name"] for e in trace), trace[:3]
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_jobs_rest_api(rt, tmp_path):
    """Job submission through the dashboard REST surface (reference:
    dashboard/modules/job/job_head.py)."""
    import urllib.request as _rq

    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(port=0).start()
    try:
        body = json.dumps({
            "entrypoint": "python -c \"print('job-output-42')\"",
        }).encode()
        req = _rq.Request(dash.url + "/api/jobs", data=body,
                          headers={"Content-Type": "application/json"})
        with _rq.urlopen(req, timeout=30) as r:
            job_id = json.loads(r.read())["job_id"]
        assert job_id

        import time as _time

        deadline = _time.monotonic() + 30
        status = None
        while _time.monotonic() < deadline:
            with _rq.urlopen(f"{dash.url}/api/jobs/{job_id}",
                             timeout=10) as r:
                status = json.loads(r.read())["status"]
            if status in ("SUCCEEDED", "FAILED"):
                break
            _time.sleep(0.2)
        assert status == "SUCCEEDED"
        with _rq.urlopen(f"{dash.url}/api/jobs/{job_id}/logs",
                         timeout=10) as r:
            assert b"job-output-42" in r.read()
    finally:
        dash.stop()


def test_accelerator_constants():
    from ray_tpu.util import accelerators as acc

    assert acc.TPU_V5P == "TPU-V5P"
    assert acc.tpu_generation_from_kind("TPU v4") == "TPU-V4"
    assert acc.tpu_generation_from_kind("TPU v5 lite") == "TPU-V5LITEPOD"
    assert acc.tpu_generation_from_kind("H100") is None
