"""Crash chaos plane (round 10): seeded process-kill rules, typed
surfacing of injected deaths, worker/replica supervision, graceful
drain, and a fixed-seed smoke soak over the conservation invariants.

The full multi-seed soak with the raylet/GCS classes runs nightly
(ci/run_ci.sh --nightly via scripts/run_chaos_soak.py); this module is
the tier-1 fence."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.runtime import fault_injection as fi
from ray_tpu.utils import exceptions as exc


# ----------------------------------------------------------------------
# unit: the crash rule engine through the test seam (no real deaths)
# ----------------------------------------------------------------------

def _plane_with(rules, label="worker", seed=0):
    plane = fi.FaultPlane()
    plane.process_label = label
    plane.load_plan({"version": 1, "seed": seed, "rules": rules})
    return plane


def test_crash_rule_fires_on_exactly_the_nth_hit():
    plane = _plane_with([{"id": "r", "fault": "crash",
                          "point": "worker.mid_task", "proc": "worker",
                          "nth": 3}])
    died = []
    plane.set_crash_handler(lambda point, rule: died.append(
        (point, rule.rid)))
    for _ in range(2):
        plane.maybe_crash("worker.mid_task")
    assert died == []
    plane.maybe_crash("worker.mid_task")
    assert died == [("worker.mid_task", "r")]
    plane.maybe_crash("worker.mid_task")   # nth fires ONCE, not >=
    assert len(died) == 1


def test_crash_rule_scopes_by_proc_and_globs_points():
    plane = _plane_with([{"id": "g", "fault": "crash",
                          "point": "replica.mid_*", "proc": "worker"}],
                        label="gcs")
    died = []
    plane.set_crash_handler(lambda p, r: died.append(p))
    plane.maybe_crash("replica.mid_decode")   # wrong proc label
    assert died == []
    plane.process_label = "worker"
    plane.maybe_crash("raylet.before_lease_grant")   # point mismatch
    assert died == []
    plane.maybe_crash("replica.mid_decode")
    plane.maybe_crash("replica.mid_request")
    assert died == ["replica.mid_decode", "replica.mid_request"]


def test_crash_rule_probability_is_seeded_and_replayable():
    def firing_indices(seed):
        plane = _plane_with([{"id": "p", "fault": "crash", "point": "x",
                              "p": 0.5}], seed=seed)
        fired = []
        plane.set_crash_handler(lambda p, r: fired.append(True))
        out = []
        for i in range(64):
            n = len(fired)
            plane.maybe_crash("x")
            if len(fired) > n:
                out.append(i)
        return out

    a, b = firing_indices(7), firing_indices(7)
    assert a == b and a, "same seed must replay the same schedule"
    assert firing_indices(8) != a, "different seed, different schedule"


def test_crash_marker_format_survives_to_handler():
    plane = _plane_with([{"id": "m", "fault": "crash", "point": "x"}])
    seen = {}
    plane.set_crash_handler(lambda p, r: seen.update(point=p, rid=r.rid))
    plane.maybe_crash("x")
    assert seen == {"point": "x", "rid": "m"}
    # the marker the real _die path writes is what the log plane keys on
    assert fi.CRASH_MARKER == "RAY_TPU_CRASH"


# ----------------------------------------------------------------------
# integration: real injected deaths on a live cluster
# ----------------------------------------------------------------------

@pytest.fixture
def chaos_cluster(monkeypatch):
    from ray_tpu import serve

    monkeypatch.setenv("RAY_TPU_FAULT_INJECTION_ENABLED", "1")
    ray_tpu.shutdown()
    fi.plane.clear()
    c = Cluster(heartbeat_timeout_s=2.0)
    c.add_node(num_cpus=4)
    ray_tpu.init(address=c.gcs_address)
    yield c
    serve.shutdown()
    fi.put_plan(c.gcs_address, {"version": 99, "rules": []})
    ray_tpu.shutdown()
    fi.stop_kv_watcher()
    c.shutdown()
    fi.plane.clear()


def test_worker_crash_surfaces_typed_error_and_crash_group(chaos_cluster):
    c = chaos_cluster
    from ray_tpu.util import state as state_api

    @ray_tpu.remote
    def victim(x):
        return x * 2

    assert ray_tpu.get(victim.remote(2), timeout=30) == 4   # warm pool

    fi.put_plan(c.gcs_address, {"version": 1, "rules": [
        {"id": "midtask", "fault": "crash", "point": "worker.mid_task",
         "proc": "worker", "nth": 1}]})
    time.sleep(0.4)   # workers poll the KV plan key

    # every crashed call resolves with a TYPED error, never a bare
    # timeout and never a wedge (the conservation invariant)
    with pytest.raises(exc.RayTpuError) as ei:
        ray_tpu.get(victim.remote(3), timeout=30)
    assert not isinstance(ei.value, TimeoutError)

    fi.put_plan(c.gcs_address, {"version": 2, "rules": []})
    # the pool respawns the crashed worker: new work flows
    assert ray_tpu.get(victim.remote(5), timeout=30) == 10

    # last-words harvest: the raw-fd marker became a trace-linked
    # 'crash' group naming the crash point
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        crash = [g for g in state_api.summarize_errors()
                 if g.get("kind") == "crash"
                 and g.get("crash_point") == "worker.mid_task"]
        if crash:
            break
        time.sleep(0.2)
    assert crash, "no crash group for worker.mid_task in summarize_errors"
    assert crash[0]["count"] >= 1


def test_replica_crash_failover_replaces_and_call_survives(chaos_cluster):
    c = chaos_cluster
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            return x

    h = serve.run(Echo.bind(), name="echo_failover")
    assert h.call(1) == 1

    fi.put_plan(c.gcs_address, {"version": 1, "rules": [
        {"id": "midreq", "fault": "crash", "point": "replica.mid_request",
         "proc": "worker", "nth": 1, "max_hits": 1}]})
    time.sleep(0.4)

    # the handling replica dies mid-request; the caller either gets an
    # answer via retry against a survivor or a TYPED fast-fail — while
    # the plan stays armed every fresh replica's FIRST request crashes
    # too (per-process nth), so both outcomes are legal. What is never
    # legal: a wedge or a bare timeout.
    t0 = time.monotonic()
    try:
        assert h.call(7) == 7
    except exc.ReplicaDiedError:
        pass
    assert time.monotonic() - t0 < 30

    fi.put_plan(c.gcs_address, {"version": 2, "rules": []})

    # the controller's probe buries the corpse and the reconciler
    # replaces it; failover_stats records detection AND recovery
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        stats = ray_tpu.get(controller.failover_stats.remote(),
                            timeout=10)
        done = [e for e in stats["events"]
                if e["deployment"] == "echo_failover"
                and e.get("replaced_at") is not None]
        if done:
            break
        time.sleep(0.2)
    assert done, f"no completed replacement in failover_stats: {stats}"
    assert stats["replaced"].get("echo_failover", 0) >= 1
    # steady state returns once the plan is cleared (replacements may
    # briefly still be dying from pre-clear requests)
    deadline = time.monotonic() + 20
    while True:
        try:
            assert h.call(9) == 9
            break
        except exc.ReplicaDiedError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.3)


def test_scale_down_drains_gracefully_without_killing_inflight(
        chaos_cluster):
    """The drain guarantee: a scale-down victim finishes its in-flight
    request before the controller kills it — the caller never sees a
    ReplicaDiedError for a deliberate downscale."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Slow:
        def __call__(self, delay):
            time.sleep(delay)
            return "done"

    h = serve.run(Slow.bind(), name="drain_me")
    assert h.call(0.01) == "done"

    # park one slow request on EACH replica so the drain victim
    # (whichever the controller picks) is mid-request when scaled down
    refs = [h.remote(2.0) for _ in range(4)]
    time.sleep(0.3)
    serve.run(Slow.options(num_replicas=1).bind(), name="drain_me")
    assert [ray_tpu.get(r, timeout=30) for r in refs] == ["done"] * 4

    # the deployment settles at 1 replica and still serves
    controller = ray_tpu.get_actor("SERVE_CONTROLLER")
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        _, table = ray_tpu.get(controller.get_routing_table.remote(
            "drain_me"), timeout=10)
        stats = ray_tpu.get(controller.failover_stats.remote(),
                            timeout=10)
        if len(table) == 1 and not stats["draining"].get("drain_me"):
            break
        time.sleep(0.2)
    assert len(table) == 1
    assert h.call(0.01) == "done"


# ----------------------------------------------------------------------
# smoke soak: the nightly harness at tier-1 scale (fixed seed, <=60s)
# ----------------------------------------------------------------------

def test_smoke_soak_conservation_holds(monkeypatch):
    from ray_tpu.chaos_soak import run_soak

    ray_tpu.shutdown()
    fi.plane.clear()
    # sized for the tier-1 budget: the injection schedule stops
    # max(6, inject_period) before t_end, so 14s still fits >= 2
    # windows; the tighter get timeout also shrinks the settle tail
    # (recovery MTTRs in this config are well under a second)
    report = run_soak(14.0, seed=11, classes=("worker", "replica"),
                      partitions=False, inject_period_s=4.0,
                      get_timeout_s=15.0, log=lambda *a: None)
    assert report["chaos_soak_invariant_violations"] == 0, \
        report["violations"]
    inj = {cls: ent["injections"]
           for cls, ent in report["per_class"].items()}
    assert inj.get("worker", 0) + inj.get("replica", 0) >= 2, inj
    # every submitted op resolved (value or typed error): conservation
    for name, w in report["workloads"].items():
        assert w["untyped_errors"] == 0, (name, w)
