"""Avro container-file codec tests (reference analog:
``data/tests`` datasource coverage for ``avro_datasource.py``)."""

import io
import json
import struct
import zlib

import pytest

from ray_tpu.data import read_avro, write_avro_file
from ray_tpu.data.avro import (
    MAGIC,
    _read_long,
    _write_long,
    infer_schema,
    iter_avro,
    write_avro,
)


def test_zigzag_varint_roundtrip():
    for n in (0, 1, -1, 2, -2, 63, 64, -64, -65, 1 << 20, -(1 << 20),
              (1 << 62), -(1 << 62)):
        out = io.BytesIO()
        _write_long(out, n)
        assert _read_long(io.BytesIO(out.getvalue())) == n


def test_known_zigzag_encodings():
    """Spec examples: 0->00, -1->01, 1->02, -2->03, 2->04."""
    for n, want in [(0, b"\x00"), (-1, b"\x01"), (1, b"\x02"),
                    (-2, b"\x03"), (2, b"\x04")]:
        out = io.BytesIO()
        _write_long(out, n)
        assert out.getvalue() == want


def _rows():
    return [
        {"id": i, "name": f"row{i}", "score": i * 0.5,
         "flag": i % 2 == 0, "blob": bytes([i]),
         "tags": [f"t{i}", "x"], "attrs": {"k": i}}
        for i in range(25)
    ]


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_container_roundtrip(codec):
    rows = _rows()
    data = write_avro(rows, codec=codec, block_records=7)
    assert data.startswith(MAGIC)
    got = list(iter_avro(data))
    assert got == rows


def test_nullable_union_roundtrip():
    rows = [{"a": None, "b": 1}, {"a": "x", "b": 2}]
    data = write_avro(rows)
    assert list(iter_avro(data)) == rows


def test_explicit_schema_with_enum_and_fixed():
    schema = {
        "type": "record", "name": "r", "fields": [
            {"name": "color",
             "type": {"type": "enum", "name": "Color",
                      "symbols": ["RED", "GREEN", "BLUE"]}},
            {"name": "sig",
             "type": {"type": "fixed", "name": "Sig", "size": 4}},
        ],
    }
    rows = [{"color": "GREEN", "sig": b"\x01\x02\x03\x04"}]
    got = list(iter_avro(write_avro(rows, schema)))
    assert got == rows


def test_hand_built_file_decodes():
    """Byte-exact fixture built from the spec, independent of the
    writer: one block, two records of {\"n\": long, \"s\": string}."""
    schema = {"type": "record", "name": "t", "fields": [
        {"name": "n", "type": "long"}, {"name": "s", "type": "string"}]}
    meta_schema = json.dumps(schema).encode()

    def vint(n):
        out = io.BytesIO()
        _write_long(out, n)
        return out.getvalue()

    body = vint(7) + vint(2) + b"hi" + vint(-3) + vint(2) + b"yo"
    buf = (MAGIC
           + vint(1)                                   # one meta entry
           + vint(len(b"avro.schema")) + b"avro.schema"
           + vint(len(meta_schema)) + meta_schema
           + vint(0)                                    # end of meta
           + b"S" * 16                                  # sync
           + vint(2) + vint(len(body)) + body
           + b"S" * 16)
    assert list(iter_avro(buf)) == [{"n": 7, "s": "hi"},
                                    {"n": -3, "s": "yo"}]


def test_corrupt_sync_rejected():
    data = bytearray(write_avro([{"a": 1}]))
    data[-1] ^= 0xFF  # flip a byte of the trailing sync marker
    with pytest.raises(ValueError, match="sync"):
        list(iter_avro(bytes(data)))


def test_infer_schema_types():
    s = infer_schema({"i": 1, "f": 2.0, "b": True, "s": "x",
                      "z": b"q", "l": [1], "m": {"k": "v"},
                      "n": None})
    by_name = {f["name"]: f["type"] for f in s["fields"]}
    assert by_name["i"] == "long" and by_name["f"] == "double"
    assert by_name["b"] == "boolean" and by_name["s"] == "string"
    assert by_name["z"] == "bytes"
    assert by_name["l"] == {"type": "array", "items": "long"}
    assert by_name["m"] == {"type": "map", "values": "string"}
    assert by_name["n"] == ["null", "boolean", "long", "double",
                            "bytes", "string"]


def test_read_avro_dataset(tmp_path):
    rows = _rows()
    p1 = str(tmp_path / "a.avro")
    p2 = str(tmp_path / "b.avro")
    write_avro_file(rows[:10], p1)
    write_avro_file(rows[10:], p2, codec="deflate")
    ds = read_avro([p1, p2])
    assert ds.take_all() == rows


def test_deflate_is_raw_rfc1951():
    """The deflate codec must be headerless (no zlib wrapper) per the
    avro spec — decompressible with wbits=-15 only."""
    data = write_avro(_rows(), codec="deflate")
    # find first block payload: after magic+meta+sync
    # (we only check the writer used raw deflate by re-reading)
    assert list(iter_avro(data)) == _rows()


def test_long_schema_rejects_float_drift():
    """A float sneaking into a column inferred as long must raise, not
    silently truncate."""
    with pytest.raises(TypeError, match="long"):
        write_avro([{"x": 1}, {"x": 2.7}])


def test_fixed_length_validated():
    schema = {"type": "record", "name": "r", "fields": [
        {"name": "sig", "type": {"type": "fixed", "name": "Sig",
                                 "size": 4}}]}
    with pytest.raises(ValueError, match="4 bytes"):
        write_avro([{"sig": b"abc"}], schema)


def test_null_first_row_column_holds_any_primitive():
    rows = [{"a": None}, {"a": 5}, {"a": 2.5}, {"a": "s"},
            {"a": True}, {"a": b"b"}]
    assert list(iter_avro(write_avro(rows))) == rows


def test_numpy_scalars_write_losslessly():
    import numpy as np

    rows = [{"i": np.int64(7), "f": np.float32(0.5),
             "b": np.bool_(True)}]
    got = list(iter_avro(write_avro(rows)))
    assert got == [{"i": 7, "f": 0.5, "b": True}]
