"""The quickstart examples must stay runnable (reference analog: doc
examples exercised in CI). Each runs as a fresh subprocess — the same
way a user would hit them."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(module: str, *, devices: int = 1, timeout: int = 420):
    env = dict(os.environ)
    prior = env.get("PYTHONPATH")
    env["PYTHONPATH"] = (_REPO + os.pathsep + prior) if prior else _REPO
    env["JAX_PLATFORMS"] = "cpu"
    if devices > 1:
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devices}")
    proc = subprocess.run(
        [sys.executable, "-m", module], env=env, cwd=_REPO,
        capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (
        f"{module} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    return proc.stdout


def test_data_pipeline_example():
    out = _run("ray_tpu.examples.data_pipeline")
    assert "mean(y)" in out and "Dataset execution" in out


def test_serve_quickstart_example():
    out = _run("ray_tpu.examples.serve_quickstart")
    assert "direct call: {'sum': 12.0}" in out
    assert "'sum': 18.0" in out


def test_rllib_quickstart_example():
    out = _run("ray_tpu.examples.rllib_quickstart")
    assert "iter 10" in out


@pytest.mark.slow
def test_train_llama_example():
    """Runs by default (CI exercises it); skip locally with
    ``pytest -m 'not slow'``."""
    out = _run("ray_tpu.examples.train_llama", devices=8)
    assert "'loss':" in out


def test_llm_serving_example():
    out = _run("ray_tpu.examples.llm_serving")
    assert "llm serving quickstart: OK" in out
