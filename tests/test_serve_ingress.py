"""ASGI mounting + gRPC ingress (reference: ``@serve.ingress(app)``
FastAPI mounting and the gRPC proxy, ``serve/_private/proxy.py:375``)."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def rt(ray_tpu_start):
    yield ray_tpu_start
    serve.shutdown()


async def echo_app(scope, receive, send):
    """Minimal ASGI app: routes on path/method, echoes the body — stands
    in for FastAPI/Starlette (any ASGI callable mounts the same way)."""
    assert scope["type"] == "http"
    msg = await receive()
    body = msg.get("body", b"")
    if scope["path"] == "/status":
        payload = b'{"status": "healthy"}'
        code = 200
    elif scope["method"] == "PUT":
        payload = b"put:" + body
        code = 201
    else:
        payload = (json.dumps({
            "method": scope["method"], "path": scope["path"],
            "echo": body.decode() if body else "",
        }).encode())
        code = 200
    await send({"type": "http.response.start", "status": code,
                "headers": [(b"content-type", b"application/json")]})
    await send({"type": "http.response.body", "body": payload})


def _http(method, url, body=None):
    req = urllib.request.Request(url, data=body, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_asgi_ingress_routes_raw_requests(rt):
    @serve.deployment(name="asgiapp")
    @serve.ingress(echo_app)
    class App:
        pass

    serve.run(App.bind())
    _, (host, port) = serve.start_http_proxy()

    code, body = _http("GET", f"http://{host}:{port}/asgiapp/status")
    assert code == 200 and json.loads(body) == {"status": "healthy"}

    code, body = _http("POST", f"http://{host}:{port}/asgiapp/predict",
                       body=b"data")
    assert code == 200
    out = json.loads(body)
    assert out["method"] == "POST" and out["path"] == "/predict"
    assert out["echo"] == "data"

    code, body = _http("PUT", f"http://{host}:{port}/asgiapp/thing",
                       body=b"xyz")
    assert code == 201 and body == b"put:xyz"


def test_asgi_handle_call_becomes_post(rt):
    @serve.deployment(name="asgih")
    @serve.ingress(echo_app)
    class App:
        pass

    handle = serve.run(App.bind())
    out = handle.call({"k": 1})
    assert out["status"] == 200
    echoed = json.loads(out["body"])
    assert json.loads(echoed["echo"]) == {"k": 1}


def test_plain_deployment_keeps_json_contract(rt):
    @serve.deployment(name="plainj")
    class Plain:
        def __call__(self, payload):
            return {"doubled": payload.get("x", 0) * 2}

    serve.run(Plain.bind())
    _, (host, port) = serve.start_http_proxy()
    code, body = _http("POST", f"http://{host}:{port}/plainj",
                       body=json.dumps({"x": 21}).encode())
    assert code == 200
    assert json.loads(body)["result"] == {"doubled": 42}


def test_grpc_ingress(rt):
    grpc = pytest.importorskip("grpc")
    from ray_tpu.serve.ingress import GRPC_SERVICE, grpc_call

    @serve.deployment(name="grpcd")
    class D:
        def __call__(self, payload):
            return {"sum": payload["a"] + payload["b"]}

    serve.run(D.bind())
    server, port = serve.start_grpc_proxy()
    try:
        out = grpc_call(port, "grpcd", {"a": 2, "b": 40})
        assert out["result"] == {"sum": 42}

        with pytest.raises(grpc.RpcError) as err:
            grpc_call(port, "nope", {})
        assert err.value.code() == grpc.StatusCode.NOT_FOUND
    finally:
        server.stop(0)
