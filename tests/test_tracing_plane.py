"""Distributed tracing plane (round 9): RPC-level context propagation,
cluster span collection through the metrics pusher into the GCS
TraceStore, the serve one-trace acceptance, the stuck-call watchdog,
the flight recorder, and the < 3% tracing-enabled hot-path gate.

Reference analog: util/tracing/tracing_helper.py (OpenTelemetry
export); here spans ride the repo's own metrics plane instead — see
docs/tracing_plane.md for the divergence rationale."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import tracing


@pytest.fixture(autouse=True)
def _tracing_off_after():
    yield
    tracing.disable_tracing()


# ---------------------------------------------------------------------------
# propagation: the _trace header on framed RPCs
# ---------------------------------------------------------------------------

def test_rpc_carries_trace_context():
    """A client call made inside a span restores that span's trace as
    the ambient context in the server handler (rpc.py `_trace` header +
    server_span), so server-side spans parent across the wire."""
    from ray_tpu.runtime.rpc import RpcClient, RpcServer

    seen = {}

    class Srv(RpcServer):
        def rpc_probe(self, conn, send_lock):
            ctx = tracing.current_context()
            seen["ctx"] = (ctx.trace_id, ctx.span_id) if ctx else None
            return "ok"

    tracing.enable_tracing()
    srv = Srv("127.0.0.1", 0).start()
    client = RpcClient(srv.address)
    try:
        with tracing.span("client-root") as root:
            assert client.call("probe") == "ok"
        assert seen["ctx"] is not None
        assert seen["ctx"][0] == root.trace_id
        # the server-side span landed in the flight ring with the
        # client's trace id and the rpc: naming convention
        spans = tracing.local_trace(root.trace_id)
        assert any(s["name"] == "rpc:probe" for s in spans)
    finally:
        client.close()
        srv.stop()


def test_untraced_rpc_has_no_header():
    """With no ambient span the request carries no `_trace` key and the
    handler sees no context — the untraced path stays untouched."""
    from ray_tpu.runtime.rpc import RpcClient, RpcServer

    seen = {}

    class Srv(RpcServer):
        def rpc_probe(self, conn, send_lock):
            seen["ctx"] = tracing.current_context()
            return "ok"

    tracing.enable_tracing()
    srv = Srv("127.0.0.1", 0).start()
    client = RpcClient(srv.address)
    try:
        assert client.call("probe") == "ok"
        assert seen["ctx"] is None
    finally:
        client.close()
        srv.stop()


# ---------------------------------------------------------------------------
# push ring + TraceStore (collection layer, no cluster needed)
# ---------------------------------------------------------------------------

def test_span_ring_bounded_drop_not_block():
    from ray_tpu.utils.config import get_config

    tracing.enable_tracing()
    tracing.drain_spans()                     # start from empty
    cap = get_config().trace_buffer_spans
    for i in range(cap + 50):
        tracing.emit(f"s{i}", start=time.time(), duration=0.0)
    drained = tracing.drain_spans(max_n=cap + 100)
    assert len(drained) <= cap                # oldest dropped, no growth
    # requeue is bounded too: re-draining returns what fits
    tracing.requeue_spans(drained)
    assert len(tracing.drain_spans(max_n=cap + 100)) <= cap


def test_trace_store_tail_retention():
    """Eviction order under pressure: unsampled normals first, then
    sampled normals, then (only if it must) error/slow traces —
    tail-based sampling keeps what an operator would want to read."""
    store = tracing.TraceStore(max_traces=4, max_spans=1000,
                               sample_n=10**9,   # no normal survives
                               slow_s=0.5)

    def spans_for(tid, *, error=False, dur=0.0, at=0.0):
        return [{"trace_id": tid, "span_id": f"{tid}-s", "name": "root",
                 "start": at, "duration": dur, "error": error}]

    for i in range(4):
        store.ingest("t", spans_for(f"{i:032x}", at=float(i)))
    # an error trace and a slow trace push two normals out
    store.ingest("t", spans_for("e" * 32, error=True, at=10.0))
    store.ingest("t", spans_for("f" * 32, dur=2.0, at=11.0))
    held = {s["trace_id"] for s in store.list(limit=10)}
    assert "e" * 32 in held and "f" * 32 in held
    assert len(held) <= 4
    st = store.stats()
    assert st["evicted_traces"] >= 2


def test_trace_store_per_trace_span_cap():
    store = tracing.TraceStore(max_traces=4, max_spans=10**6,
                               sample_n=1, slow_s=10.0,
                               per_trace_spans=8)
    tid = "a" * 32
    store.ingest("t", [{"trace_id": tid, "span_id": f"s{i}",
                        "name": f"n{i}", "start": float(i),
                        "duration": 0.0} for i in range(50)])
    assert len(store.get(tid)["spans"]) <= 8


def test_waterfall_rows():
    t0 = 100.0
    spans = [
        {"trace_id": "t", "span_id": "a", "name": "root", "start": t0,
         "duration": 0.3},
        {"trace_id": "t", "span_id": "b", "parent_id": "a",
         "name": "child", "start": t0 + 0.1, "duration": 0.1},
    ]
    rows = tracing.build_waterfall(spans)
    assert [r["name"] for r in rows] == ["root", "child"]
    assert rows[0]["depth"] == 0 and rows[1]["depth"] == 1
    assert rows[1]["offset_ms"] == pytest.approx(100.0)
    assert rows[1]["dur_ms"] == pytest.approx(100.0)


# ---------------------------------------------------------------------------
# stuck-call watchdog
# ---------------------------------------------------------------------------

def test_stuck_call_watchdog_sees_hung_rpc():
    """A deliberately-hung RPC appears in the in-flight registry with
    the trace/span ids of the span it was made under, and disappears
    once the reply lands (acceptance: injected hang -> stuck_calls())."""
    from ray_tpu.runtime.rpc import RpcClient, RpcServer

    release = {"t": 0.6}

    class Srv(RpcServer):
        def rpc_hang(self, conn, send_lock):
            time.sleep(release["t"])
            return "done"

    tracing.enable_tracing()
    srv = Srv("127.0.0.1", 0).start()
    client = RpcClient(srv.address, timeout=10)
    try:
        with tracing.span("caller") as root:
            pending = client.call_async("hang")
            time.sleep(0.2)
            stuck = tracing.local_stuck_calls(0.1)
            hung = [c for c in stuck if c["detail"] == "hang"]
            assert hung, stuck
            assert hung[0]["kind"] == "rpc"
            assert hung[0]["age_s"] >= 0.1
            assert hung[0]["trace_id"] == root.trace_id
            # the public API surfaces the same registry
            from ray_tpu.util import state as state_api

            out = state_api.stuck_calls(threshold_s=0.1)
            assert any(c["detail"] == "hang" for c in out["driver"])
            assert pending.result() == "done"
        # reply landed -> registry entry cleared
        assert not [c for c in tracing.local_stuck_calls(0.0)
                    if c["detail"] == "hang"]
    finally:
        client.close()
        srv.stop()


def test_stuck_call_cleared_on_timeout():
    """A call that times out (server never answers) must not leak its
    registry entry — the timeout pop finishes the token."""
    import socket
    from ray_tpu.runtime.rpc import RpcClient

    srv = socket.create_server(("127.0.0.1", 0))   # accepts, never replies
    client = RpcClient(srv.getsockname(), timeout=0.3)
    try:
        with pytest.raises(Exception):
            client.call("never")
        assert not [c for c in tracing.local_stuck_calls(0.0)
                    if c["detail"] == "never"]
    finally:
        client.close()
        srv.close()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_snapshot_window_and_dump(tmp_path):
    tracing.enable_tracing(str(tmp_path))
    old = time.time() - 3600.0
    tracing.emit("ancient", start=old, duration=0.001)
    tracing.emit("fresh", start=time.time(), duration=0.001)
    tracing.record_event("marker", detail="x")
    snap = tracing.flight_snapshot(last_s=60.0)
    names = {s["name"] for s in snap["spans"]}
    assert "fresh" in names and "ancient" not in names
    assert any(e["event"] == "marker" for e in snap["events"])
    path = tracing.dump_flight(str(tmp_path / "dump.json"), last_s=60.0)
    dumped = json.load(open(path))
    assert dumped["pid"] == os.getpid()
    assert any(s["name"] == "fresh" for s in dumped["spans"])


def test_crash_dump_on_sigterm(tmp_path):
    """SIGTERM to a process with the crash handler installed leaves a
    flight-<pid>-*.json in the trace dir — no network involved, so it
    works through any partition."""
    code = (
        "import os, signal, time\n"
        "from ray_tpu.util import tracing\n"
        "tracing.enable_tracing(os.environ['TD'])\n"
        "tracing.install_crash_dump()\n"
        "with tracing.span('doomed'):\n"
        "    pass\n"
        "print('ready', flush=True)\n"
        "time.sleep(30)\n"
    )
    env = {**os.environ, "TD": str(tmp_path), "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen([sys.executable, "-c", code], env=env,
                            stdout=subprocess.PIPE)
    try:
        assert proc.stdout.readline().strip() == b"ready"
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=20)
    finally:
        if proc.poll() is None:
            proc.kill()
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith(f"flight-{proc.pid}-")]
    assert dumps, os.listdir(tmp_path)
    snap = json.load(open(tmp_path / dumps[0]))
    assert any(s["name"] == "doomed" for s in snap["spans"])


def test_flight_record_state_api_local():
    from ray_tpu.util import state as state_api

    tracing.enable_tracing()
    tracing.emit("local-span", start=time.time(), duration=0.001)
    out = state_api.flight_record()
    assert "local" in out
    assert any(s["name"] == "local-span" for s in out["local"]["spans"])


# ---------------------------------------------------------------------------
# bounded file exporter (satellite)
# ---------------------------------------------------------------------------

def test_span_file_rotation(tmp_path, monkeypatch):
    from ray_tpu.utils.config import get_config

    tracing.enable_tracing(str(tmp_path))
    monkeypatch.setattr(get_config(), "trace_file_max_bytes", 4096)
    for i in range(400):
        tracing.emit(f"rotate-me-{i}", start=time.time(), duration=0.0,
                     attrs={"pad": "x" * 64})
    live = tmp_path / f"spans-{os.getpid()}.jsonl"
    rolled = tmp_path / f"spans-{os.getpid()}.jsonl.1"
    assert rolled.exists()
    # the live file may have just rotated away entirely; when present
    # it respects the cap (plus one record of slack)
    if live.exists():
        assert live.stat().st_size <= 4096 + 4096
    # iter_spans streams rotated-then-live so order is oldest-first and
    # nothing is lost beyond the single-generation rotation bound
    names = [s["name"] for s in tracing.iter_spans(str(tmp_path))]
    assert names
    assert names[-1] == "rotate-me-399"
    idx = [int(n.split("-")[-1]) for n in names]
    assert idx == sorted(idx)


def test_chrome_export_stable_sorted(tmp_path):
    tracing.enable_tracing(str(tmp_path))
    now = time.time()
    with tracing.span("b-span"):
        pass
    tracing.emit("a-span", start=now, duration=0.001)
    ev1 = tracing.export_chrome_trace(str(tmp_path))
    ev2 = tracing.export_chrome_trace(str(tmp_path))
    assert ev1 == ev2                          # deterministic re-export
    xs = [e for e in ev1 if e.get("ph") == "X"]
    assert xs == sorted(xs, key=lambda e: (e["ts"], e["pid"],
                                           e["name"]))


# ---------------------------------------------------------------------------
# overhead gate: tracing-enabled hot path < 3% (PR-4 methodology:
# amortized factor measurement, not end-to-end wall-clock diffing)
# ---------------------------------------------------------------------------

def test_tracing_enabled_hot_path_overhead():
    """Gate: with RAY_TPU_TRACE_ENABLED=1 but no ambient span (the
    steady state of every hot path — spans only exist inside explicitly
    traced requests), RPC dispatch pays one wire_context() probe per
    call. Measure the real per-call RPC cost and the probe cost
    separately (each stable under min-of-k; an end-to-end diff of two
    network loops cannot resolve a ~100ns probe) and gate the ratio."""
    from ray_tpu.runtime.rpc import RpcClient, RpcServer

    class Srv(RpcServer):
        def rpc_echo(self, conn, send_lock, *, x):
            return x

    srv = Srv("127.0.0.1", 0).start()
    client = RpcClient(srv.address)
    try:
        def rpc_loop(n=300):
            t0 = time.perf_counter()
            for i in range(n):
                client.call("echo", x=i)
            return (time.perf_counter() - t0) / n

        def probe_cost(n=200000):
            tracing.enable_tracing()
            t0 = time.perf_counter()
            for _ in range(n):
                tracing.wire_context()
            t1 = time.perf_counter()
            tracing.disable_tracing()
            t2 = time.perf_counter()
            for _ in range(n):
                tracing.wire_context()
            t3 = time.perf_counter()
            return ((t1 - t0) - (t3 - t2)) / n

        tracing.disable_tracing()
        rpc_loop(50)                          # warm
        probe_cost(1000)
        t_op = min(rpc_loop() for _ in range(3))
        t_delta = min(probe_cost() for _ in range(5))
        overhead = t_delta / t_op
        assert overhead < 0.03, \
            f"trace probe costs {overhead:.2%}/RPC (gate: 3%): " \
            f"{t_delta*1e9:.0f}ns probe on a {t_op*1e6:.0f}us call"
    finally:
        client.close()
        srv.stop()


# ---------------------------------------------------------------------------
# cluster acceptance: spans collected across processes into the GCS
# ---------------------------------------------------------------------------

@pytest.fixture
def traced_cluster(monkeypatch):
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.utils.config import reset_config

    monkeypatch.setenv("RAY_TPU_TRACE_ENABLED", "1")
    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.2")
    reset_config()
    tracing.enable_tracing()
    ray_tpu.shutdown()
    c = Cluster(external_gcs=True)
    c.add_node(num_cpus=2, external=True)
    ray_tpu.init(address=c.gcs_address)
    c.wait_for_nodes(1)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    tracing.disable_tracing()
    reset_config()


def test_cluster_trace_collected_by_id(traced_cluster):
    """The tentpole acceptance (tasks): one driver-rooted trace whose
    submit-side and worker-side spans cross process boundaries, pushed
    by each process's MetricsPusher, retrievable from the GCS
    TraceStore by trace id via util.state."""
    from ray_tpu import api
    from ray_tpu.util import state as state_api

    @ray_tpu.remote
    def traced_task(x):
        return x * 2

    with tracing.span("driver-root") as root:
        assert ray_tpu.get(traced_task.remote(21), timeout=60) == 42
    tid = root.trace_id

    rt = api._runtime()
    deadline = time.monotonic() + 30
    trace = None
    while time.monotonic() < deadline:
        rt._metrics_pusher.flush_now()        # driver spans -> GCS now
        trace = state_api.get_trace(tid)
        if trace and len(trace["spans"]) >= 3:
            names = {s["name"] for s in trace["spans"]}
            if (any(n.startswith("run:") for n in names)
                    and any(n.startswith("submit:") for n in names)):
                break
        time.sleep(0.25)
    assert trace is not None, "trace never reached the GCS store"
    names = {s["name"] for s in trace["spans"]}
    assert "driver-root" in names
    assert any(n.startswith("submit:") and n.endswith("traced_task")
               for n in names), names
    assert any(n.startswith("run:") and n.endswith("traced_task")
               for n in names), names
    # spans arrived from more than one process
    assert len({s["pid"] for s in trace["spans"]}) >= 2
    # and the listing surfaces it newest-first with the root name
    listed = state_api.list_traces(limit=20)
    assert any(t["trace_id"] == tid for t in listed)


def test_cluster_actor_call_traced_and_stuck_visible(traced_cluster):
    """A deliberately slow actor method shows up in the cluster-wide
    stuck_calls() fan-out — the executing WORKER's always-on in-flight
    registry — carrying the trace id of the span it was called under
    (acceptance: hung call appears with its parent span chain)."""
    from ray_tpu.util import state as state_api

    @ray_tpu.remote
    class Slow:
        def work(self, t):
            time.sleep(t)
            return "done"

    a = Slow.remote()
    ray_tpu.get(a.work.remote(0.0), timeout=60)    # actor is up
    with tracing.span("actor-root") as root:
        ref = a.work.remote(3.0)
        time.sleep(0.8)
        mine = []
        deadline = time.monotonic() + 6
        while time.monotonic() < deadline and not mine:
            out = state_api.stuck_calls(threshold_s=0.3)
            for procs in out.get("nodes", {}).values():
                if not isinstance(procs, dict):
                    continue
                for calls in procs.values():
                    if not isinstance(calls, list):
                        continue
                    mine += [c for c in calls
                             if c["kind"] == "actor_task"
                             and "work" in c["detail"]]
            if not mine:
                time.sleep(0.3)
        assert mine, out
        assert mine[0]["trace_id"] == root.trace_id
        assert mine[0]["age_s"] >= 0.3
        assert ray_tpu.get(ref, timeout=60) == "done"
    # finished execution left the registry
    out = state_api.stuck_calls(threshold_s=0.0)
    for procs in out.get("nodes", {}).values():
        if isinstance(procs, dict):
            for calls in procs.values():
                if isinstance(calls, list):
                    assert not [c for c in calls
                                if c["kind"] == "actor_task"
                                and "work" in c["detail"]]


def test_cluster_flight_record_and_gcs_endpoints(traced_cluster):
    """flight_record("gcs") and per-node flight_record(node_id) answer
    over RPC; the GCS's own spans are collected by its self-loop."""
    from ray_tpu import api
    from ray_tpu.util import state as state_api

    @ray_tpu.remote
    def ping():
        return 1

    with tracing.span("flight-root"):
        assert ray_tpu.get(ping.remote(), timeout=60) == 1

    out = state_api.flight_record("gcs")
    assert "gcs" in out and "pid" in out["gcs"]
    rt = api._runtime()
    nodes = rt._gcs.call("get_nodes", alive_only=True)
    assert nodes
    nid = nodes[0]["node_id"]
    out = state_api.flight_record(nid)
    assert nid in out
    # raylet answer carries its own window plus its workers'
    assert "raylet" in out[nid]


# ---------------------------------------------------------------------------
# serve acceptance: ONE trace across handle -> router -> replica ->
# engine, with stage child spans summing into the traced TTFT
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_serve_request_is_one_trace(ray_tpu_start, tmp_path):
    import jax
    from ray_tpu import serve
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMDeployment
    from ray_tpu.util import state as state_api

    def tiny_builder():
        cfg = llama.llama_tiny()
        return cfg, llama.init_params(cfg, jax.random.key(0))

    tracing.enable_tracing(str(tmp_path))
    try:
        dep = serve.deployment(LLMDeployment).bind(
            tiny_builder, max_batch=2, max_len=64)
        handle = serve.run(dep, name="llm_traced")
        got = handle.call([3, 17, 99], max_new_tokens=4)
        assert len(got) == 4

        # engine stage spans are emitted when the first token's async
        # copy lands; give the drain a beat
        deadline = time.monotonic() + 20
        spans = []
        while time.monotonic() < deadline:
            spans = [s for s in tracing.read_spans(str(tmp_path))
                     if s["name"].startswith(("serve.", "engine."))]
            if any(s["name"] == "engine.prefill" for s in spans):
                break
            time.sleep(0.2)
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], s)
        for required in ("serve.request:llm_traced", "serve.route",
                         "engine.request", "engine.queue_wait",
                         "engine.prefill"):
            assert required in by_name, sorted(by_name)
        # ONE trace: every serve/engine span shares the request root
        tid = by_name["serve.request:llm_traced"]["trace_id"]
        assert {s["trace_id"] for s in spans} == {tid}
        # the replica-side run span is in the same trace too
        run = [s for s in tracing.read_spans(str(tmp_path))
               if s["trace_id"] == tid and s["name"].startswith("run:")]
        assert run, "replica execution span missing from the trace"
        # stage children tile the engine.request parent (traced TTFT)
        req = by_name["engine.request"]
        stage_sum = sum(s["duration"] for s in spans
                        if s["name"].startswith("engine.")
                        and s["name"] != "engine.request")
        assert stage_sum == pytest.approx(req["duration"], rel=0.05)
        assert by_name["engine.queue_wait"]["parent_id"] == \
            req["span_id"]

        # retrievable by id via util.state and rendered by the
        # dashboard waterfall endpoint
        trace = state_api.get_trace(tid)
        assert trace is not None and trace["trace_id"] == tid
        from ray_tpu.dashboard import Dashboard

        dash = Dashboard(port=0).start()
        try:
            with urllib.request.urlopen(
                    f"{dash.url}/api/trace/{tid}", timeout=10) as r:
                body = json.loads(r.read())
            assert body["trace"]["trace_id"] == tid
            rows = body["waterfall"]
            assert any(r_["name"] == "engine.prefill" for r_ in rows)
            depth = {r_["name"]: r_["depth"] for r_ in rows}
            assert depth["serve.request:llm_traced"] == 0
            assert depth["engine.prefill"] > depth["engine.request"] - 1
        finally:
            dash.stop()
    finally:
        serve.shutdown()
