"""Sanitizer builds of the native store (SURVEY §5 race detection):
`make asan` / `make tsan` compile the C++ store + a unit/stress driver
under AddressSanitizer / ThreadSanitizer and run it. Slow-ish (two
compiles), so it runs as one test per sanitizer."""

import os
import shutil
import subprocess

import pytest

SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src")


@pytest.mark.parametrize("target", ["asan", "tsan"])
def test_store_under_sanitizer(target):
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no native toolchain")
    proc = subprocess.run(["make", "-C", SRC, target],
                          capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "store_test ok" in proc.stdout


def test_scheduler_native_unit_driver():
    """The scheduling-policy C++ unit driver (reference analog:
    hybrid_scheduling_policy_test.cc) builds and passes."""
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no native toolchain")
    proc = subprocess.run(["make", "-C", SRC, "sched_test"],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "all tests passed" in proc.stdout
