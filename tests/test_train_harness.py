"""Train harness + Tune tests (reference: train/tests/, tune/tests/)."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import train as rtrain
from ray_tpu import tune as rtune
from ray_tpu.air.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def test_data_parallel_fit_reports(rt, tmp_path):
    def loop(config):
        ctx = rtrain.get_context()
        for i in range(3):
            rtrain.report({"loss": 1.0 / (i + 1), "rank": ctx.rank})

    trainer = rtrain.DataParallelTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["rank"] == 0
    assert len(result.metrics_history) == 3
    assert result.metrics["loss"] == pytest.approx(1 / 3)


def test_worker_ranks_distinct(rt, tmp_path):
    def loop(config):
        ctx = rtrain.get_context()
        rtrain.report({"rank": ctx.rank, "world": ctx.world_size})

    trainer = rtrain.DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=3),
        run_config=RunConfig(storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["world"] == 3


def test_failure_config_retries(rt, tmp_path):
    marker = tmp_path / "failed_once"

    def flaky(config):
        if not os.path.exists(str(marker)):
            open(str(marker), "w").close()
            raise RuntimeError("transient-failure")
        rtrain.report({"ok": 1})

    trainer = rtrain.DataParallelTrainer(
        flaky, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path / "exp"),
                             failure_config=FailureConfig(max_failures=1)))
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["ok"] == 1


def test_checkpoint_topk(rt, tmp_path):
    def loop(config):
        ctx = rtrain.get_context()
        for i in range(4):
            ckpt = os.path.join(ctx.trial_dir, f"ckpt_{i}")
            os.makedirs(ckpt, exist_ok=True)
            with open(os.path.join(ckpt, "score"), "w") as f:
                f.write(str(i))
            rtrain.report({"score": float(i)}, checkpoint_dir=ckpt)

    trainer = rtrain.DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(
                num_to_keep=2, checkpoint_score_attribute="score")))
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint_dir.endswith("ckpt_3")  # best retained
    assert open(os.path.join(result.checkpoint_dir, "score")).read() == "3"


def test_dataset_ingest(rt, tmp_path):
    from ray_tpu import data as rdata

    ds = rdata.range(64, num_blocks=8)

    def loop(config):
        shard = config["train_shard"]
        total = sum(int(b["id"].sum())
                    for b in shard.iter_batches(batch_size=8))
        rtrain.report({"total": total})

    trainer = rtrain.DataParallelTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
        datasets={"train": ds})
    result = trainer.fit()
    assert result.error is None


def test_error_surfaces(rt, tmp_path):
    def bad(config):
        raise ValueError("broken loop")

    trainer = rtrain.DataParallelTrainer(
        bad, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path)))
    result = trainer.fit()
    assert result.error is not None
    assert "broken loop" in result.error


# ---------------------------------------------------------------------------
# Tune
# ---------------------------------------------------------------------------

def test_tuner_grid_search(rt, tmp_path):
    def trainable(config):
        rtrain.report({"score": config["x"] * 10})

    tuner = rtune.Tuner(
        trainable,
        param_space={"x": rtune.grid_search([1, 2, 3])},
        tune_config=rtune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result("score", "max")
    assert best.config["x"] == 3
    assert best.last_result["score"] == 30


def test_tuner_random_sampling(rt, tmp_path):
    def trainable(config):
        rtrain.report({"y": config["lr"]})

    tuner = rtune.Tuner(
        trainable,
        param_space={"lr": rtune.loguniform(1e-5, 1e-1)},
        tune_config=rtune.TuneConfig(num_samples=4, seed=0),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 4
    lrs = [t.last_result["y"] for t in grid]
    assert all(1e-5 <= lr <= 1e-1 for lr in lrs)
    assert len(set(lrs)) == 4


def test_asha_stops_bad_trials(rt, tmp_path):
    def trainable(config):
        for i in range(1, 10):
            rtrain.report({"acc": config["quality"] * i})

    sched = rtune.AsyncHyperBandScheduler(
        metric="acc", mode="max", grace_period=2, max_t=9,
        reduction_factor=2)
    tuner = rtune.Tuner(
        trainable,
        param_space={"quality": rtune.grid_search([0.1, 0.2, 0.9, 1.0])},
        tune_config=rtune.TuneConfig(scheduler=sched,
                                     max_concurrent_trials=4),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    stopped = [t for t in grid if t.status == "STOPPED"]
    assert stopped, "ASHA should halt at least one low-quality trial"
    best = grid.get_best_result("acc", "max")
    assert best.config["quality"] in (0.9, 1.0)


def test_experiment_state_persisted(rt, tmp_path):
    import json

    def trainable(config):
        rtrain.report({"v": 1})

    rtune.Tuner(
        trainable, param_space={"x": rtune.grid_search([1, 2])},
        run_config=RunConfig(storage_path=str(tmp_path), name="exp1"),
    ).fit()
    state_file = tmp_path / "exp1" / "experiment_state.json"
    state = json.loads(state_file.read_text())
    assert len(state) == 2
    assert all(t["status"] == "TERMINATED" for t in state)
