"""Numerics tests for ops and the Llama model on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama
from ray_tpu.ops.attention import reference_attention
from ray_tpu.ops.norms import layer_norm, rms_norm
from ray_tpu.ops.rope import apply_rope, rope_sin_cos


def test_rms_norm_matches_manual():
    x = jax.random.normal(jax.random.key(0), (4, 16), dtype=jnp.float32)
    w = jax.random.normal(jax.random.key(1), (16,)) * 0.1 + 1.0
    got = rms_norm(x, w)
    want = x / np.sqrt(np.mean(np.square(np.asarray(x)), -1, keepdims=True) + 1e-5)
    want = want * np.asarray(w)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


def test_rms_norm_bf16_stats_in_fp32():
    x = (jnp.ones((2, 8)) * 300.0).astype(jnp.bfloat16)  # squares overflow-ish in bf16
    w = jnp.ones((8,), dtype=jnp.bfloat16)
    out = rms_norm(x, w)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.ones((2, 8)), rtol=1e-2)


def test_layer_norm():
    x = jax.random.normal(jax.random.key(0), (3, 32))
    out = np.asarray(layer_norm(x, jnp.ones(32), jnp.zeros(32)))
    np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(out.std(-1), 1.0, atol=1e-2)


def test_rope_preserves_norm_and_relative_shift():
    b, s, h, d = 1, 8, 2, 16
    x = jax.random.normal(jax.random.key(0), (b, s, h, d))
    pos = jnp.arange(s)[None, :]
    sin, cos = rope_sin_cos(pos, d, theta=10000.0)
    rx = apply_rope(x, sin, cos)
    # rotation preserves pairwise norms
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(rx), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5,
    )
    # dot(q_i, k_j) depends only on i-j: shift both positions by 3
    q = jax.random.normal(jax.random.key(1), (1, 1, 1, d))
    k = jax.random.normal(jax.random.key(2), (1, 1, 1, d))
    def dot_at(pi, pj):
        sq, cq = rope_sin_cos(jnp.array([[pi]]), d, theta=10000.0)
        sk, ck = rope_sin_cos(jnp.array([[pj]]), d, theta=10000.0)
        return float(jnp.sum(apply_rope(q, sq, cq) * apply_rope(k, sk, ck)))
    assert dot_at(5, 2) == pytest.approx(dot_at(8, 5), rel=1e-4)


def _naive_attention(q, k, v, causal=True):
    b, s, h, d = q.shape
    nkv = k.shape[2]
    k = np.repeat(np.asarray(k), h // nkv, axis=2)
    v = np.repeat(np.asarray(v), h // nkv, axis=2)
    out = np.zeros_like(np.asarray(q), dtype=np.float32)
    for bi in range(b):
        for hi in range(h):
            logits = np.asarray(q)[bi, :, hi] @ k[bi, :, hi].T / np.sqrt(d)
            if causal:
                mask = np.tril(np.ones((s, s), dtype=bool))
                logits = np.where(mask, logits, -1e30)
            p = np.exp(logits - logits.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            out[bi, :, hi] = p @ v[bi, :, hi]
    return out


def test_reference_attention_matches_naive():
    rng = jax.random.key(0)
    q = jax.random.normal(rng, (2, 16, 4, 8), dtype=jnp.float32)
    k = jax.random.normal(jax.random.key(1), (2, 16, 2, 8), dtype=jnp.float32)
    v = jax.random.normal(jax.random.key(2), (2, 16, 2, 8), dtype=jnp.float32)
    got = np.asarray(reference_attention(q, k, v, causal=True))
    want = _naive_attention(q, k, v, causal=True)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_attention_causality():
    # Output at position t must not change when future tokens change.
    q = jax.random.normal(jax.random.key(0), (1, 8, 2, 8))
    k = jax.random.normal(jax.random.key(1), (1, 8, 2, 8))
    v = jax.random.normal(jax.random.key(2), (1, 8, 2, 8))
    out1 = reference_attention(q, k, v, causal=True)
    k2 = k.at[:, 5:].set(99.0)
    v2 = v.at[:, 5:].set(-99.0)
    out2 = reference_attention(q, k2, v2, causal=True)
    np.testing.assert_allclose(np.asarray(out1[:, :5]), np.asarray(out2[:, :5]),
                               rtol=1e-5)


def test_attention_segment_mask():
    # Tokens in segment 2 must not attend to segment 1.
    q = jax.random.normal(jax.random.key(0), (1, 8, 2, 8))
    k = jax.random.normal(jax.random.key(1), (1, 8, 2, 8))
    v = jax.random.normal(jax.random.key(2), (1, 8, 2, 8))
    seg = jnp.array([[1, 1, 1, 1, 2, 2, 2, 2]])
    out = reference_attention(q, k, v, causal=True, segment_ids=seg)
    # position 4 (first of segment 2) attends only to itself
    k_only = k[:, 4:5]
    v_only = v[:, 4:5]
    solo = reference_attention(q[:, 4:5], k_only, v_only, causal=True)
    np.testing.assert_allclose(np.asarray(out[:, 4]), np.asarray(solo[:, 0]),
                               rtol=1e-5)


def test_soft_cap():
    q = jax.random.normal(jax.random.key(0), (1, 4, 1, 8)) * 10
    k = jax.random.normal(jax.random.key(1), (1, 4, 1, 8)) * 10
    v = jax.random.normal(jax.random.key(2), (1, 4, 1, 8))
    out = reference_attention(q, k, v, causal=True, logits_soft_cap=5.0)
    assert np.isfinite(np.asarray(out)).all()


# --- model ---


def test_llama_forward_shapes_and_finite():
    cfg = llama.llama_tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    logits = llama.forward(cfg, params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_llama_param_axes_align():
    cfg = llama.llama_tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    axes = llama.param_logical_axes(cfg)
    flat_p = jax.tree.leaves_with_path(params)
    axes_map = {jax.tree_util.keystr(kp): v
                for kp, v in jax.tree.leaves_with_path(
                    axes, is_leaf=lambda x: isinstance(x, tuple))}
    for kp, leaf in flat_p:
        key = jax.tree_util.keystr(kp)
        assert key in axes_map, f"missing logical axes for {key}"
        assert len(axes_map[key]) == leaf.ndim, (
            f"{key}: {axes_map[key]} vs shape {leaf.shape}"
        )


def test_llama_causal_property():
    cfg = llama.llama_tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    t1 = jax.random.randint(jax.random.key(1), (1, 12), 0, cfg.vocab_size)
    t2 = t1.at[0, 8:].set(7)  # change the future
    l1 = llama.forward(cfg, params, t1)
    l2 = llama.forward(cfg, params, t2)
    np.testing.assert_allclose(np.asarray(l1[0, :8]), np.asarray(l2[0, :8]),
                               rtol=2e-3, atol=2e-3)


def test_cross_entropy_and_training_step_reduces_loss():
    import optax

    cfg = llama.llama_tiny(vocab_size=64)
    params = llama.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, 64)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]

    opt = optax.adamw(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits = llama.forward(cfg, p, inputs)
            return llama.cross_entropy_loss(logits, targets)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_loss_mask():
    logits = jnp.zeros((1, 4, 8))
    targets = jnp.zeros((1, 4), dtype=jnp.int32)
    mask = jnp.array([[1, 1, 0, 0]])
    full = llama.cross_entropy_loss(logits, targets)
    masked = llama.cross_entropy_loss(logits, targets, mask=mask)
    assert full == pytest.approx(np.log(8), rel=1e-5)
    assert masked == pytest.approx(np.log(8), rel=1e-5)


def test_llama_sharded_forward_matches_unsharded(cpu_mesh_devices):
    from ray_tpu.parallel.mesh import create_mesh
    from ray_tpu.parallel.sharding import (
        PRESETS, batch_sharding, shard_tree, tree_shardings,
    )

    cfg = llama.llama_tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)
    want = np.asarray(llama.forward(cfg, params, tokens))

    mesh = create_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    rules = PRESETS["fsdp_tp"]
    axes = llama.param_logical_axes(cfg)
    sharded_params = shard_tree(params, axes, mesh, rules)
    sharded_tokens = jax.device_put(tokens, batch_sharding(mesh, rules))

    @jax.jit
    def fwd(p, t):
        return llama.forward(cfg, p, t)

    got = np.asarray(fwd(sharded_params, sharded_tokens))
    # bf16 intermediates: sharded matmuls reduce in a different order, so
    # allow small absolute noise and require near-perfect correlation.
    np.testing.assert_allclose(got, want, rtol=0.1, atol=0.1)
    corr = np.corrcoef(got.ravel(), want.ravel())[0, 1]
    assert corr > 0.9999, corr


def test_fused_cross_entropy_matches_dense():
    """Chunked fused CE must match forward()+cross_entropy_loss exactly
    (same math, different materialization), including value AND grads."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import llama

    cfg = llama.LlamaConfig(vocab_size=96, d_model=16, n_layers=1,
                            n_heads=2, n_kv_heads=2, d_ff=32, head_dim=8,
                            remat="none", dtype="float32")
    params = llama.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    batch = rng.integers(0, 96, size=(2, 33)).astype(np.int32)
    inputs, targets = batch[:, :-1], batch[:, 1:]
    mask = np.ones_like(targets, np.float32)
    mask[:, -3:] = 0.0  # exercise masking

    def dense_loss(p):
        logits = llama.forward(cfg, p, inputs, attn_impl="reference")
        return llama.cross_entropy_loss(
            logits, jnp.maximum(jnp.asarray(targets), 0),
            mask=jnp.asarray(mask))

    def fused_loss(p):
        hidden = llama.forward_hidden(cfg, p, inputs,
                                      attn_impl="reference")
        return llama.fused_cross_entropy(
            cfg, p, hidden, jnp.asarray(targets), mask=jnp.asarray(mask),
            chunk=16)  # 64 tokens -> 4 chunks (not divisible: 64/16 ok)

    d_val, d_grad = jax.value_and_grad(dense_loss)(params)
    f_val, f_grad = jax.value_and_grad(fused_loss)(params)
    np.testing.assert_allclose(float(d_val), float(f_val), rtol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
        d_grad, f_grad)


def test_fused_cross_entropy_ragged_chunk():
    """Token count not divisible by chunk: padding must not change the
    masked mean."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import llama

    cfg = llama.LlamaConfig(vocab_size=64, d_model=8, n_layers=1,
                            n_heads=1, n_kv_heads=1, d_ff=16, head_dim=8,
                            remat="none", dtype="float32")
    params = llama.init_params(cfg, jax.random.key(1))
    rng = np.random.default_rng(1)
    inputs = rng.integers(0, 64, size=(1, 10)).astype(np.int32)
    targets = rng.integers(0, 64, size=(1, 10)).astype(np.int32)

    hidden = llama.forward_hidden(cfg, params, inputs,
                                  attn_impl="reference")
    f = llama.fused_cross_entropy(cfg, params, hidden,
                                  jnp.asarray(targets), chunk=4)  # 10 % 4 != 0
    logits = llama.forward(cfg, params, inputs, attn_impl="reference")
    d = llama.cross_entropy_loss(logits, jnp.asarray(targets))
    np.testing.assert_allclose(float(f), float(d), rtol=1e-5)
