"""Distributed reference counting + owner-scoped actor lifetime.

Reference analogs: ``src/ray/core_worker/reference_count.h:61-115``
(distributed refcounting / automatic reclamation) and
``src/ray/gcs/gcs_server/gcs_actor_manager.cc:632`` (non-detached actors
die with their owner). VERDICT round-3 done-criteria: a put/get/drop
loop holds shm usage flat, and a driver exit reaps its actors.
"""

import gc
import os
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=4)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _alloc(rt) -> int:
    return rt.store.stats()["bytes_allocated"]


def test_put_drop_soak_holds_shm_flat(cluster):
    """The round-3 leak: every primary was pinned forever; a put/drop
    loop grew shm until spill. Now dropped refs release the primary."""
    rt = ray_tpu.init(address=cluster.gcs_address)
    payload = b"x" * (1 << 20)
    base = None
    for i in range(60):
        ref = ray_tpu.put(payload)
        assert ray_tpu.get([ref])[0] == payload
        del ref
        if i == 20:
            gc.collect()
            time.sleep(1.5)
            base = _alloc(rt)
    gc.collect()
    time.sleep(2.0)
    final = _alloc(rt)
    # flat: everything released (a couple of MiB of slack for in-flight
    # releases; without refcounting this is ~40 MiB of growth)
    assert final <= max(base, 4 << 20), (base, final)


def test_task_returns_released_on_drop(cluster):
    rt = ray_tpu.init(address=cluster.gcs_address)

    @ray_tpu.remote
    def make():
        return b"r" * (1 << 20)

    refs = [make.remote() for _ in range(8)]
    assert all(len(v) == 1 << 20 for v in ray_tpu.get(refs, timeout=60))
    oids = [r.id.binary() for r in refs]
    del refs
    gc.collect()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if not any(rt.store.contains(o) for o in oids):
            break
        time.sleep(0.2)
    assert not any(rt.store.contains(o) for o in oids)


def test_args_pinned_while_task_runs(cluster):
    """Dropping the owner's last ref to an arg while a task still needs
    it must not free the object (submitted-task pin)."""
    ray_tpu.init(address=cluster.gcs_address)

    @ray_tpu.remote
    def use(x):
        time.sleep(0.8)
        return len(x)

    big = ray_tpu.put(b"y" * (1 << 20))
    r = use.remote(big)
    time.sleep(0.2)   # give the flusher a window to ship the pin
    del big
    gc.collect()
    assert ray_tpu.get([r], timeout=60)[0] == 1 << 20


def test_contains_edge_keeps_inner_alive(cluster):
    """A ref nested inside a stored value keeps its object alive until
    the outer object is released (contained-in tracking)."""
    rt = ray_tpu.init(address=cluster.gcs_address)
    inner = ray_tpu.put(b"z" * 100_000)
    inner_oid = inner.id.binary()
    outer = ray_tpu.put({"inner": inner})
    time.sleep(0.3)
    del inner
    gc.collect()
    time.sleep(1.0)
    got = ray_tpu.get([outer])[0]["inner"]
    assert ray_tpu.get([got])[0] == b"z" * 100_000
    del got, outer
    gc.collect()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if not rt.store.contains(inner_oid):
            break
        time.sleep(0.2)
    # after the outer (and the borrowed inner handle) drop, the chain
    # releases the inner too
    assert not rt.store.contains(inner_oid)


def test_fire_and_forget_return_freed_on_arrival(cluster):
    rt = ray_tpu.init(address=cluster.gcs_address)

    @ray_tpu.remote
    def make():
        return b"f" * (1 << 20)

    ref = make.remote()
    oid = ref.id.binary()
    del ref                      # dropped before the task finishes
    gc.collect()
    deadline = time.monotonic() + 15
    seen = False
    while time.monotonic() < deadline:
        if rt.store.contains(oid):
            seen = True
        elif seen:
            break                # arrived, then freed
        time.sleep(0.05)
    time.sleep(1.5)
    assert not rt.store.contains(oid)


def test_local_mode_put_drop_frees():
    ray_tpu.shutdown()
    rt = ray_tpu.init(num_cpus=2, num_tpus=0)
    ref = ray_tpu.put(list(range(10000)))
    oid = ref.id
    del ref
    gc.collect()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if not rt.store.contains(oid):
            break
        time.sleep(0.05)
    assert not rt.store.contains(oid)
    ray_tpu.shutdown()


_CHILD = """
import sys
import ray_tpu

host, port, mode = sys.argv[1], int(sys.argv[2]), sys.argv[3]
ray_tpu.init(address=(host, port), namespace="lifetimes")


@ray_tpu.remote
class A:
    def ping(self):
        return "pong"


a = A.options(name="plain").remote()
d = A.options(name="persist", lifetime="detached").remote()
assert ray_tpu.get(a.ping.remote()) == "pong"
assert ray_tpu.get(d.ping.remote()) == "pong"
if mode == "clean":
    ray_tpu.shutdown()
else:
    import os
    os._exit(0)   # no unregister: heartbeat-timeout reaping must cover
"""


@pytest.mark.parametrize("mode", ["clean", "kill"])
def test_driver_exit_reaps_non_detached_actors(mode, tmp_path):
    """Owner-scoped lifetime: a driver's actors die with it — clean
    disconnect reaps immediately, a SIGKILL'd driver via heartbeat
    timeout. lifetime="detached" opts out and survives both."""
    from ray_tpu.utils.config import reset_config

    ray_tpu.shutdown()
    # short client timeout so the kill-mode reap lands within the test
    # window (the production default is 45s — generous against falsely
    # reaping a live driver under control-plane load)
    os.environ["RAY_TPU_CLIENT_TIMEOUT_S"] = "6"
    # beats must outpace the shortened timeout (production: 5s vs 45s)
    os.environ["RAY_TPU_REF_HEARTBEAT_INTERVAL_S"] = "1"
    reset_config()
    cluster = Cluster()
    cluster.add_node(num_cpus=4)
    try:
        _drive_exit_case(cluster, mode, tmp_path)
    finally:
        os.environ.pop("RAY_TPU_CLIENT_TIMEOUT_S", None)
        os.environ.pop("RAY_TPU_REF_HEARTBEAT_INTERVAL_S", None)
        reset_config()
        ray_tpu.shutdown()
        cluster.shutdown()


def _drive_exit_case(cluster, mode, tmp_path):
    child = tmp_path / "child.py"
    child.write_text(_CHILD)
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    host, port = cluster.gcs_address
    out = subprocess.run(
        [sys.executable, str(child), host, str(port), mode],
        capture_output=True, text=True, env=env, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]

    ray_tpu.init(address=cluster.gcs_address, namespace="lifetimes")
    # non-detached: reaped (fast on clean exit; within the client
    # timeout after a hard kill)
    deadline = time.monotonic() + (10 if mode == "clean" else 30)
    reaped = False
    while time.monotonic() < deadline:
        try:
            h = ray_tpu.get_actor("plain")
        except ValueError:
            reaped = True
            break
        try:
            ray_tpu.get(h.ping.remote(), timeout=2)
        except Exception:
            reaped = True
            break
        time.sleep(0.5)
    assert reaped, "non-detached actor survived its driver"
    # detached: alive and serving
    h = ray_tpu.get_actor("persist")
    assert ray_tpu.get(h.ping.remote(), timeout=30) == "pong"
