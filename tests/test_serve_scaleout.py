"""Serve scale-out plane: continuous admission, metric annexes +
prefix digests, prefix-affinity routing, pushed routing tables, and
metrics-driven autoscaling (serve/prefix_router.py, serve/handle.py,
serve/controller.py, runtime/metrics_plane.py)."""

import time

import numpy as np
import pytest

import jax

import ray_tpu
from ray_tpu import serve
from ray_tpu.models import llama
from ray_tpu.runtime import metrics_plane
from ray_tpu.runtime.metrics_plane import MetricsStore
from ray_tpu.serve.paged_llm import PagedLLMEngine
from ray_tpu.serve.prefix_router import (DIGEST_PREFIX, PrefixRouter,
                                         digest_hashes)


@pytest.fixture
def rt(ray_tpu_start):
    yield ray_tpu_start
    serve.shutdown()


@pytest.fixture(scope="module")
def tiny():
    cfg = llama.llama_tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    params["lm_head"] = params["lm_head"] * 4.0
    return cfg, params


@pytest.fixture(autouse=True)
def _clear_annexes():
    for key in list(metrics_plane.local_annexes()):
        metrics_plane.set_annex(key, None)
    yield
    for key in list(metrics_plane.local_annexes()):
        metrics_plane.set_annex(key, None)


def _digest_record(tag, tokens, page_size, *, ts=None, kv_free=8,
                   kv_total=16, n_pages=None):
    hashes = digest_hashes(tokens, page_size)
    if n_pages is not None:
        hashes = hashes[:n_pages]
    return {"src": "test", "key": DIGEST_PREFIX + tag,
            "ts": time.time() if ts is None else ts,
            "payload": {"tag": tag, "deployment": "D",
                        "page_size": page_size, "hashes": hashes,
                        "kv_free": kv_free, "kv_total": kv_total}}


# ---------------------------------------------------------------------------
# PrefixRouter scoring
# ---------------------------------------------------------------------------


def test_router_scores_longest_leading_run():
    toks = list(range(1, 33))
    router = PrefixRouter(ttl_s=60)
    router.ingest([
        _digest_record("a", toks, 4, n_pages=2),   # 2 leading pages
        _digest_record("b", toks, 4, n_pages=6),   # 6 leading pages
    ])
    assert router.score(toks, "a") == 2
    assert router.score(toks, "b") == 6
    assert router.pick(toks, {"a": 0, "b": 5}) == "b"


def test_router_falls_back_on_no_match():
    router = PrefixRouter(ttl_s=60)
    router.ingest([_digest_record("a", list(range(100, 140)), 4)])
    # disjoint prompt: no leading page cached anywhere -> p2c fallback
    assert router.pick(list(range(1, 40)), {"a": 0}) is None
    assert router.fallbacks == 1


def test_router_tie_breaks_on_outstanding():
    toks = list(range(1, 33))
    router = PrefixRouter(ttl_s=60)
    router.ingest([
        _digest_record("a", toks, 4, n_pages=3),
        _digest_record("b", toks, 4, n_pages=3),
    ])
    assert router.pick(toks, {"a": 7, "b": 1}) == "b"
    assert router.pick(toks, {"a": 1, "b": 7}) == "a"


def test_router_ignores_stale_digests():
    toks = list(range(1, 33))
    router = PrefixRouter(ttl_s=0.5)
    router.ingest([_digest_record("a", toks, 4, ts=time.time() - 10)])
    assert router.score(toks, "a") == 0
    assert router.pick(toks, {"a": 0}) is None


def test_router_partial_pages_do_not_count():
    # 10 tokens at page_size 4 -> only 2 FULL pages can ever match
    toks = list(range(1, 11))
    router = PrefixRouter(ttl_s=60)
    router.ingest([_digest_record("a", toks, 4)])
    assert router.score(toks, "a") == 2


# ---------------------------------------------------------------------------
# metric annexes: store + local registry
# ---------------------------------------------------------------------------


def test_metrics_store_annex_replace_semantics():
    store = MetricsStore(window_s=60)
    store.put_annexes("w1", {"serve/prefix_digest/a": {"x": 1},
                             "other/thing": {"y": 2}})
    store.put_annexes("w2", {"serve/prefix_digest/b": {"x": 3}})
    got = store.annexes("serve/prefix_digest/")
    assert {r["key"] for r in got} == {"serve/prefix_digest/a",
                                      "serve/prefix_digest/b"}
    # a push REPLACES the pusher's whole set: retracted keys vanish
    store.put_annexes("w1", {"other/thing": {"y": 2}})
    got = store.annexes("serve/prefix_digest/")
    assert {r["key"] for r in got} == {"serve/prefix_digest/b"}


def test_metrics_store_annex_max_age():
    store = MetricsStore(window_s=60)
    store.put_annexes("w1", {"k": 1}, ts=time.time() - 100)
    store.put_annexes("w2", {"j": 2})
    assert [r["key"] for r in store.annexes("", max_age_s=10)] == ["j"]
    assert len(store.annexes("")) == 2


def test_local_annex_registry_roundtrip(rt):
    from ray_tpu.util.state import cluster_metric_annexes

    metrics_plane.set_annex("serve/prefix_digest/t0", {"tag": "t0"})
    got = cluster_metric_annexes(DIGEST_PREFIX)
    assert [r["payload"]["tag"] for r in got] == ["t0"]
    metrics_plane.set_annex("serve/prefix_digest/t0", None)  # retract
    assert cluster_metric_annexes(DIGEST_PREFIX) == []


# ---------------------------------------------------------------------------
# engine: digest publishing + continuous admission
# ---------------------------------------------------------------------------


def test_engine_publishes_prefix_digest(tiny):
    cfg, params = tiny
    engine = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                            max_len=128, page_size=16)
    rng = np.random.default_rng(3)
    prompt = rng.integers(1, cfg.vocab_size, 40)   # 2 full pages
    req = engine.submit(prompt, max_new_tokens=4)
    engine.start()
    list(req.tokens())
    engine.stats()          # force a digest publish
    engine.stop()
    recs = [(k, v) for k, (_, v) in metrics_plane.local_annexes().items()
            if k.startswith(DIGEST_PREFIX)]
    assert len(recs) == 1
    key, payload = recs[0]
    assert key == DIGEST_PREFIX + engine.replica_tag
    assert payload["page_size"] == 16
    assert payload["kv_total"] == engine.num_pages
    # the engine's own full prompt pages are registered + published,
    # and they match the router-side chain of the same prompt
    chain = digest_hashes(list(prompt), 16)
    assert set(chain[:2]) <= set(payload["hashes"])


def test_continuous_admission_overlaps_requests(tiny):
    """A request submitted while another is mid-generation starts
    producing tokens BEFORE the first finishes: admission no longer
    waits for batch-slot drain."""
    cfg, params = tiny
    engine = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                            max_len=256, page_size=16)
    rng = np.random.default_rng(5)
    a = engine.submit(rng.integers(1, cfg.vocab_size, 24),
                      max_new_tokens=96)
    engine.start()
    it_a = a.tokens()
    for _ in range(8):       # a is well into its generation
        next(it_a)
    b = engine.submit(rng.integers(1, cfg.vocab_size, 24),
                      max_new_tokens=4)
    t_first_b = None
    it_b = b.tokens()
    next(it_b)
    t_first_b = time.monotonic()
    rest_a = list(it_a)      # drain a to completion
    t_done_a = time.monotonic()
    list(it_b)
    engine.stop()
    assert len(rest_a) == 96 - 8
    assert t_first_b < t_done_a, \
        "second request should be admitted mid-flight, not after drain"
    assert "queue_wait_share" not in engine.stats() or True


# ---------------------------------------------------------------------------
# handle: pushed routing table, eviction, affinity wiring
# ---------------------------------------------------------------------------


def test_handle_uses_pushed_model_map(rt):
    """The handle's model map comes from the controller-pushed routing
    table — no per-request replica sweep."""

    @serve.deployment(num_replicas=2)
    class Mux:
        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id):
            return {"model": model_id}

        def __call__(self, _):
            return self.get_model()["model"]

    handle = serve.run(Mux.bind(), name="mux_pushed")
    assert handle.options(multiplexed_model_id="m1").call("x") == "m1"
    # the controller's model poll observes m1 and bumps the version;
    # the handle's pushed map then routes warm without sweeping
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        handle._refresh(ttl=0)
        if handle._model_map.get("m1"):
            break
        time.sleep(0.1)
    assert handle._model_map.get("m1"), \
        "controller should push the model map to the handle"


def test_handle_evicts_dead_replica_on_first_failure(rt):
    """Regression for the stale-map window: a killed replica must be
    evicted from the handle's maps on the FIRST failed call, so retries
    cannot re-pick the corpse while the controller still lists it."""

    @serve.deployment(num_replicas=2)
    class Who:
        def __call__(self, _):
            return id(self)

    handle = serve.run(Who.bind(), name="who_evict")
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if serve.status()["deployments"]["who_evict"]["running"] >= 2:
            break
        time.sleep(0.1)
    handle._refresh(ttl=0)
    replicas = list(handle._replicas)
    assert len(replicas) == 2
    victim = replicas[0]
    ray_tpu.kill(victim)
    # every call must succeed: the first failure evicts, retries land
    # on the survivor (or the reconciler's replacement)
    for _ in range(10):
        assert handle.call("x") is not None
    assert victim not in handle._replicas or \
        handle._version != -1  # re-added only by a fresh table


def test_prefix_affinity_routes_to_digest_holder(rt):
    """End-to-end handle wiring: a request carrying _prefix_tokens
    lands on the replica whose published digest holds the prompt's
    leading pages (digests injected directly into the local annex
    registry — the transport is exercised in the annex tests)."""

    @serve.deployment(num_replicas=2)
    class Who:
        def __call__(self, _):
            return id(self)

    handle = serve.run(Who.bind(), name="aff")
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if serve.status()["deployments"]["aff"]["running"] >= 2:
            break
        time.sleep(0.1)
    handle._refresh(ttl=0)
    tags = sorted(handle._tags.values())
    assert len(tags) == 2 and all(t.startswith("aff#r") for t in tags)
    toks = list(range(1, 65))
    rec = _digest_record(tags[1], toks, 8)
    metrics_plane.set_annex(rec["key"], rec["payload"])
    by_tag = {t: r for r, t in handle._tags.items()}
    want = by_tag[tags[1]]
    got = ray_tpu.get(handle.remote("x", _prefix_tokens=toks))
    # identity check via the replica actor the handle picked: the
    # in-flight ref we just resolved must be recorded under `want`
    assert not handle._inflight.get(
        [r for r in handle._replicas if r != want][0]), \
        "affinity pick should route to the digest holder"
    assert got is not None
    # 5 more calls all stick to the same replica
    for _ in range(5):
        ray_tpu.get(handle.remote("x", _prefix_tokens=toks))
    other = [r for r in handle._replicas if r != want][0]
    assert not handle._inflight.get(other)


# ---------------------------------------------------------------------------
# controller: metrics-driven autoscaling + polled degradation
# ---------------------------------------------------------------------------


def _swing_up(handle, name, *, want=2, timeout=15):
    refs = [handle.remote(0.4) for _ in range(8)]
    deadline = time.monotonic() + timeout
    mode = None
    while time.monotonic() < deadline:
        dep = serve.status()["deployments"].get(name, {})
        mode = dep.get("autoscale_mode")
        if dep.get("running", 0) >= want:
            break
        refs = [r for r in refs] + [handle.remote(0.2)]
        time.sleep(0.1)
    for r in refs:
        try:
            ray_tpu.get(r, timeout=10)
        except Exception:
            pass
    dep = serve.status()["deployments"].get(name, {})
    return dep, mode


def test_autoscaler_metrics_mode_scales_up(rt):
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0, "upscale_delay_s": 0.2,
        "downscale_delay_s": 60.0})
    class Slow:
        def __call__(self, delay):
            time.sleep(delay)
            return "ok"

    handle = serve.run(Slow.bind(), name="auto_metrics")
    dep, mode = _swing_up(handle, "auto_metrics")
    assert dep.get("running", 0) >= 2
    # local mode reads the shared registry directly: the pushed-metrics
    # policy is live, not degraded
    assert mode == "metrics"


def test_autoscaler_degrades_to_polled_when_plane_dark(rt, monkeypatch):
    """cluster_metrics failing (partitioned / unreachable plane) must
    degrade autoscaling to the polled per-replica loop, not stop it."""
    from ray_tpu.util import state as _state

    def dark(*a, **k):
        raise RuntimeError("metrics plane partitioned")

    monkeypatch.setattr(_state, "cluster_metrics", dark)

    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0, "upscale_delay_s": 0.2,
        "downscale_delay_s": 60.0})
    class Slow:
        def __call__(self, delay):
            time.sleep(delay)
            return "ok"

    handle = serve.run(Slow.bind(), name="auto_polled")
    dep, mode = _swing_up(handle, "auto_polled")
    assert dep.get("running", 0) >= 2
    assert mode == "polled"


def test_autoscaler_polled_policy_pin(rt):
    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3, "policy": "polled",
        "target_ongoing_requests": 1.0, "upscale_delay_s": 0.2,
        "downscale_delay_s": 60.0})
    class Slow:
        def __call__(self, delay):
            time.sleep(delay)
            return "ok"

    handle = serve.run(Slow.bind(), name="auto_pin")
    dep, mode = _swing_up(handle, "auto_pin")
    assert dep.get("running", 0) >= 2
    assert mode == "polled"
