"""Ring attention vs reference over a sequence-sharded mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import reference_attention
from ray_tpu.parallel.mesh import create_mesh
from ray_tpu.parallel.ring_attention import ring_attention


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(causal):
    mesh = create_mesh({"sp": 4}, devices=jax.devices()[:4])
    b, s, h, d = 2, 64, 4, 32
    q = jax.random.normal(jax.random.key(0), (b, s, h, d), dtype=jnp.float32)
    k = jax.random.normal(jax.random.key(1), (b, s, h, d), dtype=jnp.float32)
    v = jax.random.normal(jax.random.key(2), (b, s, h, d), dtype=jnp.float32)
    got = ring_attention(q, k, v, mesh=mesh, axis="sp", causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_ring_gqa():
    mesh = create_mesh({"sp": 4}, devices=jax.devices()[:4])
    b, s, h, hk, d = 1, 32, 8, 2, 16
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, hk, d))
    v = jax.random.normal(jax.random.key(2), (b, s, hk, d))
    got = ring_attention(q, k, v, mesh=mesh, axis="sp", causal=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_ring_inside_jit_with_sharded_inputs():
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = create_mesh({"sp": 8})
    b, s, h, d = 1, 64, 2, 16
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))
    seq_sharded = NamedSharding(mesh, P(None, "sp"))
    q = jax.device_put(q, seq_sharded)
    k = jax.device_put(k, seq_sharded)
    v = jax.device_put(v, seq_sharded)

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, mesh=mesh, axis="sp", causal=True)

    got = f(q, k, v)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_ring_grads_match():
    mesh = create_mesh({"sp": 4}, devices=jax.devices()[:4])
    b, s, h, d = 1, 32, 2, 16
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))

    g_ring = jax.grad(
        lambda q, k, v: jnp.sum(
            ring_attention(q, k, v, mesh=mesh, axis="sp", causal=True) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(
            reference_attention(q, k, v, causal=True) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-3, atol=5e-3)


def test_ring_rejects_indivisible():
    mesh = create_mesh({"sp": 4}, devices=jax.devices()[:4])
    q = jnp.zeros((1, 30, 2, 16))
    with pytest.raises(ValueError, match="divisible"):
        ring_attention(q, q, q, mesh=mesh, axis="sp")


@pytest.mark.parametrize("causal", [True, False])
def test_ring_flash_matches_reference(causal):
    """Fused Pallas ring (interpret mode off-TPU): forward parity."""
    mesh = create_mesh({"sp": 4}, devices=jax.devices()[:4])
    b, s, h, d = 2, 64, 2, 16
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))
    got = ring_attention(q, k, v, mesh=mesh, causal=causal, impl="flash")
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_ring_flash_grads_match_reference():
    """Fused Pallas ring BACKWARD: dq/dk/dv parity with autodiff through
    full reference attention (VERDICT r1 weak #7)."""
    mesh = create_mesh({"sp": 4}, devices=jax.devices()[:4])
    b, s, h, d = 1, 64, 2, 16
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, h, d))
    v = jax.random.normal(jax.random.key(2), (b, s, h, d))

    def loss_ring(q, k, v):
        o = ring_attention(q, k, v, mesh=mesh, causal=True, impl="flash")
        return (o.astype(jnp.float32) ** 2).sum()

    def loss_ref(q, k, v):
        o = reference_attention(q, k, v, causal=True)
        return (o.astype(jnp.float32) ** 2).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=5e-2, atol=5e-2,
            err_msg=f"d{name} mismatch")


def test_ring_flash_gqa_grads():
    mesh = create_mesh({"sp": 2}, devices=jax.devices()[:2])
    b, s, h, hk, d = 1, 32, 4, 2, 8
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    k = jax.random.normal(jax.random.key(1), (b, s, hk, d))
    v = jax.random.normal(jax.random.key(2), (b, s, hk, d))

    def loss(fn):
        def inner(q, k, v):
            return (fn(q, k, v).astype(jnp.float32) ** 2).sum()
        return inner

    ring_fn = loss(lambda q, k, v: ring_attention(
        q, k, v, mesh=mesh, causal=True, impl="flash"))
    ref_fn = loss(lambda q, k, v: reference_attention(
        q, k, v, causal=True))
    g_ring = jax.grad(ring_fn, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=5e-2, atol=5e-2)
