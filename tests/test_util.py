"""Utility tests: ActorPool, Queue, collectives, DAG, workflow.
(reference analogs: ray.util tests, dag tests, workflow/tests/)"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import ActorPool, Queue
from ray_tpu.util import collective as col


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def test_actor_pool_ordered(rt):
    @ray_tpu.remote
    class W:
        def work(self, x):
            return x * x

    pool = ActorPool([W.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.work.remote(v), range(8)))
    assert out == [v * v for v in range(8)]


def test_actor_pool_unordered(rt):
    import time

    @ray_tpu.remote
    class W:
        def work(self, x):
            time.sleep(0.01 * (5 - x))
            return x

    pool = ActorPool([W.remote() for _ in range(3)])
    out = list(pool.map_unordered(lambda a, v: a.work.remote(v), range(5)))
    assert sorted(out) == list(range(5))


def test_queue_basic(rt):
    q = Queue(maxsize=3)
    q.put(1)
    q.put(2)
    assert q.qsize() == 2
    assert q.get() == 1
    assert q.get() == 2
    assert q.empty()


def test_queue_full_and_empty(rt):
    from ray_tpu.util.queue import Empty, Full

    q = Queue(maxsize=1)
    q.put("a")
    with pytest.raises(Full):
        q.put("b", block=False)
    assert q.get() == "a"
    with pytest.raises(Empty):
        q.get(block=False)


def test_queue_cross_task(rt):
    q = Queue()

    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return True

    assert ray_tpu.get(producer.remote(q, 5))
    assert [q.get(timeout=10) for _ in range(5)] == list(range(5))


def test_collective_allreduce(rt):
    @ray_tpu.remote
    def rank_fn(rank, world):
        g = col.init_collective_group(world, rank, "g1")
        out = g.allreduce(np.full(4, float(rank + 1)))
        return out.tolist()

    world = 3
    outs = ray_tpu.get([rank_fn.remote(r, world) for r in range(world)])
    assert all(o == [6.0] * 4 for o in outs)  # 1+2+3


def test_collective_allgather_broadcast(rt):
    @ray_tpu.remote
    def rank_fn(rank, world):
        g = col.init_collective_group(world, rank, "g2")
        gathered = g.allgather(np.array([rank]))
        bcast = g.broadcast(np.array([rank * 10]), src_rank=1)
        return [int(a[0]) for a in gathered], int(bcast[0])

    outs = ray_tpu.get([rank_fn.remote(r, 2) for r in range(2)])
    for gathered, bcast in outs:
        assert gathered == [0, 1]
        assert bcast == 10


def test_collective_reducescatter_sendrecv(rt):
    @ray_tpu.remote
    def rank_fn(rank, world):
        g = col.init_collective_group(world, rank, "g3")
        shard = g.reducescatter(np.arange(4, dtype=np.float64))
        if rank == 0:
            g.send(np.array([42.0]), dst_rank=1)
            return shard.tolist(), None
        got = g.recv(src_rank=0)
        return shard.tolist(), got.tolist()

    outs = ray_tpu.get([rank_fn.remote(r, 2) for r in range(2)])
    assert outs[0][0] == [0.0, 2.0]   # doubled (2 ranks) halves
    assert outs[1][0] == [4.0, 6.0]
    assert outs[1][1] == [42.0]


def test_collective_module_level_send_recv(rt):
    """Module-level col.send/col.recv wrappers (reference:
    collective.py:531 exposes them at module scope)."""
    @ray_tpu.remote
    def rank_fn(rank, world):
        col.init_collective_group(world, rank, "g4")
        if rank == 0:
            col.send(np.array([7.0, 8.0]), dst_rank=1, group_name="g4")
            col.barrier("g4")
            return "sent"
        got = col.recv(src_rank=0, group_name="g4")
        col.barrier("g4")
        return got.tolist()

    outs = ray_tpu.get([rank_fn.remote(r, 2) for r in range(2)])
    assert outs[0] == "sent" and outs[1] == [7.0, 8.0]


def test_dag_bind_execute(rt):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    dag = mul.bind(add.bind(1, 2), add.bind(3, 4))  # (1+2)*(3+4)
    assert ray_tpu.get(dag.execute()) == 21


def test_dag_diamond(rt):
    @ray_tpu.remote
    def one():
        return 1

    @ray_tpu.remote
    def inc(x):
        return x + 1

    @ray_tpu.remote
    def add(a, b):
        return a + b

    base = one.bind()
    dag = add.bind(inc.bind(base), inc.bind(base))
    assert ray_tpu.get(dag.execute()) == 4


def test_workflow_run_and_resume(rt, tmp_path):
    from ray_tpu import workflow

    calls = {"n": 0}
    log = tmp_path / "calls.txt"

    def count_calls(x):
        with open(log, "a") as f:
            f.write("x")
        return x * 2

    @ray_tpu.remote
    def double(x):
        with open(str(log), "a") as f:
            f.write("c")
        return x * 2

    @ray_tpu.remote
    def add(a, b):
        return a + b

    dag = add.bind(double.bind(10), double.bind(20))
    out = workflow.run(dag, workflow_id="wf1", storage=str(tmp_path))
    assert out == 60
    assert workflow.status("wf1", storage=str(tmp_path)) == "SUCCESS"
    calls_before = log.read_text().count("c")
    # resume skips all checkpointed steps: no new executions
    out2 = workflow.resume(dag, workflow_id="wf1", storage=str(tmp_path))
    assert out2 == 60
    assert log.read_text().count("c") == calls_before


def test_runtime_context(rt):
    from ray_tpu.runtime_context import get_runtime_context

    ctx = get_runtime_context()
    assert ctx.get_worker_id() == "driver"
    assert ctx.get_job_id()
