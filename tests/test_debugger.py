"""Remote debugger (reference: ray.util.rpdb — set_trace in a task
opens a socket-bound pdb, registered in the KV; a client attaches and
drives it)."""

import io
import threading
import time

import pytest

import ray_tpu
from ray_tpu.util import debug as rdbg


@pytest.fixture(scope="module", autouse=True)
def _rt():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    yield
    ray_tpu.shutdown()


def _attach_and_send(commands: list[str], out: io.StringIO,
                     deadline_s: float = 30.0):
    """Poll for a session, attach, send commands, collect output."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        sessions = rdbg.active_sessions()
        if sessions:
            rdbg.connect(sessions[-1],
                         stdin=io.StringIO("".join(commands)), stdout=out)
            return True
        time.sleep(0.1)
    return False


def test_set_trace_suspends_until_continue():
    @ray_tpu.remote
    def task():
        x = 41
        rdbg.set_trace(timeout_s=25)
        return x + 1

    ref = task.remote()
    out = io.StringIO()
    attacher = threading.Thread(
        target=_attach_and_send, args=(["p x\n", "c\n"], out), daemon=True)
    attacher.start()
    # the task resumes only after the client sends 'c'
    assert ray_tpu.get(ref, timeout=60) == 42
    attacher.join(timeout=10)
    text = out.getvalue()
    assert "41" in text          # `p x` printed the local
    assert "(rtpu-pdb)" in text
    # session deregistered after detach
    assert not rdbg.active_sessions()


def test_set_trace_timeout_resumes_without_client():
    @ray_tpu.remote
    def task():
        rdbg.set_trace(timeout_s=0.5)   # nobody attaches
        return "resumed"

    assert ray_tpu.get(task.remote(), timeout=60) == "resumed"


def test_post_mortem_inspects_exception_frame():
    @ray_tpu.remote
    def task():
        try:
            denom = 0
            return 1 / denom
        except ZeroDivisionError:
            rdbg.post_mortem(timeout_s=25)
            return "handled"

    ref = task.remote()
    out = io.StringIO()
    attacher = threading.Thread(
        target=_attach_and_send, args=(["p denom\n", "q\n"], out),
        daemon=True)
    attacher.start()
    assert ray_tpu.get(ref, timeout=60) == "handled"
    attacher.join(timeout=10)
    assert "0" in out.getvalue()
