"""Chunked object transfer + pull admission control (VERDICT r1 item 3).

Reference: ObjectManager chunked push/pull (``object_manager.cc:339``,
5 MiB chunks ``ray_config_def.h:355``) + PullManager admission control
(``pull_manager.h:52``)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.utils.config import get_config, reset_config


@pytest.fixture
def two_node(monkeypatch):
    # small chunks so even modest objects take the chunked path, and a
    # tight in-flight budget so admission control is actually exercised
    monkeypatch.setenv("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", str(1 << 20))
    monkeypatch.setenv("RAY_TPU_OBJECT_TRANSFER_INFLIGHT_FRACTION", "0.02")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=1, store_capacity=256 << 20)   # head / driver
    c.add_node(num_cpus=2, store_capacity=256 << 20)   # producer
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    reset_config()


def test_large_object_pulls_in_chunks(two_node):
    """A ~64 MiB array produced on the worker node is pulled to the
    driver node via parallel 1 MiB chunk reads under a ~5 MiB in-flight
    budget, and arrives bit-exact."""
    cfg = get_config()
    assert cfg.object_transfer_chunk_bytes == 1 << 20

    @ray_tpu.remote(resources={"CPU": 1})
    def produce(seed):
        rng = np.random.default_rng(seed)
        return rng.integers(0, 255, size=(64 << 20) // 8,
                            dtype=np.uint8)  # 8 MiB

    refs = [produce.remote(s) for s in range(8)]   # 8 x 8 MiB
    out = ray_tpu.get(refs, timeout=120)
    for s, arr in enumerate(out):
        rng = np.random.default_rng(s)
        want = rng.integers(0, 255, size=(64 << 20) // 8, dtype=np.uint8)
        np.testing.assert_array_equal(arr, want)


def test_chunked_pull_concurrent_waiters_dedup(two_node):
    """Two gets of the same remote object share one transfer (pull
    dedup) and both see the data."""
    import threading

    @ray_tpu.remote(resources={"CPU": 1})
    def produce():
        return np.arange((16 << 20) // 8, dtype=np.float64)  # 16 MiB

    ref = produce.remote()
    results = []

    def getter():
        results.append(ray_tpu.get(ref, timeout=60))

    threads = [threading.Thread(target=getter) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 2
    np.testing.assert_array_equal(results[0], results[1])


def test_spilled_object_served_by_chunk_seek(two_node):
    """A spilled object on the source node answers chunked reads by file
    seek — no whole-object restore on the serving side."""
    c = two_node

    @ray_tpu.remote(resources={"CPU": 1})
    def produce():
        return np.ones((8 << 20) // 8, dtype=np.float64)   # 8 MiB

    ref = produce.remote()
    # force the producer raylet to spill it
    producer = [h.raylet for h in c.nodes.values()
                if h.raylet and h.raylet.total_resources.get("CPU") == 2][0]
    import time
    deadline = time.monotonic() + 10
    oid = ref.id.binary()
    while time.monotonic() < deadline and not producer.store.contains(oid):
        time.sleep(0.05)
    spilled = producer.objects.spill_bytes(64 << 20)
    out = ray_tpu.get(ref, timeout=60)
    np.testing.assert_array_equal(out, np.ones((8 << 20) // 8))
    assert spilled >= 0   # spill path exercised (0 if already pulled)
