"""Test fixtures.

Tests run on a virtual 8-device CPU platform so multi-chip sharding paths
compile and execute without TPU hardware (same mechanism the driver's
``dryrun_multichip`` uses). Two environments must work:

1. Clean env: set JAX_PLATFORMS/XLA_FLAGS before jax's first import.
2. The axon TPU-tunnel env: a sitecustomize on PYTHONPATH has ALREADY
   imported jax and registered the 'axon' PJRT plugin (whose backend init
   dials a tunnel and can block for minutes). We unregister non-CPU
   factories and force the platform to cpu before any backend initializes.

Analog of the reference's ``ray_start_regular`` fixture
(``python/ray/tests/conftest.py:410``) for the runtime tests.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Disable the host memory monitor in tests: a CI host already above the
# 95% kill threshold would otherwise see random worker kills. The OOM
# tests opt back in explicitly.
os.environ.setdefault("RAY_TPU_MEMORY_USAGE_THRESHOLD", "0")
# Per-node dashboard agents default OFF in tests (a process per node in
# every throwaway cluster); test_dashboard_agent opts back in.
os.environ.setdefault("RAY_TPU_DASHBOARD_AGENT_ENABLED", "0")
# Append (not guard): XLA's flag parsing is last-occurrence-wins, so this
# forces 8 virtual devices even if the env already set a different count.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

# Drop tunnel/TPU backends registered by sitecustomize before any backend
# init, and FAIL FAST if we cannot guarantee cpu — a silent miss here means
# the first jax.devices() call dials the tunnel and hangs the whole session.
try:
    from jax._src import xla_bridge as _xb

    if _xb._backends:
        raise RuntimeError(
            "a JAX backend was already initialized before conftest ran "
            f"({list(_xb._backends)}); tests cannot force the cpu platform. "
            "Run pytest in a fresh process."
        )
    # Pop only the tunnel backend: removing 'tpu' as well would delist it
    # from MLIR's known platforms and break chex/optax imports.
    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")
except ImportError:
    # jax internals moved. If jax was imported fresh in this process, the
    # JAX_PLATFORMS env var above still latched at import; verify it did.
    if jax.config.jax_platforms != "cpu":
        raise RuntimeError(
            "cannot force JAX onto cpu: xla_bridge internals unavailable and "
            f"jax_platforms={jax.config.jax_platforms!r}; tests would dial "
            "the TPU tunnel and hang"
        ) from None

import pytest  # noqa: E402


@pytest.fixture
def ray_tpu_start():
    """Fresh runtime per test (local in-process cluster)."""
    import ray_tpu

    ray_tpu.shutdown()
    rt = ray_tpu.init(num_cpus=8, num_tpus=0)
    yield rt
    ray_tpu.shutdown()


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices
