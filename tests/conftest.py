"""Test fixtures.

JAX is forced onto a virtual 8-device CPU platform BEFORE first import so
multi-chip sharding paths compile and run without TPU hardware (the driver's
``dryrun_multichip`` uses the same mechanism). Analog of the reference's
``ray_start_regular`` fixture (``python/ray/tests/conftest.py:410``) for the
runtime tests.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import pytest  # noqa: E402


@pytest.fixture
def ray_tpu_start():
    """Fresh runtime per test (local in-process cluster)."""
    import ray_tpu

    ray_tpu.shutdown()
    rt = ray_tpu.init(num_cpus=8, num_tpus=0)
    yield rt
    ray_tpu.shutdown()


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices
