"""Tune widening tests: TPE searcher, HyperBand, restore, limiter,
callbacks. (reference analogs: tune/tests/test_searchers.py,
test_trial_scheduler.py, test_tuner_restore.py)"""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air.config import RunConfig
from ray_tpu.train import session


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def _objective(config):
    # smooth 1-d bowl: best at x = 3
    score = -(config["x"] - 3.0) ** 2
    session.report({"score": score})


def test_tpe_searcher_improves(rt, tmp_path):
    searcher = tune.TPESearcher(
        {"x": tune.uniform(-10, 10)}, metric="score", mode="max",
        num_samples=24, n_startup=6, seed=7)
    tuner = tune.Tuner(
        _objective,
        tune_config=tune.TuneConfig(search_alg=searcher,
                                    max_concurrent_trials=4),
        run_config=RunConfig(storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 24
    best = grid.get_best_result("score", "max")
    # adaptive search should land near the optimum
    assert best.last_result["score"] > -1.5


def test_tpe_concentrates_after_observations():
    """Mechanism test: with a clear optimum observed, proposals
    concentrate near it (no tuner in the loop)."""
    searcher = tune.TPESearcher(
        {"x": tune.uniform(-10, 10)}, metric="score", mode="max",
        num_samples=100, n_startup=1, seed=3)
    for i, x in enumerate([-9, -6, -3, 0, 2.5, 3.0, 3.5, 6, 9]):
        tid = f"seed_{i}"
        searcher._configs[tid] = {"x": x}
        searcher._obs[tid] = ({"x": x}, -(x - 3.0) ** 2)
    xs = [searcher.suggest(f"t{i}")["x"] for i in range(20)]
    close = sum(1 for x in xs if abs(x - 3.0) < 3.0)
    assert close >= 12, xs


def test_bayesopt_searcher_improves(rt, tmp_path):
    searcher = tune.BayesOptSearcher(
        {"x": tune.uniform(-10, 10)}, metric="score", mode="max",
        num_samples=24, n_startup=6, seed=7)
    tuner = tune.Tuner(
        _objective,
        tune_config=tune.TuneConfig(search_alg=searcher,
                                    max_concurrent_trials=4),
        run_config=RunConfig(storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 24
    best = grid.get_best_result("score", "max")
    assert best.last_result["score"] > -1.5


def test_bayesopt_ei_concentrates():
    """Mechanism test: with a clear optimum observed, GP-EI proposals
    concentrate near it (no tuner in the loop)."""
    searcher = tune.BayesOptSearcher(
        {"x": tune.uniform(-10, 10)}, metric="score", mode="max",
        num_samples=100, n_startup=1, seed=3)
    for i, x in enumerate([-9.0, -6.0, -3.0, 0.0, 2.5, 3.0, 3.5,
                           6.0, 9.0]):
        tid = f"seed_{i}"
        searcher._configs[tid] = {"x": x}
        searcher._obs[tid] = ({"x": x}, -(x - 3.0) ** 2)
    xs = [searcher.suggest(f"t{i}")["x"] for i in range(20)]
    close = sum(1 for x in xs if abs(x - 3.0) < 3.0)
    assert close >= 12, xs


def test_bayesopt_respects_integer_domains():
    searcher = tune.BayesOptSearcher(
        {"n": tune.randint(1, 9)}, metric="score", mode="max",
        num_samples=50, n_startup=1, seed=0)
    for i in range(6):
        tid = f"s{i}"
        searcher._configs[tid] = {"n": i + 1}
        searcher._obs[tid] = ({"n": i + 1}, -abs(i + 1 - 5))
    for i in range(12):
        cfg = searcher.suggest(f"t{i}")
        assert isinstance(cfg["n"], int) and 1 <= cfg["n"] < 9, cfg


def test_concurrency_limiter(rt, tmp_path):
    searcher = tune.ConcurrencyLimiter(
        tune.TPESearcher({"x": tune.uniform(0, 1)}, metric="score",
                         mode="max", num_samples=6, seed=1),
        max_concurrent=2)
    tuner = tune.Tuner(
        _objective,
        tune_config=tune.TuneConfig(search_alg=searcher,
                                    max_concurrent_trials=4),
        run_config=RunConfig(storage_path=str(tmp_path)))
    grid = tuner.fit()
    assert len(grid) == 6


def _iterative(config):
    for i in range(1, 10):
        session.report({"acc": config["lr"] * i})


def test_hyperband_cuts(rt, tmp_path):
    sched = tune.HyperBandScheduler(metric="acc", mode="max", r=3, eta=3,
                                    max_t=9)
    tuner = tune.Tuner(
        _iterative,
        param_space={"lr": tune.grid_search([0.1, 0.2, 0.5, 1.0])},
        tune_config=tune.TuneConfig(scheduler=sched,
                                    max_concurrent_trials=4),
        run_config=RunConfig(storage_path=str(tmp_path)))
    grid = tuner.fit()
    # the best-lr trial survives to max_t; weaker ones are cut earlier
    best = grid.get_best_result("acc", "max")
    assert best.config["lr"] == 1.0
    assert best.last_result["acc"] == 9.0  # lr * max_t
    cut_early = [t for t in grid.trials
                 if t.iteration < 9 and t.status == "STOPPED"]
    assert cut_early, [(t.config, t.iteration) for t in grid.trials]


def test_callbacks(rt, tmp_path):
    events = []

    class Recorder:
        def on_trial_start(self, trial):
            events.append(("start", trial.trial_id))

        def on_trial_result(self, trial, result):
            events.append(("result", trial.trial_id))

        def on_trial_complete(self, trial):
            events.append(("complete", trial.trial_id))

    tuner = tune.Tuner(
        _objective,
        param_space={"x": tune.grid_search([1.0, 2.0])},
        tune_config=tune.TuneConfig(callbacks=[Recorder()]),
        run_config=RunConfig(storage_path=str(tmp_path)))
    tuner.fit()
    kinds = [k for k, _ in events]
    assert kinds.count("start") == 2
    assert kinds.count("complete") == 2
    assert kinds.count("result") >= 2


def test_tuner_restore(rt, tmp_path):
    """Unfinished trials resume from their checkpoints. The resume marker
    flows back through metrics (the train fn ships by cloudpickle, so
    driver-side closures would not see its writes)."""

    def train_fn(config):
        ckpt = session.get_checkpoint_dir()
        d = os.path.join(session.get_context().get_trial_dir(), "ck")
        os.makedirs(d, exist_ok=True)
        session.report({"score": config["x"],
                        "resumed_from": ckpt or ""}, checkpoint_dir=d)

    exp = str(tmp_path / "exp")
    tuner = tune.Tuner(
        train_fn, param_space={"x": tune.grid_search([1.0, 2.0])},
        run_config=RunConfig(storage_path=exp))
    grid = tuner.fit()
    assert all(t.status == "TERMINATED" for t in grid.trials)

    # simulate an interrupted run: mark one trial unfinished on disk
    import json

    state_file = os.path.join(exp, "experiment_state.json")
    state = json.load(open(state_file))
    state[0]["status"] = "RUNNING"
    json.dump(state, open(state_file, "w"))

    restored = tune.Tuner.restore(exp, train_fn)
    grid2 = restored.fit_restored()
    assert all(t.status == "TERMINATED" for t in grid2.trials)
    # the resumed trial saw its previous checkpoint dir
    rerun = next(t for t in grid2.trials
                 if t.last_result.get("resumed_from"))
    assert rerun.last_result["resumed_from"].endswith("ck")


def test_bohb_searcher_with_hyperband():
    """BOHB = HyperBand budgets + per-budget TPE models: finds a good lr
    on a deterministic objective (reference: tune/search/bohb/)."""
    from ray_tpu.tune import (BOHBSearcher, HyperBandScheduler, TuneConfig,
                              Tuner, loguniform)

    def objective(config):
        import math

        from ray_tpu.train import session

        for i in range(4):
            # best at lr=1e-2; quality improves with iterations
            loss = abs(math.log10(config["lr"]) + 2) + 1.0 / (i + 1)
            session.report({"loss": loss})

    space = {"lr": loguniform(1e-5, 1e0)}
    tuner = Tuner(
        objective,
        param_space=space,
        tune_config=TuneConfig(
            search_alg=BOHBSearcher(space, metric="loss", mode="min",
                                    num_samples=16, n_startup=4, seed=0),
            scheduler=HyperBandScheduler(metric="loss", mode="min", r=1,
                                         max_t=4),
            max_concurrent_trials=4,
        ),
    )
    results = tuner.fit()
    best = results.get_best_result(metric="loss", mode="min")
    import math
    assert abs(math.log10(best.config["lr"]) + 2) < 1.5, best.config


def test_pb2_explore_uses_observations():
    """PB2's GP-UCB explore skews proposals toward regions observed to
    IMPROVE the metric (reference: tune/schedulers/pb2.py)."""
    from types import SimpleNamespace

    from ray_tpu.tune import PB2

    sched = PB2(metric="score", mode="max", perturbation_interval=1,
                hyperparam_bounds={"lr": (0.0, 1.0)}, seed=0)
    # feed observations: configs with lr near 0.8 improve, near 0.2 regress
    for i, (lr, delta) in enumerate([(0.8, 1.0), (0.82, 0.9), (0.78, 1.1),
                                     (0.2, -1.0), (0.22, -0.8),
                                     (0.18, -1.2)] * 3):
        t = SimpleNamespace(trial_id=f"t{i}", config={"lr": lr})
        sched.on_result(t, {"score": 0.0, "training_iteration": 1})
        sched.on_result(t, {"score": delta, "training_iteration": 2})

    proposals = [sched.explore({"lr": 0.5})["lr"] for _ in range(20)]
    assert all(0.0 <= p <= 1.0 for p in proposals)
    # the bandit should prefer the improving region on average
    assert sum(p > 0.5 for p in proposals) >= 14, proposals


def test_pb2_smoke_with_tuner(tmp_path):
    """PB2 drives a small population end-to-end through the Tuner."""
    from ray_tpu.air.config import RunConfig
    from ray_tpu.tune import PB2, TuneConfig, Tuner, uniform

    def trainable(config):
        from ray_tpu.train import session

        lr = config["lr"]
        score = 0.0
        for i in range(6):
            score += 1.0 - abs(lr - 0.7)   # best at lr=0.7
            session.report({"score": score})

    tuner = Tuner(
        trainable,
        param_space={"lr": uniform(0.0, 1.0)},
        tune_config=TuneConfig(
            num_samples=4,
            scheduler=PB2(metric="score", mode="max",
                          perturbation_interval=2,
                          hyperparam_bounds={"lr": (0.0, 1.0)}, seed=0),
            max_concurrent_trials=4,
        ),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    best = results.get_best_result(metric="score", mode="max")
    assert best.last_result["score"] > 0
