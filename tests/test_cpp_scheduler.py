"""C++ scheduling policy tests (src/scheduler/scheduling.cc — reference
hybrid_scheduling_policy.cc semantics)."""

import numpy as np
import pytest

from ray_tpu._private import scheduling as sched

pytestmark = pytest.mark.skipif(not sched.available(),
                                reason="libtpusched.so not built")


def _nodes(specs):
    """specs: list of (total, available) dicts."""
    totals = [t for t, _ in specs]
    avails = [a for _, a in specs]
    ids = [f"n{i}" for i in range(len(specs))]
    return ids, totals, avails


def test_picks_lowest_utilization():
    ids, totals, avails = _nodes([
        ({"CPU": 8}, {"CPU": 2}),   # util (6+1)/8 = 0.875
        ({"CPU": 8}, {"CPU": 7}),   # util (1+1)/8 = 0.25  <- best
        ({"CPU": 8}, {"CPU": 4}),   # util (4+1)/8 = 0.625
    ])
    out = sched.pick_node(ids, totals, avails, [True] * 3, set(),
                          {"CPU": 1})
    assert out == "n1"


def test_feasible_busy_fallback_and_infeasible():
    ids, totals, avails = _nodes([
        ({"CPU": 4}, {"CPU": 0}),   # feasible but busy
        ({"CPU": 1}, {"CPU": 1}),   # infeasible for CPU:2
    ])
    assert sched.pick_node(ids, totals, avails, [True] * 2, set(),
                           {"CPU": 2}) == "n0"
    assert sched.pick_node(ids, totals, avails, [True] * 2, set(),
                           {"CPU": 64}) is None


def test_excluded_and_dead_skipped():
    ids, totals, avails = _nodes([
        ({"CPU": 8}, {"CPU": 8}),
        ({"CPU": 8}, {"CPU": 8}),
        ({"CPU": 8}, {"CPU": 8}),
    ])
    out = sched.pick_node(ids, totals, avails, [False, True, True],
                          {"n1"}, {"CPU": 1})
    assert out == "n2"


def test_multi_resource_critical_dimension():
    # node 0 is CPU-light but TPU-heavy; critical = max over kinds
    ids, totals, avails = _nodes([
        ({"CPU": 8, "TPU": 4}, {"CPU": 8, "TPU": 1}),  # TPU util 1.0
        ({"CPU": 8, "TPU": 4}, {"CPU": 4, "TPU": 4}),  # CPU util .625
    ])
    out = sched.pick_node(ids, totals, avails, [True] * 2, set(),
                          {"CPU": 1, "TPU": 1})
    assert out == "n1"


def test_spread_threshold_ties_low_utilization():
    """With a spread threshold, nodes under it tie — top_k > 1 then
    spreads among them instead of always bin-packing onto node 0."""
    ids, totals, avails = _nodes([
        ({"CPU": 16}, {"CPU": 16}),
        ({"CPU": 16}, {"CPU": 15}),
        ({"CPU": 16}, {"CPU": 14}),
    ])
    picks = {
        sched.pick_node(ids, totals, avails, [True] * 3, set(),
                        {"CPU": 1}, spread_threshold=0.5, top_k=3,
                        seed=s)
        for s in range(32)
    }
    assert len(picks) > 1  # spread actually happens
    # without the threshold, strictly lowest utilization wins every time
    always = {
        sched.pick_node(ids, totals, avails, [True] * 3, set(),
                        {"CPU": 1}, spread_threshold=0.0, top_k=1,
                        seed=s)
        for s in range(8)
    }
    assert always == {"n0"}


def test_matches_python_policy_randomized():
    """C++ policy must agree with the Python fallback on the
    deterministic (top_k=1, threshold=0) configuration."""
    from ray_tpu.runtime.gcs import _critical_utilization, _fits

    rng = np.random.default_rng(0)
    for _ in range(200):
        n = int(rng.integers(1, 6))
        specs = []
        for _ in range(n):
            total = {"CPU": float(rng.integers(1, 9)),
                     "TPU": float(rng.integers(0, 5))}
            avail = {k: float(rng.integers(0, int(v) + 1))
                     for k, v in total.items()}
            specs.append((total, avail))
        demand = {"CPU": float(rng.integers(1, 4))}
        if rng.random() < 0.5:
            # include zero-valued demands: they must still contribute
            # node utilization exactly like the Python policy
            demand["TPU"] = float(rng.integers(0, 3))
        ids, totals, avails = _nodes(specs)

        class N:  # python policy's node view
            def __init__(self, nid, t, a):
                self.node_id, self.resources, self.available = nid, t, a
                self.alive = True

        pynodes = [N(i, t, a) for i, (t, a) in zip(ids, specs)]
        best, best_score = None, None
        feasible_busy = None
        for node in pynodes:
            if not _fits(demand, node.resources):
                continue
            if _fits(demand, node.available):
                score = _critical_utilization(demand, node)
                if best_score is None or score < best_score:
                    best, best_score = node.node_id, score
            elif feasible_busy is None:
                feasible_busy = node.node_id
        expect = best if best is not None else feasible_busy

        got = sched.pick_node(ids, totals, avails, [True] * n, set(),
                              demand, spread_threshold=0.0, top_k=1)
        assert got == expect, (specs, demand, got, expect)


def test_byte_scale_resources_no_overflow():
    """Memory advertised in bytes must not overflow the scorer (the
    fixed-point multiply would wrap int64 above ~9.2e6 units)."""
    ids, totals, avails = _nodes([
        ({"memory": 64e9}, {"memory": 32e9}),
        ({"memory": 64e9}, {"memory": 60e9}),
    ])
    scores = sched.score_nodes(totals, avails, [True, True],
                               {"memory": 1e9})
    assert abs(scores[0] - (32e9 + 1e9) / 64e9) < 1e-6
    assert abs(scores[1] - (4e9 + 1e9) / 64e9) < 1e-6
    out = sched.pick_node(ids, totals, avails, [True, True], set(),
                          {"memory": 1e9})
    assert out == "n1"


def test_zero_demand_kind_scores_utilization():
    """A num_tpus=0 task must avoid the TPU-saturated node (parity with
    the Python policy, which scores zero-demand kinds too)."""
    ids, totals, avails = _nodes([
        ({"CPU": 8, "TPU": 4}, {"CPU": 8, "TPU": 0}),   # TPU util 1.0
        ({"CPU": 8, "TPU": 4}, {"CPU": 2, "TPU": 4}),   # CPU util 0.875
    ])
    out = sched.pick_node(ids, totals, avails, [True, True], set(),
                          {"CPU": 1, "TPU": 0})
    assert out == "n1"


def test_score_nodes():
    ids, totals, avails = _nodes([
        ({"CPU": 8}, {"CPU": 4}),
        ({"CPU": 1}, {"CPU": 1}),
    ])
    scores = sched.score_nodes(totals, avails, [True, True], {"CPU": 2})
    assert abs(scores[0] - 0.75) < 1e-6
    assert scores[1] == -1.0  # infeasible
