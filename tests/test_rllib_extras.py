"""Tests for the round-3 RLlib breadth: PG/A2C, ES/ARS, MARWIL, bandits.

Reference analogs: per-algorithm tests under
``rllib/algorithms/{pg,a2c,es,ars,marwil,bandit}/tests/``.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    A2CConfig,
    ARSConfig,
    BanditLinTSConfig,
    BanditLinUCBConfig,
    ESConfig,
    MARWILConfig,
    PGConfig,
    collect_dataset,
)


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def _train_until(algo, target, max_iters):
    last = -np.inf
    for _ in range(max_iters):
        last = algo.train()["episode_return_mean"]
        if last >= target:
            break
    return last


def test_a2c_learns_bandit(rt):
    algo = (A2CConfig()
            .environment("Bandit-v0")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=256)
            .training(lr=0.02)
            .build())
    try:
        assert _train_until(algo, 0.85, 30) >= 0.85
    finally:
        algo.stop()


def test_pg_learns_bandit(rt):
    algo = (PGConfig()
            .environment("Bandit-v0")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=256)
            .training(lr=0.02)
            .build())
    try:
        assert _train_until(algo, 0.85, 40) >= 0.85
    finally:
        algo.stop()


def test_a2c_save_restore(rt, tmp_path):
    algo = A2CConfig().environment("Bandit-v0").build()
    try:
        algo.train()
        path = str(tmp_path / "a2c.pkl")
        algo.save(path)
        fresh = A2CConfig().environment("Bandit-v0").build()
        try:
            fresh.restore(path)
            obs = np.array([1.0, -1.0], dtype=np.float32)
            assert fresh.compute_action(obs) == algo.compute_action(obs)
        finally:
            fresh.stop()
    finally:
        algo.stop()


def test_es_learns_bandit(rt):
    algo = (ESConfig()
            .environment("Bandit-v0")
            .rollouts(num_rollout_workers=2)
            .training(episodes_per_batch=32, sigma=0.3, lr=0.1,
                      hidden=0, episodes_per_direction=10)
            .build())
    try:
        last = -np.inf
        for _ in range(30):
            last = algo.train()["episode_return_mean"]
            if last >= 0.8:
                break
        assert last >= 0.8
        # deterministic eval should match or beat perturbed returns
        assert algo.evaluate(8)["episode_return_mean"] >= 0.8
    finally:
        algo.stop()


def test_ars_learns_bandit(rt):
    algo = (ARSConfig()
            .environment("Bandit-v0")
            .rollouts(num_rollout_workers=2)
            .training(episodes_per_batch=32, sigma=0.3, lr=0.2,
                      top_k=8, episodes_per_direction=10)
            .build())
    try:
        last = -np.inf
        for _ in range(30):
            last = algo.train()["episode_return_mean"]
            if last >= 0.8:
                break
        assert last >= 0.8
    finally:
        algo.stop()


def test_es_save_restore(rt, tmp_path):
    algo = ESConfig().environment("Bandit-v0").training(hidden=0).build()
    try:
        algo.train()
        path = str(tmp_path / "es")
        algo.save(path)
        fresh = (ESConfig().environment("Bandit-v0")
                 .training(hidden=0).build())
        try:
            fresh.restore(path)
            np.testing.assert_allclose(fresh.theta, algo.theta)
        finally:
            fresh.stop()
    finally:
        algo.stop()


def test_marwil_beats_random_behavior(tmp_path):
    """MARWIL on a mixed-quality CartPole dataset must beat the random
    behavior policy it was trained from (advantage weighting should
    upweight the lucky long episodes)."""
    path = collect_dataset("CartPole-v1", str(tmp_path / "ds"),
                           num_steps=4000, seed=0)
    algo = (MARWILConfig()
            .environment("CartPole-v1")
            .offline_data(path)
            .training(lr=3e-3, beta=1.0, batch_size=512)
            .build())
    try:
        for _ in range(150):
            result = algo.train()
        assert np.isfinite(result["policy_loss"])
        # random CartPole averages ~22 steps; cloned+reweighted should
        # hold the pole visibly longer
        assert algo.evaluate(10)["episode_return_mean"] >= 35.0
    finally:
        algo.stop()


def test_marwil_beta_zero_is_bc(tmp_path):
    """beta=0 -> uniform weights (pure behavior cloning)."""
    path = collect_dataset("Bandit-v0", str(tmp_path / "ds"),
                           num_steps=512, seed=1)
    algo = (MARWILConfig().environment("Bandit-v0")
            .offline_data(path).training(beta=0.0).build())
    try:
        result = algo.train()
        assert result["mean_adv_weight"] == pytest.approx(1.0)
    finally:
        algo.stop()


def test_linucb_learns_bandit():
    algo = (BanditLinUCBConfig().environment("Bandit-v0")
            .training(steps_per_iteration=200).build())
    r1 = algo.train()["episode_return_mean"]
    r2 = algo.train()["episode_return_mean"]
    # after 200 pulls the linear model has the structure nailed
    assert r2 >= 0.9
    assert r2 >= r1 - 0.05
    assert sum(algo.train()["arm_pulls"]) == 600


def test_lints_learns_bandit():
    algo = (BanditLinTSConfig().environment("Bandit-v0")
            .training(steps_per_iteration=200, alpha=0.5).build())
    algo.train()
    assert algo.train()["episode_return_mean"] >= 0.85


def test_linucb_save_restore(tmp_path):
    algo = (BanditLinUCBConfig().environment("Bandit-v0")
            .training(steps_per_iteration=100).build())
    algo.train()
    path = str(tmp_path / "ucb")
    algo.save(path)
    fresh = BanditLinUCBConfig().environment("Bandit-v0").build()
    fresh.restore(path)
    for obs in ([1.0, 1.0], [-1.0, 1.0]):
        x = np.asarray(obs)
        assert fresh.compute_action(x) == algo.compute_action(x)


def test_pixel_cartpole_env():
    """Pixel-obs env (reference: Atari-class large-obs suites): frames
    are 84x84, state-dependent, and drive a normal PPO iteration."""
    from ray_tpu.rllib.env import PixelCartPole

    env = PixelCartPole(seed=0)
    obs = env.reset()
    assert obs.shape == (84 * 84,)
    obs2, r, d, _ = env.step(1)
    assert obs2.shape == (84 * 84,)
    assert (obs != obs2).any()


@pytest.mark.nightly
@pytest.mark.slow
def test_rl_throughput_pixel_env(rt):
    """RL plane throughput leg (reference: release_tests.yaml rllib
    suites): vectorized rollouts + LearnerGroup on pixel obs must
    sustain a recorded env-steps/s figure."""
    import time

    from ray_tpu.rllib import IMPALAConfig

    algo = (IMPALAConfig()
            .environment("PixelCartPole-v0")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=8)
            .training(unroll_length=32, num_learners=2,
                      learner_mode="mesh", hidden=128, seed=0)
            .build())
    try:
        algo.train()                      # warm: spawn + compile
        t0 = time.monotonic()
        iters = 4
        for _ in range(iters):
            algo.train()
        el = time.monotonic() - t0
        steps = iters * 2 * 8 * 32
        print(f"\npixel env-steps/s: {steps / el:.0f}")
        assert steps / el > 100           # sanity floor, not a target
    finally:
        algo.stop()


@pytest.mark.nightly
@pytest.mark.slow
def test_ppo_learns_from_pixels(rt):
    """Pixel-obs LEARNING at nightly tier (beyond-CartPole-scale check:
    the policy must read an 84x84 frame, not a 4-float state). Measured:
    PPO reaches return ~81 by iter 10, best ~96 by 25 — threshold 70
    within 40 iters has wide margin over the ~20 random-play floor."""
    from ray_tpu.rllib import PPOConfig

    algo = (PPOConfig().environment("PixelCartPole-v0")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=512)
            .training(num_envs_per_worker=4, lr=5e-4, hidden=128,
                      minibatch_size=512, seed=0)
            .build())
    try:
        best = 0.0
        for _ in range(40):
            best = max(best,
                       algo.train()["episode_return_mean"])
            if best >= 70:
                break
        assert best >= 70, f"pixel PPO failed to learn: best {best}"
    finally:
        algo.stop()
