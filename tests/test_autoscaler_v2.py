"""Autoscaler v2-style instance manager + provider unit tests.

Reference analogs: ``autoscaler/v2/tests`` (instance storage versioning,
reconciler lifecycle) and ``FakeMultiNodeProvider``-style provider tests
— including the GKE provider's COMMAND CONSTRUCTION via an injected
runner (the cloud CLI layer itself needs no credentials to be tested).
"""

import pytest

from ray_tpu.autoscaler import GKETPUNodeProvider
from ray_tpu.instance_manager import (
    ALLOCATED,
    QUEUED,
    RAY_RUNNING,
    REQUESTED,
    TERMINATED,
    InstanceManager,
    InstanceStorage,
    VersionConflict,
)


class FakeProvider:
    """In-memory cloud: create is async-visible (like GKE — no id at
    request time until ``provision()`` is called)."""

    def __init__(self, sync: bool = True):
        self.sync = sync
        self.nodes: list[str] = []
        self.pending = 0
        self._n = 0
        self.terminated: list[str] = []

    def create_node(self, resources):
        if self.sync:
            self._n += 1
            nid = f"node-{self._n}"
            self.nodes.append(nid)
            return nid
        self.pending += 1
        return ""

    def provision(self):
        while self.pending:
            self.pending -= 1
            self._n += 1
            self.nodes.append(f"node-{self._n}")

    def terminate_node(self, node_id):
        self.terminated.append(node_id)
        if node_id in self.nodes:
            self.nodes.remove(node_id)

    def non_terminated_nodes(self):
        return list(self.nodes)


def test_instance_storage_versioning():
    st = InstanceStorage()
    inst = st.create({"CPU": 2})
    assert inst.status == QUEUED and inst.version == 0
    st.update_status(inst.instance_id, REQUESTED, 0)
    with pytest.raises(VersionConflict):
        st.update_status(inst.instance_id, ALLOCATED, 0)  # stale version
    st.update_status(inst.instance_id, ALLOCATED, 1, node_id="n1")
    assert st.get(inst.instance_id).node_id == "n1"
    assert [s for s, _ in st.get(inst.instance_id).status_history] == [
        QUEUED, REQUESTED, ALLOCATED]


def test_reconciler_sync_provider_lifecycle():
    prov = FakeProvider(sync=True)
    im = InstanceManager(prov)
    inst = im.launch({"CPU": 4})
    assert im.provisioning() and im.live_count() == 1
    im.reconcile()                       # QUEUED -> REQUESTED -> (listed)
    im.reconcile()                       # REQUESTED -> ALLOCATED
    got = im.storage.get(inst.instance_id)
    assert got.status == ALLOCATED and got.node_id == "node-1"
    im.reconcile(gcs_alive={"node-1"})   # raylet registered
    assert im.storage.get(inst.instance_id).status == RAY_RUNNING
    assert not im.provisioning()
    im.terminate("node-1")
    im.reconcile()
    assert im.storage.get(inst.instance_id).status == TERMINATED
    assert im.live_count() == 0


def test_reconciler_async_provider_claims_new_node():
    prov = FakeProvider(sync=False)
    im = InstanceManager(prov)
    inst = im.launch({"TPU": 4})
    im.reconcile()                       # request sent; no node id yet
    assert im.storage.get(inst.instance_id).status == REQUESTED
    assert im.storage.get(inst.instance_id).node_id is None
    prov.provision()                     # cloud finishes minutes later
    im.reconcile()
    got = im.storage.get(inst.instance_id)
    assert got.status == ALLOCATED and got.node_id == "node-1"


def test_reconciler_detects_lost_node_and_adopts_foreign():
    prov = FakeProvider(sync=True)
    im = InstanceManager(prov)
    inst = im.launch({"CPU": 1})
    im.reconcile()
    im.reconcile(gcs_alive={"node-1"})
    # the cloud kills the VM out from under us
    prov.nodes.remove("node-1")
    im.reconcile()
    assert im.storage.get(inst.instance_id).status == TERMINATED
    # a VM appears that nobody launched (pre-existing pool capacity):
    # it gets adopted so live_count() reflects real capacity
    prov.nodes.append("foreign-1")
    im.reconcile()
    adopted = [i for i in im.storage.list((ALLOCATED,))
               if i.node_id == "foreign-1"]
    assert adopted and im.live_count() == 1


# ---------------------------------------------------------------------------
# GKE provider: command construction against a stubbed runner
# ---------------------------------------------------------------------------

def _gke(calls, replies=None):
    replies = replies or {}

    def runner(argv):
        calls.append(argv)
        for key, out in replies.items():
            if key in " ".join(argv):
                return out
        return ""

    return GKETPUNodeProvider(cluster="c1", node_pool="tpu-pool",
                              zone="us-central2-b", project="proj",
                              runner=runner)


def test_gke_list_and_create_commands():
    calls = []
    prov = _gke(calls, {"get nodes": "gke-a gke-b"})
    assert prov.non_terminated_nodes() == ["gke-a", "gke-b"]
    kubectl = calls[0]
    assert kubectl[:3] == ["kubectl", "get", "nodes"]
    assert "cloud.google.com/gke-nodepool=tpu-pool" in " ".join(kubectl)
    prov.create_node({"TPU": 4})
    resize = calls[-1]
    assert resize[:4] == ["gcloud", "container", "clusters", "resize"]
    assert "c1" in resize
    assert "--node-pool=tpu-pool" in resize
    assert "--num-nodes=3" in resize          # 2 existing + 1
    assert "--zone=us-central2-b" in resize
    assert "--project=proj" in resize


def test_gke_terminate_commands():
    calls = []
    prov = _gke(calls, {
        "node-pools describe":
            "https://gce/projects/p/zones/z/instanceGroupManagers/mig-1",
    })
    prov.terminate_node("gke-a")
    joined = [" ".join(c) for c in calls]
    assert any(c.startswith("kubectl drain gke-a") for c in joined)
    assert any("node-pools describe tpu-pool" in c for c in joined)
    delete = [c for c in calls
              if "delete-instances" in c]
    assert delete, joined
    assert "mig-1" in delete[0]
    assert "--instances=gke-a" in delete[0]


def test_gke_terminate_survives_failed_drain():
    calls = []

    def runner(argv):
        calls.append(argv)
        if argv[0] == "kubectl" and argv[1] == "drain":
            raise RuntimeError("node unreachable")
        if "describe" in argv:
            return "https://gce/zones/z/instanceGroupManagers/mig-9"
        return ""

    prov = GKETPUNodeProvider(cluster="c", node_pool="p",
                              zone="z", runner=runner)
    prov.terminate_node("dead-node")   # must not raise
    assert any("delete-instances" in c for c in calls)
