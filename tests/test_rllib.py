"""RLlib subset tests (reference: rllib per-algorithm tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import PPOConfig, CartPole, BanditEnv


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def test_cartpole_env_dynamics():
    env = CartPole(seed=0)
    obs = env.reset()
    assert obs.shape == (4,)
    total = 0
    for _ in range(10):
        obs, reward, done, _ = env.step(1)
        total += reward
        if done:
            break
    assert total >= 1


def test_ppo_train_iteration_runs(rt):
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
            .training(num_sgd_iter=2, minibatch_size=64)
            .build())
    try:
        result = algo.train()
        assert result["training_iteration"] == 1
        assert result["num_env_steps_sampled"] == 256
        assert np.isfinite(result["policy_loss"])
        assert np.isfinite(result["vf_loss"])
        assert result["entropy"] > 0
    finally:
        algo.stop()


def test_ppo_learns_bandit(rt):
    """On the deterministic bandit, PPO must clearly beat random (0.5)."""
    algo = (PPOConfig()
            .environment("Bandit-v0")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=256)
            .training(lr=0.01, num_sgd_iter=4, minibatch_size=128,
                      entropy_coeff=0.0, gamma=0.0)
            .build())
    try:
        first = algo.train()["episode_return_mean"]
        last = None
        for _ in range(6):
            last = algo.train()["episode_return_mean"]
        assert last > 0.85, (
            f"PPO failed to learn the bandit: start={first:.2f} "
            f"end={last:.2f}")
    finally:
        algo.stop()


def test_ppo_save_restore(rt, tmp_path):
    algo = (PPOConfig().environment("Bandit-v0")
            .rollouts(num_rollout_workers=1, rollout_fragment_length=64)
            .build())
    try:
        algo.train()
        path = str(tmp_path / "ckpt.pkl")
        algo.save(path)
        action_before = algo.compute_action(np.array([1.0, 1.0]))
        algo2 = (PPOConfig().environment("Bandit-v0")
                 .rollouts(num_rollout_workers=1,
                           rollout_fragment_length=64)
                 .build())
        algo2.restore(path)
        assert algo2.compute_action(np.array([1.0, 1.0])) == action_before
        algo2.stop()
    finally:
        algo.stop()
