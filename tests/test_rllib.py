"""RLlib subset tests (reference: rllib per-algorithm tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import PPOConfig, CartPole, BanditEnv


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def test_cartpole_env_dynamics():
    env = CartPole(seed=0)
    obs = env.reset()
    assert obs.shape == (4,)
    total = 0
    for _ in range(10):
        obs, reward, done, _ = env.step(1)
        total += reward
        if done:
            break
    assert total >= 1


def test_ppo_train_iteration_runs(rt):
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
            .training(num_sgd_iter=2, minibatch_size=64)
            .build())
    try:
        result = algo.train()
        assert result["training_iteration"] == 1
        assert result["num_env_steps_sampled"] == 256
        assert np.isfinite(result["policy_loss"])
        assert np.isfinite(result["vf_loss"])
        assert result["entropy"] > 0
    finally:
        algo.stop()


def test_ppo_learns_bandit(rt):
    """On the deterministic bandit, PPO must clearly beat random (0.5)."""
    algo = (PPOConfig()
            .environment("Bandit-v0")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=256)
            .training(lr=0.01, num_sgd_iter=4, minibatch_size=128,
                      entropy_coeff=0.0, gamma=0.0)
            .build())
    try:
        first = algo.train()["episode_return_mean"]
        last = None
        for _ in range(6):
            last = algo.train()["episode_return_mean"]
        assert last > 0.85, (
            f"PPO failed to learn the bandit: start={first:.2f} "
            f"end={last:.2f}")
    finally:
        algo.stop()


def test_ppo_save_restore(rt, tmp_path):
    algo = (PPOConfig().environment("Bandit-v0")
            .rollouts(num_rollout_workers=1, rollout_fragment_length=64)
            .build())
    try:
        algo.train()
        path = str(tmp_path / "ckpt.pkl")
        algo.save(path)
        action_before = algo.compute_action(np.array([1.0, 1.0]))
        algo2 = (PPOConfig().environment("Bandit-v0")
                 .rollouts(num_rollout_workers=1,
                           rollout_fragment_length=64)
                 .build())
        algo2.restore(path)
        assert algo2.compute_action(np.array([1.0, 1.0])) == action_before
        algo2.stop()
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# DQN (reference: rllib/algorithms/dqn/ — replay, target net, double-Q)
# ---------------------------------------------------------------------------

def test_dqn_learns_bandit(rt):
    from ray_tpu.rllib import DQNConfig

    algo = (DQNConfig()
            .environment("Bandit-v0")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=64)
            .training(learning_starts=64, num_updates_per_iter=16,
                      epsilon_decay_iters=5, target_update_freq=2)
            .build())
    try:
        result = None
        for _ in range(12):
            result = algo.train()
        assert result["training_iteration"] == 12
        assert result["buffer_size"] > 0
        assert result["td_loss"] is not None
        # greedy action must match the context sign (contextual bandit)
        assert algo.compute_action([1.0, 1.0]) == 1
        assert algo.compute_action([-1.0, 1.0]) == 0
    finally:
        algo.stop()


def test_dqn_save_restore(rt, tmp_path):
    import numpy as np

    from ray_tpu.rllib import DQNConfig

    algo = DQNConfig().environment("Bandit-v0").rollouts(
        num_rollout_workers=1, rollout_fragment_length=16).build()
    try:
        algo.train()
        path = str(tmp_path / "dqn.ckpt")
        algo.save(path)
        obs = np.ones(algo.obs_dim, np.float32)
        before = algo.compute_action(obs)
        algo2 = DQNConfig().environment("Bandit-v0").rollouts(
            num_rollout_workers=1, rollout_fragment_length=16).build()
        algo2.restore(path)
        assert algo2.compute_action(obs) == before
        algo2.stop()
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# IMPALA (reference: rllib/algorithms/impala/ — V-trace correction)
# ---------------------------------------------------------------------------

def test_vtrace_matches_onpolicy_returns():
    """When behavior == target policy (rho = 1), V-trace targets reduce
    to n-step TD(lambda=1) returns — verify against a numpy rollout."""
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.rllib.impala import vtrace

    T, B = 5, 1
    rng = np.random.default_rng(0)
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    bootstrap = np.zeros((B,), np.float32)
    dones = np.zeros((T, B), np.float32)
    logp = np.zeros((T, B), np.float32)  # same policy: rho = 1
    gamma = 0.9

    vs, pg_adv, rho = vtrace(jnp.asarray(logp), jnp.asarray(logp),
                             jnp.asarray(rewards), jnp.asarray(values),
                             jnp.asarray(bootstrap), jnp.asarray(dones),
                             gamma=gamma, rho_clip=1.0, c_clip=1.0)
    # numpy reference: vs_t = r_t + gamma * vs_{t+1} (monte-carlo, since
    # deltas telescope when c = rho = 1)
    expect = np.zeros((T, B), np.float32)
    nxt = bootstrap
    for t in range(T - 1, -1, -1):
        expect[t] = rewards[t] + gamma * nxt
        nxt = expect[t]
    assert np.allclose(np.asarray(vs), expect, atol=1e-5)
    assert np.allclose(np.asarray(rho), 1.0, atol=1e-6)


def test_impala_learns_bandit(rt):
    from ray_tpu.rllib import IMPALAConfig

    algo = (IMPALAConfig()
            .environment("Bandit-v0")
            .rollouts(num_rollout_workers=2, unroll_length=64)
            .training(lr=0.02)
            .build())
    try:
        result = None
        for _ in range(15):
            result = algo.train()
        assert result["training_iteration"] == 15
        # one-step policy lag keeps importance weights near 1 (and the
        # learner clips at rho_bar=1): far-from-1 means wrong logits
        assert 0.3 < result["mean_rho"] < 3.0
        assert algo.compute_action([1.0, 1.0]) == 1
        assert algo.compute_action([-1.0, 1.0]) == 0
    finally:
        algo.stop()


def test_sac_learns_continuous_bandit():
    """SAC on the deterministic continuous bandit: the policy mean moves
    toward the known optimum (reference: rllib/algorithms/sac)."""
    from ray_tpu.rllib import SACConfig

    algo = (SACConfig()
            .environment("ContinuousBandit-v0")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=64)
            .training(learning_starts=128, train_batch_size=64,
                      num_updates_per_iter=64, lr=3e-3, gamma=0.0)
            .build())
    try:
        for _ in range(12):
            result = algo.train()
        assert np.isfinite(result["critic_loss"])
        assert result["alpha"] > 0
        # optimum action is 0.5 -> reward ~0; random policy averages ~-0.45
        a = float(algo.compute_single_action(np.zeros(1, np.float32))[0])
        assert abs(a - 0.5) < 0.25, f"policy mean {a} far from optimum 0.5"
    finally:
        algo.stop()


def test_pendulum_env_api():
    from ray_tpu.rllib import Pendulum

    env = Pendulum(seed=0)
    obs = env.reset()
    assert obs.shape == (3,)
    obs2, r, done, _ = env.step([0.5])
    assert obs2.shape == (3,) and r <= 0.0 and not done


def test_appo_learns_bandit():
    """APPO (clipped V-trace surrogate) solves the deterministic bandit."""
    from ray_tpu.rllib import APPOConfig

    algo = (APPOConfig()
            .environment("Bandit-v0")
            .rollouts(num_rollout_workers=2, unroll_length=64)
            .training(lr=5e-2, entropy_coeff=0.0)
            .build())
    try:
        for _ in range(10):
            result = algo.train()
        assert result["episode_return_mean"] > 0.85, result
    finally:
        algo.stop()


def test_td3_learns_continuous_bandit():
    """TD3 on the deterministic continuous bandit: the deterministic
    policy moves toward the known optimum (reference:
    rllib/algorithms/td3 — twin critics, smoothing, delayed policy)."""
    from ray_tpu.rllib import TD3Config

    algo = (TD3Config()
            .environment("ContinuousBandit-v0")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=64)
            .training(learning_starts=128, train_batch_size=64,
                      num_updates_per_iter=64, lr=3e-3, gamma=0.0,
                      expl_noise=0.3)
            .build())
    try:
        for _ in range(12):
            result = algo.train()
        assert np.isfinite(result["critic_loss"])
        a = float(algo.compute_single_action(np.zeros(1, np.float32))[0])
        assert abs(a - 0.5) < 0.25, f"policy {a} far from optimum 0.5"
    finally:
        algo.stop()


def test_ddpg_is_td3_degenerate_config():
    from ray_tpu.rllib import DDPG, DDPGConfig

    cfg = DDPGConfig().environment("ContinuousBandit-v0") \
        .rollouts(num_rollout_workers=1, rollout_fragment_length=32) \
        .training(learning_starts=32, num_updates_per_iter=8)
    assert cfg.twin_q is False and cfg.policy_delay == 1
    assert cfg.target_noise == 0.0
    algo = cfg.build()
    try:
        assert isinstance(algo, DDPG)
        assert "q2" not in algo.params          # single critic
        result = algo.train()
        assert result["training_iteration"] == 1
    finally:
        algo.stop()
