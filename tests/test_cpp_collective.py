"""C++ TCP collective backend tests (src/collective/tcp_collective.cc).

Layer 1 drives TcpGroup directly across real OS processes
(multiprocessing), the way multi-host ranks would use it. Layer 2 goes
through ray_tpu.util.collective with backend="tcp" (rendezvous via the
coordinator actor, data via sockets). Reference analog:
python/ray/util/collective/tests/ (gloo backend)."""

import multiprocessing as mp
import socket

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import collective as col


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def _worker(rank, world, peers, q):
    from ray_tpu._private.tcp_collective import TcpGroup

    try:
        g = TcpGroup(rank, world, peers)
        out = {}

        out["allreduce_f32"] = g.allreduce(
            np.full(1000, rank + 1, dtype=np.float32)).tolist()[:1]
        out["allreduce_max_i64"] = g.allreduce(
            np.array([rank * 10], dtype=np.int64), op="max").tolist()
        # large buffer exercises the chunked ring + full-duplex path
        big = g.allreduce(np.ones(1 << 20, dtype=np.float32))
        out["allreduce_big_ok"] = bool(np.all(big == world))
        # fewer elements than ranks: degenerate chunking
        out["allreduce_tiny"] = g.allreduce(
            np.array([1.0], dtype=np.float64)).tolist()

        out["allgather"] = [int(a[0]) for a in
                            g.allgather(np.array([rank], dtype=np.int32))]
        out["reducescatter"] = g.reducescatter(
            np.arange(world * 2, dtype=np.float64)).tolist()
        out["broadcast"] = g.broadcast(
            np.array([rank], dtype=np.int32), src_rank=world - 1).tolist()
        g.barrier()

        # p2p with out-of-order tags: rank0 sends tag1 then tag0; rank1
        # receives tag0 first, forcing the reorder stash
        if world >= 2:
            if rank == 0:
                g.send(np.array([111.0]), 1, tag=1)
                g.send(np.array([222.0]), 1, tag=0)
            elif rank == 1:
                a = g.recv(0, tag=0)
                b = g.recv(0, tag=1)
                out["p2p"] = [float(a[0]), float(b[0])]
        g.destroy()
        q.put((rank, out))
    except Exception as e:  # surface child failures in the parent
        q.put((rank, {"error": repr(e)}))


@pytest.mark.parametrize("world", [2, 3, 4])
def test_tcp_group_multiprocess(world):
    ports = _free_ports(world)
    peers = [f"127.0.0.1:{p}" for p in ports]
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_worker, args=(r, world, peers, q))
             for r in range(world)]
    for p in procs:
        p.start()
    results = {}
    for _ in range(world):
        rank, out = q.get(timeout=120)
        results[rank] = out
    for p in procs:
        p.join(timeout=30)

    for rank, out in results.items():
        assert "error" not in out, f"rank {rank}: {out['error']}"

    expect_sum = sum(r + 1 for r in range(world))
    for rank in range(world):
        out = results[rank]
        assert out["allreduce_f32"] == [float(expect_sum)]
        assert out["allreduce_max_i64"] == [(world - 1) * 10]
        assert out["allreduce_big_ok"]
        assert out["allreduce_tiny"] == [float(world)]
        assert out["allgather"] == list(range(world))
        # reducescatter: every rank contributed arange(world*2); rank r
        # owns chunk r => [world*2r, world*(2r+1)]
        assert out["reducescatter"] == [world * 2.0 * rank,
                                        world * (2.0 * rank + 1)]
        assert out["broadcast"] == [world - 1]
    assert results[1]["p2p"] == [222.0, 111.0]


def test_tcp_group_world_one():
    from ray_tpu._private.tcp_collective import TcpGroup

    g = TcpGroup(0, 1, ["127.0.0.1:0"])
    assert g.allreduce(np.array([3.0])).tolist() == [3.0]
    assert [a.tolist() for a in g.allgather(np.array([7]))] == [[7]]
    g.barrier()
    g.destroy()


def test_collective_tcp_backend_through_runtime(ray_tpu_start):
    @ray_tpu.remote
    def rank_fn(rank, world):
        g = col.init_collective_group(world, rank, "tcpg", backend="tcp")
        red = g.allreduce(np.full(8, float(rank + 1), dtype=np.float32))
        gat = g.allgather(np.array([rank], dtype=np.int64))
        g.barrier()
        g.destroy()
        return red.tolist()[:1], [int(a[0]) for a in gat]

    world = 3
    outs = ray_tpu.get([rank_fn.remote(r, world) for r in range(world)])
    for red, gat in outs:
        assert red == [6.0]
        assert gat == [0, 1, 2]


def test_tcp_group_reinit_same_name(ray_tpu_start):
    """Re-initializing a TCP group under the same group_name must form a
    fresh mesh (epoch-based rendezvous), not replay the first
    incarnation's stale addresses."""

    @ray_tpu.remote
    def rank_fn(rank, world, value):
        g = col.init_collective_group(world, rank, "reinit", backend="tcp")
        out = g.allreduce(np.array([value], dtype=np.float64))
        g.destroy()
        return float(out[0])

    outs1 = ray_tpu.get([rank_fn.remote(r, 2, 1.0) for r in range(2)])
    outs2 = ray_tpu.get([rank_fn.remote(r, 2, 10.0) for r in range(2)])
    assert outs1 == [2.0, 2.0]
    assert outs2 == [20.0, 20.0]


def test_tcp_recv_timeout(ray_tpu_start):
    @ray_tpu.remote
    def rank_fn(rank, world):
        g = col.init_collective_group(world, rank, "tmo", backend="tcp")
        if rank == 1:
            try:
                g.recv(0, tag=7, timeout=0.5)  # nothing ever sent
                return "no-timeout"
            except TimeoutError:
                return "timeout"
            finally:
                g.barrier()
                g.destroy()
        g.barrier()
        g.destroy()
        return "sender-done"

    outs = ray_tpu.get([rank_fn.remote(r, 2) for r in range(2)])
    assert outs[1] == "timeout"


def test_actor_backend_destroy_noop(ray_tpu_start):
    @ray_tpu.remote
    def rank_fn(rank, world):
        g = col.init_collective_group(world, rank, "adg")
        out = g.allreduce(np.array([1.0]))
        g.destroy()  # must exist on the actor backend too
        return float(out[0])

    assert ray_tpu.get([rank_fn.remote(r, 2) for r in range(2)]) == [2.0, 2.0]
