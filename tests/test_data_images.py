"""Image ingest → device pipeline (VERDICT r1 item 6: the ViT/CLIP
BASELINE config's input side) + byte-budget backpressure + size-based
block splitting."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata

PIL = pytest.importorskip("PIL")
from PIL import Image  # noqa: E402


@pytest.fixture
def image_dir(tmp_path):
    d = tmp_path / "imgs"
    d.mkdir()
    rng = np.random.default_rng(0)
    for i in range(12):
        arr = rng.integers(0, 255, size=(40, 40, 3), dtype=np.uint8)
        Image.fromarray(arr).save(d / f"img_{i:02d}.png")
    (d / "notes.txt").write_text("not an image")
    return str(d)


def test_read_images_decodes_and_resizes(image_dir, ray_tpu_start):
    ds = rdata.read_images(image_dir, size=(32, 32), include_paths=True)
    rows = ds.take_all() if hasattr(ds, "take_all") else ds.take(100)
    assert len(rows) == 12          # .txt file filtered out
    assert rows[0]["image"].shape == (32, 32, 3)
    assert rows[0]["image"].dtype == np.uint8
    assert rows[0]["path"].endswith(".png")


def test_read_images_grayscale(image_dir, ray_tpu_start):
    ds = rdata.read_images(image_dir, size=(16, 16), mode="L")
    row = ds.take(1)[0]
    assert row["image"].shape == (16, 16)


def test_read_images_missing_raises():
    with pytest.raises(FileNotFoundError):
        rdata.read_images("/definitely/not/a/dir/xyz")


def test_block_splitting_unit():
    from ray_tpu.data.context import DataContext
    from ray_tpu.data.execution import _maybe_split

    ctx = DataContext.get_current()
    old = ctx.target_max_block_size
    ctx.target_max_block_size = 1000
    try:
        rows = [{"x": np.zeros(100, np.float64)} for _ in range(10)]
        # ~8000 bytes over a 1000-byte target -> several blocks
        pieces = _maybe_split(rows, 10, 8000)
        assert len(pieces) > 1
        assert sum(p[1] for p in pieces) == 10
    finally:
        ctx.target_max_block_size = old


def test_pipeline_correct_under_tiny_byte_budget(ray_tpu_start):
    """Semantics survive hard backpressure: a budget far below the data
    size still yields every row exactly once."""
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    old = ctx.execution_budget_bytes
    ctx.execution_budget_bytes = 4096   # absurdly small
    try:
        ds = rdata.range(200).map(lambda r: {"y": r["id"] * 2})
        got = sorted(r["y"] for r in ds.take(1000))
        assert got == [2 * i for i in range(200)]
    finally:
        ctx.execution_budget_bytes = old


def test_vit_forward_consumes_image_pipeline(image_dir, ray_tpu_start,
                                             cpu_mesh_devices):
    """read_images → normalize → iter_jax_batches → sharded ViT forward
    on the virtual mesh (the r1 done-criterion)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.models import vit
    from ray_tpu.parallel.mesh import create_mesh

    cfg = vit.vit_tiny(image_size=32, patch_size=8, n_classes=10)
    params = vit.init_params(cfg, jax.random.key(0))
    mesh = create_mesh({"dp": 4}, devices=cpu_mesh_devices[:4])

    def normalize(batch):
        img = batch["image"].astype(np.float32) / 255.0
        return {"image": img}

    ds = (rdata.read_images(image_dir, size=(32, 32))
          .map_batches(normalize))
    fwd = jax.jit(lambda p, x: vit.forward(cfg, p, x))
    seen = 0
    for batch in ds.iterator().iter_jax_batches(batch_size=4,
                                                drop_last=True):
        x = jax.device_put(
            batch["image"], NamedSharding(mesh, P("dp", None, None, None)))
        logits = fwd(params, x)
        assert logits.shape == (4, 10)
        assert bool(jnp.isfinite(logits).all())
        seen += x.shape[0]
    assert seen >= 8


def test_vit_trains_one_step():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import vit

    cfg = vit.vit_tiny(image_size=32, patch_size=8, n_classes=4)
    params = vit.init_params(cfg, jax.random.key(0))
    images = jax.random.uniform(jax.random.key(1), (8, 32, 32, 3))
    labels = jax.random.randint(jax.random.key(2), (8,), 0, 4)
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        def loss_fn(p):
            logits = vit.forward(cfg, p, images)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    losses = []
    for _ in range(8):
        params, opt, loss = step(params, opt)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_vit_logical_axes_match_params():
    import jax

    from ray_tpu.models import vit

    cfg = vit.vit_tiny()
    params = vit.init_params(cfg, jax.random.key(0))
    axes = vit.param_logical_axes(cfg)
    p_leaves = jax.tree.leaves(params)
    a_leaves = jax.tree.leaves(
        axes, is_leaf=lambda x: isinstance(x, tuple))
    assert len(p_leaves) == len(a_leaves)
    for p, a in zip(p_leaves, a_leaves):
        assert p.ndim == len(a), (p.shape, a)
