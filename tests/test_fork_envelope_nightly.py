"""Fork-server actor envelope — the NIGHTLY 10k-actor axis.

Reference analog: ``release/benchmarks/README.md:9`` (40k actors on 64
hosts ≈ 600/host, proven nightly). The fork-server worker pool
(``runtime/prestart.py``) is what makes this axis reachable on few
hosts: every actor worker is an ``os.fork()`` of a preloaded zygote
template, so creation cost is fork + registration, not interpreter boot
+ imports, and forked siblings share their preloaded pages copy-on-write
(10k cold interpreters would not fit host memory).

Sized by ``RAY_TPU_ENVELOPE_NIGHTLY_FORK_ACTORS`` (default 10,000).
Selected only by ``ci/run_ci.sh --nightly`` (``pytest -m nightly``).
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.utils.config import get_config

pytestmark = [pytest.mark.nightly, pytest.mark.slow]

_N_ACTORS = get_config().envelope_nightly_fork_actors


@pytest.fixture(scope="module")
def fork_cluster():
    ray_tpu.shutdown()
    # same shape as the main nightly envelope: generous heartbeat (a
    # raylet starved of cpu during a 10k-process ramp must not be
    # declared dead), 3 external raylets + an IN-PROCESS head whose
    # prestart counters the test reads at the end
    c = Cluster(external_gcs=True, heartbeat_timeout_s=90.0)
    head = c.add_node(num_cpus=4)
    for _ in range(3):
        c.add_node(num_cpus=4, external=True)
    c.wait_for_nodes(4)
    ray_tpu.init(address=c.gcs_address)
    yield c, head
    ray_tpu.shutdown()
    c.shutdown()


def test_10k_actor_fork_envelope(fork_cluster):
    """10,000 concurrent trivial actors created through the fork path;
    creation rate and steady-state calls/s are the recorded envelope
    numbers (printed with ``-s``; the driver's nightly log keeps them)."""
    c, head = fork_cluster

    @ray_tpu.remote(num_cpus=0)
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    n = _N_ACTORS
    window = 500
    actors = []
    t0 = time.monotonic()
    try:
        # windowed ramp: each window is confirmed ALIVE (answered a
        # call) before the next, so a stall is visible at its window,
        # and the host never queues 10k unconfirmed creations
        while len(actors) < n:
            take = min(window, n - len(actors))
            base = len(actors)
            batch = [A.remote(base + i) for i in range(take)]
            got = ray_tpu.get([a.who.remote() for a in batch],
                              timeout=1800)
            assert got == list(range(base, base + take))
            actors.extend(batch)
        create_s = time.monotonic() - t0
        # steady state: every one of the 10k actors answers again
        t0 = time.monotonic()
        got = ray_tpu.get([a.who.remote() for a in actors], timeout=1800)
        steady_s = time.monotonic() - t0
        assert got == list(range(n))
        stats = head.raylet.workers.prestart.snapshot()
        print(f"\n{n} actors: created+confirmed in {create_s:.1f}s "
              f"({n / create_s:.1f} actors/s), steady-state "
              f"{n / steady_s:.0f} calls/s; head prestart: "
              f"forked={stats['forked']} "
              f"cold_fallback={stats['cold_fallback']} "
              f"template_spawns={stats['template_spawns']}")
        # the axis is only proven if the fork plane actually carried it
        assert stats["forked"] > 0
    finally:
        for a in actors:
            ray_tpu.kill(a)
