"""LearnerGroup: multi-learner (data-parallel) training plane.

Reference analog: ``rllib/core/learner/learner_group.py:61,145`` —
DDP-style multi-learner updates. Here: "mesh" mode shards the batch
over a dp mesh axis inside one jit (XLA inserts the gradient psum);
"actors" mode runs learner actors averaging gradients over the host
collective plane. conftest forces an 8-device CPU platform.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import IMPALAConfig, PPOConfig
from ray_tpu.rllib.learner_group import LearnerGroup


@pytest.fixture
def local_runtime():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=8, num_tpus=0)
    yield
    ray_tpu.shutdown()


def _simple_fns(dim=4):
    import jax
    import jax.numpy as jnp

    def init_fn(key):
        return {"w": jax.random.normal(key, (dim,)), "b": jnp.zeros(())}

    def grad_fn(params, batch):
        def loss(p):
            pred = batch["x"] @ p["w"] + p["b"]
            err = jnp.mean((pred - batch["y"]) ** 2)
            return err, {"loss": err}
        (_, stats), grads = jax.value_and_grad(loss, has_aux=True)(params)
        return grads, stats

    return init_fn, grad_fn


def test_mesh_learners_match_single_learner():
    """dp-sharded update must produce the same params as one learner on
    the full batch (the psum'd mean grad IS the global mean grad)."""
    import jax
    import optax

    init_fn, grad_fn = _simple_fns()
    rng = np.random.default_rng(0)
    batch = {"x": rng.normal(size=(32, 4)).astype(np.float32),
             "y": rng.normal(size=(32,)).astype(np.float32)}

    outs = []
    for n in (1, 4):
        g = LearnerGroup(init_fn=init_fn, grad_fn=grad_fn,
                         tx=optax.sgd(0.1), num_learners=n, seed=3)
        for _ in range(5):
            stats = g.update(batch)
        outs.append(g.get_params())
        assert np.isfinite(float(stats["loss"]))
    np.testing.assert_allclose(outs[0]["w"], outs[1]["w"],
                               rtol=1e-5, atol=1e-6)


def test_actor_learners_match_mesh(local_runtime):
    """Learner ACTORS (collective grad averaging across processes) must
    track the mesh (SPMD) plane's result on the same stream."""
    import optax

    init_fn, grad_fn = _simple_fns()
    rng = np.random.default_rng(1)
    batch = {"x": rng.normal(size=(16, 4)).astype(np.float32),
             "y": rng.normal(size=(16,)).astype(np.float32)}

    mesh = LearnerGroup(init_fn=init_fn, grad_fn=grad_fn,
                        tx=optax.sgd(0.1), num_learners=2, seed=7)
    actors = LearnerGroup(init_fn=init_fn, grad_fn=grad_fn,
                          tx=optax.sgd(0.1), num_learners=2, seed=7,
                          mode="actors")
    try:
        for _ in range(3):
            mesh.update(batch)
            actors.update(batch)
        np.testing.assert_allclose(
            mesh.get_params()["w"], actors.get_params()["w"],
            rtol=1e-4, atol=1e-5)
    finally:
        actors.stop()


def test_ppo_trains_with_mesh_learners(local_runtime):
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
            .training(num_sgd_iter=2, minibatch_size=64, num_learners=2,
                      num_envs_per_worker=2)
            .build())
    try:
        for _ in range(3):
            result = algo.train()
        assert result["training_iteration"] == 3
        assert np.isfinite(result["policy_loss"])
        assert result["num_env_steps_sampled"] == 2 * 2 * 128
        assert algo.compute_action(np.zeros(4, np.float32)) in (0, 1)
    finally:
        algo.stop()


def test_impala_trains_with_mesh_learners(local_runtime):
    algo = (IMPALAConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, num_envs_per_worker=2)
            .training(unroll_length=32, num_learners=2)
            .build())
    try:
        for _ in range(3):
            result = algo.train()
        assert result["training_iteration"] == 3
        assert np.isfinite(result["policy_loss"])
        assert np.isfinite(result["mean_rho"])
    finally:
        algo.stop()


def test_ppo_trains_with_actor_learners(local_runtime):
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, rollout_fragment_length=64)
            .training(num_sgd_iter=1, minibatch_size=64, num_learners=2,
                      learner_mode="actors")
            .build())
    try:
        result = algo.train()
        assert np.isfinite(result["policy_loss"])
    finally:
        algo.stop()


def test_vectorized_rollouts_learning_signal(local_runtime):
    """Vectorized env runners must still produce a usable learning
    signal: PPO on CartPole improves over its first iterations."""
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=256)
            .training(num_sgd_iter=4, minibatch_size=128,
                      num_envs_per_worker=4)
            .build())
    try:
        first = algo.train()["episode_return_mean"]
        last = first
        for _ in range(8):
            last = algo.train()["episode_return_mean"]
        assert last > first or last > 60.0, (first, last)
    finally:
        algo.stop()
