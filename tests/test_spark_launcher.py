"""Ray-on-Spark launcher protocol (reference:
python/ray/util/spark/cluster_init.py). pyspark isn't bundled: the
launch protocol is unit-tested via the factored command builder; entry
points must raise a clear ImportError."""

import sys

import pytest

from ray_tpu.util.spark import (
    MAX_NUM_WORKER_NODES,
    _worker_start_cmd,
    setup_ray_cluster,
    shutdown_ray_cluster,
)


def test_worker_start_cmd_protocol():
    cmd = _worker_start_cmd(("10.0.0.1", 6379), num_cpus=8, num_tpus=4)
    assert cmd[0] == sys.executable
    assert "--address" in cmd and "10.0.0.1:6379" in cmd
    assert cmd[cmd.index("--num-cpus") + 1] == "8"
    assert cmd[cmd.index("--num-tpus") + 1] == "4"
    assert "--block" in cmd          # long-lived barrier task


def test_max_worker_nodes_sentinel():
    assert MAX_NUM_WORKER_NODES == -1


def test_entry_points_require_pyspark():
    with pytest.raises(ImportError, match="pyspark"):
        setup_ray_cluster(num_worker_nodes=2)
    with pytest.raises(ImportError, match="pyspark"):
        shutdown_ray_cluster()
