"""Lineage reconstruction: lost objects are re-computed from their
creating task when their node dies.

Reference analog: ``python/ray/tests/test_reconstruction*.py`` —
``ObjectRecoveryManager::RecoverObject`` (object_recovery_manager.h:90)
re-executes the creating task via ``TaskManager::ResubmitTask``
(task_manager.h:234); lineage is pinned by the owner
(reference_count.h:67-115).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.runtime.task_spec import SchedulingStrategy


def _on(node_handle):
    """Soft node-affinity: initial run lands on the node; a lineage re-run
    falls back elsewhere once that node is dead."""
    return SchedulingStrategy(kind="NODE_AFFINITY",
                              node_id=node_handle.node_id)


@pytest.fixture
def two_node_cluster():
    ray_tpu.shutdown()
    c = Cluster(heartbeat_timeout_s=1.0)
    c.add_node(num_cpus=2)                              # head (driver side)
    c.add_node(num_cpus=2, resources={"side": 2})       # victim node
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _victim(cluster):
    return next(h for h in cluster.nodes.values()
                if h.raylet is not None
                and "side" in h.raylet.total_resources)


def test_object_reconstructed_after_node_death(two_node_cluster):
    victim = _victim(two_node_cluster)

    @ray_tpu.remote(max_retries=2, scheduling_strategy=_on(victim))
    def make():
        return np.arange(1000, dtype=np.int64)

    ref = make.remote()
    # materialize on the victim node first (proves it existed remotely)
    assert int(ray_tpu.get(ref).sum()) == 499500

    two_node_cluster.remove_node(victim)
    time.sleep(2.5)  # heartbeat timeout -> GCS drops locations, tombstones

    # driver never held a local copy? it pulled one during the first get —
    # drop it to force reconstruction
    head = next(h for h in two_node_cluster.nodes.values()
                if h.raylet is not None)
    head.raylet.store.delete(ref.id.binary())

    got = ray_tpu.get(ref, timeout=30)
    assert int(got.sum()) == 499500


def test_chained_reconstruction(two_node_cluster):
    """A lost object whose inputs are ALSO lost: recursive re-execution."""
    victim = _victim(two_node_cluster)

    @ray_tpu.remote(max_retries=2, scheduling_strategy=_on(victim))
    def base():
        return np.full(64, 7, dtype=np.int64)

    @ray_tpu.remote(max_retries=2, scheduling_strategy=_on(victim))
    def double(x):
        return 2 * x

    r1 = base.remote()
    r2 = double.remote(r1)
    assert int(ray_tpu.get(r2)[0]) == 14

    two_node_cluster.remove_node(victim)
    time.sleep(2.5)

    head = next(h for h in two_node_cluster.nodes.values()
                if h.raylet is not None)
    head.raylet.store.delete(r1.id.binary())
    head.raylet.store.delete(r2.id.binary())

    got = ray_tpu.get(r2, timeout=60)
    assert int(got[0]) == 14 and got.shape == (64,)


def test_no_lineage_raises_lost(two_node_cluster):
    """max_retries=0 disables reconstruction: the object stays lost."""
    victim = _victim(two_node_cluster)

    @ray_tpu.remote(max_retries=0, scheduling_strategy=_on(victim))
    def make():
        return 41

    ref = make.remote()
    assert ray_tpu.get(ref) == 41
    two_node_cluster.remove_node(victim)
    time.sleep(2.5)
    head = next(h for h in two_node_cluster.nodes.values()
                if h.raylet is not None)
    head.raylet.store.delete(ref.id.binary())

    with pytest.raises((ray_tpu.exceptions.ObjectLostError,
                        ray_tpu.exceptions.GetTimeoutError)):
        ray_tpu.get(ref, timeout=10)
