"""C++ public API (N15): msgpack wire + function-descriptor tasks.

Reference analog: ``cpp/include/ray/api.h`` usage tests — a C++ binary
submits work to a running cluster and reads results. Also unit-tests the
Python side of the cross-language codec (``runtime/xlang.py``) and the
msgpack RPC frames the C++ client speaks.
"""

import os
import socket
import struct
import subprocess

import pytest

import ray_tpu
from ray_tpu.runtime import xlang

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "src", "capi", "example_submit")


def test_xlang_codec_roundtrip():
    cases = [
        None, True, False, 0, 1, 127, 128, -1, -32, -33, 2**40, -(2**40),
        3.5, -0.25, "", "hello", "ünïcode", b"", b"\x00\xffbin",
        [], [1, "two", None, [3.0]], {},
        {"k": 1, "nested": {"a": [True, {"b": b"x"}]}},
    ]
    for case in cases:
        out = xlang.loads(xlang.dumps(case))
        if isinstance(case, tuple):
            case = list(case)
        assert out == case, (case, out)


def test_xlang_codec_rejects_objects():
    with pytest.raises(TypeError):
        xlang.dumps(object())
    with pytest.raises(TypeError):
        xlang.dumps({"fn": lambda: 1})


def test_function_ref_resolution():
    fn = xlang.resolve_function_ref("ray_tpu.examples.xlang:add")
    assert fn(2, 3) == 5
    with pytest.raises(ValueError):
        xlang.resolve_function_ref("no_colon_here")


def _msgpack_call(addr, method, **params):
    """Speak the C++ client's wire from Python: framed 'M'+msgpack."""
    params["method"] = method
    params["_id"] = 0
    payload = b"M" + xlang.dumps(params)
    with socket.create_connection(tuple(addr), timeout=30) as s:
        s.sendall(struct.pack(">Q", len(payload)) + payload)
        hdr = b""
        while len(hdr) < 8:
            hdr += s.recv(8 - len(hdr))
        (n,) = struct.unpack(">Q", hdr)
        buf = b""
        while len(buf) < n:
            buf += s.recv(min(1 << 20, n - len(buf)))
    assert buf[:1] == b"M", "server must answer msgpack with msgpack"
    reply = xlang.loads(buf[1:])
    if reply.get("error") is not None:
        raise RuntimeError(reply["error"])
    return reply["result"]


@pytest.fixture
def cluster():
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    yield c
    c.shutdown()


def test_msgpack_wire_against_gcs(cluster):
    nodes = _msgpack_call(cluster.gcs_address, "get_nodes", alive_only=True)
    assert len(nodes) == 1
    assert nodes[0]["resources"]["CPU"] == 2.0


def test_msgpack_xlang_put_get_and_task(cluster):
    raylet_addr = next(iter(cluster.nodes.values())).address
    r = _msgpack_call(raylet_addr, "xlang_put",
                      value={"x": 7, "l": [1, 2]})
    oid = r["oid"]
    got = _msgpack_call(raylet_addr, "xlang_get", oid=oid, timeout_s=5.0)
    assert got["value"] == {"x": 7, "l": [1, 2]}
    # descriptor task executed by a Python worker
    import ray_tpu.utils.ids as ids

    rid = ids.ObjectID.from_random().hex()
    _msgpack_call(raylet_addr, "submit_task", task={
        "task_id": ids.TaskID.from_random().hex(),
        "name": "xlang-add",
        "function_ref": "ray_tpu.examples.xlang:add",
        "args": [20, 22],
        "return_oids": [rid],
        "resources": {"CPU": 1.0},
        "strategy": {"kind": "DEFAULT"},
        "max_retries": 0,
    })
    got = _msgpack_call(raylet_addr, "xlang_get", oid=rid, timeout_s=30.0)
    assert got["value"] == 42


@pytest.mark.skipif(not os.path.exists(EXAMPLE),
                    reason="C++ example not built (run make -C src)")
def test_cpp_example_binary(cluster):
    host, port = cluster.gcs_address
    proc = subprocess.run([EXAMPLE, host, str(port)], capture_output=True,
                          text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert '"task": "ok"' in proc.stdout
    assert '"stats": "ok"' in proc.stdout
