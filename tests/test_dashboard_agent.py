"""Per-node dashboard agent (reference: dashboard/agent.py +
reporter_agent.py): an observability process per node, registered in the
GCS node table, serving host stats and worker stacks/profiles off the
raylet data plane."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.runtime.rpc import RpcClient
from ray_tpu.utils.config import reset_config


@pytest.fixture
def agent_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_DASHBOARD_AGENT_ENABLED", "1")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    reset_config()


def _wait_agent(c, timeout=15):
    gcs = RpcClient(c.gcs_address)
    deadline = time.monotonic() + timeout
    try:
        while time.monotonic() < deadline:
            nodes = gcs.call("get_nodes", alive_only=True)
            if nodes and nodes[0].get("agent_addr"):
                return tuple(nodes[0]["agent_addr"])
            time.sleep(0.1)
    finally:
        gcs.close()
    raise TimeoutError("agent never registered")


def test_agent_registers_and_serves(agent_cluster):
    addr = _wait_agent(agent_cluster)
    agent = RpcClient(addr, timeout=20)
    try:
        info = agent.call("agent_info")
        assert info["node_id"] == next(iter(agent_cluster.nodes))
        # the agent is its OWN process, not the raylet's
        raylet = next(iter(agent_cluster.nodes.values())).raylet
        import os

        assert info["pid"] != os.getpid()
        stats = agent.call("host_stats")
        assert isinstance(stats, dict)

        # worker stacks through the agent (spin up a worker first)
        @ray_tpu.remote
        def live():
            return 1

        assert ray_tpu.get(live.remote()) == 1
        stacks = agent.call("worker_stacks")
        assert isinstance(stacks, dict) and stacks, stacks
        assert raylet is not None
    finally:
        agent.close()


def test_state_api_prefers_agent(agent_cluster):
    _wait_agent(agent_cluster)

    @ray_tpu.remote
    def live():
        return 1

    ray_tpu.get(live.remote())
    from ray_tpu.util import state

    stacks = state.dump_worker_stacks()
    assert stacks and isinstance(stacks, dict)


def test_agent_dies_with_raylet(agent_cluster):
    _wait_agent(agent_cluster)
    handle = next(iter(agent_cluster.nodes.values()))
    proc = handle.raylet._agent_proc
    assert proc is not None and proc.poll() is None
    handle.raylet.stop()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and proc.poll() is None:
        time.sleep(0.1)
    assert proc.poll() is not None, "agent outlived its raylet"
    agent_cluster.nodes.clear()   # raylet already stopped
