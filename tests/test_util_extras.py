"""P22 utility widening: multiprocessing.Pool shim, joblib backend,
parallel iterators, tqdm_ray, internal_kv.
(reference analogs: ray/util/multiprocessing, util/joblib, util/iter.py,
experimental/tqdm_ray.py, experimental/internal_kv.py)"""

import pytest

import ray_tpu
from ray_tpu.util.multiprocessing import Pool


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


def _sq(x):
    return x * x


def test_pool_map_and_apply(rt):
    with Pool(4) as pool:
        assert pool.map(_sq, range(10)) == [x * x for x in range(10)]
        assert pool.apply(_sq, (7,)) == 49
        r = pool.apply_async(_sq, (5,))
        assert r.get(timeout=10) == 25 and r.successful()


def test_pool_starmap_imap(rt):
    with Pool(2) as pool:
        assert pool.starmap(lambda a, b: a + b,
                            [(1, 2), (3, 4)]) == [3, 7]
        assert list(pool.imap(_sq, [1, 2, 3])) == [1, 4, 9]
        assert sorted(pool.imap_unordered(_sq, [1, 2, 3])) == [1, 4, 9]


def test_pool_closed_raises(rt):
    pool = Pool(2)
    pool.close()
    with pytest.raises(ValueError):
        pool.apply_async(_sq, (1,))


def test_pool_initializer(rt):
    def init(v):
        import os

        os.environ["POOL_INIT_V"] = str(v)

    def read(_):
        import os

        return os.environ.get("POOL_INIT_V")

    with Pool(2, initializer=init, initargs=(9,)) as pool:
        assert pool.map(read, [0]) == ["9"]


def test_joblib_backend(rt):
    import joblib

    from ray_tpu.util.joblib_backend import register_ray

    register_ray()
    with joblib.parallel_backend("ray_tpu"):
        out = joblib.Parallel(n_jobs=4)(
            joblib.delayed(_sq)(i) for i in range(8))
    assert out == [i * i for i in range(8)]


def test_parallel_iterator(rt):
    from ray_tpu.util import iter as rt_iter

    it = rt_iter.from_range(12, num_shards=3)
    out = sorted(it.for_each(lambda x: x * 2)
                   .filter(lambda x: x % 4 == 0)
                   .gather_sync())
    assert out == [0, 4, 8, 12, 16, 20]

    it2 = rt_iter.from_items(list(range(6)), num_shards=2).batch(2)
    batches = list(it2.gather_async())
    assert sorted(x for b in batches for x in b) == list(range(6))
    assert it2.num_shards == 2


def test_tqdm_ray(rt):
    from ray_tpu.experimental import tqdm_ray

    @ray_tpu.remote
    def work(n):
        bar = tqdm_ray.tqdm(desc="work", total=n)
        for _ in range(n):
            bar.update(1)
        return tqdm_ray.snapshot()

    snap = ray_tpu.get(work.remote(5))
    assert any(b["n"] == 5 for b in snap.values())
    # iteration interface + render
    list(tqdm_ray.tqdm(range(3), desc="iter"))
    out = tqdm_ray.render.__module__  # render is importable
    assert out


def test_internal_kv_local(rt):
    from ray_tpu.experimental import (internal_kv_del, internal_kv_get,
                                      internal_kv_list, internal_kv_put)

    assert internal_kv_put("k1", b"v1")
    assert internal_kv_get("k1") == b"v1"
    assert not internal_kv_put("k1", b"v2", overwrite=False)
    assert internal_kv_get("k1") == b"v1"
    assert "k1" in internal_kv_list("k")
    assert internal_kv_del("k1")
    assert internal_kv_get("k1") is None


def test_internal_kv_cluster():
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.experimental import internal_kv_get, internal_kv_put

    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)
        internal_kv_put("shared", b"cluster-val")

        @ray_tpu.remote
        def read():
            from ray_tpu.experimental import internal_kv_get as g

            return g("shared")

        assert ray_tpu.get(read.remote()) == b"cluster-val"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_parallel_iterator_branching(rt):
    """Transforms must not contaminate sibling iterators branched from
    the same parent (value semantics)."""
    from ray_tpu.util import iter as rt_iter

    base = rt_iter.from_range(10, num_shards=2)
    evens = base.filter(lambda x: x % 2 == 0)
    odds = base.filter(lambda x: x % 2 == 1)
    assert sorted(evens.gather_sync()) == [0, 2, 4, 6, 8]
    assert sorted(odds.gather_sync()) == [1, 3, 5, 7, 9]
    assert sorted(base.gather_sync()) == list(range(10))


def test_async_result_pending_semantics(rt):
    import time as _time

    with Pool(2) as pool:
        r = pool.apply_async(lambda: (_time.sleep(0.5), 1)[1])
        with pytest.raises(ValueError):
            r.successful()  # pending is not failure
        assert r.get(timeout=10) == 1
        assert r.successful()
