"""Chaos: spilling, node death, and lineage reconstruction interacting.

Reference analog: ``python/ray/tests/chaos/`` + NodeKillerActor
(``_private/test_utils.py:1401``) — kill nodes under memory pressure and
assert every object is still (re)computable.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.runtime.task_spec import SchedulingStrategy


@pytest.fixture
def chaos_cluster():
    ray_tpu.shutdown()
    c = Cluster(heartbeat_timeout_s=1.0)
    # small stores: the object set (20 x 4 MiB) overflows a node's shm,
    # so spilling MUST engage while the workload runs
    c.add_node(num_cpus=2, store_capacity=48 << 20)
    c.add_node(num_cpus=2, store_capacity=48 << 20, resources={"side": 4})
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_spill_plus_node_death_recovers_everything(chaos_cluster):
    victim = next(h for h in chaos_cluster.nodes.values()
                  if h.raylet is not None
                  and "side" in h.raylet.total_resources)

    @ray_tpu.remote(max_retries=3, scheduling_strategy=SchedulingStrategy(
        kind="NODE_AFFINITY", node_id=victim.node_id))
    def make(i):
        return np.full(1 << 19, i, dtype=np.float64)   # 4 MiB

    refs = [make.remote(i) for i in range(20)]
    # materialize half on the head (pull copies; victim spills under
    # pressure while serving these)
    for i in range(0, 20, 2):
        assert float(ray_tpu.get(refs[i], timeout=60)[0]) == float(i)

    chaos_cluster.remove_node(victim)
    time.sleep(2.5)   # heartbeat timeout -> locations dropped/tombstoned

    # EVERY object must still be readable: pulled copies from the head
    # store (possibly spilled there) or re-executed from lineage
    for i, ref in enumerate(refs):
        got = ray_tpu.get(ref, timeout=90)
        assert float(got[0]) == float(i), i
        del got
