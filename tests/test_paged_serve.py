"""Paged-KV serving engine (serve/paged_llm.py).

Reference: ABSENT from the reference (it serves via user code in
replicas, SURVEY.md P15); this is the vLLM-style paged KV design
TPU-first. Tests run the tiny llama config on CPU.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.models import llama
from ray_tpu.serve.llm import LLMEngine
from ray_tpu.serve.paged_llm import PagedLLMEngine


@pytest.fixture(scope="module")
def tiny():
    cfg = llama.llama_tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    # sharpen the head: random-weight logits sit near ties, and the
    # dense/paged engines compile DIFFERENT programs whose float
    # rounding can flip a near-tie greedy argmax — a 4x margin makes
    # exact token equality robust to program-level rounding
    params["lm_head"] = params["lm_head"] * 4.0
    return cfg, params


def _run(engine, prompts, max_new=16):
    # submit BEFORE start: admission happens in ONE deterministic wave
    # (thread timing otherwise splits waves, changing which prefill
    # program — and therefore which rounding — each request sees)
    reqs = [engine.submit(p, max_new_tokens=max_new) for p in prompts]
    engine.start()
    outs = [list(r.tokens()) for r in reqs]
    return reqs, outs


def test_paged_matches_dense_greedy(tiny):
    """Greedy decode through the paged engine must produce EXACTLY the
    dense engine's tokens — paging changes layout, not math."""
    cfg, params = tiny
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, int(n))
               for n in (24, 48, 13, 70)]
    dense = LLMEngine(cfg=cfg, params=params, max_batch=4, max_len=256)
    _, out_d = _run(dense, prompts)
    dense.stop()
    paged = PagedLLMEngine(cfg=cfg, params=params, max_batch=4,
                           max_len=256, page_size=32)
    _, out_p = _run(paged, prompts)
    st = paged.stats()
    paged.stop()
    assert out_p == out_d
    # the pool is half the dense equivalent by default
    assert st["kv_pages_bytes"] * 2 == st["kv_dense_equiv_bytes"]


def test_paged_matches_dense_across_admission_waves(tiny):
    """Requests admitted SEQUENTIALLY (multiple admission waves) must
    still match the dense engine — regression for the stale device
    active-mask/table after the first wave."""
    cfg, params = tiny
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, cfg.vocab_size, int(n))
               for n in (20, 33, 27)]

    def run_sequential(engine):
        engine.start()
        outs = []
        for p in prompts:   # one at a time: each is its own wave
            req = engine.submit(p, max_new_tokens=12)
            outs.append(list(req.tokens()))
        engine.stop()
        return outs

    dense = LLMEngine(cfg=cfg, params=params, max_batch=2, max_len=128)
    out_d = run_sequential(dense)
    paged = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                           max_len=128, page_size=32)
    out_p = run_sequential(paged)
    assert out_p == out_d


def test_pages_released_on_completion(tiny):
    cfg, params = tiny
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                         max_len=128, page_size=32)
    total = eng.num_pages
    rng = np.random.default_rng(1)
    _run(eng, [rng.integers(1, cfg.vocab_size, 20) for _ in range(4)],
         max_new=8)
    # deferred frees drain within a couple of chunk syncs; poke the
    # engine with one more request to age them out
    last = eng.submit(rng.integers(1, cfg.vocab_size, 8),
                      max_new_tokens=4)
    list(last.tokens())
    eng.stop()
    # every page except possibly the final request's deferred ones is back
    assert len(eng._alloc.free) >= total - 2


def test_pool_exhaustion_applies_backpressure(tiny):
    """More concurrent requests than the pool can hold: later requests
    WAIT for pages (no crash, no corruption) and still complete."""
    cfg, params = tiny
    # pool: 4 pages of 32 = 128 tokens; each request reserves
    # ceil((20+24)/32)+1 = 3 pages -> only one fits at a time
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=4,
                         max_len=128, page_size=32, num_pages=4)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, cfg.vocab_size, 20) for _ in range(3)]
    _, outs = _run(eng, prompts, max_new=24)
    eng.stop()
    assert all(len(o) == 24 for o in outs)


def test_reservation_larger_than_pool_rejected(tiny):
    """A request whose page reservation exceeds the whole pool must be
    REJECTED (requeueing it forever would hang it and head-of-line
    block the queue behind it)."""
    cfg, params = tiny
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                         max_len=256, page_size=32, num_pages=2)
    eng.start()
    big = eng.submit(np.ones(100, np.int32), max_new_tokens=64)
    small = eng.submit(np.ones(8, np.int32), max_new_tokens=8)
    with pytest.raises(MemoryError):
        list(big.tokens())
    # the queue behind the infeasible request still drains
    assert len(list(small.tokens())) == 8
    eng.stop()


def test_prompt_too_long_rejected(tiny):
    cfg, params = tiny
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                         max_len=64, page_size=32)
    eng.start()
    req = eng.submit(np.ones(64, np.int32), max_new_tokens=4)
    with pytest.raises(ValueError):
        list(req.tokens())
    eng.stop()


def test_temperature_sampling_runs(tiny):
    cfg, params = tiny
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                         max_len=128, page_size=32)
    eng.start()
    req = eng.submit(np.arange(1, 9, dtype=np.int32),
                     max_new_tokens=12, temperature=0.8)
    toks = list(req.tokens())
    eng.stop()
    assert len(toks) == 12
    assert all(0 <= t < cfg.vocab_size for t in toks)


def test_prefix_cache_reuse_and_correctness(tiny):
    """Requests sharing a full-page prompt prefix reuse its cached KV
    pages (suffix-only prefill) and produce EXACTLY the tokens a
    prefix-cache-disabled engine produces."""
    cfg, params = tiny
    rng = np.random.default_rng(3)
    base = rng.integers(1, cfg.vocab_size, 96)     # 3 full pages @ ps=32
    prompts = [base,
               np.concatenate([base, rng.integers(1, cfg.vocab_size, 20)]),
               np.concatenate([base, rng.integers(1, cfg.vocab_size, 7)])]

    ref = PagedLLMEngine(cfg=cfg, params=params, max_batch=2, max_len=256,
                         page_size=32, prefix_cache=False)
    _, out_ref = _run(ref, prompts)
    st_ref = ref.stats()
    ref.stop()
    assert st_ref["prefix_cache"]["hit_pages"] == 0

    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2, max_len=256,
                         page_size=32)
    _, out = _run(eng, prompts)
    st = eng.stats()
    eng.stop()
    assert out == out_ref
    # at least the second wave's tailed prompt hit the base's 3 pages
    assert st["prefix_cache"]["hit_pages"] >= 3


def test_prefix_cache_eviction_under_pressure(tiny):
    """Idle cached prefix pages are LRU-evicted when admission needs
    their space; the engine keeps serving distinct prompts forever on a
    small pool."""
    cfg, params = tiny
    rng = np.random.default_rng(4)
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2, max_len=128,
                         page_size=32, num_pages=8)
    eng.start()
    for _ in range(5):
        r = eng.submit(rng.integers(1, cfg.vocab_size, 64),
                       max_new_tokens=8)
        assert len(list(r.tokens())) == 8
    st = eng.stats()
    eng.stop()
    pc = st["prefix_cache"]
    assert pc["cached_idle_pages"] + len(eng._alloc.free) <= eng.num_pages


def test_prefix_cache_exact_prompt_repeat(tiny):
    """Repeating an identical prompt reuses every full page except the
    sampling tail (at least one suffix token always prefills so the
    first output token has logits)."""
    cfg, params = tiny
    rng = np.random.default_rng(5)
    prompt = rng.integers(1, cfg.vocab_size, 64)   # exactly 2 full pages
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=1, max_len=128,
                         page_size=32, num_pages=8)
    eng.start()
    r1 = eng.submit(prompt, max_new_tokens=6)
    out1 = list(r1.tokens())
    r2 = eng.submit(prompt, max_new_tokens=6)
    out2 = list(r2.tokens())
    st = eng.stats()
    eng.stop()
    assert out1 == out2                     # greedy + same prompt
    # max reuse for plen 64 is (64-1)//32 = 1 page (suffix stays nonempty)
    assert st["prefix_cache"]["hit_pages"] >= 1


def test_warmup_prefix_compiles_suffix_variants(tiny):
    """warmup_prefix pre-compiles the suffix-bucket programs so a
    shared-prefix hit reuses a cached jit entry instead of compiling
    inside its TTFT."""
    cfg, params = tiny
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2, max_len=256,
                         page_size=32, num_pages=16)
    eng.warmup_prefix(prefix_len=64, tail_len=20, max_n=2)
    wp = eng._window_pages(64 + 32)    # tail bucket = 32
    assert wp in eng._prefill_cache
    rng = np.random.default_rng(6)
    base = rng.integers(1, cfg.vocab_size, 64)
    prompts = [base,
               np.concatenate([base, rng.integers(1, cfg.vocab_size, 20)])]
    _, outs = _run(eng, prompts, max_new=6)
    eng.stop()
    assert all(len(o) == 6 for o in outs)


def test_kv_quantization_roundtrip_error():
    from ray_tpu.ops.paged_attention import dequantize_kv, quantize_kv
    x = jax.random.normal(jax.random.key(0), (4, 16, 2, 64),
                          jnp.bfloat16) * 3.0
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == x.shape[:-1]
    y = dequantize_kv(q, s)
    rel = (np.abs(np.asarray(y, np.float32) - np.asarray(x, np.float32))
           / (np.abs(np.asarray(x, np.float32)).max()))
    assert rel.max() < 0.01
    # zero rows stay exactly zero (scale guard, no 0/0)
    q0, s0 = quantize_kv(jnp.zeros((2, 3, 1, 8), jnp.bfloat16))
    assert np.all(np.asarray(q0) == 0)
    assert np.all(np.asarray(dequantize_kv(q0, s0)) == 0)


def test_int8_kv_engine_serves(tiny):
    """kv_dtype="int8" halves page bytes and still serves correct-shape,
    deterministic streams (greedy outputs may differ from bf16 by
    quantization rounding — determinism and plausibility are the
    contract)."""
    cfg, params = tiny
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, cfg.vocab_size, int(n)) for n in (24, 40)]

    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2, max_len=128,
                         page_size=32, kv_dtype="int8")
    st = eng.stats()
    _, outs = _run(eng, prompts, max_new=12)
    eng.stop()
    assert all(len(o) == 12 for o in outs)
    assert all(0 <= t < cfg.vocab_size for o in outs for t in o)
    assert st["kv_dtype"] == "int8"

    bf16 = PagedLLMEngine(cfg=cfg, params=params, max_batch=2, max_len=128,
                          page_size=32)
    assert st["kv_pages_bytes"] < bf16.stats()["kv_pages_bytes"]
    bf16.stop()

    # deterministic: a fresh int8 engine reproduces the same tokens
    eng2 = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                          max_len=128, page_size=32, kv_dtype="int8")
    _, outs2 = _run(eng2, prompts, max_new=12)
    eng2.stop()
    assert outs == outs2


def test_int8_kv_with_prefix_cache(tiny):
    """Prefix caching composes with int8 KV: reused pages carry the
    SAME quantized content the original prompt wrote, so a repeat
    prompt decodes identically with and without the cached prefix."""
    cfg, params = tiny
    rng = np.random.default_rng(8)
    prompt = rng.integers(1, cfg.vocab_size, 80)   # 2 full pages @32
    cold = PagedLLMEngine(cfg=cfg, params=params, max_batch=1,
                          max_len=128, page_size=32, num_pages=8,
                          kv_dtype="int8", prefix_cache=False)
    cold.start()
    want = list(cold.submit(prompt, max_new_tokens=8).tokens())
    cold.stop()

    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=1,
                         max_len=128, page_size=32, num_pages=8,
                         kv_dtype="int8")
    eng.start()
    a = list(eng.submit(prompt, max_new_tokens=8).tokens())
    b = list(eng.submit(prompt, max_new_tokens=8).tokens())
    st = eng.stats()
    eng.stop()
    assert a == want and b == want
    assert st["prefix_cache"]["hit_pages"] >= 2
