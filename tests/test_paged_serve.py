"""Paged-KV serving engine (serve/paged_llm.py).

Reference: ABSENT from the reference (it serves via user code in
replicas, SURVEY.md P15); this is the vLLM-style paged KV design
TPU-first. Tests run the tiny llama config on CPU.
"""

import numpy as np
import pytest

import jax

from ray_tpu.models import llama
from ray_tpu.serve.llm import LLMEngine
from ray_tpu.serve.paged_llm import PagedLLMEngine


@pytest.fixture(scope="module")
def tiny():
    cfg = llama.llama_tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    # sharpen the head: random-weight logits sit near ties, and the
    # dense/paged engines compile DIFFERENT programs whose float
    # rounding can flip a near-tie greedy argmax — a 4x margin makes
    # exact token equality robust to program-level rounding
    params["lm_head"] = params["lm_head"] * 4.0
    return cfg, params


def _run(engine, prompts, max_new=16):
    # submit BEFORE start: admission happens in ONE deterministic wave
    # (thread timing otherwise splits waves, changing which prefill
    # program — and therefore which rounding — each request sees)
    reqs = [engine.submit(p, max_new_tokens=max_new) for p in prompts]
    engine.start()
    outs = [list(r.tokens()) for r in reqs]
    return reqs, outs


def test_paged_matches_dense_greedy(tiny):
    """Greedy decode through the paged engine must produce EXACTLY the
    dense engine's tokens — paging changes layout, not math."""
    cfg, params = tiny
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, cfg.vocab_size, int(n))
               for n in (24, 48, 13, 70)]
    dense = LLMEngine(cfg=cfg, params=params, max_batch=4, max_len=256)
    _, out_d = _run(dense, prompts)
    dense.stop()
    paged = PagedLLMEngine(cfg=cfg, params=params, max_batch=4,
                           max_len=256, page_size=32)
    _, out_p = _run(paged, prompts)
    st = paged.stats()
    paged.stop()
    assert out_p == out_d
    # the pool is half the dense equivalent by default
    assert st["kv_pages_bytes"] * 2 == st["kv_dense_equiv_bytes"]


def test_paged_matches_dense_across_admission_waves(tiny):
    """Requests admitted SEQUENTIALLY (multiple admission waves) must
    still match the dense engine — regression for the stale device
    active-mask/table after the first wave."""
    cfg, params = tiny
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, cfg.vocab_size, int(n))
               for n in (20, 33, 27)]

    def run_sequential(engine):
        engine.start()
        outs = []
        for p in prompts:   # one at a time: each is its own wave
            req = engine.submit(p, max_new_tokens=12)
            outs.append(list(req.tokens()))
        engine.stop()
        return outs

    dense = LLMEngine(cfg=cfg, params=params, max_batch=2, max_len=128)
    out_d = run_sequential(dense)
    paged = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                           max_len=128, page_size=32)
    out_p = run_sequential(paged)
    assert out_p == out_d


def test_pages_released_on_completion(tiny):
    cfg, params = tiny
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                         max_len=128, page_size=32)
    total = eng.num_pages
    rng = np.random.default_rng(1)
    _run(eng, [rng.integers(1, cfg.vocab_size, 20) for _ in range(4)],
         max_new=8)
    # deferred frees drain within a couple of chunk syncs; poke the
    # engine with one more request to age them out
    last = eng.submit(rng.integers(1, cfg.vocab_size, 8),
                      max_new_tokens=4)
    list(last.tokens())
    eng.stop()
    # every page except possibly the final request's deferred ones is back
    assert len(eng._alloc.free) >= total - 2


def test_pool_exhaustion_applies_backpressure(tiny):
    """More concurrent requests than the pool can hold: later requests
    WAIT for pages (no crash, no corruption) and still complete."""
    cfg, params = tiny
    # pool: 4 pages of 32 = 128 tokens; each request reserves
    # ceil((20+24)/32)+1 = 3 pages -> only one fits at a time
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=4,
                         max_len=128, page_size=32, num_pages=4)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, cfg.vocab_size, 20) for _ in range(3)]
    _, outs = _run(eng, prompts, max_new=24)
    eng.stop()
    assert all(len(o) == 24 for o in outs)


def test_reservation_larger_than_pool_rejected(tiny):
    """A request whose page reservation exceeds the whole pool must be
    REJECTED (requeueing it forever would hang it and head-of-line
    block the queue behind it)."""
    cfg, params = tiny
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                         max_len=256, page_size=32, num_pages=2)
    eng.start()
    big = eng.submit(np.ones(100, np.int32), max_new_tokens=64)
    small = eng.submit(np.ones(8, np.int32), max_new_tokens=8)
    with pytest.raises(MemoryError):
        list(big.tokens())
    # the queue behind the infeasible request still drains
    assert len(list(small.tokens())) == 8
    eng.stop()


def test_prompt_too_long_rejected(tiny):
    cfg, params = tiny
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                         max_len=64, page_size=32)
    eng.start()
    req = eng.submit(np.ones(64, np.int32), max_new_tokens=4)
    with pytest.raises(ValueError):
        list(req.tokens())
    eng.stop()


def test_temperature_sampling_runs(tiny):
    cfg, params = tiny
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=2,
                         max_len=128, page_size=32)
    eng.start()
    req = eng.submit(np.arange(1, 9, dtype=np.int32),
                     max_new_tokens=12, temperature=0.8)
    toks = list(req.tokens())
    eng.stop()
    assert len(toks) == 12
    assert all(0 <= t < cfg.vocab_size for t in toks)
