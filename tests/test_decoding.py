"""KV-cache decoding + continuous-batching engine tests.

Correctness anchor: prefill+decode through the cache must reproduce the
full (uncached) forward pass exactly under greedy sampling.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import decoding, llama
from ray_tpu.models.decoding import SamplingParams


@pytest.fixture(scope="module")
def tiny():
    cfg = llama.llama_tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    return cfg, params


def greedy_teacher_forced(cfg, params, prompt, n_new):
    """Reference decode: rerun the full forward each step."""
    seq = list(prompt)
    out = []
    for _ in range(n_new):
        tokens = jnp.asarray(seq, jnp.int32)[None, :]
        logits = llama.forward(cfg, params, tokens, attn_impl="reference")
        tok = int(jnp.argmax(logits[0, -1]))
        out.append(tok)
        seq.append(tok)
    return out


def test_cached_forward_matches_forward(tiny):
    cfg, params = tiny
    tokens = jax.random.randint(jax.random.key(1), (2, 24), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    full = llama.forward(cfg, params, tokens, attn_impl="reference")
    cache = decoding.init_cache(cfg, 2, 48)
    cached, _ = decoding.cached_forward(
        cfg, params, tokens, cache,
        start=jnp.zeros((2,), jnp.int32), logits_mode="all")
    np.testing.assert_allclose(np.asarray(full), np.asarray(cached),
                               rtol=2e-2, atol=2e-2)


def test_incremental_decode_matches_prefill(tiny):
    """Feeding tokens one at a time through the cache == one-shot prefill."""
    cfg, params = tiny
    tokens = jax.random.randint(jax.random.key(2), (1, 16), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    cache = decoding.init_cache(cfg, 1, 32)
    oneshot, _ = decoding.cached_forward(
        cfg, params, tokens, cache,
        start=jnp.zeros((1,), jnp.int32), logits_mode="last")

    cache = decoding.init_cache(cfg, 1, 32)
    for t in range(16):
        step_logits, cache = decoding.cached_forward(
            cfg, params, tokens[:, t:t + 1], cache,
            start=jnp.full((1,), t, jnp.int32), logits_mode="last")
    np.testing.assert_allclose(np.asarray(oneshot), np.asarray(step_logits),
                               rtol=2e-2, atol=2e-2)


def test_generate_greedy_matches_teacher_forced(tiny):
    cfg, params = tiny
    prompt = [3, 17, 99, 254, 7]
    n_new = 8
    want = greedy_teacher_forced(cfg, params, prompt, n_new)
    prompts = jnp.asarray([prompt], jnp.int32)
    got = decoding.generate(
        cfg, params, prompts,
        sampling=SamplingParams(temperature=0.0, max_new_tokens=n_new))
    assert np.asarray(got)[0].tolist() == want


def test_generate_batch_right_padded(tiny):
    """Rows with different prompt lengths decode independently and each
    matches its single-row run (padding must not leak)."""
    cfg, params = tiny
    p1, p2 = [5, 9, 13], [21, 34, 55, 89, 144, 233]
    n_new = 6
    pad = max(len(p1), len(p2))
    batch = np.zeros((2, pad), np.int32)
    batch[0, :len(p1)] = p1
    batch[1, :len(p2)] = p2
    sp = SamplingParams(temperature=0.0, max_new_tokens=n_new)
    got = np.asarray(decoding.generate(cfg, params, jnp.asarray(batch),
                                       sampling=sp))
    want1 = greedy_teacher_forced(cfg, params, p1, n_new)
    want2 = greedy_teacher_forced(cfg, params, p2, n_new)
    assert got[0].tolist() == want1
    assert got[1].tolist() == want2


def test_generate_eos_stops(tiny):
    cfg, params = tiny
    prompt = [3, 17, 99]
    want = greedy_teacher_forced(cfg, params, prompt, 8)
    eos = want[1]
    stop = want.index(eos)  # first occurrence is where generation must stop
    got = np.asarray(decoding.generate(
        cfg, params, jnp.asarray([prompt], jnp.int32),
        sampling=SamplingParams(temperature=0.0, max_new_tokens=8),
        eos_id=eos))[0]
    assert got[stop] == eos
    assert got[:stop].tolist() == want[:stop]
    assert all(t == 0 for t in got[stop + 1:])  # pad after eos


def test_sample_top_k_top_p():
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0]])
    key = jax.random.key(0)
    # top_k=1 == greedy regardless of key
    sp = SamplingParams(temperature=1.0, top_k=1)
    for i in range(5):
        tok = decoding.sample(logits, jax.random.fold_in(key, i), sp)
        assert int(tok[0]) == 3
    # top_p tiny -> only the argmax survives
    sp = SamplingParams(temperature=1.0, top_p=0.1)
    for i in range(5):
        tok = decoding.sample(logits, jax.random.fold_in(key, i), sp)
        assert int(tok[0]) == 3


# ---------------------------------------------------------------------------
# Continuous batching engine
# ---------------------------------------------------------------------------

def test_llm_engine_streams_and_matches_offline(tiny):
    from ray_tpu.serve.llm import LLMEngine

    cfg, params = tiny
    eng = LLMEngine(cfg, params, max_batch=4, max_len=128)
    eng.start()
    try:
        prompts = [[3, 17, 99, 254, 7], [5, 9, 13], [21, 34, 55, 89]]
        n_new = 6
        reqs = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
        outs = [list(r.tokens()) for r in reqs]
        for p, got in zip(prompts, outs):
            want = greedy_teacher_forced(cfg, params, p, n_new)
            assert got == want, f"prompt {p}: {got} != {want}"
        stats = eng.stats()
        assert stats["total_finished"] == 3
        assert stats["mean_ttft_s"] is not None
        for r in reqs:
            assert r.ttft is not None and r.ttft >= 0
    finally:
        eng.stop()


def _tiny_builder():
    cfg = llama.llama_tiny()
    return cfg, llama.init_params(cfg, jax.random.key(0))


def test_llm_deployment_via_serve(ray_tpu_start):
    """End-to-end: LLMEngine hosted in a Serve replica actor."""
    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMDeployment

    try:
        dep = serve.deployment(LLMDeployment).bind(
            _tiny_builder, max_batch=2, max_len=64)
        handle = serve.run(dep, name="llm")
        prompt = [3, 17, 99]
        got = handle.call(prompt, max_new_tokens=4)
        cfg, params = _tiny_builder()
        assert got == greedy_teacher_forced(cfg, params, prompt, 4)
    finally:
        serve.shutdown()


def test_llm_engine_more_requests_than_slots(tiny):
    """Requests beyond max_batch queue up and still complete correctly."""
    from ray_tpu.serve.llm import LLMEngine

    cfg, params = tiny
    eng = LLMEngine(cfg, params, max_batch=2, max_len=64)
    eng.start()
    try:
        prompts = [[i + 1, i + 2, i + 3] for i in range(5)]
        reqs = [eng.submit(p, max_new_tokens=4) for p in prompts]
        outs = [list(r.tokens()) for r in reqs]
        for p, got in zip(prompts, outs):
            assert got == greedy_teacher_forced(cfg, params, p, 4)
        assert eng.stats()["total_finished"] == 5
    finally:
        eng.stop()
