"""Worker-log forwarding: prints inside tasks/actors surface on the
driver (reference: log_monitor.py -> GCS pubsub -> driver stdout)."""

import sys
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=4)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _wait_for(capsys_readouterr, needle: str, timeout: float = 10.0):
    """Poll captured stdout+stderr until needle appears."""
    deadline = time.monotonic() + timeout
    seen = ""
    while time.monotonic() < deadline:
        cap = capsys_readouterr()
        seen += cap.out + cap.err
        if needle in seen:
            return seen
        time.sleep(0.2)
    raise AssertionError(f"{needle!r} never reached the driver; saw:\n"
                         f"{seen[-2000:]}")


def test_task_prints_reach_driver(cluster, capsys):
    @ray_tpu.remote
    def chatty():
        print("hello-from-task-xyzzy")
        return 1

    assert ray_tpu.get(chatty.remote()) == 1
    out = _wait_for(capsys.readouterr, "hello-from-task-xyzzy")
    # prefixed with worker identity like the reference
    line = next(ln for ln in out.splitlines()
                if "hello-from-task-xyzzy" in ln)
    assert "pid=" in line and "node=" in line


def test_actor_stderr_reaches_driver(cluster, capsys):
    @ray_tpu.remote
    class Grumbler:
        def grumble(self):
            print("grumble-err-qwerty", file=sys.stderr)
            return "ok"

    g = Grumbler.remote()
    assert ray_tpu.get(g.grumble.remote()) == "ok"
    _wait_for(capsys.readouterr, "grumble-err-qwerty")


def test_log_to_driver_false_suppresses(capsys):
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    try:
        ray_tpu.init(address=c.gcs_address, log_to_driver=False)

        @ray_tpu.remote
        def quiet():
            print("should-not-appear-plugh")
            return 2

        assert ray_tpu.get(quiet.remote()) == 2
        time.sleep(1.5)  # give any (wrong) forwarding time to land
        cap = capsys.readouterr()
        assert "should-not-appear-plugh" not in cap.out + cap.err
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_crashed_worker_last_words_reach_driver(cluster, capsys):
    """The pool reaps a dead worker's handle within ~0.1s; the monitor
    scans the log DIRECTORY so output written right before a hard crash
    still ships."""
    @ray_tpu.remote
    def die():
        import os as _os

        print("lastwords-grault", file=sys.stderr, flush=True)
        _os._exit(1)   # hard kill: no cleanup, no reply

    with pytest.raises(Exception):
        ray_tpu.get(die.remote())
    _wait_for(capsys.readouterr, "lastwords-grault")
