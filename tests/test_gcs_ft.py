"""GCS fault tolerance (VERDICT r1 item 4): file-backed snapshot + WAL,
restart reload, reconciliation with re-registering raylets.

Reference: ``store_client/redis_store_client.h:33`` persistence +
``gcs_init_data.cc`` restart reload (file-backed here; Redis is not in
the image)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.runtime import fault_injection as fi


@pytest.fixture
def ft_cluster():
    ray_tpu.shutdown()
    c = Cluster(gcs_fault_tolerance=True, heartbeat_timeout_s=2.0)
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_persistence_unit_roundtrip(tmp_path):
    from ray_tpu.runtime.gcs import GcsPersistence

    p = GcsPersistence(str(tmp_path / "gcs"))
    p.append(("kv", ("ns", "a"), b"1"))
    p.append(("kv", ("ns", "b"), b"2"))
    state, records = p.load()
    assert state is None and len(records) == 2
    p.snapshot({"kv": {"ns": {"a": b"1", "b": b"2"}}, "actors": {},
                "named_actors": {}, "pgs": {}, "jobs": {},
                "object_dir": {}, "object_meta": {}, "lost_objects": []})
    p.append(("kv", ("ns", "c"), b"3"))
    state, records = p.load()
    assert state["kv"]["ns"]["a"] == b"1"
    assert records == [("kv", ("ns", "c"), b"3")]
    p.close()


def test_gcs_restart_preserves_named_actors_and_kv(ft_cluster):
    c = ft_cluster

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, k):
            self.n += k
            return self.n

    counter = Counter.options(name="survivor").remote()
    assert ray_tpu.get(counter.add.remote(5)) == 5
    from ray_tpu.experimental import internal_kv

    internal_kv.internal_kv_put("durable_key", b"durable_value")
    time.sleep(0.3)   # WAL flush is synchronous; just settle in-flight

    c.kill_gcs()      # crash: no final snapshot — WAL carries the state
    time.sleep(0.5)
    c.restart_gcs()
    c.wait_for_nodes(1, timeout=10)

    # named actor resolvable AND its (never-restarted) instance retains
    # in-memory state: the worker process outlived the control plane
    again = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(again.add.remote(1), timeout=20) == 6
    assert internal_kv.internal_kv_get("durable_key") == b"durable_value"


# ----------------------------------------------------------------------
# crash coverage of the WAL window (round 10): kill the GCS BETWEEN the
# WAL append and the client reply. The record is durable but the caller
# never hears back — after the restart the retried request must be
# absorbed by idempotency, not applied twice.
# ----------------------------------------------------------------------

@pytest.fixture
def crash_ft_cluster(monkeypatch):
    monkeypatch.setenv("RAY_TPU_FAULT_INJECTION_ENABLED", "1")
    ray_tpu.shutdown()
    fi.plane.clear()
    # external GCS: the injected death must kill a real process, not
    # the test interpreter
    c = Cluster(gcs_fault_tolerance=True, external_gcs=True,
                heartbeat_timeout_s=2.0)
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    c.start_supervisor(poll_s=0.2)
    yield c
    ray_tpu.shutdown()
    fi.stop_kv_watcher()
    c.shutdown()
    fi.plane.clear()


def _arm_wal_crash(c):
    """One crash on the NEXT WAL append. The put installing this plan
    runs through rpc_kv_put itself, but its crash point is consulted
    BEFORE the plan self-applies — only the following append can fire."""
    fi.put_plan(c.gcs_address, {"version": 1, "rules": [
        {"id": "walcrash", "fault": "crash",
         "point": "gcs.after_wal_append", "proc": "gcs", "nth": 1}]})


def _wait_gcs_respawn(c, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if any(ev["class"] == "gcs" for ev in c.crash_events):
            return
        time.sleep(0.1)
    pytest.fail("supervisor never restarted the crashed GCS")


def test_gcs_crash_between_wal_append_and_reply_kv_put(crash_ft_cluster):
    c = crash_ft_cluster
    from ray_tpu.experimental import internal_kv

    _arm_wal_crash(c)
    try:
        # WAL-logged, then the GCS dies before replying; the client's
        # redial window may retry into the restarted GCS (where the key
        # already exists) or burn out — both are fine here
        internal_kv.internal_kv_put("walkey", b"first", overwrite=False)
    except Exception:  # noqa: BLE001 - reply lost to the injected crash
        pass
    _wait_gcs_respawn(c)

    # durable despite the lost reply: WAL replay restored the write
    assert internal_kv.internal_kv_get("walkey") == b"first"
    # the caller-side retry of the unacked put must be ABSORBED (key
    # exists from replay), never clobber the durable value
    internal_kv.internal_kv_put("walkey", b"second", overwrite=False)
    assert internal_kv.internal_kv_get("walkey") == b"first"
    # the repaired control plane takes new writes
    internal_kv.internal_kv_put("postcrash", b"ok")
    assert internal_kv.internal_kv_get("postcrash") == b"ok"

    ev = next(e for e in c.crash_events if e["class"] == "gcs")
    assert ev["crash_point"] == "gcs.after_wal_append"
    assert any(fi.CRASH_MARKER in ln for ln in (ev["last_words"] or ()))


def test_gcs_crash_between_wal_append_and_reply_register(crash_ft_cluster):
    c = crash_ft_cluster

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, k):
            self.n += k
            return self.n

    _arm_wal_crash(c)
    # the registration frame WAL-logs the actor, then the GCS dies
    # before acking; the coalescer's redial retries the batch against
    # the restarted GCS where per-actor-id idempotency absorbs it
    actor = Counter.options(name="walsurvivor").remote()
    assert ray_tpu.get(actor.add.remote(5), timeout=60) == 5
    _wait_gcs_respawn(c)

    # exactly ONE instance: the name resolves to the same live actor
    # (a double-register would have rejected its own name or spawned a
    # second instance with fresh state)
    again = ray_tpu.get_actor("walsurvivor")
    assert ray_tpu.get(again.add.remote(1), timeout=30) == 6


def test_gcs_restart_pending_task_completes(ft_cluster):
    """Kill the GCS while tasks are in flight: the data plane (leases,
    shm, workers) keeps running; after restart everything reconciles and
    results come back."""
    c = ft_cluster

    @ray_tpu.remote
    def slow(x):
        time.sleep(2.0)
        return x * 3

    refs = [slow.remote(i) for i in range(4)]
    time.sleep(0.3)           # tasks now running on leased workers
    c.kill_gcs()
    time.sleep(0.5)
    c.restart_gcs()
    assert ray_tpu.get(refs, timeout=30) == [0, 3, 6, 9]

    # and NEW work flows after the restart
    assert ray_tpu.get(slow.remote(10), timeout=30) == 30
