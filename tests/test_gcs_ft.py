"""GCS fault tolerance (VERDICT r1 item 4): file-backed snapshot + WAL,
restart reload, reconciliation with re-registering raylets.

Reference: ``store_client/redis_store_client.h:33`` persistence +
``gcs_init_data.cc`` restart reload (file-backed here; Redis is not in
the image)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def ft_cluster():
    ray_tpu.shutdown()
    c = Cluster(gcs_fault_tolerance=True, heartbeat_timeout_s=2.0)
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_persistence_unit_roundtrip(tmp_path):
    from ray_tpu.runtime.gcs import GcsPersistence

    p = GcsPersistence(str(tmp_path / "gcs"))
    p.append(("kv", ("ns", "a"), b"1"))
    p.append(("kv", ("ns", "b"), b"2"))
    state, records = p.load()
    assert state is None and len(records) == 2
    p.snapshot({"kv": {"ns": {"a": b"1", "b": b"2"}}, "actors": {},
                "named_actors": {}, "pgs": {}, "jobs": {},
                "object_dir": {}, "object_meta": {}, "lost_objects": []})
    p.append(("kv", ("ns", "c"), b"3"))
    state, records = p.load()
    assert state["kv"]["ns"]["a"] == b"1"
    assert records == [("kv", ("ns", "c"), b"3")]
    p.close()


def test_gcs_restart_preserves_named_actors_and_kv(ft_cluster):
    c = ft_cluster

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, k):
            self.n += k
            return self.n

    counter = Counter.options(name="survivor").remote()
    assert ray_tpu.get(counter.add.remote(5)) == 5
    from ray_tpu.experimental import internal_kv

    internal_kv.internal_kv_put("durable_key", b"durable_value")
    time.sleep(0.3)   # WAL flush is synchronous; just settle in-flight

    c.kill_gcs()      # crash: no final snapshot — WAL carries the state
    time.sleep(0.5)
    c.restart_gcs()
    c.wait_for_nodes(1, timeout=10)

    # named actor resolvable AND its (never-restarted) instance retains
    # in-memory state: the worker process outlived the control plane
    again = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(again.add.remote(1), timeout=20) == 6
    assert internal_kv.internal_kv_get("durable_key") == b"durable_value"


def test_gcs_restart_pending_task_completes(ft_cluster):
    """Kill the GCS while tasks are in flight: the data plane (leases,
    shm, workers) keeps running; after restart everything reconciles and
    results come back."""
    c = ft_cluster

    @ray_tpu.remote
    def slow(x):
        time.sleep(2.0)
        return x * 3

    refs = [slow.remote(i) for i in range(4)]
    time.sleep(0.3)           # tasks now running on leased workers
    c.kill_gcs()
    time.sleep(0.5)
    c.restart_gcs()
    assert ray_tpu.get(refs, timeout=30) == [0, 3, 6, 9]

    # and NEW work flows after the restart
    assert ray_tpu.get(slow.remote(10), timeout=30) == 30
