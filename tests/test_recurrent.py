"""Recurrent (GRU/LSTM) policies: cell math, sequence forward parity,
fragment collection with stored initial state, and learning on a
memory env (reference: rllib/models/torch/recurrent_net.py:25,
rllib/policy/rnn_sequencing.py)."""

import numpy as np
import pytest

from ray_tpu.rllib.recurrent import (
    MemoryCueEnv,
    RecurrentPPOConfig,
    _RecurrentRolloutWorker,
    forward_recurrent_seq,
    init_recurrent_module,
    np_recurrent_step,
    zero_state,
)


@pytest.fixture
def rt(ray_tpu_start):
    return ray_tpu_start


@pytest.mark.parametrize("cell", ["gru", "lstm"])
def test_np_and_jax_forward_agree(cell):
    """The rollout worker's numpy step must replay bit-for-bit what the
    learner's lax.scan computes (same params, same inputs)."""
    import jax

    params = init_recurrent_module(jax.random.key(0), 3, 2, hidden=8,
                                   cell=cell)
    params_np = {k: (v if k == "cell_type"
                     else jax.tree.map(np.asarray, v))
                 for k, v in params.items()}
    B, T = 2, 5
    rng = np.random.default_rng(0)
    obs_seq = rng.normal(size=(B, T, 3)).astype(np.float32)
    dones = np.zeros((B, T), np.float32)
    logits_j, values_j, hT = forward_recurrent_seq(
        params, obs_seq, zero_state(params_np, B), dones)
    state = zero_state(params_np, B)
    for t in range(T):
        logits_n, values_n, state = np_recurrent_step(
            params_np, obs_seq[:, t], state)
        np.testing.assert_allclose(logits_n, np.asarray(logits_j[:, t]),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(values_n, np.asarray(values_j[:, t]),
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(state, np.asarray(hT), rtol=1e-4,
                               atol=1e-5)


def test_done_resets_state_inside_fragment():
    """A done at step t must zero the carried state before t+1 — the
    scan's mask, not a host branch."""
    import jax

    params = init_recurrent_module(jax.random.key(1), 2, 2, hidden=4)
    B, T = 1, 4
    obs = np.ones((B, T, 2), np.float32)
    dones = np.zeros((B, T), np.float32)
    dones[0, 1] = 1.0   # episode ends after step 1
    logits, _, _ = forward_recurrent_seq(
        params, obs, zero_state(params, B), dones)
    # step 2 saw zeroed state + same obs as step 0 -> identical logits
    np.testing.assert_allclose(np.asarray(logits[0, 2]),
                               np.asarray(logits[0, 0]), rtol=1e-5)


def test_fragments_store_initial_state():
    import jax

    params = init_recurrent_module(jax.random.key(0), 2, 2, hidden=8)
    params_np = {k: (v if k == "cell_type"
                     else jax.tree.map(np.asarray, v))
                 for k, v in params.items()}
    w = _RecurrentRolloutWorker(MemoryCueEnv, seed=0, max_seq_len=4)
    batch = w.sample(params_np, num_steps=32, gamma=0.99, lam=0.95)
    assert batch["h0"].shape[1] == 8
    assert batch["obs"].shape[1] == 4          # padded to max_seq_len
    assert set(np.unique(batch["mask"])) <= {0.0, 1.0}
    # MemoryCueEnv episodes are 3 steps; every fragment starts at an
    # episode boundary here, so its stored state is the zero state
    np.testing.assert_allclose(batch["h0"], 0.0)


def test_memory_env_requires_memory():
    """Sanity: a memoryless optimal play of MemoryCueEnv caps at 0.5
    expected reward (the cue is unobservable at decision time)."""
    env = MemoryCueEnv(seed=0)
    total = 0.0
    episodes = 200
    for _ in range(episodes):
        env.reset()
        done = False
        while not done:
            _, r, done, _ = env.step(1)   # constant action
            total += r
    assert 0.3 <= total / episodes <= 0.7


@pytest.mark.parametrize("cell", ["gru", "lstm"])
def test_recurrent_ppo_learns_memory_env(rt, cell):
    algo = (RecurrentPPOConfig()
            .environment(MemoryCueEnv)
            .rollouts(num_rollout_workers=1,
                      rollout_fragment_length=256)
            .training(cell=cell, max_seq_len=4, lr=5e-3, hidden=32,
                      num_sgd_iter=4, seed=0)
            .build())
    try:
        best = -np.inf
        for _ in range(40):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 0.9:
                break
        # memoryless ceiling is 0.5; >=0.9 proves the cue is remembered
        assert best >= 0.9, f"{cell} failed to learn memory task: {best}"
    finally:
        algo.stop()


def test_impala_recurrent_learns_memory_env(rt):
    from ray_tpu.rllib.impala import IMPALAConfig

    algo = (IMPALAConfig()
            .environment(MemoryCueEnv)
            .rollouts(num_rollout_workers=1, num_envs_per_worker=8)
            .training(cell="gru", unroll_length=32, lr=5e-3, hidden=32,
                      seed=0)
            .build())
    try:
        best = -np.inf
        for _ in range(60):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 0.9:
                break
        assert best >= 0.9, f"recurrent IMPALA failed on memory: {best}"
    finally:
        algo.stop()


def test_stateless_cartpole_obs_dim():
    from ray_tpu.rllib.recurrent import StatelessCartPole

    env = StatelessCartPole(seed=0)
    assert env.reset().shape == (2,)
    obs, r, d, _ = env.step(0)
    assert obs.shape == (2,)
