"""Broadcast-capable object plane: multi-source striped pulls + cloud
(pyarrow.fs URI) spill targets.

Reference analog: ``ObjectManager::Push`` (object_manager.cc:339 —
proactive chunk spreading; pull-based here: chunks stripe across every
registered holder and the holder set refreshes mid-transfer) and
``_private/external_storage.py`` (smart_open/S3 spilling; pyarrow.fs
URIs here).
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.utils.config import reset_config


@pytest.fixture
def bcast_cluster(monkeypatch):
    # small chunks so striping/refresh paths actually run
    monkeypatch.setenv("RAY_TPU_OBJECT_TRANSFER_CHUNK_BYTES", str(1 << 20))
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=1, store_capacity=512 << 20)   # head/driver
    for _ in range(4):                                  # consumers
        c.add_node(num_cpus=1, store_capacity=512 << 20)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    reset_config()


def test_broadcast_fans_out_across_holders(bcast_cluster):
    """One hot object consumed on every node: pulls stripe across
    holders (the holder set grows as consumers finish), and all copies
    are intact."""
    c = bcast_cluster

    @ray_tpu.remote
    def produce():
        return np.arange((48 << 20) // 8, dtype=np.float64)   # 48 MiB

    ref = produce.remote()
    expect = np.arange((48 << 20) // 8, dtype=np.float64)
    np.testing.assert_array_equal(ray_tpu.get(ref, timeout=60), expect)

    # every node pulls a copy (node-affinity pins consumers per node)
    from ray_tpu.api import _parse_strategy  # noqa: F401 - api import ok
    from ray_tpu.runtime.task_spec import SchedulingStrategy

    @ray_tpu.remote
    def consume(x):
        return float(x[0]) + float(x[-1])

    t0 = time.monotonic()
    refs = []
    for node_id in list(c.nodes):
        strat = SchedulingStrategy(kind="NODE_AFFINITY", node_id=node_id)
        refs.append(consume.options(
            scheduling_strategy=strat).remote(ref))
    out = ray_tpu.get(refs, timeout=120)
    elapsed = time.monotonic() - t0
    assert all(abs(v - out[0]) < 1e-9 for v in out)
    # the whole broadcast (5 consumers) must beat 5x a serial transfer
    # budget; generous bound — the point is no pathological serialization
    assert elapsed < 60, elapsed
    # the object is now registered on multiple nodes (fan-out sources)
    from ray_tpu.runtime.rpc import RpcClient

    gcs = RpcClient(c.gcs_address)
    locs = gcs.call("get_object_locations", oids=[ref.id.hex()])
    gcs.close()
    assert len(locs[ref.id.hex()]) >= 3, locs


def test_multi_source_striping_direct():
    """PullManager stripes chunks across several live sources and
    completes when one source dies mid-transfer (chunk retry)."""
    from ray_tpu.runtime.pull_manager import PullManager

    chunk = 4
    size = 10 * chunk
    blob = bytes(range(10)) * chunk   # 40 bytes

    class FakeStore:
        def __init__(self):
            self.data = {}
            self.raw = None

        def contains(self, oid):
            return oid in self.data

        def create(self, oid, n):
            self.raw = bytearray(n)
            return memoryview(self.raw)

        def seal(self, oid):
            self.data[b"x"] = bytes(self.raw)

        def abort(self, oid):
            self.raw = None

    class FakeClient:
        def __init__(self, fail_after=None):
            self.calls = 0
            self.fail_after = fail_after
            self._closed = False

        def call(self, method, timeout=None, **kw):
            self.calls += 1
            if self.fail_after is not None and self.calls > self.fail_after:
                raise OSError("source died")
            off, length = kw["offset"], kw["length"]
            return blob[off:off + length]

        def close(self):
            self._closed = True

    store = FakeStore()
    clients = {("a", 1): FakeClient(), ("b", 2): FakeClient(fail_after=1)}
    pm = PullManager(fetch_local=lambda o: False,
                     peer_addresses=lambda o: [],
                     store=store, on_pulled=lambda o, s: None,
                     chunk_size=chunk, max_in_flight_bytes=1 << 20,
                     conns_per_peer=1)
    pm._checkout = lambda addr: clients[addr]
    pm._checkin = lambda addr, c: None

    class FakeView:
        pass

    # monkeypatch _verify to skip CRC (no codec header in this fake)
    pm._verify = staticmethod(lambda *a: True)
    ok = pm._pull_chunked("aa", b"x", [("a", 1), ("b", 2)], size, None)
    assert ok
    assert store.data[b"x"] == blob
    assert clients[("a", 1)].calls >= 8   # surviving source carried it


def test_uri_spill_roundtrip(tmp_path, monkeypatch):
    """Spill + restore through a pyarrow.fs file:// URI target."""
    monkeypatch.setenv("RAY_TPU_OBJECT_SPILLING_DIRECTORY",
                       f"file://{tmp_path}/spill")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=1, store_capacity=64 << 20)
    ray_tpu.init(address=c.gcs_address)
    try:
        node = next(iter(c.nodes.values())).raylet
        assert not node.objects.spill_is_local
        payload = np.ones((8 << 20) // 8)        # 8 MiB
        ref = ray_tpu.put(payload)
        spilled = node.objects.spill_bytes(64 << 20)
        assert spilled >= 1, "nothing spilled to the URI target"
        files = list((tmp_path / "spill").rglob("*"))
        assert any(f.is_file() for f in files), "no spill file on target"
        # restore on read
        np.testing.assert_array_equal(ray_tpu.get(ref, timeout=30),
                                      payload)
        assert node.objects.spill_stats["num_restored"] >= 1
    finally:
        ray_tpu.shutdown()
        c.shutdown()
        reset_config()
