"""Cluster memory plane: ownership-attributed object accounting,
spill/OOM visibility, and the `ray memory`-style debugging surface.

Reference analog: ``python/ray/tests/test_memstat.py`` + the
``ray memory`` CLI — per-object ownership rows with creation call
sites, node occupancy decomposition, and make-room attribution.

Covers (ISSUE-17):
- owner-side accounting unit behavior (callsite capture + memoization,
  ownership snapshots under churn, size backfill),
- ``util.state.list_objects`` field consistency across local and
  cluster mode,
- the two-raylet acceptance: per-owner pinned+spilled bytes reconcile
  with store occupancy, the CLI renders the top-N owner table, and a
  forced make-room spill is attributed to the owning process with its
  creation call site,
- the leak detector: a planted held ref is flagged with its creation
  site and surfaces through ``summarize_errors()``; churned refs are
  not flagged.
"""

import time

import pytest

import ray_tpu
from ray_tpu.runtime import core as _core
from ray_tpu.runtime import refcount as _refcount
from ray_tpu.scripts.cli import render_memory_summary
from ray_tpu.util import state as state_api
from ray_tpu.utils.config import get_config, reset_config


def _wait(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------
# unit: owner-side accounting (refcount.py)
# ---------------------------------------------------------------------------

def test_callsite_capture_points_here():
    def outer():
        return _refcount.capture_callsite()

    sites = []
    for _ in range(3):
        sites.append(outer())   # the SAME call line every iteration
    # capture walks to OUR frame (first outside the pkg)
    assert sites[0] is not None and __file__.split("/")[-1] in sites[0]
    # memoized: the same call site returns the identical interned string
    assert sites[0] is sites[1] is sites[2]


def test_note_owned_here_inlines_capture():
    rc = _refcount.RefCounter()

    def put_like():
        rc.note_owned_here("ab" * 16, 123)   # caller's caller = our caller

    def user_frame():
        put_like()

    user_frame()
    size, site, ts = rc.owned_meta("ab" * 16)
    assert size == 123
    assert site is not None and __file__.split("/")[-1] in site
    assert time.time() - ts < 5.0


def test_ownership_snapshot_shape_and_backfill():
    rc = _refcount.RefCounter()
    for i in range(8):
        rc.note_owned("%032x" % i, 0 if i < 4 else 100, f"f.py:{i}")
    for i in range(4):
        rc.note_owned_size("%032x" % i, 50)      # task-return backfill
    rc.note_owned_size("%032x" % 7, 999)         # must NOT overwrite
    snap = rc.ownership_snapshot(max_entries=512)
    assert snap["owned"] == 8
    assert snap["owned_bytes"] == 4 * 50 + 4 * 100
    by_oid = {e[0]: e for e in snap["entries"]}
    assert by_oid["%032x" % 0][1] == 50
    assert by_oid["%032x" % 7][1] == 100
    assert by_oid["%032x" % 3][2] == "f.py:3"
    assert snap["truncated"] == 0
    # truncation keeps the LARGEST entries and reports the cut
    small = rc.ownership_snapshot(max_entries=3)
    assert len(small["entries"]) == 3 and small["truncated"] == 5
    assert all(e[1] == 100 for e in small["entries"])


def test_snapshot_consistent_under_lockfree_churn():
    import threading

    rc = _refcount.RefCounter()
    stop = []

    def churn():
        i = 0
        while not stop:
            rc.note_owned("%032x" % (i & 1023), i, "c.py:1")
            i += 1

    t = threading.Thread(target=churn, daemon=True)
    t.start()
    try:
        for _ in range(100):
            snap = rc.ownership_snapshot()
            for e in snap["entries"]:
                assert len(e) == 4
    finally:
        stop.append(1)
        t.join(5)


# ---------------------------------------------------------------------------
# local mode: list_objects consistency + degraded-free summary
# ---------------------------------------------------------------------------

@pytest.fixture
def local_runtime():
    ray_tpu.shutdown()
    ray_tpu.init()
    yield
    ray_tpu.shutdown()


def test_local_list_objects_fields(local_runtime):
    refs = [ray_tpu.put(b"x" * (1000 * (i + 1))) for i in range(4)]
    rows = state_api.list_objects()
    assert len(rows) >= 4
    for row in rows:
        # the SAME field shape cluster mode answers with — no branching
        # on mode in callers (the round-11 field-skew fix)
        assert {"object_id", "size_bytes", "state", "locations",
                "holders", "pins"} <= set(row)
        assert row["size_bytes"] > 0, \
            f"local row lost its size: {row}"   # the skew this PR fixed
    assert rows == sorted(rows, key=lambda r: -r["size_bytes"])
    del refs


def test_local_memory_summary_and_render(local_runtime):
    keep = ray_tpu.put(b"y" * 4096)
    s = state_api.memory_summary()
    assert s["mode"] == "local"
    assert isinstance(s["owners"], list) and isinstance(s["nodes"], list)
    assert s["totals"]["store_allocated_bytes"] >= 0
    text = render_memory_summary(s)
    assert "MEMORY SUMMARY" in text.upper() or "mode" in text
    assert "NODE" in text
    assert state_api.memory_leaks() == []   # no distributed refs locally
    del keep


# ---------------------------------------------------------------------------
# acceptance: two-external-raylet cluster — reconciliation, CLI table,
# forced make-room spill attribution
# ---------------------------------------------------------------------------

@pytest.fixture
def two_raylet_cluster(monkeypatch):
    from ray_tpu.cluster_utils import Cluster

    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.2")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster(external_gcs=True)
    c.add_node(num_cpus=2, external=True)
    c.add_node(num_cpus=2, resources={"side": 4}, external=True)
    ray_tpu.init(address=c.gcs_address)
    c.wait_for_nodes(2)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    reset_config()


def test_cluster_memory_summary_reconciles(two_raylet_cluster):
    """Per-owner pinned+spilled bytes reconcile with store occupancy
    (± in-flight transfers), and the CLI renders the owner table."""
    driver_id = _core.get_runtime().client_id
    refs = [ray_tpu.put(b"m" * (256 << 10)) for _ in range(6)]

    def summary_ready():
        s = state_api.memory_summary(top_n=10)
        if s["mode"] != "cluster":
            return None
        mine = [o for o in s["owners"] if o["owner"] == driver_id]
        if not mine or mine[0]["pinned_bytes"] < 6 * (256 << 10):
            return None
        if s["totals"]["store_pinned_bytes"] <= 0:
            return None   # node occupancy annex not in the GCS yet
        return s

    s = _wait(summary_ready, 40, "owner + node annexes to land in GCS")
    mine = [o for o in s["owners"] if o["owner"] == driver_id][0]
    t = s["totals"]

    # reconciliation: what owners say they pinned+spilled must match
    # what the stores say they hold, up to in-flight transfers and
    # unattributed system objects (cached replicas are counted on the
    # store side only)
    owner_bytes = sum(o["pinned_bytes"] + o["spilled_bytes"]
                      for o in s["owners"])
    store_bytes = t["store_pinned_bytes"] + t["store_spilled_bytes"]
    slack = t["in_flight_bytes"] + 64 << 10
    assert abs(owner_bytes - store_bytes) <= slack, \
        f"owner accounting {owner_bytes} vs store occupancy " \
        f"{store_bytes} diverges past in-flight slack {slack}"

    # ownership rows carry this test as the creation call site
    top = mine["top"]
    assert top and any(e["callsite"] and
                       __file__.split("/")[-1] in e["callsite"]
                       for e in top), top
    assert all(e["state"] in ("pinned", "in_memory", "spilled",
                              "being_pulled") for e in top)

    # borrower/pin joins answered from the GCS ref tables
    assert all(e["borrowers"] is not None for e in top)

    # the CLI table renders the owner row and the callsite grouping
    text = render_memory_summary(s, top=10)
    assert driver_id[:12] in text
    assert "OWNER" in text and "CALLSITE" in text.upper()

    # field-consistent cluster listing (the list_objects skew fix)
    rows = state_api.list_objects()
    mine_rows = [r for r in rows
                 if (256 << 10) <= r["size_bytes"] <= (256 << 10) + 4096]
    assert len(mine_rows) >= 6
    for row in mine_rows:
        assert {"object_id", "size_bytes", "state", "locations",
                "holders", "pins"} <= set(row)
        assert row["state"] in ("pinned", "spilled", "in_memory",
                                "being_pulled")
        assert row["holders"], "cluster rows must name their holders"
    del refs


@pytest.fixture
def tiny_store_cluster(monkeypatch):
    from ray_tpu.cluster_utils import Cluster

    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.2")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    # 2 MiB store: a handful of 256 KiB puts crosses the 0.8 spill
    # threshold and forces make-room
    c.add_node(num_cpus=2, store_capacity=2 << 20)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    reset_config()


def test_forced_spill_attributed_to_owner(tiny_store_cluster):
    """Pinned bytes past the spill threshold force make-room; the
    pressure event names the owners whose bytes were spilled, and the
    spilled objects keep their creation call site."""
    driver_id = _core.get_runtime().client_id
    # hold every ref: the ONLY way to make room is spilling pinned
    # primaries, which is exactly what the attribution must explain
    refs = [ray_tpu.put(b"s" * (256 << 10)) for _ in range(12)]

    def spilled_summary():
        s = state_api.memory_summary(top_n=32)
        if s["mode"] != "cluster" or not s["pressure"]:
            return None
        mine = [o for o in s["owners"] if o["owner"] == driver_id]
        if not mine or mine[0]["spilled_bytes"] <= 0:
            return None
        return s

    s = _wait(spilled_summary, 40, "make-room spill + annexes in GCS")
    mine = [o for o in s["owners"] if o["owner"] == driver_id][0]

    # the make-room event is attributed to the owning process
    attributed = [ev for ev in s["pressure"]
                  if ev.get("owners") and driver_id in ev["owners"]]
    assert attributed, \
        f"no pressure event attributed to the driver: {s['pressure']}"

    # spilled entries keep their creation call site
    spilled = [e for e in mine["top"] if e["state"] == "spilled"]
    assert spilled, mine["top"]
    assert any(e["callsite"] and __file__.split("/")[-1] in e["callsite"]
               for e in spilled), spilled

    # node decomposition saw the spill + the store survived (puts/gets
    # still work under pressure)
    nd = [n for n in s["nodes"] if n.get("spilled_bytes", 0) > 0]
    assert nd and nd[0]["spill_stats"]["num_spilled"] >= 1
    assert nd[0]["spill_stats"]["spill_wall_s"] > 0
    assert ray_tpu.get(refs[0], timeout=60) == b"s" * (256 << 10)

    # the CLI surfaces the attribution line
    text = render_memory_summary(s, top=32)
    assert "make-room" in text or "pressure" in text.lower()
    del refs


# ---------------------------------------------------------------------------
# leak detector: planted ref flagged with creation site, churn is clean
# ---------------------------------------------------------------------------

@pytest.fixture
def leak_cluster(monkeypatch):
    from ray_tpu.cluster_utils import Cluster

    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.2")
    monkeypatch.setenv("RAY_TPU_MEMORY_LEAK_THRESHOLD_S", "1.5")
    monkeypatch.setenv("RAY_TPU_MEMORY_LEAK_IDLE_S", "0.4")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    reset_config()


def test_leak_detector_flags_planted_ref_only(leak_cluster):
    cfg = get_config()
    assert cfg.memory_leak_threshold_s == 1.5

    # churn: refs created and dropped immediately must never be flagged
    for i in range(50):
        ray_tpu.put(b"c" * 1024)

    planted = ray_tpu.put(b"L" * 8192)   # held for the whole test

    def planted_flagged():
        leaks = state_api.memory_leaks()
        # sizes are SERIALIZED payload bytes (slightly over the raw 8 KiB)
        return leaks if any(l["size_bytes"] >= 8192 for l in leaks) \
            else None

    leaks = _wait(planted_flagged, 30,
                  "planted ref to age past the leak threshold")
    flagged = [l for l in leaks if l["size_bytes"] >= 8192]
    assert len(flagged) == 1
    leak = flagged[0]
    assert leak["callsite"] and __file__.split("/")[-1] in leak["callsite"]
    assert leak["age_s"] >= cfg.memory_leak_threshold_s
    assert leak["owner_kind"] == "driver"
    # churned refs never show up (they died before the threshold)
    assert all(l["size_bytes"] >= 8192 for l in leaks), leaks

    # ...and the same suspicion surfaces through error aggregation
    groups = state_api.summarize_errors()
    leak_groups = [g for g in groups if g.get("kind") == "leak"]
    assert leak_groups, groups
    g = leak_groups[0]
    assert "leaked object ref @" in g["signature"]
    assert __file__.split("/")[-1] in g["signature"]
    assert g["bytes"] >= 8192 and g["count"] >= 1

    del planted
    # flag clears once the ref dies and the release flush lands
    _wait(lambda: not any(l["size_bytes"] >= 8192
                          for l in state_api.memory_leaks()),
          30, "leak flag to clear after release")
