"""Owner-side worker-lease protocol (reference:
``direct_task_transport.cc:134,240`` — lease + direct push + synchronous
loss detection). VERDICT r1 item 5's done-criterion: in-flight-loss chaos
with NO grace-period tuning, and no duplicate submissions for slow-but-
healthy tasks."""

import os
import tempfile
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def two_node_cluster():
    """Head with no CPUs (driver-only) + one worker node: every task
    leases on the worker node, which the test can kill."""
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=0)            # head: GCS + driver raylet only
    worker = c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    yield c, worker
    ray_tpu.shutdown()
    c.shutdown()


def test_lease_grants_and_reuses_workers():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    try:
        @ray_tpu.remote
        def pid():
            return os.getpid()

        # many more tasks than workers: leases must be granted AND reused
        pids = ray_tpu.get([pid.remote() for _ in range(40)])
        assert len(set(pids)) <= 2, f"more workers than CPUs: {set(pids)}"
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_inflight_loss_recovered_without_grace(two_node_cluster):
    """Kill the node executing a task mid-flight: the owner's lease
    connection breaks SYNCHRONOUSLY and the retry lands on a replacement
    node — well under the old 20s presumed-lost grace."""
    c, worker = two_node_cluster

    @ray_tpu.remote(max_retries=2)
    def slowish(x):
        time.sleep(3)
        return x * 2

    refs = [slowish.remote(i) for i in range(2)]
    time.sleep(1.0)              # tasks are now running on `worker`
    start = time.monotonic()
    c.remove_node(worker)        # node dies with tasks in flight
    c.add_node(num_cpus=2)       # replacement capacity
    out = ray_tpu.get(refs, timeout=30)
    elapsed = time.monotonic() - start
    assert out == [0, 2]
    # recovery = break detection (immediate) + re-run (~3s); the old
    # heuristic could not even START before its 20s grace
    assert elapsed < 15, f"recovery took {elapsed:.1f}s"


def test_slow_task_never_duplicated(two_node_cluster):
    """A slow-but-healthy task must run EXACTLY once even with the
    legacy presumed-lost grace tuned to something absurdly small — the
    lease path never consults it (ADVICE r1 medium)."""
    c, worker = two_node_cluster
    marker = tempfile.mktemp(prefix="lease_effect_")

    rt = ray_tpu.api._runtime()
    rt._pending_grace_s = 0.2   # old heuristic would re-submit at 0.2s

    @ray_tpu.remote(max_retries=3)
    def slow_effect(path):
        with open(path, "a") as f:
            f.write("ran\n")
        time.sleep(3)
        return "ok"

    assert ray_tpu.get(slow_effect.remote(marker), timeout=30) == "ok"
    time.sleep(0.5)
    with open(marker) as f:
        runs = f.readlines()
    os.unlink(marker)
    assert len(runs) == 1, f"slow task ran {len(runs)} times"


def test_worker_death_retries_via_lease_break(two_node_cluster):
    """Worker process dies mid-task: the push fails synchronously and the
    retry budget drives a re-execution."""
    c, worker = two_node_cluster
    marker = tempfile.mktemp(prefix="lease_die_once_")

    @ray_tpu.remote(max_retries=1)
    def die_once(path):
        if not os.path.exists(path):
            with open(path, "w") as f:
                f.write("x")
            os._exit(1)          # simulated crash on first attempt
        return "second attempt"

    assert ray_tpu.get(die_once.remote(marker), timeout=30) == \
        "second attempt"
    os.unlink(marker)


def test_worker_death_no_retries_fails_fast(two_node_cluster):
    """max_retries=0 + worker death: the owner seals an error instead of
    hanging (the old heuristic had no path for this case at all)."""
    c, worker = two_node_cluster

    @ray_tpu.remote(max_retries=0)
    def die():
        os._exit(1)

    start = time.monotonic()
    with pytest.raises(ray_tpu.exceptions.RayTpuError):
        ray_tpu.get(die.remote(), timeout=30)
    assert time.monotonic() - start < 10


def test_return_refs_registered_before_task_reaches_pusher():
    """Direct returns ride the push reply, and _accept_direct_results
    drops any arriving result whose return refs count 0 live instances
    ("every ref died while the result was in flight"). A worker fast
    enough to reply before submit_task's caller resumed used to hit that
    guard — the refs were constructed only on return from submit_task —
    deleting the only copy of a live result and wedging the later get()
    forever (~3 per 10k tasks in the envelope drain on a loaded host).
    The return ObjectRefs must be registered with the refcounter BEFORE
    the task is visible to any lease pusher."""
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=2)
    ray_tpu.init(address=c.gcs_address)
    try:
        from ray_tpu.runtime import core as _core

        rt = _core.get_runtime()
        lm = rt._leases
        counts = []
        orig = lm.submit

        def spy(task):
            counts.extend(rt._refs.count(o)
                          for o in task.get("return_oids", ()))
            orig(task)

        lm.submit = spy

        @ray_tpu.remote
        def echo(i):
            return i

        try:
            refs = [echo.remote(i) for i in range(20)]
            assert ray_tpu.get(refs, timeout=30) == list(range(20))
        finally:
            lm.submit = orig
        assert counts, "no leasable task went through the lease manager"
        assert min(counts) >= 1, (
            f"return refs not registered before push: counts={counts}")
    finally:
        ray_tpu.shutdown()
        c.shutdown()
