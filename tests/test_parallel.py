"""Mesh/sharding/collective layer tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel import collectives as col
from ray_tpu.parallel.mesh import (
    MeshSpec,
    create_hybrid_mesh,
    create_mesh,
    mesh_registry,
    slice_topology,
)
from ray_tpu.parallel.sharding import (
    FSDP_TP_RULES,
    PRESETS,
    batch_sharding,
    logical_sharding,
    shard_tree,
    tree_shardings,
)


def test_mesh_spec_wildcard():
    assert MeshSpec({"dp": -1, "tp": 2}).resolved(8) == {"dp": 4, "tp": 2}
    assert MeshSpec({"fsdp": 8}).resolved(8) == {"fsdp": 8}
    with pytest.raises(ValueError):
        MeshSpec({"dp": 3, "tp": 2}).resolved(8)
    with pytest.raises(ValueError):
        MeshSpec({"dp": -1, "tp": -1}).resolved(8)


def test_mesh_axis_canonical_order():
    resolved = MeshSpec({"tp": 2, "dp": 2, "fsdp": 2}).resolved(8)
    assert list(resolved.keys()) == ["dp", "fsdp", "tp"]


def test_create_mesh(cpu_mesh_devices):
    mesh = create_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    assert mesh.axis_names == ("dp", "fsdp", "tp")
    assert mesh.devices.shape == (2, 2, 2)


def test_hybrid_mesh(cpu_mesh_devices):
    mesh = create_hybrid_mesh({"tp": 4}, {"dp": 2})
    assert mesh.axis_names == ("dp", "tp")
    assert mesh.devices.shape == (2, 4)


def test_hybrid_mesh_train_step_matches_flat(cpu_mesh_devices):
    """A 2-slice DCN hybrid mesh (dp over DCN × fsdp/tp over ICI) runs a
    real training step with loss parity vs the same logical axes on a
    flat mesh — the layout reorders devices, never the computation
    (SURVEY §2c multi-slice row; same leg as dryrun_multichip)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama
    from ray_tpu.train.trainer import JaxTrainer, TrainConfig

    cfg = llama.llama_tiny()
    batch = jax.random.randint(jax.random.key(5), (8, 17), 0,
                               cfg.vocab_size, dtype=jnp.int32)
    losses = []
    for mesh in (
        create_hybrid_mesh({"fsdp": 2, "tp": 2}, {"dp": 2}),
        create_mesh({"dp": 2, "fsdp": 2, "tp": 2}),
    ):
        trainer = JaxTrainer(
            cfg, TrainConfig(strategy="fsdp_tp", warmup_steps=1,
                             total_steps=10), mesh=mesh)
        state = trainer.init_state(jax.random.key(0))
        _, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    hy, flat = losses
    assert jnp.isfinite(jnp.asarray(hy))
    assert abs(hy - flat) <= 1e-3 * max(abs(flat), 1.0), losses


def test_mesh_registry(cpu_mesh_devices):
    reg = mesh_registry()
    m = reg.get_or_create("test_mesh", {"dp": -1})
    assert reg.get("test_mesh") is m
    with pytest.raises(ValueError):
        reg.register("test_mesh", m)
    reg.remove("test_mesh")
    with pytest.raises(KeyError):
        reg.get("test_mesh")


def test_slice_topology(cpu_mesh_devices):
    info = slice_topology()
    assert info["num_devices"] == 8
    assert info["platform"] == "cpu"


def test_logical_sharding_rules(cpu_mesh_devices):
    mesh = create_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    s = logical_sharding(("embed", "mlp"), mesh, FSDP_TP_RULES)
    assert s.spec == P("fsdp", "tp")
    # batch sharding over dp×fsdp
    bs = batch_sharding(mesh, FSDP_TP_RULES, ndim=2)
    assert bs.spec == P(("dp", "fsdp"), None)


def test_rules_filtered_for_small_mesh(cpu_mesh_devices):
    # FSDP_TP rules on a dp-only mesh: tp/fsdp references drop to replicated.
    mesh = create_mesh({"dp": 8})
    s = logical_sharding(("embed", "mlp"), mesh, FSDP_TP_RULES)
    assert s.spec == P(None, None)


def test_shard_tree_places_arrays(cpu_mesh_devices):
    mesh = create_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    params = {"w": jnp.ones((16, 32)), "b": jnp.ones((32,))}
    logical = {"w": ("embed", "mlp"), "b": ("mlp",)}
    sharded = shard_tree(params, logical, mesh, FSDP_TP_RULES)
    assert sharded["w"].sharding.spec == P("fsdp", "tp")
    assert sharded["b"].sharding.spec == P("tp")
    np.testing.assert_allclose(np.asarray(sharded["w"]), 1.0)


def test_all_presets_produce_shardings(cpu_mesh_devices):
    mesh = create_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    for name, rules in PRESETS.items():
        s = logical_sharding(("batch", "seq", "embed"), mesh, rules)
        assert isinstance(s, NamedSharding), name


# --- device-plane collectives via shard_map ---


def test_shard_map_psum(cpu_mesh_devices):
    from jax.experimental.shard_map import shard_map

    mesh = create_mesh({"dp": 8})
    x = jnp.arange(8.0)

    f = shard_map(
        lambda v: col.psum(v, "dp"),
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
    )
    np.testing.assert_allclose(np.asarray(f(x)), np.full(8, 28.0))


def test_shard_map_ring_shift(cpu_mesh_devices):
    from jax.experimental.shard_map import shard_map

    mesh = create_mesh({"sp": 8})
    x = jnp.arange(8.0)
    f = shard_map(
        lambda v: col.ring_shift(v, "sp"),
        mesh=mesh, in_specs=P("sp"), out_specs=P("sp"),
    )
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


def test_shard_map_all_to_all(cpu_mesh_devices):
    from jax.experimental.shard_map import shard_map

    mesh = create_mesh({"ep": 4}, devices=jax.devices()[:4])
    x = jnp.arange(16.0).reshape(4, 4)  # [tokens, experts]
    f = shard_map(
        lambda v: col.all_to_all(v, "ep", split_axis=1, concat_axis=0),
        mesh=mesh, in_specs=P("ep", None), out_specs=P("ep", None),
    )
    out = np.asarray(f(x))
    assert out.shape == (16, 1)


# --- host-plane actor collectives ---


def test_host_allreduce_between_actors(ray_tpu_start):
    import ray_tpu

    @ray_tpu.remote
    class Rank:
        def __init__(self, rank, world):
            self.rank = rank
            col.init_collective_group(world, rank, group_name="g1")

        def reduce(self, x):
            return col.allreduce(np.array([x], dtype=np.float32), self.rank,
                                 group_name="g1")

    world = 4
    actors = [Rank.remote(i, world) for i in range(world)]
    refs = [a.reduce.remote(float(i)) for i, a in enumerate(actors)]
    results = ray_tpu.get(refs, timeout=30)
    for r in results:
        np.testing.assert_allclose(r, [6.0])
    col.destroy_collective_group("g1")


def test_host_broadcast_and_allgather(ray_tpu_start):
    import ray_tpu

    @ray_tpu.remote
    class Rank:
        def __init__(self, rank, world):
            self.rank = rank
            col.init_collective_group(world, rank, group_name="g2")

        def bcast(self, x):
            return col.broadcast(x, self.rank, src_rank=0, group_name="g2")

        def gather(self, x):
            return col.allgather(np.array([x]), self.rank, group_name="g2")

    world = 3
    actors = [Rank.remote(i, world) for i in range(world)]
    out = ray_tpu.get(
        [a.bcast.remote(np.array([i * 1.0])) for i, a in enumerate(actors)],
        timeout=30,
    )
    for r in out:
        np.testing.assert_allclose(r, [0.0])
    gathered = ray_tpu.get(
        [a.gather.remote(float(i)) for i, a in enumerate(actors)], timeout=30
    )
    for g in gathered:
        np.testing.assert_allclose(np.concatenate(g), [0.0, 1.0, 2.0])
    col.destroy_collective_group("g2")


def test_back_to_back_collectives_no_crosstalk(ray_tpu_start):
    import ray_tpu

    @ray_tpu.remote
    class Rank:
        def __init__(self, rank, world):
            self.rank = rank
            col.init_collective_group(world, rank, group_name="g3")

        def many(self, n):
            outs = []
            for i in range(n):
                outs.append(
                    float(
                        col.allreduce(
                            np.array([float(i)]), self.rank, group_name="g3"
                        )[0]
                    )
                )
            return outs

    world = 4
    actors = [Rank.remote(i, world) for i in range(world)]
    results = ray_tpu.get([a.many.remote(10) for a in actors], timeout=60)
    expected = [i * 4.0 for i in range(10)]
    for r in results:
        assert r == expected
    col.destroy_collective_group("g3")
