"""Nightly autoscaling swing: a square-wave load must move a serve
deployment BOTH directions — up when the pushed queue/ongoing windows
from the cluster metrics plane cross the target, back down when the
wave drops — with the autoscaler staying on the metrics-driven policy
the whole time (never silently degrading to the polled loop).

This runs on a real multi-process cluster: replica gauges originate in
WORKER processes and travel the worker pusher -> GCS MetricsStore ->
``cluster_metrics`` path the production autoscaler consumes
(``serve/controller.py:_pushed_signals``).

Run via ``ci/run_ci.sh --nightly`` (``pytest -m nightly``); the CI
default tier skips it (tens of seconds of wall-clock load shaping).
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

# slow as well: an explicit `-m 'not slow'` on the command line REPLACES
# the addopts default (`-m 'not nightly'`) — keep the swing out of
# bounded default/tier-1 runs either way
pytestmark = [pytest.mark.nightly, pytest.mark.slow]

CYCLES = 2
HIGH_CONC = 4          # concurrent 0.25s calls during the high phase
CALL_S = 0.25


@pytest.fixture
def swing_cluster(monkeypatch):
    import ray_tpu.runtime.metrics_plane as mp
    from ray_tpu import serve
    from ray_tpu.utils.config import reset_config

    # fast push + small aggregation windows so the swing settles in
    # seconds instead of the production multi-second cadence
    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.1")
    monkeypatch.setenv("RAY_TPU_METRICS_WINDOW_S", "0.5")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster(heartbeat_timeout_s=1.0)
    c.add_node(num_cpus=4)
    ray_tpu.init(address=c.gcs_address)
    # deterministic RPC-path pusher for driver-side series (the workers
    # hosting replicas run their own pushers regardless)
    mp._claimed = None
    pusher = mp.MetricsPusher(c.gcs_address, src="swing-test",
                              kind="driver", interval_s=0.1).start()
    yield c
    serve.shutdown()
    pusher.stop()
    ray_tpu.shutdown()
    c.shutdown()
    reset_config()


def _wait(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


def test_square_wave_scales_up_and_down_from_pushed_metrics(swing_cluster):
    from ray_tpu import serve

    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_ongoing_requests": 1.0,
        "upscale_delay_s": 0.3, "downscale_delay_s": 1.0,
        "metrics_window_s": 1.5})
    class Slow:
        def __call__(self, delay):
            time.sleep(delay)
            return "ok"

    handle = serve.run(Slow.bind(), name="swing")

    def dep():
        return serve.status()["deployments"].get("swing", {})

    # first call rides replica construction
    assert handle.call(0.01) == "ok"

    stop = threading.Event()
    high = threading.Event()
    failures: list = []

    def load():
        while not stop.is_set():
            if not high.is_set():
                # trickle: keeps the deployment warm but far under the
                # per-replica target, so the downscale signal is real
                try:
                    handle.call(0.01)
                except Exception as e:  # noqa: BLE001
                    failures.append(repr(e))
                time.sleep(0.5)
                continue
            try:
                refs = [handle.remote(CALL_S) for _ in range(HIGH_CONC)]
                for r in refs:
                    ray_tpu.get(r, timeout=30)
            except Exception as e:  # noqa: BLE001
                failures.append(repr(e))
                return

    th = threading.Thread(target=load, daemon=True)
    th.start()
    modes_seen = set()
    try:
        for cycle in range(CYCLES):
            high.set()
            _wait(lambda: dep().get("running", 0) >= 2, 45,
                  f"upscale in cycle {cycle}")
            modes_seen.add(dep().get("autoscale_mode"))

            high.clear()
            _wait(lambda: dep().get("running", 0) == 1, 60,
                  f"downscale in cycle {cycle}")
            modes_seen.add(dep().get("autoscale_mode"))
        assert not failures, failures
        # the whole swing ran on pushed metrics — degradation to the
        # polled loop would mean the plane lost the replica gauges
        assert modes_seen == {"metrics"}, modes_seen
    finally:
        stop.set()
        th.join(timeout=30)
    assert not failures, failures
