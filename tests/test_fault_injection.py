"""Fault-injection plane: rule semantics, RPC-layer consult points, the
GCS KV switch, and the hardened ReconnectingRpcClient redial policy.

Reference analog: the reference's chaos utilities
(``python/ray/tests/chaos``) — here the plane itself is under test
before ``test_chaos_partitions.py`` uses it against full clusters.
"""

import threading
import time

import pytest

from ray_tpu.runtime import fault_injection as fi
from ray_tpu.runtime.rpc import (ConnectionLost, ReconnectingRpcClient,
                                 RpcClient, RpcServer)


@pytest.fixture(autouse=True)
def clean_plane():
    fi.plane.clear()
    yield
    fi.stop_kv_watcher()
    fi.plane.clear()


def _plan(*rules, seed=7, version=1, endpoints=None):
    return {"version": version, "seed": seed,
            "endpoints": endpoints or {}, "rules": list(rules)}


# ----------------------------------------------------------------------
# rule semantics (no sockets)
# ----------------------------------------------------------------------

class TestRules:
    def test_inactive_plane_passes_everything(self):
        assert not fi.plane.active
        assert fi.plane.consult("driver", "send", ("h", 1), "m") == fi.PASS

    def test_src_label_scoping(self):
        fi.plane.load_plan(_plan({"fault": "drop", "src": "driver"}))
        assert fi.plane.consult("driver", "send", ("h", 1), "m") == fi.DROP
        assert fi.plane.consult("raylet", "send", ("h", 1), "m") == fi.PASS
        assert fi.plane.consult(None, "send", ("h", 1), "m") == fi.PASS

    def test_dst_address_and_endpoint_name(self):
        fi.plane.load_plan(_plan(
            {"fault": "drop", "dst": "gcs"},
            endpoints={"gcs": ["10.0.0.1:6379"]}))
        assert fi.plane.consult("x", "send", ("10.0.0.1", 6379),
                                "m") == fi.DROP
        assert fi.plane.consult("x", "send", ("10.0.0.2", 6379),
                                "m") == fi.PASS
        # literal host:port dst needs no endpoints entry
        fi.plane.load_plan(_plan({"fault": "drop", "dst": "10.9.9.9:1"},
                                 version=2))
        assert fi.plane.consult("x", "send", ("10.9.9.9", 1),
                                "m") == fi.DROP

    def test_direction_and_method_scoping(self):
        fi.plane.load_plan(_plan(
            {"fault": "drop", "direction": "recv", "method": "put"}))
        assert fi.plane.consult("x", "recv", ("h", 1), "put") == fi.DROP
        assert fi.plane.consult("x", "send", ("h", 1), "put") == fi.PASS
        assert fi.plane.consult("x", "recv", ("h", 1), "get") == fi.PASS

    def test_nth_every_max_hits(self):
        fi.plane.load_plan(_plan({"fault": "drop", "nth": 3}))
        got = [fi.plane.consult("x", "send", ("h", 1), "m")
               for _ in range(5)]
        assert got == [fi.PASS, fi.PASS, fi.DROP, fi.PASS, fi.PASS]

        fi.plane.load_plan(_plan({"fault": "drop", "every": 2}, version=2))
        got = [fi.plane.consult("x", "send", ("h", 1), "m")
               for _ in range(4)]
        assert got == [fi.PASS, fi.DROP, fi.PASS, fi.DROP]

        fi.plane.load_plan(_plan({"fault": "drop", "max_hits": 2},
                                 version=3))
        got = [fi.plane.consult("x", "send", ("h", 1), "m")
               for _ in range(4)]
        assert got == [fi.DROP, fi.DROP, fi.PASS, fi.PASS]

    def test_probabilistic_rules_are_seed_deterministic(self):
        def run(seed):
            fi.plane.load_plan(_plan({"fault": "drop", "p": 0.5},
                                     seed=seed))
            return [fi.plane.consult("x", "send", ("h", 1), "m")
                    for _ in range(64)]

        a, b, c = run(42), run(42), run(43)
        assert a == b                      # same seed -> same trace
        assert a != c                      # different seed -> different
        assert fi.DROP in a and fi.PASS in a

    def test_partition_maps_to_reset_and_blocks_connect(self):
        fi.plane.load_plan(_plan(
            {"fault": "partition", "src": "driver", "dst": "h:1"}))
        assert fi.plane.consult("driver", "send", ("h", 1),
                                "m") == fi.RESET
        with pytest.raises(fi.InjectedConnectionReset):
            fi.plane.check_connect("driver", ("h", 1))
        # other labels still connect
        fi.plane.check_connect("raylet", ("h", 1))
        # heal: empty plan deactivates
        fi.plane.load_plan(_plan(version=2))
        assert not fi.plane.active
        fi.plane.check_connect("driver", ("h", 1))

    def test_recv_only_partition_does_not_block_connect(self):
        fi.plane.load_plan(_plan(
            {"fault": "partition", "src": "driver", "direction": "recv"}))
        fi.plane.check_connect("driver", ("h", 1))
        assert fi.plane.consult("driver", "recv", ("h", 1),
                                "m") == fi.RESET
        assert fi.plane.consult("driver", "send", ("h", 1), "m") == fi.PASS

    def test_control_label_is_exempt(self):
        fi.plane.load_plan(_plan({"fault": "partition"}))
        fi.plane.check_connect(fi.FAULT_CONTROL_LABEL, ("h", 1))
        assert fi.plane.consult(fi.FAULT_CONTROL_LABEL, "send", ("h", 1),
                                "kv_put") == fi.PASS

    def test_delay_sleeps_inline(self):
        fi.plane.load_plan(_plan({"fault": "delay", "delay_s": 0.15}))
        t0 = time.monotonic()
        assert fi.plane.consult("x", "send", ("h", 1), "m") == fi.PASS
        assert time.monotonic() - t0 >= 0.14

    def test_bad_fault_rejected(self):
        with pytest.raises(ValueError):
            fi.plane.load_plan(_plan({"fault": "explode"}))

    def test_decode_plan_forms(self):
        assert fi.decode_plan(None) is None
        assert fi.decode_plan('{"version": 1}') == {"version": 1}
        assert fi.decode_plan(b'{"version": 2}') == {"version": 2}
        assert fi.decode_plan({"version": 3}) == {"version": 3}
        with pytest.raises(ValueError):
            fi.decode_plan("[1, 2]")


# ----------------------------------------------------------------------
# consult points in the real RPC layer
# ----------------------------------------------------------------------

class _Echo(RpcServer):
    def __init__(self):
        super().__init__("127.0.0.1", 0)
        self.fault_label = "server"
        self.calls = 0
        self._calls_lock = threading.Lock()

    def rpc_echo(self, conn, send_lock, *, value):
        with self._calls_lock:
            self.calls += 1
        return {"value": value}


@pytest.fixture
def echo():
    server = _Echo().start()
    yield server
    server.stop()


class TestRpcConsults:
    def test_client_send_drop_times_out(self, echo):
        client = RpcClient(echo.address, label="driver")
        try:
            fi.plane.load_plan(_plan(
                {"fault": "drop", "src": "driver", "direction": "send",
                 "max_hits": 1}))
            with pytest.raises(TimeoutError):
                client.call("echo", value=1, timeout=0.3)
            assert echo.calls == 0          # never reached the server
            assert client.call("echo", value=2,
                               timeout=5)["value"] == 2
        finally:
            client.close()

    def test_server_recv_duplicate_runs_handler_twice(self, echo):
        client = RpcClient(echo.address, label="driver")
        try:
            fi.plane.load_plan(_plan(
                {"fault": "duplicate", "src": "server",
                 "direction": "recv", "method": "echo", "max_hits": 1}))
            assert client.call("echo", value=3, timeout=5)["value"] == 3
            deadline = time.monotonic() + 5
            while echo.calls < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert echo.calls == 2
        finally:
            client.close()

    def test_client_send_reset_raises_connection_lost(self, echo):
        client = RpcClient(echo.address, label="driver")
        try:
            fi.plane.load_plan(_plan(
                {"fault": "reset", "src": "driver", "direction": "send",
                 "max_hits": 1}))
            with pytest.raises(ConnectionLost):
                client.call("echo", value=4, timeout=5)
            assert client._closed
        finally:
            client.close()

    def test_partition_blocks_dial_until_healed(self, echo):
        addr = f"{echo.address[0]}:{echo.address[1]}"
        fi.plane.load_plan(_plan(
            {"fault": "partition", "src": "driver", "dst": "srv"},
            endpoints={"srv": [addr]}))
        with pytest.raises(fi.InjectedConnectionReset):
            RpcClient(echo.address, label="driver")
        # unlabeled / other-labeled channels unaffected
        other = RpcClient(echo.address, label="raylet")
        try:
            assert other.call("echo", value=5, timeout=5)["value"] == 5
        finally:
            other.close()
        fi.plane.load_plan(_plan(version=2))
        healed = RpcClient(echo.address, label="driver")
        try:
            assert healed.call("echo", value=6, timeout=5)["value"] == 6
        finally:
            healed.close()

    def test_reconnecting_client_rides_through_reset(self, echo):
        client = ReconnectingRpcClient(echo.address, label="driver")
        try:
            fi.plane.load_plan(_plan(
                {"fault": "reset", "src": "driver", "direction": "send",
                 "max_hits": 1}))
            # one transparent redial+retry, inside the call deadline
            assert client.call("echo", value=7,
                               timeout=10)["value"] == 7
        finally:
            client.close()


# ----------------------------------------------------------------------
# redial policy
# ----------------------------------------------------------------------

class TestRedialPolicy:
    def test_backoff_schedule_and_jitter_bounds(self, echo):
        client = ReconnectingRpcClient(echo.address, label="driver")
        try:
            client._backoff_init = 0.1
            client._backoff_mult = 2.0
            client._backoff_max = 0.5
            client._jitter = 0.0
            assert client._backoff(1) == pytest.approx(0.1)
            assert client._backoff(2) == pytest.approx(0.2)
            assert client._backoff(3) == pytest.approx(0.4)
            assert client._backoff(4) == pytest.approx(0.5)   # capped
            client._jitter = 0.2
            for attempt in (1, 2, 5):
                base = min(0.5, 0.1 * 2.0 ** (attempt - 1))
                for _ in range(32):
                    d = client._backoff(attempt)
                    assert base * 0.8 <= d <= base * 1.2
        finally:
            client.close()

    def test_redial_budget_bounds_attempts(self, echo):
        dead_addr = echo.address
        client = ReconnectingRpcClient(dead_addr, label="driver",
                                       redial_window_s=30.0)
        try:
            client._max_redials = 2
            client._backoff_init = 0.01
            client._jitter = 0.0
            echo.stop()
            t0 = time.monotonic()
            with pytest.raises((ConnectionLost, OSError)):
                client.call("echo", value=8, timeout=20)
            # 2 attempts at ~10ms backoff — nowhere near the 30s window
            assert time.monotonic() - t0 < 5.0
        finally:
            client.close()

    def test_call_timeout_caps_redial_window(self):
        # dial an unroutable-but-fast-failing port: server never existed
        probe = RpcServer("127.0.0.1", 0).start()
        addr = probe.address
        client = ReconnectingRpcClient(addr, label="driver",
                                       redial_window_s=60.0)
        probe.stop()
        try:
            client._backoff_init = 0.05
            client._jitter = 0.0
            t0 = time.monotonic()
            with pytest.raises((ConnectionLost, OSError, TimeoutError)):
                client.call("echo", value=9, timeout=1.0)
            # the UNIFORM deadline (1s) bounds the whole call including
            # redials — not a fresh 60s window per attempt
            assert time.monotonic() - t0 < 8.0
        finally:
            client.close()


# ----------------------------------------------------------------------
# the GCS KV switch
# ----------------------------------------------------------------------

class TestKvSwitch:
    def test_put_plan_applies_on_gcs_and_watchers(self):
        from ray_tpu.runtime.gcs import GcsServer

        gcs = GcsServer().start()
        try:
            fi.start_kv_watcher(gcs.address, poll_s=0.05)
            fi.put_plan(gcs.address, _plan(
                {"fault": "drop", "src": "nobody"}, version=11))
            # the GCS applied it to its own (shared, in-process) plane
            # synchronously at kv_put time; the watcher converges too
            deadline = time.monotonic() + 5
            while fi.plane.version != 11 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert fi.plane.version == 11
            assert fi.plane.active
            # heal through the same key
            fi.put_plan(gcs.address, _plan(version=12))
            deadline = time.monotonic() + 5
            while fi.plane.active and time.monotonic() < deadline:
                time.sleep(0.02)
            assert not fi.plane.active
        finally:
            fi.stop_kv_watcher()
            gcs.stop()

    def test_put_plan_channel_is_exempt_while_partitioned(self):
        from ray_tpu.runtime.gcs import GcsServer

        gcs = GcsServer().start()
        try:
            addr = f"{gcs.address[0]}:{gcs.address[1]}"
            # partition EVERY labeled channel to the GCS...
            fi.put_plan(gcs.address, _plan(
                {"fault": "partition", "dst": "gcs"}, version=21,
                endpoints={"gcs": [addr]}))
            assert fi.plane.active
            with pytest.raises(fi.InjectedConnectionReset):
                RpcClient(gcs.address, label="driver")
            # ...and heal it over the exempt control channel
            fi.put_plan(gcs.address, _plan(version=22))
            assert not fi.plane.active
        finally:
            gcs.stop()

    def test_maybe_init_is_noop_when_disabled(self):
        from ray_tpu.utils.config import get_config

        assert not get_config().fault_injection_enabled
        fi.maybe_init_from_config()
        assert not fi.plane.active

    def test_maybe_init_loads_inline_plan(self, monkeypatch):
        import json

        monkeypatch.setenv("RAY_TPU_FAULT_INJECTION_ENABLED", "1")
        monkeypatch.setenv("RAY_TPU_FAULT_INJECTION_SEED", "9")
        monkeypatch.setenv("RAY_TPU_FAULT_INJECTION_PLAN", json.dumps(
            _plan({"fault": "drop", "src": "nobody"}, version=31)))
        from ray_tpu.utils import config as config_mod

        config_mod.reset_config()
        try:
            fi.maybe_init_from_config()
            assert fi.plane.active
            assert fi.plane.version == 31
        finally:
            monkeypatch.undo()
            config_mod.reset_config()
