"""Cluster metrics plane (round 7): registry + delta-frame semantics,
the GCS time-series store, pusher bounded-buffer behavior, the < 3%
hot-path overhead gate, and the cross-node histogram query acceptance
(p99 lease grant latency over ALL raylets from one driver call)."""

import time

import pytest

import ray_tpu
from ray_tpu.runtime.metrics_plane import (MetricsPusher, MetricsStore,
                                           claim_pusher, release_pusher,
                                           summarize_histogram)
from ray_tpu.util import metrics as m


@pytest.fixture(autouse=True)
def _fresh_registry():
    m.clear_registry()
    m.set_enabled(None)
    yield
    m.clear_registry()
    m.set_enabled(None)


# ---------------------------------------------------------------------------
# registry + delta frames
# ---------------------------------------------------------------------------

def test_counter_gauge_histogram_snapshot():
    c = m.counter("t_ops", tag_keys=("op",))
    c.inc(tags={"op": "put"})
    c.inc(2, tags={"op": "get"})
    g = m.gauge("t_inflight")
    g.set(7)
    h = m.histogram("t_lat", boundaries=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    snap = m.snapshot()
    assert snap["t_ops"]["series"][(("op", "put"),)] == 1
    assert snap["t_ops"]["series"][(("op", "get"),)] == 2
    assert snap["t_inflight"]["series"][()] == 7
    hist = snap["t_lat"]["series"][()]
    assert hist["count"] == 3
    assert hist["buckets"] == [1, 1, 1]      # one per bucket incl +Inf
    assert hist["sum"] == pytest.approx(5.55)


def test_snapshot_delta_ships_only_increments():
    c = m.counter("t_delta")
    h = m.histogram("t_dhist", boundaries=(1.0,))
    c.inc(5)
    h.observe(0.5)
    frame, prev = m.snapshot_delta(None)
    assert frame["t_delta"]["series"][()] == 5
    assert frame["t_dhist"]["series"][()]["count"] == 1
    # no activity -> empty frame (nothing to push)
    frame2, prev = m.snapshot_delta(prev)
    assert not frame2
    c.inc(3)
    frame3, _ = m.snapshot_delta(prev)
    assert frame3["t_delta"]["series"][()] == 3     # the delta, not 8
    assert "t_dhist" not in frame3


def test_histogram_handle_and_quantiles():
    h = m.histogram("t_q", boundaries=(0.01, 0.1, 1.0))
    handle = h.handle()
    for _ in range(90):
        handle.observe(0.05)
    for _ in range(10):
        handle.observe(0.5)
    hist = m.snapshot()["t_q"]["series"][()]
    p50 = m.quantile_from_buckets((0.01, 0.1, 1.0), hist["buckets"], 0.5)
    p99 = m.quantile_from_buckets((0.01, 0.1, 1.0), hist["buckets"], 0.99)
    assert 0.01 <= p50 <= 0.1
    assert 0.1 <= p99 <= 1.0


# ---------------------------------------------------------------------------
# GCS time-series store
# ---------------------------------------------------------------------------

def _frame(name="lat", kind="histogram", tags=(), **payload):
    if kind == "histogram":
        ent = {"count": payload.get("count", 1),
               "sum": payload.get("sum", 0.5),
               "buckets": payload.get("buckets", [1, 0])}
        return {name: {"kind": kind, "boundaries": (1.0,),
                       "series": {tuple(tags): ent}}}
    return {name: {"kind": kind,
                   "series": {tuple(tags): payload["value"]}}}


def test_store_ingest_tags_and_group_by():
    store = MetricsStore(window_s=3600.0)
    store.ingest("nodeA", _frame(tags=(("stage", "grant"),)))
    store.ingest("nodeA", _frame(tags=(("stage", "grant"),)))
    store.ingest("nodeB", _frame(tags=(("stage", "grant"),)))
    # cluster-wide merge: one group, counts added across srcs
    res = store.query("lat")
    assert res["kind"] == "histogram"
    assert len(res["groups"]) == 1
    assert res["groups"][0]["value"]["count"] == 3
    # per-src split
    res = store.query("lat", group_by=["src"])
    counts = {g["tags"]["src"]: g["value"]["count"]
              for g in res["groups"]}
    assert counts == {"nodeA": 2, "nodeB": 1}
    # tag subset filter
    res = store.query("lat", tags={"src": "nodeB"})
    assert res["groups"][0]["value"]["count"] == 1
    # unknown name answers cleanly
    assert store.query("nope")["kind"] is None


def test_store_windows_roll_and_last_s():
    store = MetricsStore(window_s=0.05, windows=4)
    store.ingest("a", _frame(name="ops", kind="counter", value=1.0))
    time.sleep(0.08)
    store.ingest("a", _frame(name="ops", kind="counter", value=2.0))
    res = store.query("ops", per_window=True)
    assert res["windows"] == 2
    total = store.query("ops")["groups"][0]["value"]
    assert total == 3.0
    # last_s excludes the rolled window once it ages out
    time.sleep(0.05)
    recent = store.query("ops", last_s=0.04)
    assert recent["windows"] <= 1


def test_store_gauge_latest_window_wins():
    store = MetricsStore(window_s=0.05)
    store.ingest("a", _frame(name="kv", kind="gauge", value=10.0))
    time.sleep(0.08)
    store.ingest("a", _frame(name="kv", kind="gauge", value=4.0))
    assert store.query("kv")["groups"][0]["value"] == 4.0


def test_summarize_histogram_digest():
    store = MetricsStore(window_s=3600.0)
    for _ in range(3):
        store.ingest("a", _frame(count=10, sum=1.0, buckets=[9, 1]))
    digest = summarize_histogram(store.query("lat"))
    assert digest["count"] == 30
    assert digest["mean"] == pytest.approx(0.1)
    assert digest["p50"] <= 1.0
    assert summarize_histogram({"groups": []}) == {"count": 0}


# ---------------------------------------------------------------------------
# pusher: claim exclusivity + bounded buffer (never blocks, never grows)
# ---------------------------------------------------------------------------

def test_pusher_claim_is_process_exclusive():
    from ray_tpu.runtime import metrics_plane as mp

    # earlier tests may leave a live claim (e.g. a driver pusher from a
    # prior cluster that outlived its shutdown); park it for the test
    held = mp._claimed
    mp._claimed = None
    try:
        assert claim_pusher("owner-a")
        assert claim_pusher("owner-a")          # re-claim by owner: ok
        assert not claim_pusher("owner-b")      # second owner: refused
        release_pusher("owner-a")
        assert claim_pusher("owner-b")
        release_pusher("owner-b")
    finally:
        mp._claimed = held


def test_pusher_buffer_bounded_against_dead_gcs():
    c = m.counter("t_push")
    # nothing listens here: every push fails fast (connection refused)
    pusher = MetricsPusher(("127.0.0.1", 1), src="t", interval_s=60.0)
    cap = pusher._buf_cap
    for i in range(cap + 3):
        c.inc()
        pusher.flush_now()
    assert len(pusher._buf) <= cap
    assert pusher.dropped >= 3
    assert pusher.pushed == 0
    pusher.stop()


# ---------------------------------------------------------------------------
# overhead gate: instrumented hot path < 3% vs RAY_TPU_METRICS_ENABLED=0
# ---------------------------------------------------------------------------

def test_hot_path_overhead_under_three_percent():
    """Gate: instrumentation adds < 3% to the store hot path vs
    RAY_TPU_METRICS_ENABLED=0.

    The true overhead (~15ns/op: one sampled op in 64 pays two
    perf_counter calls plus a histogram observe, ~1us total) is far
    below the +/-3-5% wall-clock noise floor of a shared CI host, so an
    end-to-end enabled/disabled timing diff cannot resolve it — the
    noise IS the measurement. Instead measure the two factors that are
    each stable under min-of-k:
      1. baseline per-op cost of the real put/get/free hot path with
         metrics disabled (uniform steady-state loop: no dict growth,
         so no rehash/GC spikes), and
      2. the per-SAMPLED-op delta between enabled and disabled mode,
         timed directly over the exact extra work a sampled op does
         (perf_counter pair + handle observe behind the enabled probe),
    then amortize (2) over the sampling mask and gate the ratio.
    A loose end-to-end tripwire still catches gross mistakes like
    instrumentation running unsampled on every op."""
    from ray_tpu.runtime import object_store as osmod
    from ray_tpu.runtime.object_store import ObjectStore
    from ray_tpu.utils.ids import ObjectID

    keep = ObjectID.from_random()
    cyc = ObjectID.from_random()
    payload = b"x" * 128
    store = ObjectStore()
    store.put(keep, payload)

    def op_loop(n=5000):
        t0 = time.perf_counter()
        for _ in range(n):
            store.put(cyc, payload)
            store.get([keep])
            store.free([cyc])
        return (time.perf_counter() - t0) / (2 * n)   # per instrumented op

    def instr_delta(n=20000):
        # enabled side: what a sampled op pays on top of the mask test
        h = osmod._h_put
        m.set_enabled(True)
        t0 = time.perf_counter()
        for _ in range(n):
            if m.enabled():
                a = time.perf_counter()
                h.observe(time.perf_counter() - a)
        t1 = time.perf_counter()
        # disabled side: the same probe short-circuits to nothing
        m.set_enabled(False)
        t2 = time.perf_counter()
        for _ in range(n):
            if m.enabled():
                pass
        t3 = time.perf_counter()
        return ((t1 - t0) - (t3 - t2)) / n

    mask = osmod._SAMPLE_MASK + 1
    m.set_enabled(False)
    op_loop()                                     # warm code + allocator
    instr_delta()
    t_op = min(op_loop() for _ in range(5))
    t_delta = min(instr_delta() for _ in range(5))
    overhead = t_delta / mask / t_op
    assert overhead < 0.03, \
        f"instrumented hot path costs {overhead:.2%}/op (gate: 3%): " \
        f"sampled-op delta {t_delta*1e9:.0f}ns / mask {mask} " \
        f"on a {t_op*1e9:.0f}ns baseline op"

    # gross tripwire: interleaved end-to-end mins; generous bound only
    # trips if instrumentation starts running unsampled on every op
    on, off = [], []
    for _ in range(5):
        m.set_enabled(True)
        on.append(op_loop())
        m.set_enabled(False)
        off.append(op_loop())
    m.set_enabled(None)
    assert min(on) / min(off) - 1.0 < 0.25


# ---------------------------------------------------------------------------
# acceptance: cross-node histogram query over a multi-raylet cluster
# ---------------------------------------------------------------------------

@pytest.fixture
def two_raylet_cluster(monkeypatch):
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.utils.config import reset_config

    # fast pushes; external processes inherit the env
    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.2")
    reset_config()
    ray_tpu.shutdown()
    c = Cluster(external_gcs=True)
    c.add_node(num_cpus=2, external=True)
    c.add_node(num_cpus=2, resources={"side": 4}, external=True)
    ray_tpu.init(address=c.gcs_address)
    c.wait_for_nodes(2)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    reset_config()


def test_cross_node_lease_grant_p99(two_raylet_cluster):
    """One driver call answers 'p99 lease grant latency over all
    raylets': every raylet is its own OS process pushing its own frames,
    and the GCS store groups the merged histogram by src."""
    from ray_tpu.util import state as state_api

    @ray_tpu.remote
    def nop(i):
        return i

    @ray_tpu.remote(resources={"side": 1})
    def side_nop(i):
        return i

    # lease grants on BOTH raylets
    assert ray_tpu.get([nop.remote(i) for i in range(20)],
                       timeout=120) == list(range(20))
    assert ray_tpu.get([side_nop.remote(i) for i in range(20)],
                       timeout=120) == list(range(20))

    def srcs():
        res = state_api.cluster_metrics("ray_tpu_lease_grant_s",
                                        group_by=["src"])
        return {g["tags"]["src"] for g in res.get("groups", [])
                if isinstance(g.get("value"), dict)
                and g["value"]["count"] > 0}

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and len(srcs()) < 2:
        time.sleep(0.25)
    assert len(srcs()) >= 2, \
        f"expected grants from both raylets, saw srcs {srcs()}"

    res = state_api.cluster_metrics("ray_tpu_lease_grant_s")
    digest = summarize_histogram(res)
    assert digest["count"] >= 2
    assert digest["p99"] >= digest["p50"] >= 0.0
    # and the one-call cluster digest carries the same metric
    lat = state_api.summarize_latencies(last_s=None)
    assert "ray_tpu_lease_grant_s" in lat
    assert lat["ray_tpu_lease_grant_s"]["count"] == digest["count"]
