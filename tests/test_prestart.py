"""Fork-server worker pool (``runtime/prestart.py``) edge cases.

Reference analog: ``src/ray/raylet/worker_pool_test.cc`` (PopWorker /
PrestartWorkers paths) — here against the zygote fork plane: forked
workers must be indistinguishable from cold spawns (fresh fault plane,
no inherited control fd), template death at any moment must degrade to
cold spawn without losing work, and env-keyed templates must never
serve a fork for the wrong runtime env.

The cluster fixture is IN-PROCESS (``Cluster()`` + ``add_node``), so the
raylet's ``WorkerPool``/``PrestartManager`` are directly inspectable
while real template/worker processes run underneath.
"""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.runtime import fault_injection as fi
from ray_tpu.runtime.prestart import ZYGOTE_FD_ENV
from ray_tpu.runtime_env import env_key


@pytest.fixture(scope="module")
def _shared_cluster():
    """One in-process cluster for the whole module: templates respawn
    after every kill these tests inflict, so sharing is safe and saves
    a cluster boot/teardown per test."""
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=4)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


@pytest.fixture
def cluster(_shared_cluster):
    yield _shared_cluster
    fi.plane.load_plan(None)   # heal any plan a test installed


def _pool(cluster):
    return next(iter(cluster.nodes.values())).raylet.workers


def _warm_template(mgr, key: str = "", runtime_env=None, timeout=90.0):
    """Explicitly spawn the env-keyed template (warm() bypasses the
    spawn-request threshold) and wait until it answers the ready frame."""
    mgr.warm(runtime_env)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with mgr.lock:
            t = mgr.templates.get(key)
        if t is not None and t.poll_ready(timeout=0.2):
            return t
        time.sleep(0.1)
    raise AssertionError(f"template for key {key!r} never became ready")


@ray_tpu.remote
def _probe():
    """Runs in a worker: report fork provenance + inherited-state audit."""
    from ray_tpu.runtime import fault_injection as wfi
    from ray_tpu.runtime import prestart

    return {
        "pid": os.getpid(),
        "child_info": prestart.CHILD_INFO,
        "zygote_fd_env": ZYGOTE_FD_ENV in os.environ,
        "plane_active": wfi.plane.active,
        "plane_rules": len(wfi.plane._rules),
    }


def test_forked_worker_serves_tasks_with_fresh_state(cluster):
    """Once the template is warm every spawn forks; the forked worker
    runs tasks AND carries no template leftovers: the zygote control fd
    env var is gone and the fault plane is empty even while the RAYLET's
    plane has live rules (forked children must not inherit chaos state
    the template never loaded)."""
    pool = _pool(cluster)
    _warm_template(pool.prestart)
    # raylet-side plan that no worker should ever see (method pinned to
    # an RPC name no worker calls, so it never fires — it only needs to
    # make the raylet's plane ACTIVE while children boot)
    fi.plane.load_plan({"rules": [{"fault": "drop",
                                   "method": "never_called"}]})
    forked_before = pool.prestart.stats["forked"]
    handle = pool.spawn(None)
    assert handle.forked, "warm template did not serve the fork path"
    assert pool.prestart.stats["forked"] == forked_before + 1
    # occupy every worker so at least one probe lands on the forked one
    out = ray_tpu.get([_probe.remote() for _ in range(16)], timeout=120)
    by_pid = {r["pid"]: r for r in out}
    forked = [r for r in by_pid.values() if r["child_info"] is not None]
    assert forked, "no probe task ran on a forked worker"
    for r in forked:
        assert r["child_info"]["template_pid"] > 0
        assert not r["zygote_fd_env"]
        assert not r["plane_active"]
        assert r["plane_rules"] == 0


def test_template_spawn_gated_on_demand_threshold(cluster):
    """Below ``prestart_spawn_threshold`` cumulative spawn requests for
    an env key no template exists — every request is a plain cold spawn
    with zero added cost. The Nth request justifies the template."""
    mgr = _pool(cluster).prestart
    renv = {"env_vars": {"PRESTART_MARKER": "gate"}}
    key = env_key(renv)
    from ray_tpu.utils.config import get_config
    thresh = get_config().prestart_spawn_threshold
    assert thresh > 1
    below = mgr.stats["below_threshold"]
    for i in range(thresh - 1):
        assert mgr.fork_worker(renv, f"gate-{i}", None, None) is None
        with mgr.lock:
            assert key not in mgr.templates
    assert mgr.stats["below_threshold"] == below + thresh - 1
    # the Nth request crosses the gate: the template spawns (this
    # request is still a cold-spawn miss while it preloads)
    assert mgr.fork_worker(renv, "gate-n", None, None) is None
    with mgr.lock:
        assert key in mgr.templates


def test_template_crash_falls_back_to_cold_spawn(cluster):
    """SIGKILL the template, then demand workers: everything completes
    via cold spawn and the manager respawns a fresh template."""
    pool = _pool(cluster)
    mgr = pool.prestart
    t = _warm_template(mgr)
    spawns_before = mgr.stats["template_spawns"]
    cold_before = mgr.stats["cold_fallback"]
    os.kill(t.proc.pid, signal.SIGKILL)
    t.proc.wait(timeout=10)
    # every spawn while the replacement preloads is a cold fallback;
    # tasks still complete (the fallback contract)
    handle = pool.spawn(None)
    assert not handle.forked
    assert ray_tpu.get([_probe.remote() for _ in range(8)],
                       timeout=120)
    assert mgr.stats["cold_fallback"] > cold_before
    assert mgr.stats["template_deaths"] >= 1
    assert mgr.stats["template_spawns"] == spawns_before + 1
    with mgr.lock:
        t2 = mgr.templates.get("")
    assert t2 is not None and t2 is not t and t2.alive()


def test_kill_template_fault_burst_loses_no_leases(cluster):
    """Chaos-tier criterion: a ``kill_template`` fault fired mid-burst
    (the 3rd fork acquisition) must not lose a single actor creation —
    the pool cold-spawns through the gap and respawns the template."""
    pool = _pool(cluster)
    _warm_template(pool.prestart)
    fi.plane.load_plan({"rules": [{"fault": "kill_template",
                                   "method": "fork_worker",
                                   "nth": 3, "max_hits": 1}]})

    @ray_tpu.remote(num_cpus=0)
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    n = int(os.environ.get("RAY_TPU_TEST_BURST_ACTORS", "64"))
    actors = [A.remote(i) for i in range(n)]
    try:
        got = ray_tpu.get([a.who.remote() for a in actors], timeout=600)
        assert got == list(range(n))
        assert pool.prestart.stats["fault_template_kills"] >= 1
        assert pool.prestart.stats["template_spawns"] >= 2
    finally:
        fi.plane.load_plan(None)
        for a in actors:
            ray_tpu.kill(a)


def test_env_key_mismatch_never_crosses_templates(cluster):
    """Two runtime envs get two templates, and a worker for env A is
    forked from template A (its own env var set, provenance pid = the
    A template) — never from B's."""
    pool = _pool(cluster)
    mgr = pool.prestart
    env_a = {"env_vars": {"PRESTART_MARKER": "a"}}
    env_b = {"env_vars": {"PRESTART_MARKER": "b"}}
    key_a, key_b = env_key(env_a), env_key(env_b)
    assert key_a != key_b
    ta = _warm_template(mgr, key_a, env_a)
    tb = _warm_template(mgr, key_b, env_b)
    assert ta.proc.pid != tb.proc.pid
    with mgr.lock:
        assert mgr.templates[key_a].runtime_env == env_a
        assert mgr.templates[key_b].runtime_env == env_b

    @ray_tpu.remote
    def probe_env():
        from ray_tpu.runtime import prestart

        return {"marker": os.environ.get("PRESTART_MARKER"),
                "child_info": prestart.CHILD_INFO}

    for renv, marker, template in ((env_a, "a", ta), (env_b, "b", tb)):
        out = ray_tpu.get(
            probe_env.options(runtime_env=renv).remote(), timeout=120)
        assert out["marker"] == marker
        if out["child_info"] is not None:
            assert out["child_info"]["template_pid"] == template.proc.pid


def test_template_honors_jax_fork_safety(cluster):
    """The template preloads the worker import closure but must hold no
    live XLA backend and no extra threads (fork from a threaded process
    inherits locked locks)."""
    mgr = _pool(cluster).prestart
    t = _warm_template(mgr)
    st = t.status()
    assert st["ok"]
    assert st["jax_backends_initialized"] is False
    assert st["threads"] == 1
    assert "numpy" in st["preloaded"]
    assert "ray_tpu.runtime.rpc" in st["preloaded"]


def test_reset_after_fork_clears_plane():
    """Unit: the child-side reset installs a fresh, inactive plane even
    if (impossibly) the template had loaded rules."""
    fi.plane.load_plan({"rules": [{"fault": "drop"}]})
    assert fi.plane.active
    old = fi.plane
    fi.reset_after_fork()
    try:
        assert fi.plane is not old
        assert not fi.plane.active
        assert fi.plane._rules == ()
    finally:
        old.load_plan(None)
