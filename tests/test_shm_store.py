"""C++ shared-memory object store tests.

Reference analog: ``src/ray/object_manager/plasma/test/`` (create/seal/get
lifecycle, eviction, delete) plus a cross-process zero-copy check the
reference does via its UDS client.
"""

import multiprocessing as mp
import os

import pytest

from ray_tpu._private.shm_store import (
    ObjectExistsError,
    ObjectNotFoundError,
    ShmObjectStore,
    StoreFullError,
)


def oid(n: int) -> bytes:
    return n.to_bytes(4, "big") + b"\x00" * 16


@pytest.fixture
def store():
    name = f"/tpustore_test_{os.getpid()}"
    s = ShmObjectStore(name, capacity=1 << 20, create=True)
    yield s
    s.close()


def test_put_get_roundtrip(store):
    store.put(oid(1), b"hello world")
    view = store.get(oid(1))
    assert bytes(view) == b"hello world"
    store.release(oid(1))


def test_create_seal_get(store):
    buf = store.create(oid(2), 5)
    buf[:] = b"abcde"
    assert not store.contains(oid(2))  # unsealed objects are invisible
    store.seal(oid(2))
    assert store.contains(oid(2))
    assert bytes(store.get(oid(2))) == b"abcde"
    store.release(oid(2))


def test_duplicate_create_fails(store):
    store.put(oid(3), b"x")
    with pytest.raises(ObjectExistsError):
        store.create(oid(3), 1)


def test_get_missing_nonblocking(store):
    with pytest.raises(ObjectNotFoundError):
        store.get(oid(99), timeout_ms=-1)


def test_get_timeout(store):
    with pytest.raises(ObjectNotFoundError):
        store.get(oid(98), timeout_ms=50)


def test_delete_and_refcount(store):
    store.put(oid(4), b"data")
    view = store.get(oid(4))  # refcount 1
    assert not store.delete(oid(4))  # referenced -> refuse
    del view
    store.release(oid(4))
    assert store.delete(oid(4))
    assert not store.contains(oid(4))


def test_lru_eviction_under_pressure(store):
    # Fill the 1 MiB arena with sealed, unreferenced 100 KiB objects, then
    # allocate more: oldest must be evicted, newest retained.
    blob = b"z" * (100 * 1024)
    for i in range(20):
        store.put(oid(100 + i), blob)
    stats = store.stats()
    assert stats["num_evictions"] > 0
    assert store.contains(oid(119))  # newest survives
    assert not store.contains(oid(100))  # oldest evicted


def test_pinned_objects_not_evicted(store):
    blob = b"p" * (200 * 1024)
    store.put(oid(5), blob)
    view = store.get(oid(5))  # pin
    for i in range(30):
        store.put(oid(200 + i), b"q" * (100 * 1024))
    assert store.contains(oid(5))
    assert bytes(view[:3]) == b"ppp"
    store.release(oid(5))


def test_oversized_object_rejected(store):
    with pytest.raises(StoreFullError):
        store.create(oid(6), 2 << 20)


def test_stats(store):
    store.put(oid(7), b"s" * 1000)
    st = store.stats()
    assert st["num_objects"] == 1
    assert st["bytes_allocated"] >= 1000


def _child_reader(name: str, object_id: bytes, q):
    s = ShmObjectStore(name)  # attach
    view = s.get(object_id, timeout_ms=5000)
    q.put(bytes(view))
    s.release(object_id)
    s.close()


def _child_writer(name: str, object_id: bytes, payload: bytes):
    s = ShmObjectStore(name)
    s.put(object_id, payload)
    s.close()


def test_cross_process_read(store):
    store.put(oid(8), b"cross-process payload")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_child_reader, args=(store.name, oid(8), q))
    p.start()
    assert q.get(timeout=30) == b"cross-process payload"
    p.join(timeout=10)


def test_cross_process_write_blocking_get(store):
    # Parent blocks in get() while a child creates+seals the object.
    ctx = mp.get_context("spawn")
    p = ctx.Process(target=_child_writer,
                    args=(store.name, oid(9), b"from child"))
    p.start()
    view = store.get(oid(9), timeout_ms=20000)
    assert bytes(view) == b"from child"
    store.release(oid(9))
    p.join(timeout=10)


def test_orphan_eviction(store):
    store.create(oid(10), 100)  # never sealed (simulates crashed writer)
    assert store.evict_orphans() == 1
    with pytest.raises(ObjectNotFoundError):
        store.get(oid(10), timeout_ms=-1)


def _child_reader_crash(name: str, object_id: bytes, q):
    s = ShmObjectStore(name)
    s.get(object_id, timeout_ms=5000)  # take a ref, never release
    q.put(os.getpid())
    q.close()
    q.join_thread()  # flush the feeder before crashing
    os._exit(1)  # crash while holding the ref


def test_crashed_reader_refs_reclaimed(store):
    store.put(oid(11), b"pinned by crasher")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_child_reader_crash, args=(store.name, oid(11), q))
    p.start()
    pid = q.get(timeout=30)
    p.join(timeout=10)
    assert not store.delete(oid(11))      # ref still pinned
    assert store.release_pid(pid) == 1    # crash cleanup drops it
    assert store.delete(oid(11))


def test_many_objects_fragmentation(store):
    # Alternating alloc/free exercises free-list coalescing.
    for round_ in range(3):
        for i in range(50):
            store.put(oid(1000 + i), bytes([round_]) * (1024 * (1 + i % 7)))
        for i in range(0, 50, 2):
            store.delete(oid(1000 + i))
        for i in range(1, 50, 2):
            store.delete(oid(1000 + i))
    st = store.stats()
    assert st["num_objects"] == 0


def test_get_many_hits_and_misses(store):
    store.put(oid(20), b"a" * 64)
    store.put(oid(21), b"b" * 128)
    store.create(oid(22), 16)  # created but UNSEALED -> miss
    views = store.get_many([oid(20), oid(99), oid(21), oid(22)])
    assert bytes(views[0]) == b"a" * 64
    assert views[1] is None          # absent
    assert bytes(views[2]) == b"b" * 128
    assert views[3] is None          # unsealed
    # hits hold read refs: delete refuses until released
    assert not store.delete(oid(20))
    del views
    store.release_many([oid(20), oid(21)])
    assert store.delete(oid(20))
    assert store.delete(oid(21))


def test_get_many_duplicate_ids_refcount_symmetry(store):
    store.put(oid(30), b"dup")
    ids = [oid(30)] * 5
    views = store.get_many(ids)
    assert all(bytes(v) == b"dup" for v in views)
    del views
    assert not store.delete(oid(30))   # 5 refs held
    store.release_many(ids)            # symmetric: all 5 dropped
    assert store.delete(oid(30))


def test_release_many_absent_ids_noop(store):
    store.put(oid(40), b"x")
    # releasing ids that were never acquired must not underflow others
    store.release_many([oid(40), oid(41), oid(40)])
    assert store.delete(oid(40))


def test_driver_get_fast_path_error_object_order():
    """An error object mid-list raises (in order) through the batched
    fast path, and the read refs are released (shutdown stays clean)."""
    import ray_tpu
    from ray_tpu.utils import exceptions as exc

    ray_tpu.init()
    try:
        @ray_tpu.remote
        def boom():
            raise ValueError("fastpath-err")

        good = [ray_tpu.put(i) for i in range(10)]
        bad = boom.remote()
        done, _ = ray_tpu.wait([bad], timeout=30)
        assert done
        with pytest.raises(exc.TaskError, match="fastpath-err"):
            ray_tpu.get(good + [bad] + good)
        assert ray_tpu.get(good) == list(range(10))
    finally:
        ray_tpu.shutdown()
