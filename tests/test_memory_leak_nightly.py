"""Nightly memory-plane leak soak (ISSUE-17 satellite): churn >= 50k
owned refs through put/submit/release cycles on a two-external-raylet
cluster, then assert the leak detector flags ZERO false positives on
the churn and exactly the one deliberately-held ref — with its
creation call site.

ci/run_ci.sh --nightly runs this with ``-m nightly``.
"""

import time

import pytest

import ray_tpu
from ray_tpu.runtime import core as _core
from ray_tpu.util import state as state_api
from ray_tpu.utils.config import reset_config

CHURN_REFS = 50_000
THRESHOLD_S = 5.0
IDLE_S = 1.0


def _wait(predicate, timeout, what):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.fixture
def leak_soak_cluster(monkeypatch):
    from ray_tpu.cluster_utils import Cluster

    # external raylets + GCS inherit these at spawn
    monkeypatch.setenv("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.2")
    monkeypatch.setenv("RAY_TPU_MEMORY_LEAK_THRESHOLD_S",
                       str(THRESHOLD_S))
    monkeypatch.setenv("RAY_TPU_MEMORY_LEAK_IDLE_S", str(IDLE_S))
    reset_config()
    ray_tpu.shutdown()
    c = Cluster(external_gcs=True)
    c.add_node(num_cpus=2, external=True)
    c.add_node(num_cpus=2, resources={"side": 4}, external=True)
    ray_tpu.init(address=c.gcs_address)
    c.wait_for_nodes(2)
    yield c
    ray_tpu.shutdown()
    c.shutdown()
    reset_config()


@ray_tpu.remote
def echo(x):
    return x


@ray_tpu.remote(resources={"side": 1})
def side_echo(x):
    return x


@pytest.mark.nightly
def test_leak_soak_churn_clean_planted_flagged(leak_soak_cluster):
    planted = ray_tpu.put(b"P" * 16384)   # the ONE deliberate leak

    # churn: >= 50k owned refs created and released across both raylets
    churned = 0
    t0 = time.monotonic()
    while churned < CHURN_REFS:
        batch = [ray_tpu.put(b"c" * 512) for _ in range(2000)]
        churned += len(batch)
        del batch
        # interleave task-return refs on BOTH raylets so the churn
        # exercises remote-owned releases too, not just local puts
        if churned % 10_000 == 0:
            rs = [echo.remote(i) for i in range(20)]
            rs += [side_echo.remote(i) for i in range(20)]
            assert len(ray_tpu.get(rs, timeout=120)) == 40
            churned += 40
            del rs   # still-bound task returns WOULD be real leaks
    churn_wall = time.monotonic() - t0
    print(f"churned {churned} refs in {churn_wall:.1f}s "
          f"({churned / churn_wall:,.0f}/s)")

    # now idle past the threshold: every churned ref died young, so the
    # detector must flag exactly the planted survivor
    def planted_only():
        leaks = state_api.memory_leaks()
        return leaks if leaks else None

    leaks = _wait(planted_only, THRESHOLD_S + 60,
                  "the planted ref to age past the leak threshold")
    assert len(leaks) == 1, \
        f"false-positive leak flags on churned refs: {leaks}"
    leak = leaks[0]
    assert leak["size_bytes"] >= 16384
    assert leak["owner"] == _core.get_runtime().client_id
    assert leak["callsite"] and \
        __file__.split("/")[-1] in leak["callsite"], leak
    assert leak["age_s"] >= THRESHOLD_S

    # stability: repeated sweeps stay clean (no flicker, no growth)
    for _ in range(3):
        time.sleep(1.0)
        again = state_api.memory_leaks()
        assert len(again) == 1 and \
            again[0]["object_id"] == leak["object_id"], again

    # the suspicion ALSO reaches the error surface with the call site
    groups = [g for g in state_api.summarize_errors()
              if g.get("kind") == "leak"]
    assert groups and __file__.split("/")[-1] in groups[0]["signature"]

    del planted
    _wait(lambda: not state_api.memory_leaks(), 30,
          "leak flag to clear once the planted ref dies")
