"""Gymnasium bridge + offline RL (reference: rllib/env gym-API envs and
rllib/offline/ readers/writers + BC/CQL)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    BCConfig,
    CQLConfig,
    GymEnvAdapter,
    OfflineDataset,
    PPOConfig,
    collect_dataset,
    make_env,
)

gym = pytest.importorskip("gymnasium")


# ---------------------------------------------------------------------------
# gymnasium bridge
# ---------------------------------------------------------------------------


def test_gym_adapter_discrete():
    env = GymEnvAdapter("CartPole-v1", seed=0)
    assert env.obs_dim == 4 and env.n_actions == 2 and not env.continuous
    obs = env.reset()
    assert obs.shape == (4,) and obs.dtype == np.float32
    obs, r, done, info = env.step(1)
    assert obs.shape == (4,) and isinstance(r, float)
    env.close()


def test_gym_adapter_continuous():
    env = GymEnvAdapter("Pendulum-v1", seed=0)
    assert env.continuous and env.action_dim == 1
    assert env.action_low == -2.0 and env.action_high == 2.0
    obs = env.reset()
    assert obs.shape == (3,)
    obs, r, done, _ = env.step(np.array([0.5]))
    assert obs.shape == (3,)
    env.close()


def test_make_env_falls_back_to_gymnasium():
    env = make_env("Acrobot-v1", seed=0)   # not in the builtin registry
    assert isinstance(env, GymEnvAdapter)
    assert env.obs_dim == 6 and env.n_actions == 3
    with pytest.raises(KeyError, match="unknown env"):
        make_env("DefinitelyNotAnEnv-v9")


def test_ppo_trains_on_gymnasium_env(ray_tpu_start):
    """BASELINE config 5's shape: PPO on a real gymnasium env end-to-end
    through the rollout-actor stack."""
    algo = (PPOConfig()
            .environment("Acrobot-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
            .training(num_sgd_iter=2, minibatch_size=64)
            .build())
    try:
        result = algo.train()
        assert result["training_iteration"] == 1
        assert result["num_env_steps_sampled"] == 256
        assert np.isfinite(result["policy_loss"])
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# offline IO
# ---------------------------------------------------------------------------


def test_collect_and_load_dataset(tmp_path):
    path = collect_dataset("Bandit-v0", str(tmp_path / "ds"),
                           num_steps=500, seed=0)
    ds = OfflineDataset(path)
    assert ds.size == 500
    assert ds.data["obs"].shape == (500, 2)
    assert set(np.unique(ds.data["actions"])) <= {0, 1}
    batches = list(ds.minibatches(128, np.random.default_rng(0)))
    assert len(batches) == 3 and batches[0]["obs"].shape == (128, 2)


def test_dataset_writer_shards(tmp_path):
    from ray_tpu.rllib import DatasetWriter

    w = DatasetWriter(str(tmp_path / "sh"), shard_size=100)
    for _ in range(3):
        w.write({"obs": np.zeros((80, 2), np.float32),
                 "actions": np.zeros((80,), np.int32),
                 "rewards": np.zeros((80,), np.float32),
                 "next_obs": np.zeros((80, 2), np.float32),
                 "dones": np.zeros((80,), np.float32)})
    w.close()
    ds = OfflineDataset(str(tmp_path / "sh"))
    assert ds.size == 240


# ---------------------------------------------------------------------------
# offline algorithms
# ---------------------------------------------------------------------------


def _expert_bandit_policy(obs):
    return 1 if obs[0] > 0 else 0


def test_bc_clones_expert(tmp_path):
    path = collect_dataset("Bandit-v0", str(tmp_path / "expert"),
                           num_steps=1000, policy=_expert_bandit_policy,
                           seed=0)
    algo = (BCConfig().environment("Bandit-v0").offline_data(path)
            .training(lr=3e-3).build())
    first = algo.train()["loss"]
    for _ in range(9):
        last = algo.train()
    assert last["loss"] < first
    score = algo.evaluate(num_episodes=50)["episode_return_mean"]
    assert score > 0.9, f"BC failed to clone the expert: {score}"


def test_cql_learns_from_random_data(tmp_path):
    """CQL's value: learn a BETTER-than-behavior policy from random
    logged data (BC would only clone the random 0.5 behavior)."""
    path = collect_dataset("Bandit-v0", str(tmp_path / "random"),
                           num_steps=2000, seed=0)
    algo = (CQLConfig().environment("Bandit-v0").offline_data(path)
            .training(lr=3e-3, gamma=0.0, cql_alpha=0.5).build())
    for _ in range(10):
        result = algo.train()
    assert np.isfinite(result["td_loss"])
    assert np.isfinite(result["cql_loss"])
    score = algo.evaluate(num_episodes=50)["episode_return_mean"]
    assert score > 0.9, f"CQL failed to beat the behavior policy: {score}"


# ---------------------------------------------------------------------------
# round-3: connectors + off-policy estimators
# ---------------------------------------------------------------------------

def test_connector_pipeline_obs():
    import numpy as np

    from ray_tpu.rllib import (ConnectorPipeline, FlattenObs, FrameStack,
                               NormalizeObs)

    norm = NormalizeObs()
    pipe = ConnectorPipeline([FlattenObs(), norm])
    rng = np.random.default_rng(0)
    out = None
    for _ in range(200):
        out = pipe(rng.normal(3.0, 2.0, size=(2, 2)))
    assert out.shape == (4,)
    # normalized stream is ~zero-mean unit-var
    assert abs(float(out.mean())) < 3.0
    state = pipe.state_dict()
    fresh = ConnectorPipeline([FlattenObs(), NormalizeObs()])
    fresh.load_state(state)
    np.testing.assert_allclose(fresh.connectors[1].mean, norm.mean)

    fs = FrameStack(k=3)
    first = fs(np.ones(2))
    assert first.shape == (3, 2)
    fs.reset()
    assert fs(np.zeros(2)).sum() == 0.0


def test_connector_env_actions():
    import numpy as np

    from ray_tpu.rllib import ConnectorEnv, NormalizeObs, UnsquashActions

    class RecEnv:
        n_actions = 2

        def __init__(self, seed=None):
            self.last_action = None

        def reset(self):
            return np.zeros(3, np.float32)

        def step(self, action):
            self.last_action = np.asarray(action)
            return np.ones(3, np.float32), 1.0, False, {}

    env = ConnectorEnv(RecEnv, obs_connectors=[NormalizeObs()],
                       action_connectors=[UnsquashActions(-2.0, 2.0)])
    obs = env.reset()
    assert obs.shape == (3,)
    env.step(np.array([1.0]))    # tanh-space 1.0 -> high bound
    assert float(env.env.last_action[0]) == 2.0
    env.step(np.array([-1.0]))
    assert float(env.env.last_action[0]) == -2.0


def test_ope_estimators(tmp_path):
    import numpy as np

    from ray_tpu.rllib import (ImportanceSampling,
                               WeightedImportanceSampling,
                               episodes_from_dataset)

    # synthetic 2-action bandit episodes: behavior uniform; reward = 1
    # only for action 1. A target policy preferring action 1 must score
    # HIGHER than behavior.
    rng = np.random.default_rng(0)
    n = 512
    actions = rng.integers(0, 2, n)
    data = {
        "obs": np.zeros((n, 1), np.float32),
        "actions": actions,
        "rewards": (actions == 1).astype(np.float64),
        "next_obs": np.zeros((n, 1), np.float32),
        "dones": np.ones(n),   # one-step episodes
    }
    episodes = episodes_from_dataset(data)
    assert len(episodes) == n

    def behavior_logp(obs, acts):
        return np.log(np.full(len(acts), 0.5))

    def target_logp(obs, acts):
        p = np.where(np.asarray(acts) == 1, 0.9, 0.1)
        return np.log(p)

    is_est = ImportanceSampling(gamma=1.0).estimate(
        episodes, target_logp, behavior_logp)
    wis_est = WeightedImportanceSampling(gamma=1.0).estimate(
        episodes, target_logp, behavior_logp)
    assert 0.4 < is_est["v_behavior"] < 0.6
    assert is_est["v_target"] > 0.8          # ~0.9 expected
    assert 0.8 < wis_est["v_target"] <= 1.0
    assert wis_est["effective_sample_size"] > 10
