"""Unit tests for the raylet's split-out components (worker pool,
scheduler, local object manager) — exercised against stub nodes, no
cluster boot. Reference test analog: the C++ unit suites
``worker_pool_test.cc`` / ``cluster_task_manager_test.cc`` /
``local_object_manager_test.cc`` that test these pieces in isolation."""

import threading
import time

import pytest

from ray_tpu.runtime.scheduler import TaskScheduler
from ray_tpu.runtime.worker_pool import WorkerHandle, WorkerPool


class StubNode:
    """Minimal raylet stand-in for component unit tests."""

    def __init__(self):
        self.node_id = "a" * 32
        self._stopping = False
        self.kicked = 0
        self.released = []
        self.errors = []

    def _kick_dispatch(self):
        self.kicked += 1

    def _release(self, demand):
        self.released.append(dict(demand))

    def _store_task_error(self, task, error):
        self.errors.append((task, error))

    def _forward(self, task, node_id, spill_count):
        return False


class FakeProc:
    def __init__(self):
        self.killed = False
        self.pid = 0

    def kill(self):
        self.killed = True

    def poll(self):
        return None


# ----------------------------------------------------------------------
# WorkerPool
# ----------------------------------------------------------------------

def test_bad_env_registry_ttl(monkeypatch):
    pool = WorkerPool(StubNode(), max_workers=2)
    pool.mark_bad_env("envkey", "pip exploded")
    assert pool.bad_env_error(None) is None
    # matching env key (env_key(None) != "envkey"; probe directly)
    pool._bad_envs["k2"] = ("boom", time.monotonic() - 120)
    from ray_tpu.runtime_env import env_key
    pool._bad_envs[env_key(None)] = ("fresh", time.monotonic())
    assert pool.bad_env_error(None) == "fresh"
    # expired entries are ignored
    pool._bad_envs[env_key(None)] = ("stale", time.monotonic() - 120)
    assert pool.bad_env_error(None) is None


def test_kill_policy_prefers_newest_retriable():
    pool = WorkerPool(StubNode(), max_workers=4)
    old_retriable = WorkerHandle(worker_id="w1", proc=FakeProc(),
                                 state="busy",
                                 current_task={"max_retries": 2},
                                 dispatched_at=1.0)
    new_retriable = WorkerHandle(worker_id="w2", proc=FakeProc(),
                                 state="busy",
                                 current_task={"max_retries": 2},
                                 dispatched_at=5.0)
    non_retriable = WorkerHandle(worker_id="w3", proc=FakeProc(),
                                 state="busy",
                                 current_task={"max_retries": 0},
                                 dispatched_at=9.0)
    actor = WorkerHandle(worker_id="w4", proc=FakeProc(), state="actor",
                         dispatched_at=10.0)
    pool.workers = {w.worker_id: w
                    for w in (old_retriable, new_retriable,
                              non_retriable, actor)}
    assert pool.kill_one_for_memory()
    assert new_retriable.proc.killed and new_retriable.oom_killed
    assert not old_retriable.proc.killed
    assert not actor.proc.killed          # actors never chosen


def test_kill_policy_falls_back_to_leased_then_busy():
    pool = WorkerPool(StubNode(), max_workers=4)
    leased = WorkerHandle(worker_id="w1", proc=FakeProc(), state="leased",
                          dispatched_at=2.0)
    non_retriable = WorkerHandle(worker_id="w2", proc=FakeProc(),
                                 state="busy",
                                 current_task={"max_retries": 0},
                                 dispatched_at=3.0)
    pool.workers = {w.worker_id: w for w in (leased, non_retriable)}
    assert pool.kill_one_for_memory()
    assert leased.proc.killed             # leased preferred over busy
    assert not non_retriable.proc.killed


def test_kill_policy_nothing_to_kill():
    pool = WorkerPool(StubNode(), max_workers=4)
    idle = WorkerHandle(worker_id="w1", proc=FakeProc(), state="idle")
    pool.workers = {"w1": idle}
    assert not pool.kill_one_for_memory()
    assert not idle.proc.killed


def test_death_info_bounded():
    node = StubNode()

    class NoStoreNode(StubNode):
        class store:  # noqa: N801 - stub namespace
            @staticmethod
            def evict_orphans(pid):
                pass

            @staticmethod
            def release_pid(pid):
                pass

    node = NoStoreNode()
    pool = WorkerPool(node, max_workers=1)
    for i in range(300):
        w = WorkerHandle(worker_id=f"w{i}", state="idle")
        pool.workers[w.worker_id] = w
        pool.on_worker_gone(w)
    assert len(pool._death_info) <= 256
    assert pool.death_info("w299") == {"oom_killed": False}
    assert pool.death_info("w0") is None   # evicted from the FIFO


# ----------------------------------------------------------------------
# TaskScheduler
# ----------------------------------------------------------------------

def make_sched(cpu=4.0):
    node = StubNode()
    sched = TaskScheduler(node, resources={"CPU": cpu},
                          infeasible_timeout_s=1.0)
    return node, sched


def test_resource_accounting_acquire_release():
    node, sched = make_sched(cpu=2.0)
    assert sched.try_acquire({"CPU": 1.5})
    assert not sched.try_acquire({"CPU": 1.0})
    assert sched.avail_snapshot()["CPU"] == pytest.approx(0.5)
    sched.release({"CPU": 1.5})
    assert sched.avail_snapshot()["CPU"] == pytest.approx(2.0)
    # release kicks the dispatch generation
    assert sched._dispatch_gen > 0


def test_take_queued_matching():
    _, sched = make_sched()
    t1 = {"name": "a", "return_oids": ["aa"]}
    t2 = {"name": "b", "return_oids": ["bb"]}
    sched.enqueue(t1)
    sched.enqueue(t2)
    hit = sched.take_queued_matching(
        lambda t: "bb" in t.get("return_oids", ()))
    assert hit is t2
    assert list(sched.ready) == [t1]
    assert sched.take_queued_matching(lambda t: False) is None


def test_drop_queued_with_env():
    _, sched = make_sched()
    from ray_tpu.runtime_env import env_key
    bad = {"name": "bad", "runtime_env": {"env_vars": {"X": "1"}}}
    good = {"name": "good"}
    sched.enqueue(bad)
    sched.enqueue(good)
    doomed = sched.drop_queued_with_env(env_key(bad["runtime_env"]))
    assert doomed == [bad]
    assert list(sched.ready) == [good]


def test_stop_fails_parked_lease_waiters():
    _, sched = make_sched()
    waiter = {"demand": {"CPU": 1}, "runtime_env": None,
              "event": threading.Event(), "result": None}
    with sched.cv:
        sched.lease_waiters.append(waiter)
    sched.stop()
    assert waiter["event"].is_set()
    assert waiter["result"] == {"retry": True}


def test_deferred_enqueue_fires():
    node, sched = make_sched()
    task = {"name": "t"}
    sched.defer_enqueue(task, 0.05)
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline and not sched.ready:
        time.sleep(0.01)
    assert list(sched.ready) == [task]


def test_infeasible_park_and_take():
    node, sched = make_sched()

    class GcsStub:
        def call(self, *a, **k):
            return None

    node._gcs = GcsStub()
    node._gcs_lock = threading.Lock()
    task = {"name": "big", "return_oids": ["cc"]}
    sched.park_infeasible(task, {"CPU": 64})
    hit = sched.take_infeasible_matching(
        lambda t: "cc" in t.get("return_oids", ()))
    assert hit is task
    assert sched.take_infeasible_matching(lambda t: True) is None
