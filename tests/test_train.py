"""Trainer + checkpoint tests on the 8-device CPU mesh."""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from ray_tpu.models import llama
from ray_tpu.parallel.mesh import create_mesh
from ray_tpu.train.trainer import JaxTrainer, TrainConfig


def _toy_trainer(strategy="fsdp_tp", axes=None):
    mesh = create_mesh(axes or {"dp": 2, "fsdp": 2, "tp": 2})
    cfg = llama.llama_tiny(vocab_size=128)
    tc = TrainConfig(strategy=strategy, learning_rate=1e-3, warmup_steps=2,
                     total_steps=50)
    return JaxTrainer(cfg, tc, mesh=mesh), cfg


def _batches(cfg, batch=8, seq=16, seed=0):
    key = jax.random.key(seed)
    while True:
        key, k = jax.random.split(key)
        yield jax.random.randint(k, (batch, seq + 1), 0, cfg.vocab_size,
                                 dtype=jnp.int32)


def test_init_state_is_sharded():
    trainer, cfg = _toy_trainer()
    state = trainer.init_state(jax.random.key(0))
    w1 = state.params["blocks"]["w_gate"]
    # [L, embed, mlp] with fsdp on embed, tp on mlp
    from jax.sharding import PartitionSpec as P

    assert w1.sharding.spec == P(None, "fsdp", "tp")
    # optimizer moments share the param sharding (ZeRO)
    mu = trainer.optimizer  # noqa: F841
    leaves = jax.tree.leaves(state.opt_state)
    moment = [l for l in leaves if getattr(l, "shape", ()) == w1.shape]
    assert moment and moment[0].sharding.spec == P(None, "fsdp", "tp")


def test_train_loss_decreases():
    trainer, cfg = _toy_trainer()
    state = trainer.init_state(jax.random.key(0))
    # overfit a single repeated batch
    batch = next(_batches(cfg))
    losses = []
    for _ in range(10):
        state, metrics = trainer.train_step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses
    assert int(state.step) == 10


def test_train_strategies_agree():
    # Same data, same seed, different sharding strategies -> same loss curve.
    all_losses = {}
    for strategy in ("dp", "fsdp", "fsdp_tp"):
        trainer, cfg = _toy_trainer(strategy=strategy)
        state = trainer.init_state(jax.random.key(0))
        batch = next(_batches(cfg))
        losses = []
        for _ in range(3):
            state, m = trainer.train_step(state, batch)
            losses.append(float(m["loss"]))
        all_losses[strategy] = losses
    base = all_losses["dp"]
    for name, ls in all_losses.items():
        np.testing.assert_allclose(ls, base, rtol=0.05, err_msg=name)


def test_padding_masked_in_loss():
    trainer, cfg = _toy_trainer()
    state = trainer.init_state(jax.random.key(0))
    batch = next(_batches(cfg))
    padded = batch.at[:, 8:].set(-1)  # mask later targets
    state, m1 = trainer.train_step(state, batch)
    # state was donated; continue with the returned one (recompile-free)
    state, m2 = trainer.train_step(state, padded)
    assert np.isfinite(float(m2["loss"]))


def test_checkpoint_save_restore(tmp_path):
    from ray_tpu.train.checkpoint import CheckpointManager

    trainer, cfg = _toy_trainer()
    state = trainer.init_state(jax.random.key(0))
    batch = next(_batches(cfg))
    state, _ = trainer.train_step(state, batch)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    mgr.save(int(state.step), state)
    mgr.wait()
    assert mgr.latest_step() == 1

    restored = mgr.restore(
        target=state, shardings=trainer.state_shardings()
    )
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32), np.asarray(b, dtype=np.float32)
        )
    # restored state continues training identically
    s1, m1 = trainer.train_step(state, batch)
    s2, m2 = trainer.train_step(restored, batch)
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-5)
    mgr.close()


def test_checkpoint_topk_retention(tmp_path):
    from ray_tpu.train.checkpoint import CheckpointManager

    trainer, cfg = _toy_trainer(axes={"dp": 8})
    state = trainer.init_state(jax.random.key(0))
    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2,
                            async_save=False)
    for s in (1, 2, 3, 4):
        mgr.save(s, state, force=True)
    mgr.wait()
    assert mgr.all_steps() == [3, 4]
    mgr.close()


def test_graft_entry_single():
    import importlib.util, pathlib

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__",
        pathlib.Path(__file__).resolve().parents[1] / "__graft_entry__.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (4, 512, 32768)

    mod.dryrun_multichip(8)


def test_train_with_sequence_parallel_ring_attention():
    # fsdp_tp_sp rules on a mesh with an sp axis: ring attention path.
    mesh = create_mesh({"fsdp": 2, "sp": 2, "tp": 2})
    cfg = llama.llama_tiny(vocab_size=128)
    tc = TrainConfig(strategy="fsdp_tp_sp", learning_rate=1e-3,
                     warmup_steps=2, total_steps=50)
    trainer = JaxTrainer(cfg, tc, mesh=mesh)
    assert trainer.attn_impl == "ring"
    state = trainer.init_state(jax.random.key(0))
    batch = next(_batches(cfg))
    losses = []
    for _ in range(6):
        state, m = trainer.train_step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses

    # parity: same model under plain fsdp gives the same loss curve
    mesh2 = create_mesh({"fsdp": 8})
    trainer2 = JaxTrainer(cfg, TrainConfig(strategy="fsdp", learning_rate=1e-3,
                                           warmup_steps=2, total_steps=50),
                          mesh=mesh2)
    state2 = trainer2.init_state(jax.random.key(0))
    losses2 = []
    for _ in range(6):
        state2, m2 = trainer2.train_step(state2, batch)
        losses2.append(float(m2["loss"]))
    np.testing.assert_allclose(losses, losses2, rtol=0.05)
