"""Placement groups, jobs, autoscaler, chaos (reference analogs:
test_placement_group*.py, job tests, autoscaler/v2/tests, chaos suite)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=4)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_placement_group_api_and_strategy(cluster):
    from ray_tpu.util.placement_group import (
        PlacementGroupSchedulingStrategy,
        placement_group,
        placement_group_table,
        remove_placement_group,
    )

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=10)
    table = placement_group_table(pg)
    assert table["state"] == "CREATED"

    @ray_tpu.remote(
        num_cpus=1,
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg))
    def in_pg():
        import os
        return os.environ["RAY_TPU_NODE_ID"]

    node = ray_tpu.get(in_pg.remote())
    assert node in table["bundle_nodes"]
    remove_placement_group(pg)


def test_job_submission(cluster, tmp_path):
    from ray_tpu.job_submission import JobSubmissionClient

    script = tmp_path / "job.py"
    script.write_text("print('job ran ok')\n")
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"python {script}")
    status = client.wait_until_finish(job_id, timeout=60)
    assert status == "SUCCEEDED"
    assert "job ran ok" in client.get_job_logs(job_id)


def test_job_failure_status(cluster, tmp_path):
    from ray_tpu.job_submission import JobSubmissionClient

    script = tmp_path / "bad.py"
    script.write_text("raise SystemExit(3)\n")
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=f"python {script}")
    assert client.wait_until_finish(job_id, timeout=60) == "FAILED"
    assert client.get_job_info(job_id)["returncode"] == 3


def test_autoscaler_scales_up_and_down():
    from ray_tpu.autoscaler import LocalNodeProvider, StandardAutoscaler

    ray_tpu.shutdown()
    c = Cluster(heartbeat_timeout_s=3.0)
    c.add_node(num_cpus=1)
    ray_tpu.init(address=c.gcs_address)
    provider = LocalNodeProvider(c)
    scaler = StandardAutoscaler(
        c.gcs_address, provider, node_resources={"CPU": 2},
        max_nodes=2, idle_timeout_s=1.5, poll_interval_s=0.2).start()
    try:
        @ray_tpu.remote(num_cpus=1)
        def busy():
            time.sleep(4)
            return 1

        refs = [busy.remote() for _ in range(4)]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if provider.non_terminated_nodes():
                break
            time.sleep(0.2)
        assert provider.non_terminated_nodes(), "no scale-up under load"
        # generous margins: under a saturated CI machine, worker spawn +
        # scale-up latency can stretch the 4s tasks well past a minute
        ray_tpu.get(refs, timeout=120)
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            if not provider.non_terminated_nodes():
                break
            time.sleep(0.3)
        assert not provider.non_terminated_nodes(), "no idle scale-down"
    finally:
        scaler.stop()
        ray_tpu.shutdown()
        c.shutdown()


def test_chaos_node_killer():
    """NodeKiller chaos (reference: _private/test_utils.py:1401): kill a
    worker node mid-workload; retriable tasks must still complete."""
    ray_tpu.shutdown()
    c = Cluster(heartbeat_timeout_s=1.5)
    c.add_node(num_cpus=2)          # head (in-process, survives)
    victim = c.add_node(num_cpus=2, resources={"victim": 2}, external=True)
    c.wait_for_nodes(2)
    ray_tpu.init(address=c.gcs_address)
    try:
        @ray_tpu.remote(num_cpus=1, max_retries=2)
        def slow_task(i):
            time.sleep(0.5)
            return i

        refs = [slow_task.remote(i) for i in range(8)]
        time.sleep(0.3)
        c.remove_node(victim)  # SIGKILL mid-workload
        out = sorted(ray_tpu.get(refs, timeout=120))
        assert out == list(range(8))
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_autoscaler_provisions_for_infeasible_task():
    """A task no existing node can EVER satisfy parks as pending demand;
    the autoscaler sees the demand and provisions a node that fits it
    (reference: autoscaler v2's demand-driven path)."""
    import ray_tpu
    from ray_tpu.autoscaler import LocalNodeProvider, StandardAutoscaler
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=1)  # too small for the task below
    scaler = StandardAutoscaler(
        cluster.gcs_address, LocalNodeProvider(cluster),
        node_resources={"CPU": 4}, max_nodes=2,
        poll_interval_s=0.2, idle_timeout_s=60).start()
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)

        @ray_tpu.remote(num_cpus=3)
        def big():
            return "ran"

        # would be infeasible forever on the 1-CPU node; the autoscaler
        # must provision the 4-CPU node within the grace window
        assert ray_tpu.get(big.remote(), timeout=30) == "ran"
        assert len(scaler.provider.non_terminated_nodes()) >= 1
    finally:
        scaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_infeasible_task_errors_after_grace():
    """Without an autoscaler, cluster-wide infeasible tasks still error
    (after the grace window) rather than hanging forever."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=1, infeasible_timeout_s=1.0)
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)

        @ray_tpu.remote(num_cpus=64)
        def huge():
            return 1

        with pytest.raises(Exception, match="infeasible"):
            ray_tpu.get(huge.remote(), timeout=20)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_slice_pack_placement_group():
    """SLICE_PACK confines all bundles to one TPU slice (ICI locality —
    SURVEY §7 TPU twist); cross-slice placement would silently halve
    collective bandwidth, so no-fitting-slice is strictly infeasible."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.placement_group import (placement_group,
                                              placement_group_table)

    cluster = Cluster()
    # slice A: 2 nodes x 4 TPU; slice B: 2 nodes x 2 TPU
    a1 = cluster.add_node(num_cpus=2, num_tpus=4,
                          labels={"tpu_slice": "slice-a"})
    a2 = cluster.add_node(num_cpus=2, num_tpus=4,
                          labels={"tpu_slice": "slice-a"})
    b1 = cluster.add_node(num_cpus=2, num_tpus=2,
                          labels={"tpu_slice": "slice-b"})
    b2 = cluster.add_node(num_cpus=2, num_tpus=2,
                          labels={"tpu_slice": "slice-b"})
    slice_a = {a1.node_id, a2.node_id}
    slice_b = {b1.node_id, b2.node_id}
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)

        # two 3-TPU bundles: only slice A nodes can host them
        pg = placement_group([{"TPU": 3}, {"TPU": 3}],
                             strategy="SLICE_PACK")
        assert pg.ready(timeout=15)
        info = placement_group_table(pg)
        nodes = set(info["bundle_nodes"])
        assert nodes <= slice_a, (nodes, slice_a)

        # 4 more TPUs fit only slice B now (A has 2 left after pg)
        pg2 = placement_group([{"TPU": 1}, {"TPU": 1}, {"TPU": 2}],
                              strategy="SLICE_PACK")
        assert pg2.ready(timeout=15)
        nodes2 = set(placement_group_table(pg2)["bundle_nodes"])
        assert nodes2 <= slice_b, (nodes2, slice_b)

        # no single slice can host 5+5 TPU -> strictly infeasible
        pg3 = placement_group([{"TPU": 5}, {"TPU": 5}],
                              strategy="SLICE_PACK")
        assert not pg3.ready(timeout=3)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_gke_tpu_node_provider_command_shapes():
    """GKETPUNodeProvider drives gcloud/kubectl with the right shapes
    (runner injected — no cloud in CI; reference: cloud NodeProvider
    plugins)."""
    from ray_tpu.autoscaler import GKETPUNodeProvider

    calls = []
    pool_nodes = ["gke-tpu-a"]

    def fake_runner(argv):
        calls.append(argv)
        if argv[0] == "kubectl" and argv[1] == "get":
            return " ".join(pool_nodes)
        if argv[:3] == ["gcloud", "container", "clusters"]:
            pool_nodes.append(f"gke-tpu-{chr(ord('a') + len(pool_nodes))}")
            return ""
        if "describe" in argv:
            return ("https://www.googleapis.com/compute/v1/projects/proj/"
                    "zones/us-central2-b/instanceGroupManagers/mig-tpu-1")
        if argv[:2] == ["kubectl", "drain"]:
            raise RuntimeError("node unreachable")   # reap must survive
        return ""

    p = GKETPUNodeProvider(cluster="c1", node_pool="tpu-pool",
                          zone="us-central2-b", project="proj",
                          runner=fake_runner)
    assert p.non_terminated_nodes() == ["gke-tpu-a"]

    assert p.create_node({"TPU": 4}) == ""   # async provisioning
    resize = next(c for c in calls if "resize" in c)
    assert "--node-pool=tpu-pool" in resize
    assert "--num-nodes=2" in resize
    assert "--zone=us-central2-b" in resize
    assert "--project=proj" in resize
    assert p.non_terminated_nodes() == ["gke-tpu-a", "gke-tpu-b"]

    p.terminate_node("gke-tpu-b")
    drain = next(c for c in calls if c[:2] == ["kubectl", "drain"])
    assert "gke-tpu-b" in drain               # attempted (and failed) drain
    delete = next(c for c in calls if "delete-instances" in c)
    assert "mig-tpu-1" in delete
    assert "--instances=gke-tpu-b" in delete
