"""Placement groups, jobs, autoscaler, chaos (reference analogs:
test_placement_group*.py, job tests, autoscaler/v2/tests, chaos suite)."""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    ray_tpu.shutdown()
    c = Cluster()
    c.add_node(num_cpus=4)
    ray_tpu.init(address=c.gcs_address)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_placement_group_api_and_strategy(cluster):
    from ray_tpu.util.placement_group import (
        PlacementGroupSchedulingStrategy,
        placement_group,
        placement_group_table,
        remove_placement_group,
    )

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=10)
    table = placement_group_table(pg)
    assert table["state"] == "CREATED"

    @ray_tpu.remote(
        num_cpus=1,
        scheduling_strategy=PlacementGroupSchedulingStrategy(pg))
    def in_pg():
        import os
        return os.environ["RAY_TPU_NODE_ID"]

    node = ray_tpu.get(in_pg.remote())
    assert node in table["bundle_nodes"]
    remove_placement_group(pg)


def test_job_submission(cluster, tmp_path):
    from ray_tpu.job_submission import JobSubmissionClient

    script = tmp_path / "job.py"
    script.write_text("print('job ran ok')\n")
    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint=f"python {script}")
    status = client.wait_until_finish(job_id, timeout=60)
    assert status == "SUCCEEDED"
    assert "job ran ok" in client.get_job_logs(job_id)


def test_job_failure_status(cluster, tmp_path):
    from ray_tpu.job_submission import JobSubmissionClient

    script = tmp_path / "bad.py"
    script.write_text("raise SystemExit(3)\n")
    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=f"python {script}")
    assert client.wait_until_finish(job_id, timeout=60) == "FAILED"
    assert client.get_job_info(job_id)["returncode"] == 3


def test_autoscaler_scales_up_and_down():
    from ray_tpu.autoscaler import LocalNodeProvider, StandardAutoscaler

    ray_tpu.shutdown()
    c = Cluster(heartbeat_timeout_s=3.0)
    c.add_node(num_cpus=1)
    ray_tpu.init(address=c.gcs_address)
    provider = LocalNodeProvider(c)
    scaler = StandardAutoscaler(
        c.gcs_address, provider, node_resources={"CPU": 2},
        max_nodes=2, idle_timeout_s=1.5, poll_interval_s=0.2).start()
    try:
        @ray_tpu.remote(num_cpus=1)
        def busy():
            time.sleep(4)
            return 1

        refs = [busy.remote() for _ in range(4)]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if provider.non_terminated_nodes():
                break
            time.sleep(0.2)
        assert provider.non_terminated_nodes(), "no scale-up under load"
        ray_tpu.get(refs, timeout=60)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if not provider.non_terminated_nodes():
                break
            time.sleep(0.3)
        assert not provider.non_terminated_nodes(), "no idle scale-down"
    finally:
        scaler.stop()
        ray_tpu.shutdown()
        c.shutdown()


def test_chaos_node_killer():
    """NodeKiller chaos (reference: _private/test_utils.py:1401): kill a
    worker node mid-workload; retriable tasks must still complete."""
    ray_tpu.shutdown()
    c = Cluster(heartbeat_timeout_s=1.5)
    c.add_node(num_cpus=2)          # head (in-process, survives)
    victim = c.add_node(num_cpus=2, resources={"victim": 2}, external=True)
    c.wait_for_nodes(2)
    ray_tpu.init(address=c.gcs_address)
    try:
        @ray_tpu.remote(num_cpus=1, max_retries=2)
        def slow_task(i):
            time.sleep(0.5)
            return i

        refs = [slow_task.remote(i) for i in range(8)]
        time.sleep(0.3)
        c.remove_node(victim)  # SIGKILL mid-workload
        out = sorted(ray_tpu.get(refs, timeout=120))
        assert out == list(range(8))
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_autoscaler_provisions_for_infeasible_task():
    """A task no existing node can EVER satisfy parks as pending demand;
    the autoscaler sees the demand and provisions a node that fits it
    (reference: autoscaler v2's demand-driven path)."""
    import ray_tpu
    from ray_tpu.autoscaler import LocalNodeProvider, StandardAutoscaler
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=1)  # too small for the task below
    scaler = StandardAutoscaler(
        cluster.gcs_address, LocalNodeProvider(cluster),
        node_resources={"CPU": 4}, max_nodes=2,
        poll_interval_s=0.2, idle_timeout_s=60).start()
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)

        @ray_tpu.remote(num_cpus=3)
        def big():
            return "ran"

        # would be infeasible forever on the 1-CPU node; the autoscaler
        # must provision the 4-CPU node within the grace window
        assert ray_tpu.get(big.remote(), timeout=30) == "ran"
        assert len(scaler.provider.non_terminated_nodes()) >= 1
    finally:
        scaler.stop()
        ray_tpu.shutdown()
        cluster.shutdown()


def test_infeasible_task_errors_after_grace():
    """Without an autoscaler, cluster-wide infeasible tasks still error
    (after the grace window) rather than hanging forever."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=1, infeasible_timeout_s=1.0)
    try:
        ray_tpu.shutdown()
        ray_tpu.init(address=cluster.gcs_address)

        @ray_tpu.remote(num_cpus=64)
        def huge():
            return 1

        with pytest.raises(Exception, match="infeasible"):
            ray_tpu.get(huge.remote(), timeout=20)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
