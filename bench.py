"""Benchmark: train + serve + core-op throughput in one artifact.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.
The headline metric is Llama train tokens/sec/chip; the detail block
carries the serve (req/s + p50 TTFT) and core-op (tasks/s, actor calls/s,
put/get) numbers so every round's artifact records all three surfaces
(the BASELINE metric names train AND serve; the envelope names core ops).

The reference publishes no absolute numbers (BASELINE.md: envelope only),
so vs_baseline is measured against a hardware-grounded target: 40% MFU of
the chip's peak bf16 throughput.

Env knobs:
    BENCH_MODE=all|train|serve|core  (default all)
    BENCH_PRESET=small|base   (default base; small for CPU smoke runs)
    BENCH_STEPS=N             (timed steps, default 10)
    BENCH_REQUESTS=N          (serve mode: requests, default 16)

``core`` is the microbenchmark suite analog
(``python/ray/_private/ray_perf.py:93``): task/actor/put/get op
throughput on the cluster runtime. ``envelope`` is the bounded
scalability probe (``release/benchmarks/README.md`` analog): queued-task
drain rate, actor-creation rate through the fork-server worker pool,
and steady-state calls/s across the created actors — sized by
``RAY_TPU_BENCH_ENVELOPE_TASKS`` / ``RAY_TPU_BENCH_ENVELOPE_ACTORS``
(defaults 100k tasks / 500 actors).
"""

from __future__ import annotations

import json
import os
import sys
import time


def bench_train(preset: str | None = None) -> dict:
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama
    from ray_tpu.parallel.mesh import create_mesh
    from ray_tpu.train.trainer import JaxTrainer, TrainConfig

    preset = preset or os.environ.get("BENCH_PRESET", "base")
    steps = int(os.environ.get("BENCH_STEPS", "10"))
    remat = os.environ.get("BENCH_REMAT")          # override: none|dots|full
    batch_override = os.environ.get("BENCH_BATCH")
    fused = os.environ.get("BENCH_FUSED")          # "1" forces fused CE loss

    n_dev = len(jax.devices())
    platform = jax.devices()[0].platform

    if preset == "small":
        model_cfg = llama.llama_tiny()
        if remat:
            from dataclasses import replace as _replace

            model_cfg = _replace(model_cfg, remat=remat)
        batch, seq = 8, 128
    elif preset == "longctx":
        # long-context demonstration: the 0.5B model at 16k tokens per
        # sequence — Pallas flash attention (fwd+bwd, O(seq) memory) is
        # what makes the quadratic-attention memory wall a non-issue
        model_cfg = llama.LlamaConfig(
            vocab_size=32768, d_model=1536, n_layers=12, n_heads=12,
            n_kv_heads=4, head_dim=128, d_ff=6144,
            # "dots_attn": save matmul outputs AND the flash-attention
            # residuals so the backward never re-runs the O(s^2)
            # attention forward — at 16k this was the round-3 MFU gap
            # (28.6% under remat="full"; 35.0%/55.4% incl-attn with this)
            remat=remat or "dots_attn",
        )
        # one sequence per chip (the batch dim shards over fsdp when
        # multi-chip, so it must be divisible by the device count)
        batch, seq = max(n_dev, 1), 16384
    elif preset == "large":
        # ~1.0B params: the largest honest single-chip config — full
        # rematerialization trades recompute FLOPs for HBM so params +
        # Adam moments (~12 GB f32) and activations fit a 16 GB chip
        model_cfg = llama.LlamaConfig(
            vocab_size=32768, d_model=2048, n_layers=16, n_heads=16,
            n_kv_heads=4, head_dim=128, d_ff=7168,
            # dots_attn fits at b=4 and lifts MFU 0.541 -> 0.595 over
            # "full" (no matmul or flash-fwd recompute in the backward)
            remat=remat or "dots_attn",
        )
        batch, seq = 4, 2048
    else:
        # ~0.5B-param Llama-style model: fits one v5e chip with Adam state.
        model_cfg = llama.LlamaConfig(
            vocab_size=32768, d_model=1536, n_layers=12, n_heads=12,
            n_kv_heads=4, head_dim=128, d_ff=6144,
            # "dots_attn" (save matmul outputs + flash residuals)
            # measured 0.600 MFU vs 0.586 for "dots" at this size on
            # v5e; "none" OOMs with Adam state, batch 16 OOMs
            remat=remat or "dots_attn",
        )
        batch, seq = 8, 2048
    if batch_override:
        batch = int(batch_override)

    # Multi-chip: shard params/optimizer on an fsdp axis; single chip: dp.
    axis = "fsdp" if n_dev > 1 else "dp"
    trainer = JaxTrainer(
        model_cfg,
        TrainConfig(
            mesh_axes={axis: n_dev}, strategy="fsdp" if n_dev > 1 else "dp",
            warmup_steps=10, total_steps=1000,
            fused_loss=bool(fused and fused != "0"),
        ),
        mesh=create_mesh({axis: n_dev}),
    )

    key = jax.random.key(0)
    state = trainer.init_state(key)
    n_params = llama.num_params(state.params)

    def batch_fn(i):
        return jax.random.randint(
            jax.random.key(i), (batch, seq + 1), 0, model_cfg.vocab_size,
            dtype=jnp.int32,
        )

    # warmup (compile). NOTE: sync via host value fetch, not
    # block_until_ready — through remote-device tunnels the latter can
    # return before execution finishes, inflating throughput ~1000x.
    t0 = time.perf_counter()
    state, metrics = trainer.train_step(state, batch_fn(0))
    float(metrics["loss"])
    compile_s = time.perf_counter() - t0
    state, metrics = trainer.train_step(state, batch_fn(1))
    float(metrics["loss"])

    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = trainer.train_step(state, batch_fn(i + 2))
    float(metrics["loss"])
    elapsed = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / elapsed
    per_chip = tokens_per_sec / n_dev

    # The reference publishes no absolute numbers (BASELINE.json
    # published: {}), so vs_baseline is reported against a hardware-
    # grounded target: 40% MFU of the chip's peak bf16 throughput
    # (1.0 == hitting that target).
    peak_tflops = {
        "v4": 275.0, "v5e": 197.0, "v5litepod": 197.0, "v5p": 459.0,
        "v6e": 918.0,
    }
    kind = jax.devices()[0].device_kind.lower().replace(" ", "")
    peak = next((v for k, v in peak_tflops.items() if k in kind), 197.0)
    achieved_tflops = 6 * n_params * per_chip / 1e12
    # causal attention FLOPs per token (ignored by the 6N rule; the
    # dominant term at long context): 6 * L * seq * d_attn for fwd+bwd
    # at average causal span seq/2
    attn_flops = 6 * model_cfg.n_layers * seq * \
        (model_cfg.n_heads * model_cfg.head_dim)
    tflops_incl_attn = (6 * n_params + attn_flops) * per_chip / 1e12
    vs_baseline = round(achieved_tflops / (0.4 * peak), 4) \
        if platform == "tpu" else None

    result = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": vs_baseline,
        "detail": {
            "platform": platform,
            "n_devices": n_dev,
            "params": n_params,
            "batch": batch,
            "seq": seq,
            "steps": steps,
            "step_time_s": round(elapsed / steps, 4),
            "compile_s": round(compile_s, 1),
            "final_loss": round(float(metrics["loss"]), 4),
            "model_flops_per_token": 6 * n_params,
            "tflops_per_sec_per_chip": round(
                6 * n_params * per_chip / 1e12, 2
            ),
            "mfu": (round(achieved_tflops / peak, 4)
                    if platform == "tpu" else None),
            "attn_flops_per_token": attn_flops,
            "mfu_incl_attn": (round(tflops_incl_attn / peak, 4)
                              if platform == "tpu" else None),
        },
    }
    return result


def bench_train_telemetry() -> dict:
    """Train leg WITH the telemetry plane on: per-step wall-clock
    decomposition (data_wait / compute / collective_sync / checkpoint,
    compile split out on the first step), per-rank MFU from the declared
    FLOPs-per-step, and goodput buckets via util.state.train_goodput.

    Two invariants are asserted here (and fenced in ci/perf_gate.py):
    the decomposition sums to the observed step wall on EVERY step, and
    the per-step telemetry cost — measured with the amortized-delta
    method (min-of-k probe of the stamping path, hot minus cold, like
    the metrics/tracing overhead gates) — stays under 1% of the
    measured step wall."""
    import glob as _glob
    import tempfile

    import jax

    import ray_tpu
    from ray_tpu import train as rtrain
    from ray_tpu.train import session as _session
    from ray_tpu.util import state as _state

    steps = int(os.environ.get("BENCH_STEPS", "8"))
    world_size = int(os.environ.get("BENCH_TRAIN_WORKERS", "2"))
    platform = jax.devices()[0].platform
    # MFU needs a peak FLOP/s: auto-detected on TPU, DECLARED on CPU (a
    # nominal 1 TFLOP/s so the mechanism is exercised; the artifact
    # records the declared value so the number cannot masquerade as a
    # real utilization measurement)
    from ray_tpu.train.telemetry import detect_peak_flops

    peak = detect_peak_flops() or 1e12
    storage = tempfile.mkdtemp(prefix="bench_train_telemetry_")
    run_name = "bench-telemetry"

    def loop(config):
        import json as _json

        import jax
        import jax.numpy as jnp

        from ray_tpu.models import llama
        from ray_tpu.parallel.mesh import create_mesh
        from ray_tpu.train.trainer import JaxTrainer, TrainConfig

        model_cfg = llama.llama_tiny()
        trainer = JaxTrainer(
            model_cfg, TrainConfig(mesh_axes={"dp": 1}, strategy="dp",
                                   warmup_steps=2, total_steps=1000),
            mesh=create_mesh({"dp": 1}))
        state = trainer.init_state(jax.random.key(0))
        batch, seq = 4, 128
        n_params = llama.num_params(state.params)
        _session.set_flops_per_step(6.0 * n_params * batch * seq,
                                    peak_flops=config["peak_flops"])

        def batch_fn(i):
            return jax.random.randint(
                jax.random.key(i), (batch, seq + 1), 0,
                model_cfg.vocab_size, dtype=jnp.int32)

        ctx = rtrain.get_context()
        for i in range(config["steps"]):
            with _session.timeit("data_wait"):
                tokens = batch_fn(i)
            state, metrics = trainer.train_step(state, tokens)
            loss = float(metrics["loss"])   # sync -> residual = compute
            if i == config["steps"] // 2:
                with _session.timeit("checkpoint"):
                    jax.block_until_ready(state.params)
                    with open(os.path.join(
                            ctx.trial_dir,
                            f"ckpt_rank{ctx.rank}.bin"), "wb") as f:
                        f.write(b"\0" * 4096)
                        f.flush()
                        os.fsync(f.fileno())
            _session.report({"loss": loss})
        tel = _session.telemetry()
        with open(os.path.join(ctx.trial_dir,
                               f"telemetry_rank{ctx.rank}.json"),
                  "w") as f:
            _json.dump({"rank": ctx.rank, "history": tel.history,
                        "goodput": tel.goodput}, f)

    ray_tpu.init(num_cpus=8, num_tpus=0)
    trainer = rtrain.DataParallelTrainer(
        loop,
        train_loop_config={"steps": steps, "peak_flops": peak},
        scaling_config=rtrain.ScalingConfig(num_workers=world_size),
        run_config=rtrain.RunConfig(name=run_name, storage_path=storage))
    t0 = time.perf_counter()
    result = trainer.fit()
    fit_s = time.perf_counter() - t0
    if result.error:
        raise RuntimeError(f"telemetry train leg failed: {result.error}")

    # per-rank stamps written by the ranks themselves; the sum check is
    # asserted on EVERY step of EVERY rank
    ranks = []
    for path in sorted(_glob.glob(
            os.path.join(storage, "**", "telemetry_rank*.json"),
            recursive=True)):
        with open(path) as f:
            ranks.append(json.load(f))
    assert len(ranks) == world_size, f"expected {world_size} rank files"
    max_residual = 0.0
    stage_totals: dict = {}
    mfus = []
    wall_total = 0.0
    steady_total = steady_n = 0
    n_steps = 0
    for r in ranks:
        for stamp in r["history"]:
            diff = abs(sum(stamp["stages"].values()) - stamp["wall_s"])
            assert diff < 1e-6, \
                f"decomposition != wall on step {stamp['step']}: {diff}"
            max_residual = max(max_residual, diff)
            for stage, dt in stamp["stages"].items():
                stage_totals[stage] = stage_totals.get(stage, 0.0) + dt
            wall_total += stamp["wall_s"]
            n_steps += 1
            if "compile" not in stamp["stages"]:
                steady_total += stamp["wall_s"]
                steady_n += 1
            if stamp["mfu"] is not None:
                mfus.append(stamp["mfu"])
    # overhead is fenced against the STEADY-state step wall (first
    # steps carry compile — dividing by them would flatter the ratio)
    step_wall_s = (steady_total / steady_n if steady_n
                   else wall_total / max(n_steps, 1))
    goodput = _state.train_goodput(run_name)
    stragglers = _state.train_stragglers(run_name)

    # amortized-delta overhead probe: the full stamping path (bucket
    # close + residual split + metric emission + annex/watchdog) hot,
    # minus the disabled-path guard cold, over min-of-k large loops —
    # divided by the MEASURED per-step wall above. Never a diff of two
    # noisy end-to-end rates.
    from ray_tpu.train.telemetry import StepTelemetry

    probe_tel = StepTelemetry("bench-probe", 0, flops_per_step=1e9,
                              peak_flops=peak, history_cap=8)

    def _probe_cost(fn, iters: int = 5000, k: int = 5) -> float:
        best = float("inf")
        for _ in range(k):
            t0 = time.perf_counter()
            for _ in range(iters):
                fn()
            best = min(best, (time.perf_counter() - t0) / iters)
        return best

    hot = _probe_cost(lambda: probe_tel.on_report({}))
    noop = _session.telemetry   # the off-path: one accessor + None test
    cold = _probe_cost(lambda: noop() is None)
    overhead_ratio = max(hot - cold, 0.0) / step_wall_s
    assert overhead_ratio < 0.01, \
        f"telemetry overhead {overhead_ratio:.4f} >= 1% of step wall"

    ray_tpu.shutdown()
    gp_round = {k: round(v, 4) for k, v in goodput["buckets"].items()}
    return {
        "metric": "train_telemetry_goodput_fraction",
        "value": round(goodput["goodput_fraction"] or 0.0, 4),
        "unit": "fraction",
        "vs_baseline": None,
        "detail": {
            "platform": platform,
            "world_size": world_size,
            "steps": steps,
            "fit_s": round(fit_s, 2),
            "step_time_s": round(step_wall_s, 4),
            "decomposition_s": {k: round(v, 4)
                                for k, v in sorted(stage_totals.items())},
            "decomposition_max_residual_s": max_residual,
            "steps_sample": ranks[0]["history"][:3],
            "mfu": round(sum(mfus) / len(mfus), 4) if mfus else None,
            "peak_flops_declared": peak,
            "peak_flops_is_nominal": platform != "tpu",
            "goodput": gp_round,
            "goodput_fraction": round(
                goodput["goodput_fraction"] or 0.0, 4),
            "stragglers": stragglers["stragglers"],
            "max_step_skew": stragglers["skew_steps"],
            "telemetry_overhead": {
                "probe_hot_us": round(hot * 1e6, 2),
                "probe_cold_us": round(cold * 1e6, 3),
                "per_step_ms": round(step_wall_s * 1e3, 2),
                "ratio": round(overhead_ratio, 5),
            },
        },
    }


def bench_serve() -> dict:
    """Continuous-batching decode throughput + TTFT on the paged-KV LLM
    engine: a burst phase (comparable with earlier rounds) and a
    SUSTAINED closed-loop phase (concurrency 16, a new request the
    moment one finishes)."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import llama
    from ray_tpu.serve.paged_llm import PagedLLMEngine

    preset = os.environ.get("BENCH_PRESET", "base")
    n_requests = int(os.environ.get("BENCH_REQUESTS", "16"))
    platform = jax.devices()[0].platform

    if preset == "small":
        model_cfg = llama.llama_tiny()
        max_batch, max_len, prompt_len, new_tokens = 4, 256, 32, 32
        concurrency, sustained_total = 4, 8
    else:
        model_cfg = llama.LlamaConfig(
            vocab_size=32768, d_model=1536, n_layers=12, n_heads=12,
            n_kv_heads=4, head_dim=128, d_ff=6144, remat="none",
        )
        # 4 spare slots over the offered concurrency: admission never
        # waits for a retirement (the free-slot drain path runs)
        max_batch, max_len, prompt_len, new_tokens = 20, 2048, 128, 128
        concurrency, sustained_total = 16, 64

    # the fixed per-dispatch sync cost through the device transport —
    # the TTFT floor no engine scheduling can beat (recorded so the
    # numbers are interpretable on tunneled chips)
    _f = jax.jit(lambda x: x + 1)
    _x = jnp.zeros((4,))
    np.asarray(_f(_x))
    _t = time.perf_counter()
    for _ in range(5):
        np.asarray(_f(_x))
    sync_rtt_ms = (time.perf_counter() - _t) / 5 * 1e3

    params = llama.init_params(model_cfg, jax.random.key(0))
    n_params = llama.num_params(params)
    # decode_chunk 16: the measured latency/throughput knee on a
    # ~95ms-RTT tunneled chip (async first-token pipeline). 32 gives
    # ~+14% sustained tokens/s at ~+35ms p50 TTFT; 8 is RTT-bound.
    # Sustained p50 TTFT floors at ~full-throughput pipeline depth
    # (~100ms in-flight compute) + prefill + one-way ship time ≈
    # 185ms here — a local-PCIe chip would sit near ~90ms.
    eng = PagedLLMEngine(params=params, cfg=model_cfg,
                         kv_dtype=os.environ.get("BENCH_KV_DTYPE", "bf16"),
                         max_batch=max_batch, max_len=max_len,
                         decode_chunk=int(os.environ.get(
                             "BENCH_DECODE_CHUNK",
                             "16" if preset != "small" else "8")))
    # deterministic warmup BEFORE the loop starts: every prefill group
    # size + decode programs at every pages bucket compile now, so no
    # JIT lands inside a measured window
    eng.warmup(prompt_len)
    eng.start()
    rng = np.random.default_rng(0)
    w = eng.submit(rng.integers(1, model_cfg.vocab_size, prompt_len),
                   max_new_tokens=4)
    list(w.tokens())

    # -- burst phase (round-comparable) --
    t0 = time.perf_counter()
    reqs = [
        eng.submit(rng.integers(1, model_cfg.vocab_size, prompt_len),
                   max_new_tokens=new_tokens)
        for _ in range(n_requests)
    ]
    done = [list(r.tokens()) for r in reqs]
    elapsed = time.perf_counter() - t0

    generated = sum(len(d) for d in done)
    tokens_per_sec = generated / elapsed
    ttfts = [r.ttft for r in reqs if r.ttft is not None]

    # -- sustained phase: closed loop at fixed concurrency --
    done_counts: list = []
    sus_ttfts: list = []
    lock = threading.Lock()
    remaining = [sustained_total - concurrency]
    # monotonic: Request.submit_t uses time.monotonic — mixing clocks
    # breaks the steady-state filter on platforms where their epochs
    # differ
    t0 = time.monotonic()

    def consume(req):
        toks = list(req.tokens())
        with lock:
            done_counts.append(len(toks))
            if req.ttft is not None:
                # breakdown: the MEASURED per-request TTFT decomposition
                # (queue wait / prefill / pipeline stall / first-token
                # ship) stamped by the engine; stages sum to the TTFT
                sus_ttfts.append((req.submit_t - t0, req.ttft,
                                  req.breakdown))
            go = remaining[0] > 0
            if go:
                remaining[0] -= 1
        if go:
            nxt = eng.submit(
                rng.integers(1, model_cfg.vocab_size, prompt_len),
                max_new_tokens=new_tokens)
            threading.Thread(target=consume, args=(nxt,),
                             daemon=True).start()

    for _ in range(concurrency):
        r = eng.submit(rng.integers(1, model_cfg.vocab_size, prompt_len),
                       max_new_tokens=new_tokens)
        threading.Thread(target=consume, args=(r,), daemon=True).start()
    while True:
        with lock:
            if len(done_counts) >= sustained_total:
                break
        time.sleep(0.05)
    sus_elapsed = time.monotonic() - t0
    sus_tps = sum(done_counts) / sus_elapsed
    steady_rows = [r for r in sus_ttfts if r[0] > 0.5] or sus_ttfts
    steady = [t for _, t, _ in steady_rows]
    # measured TTFT decomposition over the steady requests: per-stage
    # means, plus the sum-vs-observed check that proves the stages
    # account for the whole latency (not a model — stamped timestamps)
    steady_bds = [bd for _, _, bd in steady_rows if bd is not None]
    ttft_breakdown = None
    if steady_bds:
        ttft_breakdown = {
            k: round(float(np.mean([bd[k] for bd in steady_bds])), 4)
            for k in ("queue_wait_s", "prefill_s", "pipeline_stall_s",
                      "ship_s")}
        ttft_breakdown["sum_s"] = round(
            sum(ttft_breakdown.values()), 4)
        ttft_breakdown["mean_observed_ttft_s"] = round(
            float(np.mean([t for _, t, bd in steady_rows
                           if bd is not None])), 4)
        # queue wait as a share of the whole TTFT: the continuous-
        # admission acceptance number (ci/perf_gate.py fences it)
        if ttft_breakdown["sum_s"] > 0:
            ttft_breakdown["queue_wait_share"] = round(
                ttft_breakdown["queue_wait_s"] / ttft_breakdown["sum_s"],
                4)

    # -- prefix-cache phase: shared system prompt + unique tails --
    # (the chat/agent-serving shape; random-prompt phases above never
    # hit the cache). One prime request registers the shared pages;
    # a warm burst compiles the suffix-bucket programs; the measured
    # burst then shows cached-prefix TTFT.
    sys_len, tail_len, pre_n = 4 * prompt_len, 32, 8
    sys_prompt = rng.integers(1, model_cfg.vocab_size, sys_len)

    def _prefix_burst(n, new_tokens):
        reqs = [eng.submit(
            np.concatenate([sys_prompt,
                            rng.integers(1, model_cfg.vocab_size,
                                         tail_len)]),
            max_new_tokens=new_tokens) for _ in range(n)]
        for r in reqs:
            list(r.tokens())
        return reqs

    _prefix_burst(1, 4)          # prime: registers the prefix pages
    _prefix_burst(pre_n, 4)      # warm: compiles suffix-bucket programs
    hit0 = eng.stats()["prefix_cache"]["hit_pages"]
    pre_reqs = _prefix_burst(pre_n, 16)
    pre_ttfts = [r.ttft for r in pre_reqs if r.ttft is not None]
    pages = eng.stats()
    eng.stop()

    # end-to-end engine throughput: the window covers prefill + queueing +
    # decode for the whole request set (what a serving client experiences)
    result = {
        "metric": "llama_serve_engine_tokens_per_sec",
        # headline = SUSTAINED throughput (the serving-steady-state
        # number; the burst figure is round-comparable detail)
        "value": round(sus_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": None,  # reference publishes no serving numbers
        "detail": {
            "platform": platform,
            "params": n_params,
            "kv_layout": "paged",
            "requests": n_requests,
            "prompt_len": prompt_len,
            "new_tokens": new_tokens,
            "max_batch": max_batch,
            "burst_tokens_per_sec": round(tokens_per_sec, 1),
            "mean_ttft_s": round(float(np.mean(ttfts)), 4) if ttfts else None,
            "p50_ttft_s": round(float(np.median(ttfts)), 4) if ttfts else None,
            "requests_per_sec": round(n_requests / elapsed, 2),
            "sustained": {
                "concurrency": concurrency,
                "requests": sustained_total,
                "tokens_per_sec": round(sus_tps, 1),
                "p50_ttft_s": round(float(np.median(steady)), 4),
                "p95_ttft_s": round(float(np.percentile(steady, 95)), 4),
                "ttft_breakdown": ttft_breakdown,
            },
            # fixed per-dispatch sync latency of the device transport —
            # the floor under every TTFT above (tunneled chips pay ~2 of
            # these per prefill; a local PCIe chip pays ~1ms)
            "dispatch_sync_rtt_ms": round(sync_rtt_ms, 1),
            "prefix_cache": {
                "system_prompt_len": sys_len,
                "tail_len": tail_len,
                "requests": pre_n,
                "p50_ttft_s": round(float(np.median(pre_ttfts)), 4)
                if pre_ttfts else None,
                "hit_pages": (pages.get("prefix_cache") or {}).get(
                    "hit_pages", 0) - hit0,
            },
            "kv_pages": {
                "total": pages.get("kv_pages_total"),
                "bytes": pages.get("kv_pages_bytes"),
                "dense_equiv_bytes": pages.get("kv_dense_equiv_bytes"),
            },
        },
    }
    return result


def bench_serve_scaleout() -> dict:
    """Multi-replica serve leg: cluster tokens/s and per-replica TTFT
    decomposition at 1/2/4 replicas under REPEAT-PREFIX traffic, routed
    through the prefix-affinity DeploymentHandle (serve/prefix_router.py
    digests pushed over the metrics plane from real worker processes).

    The scaling mechanism on a 1-cpu host is redundant-prefill
    ELIMINATION, not extra compute: 8 session prefixes of 24 pages each
    (192 pages working set) round-robin against a 128-page per-replica
    pool, so one replica LRU-thrashes and re-prefills ~768 tokens per
    request, while 2+ replicas with affinity routing each keep their
    session subset cached and prefill only the 32-token tail. Efficiency
    at 2x = cluster tokens/s ratio vs the single-replica leg."""
    import threading

    import numpy as np

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.utils.config import reset_config

    # digests must reach the router fast enough to settle affinity
    # within a couple of rounds (default 2s push would dominate a leg)
    os.environ.setdefault("RAY_TPU_METRICS_PUSH_INTERVAL_S", "0.25")
    reset_config()

    PAGE, PREFIX, TAIL, NEW = 32, 768, 32, 8
    MAX_LEN, MAX_BATCH, POOL = 1024, 4, 128
    SESSIONS, CONC = 8, 4
    SETTLE_ROUNDS = int(os.environ.get("BENCH_SCALEOUT_SETTLE", "3"))
    MEASURE_ROUNDS = int(os.environ.get("BENCH_SCALEOUT_ROUNDS", "4"))
    REPLICA_LEGS = (1, 2, 4)

    from ray_tpu.models import llama
    vocab = llama.llama_tiny().vocab_size

    c = Cluster()
    c.add_node(num_cpus=max(REPLICA_LEGS) + 1)
    ray_tpu.init(address=c.gcs_address)

    rng = np.random.default_rng(0)
    session_prefixes = [rng.integers(1, vocab, PREFIX)
                        for _ in range(SESSIONS)]

    @serve.deployment(max_concurrent_queries=8)
    class ScaleLLM:
        def __init__(self):
            import jax
            from ray_tpu.models import llama as _llama
            from ray_tpu.serve.paged_llm import PagedLLMEngine

            cfg = _llama.llama_tiny()
            params = _llama.init_params(cfg, jax.random.key(0))
            self.eng = PagedLLMEngine(
                params=params, cfg=cfg, max_batch=MAX_BATCH,
                max_len=MAX_LEN, page_size=PAGE, num_pages=POOL,
                decode_chunk=8)
            # cold-miss prefill + decode buckets, then the suffix
            # programs prefix-cache hits dispatch — no XLA compile may
            # land inside a measured round
            self.eng.warmup(PREFIX + TAIL)
            self.eng.warmup_prefix(PREFIX, TAIL)
            self.eng.start()

        def __call__(self, tokens, max_new):
            import numpy as _np

            w = self.eng.submit(_np.asarray(tokens, _np.int32),
                                max_new_tokens=max_new)
            toks = list(w.tokens())
            st = self.eng.stats()     # also force-publishes the digest
            pc = st["prefix_cache"]
            return {"n": len(toks), "ttft": w.ttft,
                    "breakdown": w.breakdown,
                    "tag": self.eng.replica_tag,
                    "hit_pages": pc["hit_pages"],
                    "miss_pages": pc["miss_pages"]}

    def _run_leg(n_replicas: int) -> dict:
        name = f"scale{n_replicas}"
        handle = serve.run(
            ScaleLLM.options(name=name, num_replicas=n_replicas).bind(),
            name=name)

        def _call(req_tokens):
            toks = [int(t) for t in req_tokens]
            return ray_tpu.get(
                handle.remote(toks, NEW, _prefix_tokens=toks),
                timeout=600)

        def _run_rounds(rounds: int):
            seq = [np.concatenate([session_prefixes[s],
                                   rng.integers(1, vocab, TAIL)])
                   for _ in range(rounds) for s in range(SESSIONS)]
            out: list = []
            lock = threading.Lock()
            idx = [0]

            def worker():
                while True:
                    with lock:
                        i = idx[0]
                        if i >= len(seq):
                            return
                        idx[0] += 1
                    r = _call(seq[i])
                    with lock:
                        out.append(r)

            ths = [threading.Thread(target=worker) for _ in range(CONC)]
            t0 = time.perf_counter()
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            return out, time.perf_counter() - t0

        # settle: absorbs replica construction, prime misses, and the
        # digest-driven session->replica migration; hit/miss counters at
        # the end of settle are the measured rounds' baselines
        settle, _ = _run_rounds(SETTLE_ROUNDS)
        base: dict = {}
        for r in settle:
            b = base.setdefault(r["tag"], {"hit": 0, "miss": 0})
            b["hit"] = max(b["hit"], r["hit_pages"])
            b["miss"] = max(b["miss"], r["miss_pages"])

        measured, elapsed = _run_rounds(MEASURE_ROUNDS)
        tokens = sum(r["n"] for r in measured)
        ttfts = [r["ttft"] for r in measured if r["ttft"] is not None]
        per_tag: dict = {}
        for r in measured:
            d = per_tag.setdefault(r["tag"], {
                "requests": 0, "ttfts": [], "bds": [],
                "hit": 0, "miss": 0})
            d["requests"] += 1
            if r["ttft"] is not None:
                d["ttfts"].append(r["ttft"])
            if r["breakdown"]:
                d["bds"].append(r["breakdown"])
            d["hit"] = max(d["hit"], r["hit_pages"])
            d["miss"] = max(d["miss"], r["miss_pages"])
        per_replica = {}
        for tag, d in sorted(per_tag.items()):
            b = base.get(tag, {"hit": 0, "miss": 0})
            bd = None
            if d["bds"]:
                bd = {k: round(float(np.mean([x[k] for x in d["bds"]])), 4)
                      for k in ("queue_wait_s", "prefill_s",
                                "pipeline_stall_s", "ship_s")}
            per_replica[tag] = {
                "requests": d["requests"],
                "p50_ttft_s": (round(float(np.median(d["ttfts"])), 4)
                               if d["ttfts"] else None),
                "ttft_breakdown": bd,
                "prefix_hit_pages": d["hit"] - b["hit"],
                "prefix_miss_pages": d["miss"] - b["miss"],
            }
        leg = {
            "replicas": n_replicas,
            "requests": len(measured),
            "elapsed_s": round(elapsed, 3),
            "cluster_tokens_per_sec": round(tokens / elapsed, 1),
            "p50_ttft_s": (round(float(np.median(ttfts)), 4)
                           if ttfts else None),
            "per_replica": per_replica,
        }
        serve.delete(name)
        return leg

    legs = {str(n): _run_leg(n) for n in REPLICA_LEGS}
    tps1 = legs["1"]["cluster_tokens_per_sec"]
    eff2 = round(legs["2"]["cluster_tokens_per_sec"] / tps1, 3)
    eff4 = round(legs["4"]["cluster_tokens_per_sec"] / tps1, 3)
    serve.shutdown()
    ray_tpu.shutdown()
    c.shutdown()
    return {
        "metric": "serve_scaleout_efficiency_2x",
        "value": eff2,
        "unit": "x",
        "vs_baseline": None,  # reference publishes no serving numbers
        "detail": {
            "traffic": {
                "sessions": SESSIONS, "prefix_len": PREFIX,
                "tail_len": TAIL, "new_tokens": NEW,
                "page_size": PAGE, "pool_pages": POOL,
                "working_set_pages": SESSIONS * (PREFIX // PAGE),
                "concurrency": CONC,
                "measured_requests": MEASURE_ROUNDS * SESSIONS,
            },
            "prefix_affinity_routing": True,
            "efficiency_2x": eff2,
            "efficiency_4x": eff4,
            "legs": legs,
        },
    }


def bench_data() -> dict:
    """Data-plane leg: map_batches throughput (GiB/s) and PUSH-BASED
    shuffle rows/s on an external-process cluster, every round's rate
    recorded so spread is visible in the artifact. Per-stage bytes are
    priced through the memory plane's accounting — each stage's output
    block oids valued via the GCS memory_table (the same size table
    ``memory_summary`` reconciles against) — not driver-side guesses."""
    import numpy as np

    import ray_tpu
    from ray_tpu import data as rdata
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.data.context import DataContext
    from ray_tpu.runtime import core as _core

    rows = int(os.environ.get("BENCH_DATA_ROWS", "400000"))
    blocks = int(os.environ.get("BENCH_DATA_BLOCKS", "16"))
    rounds = int(os.environ.get("BENCH_DATA_ROUNDS", "3"))
    c = Cluster(external_gcs=True)
    c.add_node(num_cpus=4, external=True)
    ray_tpu.init(address=c.gcs_address)
    rt = _core.get_runtime()

    def priced_bytes(bundles) -> int:
        """Value a stage's output blocks through the GCS size table,
        falling back to bundle metadata for blocks the object directory
        never saw (driver-local memstore blocks)."""
        oids = [r.id.hex() for b in bundles for r in b.refs]
        table = rt._gcs.call("memory_table", oids=oids)["objects"]
        total = 0
        for b in bundles:
            sz = sum(table.get(r.id.hex(), {}).get("size", 0)
                     for r in b.refs)
            total += sz if sz else b.size_bytes
        return total

    detail: dict = {"rows": rows, "blocks": blocks, "rounds": rounds}

    # -- map_batches stage --
    map_gibs: list = []
    map_bytes = 0
    for _ in range(rounds):
        ds = rdata.range(rows, num_blocks=blocks).map_batches(
            lambda b: {"id": b["id"],
                       "val": np.sqrt(b["id"].astype(np.float64))})
        t0 = time.perf_counter()
        bundles = list(ds.iter_bundles())
        wall = time.perf_counter() - t0
        got = sum(b.num_rows for b in bundles)
        assert got == rows, f"map leg lost rows: {got} != {rows}"
        map_bytes = priced_bytes(bundles)
        map_gibs.append(round(map_bytes / wall / (1 << 30), 4))
    detail["map_batches_gib_per_sec"] = max(map_gibs)
    detail["map_batches_rounds_gib_per_sec"] = map_gibs
    detail["map_output_bytes"] = map_bytes

    # -- push-based shuffle stage --
    DataContext.get_current().use_push_based_shuffle = True
    try:
        shuf_rates: list = []
        shuf_bytes = 0
        shuf_wall = 0.0
        for i in range(rounds):
            ds = rdata.range(rows, num_blocks=blocks).random_shuffle(
                seed=i)
            t0 = time.perf_counter()
            bundles = list(ds.iter_bundles())
            shuf_wall = time.perf_counter() - t0
            got = sum(b.num_rows for b in bundles)
            assert got == rows, f"shuffle lost rows: {got} != {rows}"
            shuf_bytes = priced_bytes(bundles)
            shuf_rates.append(round(rows / shuf_wall, 1))
    finally:
        DataContext.get_current().use_push_based_shuffle = False
    detail["push_shuffle_rows_per_sec"] = max(shuf_rates)
    detail["push_shuffle_rounds_rows_per_sec"] = shuf_rates
    detail["push_shuffle_spread"] = round(
        (max(shuf_rates) - min(shuf_rates)) / max(shuf_rates), 4)
    detail["per_stage_bytes_per_sec"] = {
        "map_batches": round(max(map_gibs) * (1 << 30), 1),
        "push_shuffle": round(shuf_bytes / shuf_wall, 1),
    }
    detail["push_shuffle_output_bytes"] = shuf_bytes

    ray_tpu.shutdown()
    c.shutdown()
    return {
        "metric": "data_push_shuffle_rows_per_sec",
        "value": detail["push_shuffle_rows_per_sec"],
        "unit": "rows/s",
        "vs_baseline": None,  # reference publishes no data-plane rates
        "detail": detail,
    }


def bench_core() -> dict:
    """Core-op microbenchmarks (reference: ``ray_perf.py`` — tasks/sec,
    actor calls/sec, put/get throughput on a real multi-process cluster)."""
    import numpy as np

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    # own knob: BENCH_STEPS tunes the train loop; reusing it here would
    # shrink the op count (noisy rates) whenever train steps are reduced
    n = int(os.environ.get("BENCH_CORE_OPS", "2000"))
    # external GCS + raylet: both run as their own OS processes (exactly
    # like the reference's gcs_server + raylet) — their RPC handling
    # must not share the driver's GIL, which is the hot resource in a
    # submit microbenchmark
    c = Cluster(external_gcs=True)
    c.add_node(num_cpus=4, external=True)
    ray_tpu.init(address=c.gcs_address)
    results = {}

    rounds_detail: dict[str, list] = {}

    def best_of(fn, rounds: int = 5, name: str | None = None) -> float:
        """Steady-state rate: best of N rounds (ray_perf-style repeat).
        Five rounds, not two: this box has ONE cpu, and host scheduling
        noise swings a single round of the pure-Python RPC ops by ±35%
        between identical runs — the max over five draws is what a
        quiet machine reproducibly measures. EVERY round's rate is
        recorded in the artifact (``rounds`` detail) so noise vs real
        regression is visible in the artifact itself."""
        best = 0.0
        seen = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            fn()
            rate = n / (time.perf_counter() - t0)
            seen.append(round(rate, 1))
            best = max(best, rate)
        if name:
            rounds_detail[name] = seen
        return round(best, 1)

    @ray_tpu.remote
    def nop():
        return None

    # warm the worker pool
    ray_tpu.get([nop.remote() for _ in range(8)])
    results["tasks_per_sec"] = best_of(
        lambda: ray_tpu.get([nop.remote() for _ in range(n)]),
        name="tasks_per_sec")

    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()
    ray_tpu.get(a.m.remote())
    results["actor_calls_per_sec"] = best_of(
        lambda: ray_tpu.get([a.m.remote() for _ in range(n)]),
        name="actor_calls_per_sec")

    # tracing hot-path fence input (round 9): the amortized-delta
    # methodology from round 4's probe gates — time the per-call tracing
    # probe (wire_context with tracing ON minus OFF, min-of-k over a
    # large loop) and divide by the measured per-op cost, instead of
    # diffing two noisy end-to-end rates. ci/perf_gate.py holds the
    # ratio under an ABSOLUTE 3% ceiling (a cross-round relative fence
    # is meaningless for a ratio that sits near zero). A traced steady
    # actor round rides along as the loose end-to-end tripwire.
    from ray_tpu.util import tracing as _tracing

    def _probe_cost(iters: int = 200_000, k: int = 5) -> float:
        best = float("inf")
        for _ in range(k):
            t0 = time.perf_counter()
            for _ in range(iters):
                _tracing.wire_context()
            best = min(best, (time.perf_counter() - t0) / iters)
        return best

    cold = _probe_cost()                   # tracing disabled
    _tracing.enable_tracing()
    try:
        with _tracing.span("bench-overhead"):
            hot = _probe_cost()            # enabled + ambient context
            t0 = time.perf_counter()
            ray_tpu.get([a.m.remote() for _ in range(n)])
            traced_rate = round(n / (time.perf_counter() - t0), 1)
    finally:
        _tracing.disable_tracing()
    per_op_s = 1.0 / results["actor_calls_per_sec"]
    results["tracing_overhead"] = {
        "probe_delta_ns": round((hot - cold) * 1e9, 1),
        "per_op_us": round(per_op_s * 1e6, 1),
        "ratio": round(max(hot - cold, 0.0) / per_op_s, 5),
        "traced_actor_calls_per_sec": traced_rate,
    }

    # log-plane capture fence: amortized per-LINE delta — the stamped
    # tee emit (time.time + contextvar reads + %-format + os.write)
    # minus a plain unstamped os.write of the same text — over the
    # per-op cost. Ship/store/echo all run off-process (raylet monitor,
    # GCS), so the emit IS the whole hot-path tax a printing task pays;
    # ci/perf_gate.py holds the ratio under an absolute 3% ceiling.
    import shutil as _sh
    import tempfile as _tf

    from ray_tpu.runtime import log_plane as _log_plane

    _ldir = _tf.mkdtemp(prefix="raytpu-bench-logs-")
    cap = _log_plane.LogCapture("bench", _ldir, max_bytes=256 << 20)
    line = "bench log line with a bit of payload 0123456789"
    raw_fd = os.open(os.path.join(_ldir, "raw.txt"),
                     os.O_WRONLY | os.O_CREAT | os.O_APPEND)
    raw_data = (line + "\n").encode()

    def _line_cost(fn, iters: int = 100_000, k: int = 5) -> float:
        best = float("inf")
        for _ in range(k):
            t0 = time.perf_counter()
            for _ in range(iters):
                fn()
            best = min(best, (time.perf_counter() - t0) / iters)
        return best

    hot_line = _line_cost(lambda: cap.emit("o", line))
    cold_line = _line_cost(lambda: os.write(raw_fd, raw_data))
    os.close(raw_fd)
    cap.close()
    _sh.rmtree(_ldir, ignore_errors=True)
    results["log_overhead"] = {
        "emit_ns": round(hot_line * 1e9, 1),
        "plain_write_ns": round(cold_line * 1e9, 1),
        "delta_ns": round((hot_line - cold_line) * 1e9, 1),
        "per_op_us": round(per_op_s * 1e6, 1),
        "ratio": round(max(hot_line - cold_line, 0.0) / per_op_s, 5),
    }

    small = b"x" * 1024
    put_refs: list = []

    def do_puts():
        put_refs.clear()
        put_refs.extend(ray_tpu.put(small) for _ in range(n))

    results["puts_1kb_per_sec"] = best_of(do_puts, name="puts_1kb_per_sec")
    results["gets_1kb_per_sec"] = best_of(lambda: ray_tpu.get(put_refs),
                                          name="gets_1kb_per_sec")

    # memory-plane accounting fence: the per-put ownership tax —
    # creation-callsite capture + owned-table insert (the whole
    # addition driver.put pays for runtime/refcount.py accounting) —
    # amortized min-of-k, minus the disabled-path guard, divided by the
    # measured per-put cost above. ci/perf_gate.py holds the ratio
    # under an ABSOLUTE 3% ceiling (same methodology as the tracing and
    # log fences: never a diff of two noisy end-to-end rates).
    from ray_tpu.runtime import refcount as _refcount

    _rc = _refcount.RefCounter()
    _oids = ["%032x" % i for i in range(8192)]

    # SHORT rounds, many reps, interleaved: the probe runs inside a
    # live runtime whose flusher threads steal the GIL every few tens
    # of ms — a 100k-iter round always eats a wakeup, a 20k-iter round
    # lets the min dodge them; interleaving samples hot and cold under
    # the same box conditions
    def _mem_round(fn, iters: int = 20_000) -> float:
        t0 = time.perf_counter()
        for i in range(iters):
            fn(i)
        return (time.perf_counter() - t0) / iters

    _hot_fn = lambda i: _rc.note_owned_here(_oids[i & 8191], 1024)
    _cold_fn = lambda i: _refcount.is_active()
    _mem_round(_hot_fn)
    _mem_round(_cold_fn)  # warm both paths
    hot_mem = cold_mem = float("inf")
    for _ in range(15):
        hot_mem = min(hot_mem, _mem_round(_hot_fn))
        cold_mem = min(cold_mem, _mem_round(_cold_fn))
    per_put_s = 1.0 / results["puts_1kb_per_sec"]
    results["memory_accounting_overhead"] = {
        "probe_hot_ns": round(hot_mem * 1e9, 1),
        "probe_cold_ns": round(cold_mem * 1e9, 1),
        "per_put_us": round(per_put_s * 1e6, 1),
        "ratio": round(max(hot_mem - cold_mem, 0.0) / per_put_s, 5),
    }

    big = np.zeros(32 << 18, dtype=np.float64)  # 64 MiB
    t0 = time.perf_counter()
    bref = ray_tpu.put(big)
    put_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = ray_tpu.get(bref)
    get_s = time.perf_counter() - t0
    assert out.nbytes == big.nbytes
    results["put_gbps"] = round(big.nbytes / put_s / 1e9, 2)
    results["get_gbps"] = round(big.nbytes / get_s / 1e9, 2)
    results["rounds"] = rounds_detail

    ray_tpu.shutdown()
    c.shutdown()
    return {
        "metric": "core_tasks_per_sec",
        "value": results["tasks_per_sec"],
        "unit": "tasks/s",
        "vs_baseline": None,  # reference's numbers are external (nightly)
        "detail": results,
    }


def bench_envelope() -> dict:
    """Bounded scalability-envelope probe: how far the cluster runtime
    stretches in ONE artifact-visible leg (the full nightly tier runs
    10x+ these axes; this keeps a driver-captured record every round).

    Three axes on an external-process GCS + raylet:
      * drain rate of ``bench_envelope_tasks`` queued no-op tasks
        (submitted in windows so the host never holds every ref),
      * creation rate of ``bench_envelope_actors`` trivial actors —
        the fork-server worker pool (``runtime/prestart.py``) is what
        moves this axis: each actor is an ``os.fork()`` of the warm
        zygote template, not a cold interpreter boot,
      * steady-state actor calls/s round-robined over all of them.
    """
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.utils.config import get_config

    cfg = get_config()
    n_tasks = cfg.bench_envelope_tasks
    n_actors = cfg.bench_envelope_actors
    c = Cluster(external_gcs=True)
    c.add_node(num_cpus=4, external=True)
    ray_tpu.init(address=c.gcs_address)
    detail: dict = {"tasks": n_tasks, "actors": n_actors}

    @ray_tpu.remote
    def nop(i):
        return i

    # warm the pool + zygote template so the probe measures the runtime,
    # not first-boot imports
    ray_tpu.get([nop.remote(i) for i in range(8)])

    window = min(25_000, n_tasks)
    t0 = time.perf_counter()
    done = 0
    while done < n_tasks:
        take = min(window, n_tasks - done)
        out = ray_tpu.get([nop.remote(done + i) for i in range(take)])
        assert out[0] == done and out[-1] == done + take - 1
        done += take
    detail["envelope_tasks_per_sec"] = round(
        n_tasks / (time.perf_counter() - t0), 1)

    @ray_tpu.remote(num_cpus=0)
    class A:
        def __init__(self, i):
            self.i = i

        def who(self):
            return self.i

    # creation clock stops when every actor has ANSWERED a call (alive
    # and schedulable, not merely submitted); per-phase decomposition
    # (register / place / ready / resolve) comes from the driver's
    # registration coalescer + the GCS actor-plane counters
    from ray_tpu.runtime import core as _core
    from ray_tpu.runtime.rpc import RpcClient

    rt = _core.get_runtime()
    gcs_probe = RpcClient(tuple(c.gcs_address), label="driver")
    gcs_probe.call("actor_plane_stats", reset=True)
    polls_before = getattr(rt, "_actor_get_polls", 0)
    t0 = time.perf_counter()
    actors = [A.remote(i) for i in range(n_actors)]
    submit_s = time.perf_counter() - t0
    if hasattr(rt, "_reg_drain"):
        for a in actors:   # registration acks (cheap: set lookups)
            rt._reg_drain(a._actor_id.hex())
    register_s = time.perf_counter() - t0
    got = ray_tpu.get([a.who.remote() for a in actors])
    create_s = time.perf_counter() - t0
    assert got == list(range(n_actors))
    plane = gcs_probe.call("actor_plane_stats")
    gcs_probe.close()
    detail["actors_created_per_sec"] = round(n_actors / create_s, 1)
    detail["actor_create_elapsed_s"] = round(create_s, 1)
    detail["creation_phases"] = {
        "submit_s": round(submit_s, 3),
        "register_s": round(register_s, 3),
        "place_mean_ms": round(1e3 * plane["place_s"]
                               / max(plane["placed"], 1), 2),
        "ready_mean_ms": round(1e3 * plane["ready_s"]
                               / max(plane["ready"], 1), 2),
        "resolve_and_first_call_s": round(create_s - register_s, 3),
        "register_batches": plane["register_batches"],
        "register_batch_max": plane["register_batch_max"],
        "host_batches": plane["host_batches"],
        "host_batch_max": plane["host_batch_max"],
        "ready_batches": plane["ready_batches"],
    }

    # steady state: every live actor answers again, round-robin; the
    # location-resolve rate rides the warm pushed table (zero polls).
    # bench_profile_enabled samples the DRIVER's threads across exactly
    # this window (the submit/await path is driver-side — the axis that
    # dipped when the actor count grew) and writes the collapsed-stack
    # artifact any flamegraph renderer consumes.
    profiler = None
    if cfg.bench_profile_enabled:
        import threading as _threading

        from ray_tpu.util.profiling import sample_profile

        prof_out: list = []
        prof_stop = _threading.Event()
        profiler = _threading.Thread(
            target=lambda: prof_out.append(
                sample_profile(duration_s=600.0, hz=200, stop=prof_stop)),
            daemon=True, name="bench-profiler")
        profiler.start()
    calls = 4 * n_actors
    t0 = time.perf_counter()
    refs = [actors[i % n_actors].who.remote() for i in range(calls)]
    ray_tpu.get(refs)
    steady_s = time.perf_counter() - t0
    detail["steady_actor_calls_per_sec"] = round(calls / steady_s, 1)
    if profiler is not None:
        prof_stop.set()
        profiler.join(timeout=10)
        if prof_out:
            prof = prof_out[0]
            path = os.environ.get("BENCH_PROFILE_OUT",
                                  "PROFILE_envelope.folded")
            with open(path, "w") as f:
                f.write(prof["folded"] + "\n")
            detail["profile"] = {
                "artifact": path,
                "samples": prof["samples"],
                "duration_s": prof["duration_s"],
                # top frames inline so the artifact JSON alone shows
                # where the steady-call window went
                "top_stacks": prof["folded"].splitlines()[:5],
            }
    t0 = time.perf_counter()
    for a in actors:
        rt._actor_location(a._actor_id.hex())
    detail["actor_resolves_per_sec"] = round(
        n_actors / max(time.perf_counter() - t0, 1e-9), 1)
    detail["resolve_fallback_polls"] = (
        getattr(rt, "_actor_get_polls", 0) - polls_before)

    for a in actors:
        ray_tpu.kill(a)
    ray_tpu.shutdown()
    c.shutdown()
    return {
        "metric": "envelope_actors_created_per_sec",
        "value": detail["actors_created_per_sec"],
        "unit": "actors/s",
        "vs_baseline": None,  # reference envelope publishes no rates
        "detail": detail,
    }


def bench_chaos_soak() -> dict:
    """Seeded crash/partition soak with conservation invariants
    (ray_tpu/chaos_soak.py). Knobs: CHAOS_SOAK_DURATION (seconds per
    seed, default 300), CHAOS_SOAK_SEEDS (comma list, default "0"),
    CHAOS_SOAK_OUT (report path, default CHAOS_r10.json next to this
    file). The gate metric is the violation count — the MTTR means ride
    in detail for the perf-gate ceilings."""
    from ray_tpu.chaos_soak import run_soak_matrix

    duration = float(os.environ.get("CHAOS_SOAK_DURATION", "300"))
    seeds = [int(s) for s in
             os.environ.get("CHAOS_SOAK_SEEDS", "0").split(",")
             if s.strip()]
    out = os.environ.get(
        "CHAOS_SOAK_OUT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "CHAOS_r10.json"))
    report = run_soak_matrix(
        duration, seeds, out_path=out,
        log=lambda *a: print(*a, file=sys.stderr))
    detail = {"seeds": report["seeds"],
              "chaos_soak_invariant_violations":
                  report["chaos_soak_invariant_violations"]}
    for key in ("chaos_mttr_replica_mean_s", "chaos_mttr_raylet_mean_s"):
        if key in report:
            detail[key] = report[key]
    if isinstance(report.get("probe_overhead"), dict):
        detail["probe_overhead"] = report["probe_overhead"]
    return {
        "metric": "chaos_soak_invariant_violations",
        "value": report["chaos_soak_invariant_violations"],
        "unit": "violations",
        "vs_baseline": None,
        "detail": detail,
    }


def _bench_subprocess(mode: str, timeout: float = 900.0) -> dict:
    """Run one bench mode in a FRESH interpreter (parity with a
    standalone ``BENCH_MODE=<mode>`` run; ray_perf runs standalone too).
    bench_all orders these legs FIRST so the parent hasn't imported jax
    yet — on a 1-cpu host even an idle parent's dispatch/tunnel threads
    would steal timeslices from the child's cluster."""
    import signal
    import subprocess

    env = dict(os.environ)
    env["BENCH_MODE"] = mode
    # own process group: a timeout kill must take the child's external
    # raylet/GCS processes down with it, not orphan them on the host
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        proc.wait()
        raise RuntimeError(f"{mode} bench subprocess timed out") from None
    if proc.returncode != 0 or not stdout.strip():
        raise RuntimeError(
            f"{mode} bench subprocess failed (rc={proc.returncode}): "
            f"{(stderr or '')[-2000:]}")
    return json.loads(stdout.strip().splitlines()[-1])


def bench_core_subprocess() -> dict:
    return _bench_subprocess("core")


def bench_all() -> dict:
    """Train headline + serve/core sub-benchmarks folded into detail.
    Sub-bench failures degrade to an error string: the train number must
    still land in the round artifact.

    The core leg runs FIRST: on a small host (this CI box has ONE cpu)
    the parent's jax dispatch + device-tunnel threads — once any train
    or serve leg has initialized them — steal enough timeslices from
    the core subprocess's cluster processes to depress a pure-Python
    RPC benchmark ~25%. Before jax is ever imported, the parent is an
    idle wait and the child's numbers match a standalone run."""
    subs = [("core", bench_core_subprocess),
            ("data", lambda: _bench_subprocess("data", 1800.0)),
            ("envelope", lambda: _bench_subprocess("envelope", 1800.0)),
            # multi-replica scale-out leg: own subprocess (it builds a
            # worker-process cluster) BEFORE the in-parent serve leg
            # imports jax
            ("serve_scaleout",
             lambda: _bench_subprocess("serve_scaleout", 1800.0)),
            ("serve", bench_serve)]
    if os.environ.get("BENCH_PRESET", "base") != "small":
        # the ~1B entry is a real-chip measurement; a CPU smoke run
        # (BENCH_PRESET=small) must not train a 1B model on host
        subs.insert(1, ("train_large", lambda: bench_train("large")))
        subs.insert(2, ("train_longctx", lambda: bench_train("longctx")))
    pre: dict = {}
    for name, fn in subs:
        try:
            sub = fn()
            pre[name] = {
                "metric": sub["metric"], "value": sub["value"],
                "unit": sub["unit"], **sub["detail"]}
        except Exception as e:  # noqa: BLE001
            pre[name] = {"error": f"{type(e).__name__}: {e}"}
    try:
        result = bench_train()
    except Exception as e:  # noqa: BLE001 — a late headline failure
        # (e.g. chip preemption) must not discard the completed sub
        # results: degrade to an artifact that carries them + the error
        result = {"metric": "llama_train_tokens_per_sec_per_chip",
                  "value": 0.0, "unit": "tokens/s/chip",
                  "vs_baseline": None,
                  "detail": {"error": f"{type(e).__name__}: {e}"}}
    result["detail"].update(pre)
    return result


if __name__ == "__main__":
    mode = os.environ.get("BENCH_MODE", "all")
    fn = {"serve": bench_serve, "core": bench_core,
          "data": bench_data,
          "envelope": bench_envelope,
          "serve_scaleout": bench_serve_scaleout,
          "chaos_soak": bench_chaos_soak,
          "train": bench_train,
          "train_telemetry": bench_train_telemetry}.get(mode, bench_all)
    print(json.dumps(fn()))
    sys.exit(0)
