// Example: a C++ client driving a running ray_tpu cluster.
//
//   ./example_submit <gcs_host> <gcs_port>
//
// Puts an object, reads it back, submits a task by function descriptor
// (executed by a Python worker), and fetches the result. Prints one
// JSON-ish line per check; exits 0 on success.

#include <cstdio>
#include <cstdlib>

#include "ray_api.hpp"

int main(int argc, char** argv) {
  if (argc != 3) {
    std::fprintf(stderr, "usage: %s <gcs_host> <gcs_port>\n", argv[0]);
    return 2;
  }
  try {
    raytpu::Init(argv[1], std::atoi(argv[2]));

    // put/get round trip
    raytpu::Map payload{{"answer", raytpu::Value(int64_t{41})},
                        {"tags", raytpu::Value(raytpu::Array{
                                     raytpu::Value("a"),
                                     raytpu::Value("b")})}};
    std::string oid = raytpu::Put(raytpu::Value(payload));
    raytpu::Value back = raytpu::Get(oid);
    if (back["answer"].as_int() != 41 ||
        back["tags"].as_array().size() != 2) {
      std::fprintf(stderr, "put/get mismatch\n");
      return 1;
    }
    std::printf("{\"put_get\": \"ok\", \"oid\": \"%s\"}\n", oid.c_str());

    // task submission by function descriptor, executed by a Python worker
    std::string rid = raytpu::Task("ray_tpu.examples.xlang:add")
                          .Arg(raytpu::Value(int64_t{40}))
                          .Arg(raytpu::Value(int64_t{2}))
                          .Remote();
    int64_t sum = raytpu::Get(rid, 60.0).as_int();
    if (sum != 42) {
      std::fprintf(stderr, "task result mismatch: %lld\n",
                   static_cast<long long>(sum));
      return 1;
    }
    std::printf("{\"task\": \"ok\", \"result\": %lld}\n",
                static_cast<long long>(sum));

    // a second shape: list + dict result
    std::string rid2 =
        raytpu::Task("ray_tpu.examples.xlang:stats")
            .Arg(raytpu::Value(raytpu::Array{raytpu::Value(int64_t{3}),
                                             raytpu::Value(int64_t{1}),
                                             raytpu::Value(int64_t{8})}))
            .Remote();
    raytpu::Value st = raytpu::Get(rid2, 60.0);
    if (st["n"].as_int() != 3 || st["max"].as_double() != 8.0) {
      std::fprintf(stderr, "stats mismatch\n");
      return 1;
    }
    std::printf("{\"stats\": \"ok\", \"sum\": %.1f}\n",
                st["sum"].as_double());

    raytpu::Shutdown();
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "FAILED: %s\n", e.what());
    return 1;
  }
}
