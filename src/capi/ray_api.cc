// ray_tpu C++ public API implementation: framed msgpack RPC client.
// See ray_api.hpp; wire/protocol notes in ray_tpu/runtime/rpc.py.

#include "ray_api.hpp"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>
#include <fstream>
#include <mutex>
#include <stdexcept>

namespace raytpu {
namespace {

class RpcClient {
 public:
  RpcClient(const std::string& host, int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw std::runtime_error("socket() failed");
    int one = 1;
    ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1)
      throw std::runtime_error("bad address: " + host);
    if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      throw std::runtime_error("connect failed to " + host + ":" +
                               std::to_string(port));
  }
  ~RpcClient() {
    if (fd_ >= 0) ::close(fd_);
  }
  RpcClient(const RpcClient&) = delete;
  RpcClient& operator=(const RpcClient&) = delete;

  // One synchronous request/response (requests are serialized per
  // client with a mutex; the server answers msgpack frames in msgpack).
  Value call(const std::string& method, Map params) {
    std::lock_guard<std::mutex> g(mu_);
    params.emplace("method", Value(method));
    params.emplace("_id", Value(static_cast<int64_t>(next_id_++)));
    std::string payload = "M";
    Value(std::move(params)).pack(payload);
    std::string frame;
    for (int i = 7; i >= 0; --i)
      frame.push_back(
          static_cast<char>((payload.size() >> (8 * i)) & 0xff));
    frame += payload;
    send_all(frame.data(), frame.size());

    uint8_t hdr[8];
    recv_all(hdr, 8);
    uint64_t len = 0;
    for (int i = 0; i < 8; ++i) len = (len << 8) | hdr[i];
    std::vector<uint8_t> buf(len);
    recv_all(buf.data(), len);
    if (len == 0 || buf[0] != 'M')
      throw std::runtime_error("server replied in a non-msgpack format");
    size_t off = 1;
    Value reply = Value::unpack(buf.data(), len, off);
    const Value& err = reply["error"];
    if (!err.is_nil())
      throw std::runtime_error("rpc " + method + " failed: " + err.as_str());
    return reply["result"];
  }

 private:
  void send_all(const char* data, size_t n) {
    size_t sent = 0;
    while (sent < n) {
      ssize_t rc = ::send(fd_, data + sent, n - sent, 0);
      if (rc <= 0) throw std::runtime_error("send failed");
      sent += static_cast<size_t>(rc);
    }
  }
  void recv_all(uint8_t* data, size_t n) {
    size_t got = 0;
    while (got < n) {
      ssize_t rc = ::recv(fd_, data + got, n - got, 0);
      if (rc <= 0) throw std::runtime_error("connection lost");
      got += static_cast<size_t>(rc);
    }
  }

  int fd_ = -1;
  int64_t next_id_ = 0;
  std::mutex mu_;
};

struct Session {
  std::unique_ptr<RpcClient> gcs;
  std::unique_ptr<RpcClient> raylet;
};

Session* g_session = nullptr;

std::string random_hex(size_t nbytes) {
  std::ifstream ur("/dev/urandom", std::ios::binary);
  std::vector<uint8_t> buf(nbytes);
  ur.read(reinterpret_cast<char*>(buf.data()),
          static_cast<std::streamsize>(nbytes));
  static const char* hexd = "0123456789abcdef";
  std::string out;
  out.reserve(nbytes * 2);
  for (uint8_t b : buf) {
    out.push_back(hexd[b >> 4]);
    out.push_back(hexd[b & 0x0f]);
  }
  return out;
}

Session& session() {
  if (!g_session)
    throw std::runtime_error("raytpu::Init() has not been called");
  return *g_session;
}

}  // namespace

void Init(const std::string& gcs_host, int gcs_port) {
  auto s = std::make_unique<Session>();
  s->gcs = std::make_unique<RpcClient>(gcs_host, gcs_port);
  Value nodes = s->gcs->call("get_nodes", Map{{"alive_only", Value(true)}});
  if (nodes.as_array().empty())
    throw std::runtime_error("no alive nodes in cluster");
  // prefer the head node (label) like the Python driver does
  const Value* chosen = &nodes.as_array()[0];
  for (const auto& n : nodes.as_array()) {
    const Value& labels = n["labels"];
    if (labels.type() == Value::Type::Obj && !labels["head"].is_nil()) {
      chosen = &n;
      break;
    }
  }
  const Array& addr = (*chosen)["address"].as_array();
  s->raylet = std::make_unique<RpcClient>(
      addr[0].as_str(), static_cast<int>(addr[1].as_int()));
  delete g_session;
  g_session = s.release();
}

void Shutdown() {
  delete g_session;
  g_session = nullptr;
}

std::string Put(const Value& value) {
  Value r = session().raylet->call("xlang_put", Map{{"value", value}});
  return r["oid"].as_str();
}

Value Get(const std::string& oid_hex, double timeout_s) {
  Value r = session().raylet->call(
      "xlang_get",
      Map{{"oid", Value(oid_hex)}, {"timeout_s", Value(timeout_s)}});
  return r["value"];
}

TaskBuilder::TaskBuilder(std::string function_ref)
    : function_ref_(std::move(function_ref)) {}

TaskBuilder& TaskBuilder::Arg(Value v) {
  args_.push_back(std::move(v));
  return *this;
}

TaskBuilder& TaskBuilder::NumCpus(double n) {
  num_cpus_ = n;
  return *this;
}

std::string TaskBuilder::Remote() {
  std::string return_oid = random_hex(16);
  Map task;
  task.emplace("task_id", Value(random_hex(16)));
  task.emplace("name", Value(function_ref_));
  task.emplace("function_ref", Value(function_ref_));
  task.emplace("args", Value(args_));
  task.emplace("return_oids", Value(Array{Value(return_oid)}));
  task.emplace("resources", Value(Map{{"CPU", Value(num_cpus_)}}));
  task.emplace("strategy", Value(Map{{"kind", Value("DEFAULT")}}));
  task.emplace("max_retries", Value(int64_t{0}));
  Value r = session().raylet->call("submit_task",
                                   Map{{"task", Value(std::move(task))}});
  (void)r;
  return return_oid;
}

TaskBuilder Task(const std::string& function_ref) {
  return TaskBuilder(function_ref);
}

}  // namespace raytpu
