// Minimal msgpack codec for the ray_tpu C++ public API.
//
// Reference analog: the reference's C++ worker serializes task args and
// returns with msgpack (bazel/ray_deps_setup.bzl:304). This is a small
// self-contained implementation covering the cross-language value
// domain: nil, bool, int64, float64, str, bin, array, map<string,Value>
// (mirrors ray_tpu/runtime/xlang.py).
#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace raytpu {

class Value;
using Array = std::vector<Value>;
using Map = std::map<std::string, Value>;

class Value {
 public:
  enum class Type { Nil, Bool, Int, Float, Str, Bin, Arr, Obj };

  Value() : type_(Type::Nil) {}
  Value(std::nullptr_t) : type_(Type::Nil) {}
  Value(bool b) : type_(Type::Bool), b_(b) {}
  Value(int i) : type_(Type::Int), i_(i) {}
  Value(int64_t i) : type_(Type::Int), i_(i) {}
  Value(uint64_t i) : type_(Type::Int), i_(static_cast<int64_t>(i)) {}
  Value(double d) : type_(Type::Float), d_(d) {}
  Value(const char* s) : type_(Type::Str), s_(s) {}
  Value(std::string s) : type_(Type::Str), s_(std::move(s)) {}
  Value(std::vector<uint8_t> b) : type_(Type::Bin), bin_(std::move(b)) {}
  Value(Array a) : type_(Type::Arr), arr_(std::move(a)) {}
  Value(Map m) : type_(Type::Obj), map_(std::move(m)) {}

  Type type() const { return type_; }
  bool is_nil() const { return type_ == Type::Nil; }
  bool as_bool() const { check(Type::Bool); return b_; }
  int64_t as_int() const {
    if (type_ == Type::Float) return static_cast<int64_t>(d_);
    check(Type::Int);
    return i_;
  }
  double as_double() const {
    if (type_ == Type::Int) return static_cast<double>(i_);
    check(Type::Float);
    return d_;
  }
  const std::string& as_str() const { check(Type::Str); return s_; }
  const std::vector<uint8_t>& as_bin() const { check(Type::Bin); return bin_; }
  const Array& as_array() const { check(Type::Arr); return arr_; }
  const Map& as_map() const { check(Type::Obj); return map_; }

  const Value& operator[](const std::string& key) const {
    check(Type::Obj);
    static const Value kNil;
    auto it = map_.find(key);
    return it == map_.end() ? kNil : it->second;
  }

  // ---- encoding -----------------------------------------------------
  void pack(std::string& out) const {
    switch (type_) {
      case Type::Nil: out.push_back('\xc0'); break;
      case Type::Bool: out.push_back(b_ ? '\xc3' : '\xc2'); break;
      case Type::Int: pack_int(out, i_); break;
      case Type::Float: {
        out.push_back('\xcb');
        uint64_t bits;
        std::memcpy(&bits, &d_, 8);
        pack_be(out, bits, 8);
        break;
      }
      case Type::Str: {
        size_t n = s_.size();
        if (n <= 31) {
          out.push_back(static_cast<char>(0xa0 | n));
        } else if (n <= 0xff) {
          out.push_back('\xd9');
          out.push_back(static_cast<char>(n));
        } else if (n <= 0xffff) {
          out.push_back('\xda');
          pack_be(out, n, 2);
        } else {
          out.push_back('\xdb');
          pack_be(out, n, 4);
        }
        out.append(s_);
        break;
      }
      case Type::Bin: {
        size_t n = bin_.size();
        if (n <= 0xff) {
          out.push_back('\xc4');
          out.push_back(static_cast<char>(n));
        } else if (n <= 0xffff) {
          out.push_back('\xc5');
          pack_be(out, n, 2);
        } else {
          out.push_back('\xc6');
          pack_be(out, n, 4);
        }
        out.append(reinterpret_cast<const char*>(bin_.data()), n);
        break;
      }
      case Type::Arr: {
        size_t n = arr_.size();
        if (n <= 15) {
          out.push_back(static_cast<char>(0x90 | n));
        } else if (n <= 0xffff) {
          out.push_back('\xdc');
          pack_be(out, n, 2);
        } else {
          out.push_back('\xdd');
          pack_be(out, n, 4);
        }
        for (const auto& v : arr_) v.pack(out);
        break;
      }
      case Type::Obj: {
        size_t n = map_.size();
        if (n <= 15) {
          out.push_back(static_cast<char>(0x80 | n));
        } else if (n <= 0xffff) {
          out.push_back('\xde');
          pack_be(out, n, 2);
        } else {
          out.push_back('\xdf');
          pack_be(out, n, 4);
        }
        for (const auto& kv : map_) {
          Value(kv.first).pack(out);
          kv.second.pack(out);
        }
        break;
      }
    }
  }

  // ---- decoding -----------------------------------------------------
  static Value unpack(const uint8_t* data, size_t len, size_t& off) {
    if (off >= len) throw std::runtime_error("msgpack: truncated");
    uint8_t b = data[off++];
    if (b <= 0x7f) return Value(static_cast<int64_t>(b));
    if (b >= 0xe0) return Value(static_cast<int64_t>(static_cast<int8_t>(b)));
    if (b >= 0x80 && b <= 0x8f) return unpack_map(data, len, off, b & 0x0f);
    if (b >= 0x90 && b <= 0x9f) return unpack_arr(data, len, off, b & 0x0f);
    if (b >= 0xa0 && b <= 0xbf) return unpack_str(data, len, off, b & 0x1f);
    switch (b) {
      case 0xc0: return Value();
      case 0xc2: return Value(false);
      case 0xc3: return Value(true);
      case 0xc4: return unpack_bin(data, len, off, read_be(data, len, off, 1));
      case 0xc5: return unpack_bin(data, len, off, read_be(data, len, off, 2));
      case 0xc6: return unpack_bin(data, len, off, read_be(data, len, off, 4));
      case 0xca: {
        uint32_t bits = static_cast<uint32_t>(read_be(data, len, off, 4));
        float f;
        std::memcpy(&f, &bits, 4);
        return Value(static_cast<double>(f));
      }
      case 0xcb: {
        uint64_t bits = read_be(data, len, off, 8);
        double d;
        std::memcpy(&d, &bits, 8);
        return Value(d);
      }
      case 0xcc: return Value(static_cast<int64_t>(read_be(data, len, off, 1)));
      case 0xcd: return Value(static_cast<int64_t>(read_be(data, len, off, 2)));
      case 0xce: return Value(static_cast<int64_t>(read_be(data, len, off, 4)));
      case 0xcf: return Value(static_cast<int64_t>(read_be(data, len, off, 8)));
      case 0xd0: return Value(static_cast<int64_t>(
          static_cast<int8_t>(read_be(data, len, off, 1))));
      case 0xd1: return Value(static_cast<int64_t>(
          static_cast<int16_t>(read_be(data, len, off, 2))));
      case 0xd2: return Value(static_cast<int64_t>(
          static_cast<int32_t>(read_be(data, len, off, 4))));
      case 0xd3: return Value(static_cast<int64_t>(read_be(data, len, off, 8)));
      case 0xd9: return unpack_str(data, len, off, read_be(data, len, off, 1));
      case 0xda: return unpack_str(data, len, off, read_be(data, len, off, 2));
      case 0xdb: return unpack_str(data, len, off, read_be(data, len, off, 4));
      case 0xdc: return unpack_arr(data, len, off, read_be(data, len, off, 2));
      case 0xdd: return unpack_arr(data, len, off, read_be(data, len, off, 4));
      case 0xde: return unpack_map(data, len, off, read_be(data, len, off, 2));
      case 0xdf: return unpack_map(data, len, off, read_be(data, len, off, 4));
      default:
        throw std::runtime_error("msgpack: unsupported type byte");
    }
  }

 private:
  void check(Type t) const {
    if (type_ != t) throw std::runtime_error("msgpack: wrong Value type");
  }
  static void pack_be(std::string& out, uint64_t v, int nbytes) {
    for (int i = nbytes - 1; i >= 0; --i)
      out.push_back(static_cast<char>((v >> (8 * i)) & 0xff));
  }
  static void pack_int(std::string& out, int64_t v) {
    if (v >= 0 && v <= 0x7f) {
      out.push_back(static_cast<char>(v));
    } else if (v < 0 && v >= -32) {
      out.push_back(static_cast<char>(v));
    } else if (v >= 0) {
      out.push_back('\xcf');
      pack_be(out, static_cast<uint64_t>(v), 8);
    } else {
      out.push_back('\xd3');
      pack_be(out, static_cast<uint64_t>(v), 8);
    }
  }
  static uint64_t read_be(const uint8_t* data, size_t len, size_t& off,
                          int nbytes) {
    if (off + nbytes > len) throw std::runtime_error("msgpack: truncated");
    uint64_t v = 0;
    for (int i = 0; i < nbytes; ++i) v = (v << 8) | data[off++];
    return v;
  }
  static Value unpack_str(const uint8_t* data, size_t len, size_t& off,
                          uint64_t n) {
    if (off + n > len) throw std::runtime_error("msgpack: truncated str");
    Value v(std::string(reinterpret_cast<const char*>(data + off),
                        static_cast<size_t>(n)));
    off += n;
    return v;
  }
  static Value unpack_bin(const uint8_t* data, size_t len, size_t& off,
                          uint64_t n) {
    if (off + n > len) throw std::runtime_error("msgpack: truncated bin");
    Value v(std::vector<uint8_t>(data + off, data + off + n));
    off += n;
    return v;
  }
  static Value unpack_arr(const uint8_t* data, size_t len, size_t& off,
                          uint64_t n) {
    Array a;
    a.reserve(static_cast<size_t>(n));
    for (uint64_t i = 0; i < n; ++i) a.push_back(unpack(data, len, off));
    return Value(std::move(a));
  }
  static Value unpack_map(const uint8_t* data, size_t len, size_t& off,
                          uint64_t n) {
    Map m;
    for (uint64_t i = 0; i < n; ++i) {
      Value k = unpack(data, len, off);
      Value v = unpack(data, len, off);
      m.emplace(k.as_str(), std::move(v));
    }
    return Value(std::move(m));
  }

  Type type_;
  bool b_ = false;
  int64_t i_ = 0;
  double d_ = 0.0;
  std::string s_;
  std::vector<uint8_t> bin_;
  Array arr_;
  Map map_;
};

}  // namespace raytpu
