// ray_tpu C++ public API (N15).
//
// Reference analog: cpp/include/ray/api.h — ray::Init / ray::Put /
// ray::Get / ray::Task(...).Remote() over the cluster's RPC plane. This
// client speaks the framed msgpack wire (8-byte big-endian length +
// 'M' + msgpack map; see ray_tpu/runtime/rpc.py and runtime/xlang.py):
//
//   raytpu::Init("127.0.0.1", gcs_port);
//   auto oid = raytpu::Put(raytpu::Value(int64_t{41}));
//   raytpu::Value v = raytpu::Get(oid);
//   auto rid = raytpu::Task("ray_tpu.examples.xlang:add")
//                 .Arg(int64_t{1}).Arg(int64_t{2}).Remote();
//   int64_t sum = raytpu::Get(rid).as_int();
//
// Functions are named by DESCRIPTOR ("module:qualname"), resolved by
// import on the executing Python worker — the reference's cross-language
// calling convention (function descriptors, msgpack args), not pickled
// closures.
#pragma once

#include <string>

#include "msgpack_lite.hpp"

namespace raytpu {

// Connect to a running cluster's GCS and resolve the head raylet.
void Init(const std::string& gcs_host, int gcs_port);
void Shutdown();

// Object plane: plain-data values in, object ids (hex) out.
std::string Put(const Value& value);
Value Get(const std::string& oid_hex, double timeout_s = 30.0);

// Task plane.
class TaskBuilder {
 public:
  explicit TaskBuilder(std::string function_ref);
  TaskBuilder& Arg(Value v);
  TaskBuilder& NumCpus(double n);
  // Submit; returns the return-object id (hex) to pass to Get().
  std::string Remote();

 private:
  std::string function_ref_;
  Array args_;
  double num_cpus_ = 1.0;
};

TaskBuilder Task(const std::string& function_ref);

}  // namespace raytpu
