// Host-side CPU collective backend over TCP — the Gloo analog of the
// reference's ray.util.collective gloo backend (reference:
// python/ray/util/collective/collective_group/gloo_collective_group.py).
//
// Design: full-mesh blocking TCP sockets between ranks (pair (i,j), i<j:
// j dials i's listen port), bandwidth-optimal ring algorithms:
//   allreduce      = ring reduce-scatter + ring allgather, 2(N-1) steps
//   reduce_scatter = ring, N-1 steps
//   allgather      = ring, N-1 steps
//   broadcast      = binomial tree from root
//   barrier        = allreduce of one int64
//   send/recv      = framed p2p with tag matching (per-peer reorder buffer)
//
// Exposed as a C ABI for ctypes (no pybind11 in this image). All buffers
// are caller-owned contiguous memory; ops are synchronous. This is the
// host data plane only — device collectives are XLA ops over ICI
// (ray_tpu/parallel/collectives.py).

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cerrno>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Dtype { F32 = 0, F64 = 1, I32 = 2, I64 = 3 };
enum Op { SUM = 0, PROD = 1, MAX = 2, MIN = 3 };

size_t dtype_size(int dt) {
  switch (dt) {
    case F32: case I32: return 4;
    default: return 8;
  }
}

// ---- socket helpers -------------------------------------------------------

int send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
  return 0;
}

int recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k < 0) {
      if (errno == EINTR) continue;
      return -errno;
    }
    if (k == 0) return -ECONNRESET;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return 0;
}

void set_nodelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// ---- elementwise reduction ------------------------------------------------

template <typename T>
void reduce_typed(T* acc, const T* in, size_t count, int op) {
  switch (op) {
    case SUM:  for (size_t i = 0; i < count; i++) acc[i] += in[i]; break;
    case PROD: for (size_t i = 0; i < count; i++) acc[i] *= in[i]; break;
    case MAX:  for (size_t i = 0; i < count; i++) acc[i] = std::max(acc[i], in[i]); break;
    case MIN:  for (size_t i = 0; i < count; i++) acc[i] = std::min(acc[i], in[i]); break;
  }
}

void reduce_buf(void* acc, const void* in, size_t count, int dtype, int op) {
  switch (dtype) {
    case F32: reduce_typed(static_cast<float*>(acc), static_cast<const float*>(in), count, op); break;
    case F64: reduce_typed(static_cast<double*>(acc), static_cast<const double*>(in), count, op); break;
    case I32: reduce_typed(static_cast<int32_t*>(acc), static_cast<const int32_t*>(in), count, op); break;
    case I64: reduce_typed(static_cast<int64_t*>(acc), static_cast<const int64_t*>(in), count, op); break;
  }
}

// ---- group ----------------------------------------------------------------

struct Frame {
  int64_t tag;
  std::vector<char> payload;
};

struct Group {
  int rank = -1;
  int world = 0;
  std::vector<int> fds;  // fds[peer]; -1 for self
  // Sockets are full-duplex: independent send/recv locks per peer so a
  // large ring step can send and receive on the same socket concurrently
  // (a single lock deadlocks at world=2 once TCP buffers fill).
  std::vector<std::unique_ptr<std::mutex>> send_mu;
  std::vector<std::unique_ptr<std::mutex>> recv_mu;
  std::map<int, std::vector<Frame>> stash;  // peer -> out-of-order frames
  std::mutex stash_mu;
  // Per-group collective tag counter. Must be per-group (NOT process
  // global): multiple ranks of one group can live in one process
  // (thread-based workers), and every rank must draw identical tag
  // blocks for the same collective sequence (SPMD contract).
  int64_t ring_tag = (int64_t)1 << 40;
  // two-phase setup state (tc_listen -> rendezvous -> tc_connect)
  int lfd = -1;
  int lport = 0;

  ~Group() {
    for (int fd : fds)
      if (fd >= 0) ::close(fd);
  }
};

std::mutex g_mu;
std::map<int, std::shared_ptr<Group>> g_groups;
int g_next = 1;

std::shared_ptr<Group> get_group(int h) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_groups.find(h);
  return it == g_groups.end() ? nullptr : it->second;
}

int parse_peer(const std::string& s, std::string* host, int* port) {
  auto c = s.rfind(':');
  if (c == std::string::npos) return -1;
  *host = s.substr(0, c);
  *port = std::atoi(s.c_str() + c + 1);
  return 0;
}

int dial(const std::string& host, int port, int timeout_ms) {
  struct addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof portstr, "%d", port);
  if (getaddrinfo(host.c_str(), portstr, &hints, &res) != 0) return -1;
  // Wall-clock deadline: connect() itself can block for the kernel's SYN
  // retry window, so budgeting only the sleeps would overshoot the
  // timeout contract by orders of magnitude. Non-blocking connect + poll
  // keeps every wait accountable to the deadline.
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  int fd = -1;
  // retry loop: the listener may not be up yet during group formation
  while (std::chrono::steady_clock::now() < deadline) {
    fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) break;
    int rc = connect(fd, res->ai_addr, res->ai_addrlen);
    if (rc != 0 && errno == EINPROGRESS) {
      auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                      deadline - std::chrono::steady_clock::now())
                      .count();
      pollfd pf{fd, POLLOUT, 0};
      if (left > 0 && poll(&pf, 1, static_cast<int>(left)) == 1) {
        int err = 0;
        socklen_t elen = sizeof err;
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen);
        if (err == 0) rc = 0;
      }
    }
    if (rc == 0) {
      // back to blocking mode for the data path
      int flags = fcntl(fd, F_GETFL, 0);
      fcntl(fd, F_SETFL, flags & ~O_NONBLOCK);
      break;
    }
    ::close(fd);
    fd = -1;
    usleep(50 * 1000);
  }
  freeaddrinfo(res);
  if (fd >= 0) set_nodelay(fd);
  return fd;
}

// framed p2p: [tag:int64][nbytes:int64][payload]
int send_frame(Group& g, int dst, int64_t tag, const void* data, int64_t nbytes) {
  std::lock_guard<std::mutex> lk(*g.send_mu[dst]);
  int64_t hdr[2] = {tag, nbytes};
  int rc = send_all(g.fds[dst], hdr, sizeof hdr);
  if (rc) return rc;
  return send_all(g.fds[dst], data, static_cast<size_t>(nbytes));
}

bool take_stashed(Group& g, int src, int64_t tag, void* data, int64_t nbytes,
                  int* rc_out) {
  std::lock_guard<std::mutex> lk(g.stash_mu);
  auto& q = g.stash[src];
  for (auto it = q.begin(); it != q.end(); ++it) {
    if (it->tag == tag) {
      if (static_cast<int64_t>(it->payload.size()) != nbytes) {
        *rc_out = -EINVAL;
        return true;
      }
      memcpy(data, it->payload.data(), it->payload.size());
      q.erase(it);
      *rc_out = 0;
      return true;
    }
  }
  return false;
}

// timeout_ms <= 0 means block forever.
int recv_frame_t(Group& g, int src, int64_t tag, void* data, int64_t nbytes,
                 int timeout_ms) {
  int rc = 0;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  auto expired = [&] {
    return timeout_ms > 0 && std::chrono::steady_clock::now() >= deadline;
  };
  for (;;) {
    // Re-check the stash EVERY iteration: a concurrent recv() for a
    // different tag may have read our frame off the socket and stashed
    // it while we waited on recv_mu — checking only once deadlocks two
    // threads that each stash the other's frame.
    if (take_stashed(g, src, tag, data, nbytes, &rc)) return rc;
    if (expired()) return -ETIMEDOUT;
    std::unique_lock<std::mutex> lk(*g.recv_mu[src], std::try_to_lock);
    if (!lk.owns_lock()) {
      // another thread is draining this peer's socket; let it work,
      // then re-check the stash
      usleep(200);
      continue;
    }
    if (timeout_ms > 0) {
      pollfd pf{g.fds[src], POLLIN, 0};
      int pr = poll(&pf, 1, 50);
      if (pr == 0) continue;  // drop the lock, re-check stash/deadline
      if (pr < 0) return -errno;
    }
    int64_t hdr[2];
    rc = recv_all(g.fds[src], hdr, sizeof hdr);
    if (rc) return rc;
    if (hdr[0] == tag) {
      if (hdr[1] != nbytes) return -EINVAL;
      return recv_all(g.fds[src], data, static_cast<size_t>(nbytes));
    }
    Frame f;
    f.tag = hdr[0];
    f.payload.resize(static_cast<size_t>(hdr[1]));
    rc = recv_all(g.fds[src], f.payload.data(), f.payload.size());
    if (rc) return rc;
    std::lock_guard<std::mutex> sk(g.stash_mu);
    g.stash[src].push_back(std::move(f));
  }
}

int recv_frame(Group& g, int src, int64_t tag, void* data, int64_t nbytes) {
  return recv_frame_t(g, src, tag, data, nbytes, 0);
}

// simultaneous send-to-next / recv-from-prev without deadlock
int ring_exchange(Group& g, int64_t tag, const void* out, int64_t out_n,
                  void* in, int64_t in_n) {
  int nxt = (g.rank + 1) % g.world;
  int prv = (g.rank - 1 + g.world) % g.world;
  int send_rc = 0;
  std::thread t([&] { send_rc = send_frame(g, nxt, tag, out, out_n); });
  int recv_rc = recv_frame(g, prv, tag, in, in_n);
  t.join();
  return send_rc ? send_rc : recv_rc;
}

// Collective tags live above user tags; each collective reserves a
// disjoint block from the group's counter.
int64_t take_tags(Group& g, int64_t n) {
  int64_t t = g.ring_tag;
  g.ring_tag += n;
  return t;
}

}  // namespace

namespace {  // setup helpers

std::shared_ptr<Group> make_group(int rank, int world) {
  auto g = std::make_shared<Group>();
  g->rank = rank;
  g->world = world;
  g->fds.assign(world, -1);
  for (int i = 0; i < world; i++) {
    g->send_mu.emplace_back(new std::mutex);
    g->recv_mu.emplace_back(new std::mutex);
  }
  return g;
}

// Bind the rank's listener (port 0 = ephemeral) and record the bound port.
int do_listen(Group& g, int port) {
  int nacc = g.world - 1 - g.rank;
  if (nacc <= 0) {
    g.lport = port;
    return 0;
  }
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  if (lfd < 0) return -errno;
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) < 0 ||
      listen(lfd, g.world) < 0) {
    int e = errno;
    ::close(lfd);
    return -e;
  }
  socklen_t alen = sizeof addr;
  if (getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen) < 0) {
    int e = errno;
    ::close(lfd);
    return -e;
  }
  g.lfd = lfd;
  g.lport = ntohs(addr.sin_port);
  return 0;
}

int do_connect(Group& g, const std::vector<std::string>& peers,
               int timeout_ms) {
  // dial every rank below me (its listener is peers[j]); announce my rank
  for (int j = 0; j < g.rank; j++) {
    std::string h2;
    int p2;
    if (parse_peer(peers[j], &h2, &p2) != 0) return -EINVAL;
    int fd = dial(h2, p2, timeout_ms);
    if (fd < 0) return -ETIMEDOUT;
    int32_t me = g.rank;
    if (send_all(fd, &me, sizeof me)) {
      ::close(fd);
      return -EIO;
    }
    g.fds[j] = fd;
  }
  // accept every rank above me
  int nacc = g.world - 1 - g.rank;
  for (int k = 0; k < nacc; k++) {
    pollfd pf{g.lfd, POLLIN, 0};
    int pr = poll(&pf, 1, timeout_ms);
    if (pr <= 0) return -ETIMEDOUT;
    int fd = accept(g.lfd, nullptr, nullptr);
    if (fd < 0) return -errno;
    set_nodelay(fd);
    int32_t who = -1;
    if (recv_all(fd, &who, sizeof who) || who <= g.rank || who >= g.world ||
        g.fds[who] != -1) {
      ::close(fd);
      return -EPROTO;
    }
    g.fds[who] = fd;
  }
  if (g.lfd >= 0) {
    ::close(g.lfd);
    g.lfd = -1;
  }
  return 0;
}

std::vector<std::string> split_csv(const char* s) {
  std::vector<std::string> out;
  std::string cur, csv(s);
  for (char ch : csv) {
    if (ch == ',') {
      out.push_back(cur);
      cur.clear();
    } else {
      cur.push_back(ch);
    }
  }
  if (!cur.empty()) out.push_back(cur);
  return out;
}

int register_group(std::shared_ptr<Group> g) {
  std::lock_guard<std::mutex> lk(g_mu);
  int h = g_next++;
  g_groups[h] = std::move(g);
  return h;
}

}  // namespace

extern "C" {

// One-shot setup with pre-agreed ports. peers_csv:
// "host0:port0,host1:port1,..." — entry i is rank i's listener.
// Returns handle > 0, or negative errno.
int tc_init(int rank, int world, const char* peers_csv, int timeout_ms) {
  if (rank < 0 || world <= 0 || rank >= world) return -EINVAL;
  auto peers = split_csv(peers_csv);
  if (static_cast<int>(peers.size()) != world) return -EINVAL;
  auto g = make_group(rank, world);
  if (world == 1) return register_group(g);
  std::string host;
  int port = 0;
  if (parse_peer(peers[rank], &host, &port) != 0) return -EINVAL;
  int rc = do_listen(*g, port);
  if (rc) return rc;
  rc = do_connect(*g, peers, timeout_ms);
  if (rc) return rc;
  return register_group(g);
}

// Two-phase setup — eliminates the advertise-then-bind race: the listener
// is bound (ephemeral port) BEFORE the address is advertised through
// rendezvous.
//   h = tc_listen(rank, world); port = tc_listen_port(h);
//   <exchange host:port out of band>; tc_connect(h, peers_csv, timeout).
int tc_listen(int rank, int world) {
  if (rank < 0 || world <= 0 || rank >= world) return -EINVAL;
  auto g = make_group(rank, world);
  int rc = do_listen(*g, 0);
  if (rc) return rc;
  return register_group(g);
}

int tc_listen_port(int h) {
  auto g = get_group(h);
  return g ? g->lport : -EINVAL;
}

int tc_connect(int h, const char* peers_csv, int timeout_ms) {
  auto g = get_group(h);
  if (!g) return -EINVAL;
  if (g->world == 1) return 0;
  auto peers = split_csv(peers_csv);
  if (static_cast<int>(peers.size()) != g->world) return -EINVAL;
  return do_connect(*g, peers, timeout_ms);
}

int tc_destroy(int h) {
  std::lock_guard<std::mutex> lk(g_mu);
  return g_groups.erase(h) ? 0 : -EINVAL;
}

// In-place ring allreduce over `count` elements.
int tc_allreduce(int h, void* data, int64_t count, int dtype, int op) {
  auto g = get_group(h);
  if (!g) return -EINVAL;
  if (g->world == 1) return 0;
  size_t esz = dtype_size(dtype);
  int N = g->world;
  char* buf = static_cast<char*>(data);

  // chunk boundaries (last chunk absorbs the remainder)
  std::vector<int64_t> off(N + 1);
  int64_t per = count / N;
  for (int i = 0; i < N; i++) off[i] = i * per;
  off[N] = count;

  int64_t maxc = 0;
  for (int i = 0; i < N; i++) maxc = std::max(maxc, off[i + 1] - off[i]);
  std::vector<char> tmp(static_cast<size_t>(maxc) * esz);
  int64_t tag = take_tags(*g, 2 * N);

  // reduce-scatter: after N-1 steps, rank r owns reduced chunk (r+1)%N
  for (int s = 0; s < N - 1; s++) {
    int send_c = ((g->rank - s) % N + N) % N;
    int recv_c = ((g->rank - s - 1) % N + N) % N;
    int64_t sn = (off[send_c + 1] - off[send_c]) * esz;
    int64_t rn = (off[recv_c + 1] - off[recv_c]) * esz;
    int rc = ring_exchange(*g, tag + s, buf + off[send_c] * esz, sn,
                           tmp.data(), rn);
    if (rc) return rc;
    reduce_buf(buf + off[recv_c] * esz, tmp.data(),
               off[recv_c + 1] - off[recv_c], dtype, op);
  }
  // allgather the reduced chunks
  for (int s = 0; s < N - 1; s++) {
    int send_c = ((g->rank + 1 - s) % N + N) % N;
    int recv_c = ((g->rank - s) % N + N) % N;
    int64_t sn = (off[send_c + 1] - off[send_c]) * esz;
    int64_t rn = (off[recv_c + 1] - off[recv_c]) * esz;
    int rc = ring_exchange(*g, tag + N + s, buf + off[send_c] * esz, sn,
                           buf + off[recv_c] * esz, rn);
    if (rc) return rc;
    (void)rn;
  }
  return 0;
}

// out must hold world*count elements; rank r's contribution lands at r*count.
int tc_allgather(int h, const void* in, void* out, int64_t count, int dtype) {
  auto g = get_group(h);
  if (!g) return -EINVAL;
  size_t esz = dtype_size(dtype);
  int64_t nb = count * static_cast<int64_t>(esz);
  char* obuf = static_cast<char*>(out);
  memcpy(obuf + g->rank * nb, in, static_cast<size_t>(nb));
  if (g->world == 1) return 0;
  int N = g->world;
  int64_t tag = take_tags(*g, N);
  for (int s = 0; s < N - 1; s++) {
    int send_c = ((g->rank - s) % N + N) % N;
    int recv_c = ((g->rank - s - 1) % N + N) % N;
    int rc = ring_exchange(*g, tag + s, obuf + send_c * nb, nb,
                           obuf + recv_c * nb, nb);
    if (rc) return rc;
  }
  return 0;
}

// in has world*count elements; out gets this rank's reduced chunk (count).
int tc_reduce_scatter(int h, const void* in, void* out, int64_t count,
                      int dtype, int op) {
  auto g = get_group(h);
  if (!g) return -EINVAL;
  size_t esz = dtype_size(dtype);
  int64_t nb = count * static_cast<int64_t>(esz);
  int N = g->world;
  if (N == 1) { memcpy(out, in, static_cast<size_t>(nb)); return 0; }
  // work on a scratch copy so `in` stays const
  std::vector<char> work(static_cast<size_t>(nb) * N);
  memcpy(work.data(), in, work.size());
  std::vector<char> tmp(static_cast<size_t>(nb));
  int64_t tag = take_tags(*g, N);
  // chunk indices shifted by -1 vs the allreduce phase so the ring ends
  // with rank r owning fully-reduced chunk r (matches the API contract)
  for (int s = 0; s < N - 1; s++) {
    int send_c = ((g->rank - s - 1) % N + N) % N;
    int recv_c = ((g->rank - s - 2) % N + N) % N;
    int rc = ring_exchange(*g, tag + s, work.data() + send_c * nb, nb,
                           tmp.data(), nb);
    if (rc) return rc;
    reduce_buf(work.data() + recv_c * nb, tmp.data(), count, dtype, op);
  }
  memcpy(out, work.data() + g->rank * nb, static_cast<size_t>(nb));
  return 0;
}

// Binomial-tree broadcast from root.
int tc_broadcast(int h, void* data, int64_t count, int dtype, int root) {
  auto g = get_group(h);
  if (!g) return -EINVAL;
  if (g->world == 1) return 0;
  int N = g->world;
  int64_t nb = count * static_cast<int64_t>(dtype_size(dtype));
  int vrank = (g->rank - root + N) % N;  // root becomes virtual rank 0
  int64_t tag = take_tags(*g, 1);
  int mask = 1;
  while (mask < N) mask <<= 1;
  // binomial tree: at step `bit`, every rank that already holds the data
  // (vrank multiple of 2*bit) forwards to vrank+bit
  for (int bit = mask >> 1; bit >= 1; bit >>= 1) {
    if (vrank % (2 * bit) == 0) {
      int peer_v = vrank + bit;
      if (peer_v < N) {
        int peer = (peer_v + root) % N;
        int rc = send_frame(*g, peer, tag, data, nb);
        if (rc) return rc;
      }
    } else if (vrank % (2 * bit) == bit) {
      int peer = ((vrank - bit) + root) % N;
      int rc = recv_frame(*g, peer, tag, data, nb);
      if (rc) return rc;
    }
  }
  return 0;
}

int tc_barrier(int h) {
  int64_t x = 1;
  return tc_allreduce(h, &x, 1, I64, SUM);
}

int tc_send(int h, const void* data, int64_t nbytes, int dst, int tag) {
  auto g = get_group(h);
  if (!g || dst < 0 || dst >= g->world || dst == g->rank) return -EINVAL;
  return send_frame(*g, dst, tag, data, nbytes);
}

int tc_recv(int h, void* data, int64_t nbytes, int src, int tag) {
  auto g = get_group(h);
  if (!g || src < 0 || src >= g->world || src == g->rank) return -EINVAL;
  return recv_frame(*g, src, tag, data, nbytes);
}

// timeout_ms <= 0 blocks forever; returns -ETIMEDOUT on expiry.
int tc_recv_timeout(int h, void* data, int64_t nbytes, int src, int tag,
                    int timeout_ms) {
  auto g = get_group(h);
  if (!g || src < 0 || src >= g->world || src == g->rank) return -EINVAL;
  return recv_frame_t(*g, src, tag, data, nbytes, timeout_ms);
}

}  // extern "C"
