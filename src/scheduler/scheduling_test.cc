// Native unit driver for the hybrid scheduling policy (reference
// analog: raylet/scheduling/policy/hybrid_scheduling_policy_test.cc —
// gtest there; a dependency-free assert driver here, like
// store/store_test.cc). Build + run: `make -C src sched_test`; also run
// under ASan via `make -C src sched_asan` (part of `make sanitizers`;
// the policy is single-threaded so there is nothing for TSan to see).

#include <cassert>
#include <cstdio>
#include <cstring>
#include <set>
#include <vector>

extern "C" {
int sched_pick_node(const double* totals, const double* avails,
                    const unsigned char* alive,
                    const unsigned char* excluded, int n_nodes,
                    const double* demand, int n_kinds,
                    double spread_threshold, int top_k, unsigned int seed);
void sched_score_nodes(const double* totals, const double* avails,
                       const unsigned char* alive, int n_nodes,
                       const double* demand, int n_kinds,
                       double* scores_out);
}

namespace {

struct Fixture {
  // 2 resource kinds (CPU, TPU) x 4 nodes
  std::vector<double> totals;
  std::vector<double> avails;
  std::vector<unsigned char> alive;
  std::vector<unsigned char> excluded;
  int n = 4, k = 2;

  Fixture() {
    totals = {8, 0, /*n1*/ 8, 4, /*n2*/ 16, 0, /*n3*/ 8, 0};
    avails = {8, 0, /*n1*/ 8, 4, /*n2*/ 4, 0, /*n3*/ 2, 0};
    alive = {1, 1, 1, 1};
    excluded = {0, 0, 0, 0};
  }

  int pick(const std::vector<double>& demand, double spread = 0.5,
           int top_k = 1, unsigned seed = 0) {
    return sched_pick_node(totals.data(), avails.data(), alive.data(),
                           excluded.data(), n, demand.data(), k, spread,
                           top_k, seed);
  }
};

void test_prefers_emptiest_above_threshold() {
  Fixture f;
  // CPU demand 2: utilizations (with demand folded in) are
  // n0 2/8=0.25 n1 2/8=0.25 n2 (12+2)/16=0.875 n3 (6+2)/8=1.0;
  // spread 0.5 ties n0/n1 at the threshold; top_k=1 -> lowest index
  assert(f.pick({2, 0}) == 0);
}

void test_infeasible_returns_minus1() {
  Fixture f;
  assert(f.pick({32, 0}) == -1);       // no node has 32 total CPU
  assert(f.pick({0, 8}) == -1);        // no node has 8 TPU total
}

void test_feasible_but_busy_fallback() {
  Fixture f;
  // demand 6 CPU: only n2 (16 total) has... n0/n1/n3 total 8 >= 6 are
  // feasible; available: n0 (8) yes. Exclude n0/n1, drain n2/n3 avail.
  f.excluded[0] = f.excluded[1] = 1;
  f.avails = {8, 0, 8, 4, 4, 0, 2, 0};
  // n2 feasible (16 total) but only 4 avail < 6; n3 feasible(8) 2 avail
  assert(f.pick({6, 0}) == 2);         // first feasible-but-busy
}

void test_excluded_and_dead_skipped() {
  Fixture f;
  f.excluded[0] = 1;
  f.alive[1] = 0;
  // n0 excluded, n1 dead -> among n2 (0.875) and n3 (1.0) pick n2
  assert(f.pick({2, 0}) == 2);
}

void test_tpu_demand_routes_to_tpu_node() {
  Fixture f;
  assert(f.pick({1, 2}) == 1);         // only n1 has TPUs
}

void test_zero_demand_kind_still_penalizes_saturation() {
  Fixture f;
  // n1 TPUs fully used: a CPU-only task should prefer an idle CPU node
  f.avails[1 * 2 + 1] = 0;             // n1 TPU avail 0/4 -> util 1.0
  f.avails[0] = 8;                     // n0 idle
  int got = f.pick({2, 0}, /*spread=*/0.0);
  assert(got == 0);
}

void test_top_k_spreads_across_ties() {
  Fixture f;
  std::set<int> seen;
  for (unsigned seed = 0; seed < 64; seed++) {
    seen.insert(f.pick({2, 0}, 0.5, /*top_k=*/2, seed));
  }
  // n0 and n1 tie at the spread threshold; both must be reachable
  assert(seen.count(0) == 1 && seen.count(1) == 1);
  assert(seen.size() == 2);
}

void test_determinism_per_seed() {
  Fixture f;
  for (unsigned seed = 0; seed < 8; seed++) {
    int a = f.pick({2, 0}, 0.5, 3, seed);
    int b = f.pick({2, 0}, 0.5, 3, seed);
    assert(a == b);
  }
}

void test_huge_byte_quantities_no_overflow() {
  // memory-scale resources: 64 GB totals in BYTES must not overflow
  // the fixed-point micros representation
  std::vector<double> totals = {64e9, 64e9};
  std::vector<double> avails = {32e9, 8e9};
  std::vector<unsigned char> alive = {1, 1}, excluded = {0, 0};
  std::vector<double> demand = {16e9};
  int got = sched_pick_node(totals.data(), avails.data(), alive.data(),
                            excluded.data(), 2, demand.data(), 1, 0.0, 1,
                            0);
  assert(got == 0);                    // 0.75 util beats... n0 (32+16)/64
  // n0: (32+16)/64 = 0.75; n1: (56+16)/64 -> >1 clamped to 1.0
}

void test_score_nodes_matches_pick_ordering() {
  Fixture f;
  std::vector<double> demand = {2, 0};
  std::vector<double> scores(f.n);
  sched_score_nodes(f.totals.data(), f.avails.data(), f.alive.data(),
                    f.n, demand.data(), f.k, scores.data());
  assert(scores[0] == 0.25 && scores[1] == 0.25);
  assert(scores[2] > scores[1]);
  assert(scores[3] > scores[2]);
  // infeasible demand scores -1
  std::vector<double> big = {32, 0};
  sched_score_nodes(f.totals.data(), f.avails.data(), f.alive.data(),
                    f.n, big.data(), f.k, scores.data());
  for (int i = 0; i < f.n; i++) assert(scores[i] == -1.0);
}

}  // namespace

int main() {
  test_prefers_emptiest_above_threshold();
  test_infeasible_returns_minus1();
  test_feasible_but_busy_fallback();
  test_excluded_and_dead_skipped();
  test_tpu_demand_routes_to_tpu_node();
  test_zero_demand_kind_still_penalizes_saturation();
  test_top_k_spreads_across_ties();
  test_determinism_per_seed();
  test_huge_byte_quantities_no_overflow();
  test_score_nodes_matches_pick_ordering();
  std::printf("scheduling_test: all tests passed\n");
  return 0;
}
