// Scheduling policy library — C++ core of the node-selection path.
//
// Reference analog: src/ray/raylet/scheduling/policy/
// hybrid_scheduling_policy.cc:99-186 and the fixed-point resource
// arithmetic in src/ray/common/scheduling/ (FixedPoint, ResourceSet).
// The policy semantics mirror the reference's HybridSchedulingPolicy:
//   1. filter to alive, non-excluded nodes whose TOTAL resources fit the
//      demand (feasibility);
//   2. among nodes whose AVAILABLE resources fit, score each by
//      critical-resource utilization (max over resource kinds of
//      (used + demand) / total) — lower is better;
//   3. nodes scoring <= spread_threshold tie at the threshold (the
//      reference's clamp that spreads load instead of bin-packing onto
//      the emptiest node);
//   4. pick uniformly among the top_k best-scoring candidates
//      (top_k = max(1, min(top_k, #candidates)));
//   5. if nothing is available, fall back to the first feasible-but-busy
//      node; else report infeasible (-1).
//
// Resource QUANTITIES use fixed-point int64 micros (reference
// FixedPoint) for exact feasibility comparisons; utilization RATIOS are
// computed in double — a micros-scale multiply overflows int64 for
// byte-denominated resources like memory (64e9 * 1e6 >> 2^63).
// Exposed via C ABI for ctypes (no pybind11 in this image).

#include <cstddef>
#include <cstdint>
#include <utility>
#include <vector>

namespace {

constexpr double kScale = 1e6;  // fixed-point micros

int64_t fp(double x) {
  // clamp: 10TB-in-bytes scale quantities must not overflow the micros
  // representation (comparisons remain correct at the clamp)
  double scaled = x * kScale;
  constexpr double kMax = 9.0e18;
  if (scaled >= kMax) return static_cast<int64_t>(kMax);
  if (scaled <= -kMax) return -static_cast<int64_t>(kMax);
  return static_cast<int64_t>(scaled + 0.5);
}

}  // namespace

extern "C" {

// totals/avails: [n_nodes * n_kinds] row-major; demand: [n_kinds].
// alive/exclude: per-node flags. Returns the chosen node index or -1.
// Deterministic for a given seed (seed only matters when top_k > 1).
int sched_pick_node(const double* totals, const double* avails,
                    const unsigned char* alive,
                    const unsigned char* excluded, int n_nodes,
                    const double* demand, int n_kinds,
                    double spread_threshold, int top_k,
                    unsigned int seed) {
  std::vector<int64_t> dem(n_kinds);
  bool zero_demand = true;
  for (int k = 0; k < n_kinds; k++) {
    dem[k] = fp(demand[k]);
    if (dem[k] > 0) zero_demand = false;
  }
  (void)zero_demand;

  struct Cand {
    int node;
    double score;
  };
  std::vector<Cand> cands;
  int feasible_busy = -1;

  for (int i = 0; i < n_nodes; i++) {
    if (!alive[i] || excluded[i]) continue;
    const double* tot = totals + static_cast<int64_t>(i) * n_kinds;
    const double* avl = avails + static_cast<int64_t>(i) * n_kinds;
    bool feasible = true, available = true;
    double crit = 0.0;  // max over kinds of (used + demand) / total
    for (int k = 0; k < n_kinds; k++) {
      int64_t t = fp(tot[k]);
      int64_t a = fp(avl[k]);
      if (dem[k] > 0) {
        if (t < dem[k]) {
          feasible = false;
          break;
        }
        if (a < dem[k]) available = false;
      } else if (t <= 0) {
        continue;  // kind absent on the node AND not demanded: ignore
      }
      // zero-demand kinds still contribute their utilization (matches
      // the Python policy: a TPU-saturated node scores worse even for
      // num_tpus=0 tasks)
      double util = t <= 0 ? 0.0
                           : static_cast<double>((t - a) + dem[k])
                                 / static_cast<double>(t);
      if (util > 1.0) util = 1.0;
      if (util > crit) crit = util;
    }
    if (!feasible) continue;
    if (!available) {
      if (feasible_busy < 0) feasible_busy = i;
      continue;
    }
    // spread clamp: everything at or below the threshold ties
    double clamped = crit <= spread_threshold ? spread_threshold : crit;
    cands.push_back({i, clamped});
  }

  if (cands.empty()) return feasible_busy;

  // partial sort by (score, node index) for determinism
  for (size_t i = 0; i < cands.size(); i++) {
    size_t best = i;
    for (size_t j = i + 1; j < cands.size(); j++) {
      if (cands[j].score < cands[best].score ||
          (cands[j].score == cands[best].score &&
           cands[j].node < cands[best].node)) {
        best = j;
      }
    }
    if (best != i) std::swap(cands[i], cands[best]);
  }
  int k = top_k < 1 ? 1 : top_k;
  if (static_cast<size_t>(k) > cands.size())
    k = static_cast<int>(cands.size());
  // splitmix-style mixer: one xorshift round is linear enough that
  // small consecutive seeds all collapse to the same residue mod small k
  unsigned int x = seed + 0x9E3779B9u;
  x ^= x >> 16;
  x *= 0x85EBCA6Bu;
  x ^= x >> 13;
  x *= 0xC2B2AE35u;
  x ^= x >> 16;
  return cands[x % k].node;
}

// Batch scoring helper (autoscaler / tests): writes per-node critical
// utilization (or -1 when infeasible) into scores_out [n_nodes].
void sched_score_nodes(const double* totals, const double* avails,
                       const unsigned char* alive, int n_nodes,
                       const double* demand, int n_kinds,
                       double* scores_out) {
  for (int i = 0; i < n_nodes; i++) {
    scores_out[i] = -1.0;
    if (!alive[i]) continue;
    const double* tot = totals + static_cast<int64_t>(i) * n_kinds;
    const double* avl = avails + static_cast<int64_t>(i) * n_kinds;
    bool feasible = true;
    double crit = 0.0;
    for (int k = 0; k < n_kinds; k++) {
      int64_t d = fp(demand[k]);
      int64_t t = fp(tot[k]);
      if (d > 0 && t < d) {
        feasible = false;
        break;
      }
      if (t <= 0) continue;
      double util = static_cast<double>((t - fp(avl[k])) + d)
                    / static_cast<double>(t);
      if (util > 1.0) util = 1.0;
      if (util > crit) crit = util;
    }
    if (feasible) scores_out[i] = crit;
  }
}

}  // extern "C"
