// Native unit/stress driver for the shm object store, built under
// AddressSanitizer and ThreadSanitizer by `make asan` / `make tsan`
// (SURVEY §5 race-detection row; reference: the C++ unit suites run
// under sanitizer configs in CI).
//
// Exercises: create/seal/get/release, first-write-wins, abort, delete
// refcount guards, and a multi-threaded reader/writer/deleter storm
// over one segment attached per-thread — the paths where a data race
// or lifetime bug in the allocator/table would surface.

#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

// the store's C ABI (keep in sync with ray_tpu/_private/shm_store.py)
extern "C" {
void* store_create(const char* name, uint64_t capacity, uint64_t table_cap);
void* store_attach(const char* name);
void store_close(void* sp);
uint8_t* store_base(void* sp);
int store_create_object(void* sp, const uint8_t* id, uint64_t data_size,
                        uint64_t meta_size, uint64_t* offset_out);
int store_seal(void* sp, const uint8_t* id);
int store_get(void* sp, const uint8_t* id, int64_t timeout_ms,
              uint64_t* offset_out, uint64_t* data_size_out,
              uint64_t* meta_size_out);
int store_release(void* sp, const uint8_t* id);
int store_abort(void* sp, const uint8_t* id);
int store_delete(void* sp, const uint8_t* id);
int store_contains(void* sp, const uint8_t* id);
int store_get_many(void* sp, const uint8_t* ids, int n, uint64_t* offs,
                   uint64_t* dszs, int* rcs);
int store_release_many(void* sp, const uint8_t* ids, int n);
}

enum { TS_OK = 0, TS_ERR = -1, TS_EXISTS = -2, TS_NOT_FOUND = -3 };

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,   \
                   __LINE__, #cond);                                  \
      std::exit(1);                                                   \
    }                                                                 \
  } while (0)

// store keys are 20 bytes (ray_tpu/_private/shm_store.py ID_LEN)
static void fill_oid(uint8_t* oid, int v) {
  std::memset(oid, 0, 20);
  std::memcpy(oid, &v, sizeof(v));
}

static int put(void* s, const uint8_t* oid, const uint8_t* data,
               uint64_t n) {
  uint64_t off = 0;
  int rc = store_create_object(s, oid, n, 0, &off);
  if (rc != TS_OK) return rc;
  std::memcpy(store_base(s) + off, data, n);
  return store_seal(s, oid);
}

int main() {
  char name[64];
  std::snprintf(name, sizeof(name), "/raytpu_sani_%d", (int)getpid());
  void* store = store_create(name, 64ull << 20, 4096);
  CHECK(store != nullptr);

  // basic put/get round trip
  uint8_t oid[20];
  fill_oid(oid, 1);
  uint8_t payload[256];
  for (int i = 0; i < 256; ++i) payload[i] = (uint8_t)i;
  CHECK(put(store, oid, payload, sizeof(payload)) == TS_OK);
  uint64_t off = 0, dsz = 0, msz = 0;
  CHECK(store_get(store, oid, 0, &off, &dsz, &msz) == TS_OK);
  CHECK(dsz == 256);
  CHECK(std::memcmp(store_base(store) + off, payload, 256) == 0);
  CHECK(store_release(store, oid) == TS_OK);

  // first write wins
  CHECK(put(store, oid, payload, 8) == TS_EXISTS);

  // abort of an unsealed object frees the slot
  uint8_t oid2[20];
  fill_oid(oid2, 2);
  uint64_t off2 = 0;
  CHECK(store_create_object(store, oid2, 64, 0, &off2) == TS_OK);
  CHECK(store_abort(store, oid2) == TS_OK);
  CHECK(store_contains(store, oid2) == 0);

  // a held reader blocks delete; release then delete succeeds
  CHECK(store_get(store, oid, 0, &off, &dsz, &msz) == TS_OK);
  CHECK(store_delete(store, oid) != TS_OK);
  CHECK(store_release(store, oid) == TS_OK);
  CHECK(store_delete(store, oid) == TS_OK);
  CHECK(store_contains(store, oid) == 0);

  // batched get/release: hits + a miss in one call; duplicate ids hold
  // one ref each (release_many must drop all of them before delete)
  uint8_t oid3[20];
  fill_oid(oid3, 3);
  CHECK(put(store, oid3, payload, 32) == TS_OK);
  uint8_t batch_ids[4 * 20];
  fill_oid(batch_ids + 0, 3);
  fill_oid(batch_ids + 20, 999);   // absent
  fill_oid(batch_ids + 40, 3);     // duplicate
  fill_oid(batch_ids + 60, 3);
  uint64_t offs[4], dszs[4];
  int rcs[4];
  CHECK(store_get_many(store, batch_ids, 4, offs, dszs, rcs) == TS_OK);
  CHECK(rcs[0] == TS_OK && rcs[2] == TS_OK && rcs[3] == TS_OK);
  CHECK(rcs[1] == TS_NOT_FOUND);
  CHECK(dszs[0] == 32 && offs[0] == offs[2]);
  CHECK(store_delete(store, oid3) != TS_OK);   // 3 refs held
  CHECK(store_release_many(store, batch_ids, 4) == TS_OK);  // absent: no-op
  CHECK(store_delete(store, oid3) == TS_OK);

  // concurrent storm: writers create distinct objects, readers chase a
  // neighbor's objects, deleters race over a shared range — each
  // thread attaches its OWN handle, like real worker processes
  constexpr int kThreads = 4;
  constexpr int kObjects = 200;
  std::atomic<int> errors{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&, t] {
      void* s = store_attach(name);
      if (!s) {
        errors.fetch_add(1);
        return;
      }
      uint8_t o[20];
      uint8_t buf[512];
      std::memset(buf, t + 1, sizeof(buf));
      for (int i = 0; i < kObjects; ++i) {
        fill_oid(o, 1000 + t * kObjects + i);
        if (put(s, o, buf, sizeof(buf)) != TS_OK) errors.fetch_add(1);
        // BATCH-read two of a NEIGHBOR thread's recent objects, if
        // they exist yet (the driver's hot get([...]) path under TSan)
        uint8_t pair[2 * 20];
        fill_oid(pair, 1000 + ((t + 1) % kThreads) * kObjects + (i / 2));
        fill_oid(pair + 20,
                 1000 + ((t + 1) % kThreads) * kObjects + (i / 4));
        uint64_t ros[2], rds[2];
        int rrcs[2];
        store_get_many(s, pair, 2, ros, rds, rrcs);
        uint8_t rel[2 * 20];
        int nrel = 0;
        for (int k = 0; k < 2; ++k) {
          if (rrcs[k] == TS_OK) {
            volatile uint8_t sink = store_base(s)[ros[k]];
            (void)sink;
            std::memcpy(rel + nrel * 20, pair + k * 20, 20);
            ++nrel;
          }
        }
        if (nrel) store_release_many(s, rel, nrel);
        // race create/delete over a small shared id range
        fill_oid(o, 5000 + (i % 32));
        put(s, o, buf, 64);
        store_delete(s, o);
      }
      store_close(s);
    });
  }
  for (auto& th : threads) th.join();
  CHECK(errors.load() == 0);

  store_close(store);
  std::printf("store_test ok\n");
  return 0;
}
