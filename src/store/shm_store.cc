// shm_store: TPU-host shared-memory object store (reference analog:
// src/ray/object_manager/plasma/ — PlasmaStore store.h:55,
// ObjectLifecycleManager object_lifecycle_manager.h:101, LRU eviction
// eviction_policy.h:105,160, dlmalloc arena dlmalloc.cc).
//
// Design departure from the reference: instead of a store daemon serving a
// UDS protocol, ALL control state (object table, free list, lock, condvar)
// lives inside the shared segment itself, guarded by a robust process-shared
// mutex. Every process (node manager, workers) attaches the segment and
// operates on it directly — zero IPC round-trips on the create/seal/get hot
// path, which matters on TPU hosts where the store feeds host→HBM transfers.
//
// Layout:
//   [Header][ObjectEntry x table_cap][data arena ............]
// Free blocks form an offset-sorted singly linked list (offsets relative to
// arena start) enabling O(n) first-fit alloc with coalescing on free.
// Objects are created (writable), sealed (immutable, readers may map), and
// evicted LRU-wise among sealed refcount==0 entries when allocation fails.

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <utility>
#include <vector>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x7470755f73746f72ULL;  // "tpu_stor"
constexpr uint32_t kIdLen = 20;
constexpr uint64_t kMinBlock = 64;
constexpr uint64_t kAlign = 64;  // cache-line align objects

enum EntryState : uint32_t {
  kEmpty = 0,
  kCreated = 1,   // allocated, writer filling
  kSealed = 2,    // immutable, readable
  kTombstone = 3, // deleted slot (probe chains continue through it)
};

constexpr uint32_t kReaderSlots = 4;

struct ObjectEntry {
  uint32_t state;
  uint32_t _pad;
  uint64_t refcount;
  uint64_t offset;     // relative to arena start
  uint64_t data_size;
  uint64_t meta_size;  // metadata bytes appended after data
  uint64_t lru_tick;
  uint64_t writer_pid;  // creator process; orphan GC is scoped to dead pids
  // Per-pid reader accounting so refs held by crashed readers can be
  // reclaimed (reference: plasma's per-client disconnect cleanup). Refs
  // beyond kReaderSlots distinct pids land in untracked_refs (permanent
  // until released normally).
  uint64_t reader_pids[kReaderSlots];
  uint32_t reader_counts[kReaderSlots];
  uint64_t untracked_refs;
  uint8_t id[kIdLen];
  uint8_t _pad2[4];
};

struct FreeBlock {
  uint64_t size;
  uint64_t next;  // offset of next free block, ~0ull = none
};
constexpr uint64_t kNone = ~0ull;

struct Header {
  uint64_t magic;
  uint64_t segment_size;
  uint64_t arena_offset;   // from segment base
  uint64_t arena_size;
  uint64_t table_cap;
  uint64_t free_head;      // offset into arena, kNone if empty
  uint64_t lru_clock;
  uint64_t num_tombstones;
  // stats
  uint64_t bytes_allocated;
  uint64_t num_objects;
  uint64_t num_evictions;
  uint64_t bytes_evicted;
  pthread_mutex_t mu;
  pthread_cond_t cv;       // signalled on seal/delete (waiters: Get blocking)
};

struct Store {
  int fd;
  uint8_t* base;
  uint64_t size;
  Header* hdr;
  ObjectEntry* table;
  uint8_t* arena;
  char name[256];
  bool owner;
};

uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

uint64_t id_hash(const uint8_t* id) {
  // FNV-1a over the 20-byte id.
  uint64_t h = 14695981039346656037ULL;
  for (uint32_t i = 0; i < kIdLen; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

void lock(Header* h) {
  int rc = pthread_mutex_lock(&h->mu);
  if (rc == EOWNERDEAD) {
    // A process died holding the lock. State may be mid-mutation; we accept
    // the (already-sealed-consistent) table and continue — created-but-
    // unsealed entries of the dead process are garbage-collected by
    // store_evict_orphans from the node manager.
    pthread_mutex_consistent(&h->mu);
  }
}
void unlock(Header* h) { pthread_mutex_unlock(&h->mu); }

// Find entry slot; returns index or table_cap if absent.
uint64_t find(Store* s, const uint8_t* id) {
  Header* h = s->hdr;
  uint64_t cap = h->table_cap;
  uint64_t i = id_hash(id) % cap;
  for (uint64_t probes = 0; probes < cap; probes++, i = (i + 1) % cap) {
    ObjectEntry& e = s->table[i];
    if (e.state == kEmpty) return cap;
    if (e.state != kTombstone && memcmp(e.id, id, kIdLen) == 0) return i;
  }
  return cap;
}

// Find slot for insert (first empty/tombstone), or table_cap if full.
uint64_t find_insert(Store* s, const uint8_t* id) {
  Header* h = s->hdr;
  uint64_t cap = h->table_cap;
  uint64_t i = id_hash(id) % cap;
  uint64_t first_tomb = cap;
  for (uint64_t probes = 0; probes < cap; probes++, i = (i + 1) % cap) {
    ObjectEntry& e = s->table[i];
    if (e.state == kEmpty)
      return first_tomb != cap ? first_tomb : i;
    if (e.state == kTombstone) {
      if (first_tomb == cap) first_tomb = i;
    } else if (memcmp(e.id, id, kIdLen) == 0) {
      return cap;  // already exists
    }
  }
  return first_tomb;
}

FreeBlock* fb(Store* s, uint64_t off) {
  return reinterpret_cast<FreeBlock*>(s->arena + off);
}

// Insert block into offset-sorted free list, coalescing neighbours.
void free_insert(Store* s, uint64_t off, uint64_t size) {
  Header* h = s->hdr;
  uint64_t prev = kNone, cur = h->free_head;
  while (cur != kNone && cur < off) {
    prev = cur;
    cur = fb(s, cur)->next;
  }
  // coalesce with next
  if (cur != kNone && off + size == cur) {
    size += fb(s, cur)->size;
    cur = fb(s, cur)->next;
  }
  // coalesce with prev
  if (prev != kNone && prev + fb(s, prev)->size == off) {
    fb(s, prev)->size += size;
    fb(s, prev)->next = cur;
    return;
  }
  FreeBlock* nb = fb(s, off);
  nb->size = size;
  nb->next = cur;
  if (prev == kNone)
    h->free_head = off;
  else
    fb(s, prev)->next = off;
}

// First-fit allocation; returns offset or kNone.
uint64_t arena_alloc(Store* s, uint64_t size) {
  Header* h = s->hdr;
  size = align_up(size < kMinBlock ? kMinBlock : size, kAlign);
  uint64_t prev = kNone, cur = h->free_head;
  while (cur != kNone) {
    FreeBlock* b = fb(s, cur);
    if (b->size >= size) {
      uint64_t remain = b->size - size;
      uint64_t next = b->next;
      if (remain >= kMinBlock) {
        uint64_t split = cur + size;
        FreeBlock* sb = fb(s, split);
        sb->size = remain;
        sb->next = next;
        next = split;
      } else {
        size = b->size;  // absorb the tail fragment
      }
      if (prev == kNone)
        h->free_head = next;
      else
        fb(s, prev)->next = next;
      h->bytes_allocated += size;
      return cur;
    }
    prev = cur;
    cur = b->next;
  }
  return kNone;
}

void entry_free(Store* s, ObjectEntry& e) {
  uint64_t total = align_up(
      (e.data_size + e.meta_size) < kMinBlock ? kMinBlock
                                              : (e.data_size + e.meta_size),
      kAlign);
  free_insert(s, e.offset, total);
  s->hdr->bytes_allocated -= total;
  e.state = kTombstone;
  s->hdr->num_objects--;
  s->hdr->num_tombstones++;
}

// Once tombstones dominate, probe chains never hit kEmpty and every lookup
// degrades to a full-table scan. Rebuild the table in place: copy live
// entries aside, clear, reinsert. Caller holds the lock.
void maybe_rehash(Store* s) {
  Header* h = s->hdr;
  if (h->num_tombstones < h->table_cap / 4) return;
  std::vector<ObjectEntry> live;
  live.reserve(h->num_objects);
  for (uint64_t i = 0; i < h->table_cap; i++) {
    if (s->table[i].state == kCreated || s->table[i].state == kSealed)
      live.push_back(s->table[i]);
  }
  memset(s->table, 0, h->table_cap * sizeof(ObjectEntry));
  for (ObjectEntry& e : live) {
    uint64_t i = id_hash(e.id) % h->table_cap;
    while (s->table[i].state != kEmpty) i = (i + 1) % h->table_cap;
    s->table[i] = e;
  }
  h->num_tombstones = 0;
}

// Evict LRU sealed refcount-0 objects until `needed` bytes can be allocated.
// Caller holds the lock. Returns true if an eviction happened. One table
// scan collects candidates LRU-first; victims are freed in order until the
// allocation fits (avoids rescanning the table per victim).
bool evict_for(Store* s, uint64_t needed) {
  Header* h = s->hdr;
  std::vector<std::pair<uint64_t, uint64_t>> candidates;  // (tick, idx)
  for (uint64_t i = 0; i < h->table_cap; i++) {
    ObjectEntry& e = s->table[i];
    if (e.state == kSealed && e.refcount == 0)
      candidates.emplace_back(e.lru_tick, i);
  }
  std::sort(candidates.begin(), candidates.end());
  bool any = false;
  for (auto& [tick, idx] : candidates) {
    uint64_t off = arena_alloc(s, needed);
    if (off != kNone) {
      uint64_t size =
          align_up(needed < kMinBlock ? kMinBlock : needed, kAlign);
      free_insert(s, off, size);
      h->bytes_allocated -= size;
      return true;
    }
    ObjectEntry& e = s->table[idx];
    h->num_evictions++;
    h->bytes_evicted += e.data_size + e.meta_size;
    entry_free(s, e);
    any = true;
  }
  return any;
}

// Attribute one reference to `pid` in the entry's reader slots. An existing
// slot for this pid ALWAYS wins over an earlier empty slot — otherwise one
// pid can end up spread across two slots (or share a slot with its own
// pin), which breaks every "sum of this pid's refs" consumer
// (store_spill_candidates' pinned test, release_pid cleanup).
bool track_reader(ObjectEntry& e, uint64_t pid) {
  int empty = -1;
  for (uint32_t k = 0; k < kReaderSlots; k++) {
    if (e.reader_pids[k] == pid) {
      e.reader_counts[k]++;
      return true;
    }
    if (empty < 0 && e.reader_pids[k] == 0 && e.reader_counts[k] == 0)
      empty = (int)k;
  }
  if (empty >= 0) {
    e.reader_pids[empty] = pid;
    e.reader_counts[empty] = 1;
    return true;
  }
  return false;
}

// Bump a sealed entry's refcount for `pid` and touch its LRU tick — the
// shared hit core of store_get and store_get_many (caller holds the lock).
void acquire_locked(Header* h, ObjectEntry& e, uint64_t pid) {
  e.refcount++;
  if (!track_reader(e, pid)) e.untracked_refs++;
  e.lru_tick = ++h->lru_clock;
}

// Drop one of `pid`'s references — the shared core of store_release and
// store_release_many (caller holds the lock).
void release_locked(ObjectEntry& e, uint64_t pid) {
  if (e.refcount > 0) e.refcount--;
  bool tracked = false;
  for (uint32_t k = 0; k < kReaderSlots; k++) {
    if (e.reader_pids[k] == pid && e.reader_counts[k] > 0) {
      if (--e.reader_counts[k] == 0) e.reader_pids[k] = 0;
      tracked = true;
      break;
    }
  }
  if (!tracked && e.untracked_refs > 0) e.untracked_refs--;
}

}  // namespace

extern "C" {

// Status codes (keep in sync with ray_tpu/_private/shm_store.py).
enum {
  TS_OK = 0,
  TS_ERR = -1,
  TS_EXISTS = -2,
  TS_NOT_FOUND = -3,
  TS_OOM = -4,
  TS_TABLE_FULL = -5,
  TS_NOT_SEALED = -6,
  TS_TIMEOUT = -7,
};

void* store_create(const char* name, uint64_t capacity, uint64_t table_cap) {
  if (table_cap == 0) table_cap = 1 << 16;
  if (capacity < (1 << 12)) return nullptr;  // degenerate arena
  shm_unlink(name);  // fresh segment
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t table_bytes = table_cap * sizeof(ObjectEntry);
  uint64_t arena_off = align_up(sizeof(Header) + table_bytes, kAlign);
  uint64_t total = arena_off + capacity;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  uint8_t* base = (uint8_t*)mmap(nullptr, total, PROT_READ | PROT_WRITE,
                                 MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  // Write-touch every page NOW: tmpfs allocates pages on the first
  // WRITE fault, so without this a cold large put runs fault-bound
  // (~1.9 GB/s measured for a fresh 64 MiB object vs ~6.7 GB/s on
  // materialized pages). One memset per store boot (~0.2 s/GiB) buys
  // warm-page bandwidth for every subsequent create/put; attaching
  // processes only take cheap minor faults on the existing pages.
  // (MAP_POPULATE is not enough: it read-faults tmpfs holes without
  // allocating backing pages for writes.)
  memset(base, 0, total);
  Header* h = (Header*)base;
  memset(h, 0, sizeof(Header));
  h->segment_size = total;
  h->arena_offset = arena_off;
  h->arena_size = capacity;
  h->table_cap = table_cap;
  memset(base + sizeof(Header), 0, table_bytes);

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_condattr_setclock(&ca, CLOCK_MONOTONIC);
  pthread_cond_init(&h->cv, &ca);

  Store* s = new Store();
  s->fd = fd;
  s->base = base;
  s->size = total;
  s->hdr = h;
  s->table = (ObjectEntry*)(base + sizeof(Header));
  s->arena = base + arena_off;
  snprintf(s->name, sizeof(s->name), "%s", name);
  s->owner = true;
  // one big free block
  h->free_head = 0;
  FreeBlock* b = fb(s, 0);
  b->size = capacity;
  b->next = kNone;
  h->magic = kMagic;  // publish last
  return s;
}

void* store_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  // MAP_POPULATE on attach: the creator materialized every page (see
  // create's memset); prefaulting this process's PTEs up front turns
  // per-page minor faults on first access — the residual large-put
  // cost for attached writers — into one bulk populate at attach time.
  int attach_flags = MAP_SHARED;
#ifdef MAP_POPULATE
  attach_flags |= MAP_POPULATE;
#endif
  uint8_t* base = (uint8_t*)mmap(nullptr, (size_t)st.st_size,
                                 PROT_READ | PROT_WRITE, attach_flags,
                                 fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Header* h = (Header*)base;
  if (h->magic != kMagic) {
    munmap(base, (size_t)st.st_size);
    close(fd);
    return nullptr;
  }
  Store* s = new Store();
  s->fd = fd;
  s->base = base;
  s->size = (uint64_t)st.st_size;
  s->hdr = h;
  s->table = (ObjectEntry*)(base + sizeof(Header));
  s->arena = base + h->arena_offset;
  snprintf(s->name, sizeof(s->name), "%s", name);
  s->owner = false;
  return s;
}

void store_close(void* sp) {
  Store* s = (Store*)sp;
  munmap(s->base, s->size);
  close(s->fd);
  if (s->owner) shm_unlink(s->name);
  delete s;
}

uint8_t* store_base(void* sp) { return ((Store*)sp)->arena; }
uint64_t store_capacity(void* sp) { return ((Store*)sp)->hdr->arena_size; }

// Allocate an object; on success writes offset (relative to arena base).
int store_create_object(void* sp, const uint8_t* id, uint64_t data_size,
                        uint64_t meta_size, uint64_t* offset_out) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  uint64_t total = data_size + meta_size;
  if (total > h->arena_size) return TS_OOM;
  lock(h);
  maybe_rehash(s);
  if (find(s, id) != h->table_cap) {
    unlock(h);
    return TS_EXISTS;
  }
  uint64_t slot = find_insert(s, id);
  if (slot == h->table_cap) {
    unlock(h);
    return TS_TABLE_FULL;
  }
  uint64_t off = arena_alloc(s, total);
  if (off == kNone) {
    if (!evict_for(s, total)) {
      unlock(h);
      return TS_OOM;
    }
    off = arena_alloc(s, total);
    if (off == kNone) {
      unlock(h);
      return TS_OOM;
    }
    // eviction may have tombstoned earlier probes; re-find slot
    slot = find_insert(s, id);
    if (slot == h->table_cap) {
      unlock(h);
      return TS_TABLE_FULL;
    }
  }
  ObjectEntry& e = s->table[slot];
  memset(&e, 0, sizeof(e));
  memcpy(e.id, id, kIdLen);
  e.state = kCreated;
  e.refcount = 1;  // writer holds a ref until seal+release
  e.offset = off;
  e.data_size = data_size;
  e.meta_size = meta_size;
  e.lru_tick = ++h->lru_clock;
  e.writer_pid = (uint64_t)getpid();
  h->num_objects++;
  unlock(h);
  *offset_out = off;
  return TS_OK;
}

int store_seal(void* sp, const uint8_t* id) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  lock(h);
  uint64_t i = find(s, id);
  if (i == h->table_cap) {
    unlock(h);
    return TS_NOT_FOUND;
  }
  ObjectEntry& e = s->table[i];
  if (e.state != kCreated) {
    unlock(h);
    return TS_ERR;
  }
  e.state = kSealed;
  if (e.refcount > 0) e.refcount--;  // drop writer ref
  pthread_cond_broadcast(&h->cv);
  unlock(h);
  return TS_OK;
}

// Seal WITHOUT dropping to refcount 0: the writer's ref converts into a
// tracked reader ref, so there is NO window in which the freshly sealed
// object is evictable before the node manager pins it (the writer releases
// its hold after reporting the object). The hold is attributed to the
// writer's pid in the reader slots so crash cleanup (store_release_pid)
// reclaims it.
int store_seal_hold(void* sp, const uint8_t* id) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  lock(h);
  uint64_t i = find(s, id);
  if (i == h->table_cap) {
    unlock(h);
    return TS_NOT_FOUND;
  }
  ObjectEntry& e = s->table[i];
  if (e.state != kCreated) {
    unlock(h);
    return TS_ERR;
  }
  e.state = kSealed;
  // keep refcount as-is (writer ref becomes the hold); attribute it
  if (!track_reader(e, (uint64_t)getpid())) e.untracked_refs++;
  pthread_cond_broadcast(&h->cv);
  unlock(h);
  return TS_OK;
}

// Get a sealed object: bumps refcount, returns offset/sizes.
// timeout_ms < 0: non-blocking. timeout_ms >= 0 waits for seal.
int store_get(void* sp, const uint8_t* id, int64_t timeout_ms,
              uint64_t* offset_out, uint64_t* data_size_out,
              uint64_t* meta_size_out) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  struct timespec deadline;
  if (timeout_ms >= 0) {
    clock_gettime(CLOCK_MONOTONIC, &deadline);
    deadline.tv_sec += timeout_ms / 1000;
    deadline.tv_nsec += (timeout_ms % 1000) * 1000000L;
    if (deadline.tv_nsec >= 1000000000L) {
      deadline.tv_sec += 1;
      deadline.tv_nsec -= 1000000000L;
    }
  }
  lock(h);
  for (;;) {
    uint64_t i = find(s, id);
    if (i != h->table_cap && s->table[i].state == kSealed) {
      ObjectEntry& e = s->table[i];
      // records this reader's pid so a crash can be cleaned up
      acquire_locked(h, e, (uint64_t)getpid());
      *offset_out = e.offset;
      *data_size_out = e.data_size;
      *meta_size_out = e.meta_size;
      unlock(h);
      return TS_OK;
    }
    if (timeout_ms < 0) {
      unlock(h);
      return TS_NOT_FOUND;
    }
    int rc = pthread_cond_timedwait(&h->cv, &h->mu, &deadline);
    if (rc == ETIMEDOUT) {
      unlock(h);
      return TS_TIMEOUT;
    }
    if (rc == EOWNERDEAD) pthread_mutex_consistent(&h->mu);
  }
}

int store_release(void* sp, const uint8_t* id) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  lock(h);
  uint64_t i = find(s, id);
  if (i == h->table_cap) {
    unlock(h);
    return TS_NOT_FOUND;
  }
  release_locked(s->table[i], (uint64_t)getpid());
  unlock(h);
  return TS_OK;
}

// Drop all refs held by a (dead) process on every entry. The raylet calls
// this when a worker dies, so crashed readers cannot pin objects forever
// (reference: plasma per-client disconnect cleanup).
int store_release_pid(void* sp, uint64_t pid) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  lock(h);
  int n = 0;
  for (uint64_t i = 0; i < h->table_cap; i++) {
    ObjectEntry& e = s->table[i];
    if (e.state != kCreated && e.state != kSealed) continue;
    for (uint32_t k = 0; k < kReaderSlots; k++) {
      if (e.reader_pids[k] == pid && e.reader_counts[k] > 0) {
        uint64_t drop = e.reader_counts[k];
        e.refcount = e.refcount >= drop ? e.refcount - drop : 0;
        e.reader_counts[k] = 0;
        e.reader_pids[k] = 0;
        n += (int)drop;
      }
    }
  }
  unlock(h);
  return n;
}

// Abort a CREATED (unsealed) entry owned by the calling writer — the
// cleanup path for a failed chunked pull/write. Refuses sealed entries
// and other writers' allocations.
int store_abort(void* sp, const uint8_t* id) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  lock(h);
  uint64_t i = find(s, id);
  if (i == h->table_cap) {
    unlock(h);
    return TS_NOT_FOUND;
  }
  ObjectEntry& e = s->table[i];
  if (e.state != kCreated || e.writer_pid != (uint64_t)getpid()) {
    unlock(h);
    return TS_ERR;
  }
  entry_free(s, e);
  pthread_cond_broadcast(&h->cv);
  unlock(h);
  return TS_OK;
}

int store_delete(void* sp, const uint8_t* id) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  lock(h);
  uint64_t i = find(s, id);
  if (i == h->table_cap) {
    unlock(h);
    return TS_NOT_FOUND;
  }
  ObjectEntry& e = s->table[i];
  if (e.refcount > 0) {
    unlock(h);
    return TS_ERR;  // still referenced
  }
  entry_free(s, e);
  pthread_cond_broadcast(&h->cv);
  unlock(h);
  return TS_OK;
}

int store_contains(void* sp, const uint8_t* id) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  lock(h);
  uint64_t i = find(s, id);
  int sealed = (i != h->table_cap && s->table[i].state == kSealed) ? 1 : 0;
  unlock(h);
  return sealed;
}

// Batched non-blocking get: ONE lock acquisition resolves n ids (the
// driver's hot get([...]) path — per-object store_get/_release/_contains
// round trips dominate a 1 KiB get). ids = n*kIdLen key bytes; per-id
// results in offs/dszs/rcs (TS_OK or TS_NOT_FOUND). A hit bumps
// refcount + reader tracking exactly like store_get.
int store_get_many(void* sp, const uint8_t* ids, int n,
                   uint64_t* offs, uint64_t* dszs, int* rcs) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  uint64_t pid = (uint64_t)getpid();
  lock(h);
  for (int k = 0; k < n; k++) {
    uint64_t i = find(s, ids + (uint64_t)k * kIdLen);
    if (i == h->table_cap || s->table[i].state != kSealed) {
      rcs[k] = TS_NOT_FOUND;
      continue;
    }
    ObjectEntry& e = s->table[i];
    acquire_locked(h, e, pid);
    offs[k] = e.offset;
    dszs[k] = e.data_size;
    rcs[k] = TS_OK;
  }
  unlock(h);
  return TS_OK;
}

// Symmetric batched release for store_get_many hits.
int store_release_many(void* sp, const uint8_t* ids, int n) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  uint64_t pid = (uint64_t)getpid();
  lock(h);
  for (int k = 0; k < n; k++) {
    uint64_t i = find(s, ids + (uint64_t)k * kIdLen);
    if (i == h->table_cap) continue;
    release_locked(s->table[i], pid);
  }
  unlock(h);
  return TS_OK;
}

// Drop created-but-never-sealed entries of crashed writers. pid == 0 means
// "any writer" (store-owner cleanup); otherwise only entries created by
// that (now dead) pid are reclaimed, so live writers mid-put are safe.
int store_evict_orphans(void* sp, uint64_t pid) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  lock(h);
  int n = 0;
  for (uint64_t i = 0; i < h->table_cap; i++) {
    ObjectEntry& e = s->table[i];
    if (e.state == kCreated && (pid == 0 || e.writer_pid == pid)) {
      e.refcount = 0;
      entry_free(s, e);
      n++;
    }
  }
  pthread_cond_broadcast(&h->cv);
  unlock(h);
  return n;
}

// Collect LRU spill candidates, oldest-first, until their total payload
// bytes reach `target_bytes` or `max_out` ids are written. A sealed entry
// qualifies when its only refs are the node manager's pin (pin_pid != 0:
// refcount equals the refs held by pin_pid; pin_pid == 0: refcount == 0).
// Writes 20-byte ids consecutively into out_ids (caller provides
// max_out*20 bytes) and returns the count. The entries are NOT freed —
// the node manager copies them to external storage first, then unpins and
// calls store_delete (reference: LocalObjectManager::SpillObjects picks
// pinned-but-unused victims from plasma and deletes after the spill IO
// completes, local_object_manager.h:110).
int store_spill_candidates(void* sp, uint64_t target_bytes, uint8_t* out_ids,
                           uint64_t max_out, uint64_t pin_pid) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  lock(h);
  std::vector<std::pair<uint64_t, uint64_t>> candidates;  // (tick, idx)
  for (uint64_t i = 0; i < h->table_cap; i++) {
    ObjectEntry& e = s->table[i];
    if (e.state != kSealed) continue;
    uint64_t pinned = 0;
    if (pin_pid != 0) {
      // SUM over every slot with this pid: historic slot-scan bugs could
      // split one pid across slots, and a single-slot read then both
      // skips legitimately pinned-idle victims and can pick an object
      // the pinner is concurrently reading
      for (uint32_t k = 0; k < kReaderSlots; k++)
        if (e.reader_pids[k] == pin_pid) pinned += e.reader_counts[k];
      if (pinned == 0 || e.refcount != pinned) continue;
    } else if (e.refcount != 0) {
      continue;
    }
    candidates.emplace_back(e.lru_tick, i);
  }
  std::sort(candidates.begin(), candidates.end());
  uint64_t n = 0, bytes = 0;
  for (auto& [tick, idx] : candidates) {
    if (n >= max_out || bytes >= target_bytes) break;
    ObjectEntry& e = s->table[idx];
    memcpy(out_ids + n * kIdLen, e.id, kIdLen);
    bytes += e.data_size + e.meta_size;
    n++;
  }
  unlock(h);
  return (int)n;
}

void store_stats(void* sp, uint64_t* out6) {
  Store* s = (Store*)sp;
  Header* h = s->hdr;
  lock(h);
  out6[0] = h->arena_size;
  out6[1] = h->bytes_allocated;
  out6[2] = h->num_objects;
  out6[3] = h->num_evictions;
  out6[4] = h->bytes_evicted;
  out6[5] = h->lru_clock;
  unlock(h);
}

}  // extern "C"
