// CRC32C (Castagnoli) for the TFRecord codec (ray_tpu/data/tfrecord.py
// loads this via ctypes and falls back to pure Python when absent).
// Uses the SSE4.2 CRC32 instruction when the CPU has it (that
// instruction IS the Castagnoli polynomial), else a slicing-by-8
// software path — either way orders of magnitude over a Python loop,
// which otherwise caps TFRecord IO at single-digit MB/s.

#include <cstddef>
#include <cstdint>

#if defined(__x86_64__) || defined(_M_X64)
#include <cpuid.h>
#include <nmmintrin.h>
static bool has_sse42() {
  unsigned eax, ebx, ecx, edx;
  if (!__get_cpuid(1, &eax, &ebx, &ecx, &edx)) return false;
  return (ecx & bit_SSE4_2) != 0;
}
static uint32_t crc_hw(uint32_t crc, const uint8_t* p, size_t n) {
  uint64_t c = crc;
  while (n >= 8) {
    c = _mm_crc32_u64(c, *reinterpret_cast<const uint64_t*>(p));
    p += 8;
    n -= 8;
  }
  uint32_t c32 = (uint32_t)c;
  while (n--) c32 = _mm_crc32_u8(c32, *p++);
  return c32;
}
#else
static bool has_sse42() { return false; }
static uint32_t crc_hw(uint32_t crc, const uint8_t*, size_t) {
  return crc;
}
#endif

static uint32_t g_table[8][256];
static bool g_table_ready = false;

static void init_table() {
  const uint32_t poly = 0x82F63B78u;
  for (uint32_t n = 0; n < 256; n++) {
    uint32_t c = n;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
    g_table[0][n] = c;
  }
  for (int k = 1; k < 8; k++)
    for (uint32_t n = 0; n < 256; n++)
      g_table[k][n] = g_table[0][g_table[k - 1][n] & 0xFF] ^
                      (g_table[k - 1][n] >> 8);
  g_table_ready = true;
}

static uint32_t crc_sw(uint32_t crc, const uint8_t* p, size_t n) {
  if (!g_table_ready) init_table();
  while (n >= 8) {
    uint32_t lo = crc ^ (p[0] | p[1] << 8 | p[2] << 16 |
                         (uint32_t)p[3] << 24);
    uint32_t hi = p[4] | p[5] << 8 | p[6] << 16 | (uint32_t)p[7] << 24;
    crc = g_table[7][lo & 0xFF] ^ g_table[6][(lo >> 8) & 0xFF] ^
          g_table[5][(lo >> 16) & 0xFF] ^ g_table[4][lo >> 24] ^
          g_table[3][hi & 0xFF] ^ g_table[2][(hi >> 8) & 0xFF] ^
          g_table[1][(hi >> 16) & 0xFF] ^ g_table[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) crc = g_table[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return crc;
}

extern "C" uint32_t crc32c(const uint8_t* data, uint64_t n) {
  static const bool hw = has_sse42();
  uint32_t crc = 0xFFFFFFFFu;
  crc = hw ? crc_hw(crc, data, (size_t)n) : crc_sw(crc, data, (size_t)n);
  return crc ^ 0xFFFFFFFFu;
}
