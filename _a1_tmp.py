import time, sys
import ray_tpu
import ray_tpu.runtime.driver as drv
# patch the micro-linger in the actor flusher loop
linger = float(sys.argv[1])
src_sleep = time.sleep
from ray_tpu.cluster_utils import Cluster

c = Cluster(external_gcs=True)
c.add_node(num_cpus=4, external=True)
rt = ray_tpu.init(address=c.gcs_address)

@ray_tpu.remote
class A:
    def m(self): return None

a = A.remote()
ray_tpu.get(a.m.remote())
n = 3000
best = 0
for _ in range(3):
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n)])
    best = max(best, n/(time.perf_counter()-t0))
print("linger-default best %.0f calls/s" % best)
ray_tpu.shutdown(); c.shutdown()
