"""RuntimeEnv: per-task/actor execution environments.

Reference analog: ``python/ray/runtime_env/`` (public RuntimeEnv class +
schema) and ``python/ray/_private/runtime_env/`` (P4: plugins, URI cache,
per-node agent). Supported fields:

- ``env_vars``: dict of environment variables visible to the task/actor.
- ``working_dir``: a local directory, snapshotted by content hash into a
  shared cache (the URI-cache analog); workers chdir into the snapshot
  and put it on ``sys.path``.
- ``py_modules``: list of module directories/files added to ``sys.path``
  (cached the same way).
- ``pip``: list of requirement strings (or ``{"packages": [...]}``) —
  installed once per requirement set into a cached virtualenv with
  system-site passthrough (the ``_private/runtime_env/pip.py`` analog);
  workers activate it by prepending its site-packages to ``sys.path``.
- ``config``: opaque dict passed through (reference parity; e.g.
  ``{"setup_timeout_seconds": ...}``).

``conda``/``container`` are rejected loudly (no conda/docker in the
image) rather than silently ignored.

Workers are cached per runtime-env key exactly like the reference's
(language, runtime_env)-keyed worker pool (``worker_pool.cc``): tasks
with the same env reuse a warm worker; a different env gets its own.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any

_UNSUPPORTED = ("conda", "container")


class RuntimeEnv(dict):
    """Dict-like (wire-serializable as plain JSON)."""

    def __init__(self, *, env_vars: dict | None = None,
                 working_dir: str | None = None,
                 py_modules: list | None = None,
                 pip: list | dict | None = None,
                 config: dict | None = None, **kwargs):
        for k in _UNSUPPORTED:
            if k in kwargs:
                raise ValueError(
                    f"runtime_env field {k!r} is not supported in this "
                    "environment (use 'pip' for per-env packages, or "
                    "pre-bake dependencies into the image)")
        if kwargs:
            raise ValueError(f"unknown runtime_env fields: {list(kwargs)}")
        body: dict[str, Any] = {}
        if env_vars:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be str -> str")
            body["env_vars"] = dict(env_vars)
        if working_dir:
            if not os.path.isdir(working_dir):
                raise ValueError(
                    f"working_dir {working_dir!r} is not a directory")
            body["working_dir"] = os.path.abspath(working_dir)
        if py_modules:
            body["py_modules"] = [os.path.abspath(p) for p in py_modules]
        if pip:
            reqs = pip.get("packages") if isinstance(pip, dict) else pip
            if not (isinstance(reqs, list)
                    and all(isinstance(r, str) for r in reqs)):
                raise TypeError(
                    "pip must be a list of requirement strings or "
                    "{'packages': [...]}")
            # local-path requirements resolve against the DRIVER's cwd
            # (like working_dir/py_modules) and keep the cache key from
            # aliasing two different './pkg' paths to one venv. pip
            # semantics: a bare name is a REQUIREMENT even if a same-named
            # directory happens to exist in the cwd — only explicit path
            # prefixes or separator-containing existing paths are local.
            def _localize(r: str) -> str:
                if r.startswith((".", "/", "~")) or (
                        os.sep in r and os.path.exists(r)):
                    return os.path.abspath(os.path.expanduser(r))
                return r

            body["pip"] = [_localize(r) for r in reqs]
        if config:
            body["config"] = dict(config)
        super().__init__(body)

    def to_dict(self) -> dict:
        return dict(self)


def env_key(runtime_env: dict | None) -> str:
    """Stable identity of a runtime env — the worker-cache key."""
    if not runtime_env:
        return ""
    return hashlib.sha256(
        json.dumps(runtime_env, sort_keys=True).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# URI cache (reference: _private/runtime_env/packaging.py — content-hash
# addressed snapshots shared across workers)
# ---------------------------------------------------------------------------

def _cache_root() -> str:
    root = os.environ.get(
        "RAY_TPU_RUNTIME_ENV_CACHE",
        os.path.join(os.path.expanduser("~"), ".ray_tpu",
                     "runtime_env_cache"))
    os.makedirs(root, exist_ok=True)
    return root


def _dir_content_hash(path: str) -> str:
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(path)):
        dirnames.sort()
        for fn in sorted(filenames):
            fp = os.path.join(dirpath, fn)
            h.update(os.path.relpath(fp, path).encode())
            try:
                with open(fp, "rb") as f:
                    h.update(f.read())
            except OSError:
                continue
    return h.hexdigest()[:16]


def snapshot_dir(path: str) -> str:
    """Copy `path` into the content-addressed cache; returns the cached
    location. Idempotent AND concurrency-safe: each process stages into
    its own unique tmp dir, and a racing winner is tolerated (same
    content, same key — either copy is correct)."""
    import uuid

    path = os.path.abspath(path)
    digest = _dir_content_hash(path)
    dest = os.path.join(_cache_root(), digest)
    if not os.path.isdir(dest):
        tmp = f"{dest}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        shutil.copytree(path, tmp)
        try:
            os.replace(tmp, dest)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            if not os.path.isdir(dest):  # lost the race some OTHER way
                raise
    return dest


# ---------------------------------------------------------------------------
# pip plugin (reference: _private/runtime_env/pip.py — per-env virtualenv
# with the requirement set as its identity; here venv + system site
# packages so the baked-in jax stack stays visible underneath)
# ---------------------------------------------------------------------------

def _pip_env_key(reqs: list[str]) -> str:
    import sys

    ident = json.dumps([f"py{sys.version_info[0]}.{sys.version_info[1]}",
                        reqs])
    return hashlib.sha256(ident.encode()).hexdigest()[:16]


def _venv_site_packages(venv_dir: str) -> str:
    import glob

    hits = glob.glob(os.path.join(venv_dir, "lib", "python*",
                                  "site-packages"))
    if not hits:
        raise FileNotFoundError(f"no site-packages under {venv_dir}")
    return hits[0]


def ensure_pip_env(reqs: list[str]) -> str:
    """Create (once, cached by requirement set) a venv with the packages
    installed; returns its site-packages path. Cross-process safe via an
    exclusive lock; ``--system-site-packages`` keeps the image's baked
    stack importable beneath the env's additions."""
    import fcntl
    import subprocess
    import sys

    root = os.path.join(_cache_root(), "venvs")
    os.makedirs(root, exist_ok=True)
    dest = os.path.join(root, _pip_env_key(reqs))
    ready = os.path.join(dest, ".ray_tpu_ready")
    if os.path.exists(ready):
        return _venv_site_packages(dest)
    with open(dest + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if os.path.exists(ready):   # another process built it meanwhile
            return _venv_site_packages(dest)
        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages", dest],
            check=True, capture_output=True, timeout=300)
        base = [os.path.join(dest, "bin", "python"), "-m", "pip",
                "install", "--no-input", "--quiet"]
        last = None
        # second attempt disables build isolation: air-gapped hosts can
        # still install local sdists/paths using the system setuptools
        # (build isolation wants to DOWNLOAD its build backend)
        for extra in ((), ("--no-build-isolation",)):
            try:
                subprocess.run([*base, *extra, *reqs], check=True,
                               capture_output=True, text=True,
                               timeout=1800)
                last = None
                break
            except subprocess.CalledProcessError as e:
                last = e
        if last is not None:
            shutil.rmtree(dest, ignore_errors=True)
            raise RuntimeError(
                f"runtime_env pip install failed for {reqs}: "
                f"{last.stderr[-2000:] if last.stderr else last}") \
                from None
        open(ready, "w").close()
    return _venv_site_packages(dest)


def apply_runtime_env(runtime_env: dict | None) -> None:
    """Apply an env in-place to THIS process (worker boot path —
    reference: runtime-env agent's GetOrCreateRuntimeEnv result applied
    as the worker's startup context)."""
    import uuid

    if not runtime_env:
        return
    for k, v in (runtime_env.get("env_vars") or {}).items():
        os.environ[k] = v
    reqs = runtime_env.get("pip")
    if reqs:
        import sys

        site = ensure_pip_env(list(reqs))
        if site not in sys.path:
            # FRONT of sys.path: the env's packages shadow same-named
            # system packages, venv-activation style
            sys.path.insert(0, site)
    wd = runtime_env.get("working_dir")
    if wd:
        snap = snapshot_dir(wd)
        # Per-worker COPY of the snapshot: the worker may write to its
        # cwd, and writes must not mutate the shared content-addressed
        # cache entry (reference: per-job working_dir copies).
        workdir = os.path.join(
            _cache_root(), f"work-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        shutil.copytree(snap, workdir)
        os.chdir(workdir)
        import sys

        if workdir not in sys.path:
            sys.path.insert(0, workdir)
    for mod in runtime_env.get("py_modules") or []:
        _add_module_path(mod)


_applied_path_keys: set[str] = set()


def apply_paths(runtime_env: dict | None) -> None:
    """sys.path half of apply_runtime_env: safe for the in-process local
    runtime too (additive and idempotent — no chdir, no env mutation,
    which would be process-global and racy across worker threads).
    Memoized per env key: re-hashing/copying the working_dir tree on
    every task execution would put a full directory read on the task hot
    path."""
    import sys

    if not runtime_env:
        return
    key = env_key(runtime_env)
    if key in _applied_path_keys:
        return   # memo covers pip too (the key hashes every field)
    reqs = runtime_env.get("pip")
    if reqs:
        site = ensure_pip_env(list(reqs))
        if site not in sys.path:
            sys.path.insert(0, site)
    wd = runtime_env.get("working_dir")
    if wd:
        snap = snapshot_dir(wd)
        if snap not in sys.path:
            sys.path.insert(0, snap)
    for mod in runtime_env.get("py_modules") or []:
        _add_module_path(mod)
    _applied_path_keys.add(key)


def _add_module_path(mod: str) -> None:
    import sys

    if os.path.isdir(mod):
        snap = snapshot_dir(mod)
        # a module dir's PARENT goes on sys.path so `import <name>`
        # resolves; cached copy keeps the original name via a child
        parent = os.path.join(_cache_root(),
                              "mods-" + _dir_content_hash(mod))
        target = os.path.join(parent, os.path.basename(mod))
        if not os.path.isdir(target):
            os.makedirs(parent, exist_ok=True)
            shutil.copytree(snap, target, dirs_exist_ok=True)
        if parent not in sys.path:
            sys.path.insert(0, parent)
    else:
        d = os.path.dirname(mod)
        if d not in sys.path:
            sys.path.insert(0, d)
