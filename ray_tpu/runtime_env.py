"""RuntimeEnv: per-task/actor execution environments.

Reference analog: ``python/ray/runtime_env/`` (public RuntimeEnv class +
schema) and ``python/ray/_private/runtime_env/`` (P4: plugins, URI cache,
per-node agent). Supported fields:

- ``env_vars``: dict of environment variables visible to the task/actor.
- ``working_dir``: a local directory, snapshotted by content hash into a
  shared cache (the URI-cache analog); workers chdir into the snapshot
  and put it on ``sys.path``.
- ``py_modules``: list of module directories/files added to ``sys.path``
  (cached the same way).
- ``pip``: list of requirement strings (or ``{"packages": [...]}``) —
  installed once per requirement set into a cached virtualenv with
  system-site passthrough (the ``_private/runtime_env/pip.py`` analog);
  workers activate it by prepending its site-packages to ``sys.path``.
- ``config``: opaque dict passed through (reference parity; e.g.
  ``{"setup_timeout_seconds": ...}``).
- ``conda``: an existing conda env NAME, or a spec dict
  (``{"dependencies": [...]}`` — environment.yml content). Cached envs
  are created with the host's conda; workers prepend the env's
  site-packages (reference: ``_private/runtime_env/conda.py``). Hosts
  without conda fail the env FAST (RuntimeEnvSetupError), not silently.
- ``container``: ``{"image": ..., "run_options": [...]}`` — the worker
  process runs INSIDE the container via docker/podman with host
  networking and host IPC (so the shm object store and raylet ports
  keep working — reference: ``_private/runtime_env/container.py``).

Workers are cached per runtime-env key exactly like the reference's
(language, runtime_env)-keyed worker pool (``worker_pool.cc``): tasks
with the same env reuse a warm worker; a different env gets its own.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any

class RuntimeEnv(dict):
    """Dict-like (wire-serializable as plain JSON)."""

    def __init__(self, *, env_vars: dict | None = None,
                 working_dir: str | None = None,
                 py_modules: list | None = None,
                 pip: list | dict | None = None,
                 conda: str | dict | None = None,
                 container: dict | None = None,
                 config: dict | None = None, **kwargs):
        if kwargs:
            raise ValueError(f"unknown runtime_env fields: {list(kwargs)}")
        body: dict[str, Any] = {}
        if env_vars:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be str -> str")
            body["env_vars"] = dict(env_vars)
        if working_dir:
            if not os.path.isdir(working_dir):
                raise ValueError(
                    f"working_dir {working_dir!r} is not a directory")
            body["working_dir"] = os.path.abspath(working_dir)
        if py_modules:
            body["py_modules"] = [os.path.abspath(p) for p in py_modules]
        if pip:
            reqs = pip.get("packages") if isinstance(pip, dict) else pip
            if not (isinstance(reqs, list)
                    and all(isinstance(r, str) for r in reqs)):
                raise TypeError(
                    "pip must be a list of requirement strings or "
                    "{'packages': [...]}")
            # local-path requirements resolve against the DRIVER's cwd
            # (like working_dir/py_modules) and keep the cache key from
            # aliasing two different './pkg' paths to one venv. pip
            # semantics: a bare name is a REQUIREMENT even if a same-named
            # directory happens to exist in the cwd — only explicit path
            # prefixes or separator-containing existing paths are local.
            def _localize(r: str) -> str:
                if r.startswith((".", "/", "~")) or (
                        os.sep in r and os.path.exists(r)):
                    return os.path.abspath(os.path.expanduser(r))
                return r

            body["pip"] = [_localize(r) for r in reqs]
        if conda:
            if isinstance(conda, str):
                body["conda"] = conda
            elif isinstance(conda, dict):
                if not conda.get("dependencies"):
                    raise ValueError(
                        "conda spec dict needs a non-empty 'dependencies' "
                        "list (environment.yml content)")
                body["conda"] = dict(conda)
            else:
                raise TypeError(
                    "conda must be an env name or a spec dict")
        if container:
            if not isinstance(container, dict) or "image" not in container:
                raise TypeError(
                    "container must be a dict with at least 'image'")
            opts = container.get("run_options", [])
            if not (isinstance(opts, list)
                    and all(isinstance(o, str) for o in opts)):
                raise TypeError("container.run_options must be [str]")
            body["container"] = {"image": container["image"],
                                 "run_options": list(opts)}
        if config:
            body["config"] = dict(config)
        super().__init__(body)

    def to_dict(self) -> dict:
        return dict(self)


def env_key(runtime_env: dict | None) -> str:
    """Stable identity of a runtime env — the worker-cache key."""
    if not runtime_env:
        return ""
    return hashlib.sha256(
        json.dumps(runtime_env, sort_keys=True).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# URI cache (reference: _private/runtime_env/packaging.py — content-hash
# addressed snapshots shared across workers)
# ---------------------------------------------------------------------------

def _cache_root() -> str:
    root = os.environ.get(
        "RAY_TPU_RUNTIME_ENV_CACHE",
        os.path.join(os.path.expanduser("~"), ".ray_tpu",
                     "runtime_env_cache"))
    os.makedirs(root, exist_ok=True)
    return root


def _dir_content_hash(path: str) -> str:
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(path)):
        dirnames.sort()
        for fn in sorted(filenames):
            fp = os.path.join(dirpath, fn)
            h.update(os.path.relpath(fp, path).encode())
            try:
                with open(fp, "rb") as f:
                    h.update(f.read())
            except OSError:
                continue
    return h.hexdigest()[:16]


def snapshot_dir(path: str) -> str:
    """Copy `path` into the content-addressed cache; returns the cached
    location. Idempotent AND concurrency-safe: each process stages into
    its own unique tmp dir, and a racing winner is tolerated (same
    content, same key — either copy is correct)."""
    import uuid

    path = os.path.abspath(path)
    digest = _dir_content_hash(path)
    dest = os.path.join(_cache_root(), digest)
    if not os.path.isdir(dest):
        tmp = f"{dest}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        shutil.copytree(path, tmp)
        try:
            os.replace(tmp, dest)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            if not os.path.isdir(dest):  # lost the race some OTHER way
                raise
    return dest


# ---------------------------------------------------------------------------
# pip plugin (reference: _private/runtime_env/pip.py — per-env virtualenv
# with the requirement set as its identity; here venv + system site
# packages so the baked-in jax stack stays visible underneath)
# ---------------------------------------------------------------------------

def _pip_env_key(reqs: list[str]) -> str:
    import sys

    ident = json.dumps([f"py{sys.version_info[0]}.{sys.version_info[1]}",
                        reqs])
    return hashlib.sha256(ident.encode()).hexdigest()[:16]


def _venv_site_packages(venv_dir: str) -> str:
    import glob

    hits = glob.glob(os.path.join(venv_dir, "lib", "python*",
                                  "site-packages"))
    if not hits:
        raise FileNotFoundError(f"no site-packages under {venv_dir}")
    return hits[0]


def ensure_pip_env(reqs: list[str]) -> str:
    """Create (once, cached by requirement set) a venv with the packages
    installed; returns its site-packages path. Cross-process safe via an
    exclusive lock; ``--system-site-packages`` keeps the image's baked
    stack importable beneath the env's additions."""
    import fcntl
    import subprocess
    import sys

    root = os.path.join(_cache_root(), "venvs")
    os.makedirs(root, exist_ok=True)
    dest = os.path.join(root, _pip_env_key(reqs))
    ready = os.path.join(dest, ".ray_tpu_ready")
    if os.path.exists(ready):
        return _venv_site_packages(dest)
    with open(dest + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if os.path.exists(ready):   # another process built it meanwhile
            return _venv_site_packages(dest)
        subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages", dest],
            check=True, capture_output=True, timeout=300)
        base = [os.path.join(dest, "bin", "python"), "-m", "pip",
                "install", "--no-input", "--quiet"]
        last = None
        # second attempt disables build isolation: air-gapped hosts can
        # still install local sdists/paths using the system setuptools
        # (build isolation wants to DOWNLOAD its build backend)
        for extra in ((), ("--no-build-isolation",)):
            try:
                subprocess.run([*base, *extra, *reqs], check=True,
                               capture_output=True, text=True,
                               timeout=1800)
                last = None
                break
            except subprocess.CalledProcessError as e:
                last = e
        if last is not None:
            shutil.rmtree(dest, ignore_errors=True)
            raise RuntimeError(
                f"runtime_env pip install failed for {reqs}: "
                f"{last.stderr[-2000:] if last.stderr else last}") \
                from None
        open(ready, "w").close()
    return _venv_site_packages(dest)


# ---------------------------------------------------------------------------
# conda plugin (reference: _private/runtime_env/conda.py — cached env per
# spec; the env's site-packages layers onto the worker's sys.path)
# ---------------------------------------------------------------------------

def _find_conda() -> str | None:
    exe = os.environ.get("CONDA_EXE")
    if exe and os.path.exists(exe):
        return exe
    return shutil.which("conda") or shutil.which("mamba") \
        or shutil.which("micromamba")


def conda_create_commands(spec: dict, dest: str, conda_exe: str) -> list:
    """Command lines that materialize a conda env for ``spec`` at
    ``dest`` (pure — unit-testable without conda installed). The
    environment.yml ``{"pip": [...]}`` dependency subsection becomes a
    second pip-install step inside the env; any other non-string entry
    is an error (silent drops would cache an incomplete env forever)."""
    deps = spec.get("dependencies", [])
    conda_pkgs = [d for d in deps if isinstance(d, str)]
    pip_pkgs: list = []
    for d in deps:
        if isinstance(d, str):
            continue
        if isinstance(d, dict) and list(d.keys()) == ["pip"]:
            pip_pkgs.extend(d["pip"])
        else:
            raise ValueError(
                f"unsupported conda dependency entry: {d!r}")
    cmds = [[conda_exe, "create", "--yes", "--quiet", "--prefix", dest,
             *conda_pkgs]]
    if pip_pkgs:
        cmds.append([conda_exe, "run", "--prefix", dest, "python", "-m",
                     "pip", "install", "--no-input", *pip_pkgs])
    return cmds


def ensure_conda_env(conda_field, *, runner=None) -> str:
    """Resolve a conda field to a site-packages path. A NAME resolves
    against `conda env list`; a SPEC dict creates a cached env keyed by
    content. Fails fast (RuntimeError) when no conda binary exists —
    the raylet's bad-env registry turns that into RuntimeEnvSetupError
    for every queued task instead of a spawn/crash loop."""
    import fcntl
    import glob as _glob
    import subprocess

    runner = runner or (lambda cmd: subprocess.run(
        cmd, check=True, capture_output=True, text=True, timeout=1800))
    conda_exe = _find_conda()
    if conda_exe is None:
        raise RuntimeError(
            "runtime_env.conda requested but no conda/mamba binary is "
            "on PATH (and CONDA_EXE is unset)")
    if isinstance(conda_field, str):
        root = os.path.dirname(os.path.dirname(conda_exe))
        if conda_field == "base":
            # base lives at the ROOT prefix, not under envs/
            base = root
        elif os.sep in conda_field:
            # `conda create -p /path/env` style: the name IS the prefix
            base = os.path.expanduser(conda_field)
        else:
            base = os.path.join(root, "envs", conda_field)
        hits = _glob.glob(os.path.join(base, "lib", "python*",
                                       "site-packages"))
        if not hits:
            raise RuntimeError(
                f"conda env {conda_field!r} not found under {base}")
        return hits[0]
    digest = hashlib.sha256(
        json.dumps(conda_field, sort_keys=True).encode()).hexdigest()[:16]
    dest = os.path.join(_cache_root(), "conda", digest)
    ready = os.path.join(dest, ".ray_tpu_ready")
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    if not os.path.exists(ready):
        with open(dest + ".lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if not os.path.exists(ready):
                try:
                    for cmd in conda_create_commands(conda_field, dest,
                                                     conda_exe):
                        runner(cmd)
                except subprocess.CalledProcessError as e:
                    shutil.rmtree(dest, ignore_errors=True)
                    raise RuntimeError(
                        f"conda env create failed: "
                        f"{e.stderr[-2000:] if e.stderr else e}") from None
                open(ready, "w").close()
    hits = _glob.glob(os.path.join(dest, "lib", "python*",
                                   "site-packages"))
    if not hits:
        raise RuntimeError(f"no site-packages under conda env {dest}")
    return hits[0]


# ---------------------------------------------------------------------------
# container plugin (reference: _private/runtime_env/container.py — the
# worker process runs inside the image)
# ---------------------------------------------------------------------------

def find_container_runtime() -> str | None:
    return shutil.which("docker") or shutil.which("podman")


def container_command(container: dict, base_cmd: list,
                      env: dict, *, runtime: str,
                      mounts: list | None = None) -> list:
    """Wrap a worker command line to run inside ``container['image']``
    (pure — unit-testable without docker installed). Host networking
    keeps the raylet/GCS ports reachable; host IPC keeps the /dev/shm
    object store attachable; the package root mounts read-only so the
    image needs python but not ray_tpu."""
    import ray_tpu as _pkg

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(
        _pkg.__file__)))
    cmd = [runtime, "run", "--rm", "--network=host", "--ipc=host",
           f"-v={pkg_root}:{pkg_root}:ro"]
    for m in mounts or []:
        cmd.append(f"-v={m}:{m}")
    for k, v in env.items():
        if k.startswith(("RAY_TPU_", "JAX_", "PYTHON")):
            cmd.append(f"-e={k}={v}")
    cmd.append(f"-e=PYTHONPATH={pkg_root}")
    cmd += container.get("run_options", [])
    cmd.append(container["image"])
    return cmd + base_cmd


def apply_runtime_env(runtime_env: dict | None) -> None:
    """Apply an env in-place to THIS process (worker boot path —
    reference: runtime-env agent's GetOrCreateRuntimeEnv result applied
    as the worker's startup context)."""
    import uuid

    if not runtime_env:
        return
    for k, v in (runtime_env.get("env_vars") or {}).items():
        os.environ[k] = v
    reqs = runtime_env.get("pip")
    if reqs:
        import sys

        site = ensure_pip_env(list(reqs))
        if site not in sys.path:
            # FRONT of sys.path: the env's packages shadow same-named
            # system packages, venv-activation style
            sys.path.insert(0, site)
    conda_field = runtime_env.get("conda")
    if conda_field:
        import sys

        site = ensure_conda_env(conda_field)
        if site not in sys.path:
            sys.path.insert(0, site)
    wd = runtime_env.get("working_dir")
    if wd:
        snap = snapshot_dir(wd)
        # Per-worker COPY of the snapshot: the worker may write to its
        # cwd, and writes must not mutate the shared content-addressed
        # cache entry (reference: per-job working_dir copies).
        workdir = os.path.join(
            _cache_root(), f"work-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        shutil.copytree(snap, workdir)
        os.chdir(workdir)
        import sys

        if workdir not in sys.path:
            sys.path.insert(0, workdir)
    for mod in runtime_env.get("py_modules") or []:
        _add_module_path(mod)


_applied_path_keys: set[str] = set()


def apply_paths(runtime_env: dict | None) -> None:
    """sys.path half of apply_runtime_env: safe for the in-process local
    runtime too (additive and idempotent — no chdir, no env mutation,
    which would be process-global and racy across worker threads).
    Memoized per env key: re-hashing/copying the working_dir tree on
    every task execution would put a full directory read on the task hot
    path."""
    import sys

    if not runtime_env:
        return
    key = env_key(runtime_env)
    if key in _applied_path_keys:
        return   # memo covers pip too (the key hashes every field)
    reqs = runtime_env.get("pip")
    if reqs:
        site = ensure_pip_env(list(reqs))
        if site not in sys.path:
            sys.path.insert(0, site)
    wd = runtime_env.get("working_dir")
    if wd:
        snap = snapshot_dir(wd)
        if snap not in sys.path:
            sys.path.insert(0, snap)
    for mod in runtime_env.get("py_modules") or []:
        _add_module_path(mod)
    _applied_path_keys.add(key)


def _add_module_path(mod: str) -> None:
    import sys

    if os.path.isdir(mod):
        snap = snapshot_dir(mod)
        # a module dir's PARENT goes on sys.path so `import <name>`
        # resolves; cached copy keeps the original name via a child
        parent = os.path.join(_cache_root(),
                              "mods-" + _dir_content_hash(mod))
        target = os.path.join(parent, os.path.basename(mod))
        if not os.path.isdir(target):
            os.makedirs(parent, exist_ok=True)
            shutil.copytree(snap, target, dirs_exist_ok=True)
        if parent not in sys.path:
            sys.path.insert(0, parent)
    else:
        d = os.path.dirname(mod)
        if d not in sys.path:
            sys.path.insert(0, d)
