"""RuntimeEnv: per-task/actor execution environments.

Reference analog: ``python/ray/runtime_env/`` (public RuntimeEnv class +
schema) and ``python/ray/_private/runtime_env/`` (P4: plugins, URI cache,
per-node agent). Supported fields:

- ``env_vars``: dict of environment variables visible to the task/actor.
- ``working_dir``: a local directory, snapshotted by content hash into a
  shared cache (the URI-cache analog); workers chdir into the snapshot
  and put it on ``sys.path``.
- ``py_modules``: list of module directories/files added to ``sys.path``
  (cached the same way).
- ``config``: opaque dict passed through (reference parity; e.g.
  ``{"setup_timeout_seconds": ...}``).

``pip``/``conda`` are intentionally rejected here: this image forbids
package installation, so the field is validated out loudly rather than
silently ignored (reference behavior is to build an env — see
``_private/runtime_env/pip.py``).

Workers are cached per runtime-env key exactly like the reference's
(language, runtime_env)-keyed worker pool (``worker_pool.cc``): tasks
with the same env reuse a warm worker; a different env gets its own.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
from typing import Any

_UNSUPPORTED = ("pip", "conda", "container")


class RuntimeEnv(dict):
    """Dict-like (wire-serializable as plain JSON)."""

    def __init__(self, *, env_vars: dict | None = None,
                 working_dir: str | None = None,
                 py_modules: list | None = None,
                 config: dict | None = None, **kwargs):
        for k in _UNSUPPORTED:
            if k in kwargs:
                raise ValueError(
                    f"runtime_env field {k!r} is not supported in this "
                    "environment (package installation is disabled); "
                    "pre-bake dependencies into the image instead")
        if kwargs:
            raise ValueError(f"unknown runtime_env fields: {list(kwargs)}")
        body: dict[str, Any] = {}
        if env_vars:
            if not all(isinstance(k, str) and isinstance(v, str)
                       for k, v in env_vars.items()):
                raise TypeError("env_vars must be str -> str")
            body["env_vars"] = dict(env_vars)
        if working_dir:
            if not os.path.isdir(working_dir):
                raise ValueError(
                    f"working_dir {working_dir!r} is not a directory")
            body["working_dir"] = os.path.abspath(working_dir)
        if py_modules:
            body["py_modules"] = [os.path.abspath(p) for p in py_modules]
        if config:
            body["config"] = dict(config)
        super().__init__(body)

    def to_dict(self) -> dict:
        return dict(self)


def env_key(runtime_env: dict | None) -> str:
    """Stable identity of a runtime env — the worker-cache key."""
    if not runtime_env:
        return ""
    return hashlib.sha256(
        json.dumps(runtime_env, sort_keys=True).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# URI cache (reference: _private/runtime_env/packaging.py — content-hash
# addressed snapshots shared across workers)
# ---------------------------------------------------------------------------

def _cache_root() -> str:
    root = os.environ.get(
        "RAY_TPU_RUNTIME_ENV_CACHE",
        os.path.join(os.path.expanduser("~"), ".ray_tpu",
                     "runtime_env_cache"))
    os.makedirs(root, exist_ok=True)
    return root


def _dir_content_hash(path: str) -> str:
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(path)):
        dirnames.sort()
        for fn in sorted(filenames):
            fp = os.path.join(dirpath, fn)
            h.update(os.path.relpath(fp, path).encode())
            try:
                with open(fp, "rb") as f:
                    h.update(f.read())
            except OSError:
                continue
    return h.hexdigest()[:16]


def snapshot_dir(path: str) -> str:
    """Copy `path` into the content-addressed cache; returns the cached
    location. Idempotent AND concurrency-safe: each process stages into
    its own unique tmp dir, and a racing winner is tolerated (same
    content, same key — either copy is correct)."""
    import uuid

    path = os.path.abspath(path)
    digest = _dir_content_hash(path)
    dest = os.path.join(_cache_root(), digest)
    if not os.path.isdir(dest):
        tmp = f"{dest}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        shutil.copytree(path, tmp)
        try:
            os.replace(tmp, dest)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            if not os.path.isdir(dest):  # lost the race some OTHER way
                raise
    return dest


def apply_runtime_env(runtime_env: dict | None) -> None:
    """Apply an env in-place to THIS process (worker boot path —
    reference: runtime-env agent's GetOrCreateRuntimeEnv result applied
    as the worker's startup context)."""
    import uuid

    if not runtime_env:
        return
    for k, v in (runtime_env.get("env_vars") or {}).items():
        os.environ[k] = v
    wd = runtime_env.get("working_dir")
    if wd:
        snap = snapshot_dir(wd)
        # Per-worker COPY of the snapshot: the worker may write to its
        # cwd, and writes must not mutate the shared content-addressed
        # cache entry (reference: per-job working_dir copies).
        workdir = os.path.join(
            _cache_root(), f"work-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        shutil.copytree(snap, workdir)
        os.chdir(workdir)
        import sys

        if workdir not in sys.path:
            sys.path.insert(0, workdir)
    for mod in runtime_env.get("py_modules") or []:
        _add_module_path(mod)


_applied_path_keys: set[str] = set()


def apply_paths(runtime_env: dict | None) -> None:
    """sys.path half of apply_runtime_env: safe for the in-process local
    runtime too (additive and idempotent — no chdir, no env mutation,
    which would be process-global and racy across worker threads).
    Memoized per env key: re-hashing/copying the working_dir tree on
    every task execution would put a full directory read on the task hot
    path."""
    import sys

    if not runtime_env:
        return
    key = env_key(runtime_env)
    if key in _applied_path_keys:
        return
    wd = runtime_env.get("working_dir")
    if wd:
        snap = snapshot_dir(wd)
        if snap not in sys.path:
            sys.path.insert(0, snap)
    for mod in runtime_env.get("py_modules") or []:
        _add_module_path(mod)
    _applied_path_keys.add(key)


def _add_module_path(mod: str) -> None:
    import sys

    if os.path.isdir(mod):
        snap = snapshot_dir(mod)
        # a module dir's PARENT goes on sys.path so `import <name>`
        # resolves; cached copy keeps the original name via a child
        parent = os.path.join(_cache_root(),
                              "mods-" + _dir_content_hash(mod))
        target = os.path.join(parent, os.path.basename(mod))
        if not os.path.isdir(target):
            os.makedirs(parent, exist_ok=True)
            shutil.copytree(snap, target, dirs_exist_ok=True)
        if parent not in sys.path:
            sys.path.insert(0, parent)
    else:
        d = os.path.dirname(mod)
        if d not in sys.path:
            sys.path.insert(0, d)
