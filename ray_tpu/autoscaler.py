"""Autoscaler: demand-driven node scaling over a NodeProvider.

Reference analog: ``autoscaler/_private/autoscaler.py``
(``StandardAutoscaler:171``) driven by ``Monitor`` (monitor.py:126), with
cloud ``NodeProvider`` plugins; tests use ``FakeMultiNodeProvider``
(fake_multi_node/node_provider.py:237). Here the demand signal is the
GCS resource view (pending infeasible demand + utilization) and the
provider contract is create/terminate of raylet-bearing nodes; the
``LocalNodeProvider`` spawns real raylet processes on this host (the
GKE TPU-pool provider slots in behind the same interface)."""

from __future__ import annotations

import threading
import time

from ray_tpu.runtime.rpc import RpcClient


class NodeProvider:
    """Provider contract (reference: ``autoscaler/node_provider.py``)."""

    def create_node(self, resources: dict) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Spawns raylet processes on this host (FakeMultiNodeProvider
    analog — 'multi-node' without a cloud)."""

    def __init__(self, cluster):
        self.cluster = cluster  # cluster_utils.Cluster
        self.created: dict[str, object] = {}

    def create_node(self, resources: dict) -> str:
        res = dict(resources)
        num_cpus = res.pop("CPU", 1)
        num_tpus = res.pop("TPU", 0)
        handle = self.cluster.add_node(
            num_cpus=num_cpus, num_tpus=num_tpus, resources=res,
            external=True)
        self.created[handle.node_id] = handle
        return handle.node_id

    def terminate_node(self, node_id: str) -> None:
        handle = self.created.pop(node_id, None)
        if handle is not None:
            self.cluster.remove_node(handle, graceful=True)

    def non_terminated_nodes(self) -> list[str]:
        return list(self.created)


class GKETPUNodeProvider(NodeProvider):
    """GKE TPU node-pool provider (reference: the cloud NodeProvider
    plugins — ``autoscaler/_private/gcp/`` and the kuberay
    batching_node_provider): scale-up resizes a dedicated TPU node pool,
    scale-down deletes specific nodes from it.

    Contract: raylets on the pool's VMs start with
    ``RAY_TPU_NODE_ID=<kubernetes node name>`` (the pool's startup
    DaemonSet sets it), so provider node ids line up with GCS node ids
    and the autoscaler's reap/idle bookkeeping works unchanged.

    All cloud interaction shells out to ``gcloud``/``kubectl`` through
    an injectable ``runner`` (tests fake it; real use needs credentials
    on the head node).
    """

    LIST_CACHE_TTL_S = 1.0   # one kubectl listing per autoscaler tick,
                             # not one per call site within the tick

    def __init__(self, *, cluster: str, node_pool: str, zone: str,
                 project: str | None = None, runner=None):
        self.cluster = cluster
        self.node_pool = node_pool
        self.zone = zone
        self.project = project
        self._run = runner or self._subprocess_runner
        self._listed_at = 0.0
        self._listed: list[str] = []

    @staticmethod
    def _subprocess_runner(argv: list[str]) -> str:
        import subprocess

        return subprocess.run(argv, check=True, capture_output=True,
                              text=True, timeout=600).stdout

    def _gcloud(self, *args) -> str:
        argv = ["gcloud", *args, f"--zone={self.zone}", "--quiet"]
        if self.project:
            argv.append(f"--project={self.project}")
        return self._run(argv)

    def create_node(self, resources: dict) -> str:
        target = len(self.non_terminated_nodes()) + 1
        self._gcloud("container", "clusters", "resize", self.cluster,
                     f"--node-pool={self.node_pool}",
                     f"--num-nodes={target}")
        self._listed_at = 0.0   # force a fresh listing next call
        # GKE provisions asynchronously over minutes: the new VM has no
        # name yet. The autoscaler tracks membership via
        # non_terminated_nodes(), not this return value (the raylet on
        # the VM self-registers with RAY_TPU_NODE_ID=<k8s node name>).
        return ""

    def terminate_node(self, node_id: str) -> None:
        # drain best-effort (an unreachable/crashed VM fails the drain;
        # the VM delete below must still run or dead nodes wedge the
        # autoscaler's reap forever)
        try:
            self._run(["kubectl", "drain", node_id,
                       "--ignore-daemonsets", "--delete-emptydir-data",
                       "--force", "--timeout=120s"])
        except Exception:  # noqa: BLE001
            pass
        # removing a SPECIFIC VM from a pool = delete it from the pool's
        # managed instance group (there is no gcloud node-pools
        # delete-nodes); the MIG url comes from the pool description
        mig_urls = self._gcloud(
            "container", "node-pools", "describe", self.node_pool,
            f"--cluster={self.cluster}",
            "--format=value(instanceGroupUrls)")
        for url in mig_urls.replace(";", "\n").split():
            mig = url.rstrip("/").rsplit("/", 1)[-1]
            if not mig:
                continue
            try:
                self._gcloud("compute", "instance-groups", "managed",
                             "delete-instances", mig,
                             f"--instances={node_id}")
                break
            except Exception:  # noqa: BLE001 - wrong MIG for this VM
                continue
        self._listed_at = 0.0

    def non_terminated_nodes(self) -> list[str]:
        now = time.monotonic()
        if now - self._listed_at < self.LIST_CACHE_TTL_S:
            return list(self._listed)
        out = self._run([
            "kubectl", "get", "nodes",
            "-l", f"cloud.google.com/gke-nodepool={self.node_pool}",
            "-o", "jsonpath={.items[*].metadata.name}"])
        self._listed = [n for n in out.split() if n]
        self._listed_at = now
        return list(self._listed)


class StandardAutoscaler:
    """Scale up when the cluster cannot satisfy demand; scale down idle
    provider nodes after ``idle_timeout_s``."""

    def __init__(self, gcs_address, provider: NodeProvider, *,
                 node_resources: dict | None = None,
                 max_nodes: int = 4, idle_timeout_s: float = 5.0,
                 poll_interval_s: float = 0.5,
                 utilization_threshold: float = 0.9):
        from ray_tpu.instance_manager import InstanceManager

        self.gcs = RpcClient(tuple(gcs_address))
        self.provider = provider
        # v2-style bookkeeping: launches/terminations become versioned
        # instance records; the reconciler (not this policy code) owns
        # lifecycle transitions against the provider + GCS views
        self.im = InstanceManager(provider)
        self.node_resources = node_resources or {"CPU": 2}
        self.max_nodes = max_nodes
        self.idle_timeout_s = idle_timeout_s
        self.poll_interval_s = poll_interval_s
        self.utilization_threshold = utilization_threshold
        self._idle_since: dict[str, float] = {}
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop = True

    def _run(self):
        while not self._stop:
            try:
                self.update()
            except Exception:  # noqa: BLE001 - keep monitoring
                pass
            time.sleep(self.poll_interval_s)

    def update(self):
        res = self.gcs.call("cluster_resources")
        total, avail = res["total"], res["available"]
        # scale up (1): explicit unmet demand — tasks parked as
        # cluster-wide infeasible (reference: autoscaler v2's demand-
        # driven path from GcsAutoscalerStateManager). Skips while a
        # provider node is still booting (not yet registered in GCS):
        # the demand stays pending for the whole provision window, and
        # re-creating per poll would over-provision for one task.
        all_nodes = {n["node_id"]: n
                     for n in self.gcs.call("get_nodes", alive_only=False)}
        alive = {nid for nid, n in all_nodes.items() if n.get("alive")}
        self.im.reconcile(gcs_alive=alive)
        # reap provider nodes the GCS declared dead — left in place they
        # count as "provisioning" forever and wedge demand-driven scaling
        for nid in list(self.provider.non_terminated_nodes()):
            if nid in all_nodes and not all_nodes[nid].get("alive"):
                self.im.terminate(nid)
                self._idle_since.pop(nid, None)
        self.im.reconcile(gcs_alive=alive)
        provisioning = self.im.provisioning()
        # capacity AFTER the reap: the cycle that frees a dead node's
        # slot must be able to provision its replacement immediately
        under_cap = self.im.live_count() < self.max_nodes
        if under_cap and not provisioning:
            try:
                pending = self.gcs.call("get_pending_demand")
            except Exception:  # noqa: BLE001 - older GCS
                pending = []
            satisfiable = [d for d in pending
                           if all(self.node_resources.get(k, 0) >= v
                                  for k, v in d.items())]
            if satisfiable:
                self.im.launch(dict(self.node_resources))
                self.im.reconcile(gcs_alive=alive)
                return
        # scale up (2): demanded resource classes nearly exhausted
        busy = any(
            total.get(k, 0) > 0
            and (total[k] - avail.get(k, 0)) / total[k]
            >= self.utilization_threshold
            for k in ("CPU", "TPU") if total.get(k))
        if busy and under_cap:
            self.im.launch(dict(self.node_resources))
            self.im.reconcile(gcs_alive=alive)
            return
        # scale down: provider nodes fully idle past the timeout
        nodes = {n["node_id"]: n
                 for n in self.gcs.call("get_nodes", alive_only=True)}
        now = time.monotonic()
        for node_id in self.provider.non_terminated_nodes():
            info = nodes.get(node_id)
            if info is None:
                continue
            idle = info["available"] == info["resources"]
            if not idle:
                self._idle_since.pop(node_id, None)
                continue
            since = self._idle_since.setdefault(node_id, now)
            if now - since > self.idle_timeout_s:
                self.im.terminate(node_id)
                self._idle_since.pop(node_id, None)
                return
