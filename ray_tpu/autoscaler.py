"""Autoscaler: demand-driven node scaling over a NodeProvider.

Reference analog: ``autoscaler/_private/autoscaler.py``
(``StandardAutoscaler:171``) driven by ``Monitor`` (monitor.py:126), with
cloud ``NodeProvider`` plugins; tests use ``FakeMultiNodeProvider``
(fake_multi_node/node_provider.py:237). Here the demand signal is the
GCS resource view (pending infeasible demand + utilization) and the
provider contract is create/terminate of raylet-bearing nodes; the
``LocalNodeProvider`` spawns real raylet processes on this host (the
GKE TPU-pool provider slots in behind the same interface)."""

from __future__ import annotations

import threading
import time

from ray_tpu.runtime.rpc import RpcClient


class NodeProvider:
    """Provider contract (reference: ``autoscaler/node_provider.py``)."""

    def create_node(self, resources: dict) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Spawns raylet processes on this host (FakeMultiNodeProvider
    analog — 'multi-node' without a cloud)."""

    def __init__(self, cluster):
        self.cluster = cluster  # cluster_utils.Cluster
        self.created: dict[str, object] = {}

    def create_node(self, resources: dict) -> str:
        res = dict(resources)
        num_cpus = res.pop("CPU", 1)
        num_tpus = res.pop("TPU", 0)
        handle = self.cluster.add_node(
            num_cpus=num_cpus, num_tpus=num_tpus, resources=res,
            external=True)
        self.created[handle.node_id] = handle
        return handle.node_id

    def terminate_node(self, node_id: str) -> None:
        handle = self.created.pop(node_id, None)
        if handle is not None:
            self.cluster.remove_node(handle, graceful=True)

    def non_terminated_nodes(self) -> list[str]:
        return list(self.created)


class StandardAutoscaler:
    """Scale up when the cluster cannot satisfy demand; scale down idle
    provider nodes after ``idle_timeout_s``."""

    def __init__(self, gcs_address, provider: NodeProvider, *,
                 node_resources: dict | None = None,
                 max_nodes: int = 4, idle_timeout_s: float = 5.0,
                 poll_interval_s: float = 0.5,
                 utilization_threshold: float = 0.9):
        self.gcs = RpcClient(tuple(gcs_address))
        self.provider = provider
        self.node_resources = node_resources or {"CPU": 2}
        self.max_nodes = max_nodes
        self.idle_timeout_s = idle_timeout_s
        self.poll_interval_s = poll_interval_s
        self.utilization_threshold = utilization_threshold
        self._idle_since: dict[str, float] = {}
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop = True

    def _run(self):
        while not self._stop:
            try:
                self.update()
            except Exception:  # noqa: BLE001 - keep monitoring
                pass
            time.sleep(self.poll_interval_s)

    def update(self):
        res = self.gcs.call("cluster_resources")
        total, avail = res["total"], res["available"]
        # scale up (1): explicit unmet demand — tasks parked as
        # cluster-wide infeasible (reference: autoscaler v2's demand-
        # driven path from GcsAutoscalerStateManager). Skips while a
        # provider node is still booting (not yet registered in GCS):
        # the demand stays pending for the whole provision window, and
        # re-creating per poll would over-provision for one task.
        all_nodes = {n["node_id"]: n
                     for n in self.gcs.call("get_nodes", alive_only=False)}
        alive = {nid for nid, n in all_nodes.items() if n.get("alive")}
        # reap provider nodes the GCS declared dead — left in place they
        # count as "provisioning" forever and wedge demand-driven scaling
        for nid in list(self.provider.non_terminated_nodes()):
            if nid in all_nodes and not all_nodes[nid].get("alive"):
                self.provider.terminate_node(nid)
                self._idle_since.pop(nid, None)
        provisioning = [n for n in self.provider.non_terminated_nodes()
                        if n not in alive]
        # capacity AFTER the reap: the cycle that frees a dead node's
        # slot must be able to provision its replacement immediately
        under_cap = (len(self.provider.non_terminated_nodes())
                     < self.max_nodes)
        if under_cap and not provisioning:
            try:
                pending = self.gcs.call("get_pending_demand")
            except Exception:  # noqa: BLE001 - older GCS
                pending = []
            satisfiable = [d for d in pending
                           if all(self.node_resources.get(k, 0) >= v
                                  for k, v in d.items())]
            if satisfiable:
                self.provider.create_node(dict(self.node_resources))
                return
        # scale up (2): demanded resource classes nearly exhausted
        busy = any(
            total.get(k, 0) > 0
            and (total[k] - avail.get(k, 0)) / total[k]
            >= self.utilization_threshold
            for k in ("CPU", "TPU") if total.get(k))
        if busy and under_cap:
            self.provider.create_node(dict(self.node_resources))
            return
        # scale down: provider nodes fully idle past the timeout
        nodes = {n["node_id"]: n
                 for n in self.gcs.call("get_nodes", alive_only=True)}
        now = time.monotonic()
        for node_id in self.provider.non_terminated_nodes():
            info = nodes.get(node_id)
            if info is None:
                continue
            idle = info["available"] == info["resources"]
            if not idle:
                self._idle_since.pop(node_id, None)
                continue
            since = self._idle_since.setdefault(node_id, now)
            if now - since > self.idle_timeout_s:
                self.provider.terminate_node(node_id)
                self._idle_since.pop(node_id, None)
                return
