"""Explicit object release (reference: ``ray._private.internal_api.free``
exposed via ``ray.experimental``): drop every stored copy of the objects
cluster-wide AND their lineage, so memory is reclaimed immediately and a
later ``get`` raises ``ObjectLostError`` instead of reconstructing."""

from __future__ import annotations

from ray_tpu.runtime.object_ref import ObjectRef


def free(refs):
    from ray_tpu import api as _api

    if isinstance(refs, ObjectRef):
        refs = [refs]
    # lazy like every other entry point: auto-connects inside workers
    _api._runtime().free(list(refs))
