"""ray_tpu.experimental (reference: ``python/ray/experimental/`` — P22)."""

from ray_tpu.experimental import tqdm_ray
from ray_tpu.experimental.free import free
from ray_tpu.experimental.internal_kv import (internal_kv_del,
                                              internal_kv_get,
                                              internal_kv_list,
                                              internal_kv_put)

__all__ = [
    "free",
    "internal_kv_del",
    "internal_kv_get",
    "internal_kv_list",
    "internal_kv_put",
    "tqdm_ray",
]
