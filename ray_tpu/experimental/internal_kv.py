"""Internal KV store API.

Reference analog: ``python/ray/experimental/internal_kv.py`` — thin
functions over the GCS KV (``GcsKvManager``). Cluster mode talks to the
GCS ``kv_*`` RPCs; local mode uses a process-local table with the same
semantics (namespaced bytes keys).
"""

from __future__ import annotations

import threading

from ray_tpu.runtime import core as _core

_NS = "internal_kv"
_local_kv: dict[str, bytes] = {}
_lock = threading.Lock()


def _backend():
    """Returns ("gcs", client) | ("client", rt) | ("local", None)."""
    if not _core.is_initialized():
        import os

        if os.environ.get("RAY_TPU_GCS_HOST"):
            # inside a cluster worker: resolve the implicit runtime the
            # same way the task API does, so KV reads hit the GCS
            from ray_tpu.api import _runtime

            _runtime()
        else:
            return "local", None
    rt = _core.get_runtime()
    if getattr(rt, "is_client", False):
        return "client", rt
    gcs = getattr(rt, "_gcs", None)
    if gcs is not None:
        return "gcs", gcs
    return "local", None


def _as_str(x) -> str:
    return x.decode() if isinstance(x, bytes) else str(x)


def internal_kv_put(key, value, overwrite: bool = True) -> bool:
    key = _as_str(key)
    value = value if isinstance(value, bytes) else str(value).encode()
    kind, backend = _backend()
    if kind == "client":
        return bool(backend._rpc.call("client_kv", op="put", key=key,
                                      value=value, overwrite=overwrite))
    if kind == "gcs":
        reply = backend.call("kv_put", ns=_NS, key=key, value=value,
                             overwrite=overwrite)
        if isinstance(reply, dict):
            return bool(reply.get("ok"))
        return bool(reply)
    with _lock:
        if not overwrite and key in _local_kv:
            return False
        _local_kv[key] = value
        return True


def internal_kv_get(key) -> bytes | None:
    key = _as_str(key)
    kind, backend = _backend()
    if kind == "client":
        return backend._rpc.call("client_kv", op="get", key=key)
    if kind == "gcs":
        return backend.call("kv_get", ns=_NS, key=key)
    with _lock:
        return _local_kv.get(key)


def internal_kv_del(key) -> bool:
    key = _as_str(key)
    kind, backend = _backend()
    if kind == "client":
        return bool(backend._rpc.call("client_kv", op="del", key=key))
    if kind == "gcs":
        return bool(backend.call("kv_del", ns=_NS, key=key).get("ok"))
    with _lock:
        return _local_kv.pop(key, None) is not None


def internal_kv_list(prefix="") -> list[str]:
    prefix = _as_str(prefix)
    kind, backend = _backend()
    if kind == "client":
        return backend._rpc.call("client_kv", op="list", prefix=prefix)
    if kind == "gcs":
        return backend.call("kv_keys", ns=_NS, prefix=prefix)
    with _lock:
        return [k for k in _local_kv if k.startswith(prefix)]
