"""Distributed progress bars.

Reference analog: ``python/ray/experimental/tqdm_ray.py`` — tqdm-like
bars whose updates flow from remote tasks/actors to the driver (a named
aggregator actor) so concurrent workers don't corrupt the terminal.
"""

from __future__ import annotations

import sys
import threading
import time

import ray_tpu

_AGGREGATOR_NAME = "__tqdm_ray_aggregator"


class _Aggregator:
    def __init__(self):
        self.bars: dict[str, dict] = {}
        self.lock = threading.Lock()

    def update(self, bar_id: str, desc: str, total, n: int):
        with self.lock:
            bar = self.bars.setdefault(
                bar_id, {"desc": desc, "total": total, "n": 0})
            bar["n"] += n
            bar["total"] = total
            return dict(bar)

    def close_bar(self, bar_id: str):
        with self.lock:
            return self.bars.pop(bar_id, None)

    def snapshot(self):
        with self.lock:
            return {k: dict(v) for k, v in self.bars.items()}


def _aggregator():
    try:
        return ray_tpu.get_actor(_AGGREGATOR_NAME)
    except ValueError:
        cls = ray_tpu.remote(_Aggregator)
        try:
            # SERIAL actor: per-caller submission order then becomes
            # execution order, so a snapshot() submitted after a burst
            # of fire-and-forget update()s observes all of them — with
            # a concurrency pool a snapshot can overtake in-flight
            # updates under CPU load (observed as a count-short flake).
            return cls.options(name=_AGGREGATOR_NAME).remote()
        except ValueError:
            return ray_tpu.get_actor(_AGGREGATOR_NAME)


class tqdm:  # noqa: N801 - mirrors the tqdm API name
    """Works inside remote tasks: updates aggregate on the driver-side
    actor; rendering happens wherever flush() runs (driver)."""

    def __init__(self, iterable=None, *, desc: str = "", total=None,
                 position: int = 0):
        self._iterable = iterable
        self.desc = desc or "progress"
        self.total = total if total is not None else (
            len(iterable) if iterable is not None and
            hasattr(iterable, "__len__") else None)
        import uuid

        self._id = uuid.uuid4().hex[:12]
        self._agg = _aggregator()

    def update(self, n: int = 1):
        # fire-and-forget: a blocking get per element would serialize
        # the wrapped loop on actor RPC latency
        self._agg.update.remote(self._id, self.desc, self.total, n)

    def close(self):
        ray_tpu.get(self._agg.close_bar.remote(self._id))

    def __iter__(self):
        try:
            for x in self._iterable:
                yield x
                self.update(1)
        finally:
            # break/exception must still retire the bar from the
            # long-lived aggregator actor
            self.close()


def snapshot() -> dict:
    """All live bars' state (driver-side render source)."""
    return ray_tpu.get(_aggregator().snapshot.remote())


def render(stream=None, *, clear: bool = False):
    """One-shot textual render of every live bar."""
    stream = stream or sys.stderr
    bars = snapshot()
    lines = []
    for bar in bars.values():
        total = bar["total"]
        n = bar["n"]
        if total:
            frac = min(1.0, n / total)
            fill = int(frac * 20)
            lines.append(f"{bar['desc']}: |{'#' * fill}{'-' * (20 - fill)}| "
                         f"{n}/{total}")
        else:
            lines.append(f"{bar['desc']}: {n} it")
    out = "\n".join(lines)
    if out:
        stream.write(out + "\n")
    return out


def watch(interval: float = 0.5, *, duration: float = 5.0):
    """Poll-and-render loop (driver helper)."""
    deadline = time.monotonic() + duration
    while time.monotonic() < deadline:
        if not snapshot():
            return
        render()
        time.sleep(interval)
