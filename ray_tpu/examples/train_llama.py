"""Quickstart: train a Llama-family model on a device mesh.

Run on any host (CPU mesh works for smoke tests):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m ray_tpu.examples.train_llama

Reference analog: the TorchTrainer quickstarts in the reference's Train
docs — here the backend is a `jax.sharding.Mesh` + GSPMD presets
instead of a torch process group.
"""

import jax
import jax.numpy as jnp

from ray_tpu.models import llama
from ray_tpu.parallel.mesh import create_mesh
from ray_tpu.train.trainer import JaxTrainer, TrainConfig


def main():
    n = len(jax.devices())
    mesh = create_mesh({"dp": 1, "fsdp": max(n // 2, 1),
                        "tp": 2 if n >= 2 else 1})
    trainer = JaxTrainer(
        llama.llama_tiny(),                    # swap for llama3_8b() on a pod
        TrainConfig(strategy="fsdp_tp", learning_rate=1e-3,
                    warmup_steps=5, total_steps=100),
        mesh=mesh,
    )
    state = trainer.init_state(jax.random.key(0))

    def batches():
        i = 0
        while True:
            yield jax.random.randint(jax.random.key(i), (8, 129), 0, 512,
                                     dtype=jnp.int32)
            i += 1

    state, history = trainer.fit(state, batches(), steps=30, log_every=10)
    for h in history:
        print({k: round(v, 4) for k, v in h.items()})


if __name__ == "__main__":
    main()
