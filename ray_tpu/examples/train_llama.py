"""Quickstart: train a Llama-family model on a device mesh.

Run on any host (CPU mesh works for smoke tests):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m ray_tpu.examples.train_llama

Reference analog: the TorchTrainer quickstarts in the reference's Train
docs — here the backend is a `jax.sharding.Mesh` + GSPMD presets
instead of a torch process group.
"""

import jax
import jax.numpy as jnp

from ray_tpu.models import llama
from ray_tpu.parallel.mesh import create_mesh
from ray_tpu.train.trainer import JaxTrainer, TrainConfig


def main():
    # factor the device count: tp=2 on even hosts; the rest becomes
    # fsdp when it's a power of two (so param dims stay divisible),
    # otherwise plain dp (replicated params shard nothing) — the mesh
    # resolves on any host: 1, 2, 5, or 8 devices alike
    n = len(jax.devices())
    tp = 2 if n % 2 == 0 and n >= 2 else 1
    rest = n // tp
    pow2 = rest > 0 and (rest & (rest - 1)) == 0
    mesh = create_mesh({"dp": 1 if pow2 else rest,
                        "fsdp": rest if pow2 else 1, "tp": tp})
    trainer = JaxTrainer(
        llama.llama_tiny(),                    # swap for llama3_8b() on a pod
        TrainConfig(strategy="fsdp_tp", learning_rate=1e-3,
                    warmup_steps=5, total_steps=100),
        mesh=mesh,
    )
    state = trainer.init_state(jax.random.key(0))

    batch_size = rest * max(8 // rest, 1)   # a multiple of the data axes

    def batches():
        i = 0
        while True:
            yield jax.random.randint(jax.random.key(i),
                                     (batch_size, 129), 0, 512,
                                     dtype=jnp.int32)
            i += 1

    state, history = trainer.fit(state, batches(), steps=30, log_every=10)
    for h in history:
        print({k: round(v, 4) for k, v in h.items()})


if __name__ == "__main__":
    main()
