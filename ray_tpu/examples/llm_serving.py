"""Quickstart: continuous-batching LLM serving on the paged-KV engine.

    python -m ray_tpu.examples.llm_serving

Shows the TPU-native serving stack end to end: a PagedLLMEngine with
automatic prefix caching (shared system prompts reuse their KV pages,
only tails prefill), streamed tokens, temperature sampling, and the
engine stats a Serve autoscaler would act on. Uses the tiny demo model
so it runs anywhere (swap ``llama_tiny`` for a real config + weights on
a chip). Reference analog: the reference serves models via user code in
replicas and has no engine — SURVEY.md P15.
"""

import numpy as np

import jax

from ray_tpu.models import llama
from ray_tpu.serve.paged_llm import PagedLLMEngine


def main():
    cfg = llama.llama_tiny()
    params = llama.init_params(cfg, jax.random.key(0))
    eng = PagedLLMEngine(cfg=cfg, params=params, max_batch=4,
                         max_len=256, page_size=32, num_pages=24,
                         decode_chunk=8)
    eng.start()
    rng = np.random.default_rng(0)

    # a shared "system prompt" + per-request tails: once the FIRST
    # request's prefill registers the prompt pages, later requests
    # reuse them read-only and prefill only their tails (requests
    # admitted in the same wave as the first can't see its pages yet —
    # registration happens at its prefill dispatch)
    system = rng.integers(1, cfg.vocab_size, 64)

    def chat(temperature=0.0):
        return eng.submit(
            np.concatenate([system, rng.integers(1, cfg.vocab_size, 12)]),
            max_new_tokens=12, temperature=temperature)

    first = chat()
    print(f"request 0: {len(list(first.tokens()))} tokens, "
          f"ttft={first.ttft:.3f}s (cold: registers the system prompt)")
    reqs = [chat(temperature=0.0 if i % 2 == 0 else 0.7)
            for i in range(1, 4)]
    for i, r in enumerate(reqs, start=1):
        toks = list(r.tokens())          # streaming: consume as they land
        print(f"request {i}: {len(toks)} tokens, "
              f"ttft={r.ttft:.3f}s -> {toks[:6]}...")

    st = eng.stats()
    pc = st["prefix_cache"]
    print(f"prefix cache: {pc['hit_pages']} page hits, "
          f"{pc['cached_idle_pages']} cached idle")
    print(f"kv pages: {st['kv_pages_free']}/{st['kv_pages_total']} free "
          f"({st['kv_pages_bytes'] >> 10} KiB vs "
          f"{st['kv_dense_equiv_bytes'] >> 10} KiB dense)")
    eng.stop()
    assert pc["hit_pages"] >= 2, pc
    print("llm serving quickstart: OK")


if __name__ == "__main__":
    main()
