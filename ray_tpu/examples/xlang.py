"""Functions callable from external-language clients by descriptor
(``ray_tpu.examples.xlang:add`` etc. — see ``runtime/xlang.py`` and the
C++ API in ``src/capi/``)."""


def add(a, b):
    return a + b


def concat(parts):
    return "".join(parts)


def stats(xs):
    return {"n": len(xs), "sum": float(sum(xs)),
            "max": float(max(xs)), "min": float(min(xs))}
