"""Quickstart: serve a model graph over HTTP.

    python -m ray_tpu.examples.serve_quickstart

Reference analog: the serve.run / deployment-graph quickstarts in the
reference's Serve docs.
"""

import json
import urllib.request

import ray_tpu
from ray_tpu import serve


@serve.deployment
class Preprocess:
    def __call__(self, payload):
        return [float(x) for x in payload["values"]]


@serve.deployment(num_replicas=2)
class Model:
    def __init__(self, scale):
        self.scale = scale

    def __call__(self, values):
        return {"sum": sum(values) * self.scale}


@serve.deployment
class Pipeline:
    def __init__(self, pre, model):
        self.pre, self.model = pre, model

    def __call__(self, payload):
        values = ray_tpu.get(self.pre.remote(payload))
        return ray_tpu.get(self.model.remote(values))


def main():
    ray_tpu.init(num_cpus=4)
    handle = serve.run(Pipeline.bind(Preprocess.bind(), Model.bind(2.0)))
    print("direct call:", handle.call({"values": [1, 2, 3]}))

    server, (host, port) = serve.start_http_proxy()
    req = urllib.request.Request(
        f"http://{host}:{port}/Pipeline",
        data=json.dumps({"values": [4, 5]}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        print("HTTP call:", json.load(resp))
    server.shutdown()
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
