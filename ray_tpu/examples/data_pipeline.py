"""Quickstart: a streaming data pipeline with distributed transforms.

    python -m ray_tpu.examples.data_pipeline

Reference analog: the Dataset quickstarts in the reference's Data docs
(read -> map_batches -> groupby -> iterate).
"""

import numpy as np

import ray_tpu
from ray_tpu import data


def main():
    ray_tpu.init(num_cpus=4)
    ds = data.from_numpy({
        "x": np.arange(10_000, dtype=np.float32),
        "group": np.arange(10_000) % 7,
    })
    out = (ds
           .map_batches(lambda b: {**b, "y": b["x"] * 2 + 1})
           .filter(lambda row: row["group"] != 3)
           .groupby("group").mean("y"))
    for row in sorted(out.take_all(), key=lambda r: r["group"]):
        print(row)
    print(out.stats())
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
