"""Quickstart: train PPO with distributed rollout workers.

    python -m ray_tpu.examples.rllib_quickstart

Reference analog: the `Algorithm` quickstarts in the reference's RLlib
docs (config builder -> .build() -> train loop).
"""

import ray_tpu
from ray_tpu.rllib import PPOConfig


def main():
    ray_tpu.init(num_cpus=4)
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=256)
            .training(lr=3e-4, num_sgd_iter=4)
            .build())
    try:
        for _ in range(10):
            result = algo.train()
            print(f"iter {result['training_iteration']:2d} "
                  f"return={result['episode_return_mean']:.1f} "
                  f"episodes={result['num_episodes']}")
    finally:
        algo.stop()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
