"""ray_tpu: a TPU-native distributed AI framework.

Task/actor/object core (analog of Ray Core) plus a JAX/XLA-first device
plane: meshes, GSPMD shardings, Pallas kernels, and the AI library surface
(data, train, tune, serve) built on them.
"""

from ray_tpu.api import (
    available_resources,
    timeline,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu.runtime.object_ref import ObjectRef
from ray_tpu.runtime.streaming import ObjectRefGenerator
from ray_tpu.runtime_context import get_runtime_context
from ray_tpu.runtime_env import RuntimeEnv
from ray_tpu.utils import exceptions

__version__ = "0.1.0"

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "get_runtime_context",
    "cluster_resources",
    "available_resources",
    "timeline",
    "ObjectRef",
    "ObjectRefGenerator",
    "RuntimeEnv",
    "exceptions",
    "__version__",
]
