"""Job submission: run driver scripts as supervised subprocesses.

Reference analog: ``dashboard/modules/job/job_manager.py`` — per-job
``JobSupervisor`` actor (:140) runs the entrypoint as a subprocess;
``JobManager`` (:525) tracks state in the GCS KV; plus the SDK surface
``python/ray/job_submission/`` (``JobSubmissionClient``)."""

from __future__ import annotations

import json
import os
import subprocess
import threading
import time
import uuid

import ray_tpu


class _JobSupervisor:
    """Actor supervising one job subprocess (stdout/stderr captured)."""

    def __init__(self, job_id: str, entrypoint: str, env: dict,
                 working_dir: str | None):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.status = "PENDING"
        self.returncode = None
        self.logs = ""
        full_env = dict(os.environ)
        full_env.update(env or {})
        self._proc = subprocess.Popen(
            entrypoint, shell=True, env=full_env,
            cwd=working_dir or os.getcwd(),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        self.status = "RUNNING"
        self._waiter = threading.Thread(target=self._wait, daemon=True)
        self._waiter.start()

    def _wait(self):
        out, _ = self._proc.communicate()
        self.logs = out or ""
        self.returncode = self._proc.returncode
        self.status = "SUCCEEDED" if self.returncode == 0 else "FAILED"

    def get_status(self):
        return {"job_id": self.job_id, "status": self.status,
                "returncode": self.returncode,
                "entrypoint": self.entrypoint}

    def get_logs(self):
        return self.logs

    def stop(self):
        if self._proc.poll() is None:
            self._proc.terminate()
            self.status = "STOPPED"
        return True


class JobSubmissionClient:
    """Submit/inspect/stop jobs (reference: job_submission SDK)."""

    def submit_job(self, *, entrypoint: str, env: dict | None = None,
                   working_dir: str | None = None,
                   submission_id: str | None = None) -> str:
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:8]}"
        supervisor_cls = ray_tpu.remote(_JobSupervisor)
        supervisor = supervisor_cls.options(
            name=f"_job_{job_id}").remote(
            job_id, entrypoint, env or {}, working_dir)
        # materialize the actor (surfaces spawn errors early)
        ray_tpu.get(supervisor.get_status.remote())
        return job_id

    def _supervisor(self, job_id: str):
        return ray_tpu.get_actor(f"_job_{job_id}")

    def get_job_status(self, job_id: str) -> str:
        return ray_tpu.get(
            self._supervisor(job_id).get_status.remote())["status"]

    def get_job_info(self, job_id: str) -> dict:
        return ray_tpu.get(self._supervisor(job_id).get_status.remote())

    def get_job_logs(self, job_id: str) -> str:
        return ray_tpu.get(self._supervisor(job_id).get_logs.remote())

    def stop_job(self, job_id: str) -> bool:
        return ray_tpu.get(self._supervisor(job_id).stop.remote())

    def wait_until_finish(self, job_id: str, timeout: float = 120.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in ("SUCCEEDED", "FAILED", "STOPPED"):
                return status
            time.sleep(0.1)
        raise TimeoutError(f"job {job_id} still {status} after {timeout}s")
