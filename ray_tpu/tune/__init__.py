"""ray_tpu.tune: hyperparameter search (reference: Ray Tune, SURVEY P16)."""

from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("tune")


from ray_tpu.tune.schedulers import (
    PB2,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
)
from ray_tpu.tune.search import (
    BOHBSearcher,
    BasicVariantGenerator,
    BayesOptSearcher,
    ConcurrencyLimiter,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from ray_tpu.tune.tuner import ResultGrid, Trial, TuneConfig, Tuner

__all__ = [
    "AsyncHyperBandScheduler",
    "BOHBSearcher",
    "BayesOptSearcher",
    "BasicVariantGenerator",
    "ConcurrencyLimiter",
    "HyperBandScheduler",
    "Searcher",
    "TPESearcher",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PB2",
    "PopulationBasedTraining",
    "ResultGrid",
    "Trial",
    "TuneConfig",
    "Tuner",
    "choice",
    "grid_search",
    "loguniform",
    "randint",
    "uniform",
]
